"""REST proxy tests — the analog of the reference tests/dhtproxytester.cpp
(:34-60): a peer node, a proxy node carrying a DhtProxyServer, and a
DhtProxyClient doing get/put/listen through REST, plus JSON-codec unit
round-trips and the SecureDht-over-proxy path."""

import json
import time
import urllib.request

import pytest

from opendht_tpu import crypto
from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.proxy import (
    DhtProxyClient, DhtProxyServer, value_from_json, value_to_json,
)
from opendht_tpu.runtime.config import NodeStatus
from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig
from opendht_tpu.runtime.secure_dht import SecureDht


def wait_for(pred, timeout=20.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


@pytest.fixture(scope="module")
def topology():
    """peer node ↔ proxy node + DhtProxyServer + DhtProxyClient
    (dhtproxytester.cpp:34-60, minus the separate client node)."""
    peer, proxy_node = DhtRunner(), DhtRunner()
    peer.run(0)
    proxy_node.run(0)
    proxy_node.bootstrap("127.0.0.1", peer.get_bound_port())
    assert wait_for(lambda: peer.get_status() is NodeStatus.CONNECTED
                    and proxy_node.get_status() is NodeStatus.CONNECTED)
    server = DhtProxyServer(proxy_node, port=0)
    client = DhtProxyClient("127.0.0.1", server.port)
    yield peer, proxy_node, server, client
    client.join()
    server.stop()
    peer.join()
    proxy_node.join()


# ---------------------------------------------------------------- unit: codec

def test_json_roundtrip_plain():
    v = Value(b"hello world", type_id=3, value_id=42, user_type="text/plain")
    v2 = value_from_json(value_to_json(v))
    assert v2.id == 42 and v2.data == b"hello world"
    assert v2.type == 3 and v2.user_type == "text/plain"


def test_json_roundtrip_signed():
    ident = crypto.generate_identity("codec-test", key_length=1024)
    v = Value(b"signed payload", value_id=7)
    v.sign(ident.first)
    obj = value_to_json(v)
    assert "sig" in obj and "owner" in obj
    v2 = value_from_json(obj)
    assert v2.data == b"signed payload"
    assert v2.check_signature()


def test_json_roundtrip_encrypted():
    v = Value(value_id=9)
    v.cypher = b"\x01\x02\x03"
    v2 = value_from_json(value_to_json(v))
    assert v2.is_encrypted() and v2.cypher == b"\x01\x02\x03"


# ------------------------------------------------------------------ rest api

def test_node_info(topology):
    peer, proxy_node, server, client = topology
    info = client.get_proxy_info()
    assert info is not None
    assert info["node_id"] == proxy_node.get_node_id().hex()
    assert "ipv4" in info
    assert wait_for(lambda: client.get_status() is NodeStatus.CONNECTED,
                    timeout=25.0)


def test_put_via_proxy_get_via_udp(topology):
    peer, proxy_node, server, client = topology
    key = InfoHash.get("proxy-put-key")
    done = []
    client.put(key, Value(b"via-proxy", value_id=11),
               lambda ok, ns: done.append(ok))
    assert wait_for(lambda: bool(done)) and done[0]
    vals = peer.get_sync(key, timeout=20.0)
    assert any(v.data == b"via-proxy" for v in vals)


def test_put_via_udp_get_via_proxy(topology):
    peer, proxy_node, server, client = topology
    key = InfoHash.get("proxy-get-key")
    assert peer.put_sync(key, Value(b"via-udp", value_id=12), timeout=20.0)
    vals = client.get_sync(key, timeout=20.0)
    assert any(v.data == b"via-udp" for v in vals)


def test_get_specific_value_id(topology):
    peer, proxy_node, server, client = topology
    key = InfoHash.get("proxy-vid-key")
    assert peer.put_sync(key, Value(b"one", value_id=21), timeout=20.0)
    assert peer.put_sync(key, Value(b"two", value_id=22), timeout=20.0)
    url = "http://127.0.0.1:%d/%s/22" % (server.port, key.hex())
    with urllib.request.urlopen(url, timeout=20.0) as r:
        lines = [json.loads(l) for l in r.read().decode().splitlines() if l.strip()]
    assert lines and all(int(o["id"]) == 22 for o in lines)


def test_listen_via_proxy(topology):
    peer, proxy_node, server, client = topology
    key = InfoHash.get("proxy-listen-key")
    heard = []
    token = client.listen(key, lambda vals, expired:
                          heard.extend(v.data for v in vals) or True)
    time.sleep(1.0)                      # let the long-poll attach
    assert peer.put_sync(key, Value(b"pushed", value_id=31), timeout=20.0)
    assert wait_for(lambda: b"pushed" in heard, timeout=25.0), heard
    assert client.cancel_listen(key, token)


def test_stats_endpoint(topology):
    peer, proxy_node, server, client = topology
    st = client._request_json("STATS", "/")
    assert st is not None
    assert "putCount" in st and "listenCount" in st and "nodeInfo" in st


def test_subscribe_push_notifications(topology):
    """SUBSCRIBE registers a push listener; value arrivals invoke the
    server's push sender (the reference POSTs to Gorush,
    dht_proxy_server.cpp:411-469); UNSUBSCRIBE stops it."""
    peer, proxy_node, server, client = topology
    pushed = []
    server._push_sender = lambda client_id, payload: pushed.append(
        (client_id, payload))
    try:
        push_client = DhtProxyClient("127.0.0.1", server.port,
                                     client_id="device-42")
        key = InfoHash.get("push-key")
        res = push_client.subscribe(key)
        assert res is not None and "token" in res
        time.sleep(1.0)
        assert peer.put_sync(key, Value(b"push-me", value_id=61),
                             timeout=20.0)
        assert wait_for(lambda: any(cid == "device-42" and
                                    61 in p.get("ids", [])
                                    for cid, p in pushed), timeout=25.0), \
            pushed
        assert push_client.unsubscribe(key).get("ok") is True
        push_client.join()
    finally:
        server._push_sender = None


def test_push_gateway_http(topology):
    """A SUBSCRIBE with gateway fields drives real Gorush-shaped POSTs to
    an HTTP push server on value arrival, and a refresh push near expiry
    (dht_proxy_server.cpp:411-469 subscribe, :548-583 sender,
    :462-470 expireNotifyJob)."""
    import http.server
    import threading

    peer, proxy_node, server, client = topology
    got = []

    class FakeGorush(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            got.append((self.path, json.loads(body)))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, fmt, *args):
            pass

    gw = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeGorush)
    gw_thread = threading.Thread(target=gw.serve_forever, daemon=True)
    gw_thread.start()

    from opendht_tpu.proxy.push import GorushPushSender
    server._gorush = GorushPushSender("127.0.0.1:%d" % gw.server_address[1])
    try:
        push_client = DhtProxyClient("127.0.0.1", server.port,
                                     client_id="gw-client")
        key = InfoHash.get("gorush-key")
        res = push_client.subscribe(key, push_token="device-token-xyz",
                                    platform="ios", token=777)
        assert res is not None and res.get("token") == 777
        time.sleep(1.0)
        assert peer.put_sync(key, Value(b"notify-me", value_id=91),
                             timeout=20.0)
        assert wait_for(lambda: len(got) > 0, timeout=25.0)
        path, payload = got[0]
        assert path == "/api/push"
        n = payload["notifications"][0]
        assert n["tokens"] == ["device-token-xyz"]
        assert n["platform"] == 1            # ios
        assert n["priority"] == "high" and n["time_to_live"] == 600
        assert n["data"]["key"] == key.hex()
        assert n["data"]["to"] == "gw-client"
        assert n["data"]["token"] == "777"

        # force the expiry-refresh window and expect the "timeout" push
        with server._lock:
            rec = server._push_listeners[(key, "gw-client")]
            rec.deadline = time.monotonic() + 1.0   # within OP_MARGIN
        assert wait_for(lambda: any("timeout" in p["notifications"][0]["data"]
                                    for _, p in got), timeout=10.0), got
        refresh = next(p for _, p in got
                       if "timeout" in p["notifications"][0]["data"])
        d = refresh["notifications"][0]["data"]
        assert d["timeout"] == key.hex() and d["token"] == "777"

        assert push_client.unsubscribe(key).get("ok") is True
        push_client.join()
    finally:
        server._gorush.join()
        server._gorush = None
        gw.shutdown()
        gw.server_close()


def test_runner_enable_proxy_hotswap(topology):
    """A third runner switches its backend to the REST proxy, ops and the
    live listener carry over, then it swaps back (dhtrunner.cpp:992-1041,
    dhtproxytester.cpp client-node role)."""
    peer, proxy_node, server, client = topology
    c = DhtRunner()
    c.run(0)
    try:
        heard = []
        key = InfoHash.get("hotswap-listen")
        tok = c.listen(key, lambda vals, expired:
                       heard.extend(v.data for v in vals) or True)
        tok.result(10.0)

        c.enable_proxy("127.0.0.1:%d" % server.port)
        assert wait_for(lambda: c.use_proxy, timeout=10.0)
        assert wait_for(lambda: c.get_status() is NodeStatus.CONNECTED,
                        timeout=25.0)
        key2 = InfoHash.get("hotswap-put")
        assert c.put_sync(key2, Value(b"over-proxy", value_id=51),
                          timeout=25.0)
        vals = peer.get_sync(key2, timeout=20.0)
        assert any(v.data == b"over-proxy" for v in vals)

        # the pre-swap listener must now ride the proxy long-poll
        time.sleep(1.0)
        assert peer.put_sync(key, Value(b"carried", value_id=52), timeout=20.0)
        assert wait_for(lambda: b"carried" in heard, timeout=25.0), heard

        c.enable_proxy(None)
        assert wait_for(lambda: not c.use_proxy, timeout=10.0)
    finally:
        c.join()


def test_runner_config_proxy_server_startup(topology):
    """RunnerConfig.proxy_server starts the node proxied from run()
    (↔ DhtRunner::Config::proxy_server, dhtrunner.cpp:98-149)."""
    peer, proxy_node, server, client = topology
    c = DhtRunner()
    c.run(0, RunnerConfig(proxy_server="127.0.0.1:%d" % server.port))
    try:
        assert wait_for(lambda: c.use_proxy, timeout=10.0)
        assert wait_for(lambda: c.get_status() is NodeStatus.CONNECTED,
                        timeout=25.0)
        key = InfoHash.get("config-proxy-key")
        assert c.put_sync(key, Value(b"from-config-proxy", value_id=71),
                          timeout=25.0)
        vals = peer.get_sync(key, timeout=20.0)
        assert any(v.data == b"from-config-proxy" for v in vals)
    finally:
        c.join()


def test_secure_dht_over_proxy(topology):
    """SecureDht wrapping the REST backend: signed put through the proxy,
    verified via UDP get (↔ the reference's SecureDhtProxy stack)."""
    peer, proxy_node, server, client = topology
    ident = crypto.generate_identity("proxy-sec", key_length=1024)
    sdht = SecureDht(client, (ident.first, ident.second))
    key = InfoHash.get("proxy-signed-key")
    done = []
    sdht.put_signed(key, Value(b"signed-over-rest", value_id=41),
                    lambda ok, ns: done.append(ok))
    assert wait_for(lambda: bool(done), timeout=25.0) and done[0]
    vals = peer.get_sync(key, timeout=20.0)
    got = [v for v in vals if v.data == b"signed-over-rest"]
    assert got and got[0].is_signed() and got[0].check_signature()


def test_listen_and_subscribe_shed_return_503():
    """Round-12 review regression: a backend listen shed at ingest
    admission (Dht.listen's 0 sentinel) must surface as an HTTP error
    on the proxy's LISTEN stream and SUBSCRIBE registration — never an
    open heartbeat stream or a push token for a subscription that does
    not exist."""
    import urllib.error
    from opendht_tpu.runtime import Config

    r = DhtRunner()
    try:
        # queue_max=0 sheds every new op at admission
        r.run(0, RunnerConfig(dht_config=Config(ingest_queue_max=0)))
        server = DhtProxyServer(r, 0)
        try:
            key_hex = InfoHash.get("shed-proxy").hex()
            req = urllib.request.Request(
                "http://127.0.0.1:%d/%s" % (server.port, key_hex),
                method="LISTEN")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=15)
            assert ei.value.code == 503
            req = urllib.request.Request(
                "http://127.0.0.1:%d/%s" % (server.port, key_hex),
                data=json.dumps({"client_id": "shed-c"}).encode(),
                method="SUBSCRIBE",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=15)
            assert ei.value.code == 503
            assert server.get_stats().push_listeners_count == 0
        finally:
            server.stop()
    finally:
        r.join()
