"""Adversarial chaos plane tests (ISSUE-13): FaultPlan grammar, the
shared FaultInjector seam, the virtual net's extended netem model
(per-link asymmetric rules, duplication, reordering, per-rule drop
accounting), the live engine's guarded fault hook (byte-identical when
unarmed), the net/request.py retransmit state machine under injected
loss/reorder/duplication, and the sybil/eclipse resistance of the
routing table's admission rules."""

import socket

import pytest

from opendht_tpu import chaos
from opendht_tpu.chaos import (
    FaultInjector, FaultPlan, LinkRule, Partition, Phase, Storm,
)
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import EngineCallbacks, NetworkEngine
from opendht_tpu.net.request import MAX_ATTEMPT_COUNT, RequestState
from opendht_tpu.net.node import MAX_RESPONSE_TIME
from opendht_tpu.runtime import Config
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.testing import VirtualNet
from opendht_tpu.utils import pack_msg
from opendht_tpu.net.parsed_message import pack_tid

pytestmark = pytest.mark.quick


# ============================================================ plan grammar
def test_phase_windows_and_healing():
    plan = FaultPlan([
        Phase("early", start=1.0, duration=2.0),
        Phase("open", start=5.0),
    ])
    assert [p.name for p in plan.phases_at(0.5)] == []
    assert [p.name for p in plan.phases_at(1.0)] == ["early"]
    assert [p.name for p in plan.phases_at(2.9)] == ["early"]
    assert [p.name for p in plan.phases_at(3.0)] == []     # healed
    assert [p.name for p in plan.phases_at(9.0)] == ["open"]
    assert plan.end_time() is None
    assert FaultPlan([Phase("a", 1.0, 2.0)]).end_time() == 3.0


def test_link_rule_matching():
    r = LinkRule(name="ab", src="a", dst="b", loss=1.0)
    assert r.matches("a", "b")
    assert not r.matches("b", "a"), "rules are asymmetric by default"
    assert not r.matches("a", "c")
    sym = LinkRule(name="s", src="a", dst="b", symmetric=True)
    assert sym.matches("a", "b") and sym.matches("b", "a")
    wild = LinkRule(name="w")
    assert wild.matches("x", "y")


def test_partition_blocks_directed():
    p = Partition(block=[("a", "b")])
    assert p.blocks("a", "b") and not p.blocks("b", "a")
    s = Partition(block=[("a", "b")], symmetric=True)
    assert s.blocks("a", "b") and s.blocks("b", "a")


def test_injector_deterministic_and_counted():
    def make():
        plan = FaultPlan([Phase("lossy", rules=[
            LinkRule(name="wan", loss=0.5, dup=0.2)])], seed=9)
        inj = FaultInjector(plan)
        inj.arm(0.0)
        return inj

    a, b = make(), make()
    fa = [a.fate("x", "y", 0.1) for _ in range(200)]
    fb = [b.fate("x", "y", 0.1) for _ in range(200)]
    assert fa == fb, "seeded injector must replay identically"
    assert a.counts["wan"]["dropped"] == sum(f.drop for f in fa) > 0
    assert a.dropped_by_rule()["wan"] == a.counts["wan"]["dropped"]
    assert sum(f.dup for f in fa) > 0
    # disarmed: everything passes untouched
    a.disarm()
    assert not a.fate("x", "y", 0.1).touched


def test_injector_partition_beats_rules():
    plan = FaultPlan(
        [Phase("split", partition=Partition(block=[("a", "b")]))],
        membership={"k1": "a", "k2": "b"})
    inj = FaultInjector(plan)
    inj.arm(0.0)
    assert inj.fate("k1", "k2", 1.0).drop
    assert not inj.fate("k2", "k1", 1.0).touched, "asymmetric"
    assert inj.dropped_by_rule() == {"partition:split": 1}


# ====================================================== virtual-net netem
def _two_nodes(net):
    a = net.add_node()
    b = net.add_node()
    return a, b


def test_vnet_asymmetric_link_loss():
    """a→b drops, b→a delivers: the netem model is now per-link and
    directional, with drops attributed per rule."""
    net = VirtualNet(delay=0.01)
    a, b = _two_nodes(net)
    net.set_group(a, "a")
    net.set_group(b, "b")
    net.add_link_rule(LinkRule(name="cut", src="a", dst="b", loss=1.0))
    a.ping_node(b.bound_addr)
    b.ping_node(a.bound_addr)
    net.settle(10.0)
    # b's ping reaches a (and retries: a's pong back is a→b, cut too)
    assert a.engine.in_stats.ping >= 1, "b→a must deliver"
    assert b.engine.in_stats.ping == 0, "a→b must drop"
    assert net.dropped_by_rule.get("cut", 0) > 0
    assert net.dropped == sum(net.dropped_by_rule.values())


def test_vnet_duplication_delivers_twice_completes_once():
    """dup=1.0 doubles every datagram on the wire; the receiver sees
    two requests, the sender's RPC still completes exactly once
    (duplicate replies matched by tid once — request.h semantics)."""
    net = VirtualNet(delay=0.01)
    a, b = _two_nodes(net)
    net.add_link_rule(LinkRule(name="dup", dup=1.0))
    n = a.engine.cache.get_node(b.myid, b.bound_addr, 0.0, confirm=False)
    done = []
    req = a.engine.send_ping(n, on_done=lambda r, ans: done.append(r))
    net.settle(10.0)
    assert b.engine.in_stats.ping == 2, "duplicate never delivered"
    assert len(done) == 1, "duplicated reply completed the RPC twice"
    assert req.completed
    assert net.injector.counts["dup"]["dup"] > 0


def test_vnet_reorder_breaks_send_order():
    """With a reorder rule armed, delivery is no longer send-ordered:
    held-back packets arrive after later ones."""
    net = VirtualNet(delay=0.01, seed=4)
    a, b = _two_nodes(net)
    net.add_link_rule(LinkRule(name="ro", reorder=0.5,
                               reorder_delay=0.2))
    n = a.engine.cache.get_node(b.myid, b.bound_addr, 0.0, confirm=False)
    for _ in range(30):
        a.engine.send_ping(n)
    entries = sorted(net._queue)           # (arrival, send_seq, ...)
    seqs = [e[1] for e in entries]
    assert seqs != sorted(seqs), \
        "reorder rule must invert send order for some pairs"
    assert net.injector.counts["ro"]["reordered"] > 0


def test_vnet_chaos_off_equals_baseline():
    """An armed-but-empty FaultPlan is byte-for-byte the baseline: the
    same seeded scenario delivers the same values with zero drops."""
    def scenario(plan):
        net = VirtualNet(seed=11, plan=plan)
        seed = net.add_node()
        for _ in range(3):
            net.add_node()
        net.bootstrap_all(seed)
        assert net.run(60, net.all_connected)
        nodes = list(net.nodes.values())
        from opendht_tpu.core.value import Value
        key = InfoHash.get("chaos-off-pin")
        nodes[1].put(key, Value(b"payload"))
        got, done = [], {}
        nodes[3].get(key, lambda vals: got.extend(vals) or True,
                     lambda ok, ns: done.update(ok=ok))
        assert net.run(60, lambda: "ok" in done)
        return ([v.data for v in got], net.dropped,
                dict(net.dropped_by_rule))

    base = scenario(None)
    armed = scenario(FaultPlan([]))
    assert base == armed
    assert base[1] == 0 and base[2] == {}


def test_vnet_storm_step():
    net = VirtualNet(seed=2)
    seed = net.add_node()
    for _ in range(9):
        net.add_node()
    net.bootstrap_all(seed)
    left, joined = net.step_storm(Storm(leave_rate=0.5, join_rate=0.2),
                                  seed)
    assert left > 0 and joined > 0
    assert len(net.nodes) == 10 - left + joined


# ==================================================== live engine fault hook
def _mk_engine(sent, clock=None):
    sched = Scheduler(clock=clock) if clock else Scheduler()
    return NetworkEngine(
        InfoHash.get("chaos-engine"), 0,
        lambda data, dst: sent.append((bytes(data), dst)) or 0,
        sched, EngineCallbacks())


def test_engine_bytes_identical_unarmed_and_empty_plan():
    """The acceptance pin: with no FaultPlan armed the live engine's
    wire bytes are bit-identical — both with the hook at its None
    default and with an armed-but-empty plan installed."""
    def one_exchange(arm_empty):
        sent = []
        eng = _mk_engine(sent)
        assert eng.fault_hook is None, "hook must default to None"
        if arm_empty:
            inj = FaultInjector(FaultPlan([]))
            inj.arm(0.0)
            chaos.arm_engine(eng, inj, ("10.0.0.1", 4001))
        peer = eng.cache.get_node(InfoHash.get("peer"),
                                  SockAddr("10.0.0.2", 4002), 0.0,
                                  confirm=False)
        peer._tid = 100          # pin the random tid seed for the diff
        eng.send_ping(peer)
        eng.send_find_node(peer, InfoHash.get("target"))
        return [d for d, _ in sent]

    assert one_exchange(False) == one_exchange(True)


def test_engine_hook_partition_drops():
    sent = []
    eng = _mk_engine(sent)
    plan = FaultPlan(
        [Phase("split", partition=Partition(block=[("me", "them")]))],
        membership={("10.0.0.1", 4001): "me", ("10.0.0.2", 4002): "them"})
    inj = FaultInjector(plan)
    inj.arm(eng.scheduler.time())
    chaos.arm_engine(eng, inj, ("10.0.0.1", 4001))
    peer = eng.cache.get_node(InfoHash.get("peer"),
                              SockAddr("10.0.0.2", 4002), 0.0,
                              confirm=False)
    eng.send_ping(peer)
    assert sent == [], "partitioned send must be consumed"
    assert inj.dropped_by_rule() == {"partition:split": 1}
    chaos.disarm_engine(eng)
    eng.send_ping(peer)
    assert len(sent) == 1, "disarm must restore the send path"


def test_engine_hook_delay_reschedules():
    clock = [0.0]
    sent = []
    eng = _mk_engine(sent, clock=lambda: clock[0])
    plan = FaultPlan([Phase("slow", rules=[
        LinkRule(name="slow", delay=0.5)])])
    inj = FaultInjector(plan)
    inj.arm(0.0)
    chaos.arm_engine(eng, inj, ("10.0.0.1", 4001))
    peer = eng.cache.get_node(InfoHash.get("peer"),
                              SockAddr("10.0.0.2", 4002), 0.0,
                              confirm=False)
    eng.send_ping(peer)
    assert sent == [], "delayed packet must not send inline"
    clock[0] = 0.6
    eng.scheduler.run()
    assert len(sent) == 1, "delayed packet must replay via the scheduler"


def test_arm_dht_guard():
    net = VirtualNet()
    d = net.add_node(Config())
    inj = FaultInjector(FaultPlan([]))
    inj.arm(0.0)
    with pytest.raises(RuntimeError):
        chaos.arm_dht(d, inj)
    chaos.arm_dht(d, inj, force=True)           # owning harness
    assert d.engine.fault_hook is not None
    chaos.disarm_dht(d)
    d2 = net.add_node(Config(chaos_enabled=True))
    chaos.arm_dht(d2, inj)                      # opted in
    assert d2.engine.fault_hook is not None


def test_dhtnetwork_arm_covers_late_launched_nodes():
    """A node launched AFTER DhtNetwork.arm (churn replacement) must be
    hooked too — an armed partition cannot silently leak through
    cluster churn (review finding)."""
    from opendht_tpu.testing.network import DhtNetwork

    net = DhtNetwork(2)
    try:
        plan = FaultPlan([Phase(
            "cut", partition=Partition(block=[("a", "b")]))])
        net.arm(plan, groups={0: "a"}, default_group="b")
        for r in net.nodes:
            assert r._dht._dht.engine.fault_hook is not None
        late = net.launch_node()
        eng = late._dht._dht.engine
        assert eng.fault_hook is not None, \
            "late-launched node escaped the armed plan"
        key = ("127.0.0.1", late.get_bound_port())
        assert net.injector.plan.membership[key] == "b"
        net.disarm()
        assert all(r._dht._dht.engine.fault_hook is None
                   for r in net.nodes)
    finally:
        net.shutdown()


# ================================== request machine under injected faults
class _Link:
    """Two engines joined by a controllable queue: the retransmit state
    machine harness (drops/dups/holds are scripted per test)."""

    def __init__(self):
        self.clock = [0.0]
        self.queue = []            # (data, src_addr, dst_addr)
        self.endpoints = {}
        self.drop = lambda data, src, dst: False

    def engine(self, name, last_octet):
        addr = SockAddr("10.0.1.%d" % last_octet, 4100 + last_octet)
        eng = NetworkEngine(
            InfoHash.get(name), 0,
            lambda data, dst, _a=addr:
                self.queue.append((bytes(data), _a, dst)) or 0,
            Scheduler(clock=lambda: self.clock[0]), EngineCallbacks())
        self.endpoints[(addr.host, addr.port)] = eng
        return eng, addr

    def pump(self):
        while self.queue:
            data, src, dst = self.queue.pop(0)
            if self.drop(data, src, dst):
                continue
            eng = self.endpoints.get((dst.host, dst.port))
            if eng is not None:
                eng.process_message(data, src)

    def advance(self, dt):
        self.clock[0] += dt
        for eng in self.endpoints.values():
            eng.scheduler.run()


def test_retransmit_full_loss_3_attempts_then_expired():
    """Under total loss the request retries 3 x MAX_RESPONSE_TIME: the
    early done=False hint fires exactly once after the first
    re-attempt, final expiry fires done=True once, attempts == 3."""
    link = _Link()
    a, _aa = link.engine("req-a", 1)
    _b, ba = link.engine("req-b", 2)
    link.drop = lambda data, src, dst: True       # injected 100% loss
    peer = a.cache.get_node(InfoHash.get("req-b"), ba, 0.0,
                            confirm=False)
    hints = []
    req = a.send_ping(peer, on_expired=lambda r, done: hints.append(done))
    sent0 = req.attempt_count
    assert sent0 == 1 and hints == []
    for _ in range(MAX_ATTEMPT_COUNT + 1):
        link.advance(MAX_RESPONSE_TIME)
        link.pump()
    assert req.state is RequestState.EXPIRED
    assert req.attempt_count == MAX_ATTEMPT_COUNT
    assert hints == [False, True], \
        "early hint once after first re-attempt, then final expiry"


def test_duplicate_reply_matched_by_tid_exactly_once():
    link = _Link()
    a, _aa = link.engine("dup-a", 3)
    b, ba = link.engine("dup-b", 4)
    captured = []
    link.drop = lambda data, src, dst: (
        captured.append((data, src, dst)) or True
        if (dst.host, dst.port) == ("10.0.1.3", 4103) else False)
    peer = a.cache.get_node(InfoHash.get("dup-b"), ba, 0.0,
                            confirm=False)
    done = []
    req = a.send_ping(peer, on_done=lambda r, ans: done.append(r))
    link.pump()                                    # b replies; we hold it
    assert len(captured) == 1
    link.drop = lambda data, src, dst: False
    data, src, _dst = captured[0]
    a.process_message(data, src)                   # the reply
    a.process_message(data, src)                   # injected duplicate
    assert req.state is RequestState.COMPLETED
    assert len(done) == 1, "duplicate reply must not re-complete"


def test_late_reply_after_expiry_never_resurrects():
    link = _Link()
    a, _aa = link.engine("late-a", 5)
    b, ba = link.engine("late-b", 6)
    captured = []
    link.drop = lambda data, src, dst: (
        captured.append((data, src, dst)) or True
        if (dst.host, dst.port) == ("10.0.1.5", 4105) else False)
    peer = a.cache.get_node(InfoHash.get("late-b"), ba, 0.0,
                            confirm=False)
    done, hints = [], []
    req = a.send_ping(peer, on_done=lambda r, ans: done.append(r),
                      on_expired=lambda r, d: hints.append(d))
    link.pump()
    for _ in range(MAX_ATTEMPT_COUNT + 1):
        link.advance(MAX_RESPONSE_TIME)
        link.pump()
    assert req.state is RequestState.EXPIRED and hints[-1] is True
    data, src, _dst = captured[0]
    a.process_message(data, src)                   # the late reply
    assert req.state is RequestState.EXPIRED, \
        "a reply after expiry must never resurrect the request"
    assert done == []


def test_reordered_replies_complete_out_of_order_requests():
    """Reordering across two in-flight RPCs: the later request's reply
    arriving first completes each request exactly once by tid."""
    link = _Link()
    a, _aa = link.engine("ro-a", 7)
    b, ba = link.engine("ro-b", 8)
    replies = []
    link.drop = lambda data, src, dst: (
        replies.append((data, src)) or True
        if (dst.host, dst.port) == ("10.0.1.7", 4107) else False)
    peer = a.cache.get_node(InfoHash.get("ro-b"), ba, 0.0,
                            confirm=False)
    done = []
    r1 = a.send_ping(peer, on_done=lambda r, ans: done.append(1))
    r2 = a.send_ping(peer, on_done=lambda r, ans: done.append(2))
    link.pump()
    assert len(replies) == 2
    for data, src in reversed(replies):            # injected reorder
        a.process_message(data, src)
    assert done == [2, 1]
    assert r1.completed and r2.completed


# ================================================ sybil/eclipse resistance
def _sybil_id(victim: InfoHash, bucket: int, salt: int) -> bytes:
    """An id sharing the victim's first ``bucket`` bits, differing at
    bit ``bucket`` — lands exactly in that k-bucket."""
    v = int.from_bytes(bytes(victim), "big")
    flip = v ^ (1 << (159 - bucket))
    keep = (~0) << (159 - bucket)            # bits above `bucket` + flip
    noise = (salt * 0x9E3779B97F4A7C15) & ((1 << (159 - bucket)) - 1)
    return ((flip & keep) | noise).to_bytes(20, "big")


def _ping_packet(node_id: bytes, tid: int) -> bytes:
    return pack_msg({"a": {"id": node_id}, "q": "ping",
                     "t": pack_tid(tid), "y": "q", "v": "SY"})


def test_sybil_flood_bounded_by_admission_and_honest_keys_survive():
    """A poisoning flood — hundreds of attacker-controlled ids from TWO
    source addresses aimed at a victim's deep buckets — is bounded by
    the routing table's admission rules (at most k per bucket,
    full-bucket rejection keeps occupied shallow buckets intact), and
    honest put/get traffic still completes: the sybil addresses never
    answer, so searches expire them (3 x 1 s) and fall back to honest
    peers.

    DOCUMENTED GAP (not silently tuned away — see PARITY.md
    "Adversarial chaos plane"): like the reference routing table
    (src/routing_table.cpp:204-262), admission has NO per-IP diversity
    bound inside a bucket — a single address may claim every free slot
    of every non-full bucket, and those never-replied entries are
    served to peers in reply blobs until they expire.  The effective
    bounds are k-per-bucket, the per-IP ingress rate limit (1/8 of
    max_req_per_sec), and request expiry."""
    net = VirtualNet(seed=6)
    # pinned node ids: the whole scenario (bucket layout, search
    # trajectories) is deterministic run to run
    def cfg(i):
        return Config(max_req_per_sec=100000,   # isolate table admission
                      node_id=InfoHash.get("sybil-scenario-%d" % i))
    seed = net.add_node(cfg(0))
    for i in range(9):
        net.add_node(cfg(i + 1))
    net.bootstrap_all(seed)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    victim = nodes[0]
    table = victim.tables[socket.AF_INET]
    occ_before = table.bucket_occupancy().copy()

    attacker_addrs = [SockAddr("203.0.113.7", 4242),
                      SockAddr("203.0.113.9", 4242)]
    target_buckets = list(range(100, 160))
    sybils = set()
    tid = 7000
    for b in target_buckets:
        for i in range(24):                 # 3x the per-bucket capacity
            sid = _sybil_id(victim.myid, b, salt=b * 100 + i)
            sybils.add(sid)
            tid += 1
            victim.periodic(_ping_packet(sid, tid),
                            attacker_addrs[i % 2])

    occ = table.bucket_occupancy()
    assert occ.max() <= table.k, \
        "a bucket admitted more than k entries under the flood"
    # full shallow buckets reject the flood outright
    for b in range(160):
        if occ_before[b] >= table.k:
            assert occ[b] == occ_before[b], \
                "a full bucket changed under hearsay pressure (b=%d)" % b
    n_attacker = sum(1 for sid in sybils
                     if table.row_of(InfoHash(sid)) is not None)
    free_slots = int(sum(max(table.k - occ_before[b], 0)
                         for b in target_buckets))
    assert 0 < n_attacker <= free_slots, (n_attacker, free_slots)

    # honest-key invariant: traffic through the poisoned victim still
    # completes (sybil peers expire; honest replicas answer)
    from opendht_tpu.core.value import Value
    key = InfoHash.get("honest-key-under-eclipse")
    put_done = {}
    nodes[3].put(key, Value(b"survives"),
                 lambda ok, ns: put_done.update(ok=ok))
    assert net.run(120, lambda: "ok" in put_done) and put_done["ok"]
    got, done = [], {}
    victim.get(key, lambda vals: got.extend(vals) or True,
               lambda ok, ns: done.update(ok=ok))
    assert net.run(180, lambda: "ok" in done), "get never completed"
    assert any(v.data == b"survives" for v in got), \
        "honest lookup failed under sybil pressure"


def test_sybil_flood_rate_limited_at_default_ingress():
    """With the default ingress budget, the per-IP limiter bounds how
    fast a single source can even present sybil ids: one instant's
    500-packet burst admits at most max_req_per_sec // 8 of them."""
    net = VirtualNet(seed=8)
    victim = net.add_node(Config())        # default 1600/s -> 200/s per IP
    table = victim.tables[socket.AF_INET]
    addr = SockAddr("203.0.113.50", 4242)
    for i in range(500):
        sid = _sybil_id(victim.myid, 100 + (i % 50), salt=i)
        victim.periodic(_ping_packet(sid, 8000 + i), addr)
    admitted = len(table)
    assert admitted <= victim.config.max_req_per_sec // 8, admitted
    assert admitted > 0


def test_chaos_counters_ride_the_metrics_surfaces():
    """ISSUE-15 satellite: every injection the FaultInjector counts is
    mirrored to the shared registry as dht_chaos_injected_total
    {action=, rule=} — so it rides DhtRunner.get_metrics() and the
    proxy's GET /stats exposition with no extra plumbing."""
    from opendht_tpu import telemetry

    reg = telemetry.MetricsRegistry()
    plan = FaultPlan([Phase("lossy", rules=[
        LinkRule(name="wan", loss=1.0)])], seed=3)
    inj = FaultInjector(plan, registry=reg)
    inj.arm(0.0)
    for _ in range(7):
        inj.fate("x", "y", 0.1)
    snap = reg.snapshot()["counters"]
    key = 'dht_chaos_injected_total{action="dropped",rule="wan"}'
    assert snap.get(key) == inj.counts["wan"]["dropped"] == 7
    # the exposition GET /stats serves carries the same series
    assert 'dht_chaos_injected_total{action="dropped",rule="wan"} 7' \
        in reg.prometheus()
    # unarmed-by-default injectors fall back to the process registry —
    # the path live nodes take (Config.chaos_enabled)
    g_inj = FaultInjector(plan)
    g_inj.arm(0.0)
    g0 = telemetry.get_registry().snapshot()["counters"].get(key, 0)
    g_inj.fate("x", "y", 0.1)
    assert telemetry.get_registry().snapshot()["counters"][key] == g0 + 1
