"""Round-14 health observatory (ISSUE-9): burn-rate window math
(fast-burn vs slow-burn detection), verdict hysteresis (no flapping on
a boundary value), the healthy-unknown zero-traffic contract, the
batched replica-coverage probe pinned vs a per-key scalar oracle
(including a t-sharded resolve and a census smaller than k), flight-
recorder filtering (eviction order unchanged), and kernel bit-identity
with the health tick enabled."""

import numpy as np
import pytest

import jax

from opendht_tpu import health, telemetry, tracing
from opendht_tpu.health import (
    DEGRADED, HEALTHY, UNHEALTHY, HealthConfig, HealthEvaluator,
    SloObjective, parse_alerts, percentile_breaches,
    quantile_from_cumulative)
from opendht_tpu.infohash import InfoHash
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


def _rand_hash(rng):
    return InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))


class _Env:
    """Fresh registry + tracer + manual clock per test — the evaluator
    must never need the process-global singletons."""

    def __init__(self, **cfg_kw):
        self.reg = telemetry.MetricsRegistry()
        self.tr = tracing.Tracer(capacity=256, node="healthtest")
        self.t = 0.0
        self.cfg = HealthConfig(**cfg_kw)
        self.ev = HealthEvaluator(self.cfg, registry=self.reg,
                                  tracer=self.tr, clock=lambda: self.t)

    def ops(self, op="get", ok=0, bad=0):
        if ok:
            self.reg.counter("dht_ops_total", op=op, ok="true").inc(ok)
        if bad:
            self.reg.counter("dht_ops_total", op=op, ok="false").inc(bad)

    def tick(self, at=None):
        if at is not None:
            self.t = at
        return self.ev.tick()


# ------------------------------------------------------ burn-rate windows
def test_empty_registry_reports_healthy_unknown():
    """Zero traffic / empty registry must report healthy-unknown, never
    unhealthy (absence of evidence is not an outage)."""
    env = _Env()
    r = env.tick(0.0)
    r = env.tick(1.0)
    assert r["verdict"] == HEALTHY
    assert "get_availability" in r["unknown"]
    assert r["slo"]["get_availability"]["unknown"]
    assert r["slo"]["get_availability"]["fast"]["burn"] is None
    # only the boot transition (unknown -> healthy); no flapping after
    evs = env.tr.events(name="health_transition")
    assert [(e["attrs"]["from"], e["attrs"]["to"]) for e in evs] == \
        [("unknown", "healthy")]


def test_fast_burn_detects_total_failure():
    """A sudden 100% failure rate trips the fast window within one
    tick — burn = 1.0 / 0.01 budget = 100 >= 14.4."""
    env = _Env(fast_window=10.0, slow_window=100.0)
    env.tick(0.0)                              # baseline snapshot
    env.ops(bad=10)
    r = env.tick(2.0)
    assert r["verdict"] == UNHEALTHY
    assert r["slo"]["get_availability"]["level"] == UNHEALTHY
    assert r["slo"]["get_availability"]["fast"]["burn"] == \
        pytest.approx(100.0, rel=1e-6)
    assert "get_availability" in r["causes"]
    evs = env.tr.events(name="slo_violation")
    assert evs and evs[-1]["attrs"]["objective"] == "get_availability"
    assert env.tr.events(name="health_transition")


def test_slow_burn_detected_where_fast_is_not():
    """A sustained modest budget leak (30% errors vs a 90% objective =
    3x burn) never trips the high fast threshold but does trip the slow
    one: degraded, not unhealthy."""
    env = _Env(fast_window=5.0, fast_burn=20.0,
               slow_window=60.0, slow_burn=2.0)
    env.cfg.slos = (SloObjective("get_availability", "get",
                                 "availability", 0.9),)
    env.ev = HealthEvaluator(env.cfg, registry=env.reg, tracer=env.tr,
                             clock=lambda: env.t)
    env.tick(0.0)
    for i in range(1, 30):
        env.ops(ok=7, bad=3)
        r = env.tick(float(i))
    slo = r["slo"]["get_availability"]
    assert slo["fast"]["burn"] == pytest.approx(3.0, rel=1e-6)
    assert r["verdict"] == DEGRADED
    assert slo["level"] == DEGRADED


def test_min_events_guards_tiny_windows():
    """One failed op at boot is not an outage: windows below
    ``min_events`` never trip."""
    env = _Env(min_events=4)
    env.tick(0.0)
    env.ops(bad=2)
    r = env.tick(1.0)
    assert r["verdict"] == HEALTHY
    assert r["slo"]["get_availability"]["fast"]["burn"] is None


def test_verdict_hysteresis_no_flap_on_boundary():
    """An error rate oscillating around the trip threshold must not
    flap the verdict: once degraded, clearing requires dropping below
    recover_ratio x threshold."""
    env = _Env(fast_window=0.5, fast_burn=1e9,
               slow_window=1.0, slow_burn=2.0, recover_ratio=0.8,
               min_events=1)
    env.cfg.slos = (SloObjective("get_availability", "get",
                                 "availability", 0.9),)
    env.ev = HealthEvaluator(env.cfg, registry=env.reg, tracer=env.tr,
                             clock=lambda: env.t)
    env.tick(0.0)
    verdicts = []
    # windowed per-tick rates: 0.25 (trip), 0.19 (boundary, burn 1.9 —
    # above the 1.6 clear line), 0.21, then 0.05 (clear)
    for ok, bad in ((75, 25), (81, 19), (79, 21), (95, 5)):
        env.ops(ok=ok, bad=bad)
        verdicts.append(env.tick(env.t + 1.0)["verdict"])
    assert verdicts == [DEGRADED, DEGRADED, DEGRADED, HEALTHY]
    transitions = [e["attrs"] for e in
                   env.tr.events(name="health_transition")]
    assert [(t["from"], t["to"]) for t in transitions] == \
        [("unknown", "healthy"), ("healthy", "degraded"),
         ("degraded", "healthy")]


def test_latency_slo_over_threshold_fraction():
    """Latency objectives reduce to the same burn-rate machine: bad =
    observations over threshold_s (exact at power-of-two thresholds —
    the log-bucket edge)."""
    env = _Env(fast_window=10.0, fast_burn=5.0, slow_window=100.0)
    env.cfg.slos = (SloObjective("get_latency", "get", "latency",
                                 0.9, threshold_s=1.0),)
    env.ev = HealthEvaluator(env.cfg, registry=env.reg, tracer=env.tr,
                             clock=lambda: env.t)
    h = env.reg.histogram("dht_op_seconds", op="get")
    env.tick(0.0)
    for _ in range(20):
        h.observe(0.4)
    r = env.tick(1.0)
    assert r["verdict"] == HEALTHY
    for _ in range(20):
        h.observe(4.0)
    r = env.tick(2.0)
    slo = r["slo"]["get_latency"]
    assert slo["fast"]["bad"] == pytest.approx(20.0)
    assert r["verdict"] == UNHEALTHY


def test_latch_decay_as_windows_roll_past_failure():
    """A violating objective stays latched while the failure is inside
    its window, then DECAYS as each window rolls past it — a drained
    node (503 → LB sends nothing → zero new events) must not hold
    unhealthy forever (review finding).  Fast clears first (shorter
    window → degraded via the still-latched slow pair), then slow."""
    env = _Env(fast_window=2.0, slow_window=4.0)
    env.tick(0.0)
    env.ops(bad=10)
    assert env.tick(1.0)["verdict"] == UNHEALTHY
    # failure still inside both windows: zero new traffic keeps state
    assert env.tick(1.5)["verdict"] == UNHEALTHY
    assert env.tick(1.8)["verdict"] == UNHEALTHY
    # fast window (2 s) has rolled past the burst; slow (4 s) has not
    assert env.tick(4.0)["verdict"] == DEGRADED
    # slow window rolls past too: fully recovered with zero traffic
    assert env.tick(7.0)["verdict"] == HEALTHY


# -------------------------------------------------------------- signals
def test_signal_thresholds_and_hysteresis():
    vals = {"x": 0.0}
    env = _Env()
    env.cfg.slos = ()
    env.cfg.signal_thresholds["ingest_queue"] = (0.5, 0.9)
    env.ev = HealthEvaluator(env.cfg, registry=env.reg, tracer=env.tr,
                             clock=lambda: env.t,
                             providers={"ingest_queue":
                                        lambda: vals["x"]})
    assert env.tick(0.0)["verdict"] == HEALTHY
    vals["x"] = 0.6
    r = env.tick(1.0)
    assert r["verdict"] == DEGRADED and r["causes"] == ["ingest_queue"]
    vals["x"] = 0.95
    assert env.tick(2.0)["verdict"] == UNHEALTHY
    # hysteresis: 0.75 is below the 0.9 unhealthy line but above the
    # 0.72 (= 0.9 * 0.8) clear line — stays unhealthy
    vals["x"] = 0.75
    assert env.tick(3.0)["verdict"] == UNHEALTHY
    vals["x"] = 0.1
    assert env.tick(4.0)["verdict"] == HEALTHY


def test_unknown_signal_keeps_previous_level():
    vals = {"x": 0.95}
    env = _Env()
    env.cfg.slos = ()
    env.ev = HealthEvaluator(env.cfg, registry=env.reg, tracer=env.tr,
                             clock=lambda: env.t,
                             providers={"ingest_queue":
                                        lambda: vals["x"]})
    assert env.tick(0.0)["verdict"] == UNHEALTHY
    vals["x"] = None
    r = env.tick(1.0)
    assert r["verdict"] == UNHEALTHY
    assert "ingest_queue" in r["unknown"]


def test_gauges_exported_on_tick():
    env = _Env()
    env.tick(0.0)
    env.ops(bad=10)
    env.tick(1.0)
    snap = env.reg.snapshot()
    assert snap["gauges"]["dht_health_status"] == 2.0
    assert snap["gauges"][
        'dht_slo_violation{objective="get_availability"}'] == 2.0
    assert 'dht_slo_burn_rate{objective="get_availability"'\
        ',window="fast"}' in snap["gauges"]
    assert 'dht_health_signal{signal="timeout_ratio"}' in snap["gauges"]


# ------------------------------------------------------- shared helpers
def test_parse_alerts_shared_grammar():
    assert parse_alerts(["p95=2.5", "50=1"]) == {95.0: 2.5, 50.0: 1.0}
    assert parse_alerts([]) == {}
    with pytest.raises(ValueError):
        parse_alerts(["p95"])
    with pytest.raises(ValueError):
        parse_alerts(["p101=4"])


def test_percentile_breaches():
    alerts = {50.0: 1.0, 95.0: 2.0}
    out = percentile_breaches(lambda q: 1.5 if q < 0.9 else 1.9, alerts)
    assert out == [(50.0, 1.5, 1.0)]
    assert percentile_breaches(lambda q: None, alerts) == []


def test_quantile_from_cumulative_matches_histogram():
    h = telemetry.Histogram()
    rng = np.random.default_rng(7)
    for v in rng.uniform(0.001, 4.0, 500):
        h.observe(float(v))
    d = h.to_dict()
    pairs = []
    cum = 0
    for le, c in d["buckets"]:
        cum += c
        pairs.append((le, cum))
    for q in (0.5, 0.95, 0.99):
        assert quantile_from_cumulative(pairs, q) == \
            pytest.approx(h.quantile(q), rel=1e-9)
    assert quantile_from_cumulative([], 0.5) is None


def test_stale_signal_gated_on_bucket_occupancy():
    """The stale-bucket fraction only counts for families with enough
    occupied buckets — a 2-bucket bootstrap table's 0→1 swings are
    noise, not a verdict input (review finding: fresh 3-node clusters
    flapped to degraded on this signal)."""

    class _Sched:
        time = staticmethod(lambda: 0.0)

    class _WB:
        enabled = False
        queue_max = 0
        pending = staticmethod(lambda: 0)

    class _Dht:
        scheduler = _Sched()
        wave_builder = _WB()
        myid = "fakenode"

        def get_status(self):
            from opendht_tpu.runtime.config import NodeStatus
            return NodeStatus.CONNECTED

    nh = health.NodeHealth(_Dht())
    # ingest saturation: a zero queue bound sheds every op — the MOST
    # saturated state, not the least (review finding)
    _Dht.wave_builder.enabled = True
    assert nh._ingest_queue() == 1.0
    _Dht.wave_builder.enabled = False
    assert nh._ingest_queue() == 0.0
    reg = telemetry.get_registry()
    me = {"node": "fakenode"}
    reg.gauge("dht_maintenance_stale_fraction",
              family="ipv4", **me).set(1.0)
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv4", **me).set(2)
    reg.gauge("dht_maintenance_stale_fraction",
              family="ipv6", **me).set(0.2)
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv6", **me).set(12)
    # a co-resident node's sweep must never feed THIS node's signal
    # (the gauges are node-keyed — review finding)
    reg.gauge("dht_maintenance_stale_fraction",
              family="ipv4", node="other").set(1.0)
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv4", node="other").set(100)
    # own ipv4 is below the occupancy floor: only own ipv6's 0.2 counts
    assert nh._stale_buckets() == pytest.approx(0.2)
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv4", **me).set(20)
    assert nh._stale_buckets() == pytest.approx(1.0)
    # both own families below the floor -> unknown, never a trip
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv4", **me).set(1)
    reg.gauge("dht_maintenance_occupied_buckets",
              family="ipv6", **me).set(1)
    assert nh._stale_buckets() is None


# ------------------------------------------------ replica-coverage probe
def _census(n_nodes, rng):
    from opendht_tpu.testing.health_monitor import census_table
    ids = [_rand_hash(rng) for _ in range(n_nodes)]
    nodes = [(nid, SockAddr("127.0.0.1", 1000 + i))
             for i, nid in enumerate(ids)]
    return census_table(nodes, now=100.0), ids


def _scalar_oracle(table, keys, k):
    from opendht_tpu.testing.health_monitor import closest_ids
    return [closest_ids(table, [key], k=k, now=100.0)[0] for key in keys]


def test_census_table_holds_every_node():
    """A census must hold ALL live nodes — k-bucket admission (which a
    routing table legitimately uses to cache-and-drop far peers) is
    widened to the census size."""
    rng = np.random.default_rng(3)
    table, ids = _census(64, rng)
    assert len(table) == 64


def test_replica_probe_batched_matches_scalar_oracle():
    from opendht_tpu.testing.health_monitor import closest_ids
    rng = np.random.default_rng(5)
    table, _ids = _census(24, rng)
    keys = [_rand_hash(rng) for _ in range(20)]
    batched = closest_ids(table, keys, k=8, now=100.0)
    oracle = _scalar_oracle(table, keys, 8)
    assert [[str(i) for i in row] for row in batched] == \
        [[str(i) for i in row] for row in oracle]
    assert all(len(row) == 8 for row in batched)


def test_replica_probe_fewer_than_k_nodes():
    """A census smaller than k returns every live node, ordered by XOR
    distance — never padded rows."""
    from opendht_tpu.testing.health_monitor import closest_ids
    rng = np.random.default_rng(6)
    table, ids = _census(5, rng)
    keys = [_rand_hash(rng) for _ in range(7)]
    batched = closest_ids(table, keys, k=8, now=100.0)
    oracle = _scalar_oracle(table, keys, 8)
    assert [[str(i) for i in row] for row in batched] == \
        [[str(i) for i in row] for row in oracle]
    want = {str(i) for i in ids}
    for row in batched:
        assert len(row) == 5 and {str(i) for i in row} == want


def test_replica_probe_tsharded_matches_oracle():
    """The probe riding the t-sharded resolve (round 13) stays pinned
    to the per-key scalar oracle.  >64 keys forces the device snapshot
    path (HOST_SCAN_MAX_QUERIES), where the mesh is honored."""
    from opendht_tpu.parallel import make_mesh
    from opendht_tpu.testing.health_monitor import closest_ids
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    rng = np.random.default_rng(8)
    table, _ids = _census(96, rng)
    keys = [_rand_hash(rng) for _ in range(80)]
    mesh = make_mesh(2, q=1, t=2)
    sharded = closest_ids(table, keys, k=8, mesh=mesh, now=100.0)
    oracle = _scalar_oracle(table, keys, 8)
    assert [[str(i) for i in row] for row in sharded] == \
        [[str(i) for i in row] for row in oracle]


def test_replica_coverage_end_to_end_fake_runners():
    """Coverage accounting over fake runner objects: a value held by
    every census node scores 1.0, a value held nowhere scores 0.0."""
    from opendht_tpu.testing import health_monitor as hm

    class _St:
        def __init__(self, has):
            self._has = has

        def empty(self):
            return not self._has

    class _FakeRunner:
        def __init__(self, nid, store):
            self._nid = nid
            self._dht = type("D", (), {"store": store})()

        def get_node_id(self):
            return self._nid

        def get_bound_port(self):
            return 4000

    rng = np.random.default_rng(9)
    ids = [_rand_hash(rng) for _ in range(4)]
    k_full, k_none = _rand_hash(rng), _rand_hash(rng)
    runners = [_FakeRunner(nid, {k_full: _St(True), k_none: _St(False)})
               for nid in ids]
    cov = hm.replica_coverage(runners, k=8)
    assert cov["keys"] == 1                  # k_none stored nowhere
    assert cov["mean_coverage"] == 1.0
    per = {p["key"]: p for p in cov["per_key"]}
    assert per[k_full.hex()]["expected"] == 4


# ------------------------------------------------ flight-recorder filter
def test_flight_filter_is_readside_and_eviction_unchanged():
    """``dump(name=)`` is a read-side projection: the ring contents and
    eviction order are identical before and after filtered dumps."""
    tr = tracing.Tracer(capacity=8, node="f")
    for i in range(20):
        tr.event("alpha_ev" if i % 2 == 0 else "beta_ev", i=i)
    before = [r["attrs"]["i"] for r in tr.records()]
    d = tr.dump(name="alpha")
    assert [e["attrs"]["i"] for e in d["events"]] == \
        [i for i in before if i % 2 == 0]
    assert all(e["ev"] == "alpha_ev" for e in d["events"])
    # eviction order (oldest evicted, capacity retained) unchanged by
    # the filtered dump
    after = [r["attrs"]["i"] for r in tr.records()]
    assert after == before == list(range(12, 20))
    # unfiltered dump still returns everything
    assert len(tr.dump()["events"]) == 8
    # span names filter through the same parameter
    sp = tr.span("alpha_span")
    sp.end()
    tr.event("beta_ev", i=99)
    d = tr.dump(name="alpha")
    assert [s["name"] for s in d["spans"]] == ["alpha_span"]
    assert all("alpha" in e["ev"] for e in d["events"])


# ------------------------------------------- kernels + tick bit-identity
def test_kernels_bit_identical_with_health_tick():
    """The health tick is host-side snapshot subtraction only: the
    shipped search engine's outputs are bit-identical with an evaluator
    ticking between launches."""
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut,
                                              default_lut_bits,
                                              sort_table)
    key = jax.random.PRNGKey(14)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (2048, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (64, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = build_prefix_lut(sorted_ids, n_valid,
                           bits=default_lut_bits(2048))

    def wave():
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        return jax.block_until_ready(out)

    base = wave()
    env = _Env()
    env.tick(0.0)
    env.reg.counter("dht_ops_total", op="get", ok="true").inc(5)
    env.tick(1.0)
    ticked = wave()
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(ticked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------- evaluator cheapness
def test_config_carries_health_and_runner_surfaces():
    """Config.health is the declarative knob surface; period=0 keeps
    the runner from attaching an evaluator (get_health → unknown)."""
    from opendht_tpu.runtime.config import Config
    cfg = Config()
    assert cfg.health.period == 1.0
    assert any(o.name == "get_availability" for o in cfg.health.slos)
    from opendht_tpu.runtime.runner import DhtRunner
    r = DhtRunner()             # not started: health surface still sane
    rep = r.get_health()
    assert rep["verdict"] == "unknown" and rep["enabled"] is False


# ------------------------------------- dhtmon --window skip + imbalance
def test_dhtmon_window_skips_second_scrape_when_not_windowed(monkeypatch):
    """ISSUE-10 satellite: --window only windows the success/latency
    invariants — when neither is requested (readiness/imbalance/
    coverage-only runs), the baseline scrape and the wait are skipped
    (the old path scraped every node twice and slept for nothing)."""
    from opendht_tpu.tools import dhtmon
    from opendht_tpu.testing import health_monitor as hm

    calls = []
    fake = {"ready": True, "verdict": "healthy", "health": {},
            "series": {'dht_shard_imbalance{node="x"}': 6.5,
                       'dht_ops_total{ok="true",op="get"}': 10.0}}

    def fake_scrape(ep, timeout=10.0):
        calls.append(ep)
        return dict(fake, endpoint=ep)

    slept = []
    monkeypatch.setattr(hm, "scrape_node", fake_scrape)
    monkeypatch.setattr(dhtmon.time, "sleep", lambda s: slept.append(s))

    # imbalance-only + window: ONE scrape per endpoint, no sleep, and
    # the report says the window did not apply
    v, doc = dhtmon.run_checks(["n1", "n2"], window=5.0,
                               max_imbalance=5.0)
    assert len(calls) == 2 and slept == []
    assert doc["window_s"] is None
    assert any("imbalance 6.5" in s for s in v)
    assert doc["shard_imbalance"]["max"] == 6.5

    # a windowed invariant requested: baseline + wait + re-scrape
    calls.clear()
    v, doc = dhtmon.run_checks(["n1"], window=5.0, min_success=0.5)
    assert len(calls) == 2 and slept == [5.0]
    assert doc["window_s"] == 5.0
    # windowed diff of identical cumulative scrapes = zero traffic →
    # success unknown, not a violation
    assert doc["lookup_success"] is None and v == []


def test_dhtmon_imbalance_unknown_never_violates(monkeypatch):
    from opendht_tpu.tools import dhtmon
    from opendht_tpu.testing import health_monitor as hm
    fake = {"ready": True, "verdict": "healthy", "health": {},
            "series": {'dht_shard_imbalance{node="x"}': -1.0}}
    monkeypatch.setattr(hm, "scrape_node",
                        lambda ep, timeout=10.0: dict(fake, endpoint=ep))
    v, doc = dhtmon.run_checks(["n1"], max_imbalance=1.5)
    assert v == []
    assert doc["shard_imbalance"]["max"] is None
    # a known value over the gate violates, and the worst node is named
    fake["series"]['dht_shard_imbalance{node="x"}'] = 2.0
    v, doc = dhtmon.run_checks(["n1"], max_imbalance=1.5)
    assert len(v) == 1 and "n1" in v[0]


# ----------------------------------------- dhtmon --max-listener-lag
def test_dhtmon_listener_lag_gate(monkeypatch):
    """ISSUE-20 satellite: --max-listener-lag gates the worst node's
    dht_listener_lag_p95 gauge (windowed store->dispatch lag through
    the round-24 wave-batched match) with the --max-imbalance unknown
    contract: -1/absent never violates."""
    from opendht_tpu.tools import dhtmon
    from opendht_tpu.testing import health_monitor as hm
    scrapes = {
        "n1": {'dht_listener_lag_p95{node="a"}': 0.004},
        "n2": {'dht_listener_lag_p95{node="b"}': 0.200},
    }
    monkeypatch.setattr(
        hm, "scrape_node",
        lambda ep, timeout=10.0: {"endpoint": ep, "ready": True,
                                  "verdict": "healthy", "health": {},
                                  "series": dict(scrapes[ep])})
    # worst node over the gate violates and is named
    v, doc = dhtmon.run_checks(["n1", "n2"], max_listener_lag=0.05)
    assert len(v) == 1 and "n2" in v[0] and "0.2000" in v[0]
    assert doc["listener_lag"]["max"] == 0.200
    # both under the gate: healthy, report carries the worst value
    v, doc = dhtmon.run_checks(["n1", "n2"], max_listener_lag=0.5)
    assert v == []
    assert doc["listener_lag"]["max"] == 0.200


def test_dhtmon_listener_lag_unknown_never_violates(monkeypatch):
    from opendht_tpu.tools import dhtmon
    from opendht_tpu.testing import health_monitor as hm
    fake = {"ready": True, "verdict": "healthy", "health": {},
            "series": {'dht_listener_lag_p95{node="x"}': -1.0}}
    monkeypatch.setattr(hm, "scrape_node",
                        lambda ep, timeout=10.0: dict(fake, endpoint=ep))
    # -1 = unknown (table off / dark / no delivery window): no violation
    v, doc = dhtmon.run_checks(["n1"], max_listener_lag=0.01)
    assert v == []
    assert doc["listener_lag"]["max"] is None
    # absent series: same
    fake["series"] = {}
    v, doc = dhtmon.run_checks(["n1"], max_listener_lag=0.01)
    assert v == []
    assert doc["listener_lag"]["max"] is None
    # the CLI rejects a gate violation with exit 1 and names the node
    fake["series"] = {'dht_listener_lag_p95{node="x"}': 0.5}
    rc = dhtmon.main(["--nodes", "n1", "--max-listener-lag", "0.01"])
    assert rc == 1
