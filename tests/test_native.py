"""Native C++ engine tests: scalar-kernel parity with the Python
InfoHash reference, sorted-walk vs full-scan agreement, and the UDP
engine's loopback datagram path + ingress guards."""

import time

import numpy as np
import pytest

from opendht_tpu.infohash import InfoHash
from opendht_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def _rand_ids(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, 20), dtype=np.uint8)


# ------------------------------------------------------------ scalar parity

def test_xor_cmp_matches_python():
    ids = _rand_ids(64, 1)
    s = InfoHash(bytes(ids[0]))
    for i in range(1, 31, 3):
        a, b = InfoHash(bytes(ids[i])), InfoHash(bytes(ids[i + 1]))
        assert native.xor_cmp(bytes(s), bytes(a), bytes(b)) == \
            s.xor_cmp(a, b)
    assert native.xor_cmp(bytes(s), bytes(ids[5]), bytes(ids[5])) == 0


def test_common_bits_matches_python():
    ids = _rand_ids(32, 2)
    for i in range(0, 30, 2):
        a, b = InfoHash(bytes(ids[i])), InfoHash(bytes(ids[i + 1]))
        assert native.common_bits(bytes(a), bytes(b)) == \
            InfoHash.common_bits(a, b)
    a = InfoHash(bytes(ids[0]))
    assert native.common_bits(bytes(a), bytes(a)) == 160


# ------------------------------------------------------------- table lookup

def test_sorted_walk_equals_full_scan():
    ids = _rand_ids(500, 3)
    queries = _rand_ids(40, 4)
    sorted_ids, perm = native.sort_ids(ids)
    walk = native.sorted_closest(sorted_ids, queries, k=8, window=64)
    scan = native.scan_closest(ids, queries, k=8)
    # map walk's sorted indices back to original rows
    walk_rows = np.where(walk >= 0, perm[np.clip(walk, 0, None)], -1)
    assert np.array_equal(walk_rows, scan)


def test_sorted_walk_matches_device_kernel():
    """Native outward walk == JAX full-scan oracle (ops/xor_topk)."""
    import jax.numpy as jnp
    from opendht_tpu.ops.ids import ids_from_bytes
    from opendht_tpu.ops.xor_topk import xor_topk

    ids = _rand_ids(300, 5)
    queries = _rand_ids(17, 6)
    sorted_ids, perm = native.sort_ids(ids)
    walk = native.sorted_closest(sorted_ids, queries, k=8)
    walk_rows = np.where(walk >= 0, perm[np.clip(walk, 0, None)], -1)

    _, idx = xor_topk(jnp.asarray(ids_from_bytes(queries)),
                      jnp.asarray(ids_from_bytes(ids)), k=8)
    assert np.array_equal(walk_rows, np.asarray(idx))


def test_clustered_table_certificate_fallback():
    """Adversarially clustered ids (hundreds sharing a prefix) defeat a
    fixed window; the native certificate must trigger the full-scan
    fallback so results stay exact even with a tiny window."""
    ids = _rand_ids(300, 9)
    ids[:200, :6] = 0xAB                 # 200 ids share a 48-bit prefix
    queries = _rand_ids(25, 10)
    queries[:10, :6] = 0xAB              # some queries land in the cluster
    sorted_ids, perm = native.sort_ids(ids)
    walk = native.sorted_closest(sorted_ids, queries, k=8, window=16)
    scan = native.scan_closest(ids, queries, k=8)
    walk_rows = np.where(walk >= 0, perm[np.clip(walk, 0, None)], -1)
    # fallback results are original-row indices already mapped via the
    # sorted table; map both sides to distances for comparison
    def dist(i, q):
        return bytes(a ^ b for a, b in zip(ids[i], queries[q]))
    for qi in range(queries.shape[0]):
        got = sorted(dist(i, qi) for i in walk_rows[qi])
        want = sorted(dist(i, qi) for i in scan[qi])
        assert got == want, qi


def test_small_table_padding():
    ids = _rand_ids(3, 7)
    queries = _rand_ids(2, 8)
    sorted_ids, perm = native.sort_ids(ids)
    out = native.sorted_closest(sorted_ids, queries, k=8)
    assert (out[:, :3] >= 0).all() and (out[:, 3:] == -1).all()


# --------------------------------------------------------------- UDP engine

def test_udp_loopback_roundtrip():
    with native.UdpEngine(0) as a, native.UdpEngine(0) as b:
        assert a.port > 0 and b.port > 0
        assert a.send(b"ping-payload", ("127.0.0.1", b.port)) == 0
        deadline = time.monotonic() + 5.0
        pkts = []
        while not pkts and time.monotonic() < deadline:
            pkts = b.poll()
            time.sleep(0.01)
        assert pkts, "packet never arrived"
        rx_time, data, (host, port) = pkts[0]
        assert data == b"ping-payload"
        assert host == "127.0.0.1" and port == a.port
        assert rx_time > 0
        st = b.stats()
        assert st["rx"] == 1 and st["queued"] == 0


def test_udp_rate_limit_drops():
    with native.UdpEngine(0) as a, \
            native.UdpEngine(0, per_ip_rps=10, global_rps=10,
                             exempt_loopback=False) as b:
        for i in range(50):
            a.send(b"x%d" % i, ("127.0.0.1", b.port))
        time.sleep(0.5)
        got = len(b.poll(max_pkts=100))
        st = b.stats()
        assert got <= 10
        assert st["dropped_rate"] >= 30


def test_udp_loopback_exempt_from_limits():
    """Default engines never rate-limit 127.0.0.1 sources (local
    clusters share that IP)."""
    with native.UdpEngine(0) as a, \
            native.UdpEngine(0, per_ip_rps=5, global_rps=5) as b:
        for i in range(40):
            a.send(b"y%d" % i, ("127.0.0.1", b.port))
        deadline = time.monotonic() + 5.0
        got = []
        while len(got) < 40 and time.monotonic() < deadline:
            got.extend(b.poll(max_pkts=64))
            time.sleep(0.01)
        assert len(got) == 40
        assert b.stats()["dropped_rate"] == 0


def test_udp_v6_loopback_exempt_from_limits():
    """::1 joins the 127/8 rate-limit exemption (local v6 clusters share
    that source the same way v4 ones share 127.0.0.1)."""
    with native.UdpEngine(0) as a, \
            native.UdpEngine(0, per_ip_rps=5, global_rps=5) as b:
        if not (a.has_v6 and b.has_v6):
            pytest.skip("no IPv6 on this host")
        for i in range(40):
            a.send(b"z%d" % i, ("::1", b.port))
        deadline = time.monotonic() + 5.0
        got = []
        while len(got) < 40 and time.monotonic() < deadline:
            got.extend(b.poll(max_pkts=64))
            time.sleep(0.01)
        assert len(got) == 40
        assert b.stats()["dropped_rate"] == 0


def test_udp_batch_poll():
    with native.UdpEngine(0) as a, native.UdpEngine(0) as b:
        for i in range(20):
            a.send(("msg-%02d" % i).encode(), ("127.0.0.1", b.port))
        deadline = time.monotonic() + 5.0
        got = []
        while len(got) < 20 and time.monotonic() < deadline:
            got.extend(b.poll(max_pkts=64))
            time.sleep(0.01)
        assert len(got) == 20
        assert [p[1] for p in got] == \
            [("msg-%02d" % i).encode() for i in range(20)]


def test_helpers_raise_without_lib(monkeypatch):
    # On hosts without a toolchain get_lib() returns None; module-level
    # helpers must raise the actionable RuntimeError, not AttributeError.
    import pytest
    from opendht_tpu.native import wrappers
    monkeypatch.setattr(wrappers, "get_lib", lambda: None)
    with pytest.raises(RuntimeError, match="native library unavailable"):
        wrappers.common_bits(b"\0" * 20, b"\0" * 20)
    with pytest.raises(RuntimeError, match="native library unavailable"):
        wrappers.UdpEngine(0)


def test_udp_v6_roundtrip():
    with native.UdpEngine(0) as a, native.UdpEngine(0) as b:
        if not (a.has_v6 and b.has_v6):
            pytest.skip("no IPv6 on this host")
        a.send(b"over six", ("::1", b.port))
        deadline = time.monotonic() + 5.0
        got = []
        while not got and time.monotonic() < deadline:
            got.extend(b.poll())
            time.sleep(0.01)
        assert got and got[0][1] == b"over six"
        assert got[0][2] == ("::1", a.port)


def test_udp_dual_stack_same_port():
    with native.UdpEngine(0) as a, native.UdpEngine(0) as b:
        if not b.has_v6:
            pytest.skip("no IPv6 on this host")
        a.send(b"via four", ("127.0.0.1", b.port))
        a.send(b"via six", ("::1", b.port))
        deadline = time.monotonic() + 5.0
        got = []
        while len(got) < 2 and time.monotonic() < deadline:
            got.extend(b.poll())
            time.sleep(0.01)
        assert {p[1] for p in got} == {b"via four", b"via six"}
        fams = {(":" in p[2][0]) for p in got}
        assert fams == {True, False}


def test_udp_v6_disabled():
    with native.UdpEngine(0, ipv6=False) as e:
        assert not e.has_v6
        assert e.send(b"x", ("::1", 1)) != 0     # EAFNOSUPPORT
