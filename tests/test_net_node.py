"""First unit coverage for net/node.py (ISSUE-19 satellite): the
liveness classification boundaries the round-23 per-peer ledger
mirrors (reference node.h:79-92, node.cpp:39-46), the strict
``time > reply_time`` incoming rule, reset/expiry bookkeeping, auth
strikes, tid generation, and the request-side seams the ledger hangs
off (``Request.is_expired`` honouring the per-peer ``rto``, and the
censored-attempt counter ticked at the EXPIRED transition)."""

import pytest

from opendht_tpu import telemetry
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import MessageType
from opendht_tpu.net.node import (
    MAX_AUTH_ERRORS, MAX_RESPONSE_TIME, NODE_EXPIRE_TIME,
    NODE_GOOD_TIME, Node)
from opendht_tpu.net.request import MAX_ATTEMPT_COUNT, Request
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

NOW = 1_000_000.0


def _node(name="peer"):
    return Node(InfoHash.get(name), SockAddr("10.0.0.9", 4009))


def _req(node, tid=1):
    return Request(MessageType.PING, tid, node, b"", None, None)


# ----------------------------------------------- liveness boundaries
def test_is_good_boundaries():
    """is_good = replied within NODE_GOOD_TIME AND heard within
    NODE_EXPIRE_TIME (both inclusive, node.h:79-82) AND not expired."""
    n = _node()
    n.time = n.reply_time = NOW
    assert n.is_good(NOW)
    # reply exactly at the 2 h boundary still counts (>=)
    n.reply_time = NOW - NODE_GOOD_TIME
    assert n.is_good(NOW)
    n.reply_time = NOW - NODE_GOOD_TIME - 1e-3
    assert not n.is_good(NOW)
    # heard exactly at the 10 min boundary still counts
    n.reply_time = NOW
    n.time = NOW - NODE_EXPIRE_TIME
    assert n.is_good(NOW)
    n.time = NOW - NODE_EXPIRE_TIME - 1e-3
    assert not n.is_good(NOW)
    # the expired flag vetoes everything
    n.time = n.reply_time = NOW
    n.expired = True
    assert not n.is_good(NOW)
    # a never-heard node is neither good nor removable
    fresh = _node("fresh")
    assert not fresh.is_good(NOW)
    assert not fresh.is_removable(NOW)


def test_is_old_and_removable_boundaries():
    n = _node()
    n.time = NOW - NODE_EXPIRE_TIME
    # strict compare: time + NODE_EXPIRE_TIME < now
    assert not n.is_old(NOW)
    assert n.is_old(NOW + 1e-3)
    n.expired = True
    assert not n.is_removable(NOW)          # expired but not old yet
    assert n.is_removable(NOW + 1e-3)       # both
    n.expired = False
    assert not n.is_removable(NOW + 1e-3)   # old but not expired


def test_is_incoming_strict_rule():
    """time > reply_time, STRICT: a node we only heard from (never
    answered us) is incoming; a node whose last event was our reply
    is not."""
    n = _node()
    assert not n.is_incoming()              # both -inf: equal
    n.received(NOW)                          # heard, no reply
    assert n.is_incoming()
    req = _req(n)
    n.requested(req)
    n.received(NOW + 1.0, req)               # answered: time == reply_time
    assert not n.is_incoming()


# -------------------------------------------- received/reset/expiry
def test_received_updates_times_and_clears_expired():
    n = _node()
    n.set_expired()
    assert n.expired
    n.received(NOW)
    assert n.time == NOW and n.reply_time < NOW
    assert not n.expired
    req = _req(n, tid=7)
    n.requested(req)
    assert n.get_request(7) is req
    n.received(NOW + 2.0, req)
    assert n.reply_time == NOW + 2.0
    assert n.get_request(7) is None          # answered requests drop


def test_reset_clears_expired_and_reply_time_keeps_time():
    n = _node()
    req = _req(n)
    n.requested(req)
    n.received(NOW, req)
    n.set_expired()
    n.reset()
    assert not n.expired
    assert n.reply_time == float("-inf")     # must re-earn goodness
    assert n.time == NOW                     # but we did hear from it
    assert not n.is_good(NOW)


def test_set_expired_cascades_to_requests_and_sockets():
    n = _node()
    r1, r2 = _req(n, 1), _req(n, 2)
    n.requested(r1)
    n.requested(r2)
    sid = n.open_socket(lambda node, msg: None)
    assert n.get_socket(sid) is not None
    n.set_expired()
    assert n.expired
    assert r1.expired and r2.expired
    assert n.requests == {} and n.sockets == {}


def test_requested_replaces_stale_same_tid():
    n = _node()
    old, new = _req(n, 5), _req(n, 5)
    n.requested(old)
    n.requested(new)
    assert old.expired                       # the stale one is expired
    assert n.get_request(5) is new


def test_cancel_request_pops_and_cancels():
    n = _node()
    req = _req(n, 9)
    n.requested(req)
    n.cancel_request(req)
    assert req.cancelled
    assert n.get_request(9) is None
    n.cancel_request(None)                   # no-op, no crash


def test_auth_strikes_and_recovery():
    n = _node()
    for _ in range(MAX_AUTH_ERRORS):
        n.auth_error()
    assert not n.expired                     # at the limit: still in
    n.auth_error()                           # one past it
    assert n.expired
    n.auth_success()
    assert n.auth_errors == 0


def test_tid_generator_skips_zero_and_wraps():
    n = _node()
    n._tid = 0xFFFFFFFF
    assert n.get_new_tid() == 1              # 0 is reserved
    assert n.get_new_tid() == 2


# ------------------------------------------------ request-side seams
def test_request_is_expired_honours_per_peer_rto():
    """is_expired fires at last_try + rto INCLUSIVE; rto is the
    ledger's adaptive per-peer timeout when enabled and stays the
    fixed MAX_RESPONSE_TIME otherwise (ISSUE-19)."""
    n = _node()
    req = _req(n)
    req.attempt_count = MAX_ATTEMPT_COUNT
    req.last_try = NOW
    assert req.rto == MAX_RESPONSE_TIME      # the default is the pin
    assert not req.is_expired(NOW + MAX_RESPONSE_TIME - 1e-3)
    assert req.is_expired(NOW + MAX_RESPONSE_TIME)
    req.rto = 0.25                           # an adaptive fast peer
    assert req.is_expired(NOW + 0.25)
    req.rto = 2.5                            # a backed-off slow peer
    assert not req.is_expired(NOW + 1.0)
    assert req.is_expired(NOW + 2.5)
    # attempts not used up yet: never expired, whatever the clock says
    req.attempt_count = MAX_ATTEMPT_COUNT - 1
    assert not req.is_expired(NOW + 100.0)


def _attempt_timeouts_total():
    reg = telemetry.get_registry()
    return sum(m.value for m in
               reg.series("dht_net_attempt_timeouts_total").values())


def test_attempt_timeouts_counter_ticks_at_expired():
    """ISSUE-19 satellite: every attempt of an expired request timed
    out without reaching dht_net_rtt_seconds — the censored attempts
    are counted so loss shows up next to RTT instead of silently
    thinning the histogram."""
    n = _node()
    req = _req(n, 1)
    req.attempt_count = 3
    base = _attempt_timeouts_total()
    req.set_expired()
    assert _attempt_timeouts_total() == base + 3
    # a request expired before any attempt (node.set_expired) still
    # censored one solicited answer
    req0 = _req(n, 2)
    assert req0.attempt_count == 0
    base = _attempt_timeouts_total()
    req0.set_expired()
    assert _attempt_timeouts_total() == base + 1
    # cancellation does NOT touch the censored counter
    req1 = _req(n, 3)
    req1.attempt_count = 2
    base = _attempt_timeouts_total()
    req1.cancel()
    assert _attempt_timeouts_total() == base
