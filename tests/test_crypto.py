"""Crypto layer tests, mirroring the reference suite tests/cryptotester.cpp
(testSignatureEncryption :33-88, testCertificateRevocation) plus coverage of
the serialization/KDF helpers."""

import datetime

import pytest

from opendht_tpu import crypto
from opendht_tpu.infohash import InfoHash

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


@pytest.fixture(scope="module")
def identity():
    # small RSA keys keep the suite fast; 1024 still exercises every path
    return crypto.generate_identity("testsign", key_length=1024)


@pytest.fixture(scope="module")
def ec_identity():
    return crypto.generate_ec_identity("testsign-ec")


def test_sign_verify(identity):
    key = identity.first
    pk = key.public_key()
    data = b"hello dht" * 10
    sig = key.sign(data)
    assert pk.check_signature(data, sig)
    assert not pk.check_signature(data + b"!", sig)
    assert not pk.check_signature(data, sig[:-1] + bytes([sig[-1] ^ 1]))


def test_sign_verify_ec(ec_identity):
    key = ec_identity.first
    pk = key.public_key()
    data = b"elliptic"
    sig = key.sign(data)
    assert pk.check_signature(data, sig)
    assert not pk.check_signature(b"other", sig)


@pytest.mark.parametrize("size", [0, 1, 100, 500, 2000, 65536])
def test_encrypt_decrypt_roundtrip(identity, size):
    # cryptotester.cpp:45-58: both the plain-RSA and the hybrid path
    key = identity.first
    data = bytes(range(256)) * (size // 256) + bytes(range(size % 256))
    cipher = key.public_key().encrypt(data)
    assert key.decrypt(cipher) == data
    if size > key.public_key()._pk.key_size // 8 - 11:
        # hybrid layout: RSA block + IV + ct + tag
        assert len(cipher) == (key.public_key()._pk.key_size // 8
                               + crypto.GCM_IV_SIZE + size
                               + crypto.GCM_DIGEST_SIZE)


def test_decrypt_garbage_fails(identity):
    with pytest.raises(crypto.CryptoException):
        identity.first.decrypt(b"short")
    cipher = identity.first.public_key().encrypt(b"x" * 4000)
    bad = bytes([cipher[0] ^ 1]) + cipher[1:]
    with pytest.raises(crypto.CryptoException):
        identity.first.decrypt(bad)


def test_aes_roundtrip():
    key = bytes(range(32))
    data = b"secret payload"
    enc = crypto.aes_encrypt(data, key)
    assert crypto.aes_decrypt(enc, key) == data
    with pytest.raises(crypto.DecryptError):
        crypto.aes_decrypt(enc[:-1] + bytes([enc[-1] ^ 1]), key)
    with pytest.raises(crypto.DecryptError):
        crypto.aes_encrypt(data, b"badlen")


def test_aes_password_roundtrip():
    enc = crypto.aes_encrypt_password(b"data", "hunter2")
    assert crypto.aes_decrypt_password(enc, "hunter2") == b"data"
    with pytest.raises(crypto.DecryptError):
        crypto.aes_decrypt_password(enc, "wrong")


def test_stretch_key_deterministic():
    k1, salt = crypto.stretch_key("pw", None, 32)
    k2, _ = crypto.stretch_key("pw", salt, 32)
    assert k1 == k2 and len(k1) == 32
    k3, _ = crypto.stretch_key("pw2", salt, 32)
    assert k3 != k1


def test_argon2i_public_vector():
    # phc-winner-argon2 test.c: argon2i v1.3, t=2, m=2^16 KiB, p=1,
    # "password"/"somesalt" — pins that the KDF backing stretch_key is
    # real argon2i, not a stand-in.
    from argon2.low_level import hash_secret_raw, Type
    out = hash_secret_raw(b"password", b"somesalt", time_cost=2,
                          memory_cost=65536, parallelism=1, hash_len=32,
                          type=Type.I)
    assert out.hex() == ("c1628832147d9720c5bd1cfd61367078"
                         "729f6dfb6f8fea9ff98158e0d7816ed0")


def test_stretch_key_known_answer():
    # Frozen output of the reference stretchKey pipeline
    # (src/crypto.cpp:193-206): argon2i(t=16, m=64MiB, p=1, out=32)
    # then the length-selected digest.  Computed once with argon2-cffi
    # (official phc C implementation) and pinned so param drift fails.
    salt = b"\x02" * 16
    k32, _ = crypto.stretch_key("test password", salt, 32)
    assert k32.hex() == ("ac0c1cd67e16026dc8d1fdc3aa5e69ba"
                         "85035bcddc56d6aa87bc0b4424c4f1ab")


def test_password_decrypt_scrypt_legacy():
    # Blobs written by round-1 builds (scrypt KDF) must stay readable.
    import hashlib
    salt = b"\x07" * crypto.PASSWORD_SALT_LENGTH
    raw = hashlib.scrypt(b"hunter2", salt=salt, n=2 ** 15, r=8, p=1,
                         maxmem=64 * 1024 * 1024, dklen=32)
    legacy_key = crypto.hash_data(raw, 32)
    blob = salt + crypto.aes_encrypt(b"old data", legacy_key)
    assert crypto.aes_decrypt_password(blob, "hunter2") == b"old data"


def test_hash_by_length():
    import hashlib
    d = b"data"
    assert crypto.hash_data(d, 20) == hashlib.sha1(d).digest()
    assert crypto.hash_data(d, 32) == hashlib.sha256(d).digest()
    assert crypto.hash_data(d, 64) == hashlib.sha512(d).digest()


def test_key_serialize_roundtrip(identity):
    pem = identity.first.serialize()
    key2 = crypto.PrivateKey(pem)
    assert key2.public_key().get_id() == identity.first.public_key().get_id()
    enc = identity.first.serialize("pw")
    key3 = crypto.PrivateKey(enc, password="pw")
    assert key3.public_key().get_id() == identity.first.public_key().get_id()
    with pytest.raises(crypto.CryptoException):
        crypto.PrivateKey(enc, password="nope")


def test_public_key_der_roundtrip(identity):
    pk = identity.first.public_key()
    pk2 = crypto.PublicKey(pk.export_der())
    assert pk2.get_id() == pk.get_id()
    assert pk2 == pk
    data, sig = b"msg", identity.first.sign(b"msg")
    assert pk2.check_signature(data, sig)


def test_certificate_identity(identity):
    cert = identity.second
    assert cert.get_name() == "testsign"
    assert cert.get_uid() == str(identity.first.public_key().get_id())
    assert cert.get_id() == identity.first.public_key().get_id()
    assert cert.is_ca()  # no CA given → self-signed CA


def test_certificate_pack_roundtrip(identity):
    packed = identity.second.pack()
    cert2 = crypto.Certificate(packed)
    assert cert2.get_id() == identity.second.get_id()
    assert cert2.get_name() == "testsign"


def test_certificate_chain():
    ca = crypto.generate_identity("acme CA", key_length=1024)
    dev = crypto.generate_identity("acme device", ca, key_length=1024)
    assert not dev.second.is_ca()
    assert dev.second.get_issuer_name() == "acme CA"
    assert dev.second.issuer is not None
    assert dev.second.signed_by(ca.second)
    # chain survives pack/unpack (leaf-first concatenated DER)
    again = crypto.Certificate(dev.second.pack())
    assert again.issuer is not None
    assert again.issuer.get_id() == ca.second.get_id()
    assert again.signed_by(ca.second)


def test_trust_list_and_revocation():
    # cryptotester.cpp:33-60: device cert trusted via CA, then revoked
    ca = crypto.generate_identity("acme CA", key_length=1024)
    dev = crypto.generate_identity("acme device", ca, key_length=1024)
    other = crypto.generate_identity("other dev", key_length=1024)

    tl = crypto.TrustList()
    tl.add(ca.second)
    assert tl.verify(dev.second)
    assert not tl.verify(other.second)

    crl = crypto.RevocationList()
    crl.revoke(dev.second)
    crl.sign(ca)
    assert crl.is_signed_by(ca.second)
    assert crl.is_revoked(dev.second)

    tl.add_revocation_list(crl)
    res = tl.verify(dev.second)
    assert not res and "revoked" in res.reason


def test_crl_pack_roundtrip():
    ca = crypto.generate_identity("ca", key_length=1024)
    dev = crypto.generate_identity("dev", ca, key_length=1024)
    crl = crypto.RevocationList()
    crl.revoke(dev.second)
    crl.sign(ca)
    crl2 = crypto.RevocationList(crl.pack())
    assert crl2.is_revoked(dev.second)
    assert crl2.get_issuer_name() == "ca"
    assert crl2.is_signed_by(ca.second)


def test_value_owner_integration(identity):
    """The real PublicKey satisfies core.value's owner protocol."""
    from opendht_tpu.core.value import Value, RawPublicKey
    v = Value(b"payload")
    v.owner = identity.first.public_key()
    v.seq = 1
    v.signature = identity.first.sign(v.get_to_sign())
    assert v.check_signature()
    # wire round-trip: owner comes back as DER; re-parse and verify
    v2 = Value.from_packed(v.get_packed())
    assert isinstance(v2.owner, RawPublicKey)
    v2.owner = crypto.PublicKey(v2.owner.export_der())
    assert v2.check_signature()
