"""Proxy observability routes (ISSUE-10): GET /keyspace, the
GET /trace ?name= filter, and the malformed-vs-unknown trace-id
distinction.  Crypto-free on purpose — unlike tests/test_proxy.py
(which needs the `cryptography` wheel for its codec/SecureDht halves),
these routes must stay testable in minimal containers, the same rule
as the lazy crypto re-exports in opendht_tpu/__init__.py."""

import json
import time
import urllib.error
import urllib.request

import pytest

from opendht_tpu import tracing
from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.proxy import DhtProxyServer
from opendht_tpu.runtime.config import NodeStatus
from opendht_tpu.runtime.runner import DhtRunner


def wait_for(pred, timeout=20.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


@pytest.fixture(scope="module")
def topology():
    peer, proxy_node = DhtRunner(), DhtRunner()
    peer.run(0)
    proxy_node.run(0)
    proxy_node.bootstrap("127.0.0.1", peer.get_bound_port())
    assert wait_for(lambda: peer.get_status() is NodeStatus.CONNECTED
                    and proxy_node.get_status() is NodeStatus.CONNECTED)
    server = DhtProxyServer(proxy_node, port=0)
    yield peer, proxy_node, server
    server.stop()
    peer.join()
    proxy_node.join()


def _get(server, path):
    url = "http://127.0.0.1:%d%s" % (server.port, path)
    with urllib.request.urlopen(url, timeout=20.0) as r:
        return r.status, json.loads(r.read().decode())


def test_trace_route_name_filter(topology):
    """ISSUE-10 satellite: GET /trace?name= passes the round-14
    flight-recorder name filter through (parity with the REPL's
    `dump [n] [name]` and get_flight_recorder(name=)) — the route
    previously called tr.dump() with no args."""
    peer, proxy_node, server = topology
    tr = tracing.get_tracer()
    tr.event("proxy_filter_probe_a", marker=1)
    tr.event("proxy_filter_probe_b", marker=2)

    _code, full = _get(server, "/trace")
    names = {e["ev"] for e in full["events"]}
    assert {"proxy_filter_probe_a", "proxy_filter_probe_b"} <= names
    _code, filt = _get(server, "/trace?name=proxy_filter_probe_a")
    assert filt["events"], "filtered dump dropped the matching event"
    assert all(e["ev"] == "proxy_filter_probe_a" for e in filt["events"])
    # read-side projection: identical to filtering the unfiltered dump
    # post-hoc (same records, same order)
    want = [e for e in full["events"] if "proxy_filter_probe_a" in e["ev"]]
    assert [e["seq"] for e in filt["events"]] == [e["seq"] for e in want]
    # spans filter too (name substring applies to both record kinds)
    assert all("proxy_filter_probe_a" in s["name"]
               for s in filt["spans"])


def test_trace_route_malformed_vs_unknown_id(topology):
    """ISSUE-10 satellite: a malformed trace id is a 400; only a
    WELL-FORMED unknown id reports an empty span list (the two cases
    were previously indistinguishable — both silently returned [])."""
    peer, proxy_node, server = topology
    base = "http://127.0.0.1:%d/trace/" % server.port
    for bad in ("zz-not-hex", "0xqqqqqqqq", "a" * 33):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + bad, timeout=20.0)
        assert ei.value.code == 400, bad
        assert "invalid trace id" in json.loads(
            ei.value.read().decode())["err"]
    # well-formed but unknown: 200 + empty spans
    code, doc = _get(server, "/trace/" + "f" * 32)
    assert code == 200 and doc["spans"] == []
    # chrome format of a well-formed unknown id: empty trace, no error
    code, doc = _get(server, "/trace/" + "f" * 32 + "?fmt=chrome")
    assert code == 200 and doc["traceEvents"] == []


def test_cache_endpoint(topology):
    """GET /cache (ISSUE-11): the hot-value cache snapshot as JSON —
    a key admitted through the observe→act loop shows up with its hit
    bookkeeping; the route never 500s on an empty cache."""
    peer, proxy_node, server = topology
    code, doc = _get(server, "/cache")
    assert code == 200 and doc["enabled"] is True
    assert doc["occupancy"] == len(doc["entries"])
    key = InfoHash.get("proxy-cache-key")
    assert proxy_node.put_sync(key, Value(b"cv", value_id=91),
                               timeout=20.0)
    ks = proxy_node._dht.keyspace
    for _ in range(max(40, ks.cfg.hot_min_count + 8)):
        ks.observe_hashes([key])
    ks.tick()                      # admits through the subscriber hook
    code, doc = _get(server, "/cache")
    assert code == 200
    assert key.hex() in [e["key"] for e in doc["entries"]], doc
    assert key.hex() in doc["hot_keys"]
    assert doc["replica_k"] == {"base": 8, "widened": 16}


def test_keyspace_endpoint(topology):
    """GET /keyspace (ISSUE-10): the observatory snapshot as JSON —
    traffic driven through the proxy node surfaces in the histogram
    and (after a tick) the heavy-hitter list."""
    peer, proxy_node, server = topology
    key = InfoHash.get("proxy-keyspace-key")
    assert peer.put_sync(key, Value(b"ks", value_id=81), timeout=20.0)
    # stride 1 so the handful of gets below deterministically admit
    # the key into the candidate set regardless of the global sample
    # phase other tests advanced (production stride is 8)
    proxy_node._dht.keyspace.cfg.sample_stride = 1
    for _ in range(6):
        proxy_node.get_sync(key, timeout=20.0)
    # force a tick so the snapshot publishes without waiting out the
    # 2 s production cadence
    proxy_node._dht.keyspace.tick()
    code, doc = _get(server, "/keyspace")
    assert code == 200 and doc["enabled"] is True
    assert doc["observed_total"] > 0
    assert len(doc["hist"]) == 256
    assert "imbalance" in doc["shards"]
    assert any(t["key"] == key.hex() for t in doc["top"]), doc["top"]


def test_trace_limit_pagination(topology):
    """Round-17 satellite: ?limit= bounds the /trace dump (a full ring
    dump over the proxy was unbounded); malformed limits are a 400."""
    peer, proxy_node, server = topology
    tr = tracing.get_tracer()
    for i in range(8):
        tr.event("limit_probe", n=i)
    _code, full = _get(server, "/trace?name=limit_probe")
    assert len(full["events"]) == 8
    _code, lim = _get(server, "/trace?name=limit_probe&limit=3")
    assert lim["limit"] == 3
    # the NEWEST 3, same order as the tail of the unlimited dump
    assert [e["seq"] for e in lim["events"]] == \
        [e["seq"] for e in full["events"][-3:]]
    assert len(lim["spans"]) <= 3
    _code, zero = _get(server, "/trace?limit=0")
    assert zero["events"] == [] and zero["spans"] == []
    # per-trace span route paginates too
    _code, doc = _get(server, "/trace/" + "f" * 32 + "?limit=5")
    assert doc["spans"] == []
    for bad in ("nan", "-1", "1.5", "x", "1_5", "%2B5"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                "http://127.0.0.1:%d/trace?limit=%s"
                % (server.port, bad), timeout=20.0)
        assert ei.value.code == 400, bad
        assert "invalid limit" in json.loads(
            ei.value.read().decode())["err"]


def test_history_endpoint(topology):
    """GET /history (round 17): the flight data recorder's frames with
    the server clocks; since/limit filter; malformed params 400."""
    peer, proxy_node, server = topology
    h = proxy_node._history
    assert h is not None
    # drive traffic + ticks deterministically (the live cadence is 1 s)
    key = InfoHash.get("proxy-history-key")
    assert proxy_node.put_sync(key, Value(b"hv", value_id=71),
                               timeout=20.0)
    h.tick()
    assert proxy_node.get_sync(key, timeout=20.0)
    h.tick()
    code, doc = _get(server, "/history")
    assert code == 200 and doc["enabled"] is True
    assert doc["frames"] and "time" in doc and "mono" in doc
    assert doc["node_id"] == proxy_node.get_node_id().hex()
    code, lim = _get(server, "/history?limit=1")
    assert len(lim["frames"]) == 1
    assert lim["frames"][0]["seq"] == doc["frames"][-1]["seq"]
    code, win = _get(server, "/history?since=0.0001")
    assert len(win["frames"]) <= len(doc["frames"])
    # limit=0 is a valid empty page, not "unlimited" (review finding)
    code, zero = _get(server, "/history?limit=0")
    assert code == 200 and zero["frames"] == []
    # NaN fails every comparison and inf is "the whole ring" dressed
    # as a window — both malformed (review finding)
    # Python-literal leniencies (digit-group underscores, sign
    # prefixes, whitespace via urlencoded '+') are malformed here too
    for bad in ("since=-1", "since=x", "since=nan", "since=inf",
                "limit=-2", "limit=1.5", "limit=1_5", "since=1_0",
                "limit=%2B5"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                "http://127.0.0.1:%d/history?%s" % (server.port, bad),
                timeout=20.0)
        assert ei.value.code == 400, bad


def test_debug_bundle_endpoint(topology):
    """GET /debug/bundle (round 17): a fresh black-box bundle over the
    proxy — every section present, JSON round-trips."""
    peer, proxy_node, server = topology
    proxy_node._history.tick()
    code, b = _get(server, "/debug/bundle")
    assert code == 200
    assert b["kind"] == "dht-blackbox-bundle"
    assert b["node_id"] == proxy_node.get_node_id().hex()
    assert b["reason"] == "on_demand"
    for section in ("history", "flight_recorder", "health", "keyspace",
                    "cache", "metrics", "auto_captures"):
        assert section in b, section
    assert b["history"]["enabled"] is True
    assert b["history"]["frames"]
    assert isinstance(b["flight_recorder"]["events"], list)
