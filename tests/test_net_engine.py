"""Network engine tests: wire-format layout, request lifecycle with
retries, the full RPC matrix over a two-engine loopback harness,
fragmentation/reassembly, rate limiting, martian filtering, and compact
node blobs (reference contracts: src/network_engine.cpp,
parsed_message.h, request.h, node_cache.cpp)."""

import socket

import msgpack
import pytest

from opendht_tpu.core.value import Query, Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import (
    EngineCallbacks, MessageType, NetworkEngine, Node, NodeCache,
    ParsedMessage, RequestAnswer,
)
from opendht_tpu.net.engine import (
    MAX_PACKET_VALUE_SIZE, MTU, SEND_NODES, is_martian,
)
from opendht_tpu.net.parsed_message import pack_tid, unpack_tid
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class Net:
    """Two (or more) engines wired through an in-memory packet switch."""

    def __init__(self):
        self.clock = FakeClock()
        self.endpoints = {}           # SockAddr -> engine
        self.queue = []
        self.drop = lambda data, src, dst: False

    def make_engine(self, name, port, callbacks=None, network=0):
        sched = Scheduler(clock=self.clock)
        addr = SockAddr("10.0.0.%d" % port, 4000 + port)
        holder = {}
        eng = NetworkEngine(
            InfoHash.get(name), network,
            lambda data, dst: self.queue.append((data, holder["addr"], dst)) or 0,
            sched, callbacks or EngineCallbacks())
        holder["addr"] = addr
        self.endpoints[addr] = eng
        return eng

    def pump(self, steps=50):
        """Deliver queued packets and run schedulers until quiescent."""
        for _ in range(steps):
            progressed = False
            while self.queue:
                data, src, dst = self.queue.pop(0)
                eng = self.endpoints.get(dst)
                if eng is None:
                    continue
                if not self.drop(data, src, dst):
                    eng.process_message(data, src)
                progressed = True
            for eng in self.endpoints.values():
                eng.scheduler.run()
            if not progressed and not self.queue:
                break

    def advance(self, dt):
        self.clock.t += dt
        for eng in self.endpoints.values():
            eng.scheduler.run()


@pytest.fixture()
def net():
    return Net()


def make_pair(net, cbs_a=None, cbs_b=None):
    a = net.make_engine("alice", 1, cbs_a)
    b = net.make_engine("bob", 2, cbs_b)
    addr_a = next(ad for ad, e in net.endpoints.items() if e is a)
    addr_b = next(ad for ad, e in net.endpoints.items() if e is b)
    node_b_for_a = a.cache.get_node(b.myid, addr_b, 0.0, confirm=True)
    node_a_for_b = b.cache.get_node(a.myid, addr_a, 0.0, confirm=True)
    return a, b, node_b_for_a, node_a_for_b


# ------------------------------------------------------------- wire format
def test_ping_wire_layout(net):
    sent = []
    eng = net.make_engine("alice", 1)
    eng._send_fn = lambda data, dst: sent.append(data) or 0
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    eng.send_ping(node)
    obj = msgpack.unpackb(sent[0], raw=False, strict_map_key=False)
    # exact top-level/arg layout (network_engine.cpp:677-695)
    assert list(obj) == ["a", "q", "t", "y", "v"]
    assert obj["a"] == {"id": bytes(eng.myid)}
    assert obj["q"] == "ping" and obj["y"] == "q" and obj["v"] == "RNG1"
    assert len(obj["t"]) == 4


def test_netid_in_header_and_filtering(net):
    sent = []
    eng = net.make_engine("alice", 1, network=7)
    eng._send_fn = lambda data, dst: sent.append(data) or 0
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    eng.send_ping(node)
    obj = msgpack.unpackb(sent[0], raw=False)
    assert obj["n"] == 7
    # a mismatched-network packet is dropped silently
    other = net.make_engine("carol", 2, network=0)
    got = []
    other.cb.on_ping = lambda n: got.append(n) or RequestAnswer()
    other.process_message(sent[0], SockAddr("10.0.0.1", 4001))
    assert got == []


def test_tid_roundtrip():
    assert unpack_tid(pack_tid(0xDEADBEEF)) == 0xDEADBEEF
    assert unpack_tid(12345) == 12345
    with pytest.raises(ValueError):
        unpack_tid(b"\x01\x02")


def test_martian_filter():
    assert is_martian(SockAddr("10.0.0.1", 0))            # port 0
    assert is_martian(SockAddr("0.1.2.3", 80))            # 0.x
    assert is_martian(SockAddr("224.0.0.1", 80))          # multicast
    assert not is_martian(SockAddr("8.8.8.8", 80))
    assert is_martian(SockAddr("ff02::1", 80))            # v6 multicast
    assert is_martian(SockAddr("fe80::1", 80))            # link-local
    assert is_martian(SockAddr("::", 80))
    assert not is_martian(SockAddr("2001:db8::1", 80))


# ------------------------------------------------------------ rpc round-trips
def test_ping_pong_roundtrip(net):
    a, b, node_b, _ = make_pair(net)
    done = []
    a.send_ping(node_b, on_done=lambda req, ans: done.append(req))
    net.pump()
    assert len(done) == 1
    assert done[0].completed
    assert node_b.reply_time == net.clock.t
    # bob learned about alice through the exchange
    assert b.cache.size(socket.AF_INET) >= 1


def test_find_node_returns_sorted_truncated_nodes(net):
    target = InfoHash.get("target")

    def on_find(node, t, want):
        ans = RequestAnswer()
        # hand back 20 candidate nodes; engine must sort by XOR and cut to 8
        ans.nodes4 = [Node(InfoHash.get(f"n{i}"), SockAddr("10.0.1.%d" % i, 100 + i))
                      for i in range(1, 21)]
        return ans

    cbs = EngineCallbacks(on_find_node=on_find)
    a, b, node_b, _ = make_pair(net, cbs_b=cbs)
    got = []
    a.send_find_node(node_b, target, want=1,
                     on_done=lambda req, ans: got.append(ans))
    net.pump()
    assert len(got) == 1
    ids = [n.id for n in got[0].nodes4]
    assert len(ids) == SEND_NODES
    dists = [bytes(target.xor(i)) for i in ids]
    assert dists == sorted(dists)


def test_get_values_inline_and_token(net):
    val = Value(b"payload", value_id=42)

    def on_get(node, h, want, query):
        return RequestAnswer(ntoken=b"tok123", values=[val])

    a, b, node_b, _ = make_pair(net, cbs_b=EngineCallbacks(on_get_values=on_get))
    got = []
    a.send_get_values(node_b, InfoHash.get("key"), Query(),
                      on_done=lambda req, ans: got.append(ans))
    net.pump()
    assert len(got) == 1
    assert got[0].ntoken == b"tok123"
    assert got[0].values == [val]


def test_get_values_field_projection(net):
    val = Value(b"payload", type_id=5, value_id=42)
    val.seq = 9

    def on_get(node, h, want, query):
        return RequestAnswer(values=[val])

    a, b, node_b, _ = make_pair(net, cbs_b=EngineCallbacks(on_get_values=on_get))
    got = []
    a.send_get_values(node_b, InfoHash.get("key"), Query("SELECT id, seq"),
                      on_done=lambda req, ans: got.append(ans))
    net.pump()
    assert len(got) == 1 and not got[0].values
    fields = got[0].fields
    assert len(fields) == 1
    from opendht_tpu.core.value import Field
    assert fields[0].index[Field.ID].value == 42
    assert fields[0].index[Field.SEQ_NUM].value == 9


def test_announce_value_roundtrip_and_large_value_fragmentation(net):
    stored = []

    def on_announce(node, h, token, values, created):
        stored.extend(values)
        return RequestAnswer()

    a, b, node_b, _ = make_pair(net, cbs_b=EngineCallbacks(on_announce=on_announce))
    big = Value(b"\xab" * (4 * MTU), value_id=77)   # forces ValueData parts
    acked = []
    a.send_announce_value(node_b, InfoHash.get("key"), big, None, b"tok",
                          on_done=lambda req, ans: acked.append(ans.vid))
    net.pump()
    assert len(stored) == 1
    assert stored[0].id == 77 and stored[0].data == big.data
    assert acked == [77]


def test_small_value_stays_in_one_packet(net):
    captured = []
    a = net.make_engine("alice", 1)
    a._send_fn = lambda data, dst: captured.append(data) or 0
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    small = Value(b"x" * 100, value_id=5)
    a.send_announce_value(node, InfoHash.get("k"), small, None, b"t")
    assert len(captured) == 1                      # no part packets
    obj = msgpack.unpackb(captured[0], raw=False)
    assert isinstance(obj["a"]["values"][0], dict)  # inline value


def test_listen_push_channel(net):
    """listen opens a per-node socket; pushes and id-updates arrive on it."""
    listens = []

    def on_listen(node, h, token, sid, query):
        listens.append((node, sid))
        return RequestAnswer()

    a, b, node_b, node_a = make_pair(net, cbs_b=EngineCallbacks(on_listen=on_listen))
    pushes = []

    def socket_cb(node, msg):
        pushes.append(msg)

    req = a.send_listen(node_b, InfoHash.get("room"), Query(), b"tok", None,
                        socket_cb=socket_cb)
    net.pump()
    assert len(listens) == 1
    peer_node, sid = listens[0]
    assert sid == req.socket_id

    # bob pushes a value over the socket
    v = Value(b"new", value_id=3)
    b.tell_listener(node_a, sid, InfoHash.get("room"), -1, b"tok", [], [], [v],
                    Query())
    net.pump()
    assert len(pushes) == 1 and pushes[0].values == [v]

    # refreshed / expired id lists
    b.tell_listener_refreshed(node_a, sid, InfoHash.get("room"), b"tok", [3])
    b.tell_listener_expired(node_a, sid, InfoHash.get("room"), b"tok", [3])
    net.pump()
    assert pushes[1].refreshed_values == [3]
    assert pushes[2].expired_values == [3]


def test_error_reply_reaches_on_error(net):
    """A 401 on announce routes to the on_error callback
    (network_engine.cpp:536-553)."""
    from opendht_tpu.net.engine import DhtProtocolException

    def on_announce(node, h, token, values, created):
        raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                   DhtProtocolException.PUT_WRONG_TOKEN)

    errors = []
    cbs_a = EngineCallbacks()
    cbs_a.on_error = lambda req, e: errors.append(e.code)
    a, b, node_b, _ = make_pair(net, cbs_a=cbs_a,
                                cbs_b=EngineCallbacks(on_announce=on_announce))
    a.send_announce_value(node_b, InfoHash.get("k"), Value(b"v", value_id=1),
                          None, b"bad")
    net.pump()
    assert errors == [401]


# ------------------------------------------------------- request lifecycle
def test_request_retries_then_expires(net):
    a = net.make_engine("alice", 1)
    sent = []
    a._send_fn = lambda data, dst: sent.append(data) or 0   # black hole
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    expiries = []
    req = a.send_ping(node, on_expired=lambda r, done: expiries.append(done))
    assert len(sent) == 1
    for _ in range(5):
        net.advance(1.1)
    assert len(sent) == 3                 # MAX_ATTEMPT_COUNT
    assert req.expired
    assert expiries == [False, True]      # early hint + final
    assert node.expired


def test_reply_to_expired_request_ignored(net):
    a, b, node_b, _ = make_pair(net)
    done = []
    # drop everything for a while
    held = []
    net.drop = lambda data, src, dst: held.append((data, src, dst)) or True
    a.send_ping(node_b, on_done=lambda r, ans: done.append(1))
    for _ in range(5):
        net.advance(1.1)
    net.drop = lambda data, src, dst: False
    # deliver the stale ping now; bob answers; alice must not fire on_done
    for data, src, dst in held:
        net.endpoints[dst].process_message(data, src)
    net.pump()
    assert done == []


# ---------------------------------------------------------- rx protections
def test_rate_limit_drops_request_floods(net):
    hits = []
    cbs = EngineCallbacks(on_ping=lambda n: hits.append(1) or RequestAnswer())
    b = net.make_engine("bob", 2, cbs)
    src = SockAddr("10.0.0.1", 4001)
    ping = msgpack.packb({"a": {"id": bytes(InfoHash.get("alice"))},
                          "q": "ping", "t": pack_tid(1), "y": "q",
                          "v": "RNG1"}, use_bin_type=True)
    for _ in range(400):
        b.process_message(ping, src)
    # per-IP cap is 200/s
    assert len(hits) == 200


def test_blacklist_and_self_message_dropped(net):
    a, b, node_b, node_a = make_pair(net)
    hits = []
    b.cb.on_ping = lambda n: hits.append(1) or RequestAnswer()
    b.blacklist_node(node_a)
    a.send_ping(node_b)
    net.pump()
    assert hits == []
    # message with b's own id is ignored
    self_ping = msgpack.packb({"a": {"id": bytes(b.myid)}, "q": "ping",
                               "t": pack_tid(9), "y": "q", "v": "RNG1"},
                              use_bin_type=True)
    b.process_message(self_ping, SockAddr("10.0.0.50", 999))
    assert hits == []


def test_stalled_fragment_reassembly_times_out(net):
    stored = []
    a, b, node_b, _ = make_pair(
        net, cbs_b=EngineCallbacks(
            on_announce=lambda n, h, t, v, c: stored.extend(v) or RequestAnswer()))
    big = Value(b"\xcd" * (4 * MTU), value_id=9)
    # drop all ValueData part packets
    net.drop = lambda data, src, dst: msgpack.unpackb(
        data, raw=False, strict_map_key=False).get("y") == "v"
    a.send_announce_value(node_b, InfoHash.get("k"), big, None, b"tok")
    net.pump(steps=2)
    assert len(b._partials) == 1
    net.advance(11.0)             # > RX_MAX_PACKET_TIME
    assert len(b._partials) == 0
    assert stored == []


# ----------------------------------------------------------------- NodeCache
def test_node_cache_interning_and_closest():
    cache = NodeCache()
    nodes = []
    for i in range(64):
        nid = InfoHash.get(f"node{i}")
        nodes.append(cache.get_node(nid, SockAddr("10.1.0.%d" % (i + 1), 100),
                                    now=0.0, confirm=True))
    # interning: same id gives the same object
    again = cache.get_node(nodes[0].id, nodes[0].addr, 0.0, confirm=False)
    assert again is nodes[0]

    target = InfoHash.get("target")
    # Oracle: the reference's greedy frontier walk (node_cache.cpp:41-74).
    # Note this is deliberately NOT the exact global top-k — XOR distance
    # is non-monotone along lexicographic order within one side, and the
    # reference accepts the approximation for cache refill.
    keys = sorted(bytes(n.id) for n in nodes)
    tkey = bytes(target)
    lo = __import__("bisect").bisect_left(keys, tkey) - 1
    hi = lo + 1
    expect = []
    while len(expect) < 8 and (lo >= 0 or hi < len(keys)):
        if lo < 0:
            expect.append(keys[hi]); hi += 1
        elif hi >= len(keys):
            expect.append(keys[lo]); lo -= 1
        elif bytes(target.xor(InfoHash(keys[lo]))) < bytes(target.xor(InfoHash(keys[hi]))):
            expect.append(keys[lo]); lo -= 1
        else:
            expect.append(keys[hi]); hi += 1
    got = cache.get_cached_nodes(target, socket.AF_INET, 8)
    assert [bytes(n.id) for n in got] == expect
    # every returned node is among the 2*count lexicographic neighbors —
    # the walk's locality guarantee
    window = set(keys[max(0, lo - 16):hi + 16])
    assert all(bytes(n.id) in window for n in got)

    # expired nodes are skipped
    got[0].set_expired()
    dead_id = got[0].id
    got2 = cache.get_cached_nodes(target, socket.AF_INET, 8)
    assert dead_id not in [n.id for n in got2]
    assert len(got2) == 8          # backfilled from the next frontier


def test_node_cache_weak_refs():
    cache = NodeCache()
    n = cache.get_node(InfoHash.get("x"), SockAddr("10.1.0.1", 100), 0.0, True)
    assert cache.size(socket.AF_INET) == 1
    del n
    import gc
    gc.collect()
    assert cache.lookup(InfoHash.get("x"), socket.AF_INET) is None
