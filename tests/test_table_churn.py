"""Churn engine: append+tombstone lookups without re-sorting.

SURVEY §7 "incremental updates" (the round-3 verdict's top ask): inserts
land in a delta side-slab, evictions set tombstone bits over sorted
positions, lookups merge both — bit-identical to a full re-sort of the
mutated id set (reference mutation path src/routing_table.cpp:204-262).

Kernel tier: ops/sorted_table.churn_lookup_topk vs the brute-force
oracle over the combined live id set.  Table tier: NodeTable mutation
streams, churn view vs forced compaction, host-scan vs device parity.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from opendht_tpu.infohash import InfoHash
from opendht_tpu.ops import ids as K
from opendht_tpu.ops.sorted_table import (
    sort_table, expand_table, build_prefix_lut, churn_lookup_topk,
    expanded_topk, unpack_tomb_bits)
from opendht_tpu.ops.xor_topk import xor_topk
from opendht_tpu.core.table import NodeTable, ChurnView


def _pack_bits(mask: np.ndarray) -> np.ndarray:
    """bool [N] → packed little-endian uint32 words (core/table.py's
    layout: word w bit b = position 32*w + b)."""
    n = len(mask)
    out = np.zeros((n + 31) // 32, dtype=np.uint32)
    for p in np.nonzero(mask)[0]:
        out[p >> 5] |= np.uint32(1) << (int(p) & 31)
    return out


def _oracle(sorted_ids, n_valid, tomb, delta_ids, n_delta, q, k):
    """Exact top-k over (live base rows ∪ delta) by brute force; returns
    (dist, ids bytes-tuple list) for comparison."""
    base = np.asarray(sorted_ids)[:int(n_valid)]
    live = base[~tomb[:int(n_valid)]]
    combined = np.concatenate([live, np.asarray(delta_ids)[:n_delta]], axis=0)
    if len(combined) == 0:
        Q = q.shape[0]
        return (np.full((Q, k, 5), 0xFFFFFFFF, np.uint32),
                [[None] * k for _ in range(Q)])
    d, i = xor_topk(jnp.asarray(q), jnp.asarray(combined), k=k,
                    tile=max(1, min(len(combined), 4096)))
    d, i = np.asarray(d), np.asarray(i)
    ids = [[combined[j].tobytes() if j >= 0 else None for j in row]
           for row in i]
    return d, ids


def _churn_ids(sorted_ids, d_sorted, enc):
    """enc idx ([0,N) = base sorted pos, [N,N+D) = delta sorted pos) →
    id bytes."""
    s = np.asarray(sorted_ids)
    dl = np.asarray(d_sorted)
    N = s.shape[0]
    return [[(s[j].tobytes() if j < N else dl[j - N].tobytes())
             if j >= 0 else None for j in row] for row in enc]


def _delta_dev(delta_np, n_delta):
    """Unsorted delta slots → (d_sorted, d_expanded, d_n_valid) the way
    ChurnView builds them."""
    D = delta_np.shape[0]
    valid = np.zeros(D, bool)
    valid[:n_delta] = True
    ds, _dp, dnv = sort_table(jnp.asarray(delta_np), jnp.asarray(valid))
    return ds, expand_table(ds, stride=32), dnv


def _mk_table(n, seed, n_valid_frac=1.0):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, 20), dtype=np.uint8)
    ids = jnp.asarray(K.ids_from_bytes(raw))
    valid = np.ones(n, bool)
    nv = int(n * n_valid_frac)
    valid[nv:] = False
    return sort_table(ids, jnp.asarray(valid)), rng


@pytest.mark.parametrize("k", [8, 16])
def test_churn_kernel_exact_vs_oracle(k):
    """Random tombstones (~10%) + a busy delta slab: the one-call churn
    kernel equals brute force over the combined live id set, node set,
    order, and distances."""
    (sorted_ids, perm, n_valid), rng = _mk_table(8192, 101)
    exp = expand_table(sorted_ids)                 # stride 64 (32-aligned)
    tomb = rng.random(8192) < 0.10
    tomb[int(n_valid):] = False
    D = 512
    n_delta = 300
    delta = np.zeros((D, 5), np.uint32)
    delta[:n_delta] = K.ids_from_bytes(
        rng.integers(0, 256, size=(n_delta, 20), dtype=np.uint8))
    q = K.ids_from_bytes(rng.integers(0, 256, size=(256, 20), dtype=np.uint8))

    ds, de, dnv = _delta_dev(delta, n_delta)
    dist, enc, cert = churn_lookup_topk(
        sorted_ids, exp, n_valid, jnp.asarray(_pack_bits(tomb)),
        ds, de, dnv, jnp.asarray(q), k=k)
    assert bool(np.asarray(cert).all())
    d_ref, ids_ref = _oracle(sorted_ids, n_valid, tomb, delta, n_delta, q, k)
    assert _churn_ids(sorted_ids, ds, np.asarray(enc)) == ids_ref
    np.testing.assert_array_equal(np.asarray(dist), d_ref)


def test_churn_kernel_tomb_heavy_windows_fall_back_exact():
    """95% tombstoned: nearly every window has < k live rows, the
    certificate fails, and the on-device exact branch must still return
    the true top-k of the survivors."""
    (sorted_ids, perm, n_valid), rng = _mk_table(4096, 102)
    exp = expand_table(sorted_ids)
    tomb = rng.random(4096) < 0.95
    tomb[int(n_valid):] = False
    D = 64
    delta = np.zeros((D, 5), np.uint32)
    q = K.ids_from_bytes(rng.integers(0, 256, size=(64, 20), dtype=np.uint8))
    ds, de, dnv = _delta_dev(delta, 0)
    dist, enc, cert = churn_lookup_topk(
        sorted_ids, exp, n_valid, jnp.asarray(_pack_bits(tomb)),
        ds, de, dnv, jnp.asarray(q), k=8)
    d_ref, ids_ref = _oracle(sorted_ids, n_valid, tomb, delta, 0, q, 8)
    assert _churn_ids(sorted_ids, ds, np.asarray(enc)) == ids_ref
    np.testing.assert_array_equal(np.asarray(dist), d_ref)


def test_churn_kernel_empty_base_delta_only():
    """Fresh node regime: an empty base snapshot with all peers in the
    delta slab still answers exactly."""
    ids = jnp.zeros((256, 5), jnp.uint32)
    sorted_ids, perm, n_valid = sort_table(ids, jnp.zeros(256, bool))
    exp = expand_table(sorted_ids)
    rng = np.random.default_rng(103)
    D = 64
    n_delta = 17
    delta = np.zeros((D, 5), np.uint32)
    delta[:n_delta] = K.ids_from_bytes(
        rng.integers(0, 256, size=(n_delta, 20), dtype=np.uint8))
    q = K.ids_from_bytes(rng.integers(0, 256, size=(16, 20), dtype=np.uint8))
    tomb = np.zeros(8, np.uint32)
    ds, de, dnv = _delta_dev(delta, n_delta)
    dist, enc, _ = churn_lookup_topk(
        sorted_ids, exp, n_valid, jnp.asarray(tomb),
        ds, de, dnv, jnp.asarray(q), k=8)
    d_ref, ids_ref = _oracle(sorted_ids, 0, np.zeros(256, bool), delta,
                             n_delta, q, 8)
    assert _churn_ids(sorted_ids, ds, np.asarray(enc)) == ids_ref
    np.testing.assert_array_equal(np.asarray(dist), d_ref)


@pytest.mark.parametrize("pack", [2, 3, 8, 16])
def test_packed_merge_bit_identical_sweep(pack):
    """Lane-packed merge property sweep (round-7 tentpole): for every
    pack width, the packed merge must be BIT-identical to the unpacked
    merge_pack=1 path — and both to the brute-force oracle — across
    ragged Q (107 % pack != 0 for every width here), tombstone density
    0 / 0.1 / 0.95 / 1.0 (the fully-tombstoned-windows edge), a
    truncated n_valid edge, both k tiers, and both merge key forms
    (fast3 full limbs, fast2 top-64 + tie repair)."""
    (sorted_ids, perm, n_valid), rng = _mk_table(2048, 120,
                                                 n_valid_frac=0.9)
    exp = expand_table(sorted_ids)
    q = K.ids_from_bytes(
        rng.integers(0, 256, size=(107, 20), dtype=np.uint8))
    for dens, k, n_delta in ((0.0, 8, 37), (0.1, 8, 37),
                             (0.95, 16, 5), (1.0, 8, 5)):
        tomb = rng.random(2048) < dens
        tomb[int(n_valid):] = False
        delta = np.zeros((64, 5), np.uint32)
        delta[:n_delta] = K.ids_from_bytes(
            rng.integers(0, 256, size=(n_delta, 20), dtype=np.uint8))
        ds, de, dnv = _delta_dev(delta, n_delta)
        tb = jnp.asarray(_pack_bits(tomb))
        qd = jnp.asarray(q)

        d_ref, enc_ref, _ = churn_lookup_topk(
            sorted_ids, exp, n_valid, tb, ds, de, dnv, qd, k=k,
            merge_pack=1)
        d_got, enc_got, cert = churn_lookup_topk(
            sorted_ids, exp, n_valid, tb, ds, de, dnv, qd, k=k,
            merge_pack=pack)
        assert bool(np.asarray(cert).all())
        np.testing.assert_array_equal(np.asarray(enc_got),
                                      np.asarray(enc_ref))
        np.testing.assert_array_equal(np.asarray(d_got),
                                      np.asarray(d_ref))

        # fast2 (nodes-not-distances contract, 2-key merge + tie check)
        exp2 = expand_table(sorted_ids, limbs=2)
        de2 = expand_table(ds, stride=16, limbs=2)
        dew = expand_table(ds, stride=64, limbs=2)
        _n, f2_ref, _ = churn_lookup_topk(
            sorted_ids, exp2, n_valid, tb, ds, de2, dnv, qd, k=k,
            d_exp_wide=dew, select="fast2", planes=2, merge_pack=1)
        _n, f2_got, _ = churn_lookup_topk(
            sorted_ids, exp2, n_valid, tb, ds, de2, dnv, qd, k=k,
            d_exp_wide=dew, select="fast2", planes=2, merge_pack=pack)
        np.testing.assert_array_equal(np.asarray(f2_got),
                                      np.asarray(f2_ref))

        # and the full-materialization oracle over (live base ∪ delta)
        d_o, ids_o = _oracle(sorted_ids, n_valid, tomb, delta, n_delta,
                             q, k)
        assert _churn_ids(sorted_ids, ds, np.asarray(enc_got)) == ids_o
        np.testing.assert_array_equal(np.asarray(d_got), d_o)


def test_merge_pack_rejects_invalid_width():
    (sorted_ids, _, n_valid), rng = _mk_table(256, 121)
    exp = expand_table(sorted_ids)
    delta = np.zeros((64, 5), np.uint32)
    ds, de, dnv = _delta_dev(delta, 0)
    q = jnp.asarray(K.ids_from_bytes(
        rng.integers(0, 256, size=(4, 20), dtype=np.uint8)))
    with pytest.raises(ValueError, match="merge_pack"):
        churn_lookup_topk(sorted_ids, exp, n_valid,
                          jnp.zeros(8, jnp.uint32), ds, de, dnv, q,
                          k=8, merge_pack=0)


def test_tomb_bits_require_aligned_stride():
    """The gather-free word extraction needs window starts on 32-bit
    word boundaries; unaligned strides must refuse loudly."""
    (sorted_ids, _, n_valid), rng = _mk_table(1024, 104)
    exp42 = expand_table(sorted_ids, stride=42)
    q = jnp.asarray(K.ids_from_bytes(
        rng.integers(0, 256, size=(4, 20), dtype=np.uint8)))
    with pytest.raises(ValueError, match="stride"):
        expanded_topk(sorted_ids, exp42, n_valid, q, k=8,
                      tomb_bits=jnp.zeros(32, jnp.uint32))


def test_unpack_tomb_bits_roundtrip():
    rng = np.random.default_rng(105)
    mask = rng.random(1000) < 0.3
    bits = _pack_bits(mask)
    got = np.asarray(unpack_tomb_bits(jnp.asarray(bits), 1000))
    np.testing.assert_array_equal(got, mask)


# --------------------------------------------------------------- table tier

def _rand_hashes(rng, n):
    return [InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
            for _ in range(n)]


def test_nodetable_churn_view_matches_forced_compaction():
    """A mixed mutation stream (inserts, removes, expiries, revivals) is
    absorbed without dropping the base snapshot; the churn view's
    results are bit-identical to the same table after a forced full
    rebuild (the re-sort oracle)."""
    rng = np.random.default_rng(7)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=4096, k=64, delta_cap=512)
    ids = _rand_hashes(rng, 900)
    for h in ids:
        t.insert(h, ("127.0.0.1", 4000), now=100.0, confirm=2)
    targets = _rand_hashes(rng, 64)
    t.snapshot(now=101.0)                          # build the base view
    base = t._snap
    assert base is not None

    for h in _rand_hashes(rng, 100):
        t.insert(h, None, now=102.0, confirm=2)
    for h in ids[:60]:
        t.remove(h)
    for h in ids[60:90]:
        t.on_expired(h)
    for h in ids[60:70]:                           # revive a third of them
        t.insert(h, None, now=103.0, confirm=2)
    assert t._snap is base                         # base survived the churn
    assert t.churn_pending > 0

    # the small-table host path and the device churn path must agree —
    # query both explicitly
    q = K.ids_from_hashes(targets)
    rows_host, dist_host = t._find_closest_host(q, 8, 104.0, "reachable")
    rows_dev, dist_dev = t.view(104.0).lookup(q, k=8)
    ids_host = [[bytes(t.id_of(int(r))) if r >= 0 else None for r in row]
                for row in rows_host]
    ids_dev = [[bytes(t.id_of(int(r))) if r >= 0 else None for r in row]
               for row in rows_dev]
    assert ids_host == ids_dev
    np.testing.assert_array_equal(dist_host, dist_dev)

    # forced compaction (snapshot() rebuilds when churn is pending)
    t.snapshot(now=104.0)
    assert t.churn_pending == 0
    rows_c, dist_c = t.view(104.0).lookup(q, k=8)
    ids_c = [[bytes(t.id_of(int(r))) if r >= 0 else None for r in row]
             for row in rows_c]
    assert ids_c == ids_dev
    np.testing.assert_array_equal(dist_c, dist_dev)


def test_nodetable_revival_returns_once():
    """Expire + revive: the revived id must appear exactly once (its
    base copy is tombstoned, the live copy sits in the delta)."""
    rng = np.random.default_rng(8)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=256, k=64, delta_cap=64)
    ids = _rand_hashes(rng, 20)
    for h in ids:
        t.insert(h, None, now=1.0, confirm=2)
    t.snapshot(now=2.0)                            # build base
    t.on_expired(ids[0])
    t.insert(ids[0], None, now=3.0, confirm=2)     # revive
    assert t.churn_pending >= 1
    q = K.ids_from_hashes([ids[0]])
    rows, _ = t.view(4.0).lookup(q, k=20)
    got = [bytes(t.id_of(int(r))) for r in rows[0] if r >= 0]
    assert got.count(bytes(ids[0])) == 1
    assert len(got) == len(set(got)) == 20


def test_nodetable_delta_overflow_grows_and_compacts_nonblocking():
    """Delta overflow no longer stalls: the slab doubles, the base view
    keeps serving, and a BACKGROUND compaction is dispatched that the
    next view() installs — with every post-dispatch mutation replayed
    (round-4 verdict ask #5).  Lookups stay exact throughout."""
    rng = np.random.default_rng(9)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=512, k=64, delta_cap=8)
    for h in _rand_hashes(rng, 50):
        t.insert(h, None, now=1.0, confirm=2)
    t.snapshot(now=2.0)
    base = t._snap
    for h in _rand_hashes(rng, 8):                 # fills delta_cap=8
        t.insert(h, None, now=3.0, confirm=2)
    assert t._snap is base and t.churn_pending == 8
    c0 = t.compactions
    over = _rand_hashes(rng, 3)
    t.insert(over[0], None, now=4.0, confirm=2)
    # overflow: base SURVIVES (no stall), delta doubled, rebuild pending
    assert t._snap is base
    assert t._pending_base is not None
    assert t._churn.delta_ids_np.shape[0] == 16
    # mutations after dispatch land in the view AND the replay log
    t.insert(over[1], None, now=4.5, confirm=2)
    t.on_expired(over[0])
    assert len(t._pending_base["mutlog"]) >= 2
    # lookups during the pending window are exact vs the host oracle
    q = K.ids_from_hashes(over[:2])
    rows_dev, dist_dev = t._churn.lookup(q, k=8)
    rows_host, dist_host = t._find_closest_host(q, 8, 5.0, "reachable")
    np.testing.assert_array_equal(dist_dev, dist_host)
    # the swap installs the new base and replays the log
    v = t.view(6.0)
    assert t._pending_base is None
    assert t.compactions == c0 + 1
    assert t._snap is not base
    rows2, dist2 = v.lookup(q, k=8)
    np.testing.assert_array_equal(dist2, dist_host)
    # the replayed view agrees with a forced full rebuild
    t.snapshot(now=7.0)
    rows3, dist3 = t.view(7.0).lookup(q, k=8)
    np.testing.assert_array_equal(dist3, dist_host)


def test_replay_overflow_counts_one_compaction():
    """ADVICE r5 finding 2: when _maybe_swap's mutation-log replay
    overflows the fresh delta slab, the forced full rebuild used to
    book the SAME compaction twice (once in _maybe_swap, once again in
    _touch via the partially-replayed view's pending entries).  The
    whole episode — swap + overflow + rebuild — must count exactly one
    compaction, and lookups must stay exact through it."""
    rng = np.random.default_rng(53)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=512, k=64, delta_cap=4)
    for h in _rand_hashes(rng, 40):
        t.insert(h, None, now=1.0, confirm=2)
    t.snapshot(now=2.0)
    for h in _rand_hashes(rng, 5):          # 5th overflows delta_cap=4 →
        t.insert(h, None, now=3.0, confirm=2)   # background compaction
    assert t._pending_base is not None
    c0 = t.compactions
    # more post-dispatch inserts than the FRESH view's slab (4) holds →
    # the replay at swap must overflow
    late = _rand_hashes(rng, 6)
    for h in late:
        t.insert(h, None, now=4.0, confirm=2)
    assert sum(op == "i" for op, _ in t._pending_base["mutlog"]) > 4
    # view() only installs a FINISHED compaction (_maybe_swap checks
    # is_ready without force) — block on the async dispatch first, or
    # a loaded CI host intermittently reaches view() before the
    # background result lands and the swap assertions below flake
    t._pending_base["n_valid"].block_until_ready()
    v = t.view(5.0)                         # swap + overflowing replay
    assert t._pending_base is None
    assert t.compactions == c0 + 1, \
        "replay overflow double-counted the compaction"
    # exactness through the episode: every late insert resolvable
    q = K.ids_from_hashes(late[:4])
    rows, dist = v.lookup(q, k=1)
    for qi in range(4):
        assert rows[qi, 0] >= 0
        assert np.array_equal(t._ids[int(rows[qi, 0])],
                              np.asarray(q)[qi])


def test_bulk_load_during_pending_compaction_replays_at_swap(monkeypatch):
    """Rows bulk-loaded while a background compaction is in flight must
    reach the pending build's mutation log — or they vanish from the
    serving view at swap (review finding on the round-5 non-blocking
    compaction; bulk_load now routes through _absorb_insert)."""
    import opendht_tpu.core.table as table_mod
    monkeypatch.setattr(table_mod, "TOMB_MIN", 16)
    rng = np.random.default_rng(41)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=1024, k=64, delta_cap=128)
    for h in _rand_hashes(rng, 300):
        t.insert(h, None, now=1.0, confirm=2)
    t.snapshot(now=2.0)
    live = [t.id_of(int(r)) for r in np.nonzero(t._valid)[0][:20]]
    for h in live:
        t.on_expired(h)                    # crosses the patched limit
    assert t._pending_base is not None
    fresh = rng.integers(0, 2**32, size=(12, 5), dtype=np.uint32)
    t.bulk_load(fresh, now=3.0)            # lands while pending
    assert any(op == "i" for op, _ in t._pending_base["mutlog"])
    v = t.view(4.0)                        # installs the swap + replay
    assert t._pending_base is None
    rows, dist = v.lookup(fresh[:4], k=1)
    # every bulk-loaded id must be found at distance zero
    for qi in range(4):
        assert rows[qi, 0] >= 0
        assert np.array_equal(t._ids[int(rows[qi, 0])], fresh[qi])


def test_nodetable_tombstone_limit_compacts_nonblocking(monkeypatch):
    """Crossing the tombstone limit dispatches a background rebuild
    instead of invalidating the view; serving continues from the old
    base + tombstones until the swap."""
    import opendht_tpu.core.table as table_mod
    monkeypatch.setattr(table_mod, "TOMB_MIN", 32)
    rng = np.random.default_rng(29)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=1024, k=64, delta_cap=64)
    ids = _rand_hashes(rng, 400)
    for h in ids:
        t.insert(h, None, now=1.0, confirm=2)
    t.snapshot(now=2.0)
    base = t._snap
    # expire enough LIVE rows to cross the (patched) tombstone floor —
    # expiry tombstones without promoting cached candidates
    live = [t.id_of(int(r)) for r in np.nonzero(t._valid)[0][:40]]
    for h in live:
        t.on_expired(h)
    assert t._snap is base                   # still serving
    assert t._pending_base is not None       # rebuild dispatched
    q = K.ids_from_hashes([t.id_of(int(r))
                           for r in np.nonzero(t._valid)[0][-8:]])
    rows_dev, dist_dev = t._churn.lookup(q, k=8)
    rows_host, dist_host = t._find_closest_host(q, 8, 3.0, "reachable")
    np.testing.assert_array_equal(dist_dev, dist_host)
    # the next view installs the swap; results unchanged
    v = t.view(4.0)
    assert t._pending_base is None
    _, dist2 = v.lookup(q, k=8)
    np.testing.assert_array_equal(dist2, dist_host)


def test_nodetable_bulk_load_absorbed_into_delta():
    """bulk_load lands in the delta when it fits (base snapshot kept);
    oversized loads fall back to full invalidation."""
    rng = np.random.default_rng(11)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=1024, k=64, delta_cap=32)
    ids0 = rng.integers(0, 2**32, size=(100, 5), dtype=np.uint32)
    t.bulk_load(ids0, now=1.0)
    t.snapshot(now=2.0)
    base = t._snap
    small = rng.integers(0, 2**32, size=(16, 5), dtype=np.uint32)
    t.bulk_load(small, now=3.0)
    assert t._snap is base and t.churn_pending == 16
    # lookup through the churn view sees the new rows
    q = small[:1]
    rows, _ = t.view(3.0).lookup(q, k=1)
    assert np.array_equal(t._ids[int(rows[0, 0])], small[0])
    big = rng.integers(0, 2**32, size=(64, 5), dtype=np.uint32)
    t.bulk_load(big, now=4.0)              # 16 + 64 > delta_cap=32
    assert t._snap is None                 # full rebuild due


def test_nodetable_host_scan_thresholds():
    """find_closest routes small workloads to the host scan (no
    snapshot build at all) and equals the device view on demand."""
    rng = np.random.default_rng(10)
    self_id = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
    t = NodeTable(self_id, capacity=1024, k=64, delta_cap=64)
    for h in _rand_hashes(rng, 200):
        t.insert(h, None, now=1.0, confirm=2)
    assert t._snap is None                         # host path built nothing
    targets = _rand_hashes(rng, 8)
    rows, dist = t.find_closest(targets, k=8, now=2.0)
    assert t._snap is None
    q = K.ids_from_hashes(targets)
    rows_dev, dist_dev = t.view(2.0).lookup(q, k=8)
    ids_h = [[bytes(t.id_of(int(r))) if r >= 0 else None for r in row]
             for row in rows]
    ids_d = [[bytes(t.id_of(int(r))) if r >= 0 else None for r in row]
             for row in rows_dev]
    assert ids_h == ids_d
    np.testing.assert_array_equal(dist, dist_dev)
