"""Cluster-harness tests (↔ the reference's tier-3 suites,
python/tools/dht/tests.py run at CI scale): latency rounds with churn,
the node-kill delete test, and maintain_storage persistence — all on the
deterministic virtual clock."""

import pytest

from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime.config import Config
from opendht_tpu.testing import PerformanceTest, PersistenceTest
from opendht_tpu.testing.scenarios import build_net


def test_gets_times_with_replacement():
    net = build_net(12, seed=5)
    stats = PerformanceTest(net, seed=5).gets_times(
        rounds=2, gets_per_round=6, replace=2, config=Config())
    s = stats.summary()
    assert s["count"] == 12
    assert 0 < s["mean"] < 5.0          # virtual seconds
    assert s["min"] > 0

def test_replication_is_k_closest():
    """A put lands on exactly the 8 XOR-closest nodes (+ the putter's
    local store) — the k=8 replica invariant (routing_table.h:26)."""
    net = build_net(16, seed=2)
    key = InfoHash.get("replication-check")
    nodes = list(net.nodes.values())
    done = []
    nodes[-1].put(key, Value(b"x"), lambda ok, ns: done.append(ok))
    assert net.run(max_time=30.0, until=lambda: bool(done))
    holders = set(map(id, net.storers_of(key)))
    ranked = sorted(nodes, key=lambda d: bytes(
        a ^ b for a, b in zip(bytes(d.myid), bytes(key))))
    closest8 = set(map(id, ranked[:8]))
    # announce targets the 8 closest *synced* nodes; sync order can swap
    # a couple of boundary ranks, so require strong overlap, not equality
    assert len(closest8 & holders) >= 6
    assert len(holders) <= 10           # ~8 + putter (+ sync-drift slack)


def test_delete_reports_holders():
    net = build_net(10, seed=3)
    survived, holders = PerformanceTest(net, seed=3).delete_test()
    assert holders >= 8                 # value was replicated before kill
    # with every holder gone at once and no republication configured the
    # value is usually lost; the scenario reports rather than asserts —
    # here we only require the harness executed end-to-end
    assert isinstance(survived, bool)


@pytest.mark.slow
def test_persistence_under_churn():
    conf = Config(maintain_storage=True)
    net = build_net(14, seed=4, config=conf)
    ok = PersistenceTest(net, seed=4).churn_survival(
        kills=3, between=700.0, config=conf)
    assert ok
