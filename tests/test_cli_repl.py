"""Drive the CLI tool bodies end to end: the dhtnode REPL dispatch
(g/l/p/pp/cpp/s/e/q?/il/ii/info/ll/cc/stt/pst/log), the dhtchat
mainline, and the dhtscanner mainline — previously covered only by
manual smoke runs (↔ reference tools/dhtnode.cpp:104-460,
dhtchat.cpp, dhtscanner.cpp)."""

import builtins
import contextlib
import io
import re
import time

import pytest

from opendht_tpu import crypto
from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime.config import Config, NodeStatus
from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig
from opendht_tpu.tools.dhtnode import cmd_loop


def wait_for(pred, timeout=20.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


@pytest.fixture(scope="module")
def net():
    """peer ↔ node, both with identities (for s/e ops)."""
    ident_a = crypto.generate_identity("repl-peer", key_length=1024)
    ident_b = crypto.generate_identity("repl-node", key_length=1024)
    peer = DhtRunner()
    node = DhtRunner()
    peer.run(0, RunnerConfig(dht_config=Config(), identity=ident_a))
    node.run(0, RunnerConfig(dht_config=Config(), identity=ident_b))
    node.bootstrap("127.0.0.1", peer.get_bound_port())
    assert wait_for(lambda: peer.get_status() is NodeStatus.CONNECTED
                    and node.get_status() is NodeStatus.CONNECTED)
    yield peer, node
    peer.join()
    node.join()


def repl(node, script, monkeypatch):
    """Run cmd_loop feeding `script` lines; returns captured stdout."""
    lines = iter(script)

    def fake_input(prompt=""):
        try:
            return next(lines)
        except StopIteration:
            raise EOFError

    monkeypatch.setattr(builtins, "input", fake_input)
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        cmd_loop(node, None)
    return out.getvalue()


def test_repl_core_ops(net, monkeypatch):
    peer, node = net
    out = repl(node, [
        "h",
        "info",
        "p repl-key hello from repl",
        "g repl-key",
        "pp perm-key permanent payload",
        "s signed-key signed payload",
        "q? repl-key select id",
        "ll",
        "cc",
        "bogus-op",
        "g",                      # missing argument
        "x",
    ], monkeypatch)
    assert "Put: True" in out
    assert "hello from repl" in out and re.search(r"Get: \d+ value", out)
    assert "PutSigned: True" in out
    assert "Node id:" in out or "id:" in out          # info output
    assert "connectivity change signalled" in out
    assert "unknown op 'bogus-op'" in out
    assert "missing argument" in out
    # pp printed the value id for cpp
    m = re.search(r"Put: True \(id ([0-9a-f]+)\)\nPutSigned", out)
    assert "Put: True (id " in out

    # the permanent put is cancellable in a second session
    vid = re.findall(r"Put: True \(id ([0-9a-f]+)\)", out)[-1]
    out2 = repl(node, ["cpp perm-key %s" % vid, "x"], monkeypatch)
    assert "cancelled" in out2


def test_repl_listen_and_cancel(net, monkeypatch):
    peer, node = net
    out = repl(node, ["l listen-key", "x"], monkeypatch)
    m = re.search(r"listening, token (\d+)", out)
    assert m, out
    token = m.group(1)
    # push a value from the peer; then cancel by token in a new session
    assert peer.put_sync(InfoHash.get("listen-key"), Value(b"heard"),
                         timeout=20.0)
    out2 = repl(node, ["cl %s" % token, "x"], monkeypatch)
    # the listen token map is per-cmd_loop call, so cl in a fresh session
    # reports the friendly error rather than cancelling
    assert "error" in out2 or "cancelled" in out2


def test_repl_encrypted_put(net, monkeypatch):
    peer, node = net
    # encrypt to our own identity: the cert is known locally and the
    # value round-trips through the DHT encrypted
    my_id = node.get_id().hex()
    out = repl(node, ["e enc-key %s secret text" % my_id, "x"], monkeypatch)
    assert "PutEncrypted: True" in out, out


def test_repl_index_ops(net, monkeypatch):
    peer, node = net
    out = repl(node, [
        "il myindex somefield 7",
        "ii myindex somefield",
        "x",
    ], monkeypatch)
    assert "Index insert: True" in out, out
    assert "Lookup: True" in out, out


def test_repl_proxy_ops(net, monkeypatch):
    peer, node = net
    from opendht_tpu.proxy import DhtProxyServer
    server = DhtProxyServer(peer, port=0)
    try:
        out = repl(node, [
            "stt 0",
            "stp",
            "pst 127.0.0.1:%d" % server.port,
            "p via-proxy proxied payload",
            "g via-proxy",
            "psp",
            "x",
        ], monkeypatch)
        assert re.search(r"proxy server on port \d+", out)
        assert "proxy server stopped" in out
        assert "backend switched to proxy" in out
        assert "Put: True" in out
        assert "proxied payload" in out
        assert "backend switched to UDP" in out
    finally:
        server.stop()


def test_repl_ingest_state(net, monkeypatch):
    """The round-12 `ingest` command surfaces the wave builder's
    coalescing health (queue depth, occupancy, time-in-queue, sheds)."""
    peer, node = net
    out = repl(node, [
        "p ingest-repl-key some value",    # drive at least one wave
        "ingest",
        "x",
    ], monkeypatch)
    assert "batching on" in out
    assert re.search(r"queue \d+/\d+", out)
    assert re.search(r"waves \d+  occupancy mean", out)
    assert re.search(r"time-in-queue p50 .* sheds \d+", out)


def test_repl_cache_state(net, monkeypatch):
    """The round-16 `cache` command surfaces the hot-value cache
    (occupancy, hit ratio, replica-k) and the `json` form dumps the
    full GET /cache snapshot."""
    peer, node = net
    out = repl(node, ["cache", "cache json", "x"], monkeypatch)
    assert re.search(r"occupancy \d+/\d+  hit ratio", out)
    assert re.search(r"replica k 8->16 on \d+ hot key\(s\)", out)
    assert '"enabled": true' in out        # the json dump


def test_repl_log_toggle(net, monkeypatch):
    peer, node = net
    out = repl(node, ["log", "log off", "x"], monkeypatch)
    assert "logging on" in out and "logging off" in out


def test_dhtchat_mainline(net, monkeypatch):
    peer, node = net
    from opendht_tpu.core.default_types import ImMessage
    from opendht_tpu.tools import dhtchat

    heard = []
    room = InfoHash.get("room:testroom")
    peer.listen(room, lambda vals, expired: heard.extend(
        v for v in vals if not expired) or True)
    time.sleep(0.5)

    lines = ["hello over dht"]

    def fake_input(prompt=""):
        if lines:
            return lines.pop(0)
        # give the signed put time to announce before quitting (main
        # joins the node immediately after the empty line)
        wait_for(lambda: any(b"hello over dht" in v.data for v in heard),
                 timeout=20.0)
        return ""

    monkeypatch.setattr(builtins, "input", fake_input)
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = dhtchat.main(["-b", "127.0.0.1:%d" % peer.get_bound_port(),
                           "testroom"])
    assert rc == 0
    assert "Joined room testroom" in out.getvalue()
    assert wait_for(lambda: any(
        b"hello over dht" in v.data for v in heard
        if not v.is_encrypted()), timeout=20.0), heard


def test_dhtscanner_mainline(net, monkeypatch):
    peer, node = net
    from opendht_tpu.tools import dhtscanner
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = dhtscanner.main(["-b", "127.0.0.1:%d" % peer.get_bound_port(),
                              "--rounds", "2"])
    assert rc == 0
    text = out.getvalue()
    assert "nodes discovered" in text
    assert "network size estimation" in text
