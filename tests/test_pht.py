"""PHT (prefix hash tree) tests — Prefix/Cache unit coverage plus
insert/lookup scenarios over the in-process virtual network (analog of the
reference PhtTest suite, python/tools/dht/tests.py:219-368)."""

import pytest

from opendht_tpu.core.value import Value
from opendht_tpu.indexation.pht import (
    MAX_NODE_ENTRY_COUNT, Cache, IndexEntry, Pht, Prefix)
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime.config import Config

from opendht_tpu.testing import VirtualNet


# ------------------------------------------------------------------ Prefix
def test_prefix_basics():
    p = Prefix(b"\xaa\x55")          # 10101010 01010101
    assert p.size == 16
    assert p.is_content_bit_active(0)
    assert not p.is_content_bit_active(1)
    assert not p.is_content_bit_active(8)
    assert p.is_content_bit_active(15)


def test_prefix_get_prefix():
    p = Prefix(b"\xff\x00")
    q = p.get_prefix(4)
    assert q.size == 4
    assert q.content == b"\xf0"
    r = p.get_prefix(-8)             # size - 8
    assert r.size == 8 and r.content == b"\xff"
    with pytest.raises(IndexError):
        p.get_prefix(17)


def test_prefix_sibling():
    p = Prefix(b"\b0", size=8)
    p = Prefix(b"\xf0", size=8)
    s = p.get_sibling()
    assert s.content == b"\xf1"      # last bit (pos 7) flipped
    assert p.get_prefix(4).get_sibling().content == b"\xe0"


def test_prefix_hash_distinct_by_depth():
    p = Prefix(b"\xab\xcd")
    assert p.get_prefix(8).hash() != p.get_prefix(16).hash()
    assert p.get_prefix(8).hash() == Prefix(b"\xab").hash()


def test_common_bits():
    a = Prefix(b"\xff\x00")
    b = Prefix(b"\xff\x80")
    assert Prefix.common_bits(a, b) == 8
    assert Prefix.common_bits(a, a) == 16
    c = Prefix(b"\x00\x00")
    assert Prefix.common_bits(a, c) == 0
    # capped by the shorter prefix
    assert Prefix.common_bits(a, b.get_prefix(4)) == 4


def test_padding_and_flags():
    p = Prefix(b"\xab")
    p.add_padding_content(3)
    assert len(p.content) == 3
    # first pad bit is marked to keep "ab" distinct from "ab\0"
    assert p.is_content_bit_active(8)
    p.update_flags()
    # update_flags marks the whole (padded) content known (pht.h:185-199)
    assert p.is_flag_active(0) and p.is_flag_active(7)
    assert len(p.flags) == len(p.content)


def test_zcurve_interleave():
    a = Prefix(b"\xff")
    a.update_flags()
    b = Prefix(b"\x00")
    b.update_flags()
    z = Pht.zcurve([a, b])
    assert z.size == 16
    assert z.content == b"\xaa\xaa"  # 1,0 interleaved


# ------------------------------------------------------------------- Cache
def test_cache_insert_lookup():
    t = {"now": 0.0}
    c = Cache(clock=lambda: t["now"])
    assert c.lookup(Prefix(b"\xf0")) == -1
    c.insert(Prefix(b"\xf0").get_prefix(4))
    assert c.lookup(Prefix(b"\xf0")) == 4
    # a diverging key only shares the cached branch partway
    assert c.lookup(Prefix(b"\x80")) == 1
    # expiry drops the branch
    t["now"] = 1000.0
    assert c.lookup(Prefix(b"\xf0")) == -1


# ---------------------------------------------------------------- on-DHT
def make_net(n=4):
    # Distinct loopback IPs per node, and a raised ingress budget: the
    # discrete-event clock compresses whole PHT insert cascades into
    # fractions of a virtual second, which would (correctly) trip the
    # default 200 req/s per-IP limiter even though a wall-clock run
    # would not.
    net = VirtualNet()
    cfg = lambda: Config(max_req_per_sec=100_000)
    seed = net.add_node(cfg(), host="127.0.0.1")
    for i in range(n - 1):
        net.add_node(cfg(), host=f"127.0.0.{i + 2}")
    net.bootstrap_all(seed)
    assert net.run(90, net.all_connected)
    return net


def do_insert(net, pht, key, value):
    done = {}
    pht.insert(key, value, lambda ok: done.update(ok=ok))
    assert net.run(120, lambda: "ok" in done), "insert never completed"
    assert done["ok"], "insert failed"


def do_lookup(net, pht, key, exact=True):
    out = {}
    pht.lookup(key,
               lambda vals, p: out.update(vals=list(vals), prefix=p),
               lambda ok: out.update(done=ok), exact_match=exact)
    assert net.run(120, lambda: "done" in out), "lookup never completed"
    assert out["done"], "lookup failed"
    return out.get("vals", [])


def test_pht_insert_lookup_single():
    net = make_net()
    nodes = list(net.nodes.values())
    pht = Pht("test", {"name": 4}, nodes[0])
    key = {"name": b"ab"}
    target = (InfoHash.get("indexed"), 42)
    do_insert(net, pht, key, target)
    vals = do_lookup(net, pht, key)
    assert target in vals

    # a different key finds nothing (exact match)
    vals2 = do_lookup(net, pht, {"name": b"zz"})
    assert target not in vals2


def test_pht_lookup_from_other_node():
    net = make_net()
    nodes = list(net.nodes.values())
    pht_a = Pht("shared", {"k": 4}, nodes[0])
    pht_b = Pht("shared", {"k": 4}, nodes[2])
    target = (InfoHash.get("val"), 7)
    do_insert(net, pht_a, {"k": b"key1"}, target)
    vals = do_lookup(net, pht_b, {"k": b"key1"})
    assert target in vals


def test_pht_multiple_entries_same_key():
    net = make_net()
    nodes = list(net.nodes.values())
    pht = Pht("multi", {"k": 2}, nodes[1])
    key = {"k": b"xy"}
    targets = [(InfoHash.get(f"v{i}"), i + 1) for i in range(4)]
    for t in targets:
        do_insert(net, pht, key, t)
    vals = do_lookup(net, pht, key)
    for t in targets:
        assert t in vals


def test_pht_split_beyond_node_capacity():
    """More than MAX_NODE_ENTRY_COUNT distinct keys forces a leaf split;
    everything must stay findable afterwards."""
    net = make_net(3)
    nodes = list(net.nodes.values())
    pht = Pht("split", {"k": 2}, nodes[0])
    n = MAX_NODE_ENTRY_COUNT + 3
    pairs = [({"k": bytes([i, 255 - i])}, (InfoHash.get(f"s{i}"), i + 1))
             for i in range(n)]
    for key, target in pairs:
        do_insert(net, pht, key, target)
    # spot-check across the key space, including both extremes
    for key, target in [pairs[0], pairs[n // 2], pairs[-1]]:
        vals = do_lookup(net, pht, key)
        assert target in vals, f"lost {key} after split"


def test_index_entry_roundtrip():
    e = IndexEntry(b"\xab\xcd", (InfoHash.get("x"), 99), "index.pht.t")
    v = e.pack()
    assert v.user_type == "index.pht.t"
    e2 = IndexEntry.unpack(v)
    assert e2.prefix == e.prefix and e2.value == e.value
