"""Live node at device scale (round-4 verdict ask #3).

A real ``Dht`` node with a table PAST the host-scan threshold
(core/table.py HOST_SCAN_MAX_ROWS) must serve protocol requests through
the device snapshot path — engine → Dht → NodeTable →
Snapshot.lookup_launch (the round-20 launch/consume seam every resolve,
sync or pipelined, funnels through) — and this is asserted, not
assumed: every closest-node resolve during
the burst is counted through the snapshot/churn view, and the snapshot
version must match the table's.  benchmarks/live_node_scale.py is the
full-scale driver (1M rows on the chip); this test runs the same stack
at 8K rows over real localhost UDP.
"""

import secrets
import select
import socket
import threading
import time

import numpy as np
import pytest

from opendht_tpu.core import table as table_mod
from opendht_tpu.core.value import Query
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net.engine import EngineCallbacks, NetworkEngine
from opendht_tpu.runtime.config import Config
from opendht_tpu.runtime.dht import Dht
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

N_ROWS = 8192            # > HOST_SCAN_MAX_ROWS → every lookup is device
N_BURST = 12


def test_live_node_serves_burst_through_device_path(monkeypatch):
    assert N_ROWS > table_mod.HOST_SCAN_MAX_ROWS

    ssock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    ssock.bind(("127.0.0.1", 0))
    sport = ssock.getsockname()[1]
    ssock.setblocking(False)
    dht = Dht(lambda data, dst: ssock.sendto(data, (str(dst.ip), dst.port))
              and 0, Config(max_req_per_sec=1_000_000), has_v6=False)
    table = dht.tables[socket.AF_INET]
    rng = np.random.default_rng(3)
    table.bulk_load(rng.integers(0, 2 ** 32, size=(N_ROWS, 5),
                                 dtype=np.uint32),
                    dht.scheduler.time(), addrs=SockAddr("10.9.9.9", 999))
    dht.warmup()
    assert table._snap is not None

    calls = {"n": 0}
    # lookup_launch is the one seam both the sync and the pipelined
    # resolve forms share (lookup() itself delegates to it) — counting
    # here covers the device path whatever ingest_pipeline_depth is
    for cls in (table_mod.Snapshot, table_mod.ChurnView):
        orig = cls.lookup_launch

        def counted(self, queries, *, _orig=orig, **kw):
            calls["n"] += 1
            return _orig(self, queries, **kw)

        monkeypatch.setattr(cls, "lookup_launch", counted)

    stop = threading.Event()

    def serve():
        while not stop.is_set():
            r, _, _ = select.select([ssock], [], [], 0.02)
            if not r:
                continue
            try:
                data, addr = ssock.recvfrom(64 * 1024)
            except OSError:
                continue
            dht.periodic(data, SockAddr(addr[0], addr[1]))

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    try:
        csock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        csock.bind(("127.0.0.1", 0))
        csock.setblocking(False)
        ceng = NetworkEngine(InfoHash.get("client"), 0,
                             lambda data, dst: csock.sendto(
                                 data, (str(dst.ip), dst.port)) and 0,
                             Scheduler(), EngineCallbacks())
        node = ceng.cache.get_node(dht.myid, SockAddr("127.0.0.1", sport),
                                   time.monotonic(), confirm=True)
        done = []
        calls["n"] = 0
        for i in range(N_BURST):
            tgt = InfoHash.get(b"burst-" + secrets.token_bytes(8))
            if i % 2:
                ceng.send_find_node(node, tgt, want=1,
                                    on_done=lambda r, a: done.append(a))
            else:
                ceng.send_get_values(node, tgt, Query(), want=1,
                                     on_done=lambda r, a: done.append(a))
        deadline = time.monotonic() + 90
        while len(done) < N_BURST and time.monotonic() < deadline:
            ceng.scheduler.run()
            r, _, _ = select.select([csock], [], [], 0.02)
            if r:
                try:
                    data, addr = csock.recvfrom(64 * 1024)
                except OSError:
                    continue
                ceng.process_message(data, SockAddr(addr[0], addr[1]))
        csock.close()
    finally:
        stop.set()
        th.join()
        ssock.close()

    assert len(done) == N_BURST
    # every reply resolved its closest set on the DEVICE path
    assert calls["n"] >= N_BURST
    assert table._snap is not None
    assert table._snap.version == table._version
    # replies actually carry closest nodes from the loaded table
    assert all(len(a.nodes4) == 8 for a in done)
