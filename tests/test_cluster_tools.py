"""Cluster-toolkit periphery: network_monitor, dhtcluster, scanner
(analogs of reference python/tools/network_monitor.py, dhtcluster.py,
scanner.py — live-UDP, small sizes)."""

import pytest

import io
import json

from opendht_tpu.testing.dhtcluster import ClusterShell, NodeCluster
from opendht_tpu.testing.network_monitor import Monitor, main as monitor_main
from opendht_tpu.testing.scanner import Scanner, offline_geo
from opendht_tpu.runtime.config import NodeStatus
from opendht_tpu.runtime.runner import DhtRunner


def test_network_monitor_round():
    mon = Monitor(None, num_ops=3, timeout=20.0)
    try:
        assert mon.wait_connected()
        dt = mon.run_test()
        assert dt < 20.0
        dt2 = mon.run_test()         # second round reuses the listeners
        assert dt2 < 20.0
    finally:
        mon.close()


def test_network_monitor_cli():
    assert monitor_main(["--local", "-n", "2", "--rounds", "1",
                         "-t", "25", "-p", "0.1"]) == 0


def test_network_monitor_percentile_alerting(capsys):
    """ISSUE-3 satellite: per-percentile alert thresholds drive the exit
    code, and the round report quotes p50/p95 from the round-trip
    histogram (not just the last round's wall time)."""
    # an impossible p50 threshold must trip the alert exit code
    assert monitor_main(["--local", "-n", "2", "--rounds", "2",
                         "-t", "25", "-p", "0.1",
                         "--alert", "p50=0.000001"]) == 1
    out = capsys.readouterr()
    assert "round-trip p50=" in out.out and "p95=" in out.out
    assert "ALERT: round-trip p50" in out.err
    # malformed specs are a usage error (exit 2), not a crash
    assert monitor_main(["--local", "--alert", "p50"]) == 2
    assert monitor_main(["--local", "--alert", "p200=1"]) == 2


def test_dhtcluster_resize_and_stats():
    net = NodeCluster()
    try:
        net.resize(3)
        assert len(net.nodes) == 3
        assert net.front() is net.nodes[0]
        assert net.get(2) is net.nodes[2]
        assert net.get(3) is None
        stats = net.get_message_stats()
        assert stats[0] == 3 and len(stats) == 6
        net.resize(1)
        assert len(net.nodes) == 1
    finally:
        net.close()
    assert len(net.nodes) == 0


def test_dhtcluster_shell():
    net = NodeCluster()
    net.resize(2)
    out = io.StringIO()
    shell = ClusterShell(net, stdout=out,
                         stdin=io.StringIO(
                             "ll\nnode 2\nll\nstats\nnode 99\n"
                             "resize 1\nls\nll\nnode\nll\nexit\n"))
    shell.cmdloop()
    text = out.getvalue()
    assert "2 nodes running." in text
    assert "Node " in text                       # selected node id
    assert "Invalid node number: 99" in text
    # shrinking past the selected node deselects it instead of leaving a
    # dead runner selected ('ls' right after must not crash/time out)
    assert "(selected node 2 was removed)" in text
    assert "No node selected." in text
    assert "1 nodes running." in text
    assert shell.net is None and net.nodes == []  # closed by exit


def test_scanner_crawls_local_network():
    net = NodeCluster()
    scan_node = DhtRunner()
    try:
        net.resize(4)
        scan_node.run(0)
        scan_node.bootstrap("127.0.0.1", net.front().get_bound_port())
        import time
        t0 = time.monotonic()
        while (scan_node.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() - t0 < 30.0):
            time.sleep(0.1)
        sc = Scanner(scan_node)
        sc.scan(timeout=60.0)
        s = sc.summary()
        json.dumps(s)                            # serializable
        assert s["probes"] >= 1
        assert s["nodes"] >= 3                   # found most of the net
        assert s["geo"].get("loopback", 0) >= 1  # offline geo classifier
        assert len(s["ring"]) == s["nodes"]
        assert all(abs(p["x"] ** 2 + p["y"] ** 2 - 1) < 1e-6
                   for p in s["ring"])
    finally:
        scan_node.join()
        net.close()


@pytest.mark.slow
def test_http_server_roundtrip():
    """POST form-encoded put, GET filtered json — the reference tool's
    interface (python/tools/http_server.py:35-67)."""
    import urllib.parse
    import urllib.request

    from opendht_tpu.testing.http_server import DhtHttpServer

    a, b = DhtRunner(), DhtRunner()
    srv = None
    try:
        a.run(0)
        b.run(0)
        b.bootstrap("127.0.0.1", a.get_bound_port())
        import time
        t0 = time.monotonic()
        while (b.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() - t0 < 30.0):
            time.sleep(0.1)
        srv = DhtHttpServer(b, http_port=0)
        base = "http://127.0.0.1:%d" % srv.port

        body = urllib.parse.urlencode(
            {"data": "hello http", "id": "77",
             "user_type": "text/plain"}).encode()
        with urllib.request.urlopen(base + "/some-key", data=body,
                                    timeout=30) as r:
            assert json.loads(r.read())["success"] is True

        with urllib.request.urlopen(base + "/some-key", timeout=30) as r:
            res = json.loads(r.read())
        assert res.get("4d") == {"base64": "aGVsbG8gaHR0cA=="}

        # WHERE filter on id: a non-matching id returns nothing
        with urllib.request.urlopen(base + "/some-key?id=123",
                                    timeout=30) as r:
            assert json.loads(r.read()) == {}

        # 'owner' param maps onto the Where grammar's owner_pk; a
        # malformed filter value returns a JSON 400, not a dropped
        # connection
        with urllib.request.urlopen(
                base + "/some-key?owner=" + "cd" * 20, timeout=30) as r:
            assert json.loads(r.read()) == {}
        import urllib.error
        try:
            urllib.request.urlopen(base + "/some-key?id=not-a-number",
                                   timeout=30)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())

        # 40-hex path is used as a literal infohash
        khex = "ab" * 20
        with urllib.request.urlopen(
                base + "/" + khex,
                data=urllib.parse.urlencode({"base64": "AQID"}).encode(),
                timeout=30) as r:
            assert json.loads(r.read())["success"] is True
        from opendht_tpu.infohash import InfoHash
        vals = a.get_sync(InfoHash(bytes.fromhex(khex)), timeout=20.0)
        assert any(v.data == b"\x01\x02\x03" for v in vals)
    finally:
        if srv is not None:
            srv.stop()
        a.join()
        b.join()


def test_offline_geo_classes():
    assert offline_geo("127.0.0.1")["class"] == "loopback"
    assert offline_geo("10.1.2.3")["class"] == "private"
    assert offline_geo("8.8.8.8")["class"] == "global"
    assert offline_geo("::1")["class"] == "loopback"
    assert offline_geo("bogus")["class"] == "invalid"
