"""Unified telemetry (ISSUE-3): registry primitives, Prometheus golden,
span timers, scheduler stale-heap compaction, request-lifecycle counters
over the loopback engine harness, the stats islands (get_nodes_stats /
get_node_message_stats), and kernel bit-identity with telemetry on/off."""

import json
import math
import os
import socket

import numpy as np
import pytest

from opendht_tpu import telemetry
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import EngineCallbacks, NetworkEngine
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.testing.telemetry_smoke import parse_exposition

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

GOLDENS = os.path.join(os.path.dirname(__file__), "goldens")


# ------------------------------------------------------------ primitives
def test_counter_gauge_label_series():
    reg = telemetry.MetricsRegistry()
    reg.counter("a_total", type="x").inc()
    reg.counter("a_total", type="x").inc(2)
    reg.counter("a_total", type="y").inc()
    reg.gauge("g").set(3)
    reg.gauge("g").inc(2)
    snap = reg.snapshot()
    assert snap["counters"] == {'a_total{type="x"}': 3,
                                'a_total{type="y"}': 1}
    assert snap["gauges"] == {"g": 5}


def test_metric_kind_clash_raises():
    reg = telemetry.MetricsRegistry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")


def test_histogram_buckets_and_quantiles():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("h_seconds")
    # exact powers of two land in the bucket whose upper bound they are
    h.observe(0.25)
    d = h.to_dict()
    assert d["buckets"] == [[0.25, 1]]
    h.observe_many([0.1] * 99)            # bulk path, same series
    assert h.count == 100
    # ~all mass in (0.0625, 0.125]; p50 interpolates inside it
    assert 0.0625 < h.quantile(0.5) <= 0.125
    assert h.quantile(0.99) <= 0.25
    # zero / negative observations are counted, bucketed lowest
    h.observe(0.0)
    assert h.count == 101


def test_histogram_bulk_matches_scalar():
    a = telemetry.MetricsRegistry().histogram("a")
    b = telemetry.MetricsRegistry().histogram("b")
    vals = [1e-9, 0.001, 0.5, 1.0, 7.0, 1e6, 0.0]
    for v in vals:
        a.observe(v)
    b.observe_many(vals)
    assert a.to_dict() == b.to_dict()


def test_span_times_and_observes():
    reg = telemetry.MetricsRegistry()
    with reg.span("s_seconds", op="t") as sp:
        pass
    assert sp.elapsed >= 0.0
    assert reg.histogram("s_seconds", op="t").count == 1
    # record=False: timing still returned, histogram untouched
    with reg.span("s_seconds", record=False, op="t") as sp2:
        pass
    assert sp2.elapsed >= 0.0
    assert reg.histogram("s_seconds", op="t").count == 1


def test_prometheus_escaping_and_validity():
    reg = telemetry.MetricsRegistry()
    reg.counter("esc_total", path='a"b\\c\nd').inc()
    text = reg.prometheus()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    parse_exposition(text)                 # grammar-valid


# ---------------------------------------------------------------- golden
def _golden_registry():
    reg = telemetry.MetricsRegistry()
    reg.counter("dht_demo_requests_total", type="ping").inc(3)
    reg.counter("dht_demo_requests_total", type="get").inc()
    reg.gauge("dht_demo_queue_depth").set(7)
    reg.gauge("dht_demo_load", family="ipv4").set(0.5)
    h = reg.histogram("dht_demo_rtt_seconds", type="get")
    for v in (0.0005, 0.003, 0.004, 0.25, 1.5):
        h.observe(v)
    return reg


def test_prometheus_exposition_golden():
    """The text exposition format is a wire contract (scraped by real
    Prometheus servers): pin it byte-for-byte."""
    text = _golden_registry().prometheus()
    path = os.path.join(GOLDENS, "prometheus_stats.txt")
    with open(path) as f:
        assert text == f.read()
    parse_exposition(text)


def test_snapshot_prometheus_same_registry():
    reg = _golden_registry()
    snap = reg.snapshot()
    series = parse_exposition(reg.prometheus())
    for k, v in snap["counters"].items():
        assert series[k] == v
    for k, v in snap["gauges"].items():
        assert series[k] == v
    for k, d in snap["histograms"].items():
        # name{labels} → name_count{labels} (the exposition suffixes the
        # family name, not the labeled series)
        base, _, lbl = k.partition("{")
        suffix = ("{" + lbl) if lbl else ""
        assert series[base + "_count" + suffix] == d["count"]
        assert math.isclose(series[base + "_sum" + suffix], d["sum"])
    json.dumps(snap)


# ------------------------------------------------- scheduler (satellite 3)
def test_scheduler_stale_tracking_and_compaction():
    reg = telemetry.get_registry()
    comp = reg.counter("dht_scheduler_heap_compactions_total")
    c0 = comp.value
    clock = [0.0]
    s = Scheduler(clock=lambda: clock[0])
    # live survivor at the HEAD: the run()-entry drain stops at it, so
    # the 500 stale entries behind it are only removable by compaction
    keep = s.add(1.0, lambda: None)
    jobs = [s.add(1000.0 + i, lambda: None) for i in range(500)]
    for j in jobs:
        j.cancel()
    assert s.stale_entries == 500
    assert len(s._heap) == 501
    s.run()
    # compaction: cancelled entries dropped, live job kept, counted
    assert len(s._heap) == 1 and not s._heap[0][2].cancelled
    assert s.stale_entries == 0
    assert comp.value == c0 + 1
    assert reg.gauge("dht_scheduler_stale_entries").value == 0
    assert not keep.cancelled


def test_scheduler_edit_counts_stale():
    clock = [0.0]
    s = Scheduler(clock=lambda: clock[0])
    j = s.add(100.0, lambda: None)
    j2 = s.edit(j, 200.0)
    assert s.stale_entries == 1                   # old entry left behind
    assert j2 is not None and not j2.cancelled


def test_scheduler_cancel_heavy_heap_bounded():
    """Regression (ISSUE-3 satellite): a cancel-heavy workload must not
    grow the heap unboundedly under lazy deletion."""
    clock = [0.0]
    s = Scheduler(clock=lambda: clock[0])
    for i in range(10_000):
        s.add(5000.0 + i, lambda: None).cancel()
        if i % 100 == 0:
            s.run()
    s.run()
    assert len(s._heap) <= 2 * 100 + 1


def test_scheduler_tick_lag_observed():
    reg = telemetry.get_registry()
    h = reg.histogram("dht_scheduler_tick_lag_seconds")
    n0, s0 = h.count, h.sum
    clock = [0.0]
    s = Scheduler(clock=lambda: clock[0])
    fired = []
    s.add(1.0, lambda: fired.append(1))
    clock[0] = 3.0
    s.run()
    assert fired == [1]
    assert h.count == n0 + 1
    assert h.sum - s0 == pytest.approx(2.0)


# ----------------------------------------- engine lifecycle (tentpole+sat 4)
class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Net:
    """Minimal two-engine in-memory switch (same shape as the
    test_net_engine harness)."""

    def __init__(self):
        self.clock = _FakeClock()
        self.endpoints = {}
        self.queue = []

    def make_engine(self, name, port, callbacks=None, **kw):
        sched = Scheduler(clock=self.clock)
        addr = SockAddr("10.0.0.%d" % port, 4000 + port)
        eng = NetworkEngine(
            InfoHash.get(name), 0,
            lambda data, dst, a=addr: self.queue.append((data, a, dst)) or 0,
            sched, callbacks or EngineCallbacks(), **kw)
        self.endpoints[addr] = eng
        return eng, addr

    def pump(self, steps=50):
        for _ in range(steps):
            moved = False
            while self.queue:
                data, src, dst = self.queue.pop(0)
                eng = self.endpoints.get(dst)
                if eng is not None:
                    eng.process_message(data, src)
                moved = True
            for eng in self.endpoints.values():
                eng.scheduler.run()
            if not moved and not self.queue:
                break


def _counter_value(name, **labels):
    return telemetry.get_registry().counter(name, **labels).value


def test_request_lifecycle_counters_and_message_stats():
    """Scripted exchange: every RPC type once; asserts BOTH the
    MessageStats island (get_node_message_stats in/out + reset-on-read)
    and the registry mirrors/lifecycle series advanced together.  The
    registry deltas go through ``snapshot_diff`` (ISSUE-4 satellite)
    instead of hand-rolled before/after subtraction."""
    from opendht_tpu.core.value import Query, Value

    reg = telemetry.get_registry()
    before = reg.snapshot()

    net = _Net()
    a, addr_a = net.make_engine("alice", 1)
    b, addr_b = net.make_engine("bob", 2)
    node_b = a.cache.get_node(b.myid, addr_b, 0.0, confirm=True)

    done = []
    a.send_ping(node_b, on_done=lambda r, ans: done.append("ping"))
    a.send_find_node(node_b, InfoHash.get("t"),
                     on_done=lambda r, ans: done.append("find"))
    a.send_get_values(node_b, InfoHash.get("k"), Query(),
                      on_done=lambda r, ans: done.append("get"))
    a.send_listen(node_b, InfoHash.get("k"), Query(), b"token", None,
                  socket_cb=lambda n, m: None)
    a.send_announce_value(node_b, InfoHash.get("k"), Value(b"v"), None,
                          b"token")
    a.send_refresh_value(node_b, InfoHash.get("k"), 1, b"token")
    net.pump()
    assert "ping" in done and "find" in done and "get" in done

    # the island: [ping, find, get, listen, put], reset on read
    assert b.get_node_message_stats(incoming=True) == [1, 1, 1, 1, 1]
    assert b.get_node_message_stats(incoming=True) == [0, 0, 0, 0, 0]
    assert a.get_node_message_stats(incoming=False) == [1, 1, 1, 1, 1]
    assert b.in_stats.refresh == 0          # reset cleared it too

    # the registry mirrors advanced with the island (no reset: the
    # registry is cumulative — Prometheus counters never rewind)
    d = telemetry.snapshot_diff(before, reg.snapshot())
    assert d["counters"]['dht_net_requests_sent_total{type="ping"}'] == 1
    assert d["counters"][
        'dht_net_requests_completed_total{type="ping"}'] == 1
    assert d["counters"][
        'dht_net_messages_total{direction="in",type="ping"}'] == 1
    assert d["counters"][
        'dht_net_messages_total{direction="out",type="put"}'] == 1
    assert d["histograms"]['dht_net_rtt_seconds{type="ping"}']["count"] == 1


def test_request_expiry_and_timeout_counters():
    reg = telemetry.get_registry()
    exp0 = _counter_value("dht_net_requests_expired_total", type="ping")
    to0 = reg.counter("dht_net_request_timeouts_total").value

    net = _Net()
    a, _ = net.make_engine("alice", 1)
    dead = SockAddr("10.0.0.99", 4099)      # nothing listens there
    node = a.cache.get_node(InfoHash.get("ghost"), dead, 0.0, confirm=True)
    expired = []
    a.send_ping(node, on_expired=lambda r, over: expired.append(over))
    for _ in range(8):                      # 3 attempts × 1 s + expiry
        net.clock.t += 1.0
        a.scheduler.run()
    assert True in expired
    assert _counter_value("dht_net_requests_expired_total",
                          type="ping") == exp0 + 1
    # 2 retries after the first attempt
    assert reg.counter("dht_net_request_timeouts_total").value == to0 + 2


def test_rate_limit_drop_counter():
    drops = telemetry.get_registry().counter("dht_net_ratelimit_drops_total")
    d0 = drops.value
    net = _Net()
    a, addr_a = net.make_engine("alice", 1)
    b, _ = net.make_engine("bob", 2, max_req_per_sec=8)  # per-IP = 1/s
    sent = []
    a._send_fn = lambda data, dst: sent.append(data) or 0
    node_b = a.cache.get_node(b.myid, SockAddr("10.0.0.2", 4002), 0.0,
                              confirm=True)
    for _ in range(10):
        a.send_ping(node_b)
    for pkt in sent:
        b.process_message(pkt, addr_a)
    assert drops.value > d0


# -------------------------------------- stats islands tests (satellite 4)
def _mk_dht(**kw):
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    clock = _FakeClock()
    clock.t = 100_000.0
    sched = Scheduler(clock=clock)
    dht = Dht(lambda data, addr: 0, Config(node_id=InfoHash.get("self")),
              sched, has_v4=True, has_v6=False, **kw)
    return dht, clock


def test_get_nodes_stats_field_by_field():
    """(satellite 4) the island checked against a hand-populated table:
    good / dubious / incoming / cached / table_depth / searches /
    node_cache_size each verified independently."""
    from opendht_tpu.core.table import NODE_GOOD_TIME

    dht, clock = _mk_dht()
    af = socket.AF_INET
    table = dht.tables[af]
    now = dht.scheduler.time()

    # 3 good nodes (replied now)
    good_ids = [InfoHash.get("good%d" % i) for i in range(3)]
    for i, h in enumerate(good_ids):
        table.insert(h, SockAddr("10.1.0.%d" % (i + 1), 4000), now,
                     confirm=2)
    # 2 dubious (heard of, never replied)
    for i in range(2):
        table.insert(InfoHash.get("dub%d" % i),
                     SockAddr("10.2.0.%d" % (i + 1), 4000), now, confirm=0)
    # 1 stale: replied long ago -> falls out of the good window
    table.insert(InfoHash.get("old"), SockAddr("10.3.0.1", 4000),
                 now - NODE_GOOD_TIME - 10, confirm=2)
    # 1 incoming: good AND seen (query) after its last reply
    table.insert(good_ids[0], SockAddr("10.1.0.1", 4000), now + 1,
                 confirm=1)

    st = dht.get_nodes_stats(af)
    assert st.good_nodes == 3
    assert st.dubious_nodes == 3            # 2 hearsay + 1 stale replier
    assert st.incoming_nodes == 1
    assert st.get_known_nodes() == 6
    assert st.cached_nodes == 0
    assert st.searches == 0
    assert st.node_cache_size == 0

    # table_depth = deepest occupied bucket + 1
    occ = table.bucket_occupancy()
    expect_depth = int(np.nonzero(occ)[0][-1] + 1)
    assert st.table_depth == expect_depth
    assert st.get_network_size_estimation() == 8 * 2 ** expect_depth

    # a search and an engine-cache node move their gauges
    dht.get(InfoHash.get("needle"), lambda vals: True, lambda ok, ns: None)
    dht.engine.cache.get_node(InfoHash.get("peer"),
                              SockAddr("10.9.0.1", 4000), now, confirm=True)
    st2 = dht.get_nodes_stats(af)
    assert st2.searches == 1
    assert st2.node_cache_size >= 1      # the search interns peers too

    # the dict the proxy's GET / serves carries every field
    d = st2.to_dict()
    for key in ("good", "dubious", "cached", "incoming", "searches",
                "node_cache", "table_depth", "network_size_estimation"):
        assert key in d

    # empty family: all-zero stats, no crash
    st6 = dht.get_nodes_stats(socket.AF_INET6)
    assert st6.good_nodes == 0 and st6.get_known_nodes() == 0


# ------------------------------------------- kernel bit-identity (tentpole)
def test_simulate_lookups_bitidentical_with_telemetry():
    """Telemetry enabled vs disabled must not change a single bit of the
    search engine's output (host-side envelope only), while the wave
    histograms advance only when enabled."""
    from opendht_tpu.core.search import simulate_lookups

    rng = np.random.default_rng(5)
    N, Q = 2048, 64
    raw = rng.integers(0, 2 ** 32, (N, 5), dtype=np.uint32)
    ids = raw[np.lexsort([raw[:, i] for i in range(4, -1, -1)])]
    targets = rng.integers(0, 2 ** 32, (Q, 5), dtype=np.uint32)

    reg = telemetry.get_registry()
    wave = reg.histogram("dht_search_wave_seconds")
    width = reg.histogram("dht_search_wave_width", mode="single")
    hops_h = reg.histogram("dht_search_hops", mode="single")
    n_wave, n_width, n_hops = wave.count, width.count, hops_h.count

    reg.enabled = True
    out_on = simulate_lookups(ids, N, targets, seed=3)
    assert width.count == n_width + 1
    assert hops_h.count == n_hops + Q
    try:
        reg.enabled = False
        out_off = simulate_lookups(ids, N, targets, seed=3)
        assert width.count == n_width + 1      # no new observations
    finally:
        reg.enabled = True
    for k in ("nodes", "dist", "hops", "converged"):
        assert np.array_equal(np.asarray(out_on[k]),
                              np.asarray(out_off[k])), k


# ------------------------------------------------ monitor (satellite 2)
def test_monitor_parse_alerts():
    from opendht_tpu.testing.network_monitor import parse_alerts
    assert parse_alerts(["p95=2.5", "50=1"]) == {95.0: 2.5, 50.0: 1.0}
    assert parse_alerts([]) == {}
    with pytest.raises(ValueError):
        parse_alerts(["p95"])
    with pytest.raises(ValueError):
        parse_alerts(["p101=4"])


# --------------------------------------------------- proxy route (tentpole)
class _StubRunner:
    """The minimum surface DhtProxyServer touches for GET / + /stats."""

    def get_node_id(self):
        return InfoHash.get("stub-node")

    def get_id(self):
        return InfoHash()

    def get_node_stats(self, af):
        raise RuntimeError("no table")

    def get_metrics(self):
        return telemetry.get_registry().snapshot()


def test_proxy_stats_prometheus_route():
    import urllib.request
    from opendht_tpu.proxy.server import DhtProxyServer

    telemetry.get_registry().counter("dht_test_probe_total").inc()
    srv = DhtProxyServer(_StubRunner(), 0)
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % srv.port, timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers.get("Content-Type", "")
            text = r.read().decode()
        series = parse_exposition(text)
        assert series["dht_test_probe_total"] >= 1
        assert series["dht_proxy_requests_total"] >= 1
        assert "dht_proxy_listen_count" in series
        # the JSON STATS island still serves (reference STATS / route)
        req = urllib.request.Request(
            "http://127.0.0.1:%d/" % srv.port, method="STATS")
        with urllib.request.urlopen(req, timeout=10) as r:
            obj = json.loads(r.read())
        assert "requestRate" in obj
    finally:
        srv.stop()


# ------------------------------------------- snapshot_diff edges (round 17)
def test_snapshot_diff_series_only_in_after():
    """A series born between the snapshots diffs against zero — the
    case every overhead driver hits on its first instrumented rep
    (round-17 satellite: snapshot_diff was load-bearing for the paired
    drivers but only exercised indirectly)."""
    reg = telemetry.MetricsRegistry()
    before = reg.snapshot()
    reg.counter("sd_new_total", op="x").inc(7)
    reg.histogram("sd_new_seconds").observe(0.25)
    d = telemetry.snapshot_diff(before, reg.snapshot())
    assert d["counters"]['sd_new_total{op="x"}'] == 7
    assert d["histograms"]["sd_new_seconds"] == {"count": 1, "sum": 0.25}


def test_snapshot_diff_bucket_set_growth():
    """Observations landing in a bucket the ``before`` snapshot never
    had must still produce the right count/sum delta (the diff reads
    count/sum, never assumes matching bucket sets)."""
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("sd_grow_seconds")
    h.observe(0.5)
    before = reg.snapshot()
    h.observe(1e6)          # a brand-new (far) bucket
    h.observe(1e6)
    d = telemetry.snapshot_diff(before, reg.snapshot())
    got = d["histograms"]["sd_grow_seconds"]
    assert got["count"] == 2
    assert got["sum"] == pytest.approx(2e6)
    # bucket sets genuinely differ between the snapshots
    nb = len(reg.snapshot()["histograms"]["sd_grow_seconds"]["buckets"])
    assert nb == 2


def test_snapshot_diff_labeled_series_mismatch():
    """Label sets that exist on only ONE side stay distinct series:
    present-only-in-after diffs against zero, present-only-in-before
    (a registry reset mid-run) surfaces as a NEGATIVE delta rather
    than silently vanishing — the overhead drivers would misattribute
    a whole mode otherwise."""
    reg = telemetry.MetricsRegistry()
    reg.counter("sd_lab_total", mode="a").inc(3)
    before = reg.snapshot()
    reg.reset()                        # zero IN PLACE (test helper)
    reg.counter("sd_lab_total", mode="b").inc(5)
    d = telemetry.snapshot_diff(before, reg.snapshot())
    assert d["counters"]['sd_lab_total{mode="b"}'] == 5
    assert d["counters"]['sd_lab_total{mode="a"}'] == -3
    # zero-delta series are dropped entirely
    reg2 = telemetry.MetricsRegistry()
    reg2.counter("sd_zero_total").inc(2)
    snap = reg2.snapshot()
    d2 = telemetry.snapshot_diff(snap, snap)
    assert d2 == {"counters": {}, "gauges": {}, "histograms": {}}
