"""Driver entry-point checks.

Round-1 regression: the driver runs ``dryrun_multichip`` in a fresh
process whose default backend is the single-chip TPU tunnel, and the
round-1 build relied on the *caller* provisioning the 8-device virtual
CPU platform — so the driver's check crashed (MULTICHIP_r01.json rc=1)
even though the sharded code was correct.  ``_provision_devices`` now
applies the conftest recipe itself; these tests pin both execution
environments.
"""

import os
import subprocess
import sys

import jax
import pytest

pytestmark = pytest.mark.slow  # subprocess driver runs (quick: -m 'not slow')

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_compiles_and_runs():
    import __graft_entry__ as g
    fn, args = g.entry()
    dist, rows, cert = jax.jit(fn)(*args)
    assert rows.shape == (256, 8)
    assert bool(cert.all())


def test_dryrun_multichip_warm_backend():
    # With the backend warm (8 virtual CPU devices), the guard must
    # detect it, leave it alone, and still pass.  Initialize explicitly
    # so the warm path is exercised regardless of test selection order.
    assert len(jax.devices()) == 8
    import __graft_entry__ as g
    g.dryrun_multichip(8)


def test_dryrun_multichip_cold_process():
    # The driver condition: fresh interpreter, no XLA_FLAGS, default
    # platform.  dryrun_multichip must self-provision.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_dryrun_multichip_stale_smaller_flag():
    # A wrapper already exported a *smaller* forced-device count; the
    # provisioner must replace it with max(n_devices, prior), not skip on
    # a substring match (round-2 review finding).
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_provision_refuses_oversubscription():
    # Backend warm with 8 devices; asking for more must raise the
    # actionable error, not crash downstream in make_mesh.  Warm it
    # explicitly so the test holds when run in isolation.
    assert len(jax.devices()) == 8
    import __graft_entry__ as g
    with pytest.raises(RuntimeError, match="fresh process"):
        g._provision_devices(64)
