"""Test config: force the CPU backend with 8 virtual devices so that
multi-chip sharding paths (jax.sharding.Mesh / shard_map) are exercised
without TPU hardware.

Note: this environment registers an 'axon' TPU-tunnel backend via
sitecustomize and forces jax_platforms=axon; the tunnel admits a single
client, so tests must never touch it (the benchmark owns it).  Setting
the env var is not enough — the registration hook overrides it — but a
config update before first backend use wins.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running live-cluster / subprocess / fuzz tests "
        "(`-m 'not slow'` = the ~4-minute medium tier)")
    config.addinivalue_line(
        "markers",
        "quick: fast broad-coverage smoke modules — `pytest -m quick` "
        "is the sub-minute iteration tier; the full suite (CI, "
        "pre-merge) runs everything")
