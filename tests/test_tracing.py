"""Distributed tracing + flight recorder (ISSUE-4): context/wire
primitives, bounded ring, sampling, Chrome/Perfetto export, kernel
bit-identity with tracing on/off, request-lifecycle spans over the
loopback engine harness, cross-node span assembly over a real UDP
cluster, the proxy ``GET /trace`` route, and ``snapshot_diff``."""

import json

import numpy as np
import pytest

from opendht_tpu import telemetry, tracing
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import EngineCallbacks, NetworkEngine
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.testing.trace_assembler import (_wait_connected,
                                                 assemble_trace, check_tree,
                                                 collect_spans)

pytestmark = pytest.mark.quick


# ------------------------------------------------------------- primitives
def test_context_wire_roundtrip():
    ctx = tracing.TraceContext.new_root()
    assert ctx.sampled
    back = tracing.decode_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.flags) == \
        (ctx.trace_id, ctx.span_id, ctx.flags)
    child = ctx.child()
    assert child.trace_id == ctx.trace_id
    assert child.span_id != ctx.span_id
    assert not tracing.TraceContext.new_root(sampled=False).sampled


def test_decode_wire_bounded():
    good = {"i": b"\x01" * 16, "s": b"\x02" * 8, "f": 3}
    assert tracing.decode_wire(good) is not None
    for bad in (None, 7, "x", b"\x00" * 26, [1], {},
                {"i": b"\x01" * 16}, {"s": b"\x02" * 8},
                {"i": b"\x01" * 16, "s": b"\x02" * 8, "f": []},
                {"i": b"\x01" * 1000000, "s": b"\x02" * 8},
                {"i": b"\x00" * 16, "s": b"\x02" * 8}):
        assert tracing.decode_wire(bad) is None, repr(bad)[:40]


def test_ring_bounded_and_oldest_evicted():
    tr = tracing.Tracer(capacity=32, node="n")
    for i in range(100):
        tr.event("e", i=i)
    recs = tr.records()
    assert len(recs) == 32
    assert min(r["attrs"]["i"] for r in recs) == 68   # oldest evicted
    tr.clear()
    assert not tr.records()


def test_span_nesting_and_ambient_context():
    tr = tracing.Tracer(node="n")
    assert tracing.current() is None
    with tr.span("outer", kind="client") as outer:
        assert tracing.current() is outer.ctx
        with tr.span("inner", parent=tracing.current()) as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
    assert tracing.current() is None
    spans = tr.spans(outer.ctx.trace_id)
    assert {s["name"] for s in spans} == {"outer", "inner"}
    by = {s["name"]: s for s in spans}
    assert by["inner"]["parent_id"] == by["outer"]["span_id"]
    assert by["outer"]["parent_id"] is None
    assert by["inner"]["start"] >= by["outer"]["start"]


def test_sampling_disabled_and_rate_limited():
    tr = tracing.Tracer(node="n")
    tr.enabled = False
    assert not tr.span("x")
    assert tr.record("x", 0.0, 1.0) is None
    tr.event("x")
    assert not tr.records()
    tr.enabled = True
    tr.set_sample_rate(0.0)
    assert not tr.span("x")                   # roots rejected
    parent = tracing.TraceContext.new_root()
    assert tr.span("x", parent=parent)        # children follow the flag
    tr.set_sample_rate(None)
    assert tr.span("x")
    # unsampled parent → no child span
    cold = tracing.TraceContext.new_root(sampled=False)
    assert not tr.span("x", parent=cold)


def test_run_with_and_activate():
    ctx = tracing.TraceContext.new_root()
    got = tracing.run_with(ctx, tracing.current)
    assert got is ctx and tracing.current() is None
    with tracing.activate(ctx):
        with tracing.activate(None):          # explicit clearing
            assert tracing.current() is None
        assert tracing.current() is ctx


# ----------------------------------------------------------- chrome export
def test_chrome_trace_fields_and_roundtrip():
    tr = tracing.Tracer(node="node-a")
    with tr.span("dht.op.get", kind="client", op="get") as sp:
        tr.record("dht.search.wave", sp.start, 0.001, parent=sp.ctx,
                  node="node-b", width=64)
    tr.event("request_timeout", type="get", tid=7)
    dump = tracing.to_chrome_trace(tr.records())
    back = json.loads(json.dumps(dump))
    evs = back["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        for field in ("name", "pid", "tid", "ts", "dur", "args"):
            assert field in e
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["dur"] >= 0
    # one pid per node, named via metadata
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"node-a", "node-b"}
    assert {e["pid"] for e in xs} == {1, 2}
    # the instant event
    assert any(e["ph"] == "i" and e["name"] == "request_timeout"
               for e in evs)


# ------------------------------------------- kernel bit-identity (tentpole)
def test_simulate_lookups_bitidentical_with_tracing():
    """Tracing on (ambient sampled context active) vs tracer disabled
    must not change a single bit of the search engine's output — the
    wave/round spans are recorded from the host envelope AFTER the
    compiled computation.  Untraced waves (no ambient context) record
    NOTHING, so bench loops cannot churn the flight-recorder ring."""
    from opendht_tpu.core.search import simulate_lookups

    rng = np.random.default_rng(11)
    N, Q = 2048, 64
    raw = rng.integers(0, 2 ** 32, (N, 5), dtype=np.uint32)
    ids = raw[np.lexsort([raw[:, i] for i in range(4, -1, -1)])]
    targets = rng.integers(0, 2 ** 32, (Q, 5), dtype=np.uint32)

    tr = tracing.get_tracer()
    tr.clear()
    tr.enabled = True
    root = tracing.TraceContext.new_root()
    with tracing.activate(root):
        out_on = simulate_lookups(ids, N, targets, seed=3)
    waves = [s for s in tr.spans(root.trace_id)
             if s["name"] == "dht.search.wave"]
    assert len(waves) == 1
    assert waves[0]["attrs"]["width"] == Q
    assert waves[0]["parent_id"] == root.span_hex
    rounds = [s for s in tr.spans() if s["name"] == "dht.search.round"]
    assert len(rounds) == waves[0]["attrs"]["rounds"]
    assert all(r["parent_id"] == waves[0]["span_id"] for r in rounds)
    # enabled tracer, no ambient context: ring stays untouched
    n_spans = len(tr.records())
    out_plain = simulate_lookups(ids, N, targets, seed=3)
    assert len(tr.records()) == n_spans
    try:
        tr.enabled = False
        with tracing.activate(tracing.TraceContext.new_root()):
            out_off = simulate_lookups(ids, N, targets, seed=3)
        assert len(tr.records()) == n_spans       # nothing recorded
    finally:
        tr.enabled = True
    for k in ("nodes", "dist", "hops", "converged"):
        a = np.asarray(out_on[k])
        assert np.array_equal(a, np.asarray(out_off[k])), k
        assert np.array_equal(a, np.asarray(out_plain[k])), k


# ------------------------------------ engine lifecycle over loopback harness
class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Net:
    def __init__(self):
        self.clock = _FakeClock()
        self.endpoints = {}
        self.queue = []

    def make_engine(self, name, port, callbacks=None, **kw):
        sched = Scheduler(clock=self.clock)
        addr = SockAddr("10.0.0.%d" % port, 4000 + port)
        eng = NetworkEngine(
            InfoHash.get(name), 0,
            lambda data, dst, a=addr: self.queue.append((data, a, dst)) or 0,
            sched, callbacks or EngineCallbacks(), **kw)
        self.endpoints[addr] = eng
        return eng, addr

    def pump(self, steps=50):
        for _ in range(steps):
            moved = False
            while self.queue:
                data, src, dst = self.queue.pop(0)
                eng = self.endpoints.get(dst)
                if eng is not None:
                    eng.process_message(data, src)
                moved = True
            for eng in self.endpoints.values():
                eng.scheduler.run()
            if not moved and not self.queue:
                break


def test_rpc_spans_client_server_pair():
    tr = tracing.get_tracer()
    tr.clear()
    net = _Net()
    a, _ = net.make_engine("alice", 1)
    b, addr_b = net.make_engine("bob", 2)
    node_b = a.cache.get_node(b.myid, addr_b, 0.0, confirm=True)
    root = tracing.TraceContext.new_root()
    done = []
    with tracing.activate(root):
        a.send_ping(node_b, on_done=lambda r, m: done.append(1))
    net.pump()
    assert done
    spans = tr.spans(root.trace_id)
    by = {s["name"]: s for s in spans}
    assert set(by) == {"dht.rpc.ping", "dht.server.ping"}
    client, server = by["dht.rpc.ping"], by["dht.server.ping"]
    assert client["parent_id"] == root.span_hex
    assert server["parent_id"] == client["span_id"]
    assert client["kind"] == "client" and server["kind"] == "server"
    assert client["node"] == str(a.myid) and server["node"] == str(b.myid)
    assert client["attrs"]["outcome"] == "completed"
    # client span covers the whole RTT: it cannot end before the server
    # span started (same process clock)
    assert client["dur"] >= server["dur"] * 0.5


def test_expired_request_closes_span_and_records_event():
    tr = tracing.get_tracer()
    tr.clear()
    net = _Net()
    a, _ = net.make_engine("alice", 1)
    ghost = a.cache.get_node(InfoHash.get("ghost"),
                             SockAddr("10.0.0.99", 4099), 0.0, confirm=True)
    root = tracing.TraceContext.new_root()
    with tracing.activate(root):
        a.send_ping(ghost)
    for _ in range(8):
        net.clock.t += 1.0
        a.scheduler.run()
    spans = tr.spans(root.trace_id)
    assert len(spans) == 1
    assert spans[0]["attrs"]["outcome"] == "expired"
    assert spans[0]["attrs"]["attempts"] >= 3
    evs = {e["ev"] for e in tr.events()}
    assert "request_expired" in evs
    assert "request_timeout" in evs


def test_untraced_traffic_records_nothing():
    tr = tracing.get_tracer()
    tr.clear()
    net = _Net()
    a, _ = net.make_engine("alice", 1)
    b, addr_b = net.make_engine("bob", 2)
    node_b = a.cache.get_node(b.myid, addr_b, 0.0, confirm=True)
    a.send_ping(node_b)
    net.pump()
    assert not tr.spans()


# ------------------------------------------------ cross-node assembly (sat)


def test_cross_node_span_assembly_udp_cluster():
    """Boot a real-UDP cluster, run one traced put+get, assert the
    assembled tree: client op spans → per-hop rpc spans → remote server
    spans, monotone timestamps, ≥3 contributing nodes, and the Chrome
    dump round-trips with the exact Perfetto fields."""
    from opendht_tpu.core.value import Value
    from opendht_tpu.testing.dhtcluster import NodeCluster

    tr = tracing.get_tracer()
    tr.clear()
    net = NodeCluster()
    try:
        net.resize(5)
        assert _wait_connected(net.nodes)
        key = InfoHash.get("traced-op")
        root = tracing.TraceContext.new_root()
        with tracing.activate(root):
            assert net.nodes[-1].put_sync(key, Value(b"t"), timeout=20.0)
            vals = net.nodes[-1].get_sync(key, timeout=20.0)
        assert any(v.data == b"t" for v in vals)

        tree = assemble_trace(net.nodes, root.trace_id)
        assert tree["trace_id"] == root.trace_hex
        assert tree["spans"] >= 5
        contributing = [n for n in tree["nodes"] if n]
        assert len(contributing) >= 3, contributing
        assert check_tree(tree) == []
        # the roots under the user's ambient context are the two op spans
        root_ops = sorted(r["name"] for r in tree["roots"]
                          if r["name"].startswith("dht.op."))
        assert root_ops == ["dht.op.get", "dht.op.put"]
        for r in tree["roots"]:
            if r["name"].startswith("dht.op."):
                assert r["parent_id"] == root.span_hex
                assert r["attrs"]["ok"] is True
        # every node's own get_trace view feeds the same assembly
        assert collect_spans([net.nodes[0]], root.trace_id)

        # chrome dump round-trip with the exact Perfetto fields
        dump = tracing.to_chrome_trace(
            collect_spans(net.nodes, root.trace_id))
        back = json.loads(json.dumps(dump))
        xs = [e for e in back["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == tree["spans"]
        for e in xs:
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert len({e["pid"] for e in xs}) >= 3       # one pid per node
    finally:
        net.close()


def test_reused_search_does_not_leak_finished_trace():
    """Review regression: a Search reused by a later UNTRACED op must
    drop the earlier op's context — otherwise the new op's RPCs record
    into (and wire-propagate) a trace that already ended."""
    import socket as _socket
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht

    clock = _FakeClock()
    clock.t = 100_000.0
    dht = Dht(lambda data, addr: 0, Config(node_id=InfoHash.get("self")),
              Scheduler(clock=clock), has_v4=True, has_v6=False)
    key = InfoHash.get("reused")
    root = tracing.TraceContext.new_root()
    with tracing.activate(root):
        dht.get(key, lambda vals: True, lambda ok, ns: None)
    sr = dht.searches[_socket.AF_INET][key]
    assert sr.trace_ctx is root
    dht.get(key, lambda vals: True, lambda ok, ns: None)   # untraced
    assert dht.searches[_socket.AF_INET][key] is sr        # reused
    assert sr.trace_ctx is None                            # cleared


def test_scanner_topology_snapshot():
    """ISSUE-4 satellite: dhtscanner's per-node snapshot is JSON-able
    and carries routing/bucket/storage/flight-recorder sections."""
    from opendht_tpu.core.value import Value
    from opendht_tpu.testing.dhtcluster import NodeCluster
    from opendht_tpu.tools.dhtscanner import topology_snapshot

    net = NodeCluster()
    try:
        net.resize(3)
        assert _wait_connected(net.nodes)
        assert net.nodes[1].put_sync(InfoHash.get("snap"), Value(b"x"),
                                     timeout=20.0)
        snap = topology_snapshot(net.nodes[0])
        json.dumps(snap)
        assert len(snap["node_id"]) == 40
        assert snap["known_nodes"] >= 2
        assert sum(snap["bucket_fill"]) >= 2
        assert snap["routing"]["ipv4"]["good"] >= 0
        assert "keys" in snap["storage"]
        assert isinstance(snap["events"], list)
        # round-10 maintenance stats ride the snapshot for soak-diffing
        assert isinstance(snap["maintenance"], dict)
        assert all(k.startswith("dht_maintenance_")
                   for k in snap["maintenance"])
    finally:
        net.close()


# --------------------------------------------------------- proxy route
class _StubRunner:
    def get_node_id(self):
        return InfoHash.get("stub-node")

    def get_id(self):
        return InfoHash()

    def get_node_stats(self, af):
        raise RuntimeError("no table")

    def get_metrics(self):
        return telemetry.get_registry().snapshot()


def test_proxy_trace_routes():
    import urllib.request
    from opendht_tpu.proxy.server import DhtProxyServer

    tr = tracing.get_tracer()
    tr.clear()
    with tr.span("dht.op.get", kind="client") as sp:
        pass
    trace_hex = sp.ctx.trace_hex
    tr.event("probe_event", x=1)
    srv = DhtProxyServer(_StubRunner(), 0)
    try:
        base = "http://127.0.0.1:%d" % srv.port
        with urllib.request.urlopen(base + "/trace", timeout=10) as r:
            dump = json.loads(r.read())
        assert any(e["ev"] == "probe_event" for e in dump["events"])
        assert dump["capacity"] == tr.capacity
        with urllib.request.urlopen(base + "/trace/" + trace_hex,
                                    timeout=10) as r:
            obj = json.loads(r.read())
        assert obj["trace_id"] == trace_hex
        assert [s["name"] for s in obj["spans"]] == ["dht.op.get"]
        with urllib.request.urlopen(
                base + "/trace/" + trace_hex + "?fmt=chrome",
                timeout=10) as r:
            chrome = json.loads(r.read())
        assert any(e.get("ph") == "X" for e in chrome["traceEvents"])
    finally:
        srv.stop()


# --------------------------------------------------------- dhtnode REPL
def test_repl_trace_and_dump_commands(monkeypatch, tmp_path):
    """The `trace`/`dump` REPL commands (the reference's dumpTables
    surface): trace listing, one-trace tree, chrome file export, and
    the flight-recorder dump — driven through cmd_loop on a live
    runner, no identity needed."""
    import builtins
    import contextlib
    import io

    from opendht_tpu.runtime.runner import DhtRunner
    from opendht_tpu.tools.dhtnode import cmd_loop

    tr = tracing.get_tracer()
    tr.clear()
    with tr.span("dht.op.get", kind="client", node="repl-node") as sp:
        pass
    tr.event("request_expired", type="ping", tid=9)
    chrome_path = tmp_path / "trace.json"

    node = DhtRunner()
    node.run(0)
    try:
        script = iter(["trace", "trace %s" % sp.ctx.trace_hex,
                       "trace chrome %s" % chrome_path, "dump 5", "x"])
        monkeypatch.setattr(builtins, "input",
                            lambda prompt="": next(script))
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            cmd_loop(node, None)
        text = out.getvalue()
    finally:
        node.join()
    assert sp.ctx.trace_hex in text                 # listing shows the id
    assert '"dht.op.get"' in text                   # tree dump
    assert "trace events" in text                   # chrome export line
    assert "request_expired" in text                # flight recorder
    assert "ring capacity" in text
    chrome = json.loads(chrome_path.read_text())
    assert any(e.get("ph") == "X" and e["name"] == "dht.op.get"
               for e in chrome["traceEvents"])


# ------------------------------------------------------- snapshot_diff (sat)
def test_snapshot_diff():
    reg = telemetry.MetricsRegistry()
    reg.counter("c_total", op="a").inc(2)
    reg.gauge("g").set(5)
    reg.histogram("h_seconds").observe(0.5)
    before = reg.snapshot()
    reg.counter("c_total", op="a").inc(3)
    reg.counter("c_total", op="b").inc()          # new series
    reg.gauge("g").set(4)
    reg.histogram("h_seconds").observe(0.25)
    after = reg.snapshot()
    d = telemetry.snapshot_diff(before, after)
    assert d["counters"] == {'c_total{op="a"}': 3, 'c_total{op="b"}': 1}
    assert d["gauges"] == {"g": -1}
    assert d["histograms"]["h_seconds"]["count"] == 1
    assert d["histograms"]["h_seconds"]["sum"] == pytest.approx(0.25)
    # no movement → empty sections
    d2 = telemetry.snapshot_diff(after, after)
    assert d2 == {"counters": {}, "gauges": {}, "histograms": {}}


# ------------------------------------- name-filtered dump x eviction (sat)
def test_dump_name_filter_matches_posthoc_under_eviction():
    """ISSUE-10 satellite: a name-filtered dump taken MID-FLOOD (the
    ring actively evicting) must equal the unfiltered dump filtered
    post-hoc — the filter is a read-side projection and can never see
    records eviction already dropped, nor retain extras."""
    tr = tracing.Tracer(capacity=64, node="evict-test")
    # flood 10x capacity with two interleaved event names plus spans
    for i in range(320):
        tr.event("keep_me" if i % 3 == 0 else "drop_me", i=i)
        if i % 7 == 0:
            tr.record("keep_me.span", float(i), 0.001)
    full = tr.dump()
    filt = tr.dump(name="keep_me")
    want_ev = [e for e in full["events"] if "keep_me" in e["ev"]]
    want_sp = [s for s in full["spans"] if "keep_me" in s["name"]]
    assert [e["seq"] for e in filt["events"]] == [e["seq"] for e in want_ev]
    assert [s["seq"] for s in filt["spans"]] == [s["seq"] for s in want_sp]
    # eviction really happened: the oldest retained seq is deep into
    # the flood, and the filtered view starts no earlier
    total = 320 + len(range(0, 320, 7))
    oldest = min(r["seq"] for r in tr.records())
    assert oldest >= total - 64
    assert filt["events"][0]["seq"] >= oldest
    # monotone order preserved through filtering
    seqs = [e["seq"] for e in filt["events"]]
    assert seqs == sorted(seqs)


def test_trace_hex_strict_and_spans_guard():
    """ISSUE-10 satellite: _trace_hex returns None for malformed ids
    (non-hex, oversized, empty) and Tracer.spans() with a malformed id
    returns [] — never the whole ring (the old char-strip
    normalization made bogus ids look like valid zero-padded ones)."""
    from opendht_tpu.tracing import _trace_hex
    assert _trace_hex(None) is None
    assert _trace_hex("zz") is None
    assert _trace_hex("") is None
    assert _trace_hex("a" * 33) is None
    assert _trace_hex("0x" + "g" * 4) is None
    # int(s, 16) would accept digit-group underscores and sign
    # prefixes — these are malformed, not well-formed-unknown (review
    # finding)
    assert _trace_hex("a_b") is None
    assert _trace_hex("+ab") is None
    assert _trace_hex("-1") is None
    # well-formed ids normalize to 32 hex digits
    assert _trace_hex("ab") == "ab".rjust(32, "0")
    assert _trace_hex("0xAB") == "ab".rjust(32, "0")
    assert _trace_hex(0xAB) == "%032x" % 0xAB
    ctx = tracing.TraceContext.new_root()
    assert _trace_hex(ctx) == ctx.trace_hex
    tr = tracing.Tracer(capacity=16)
    tr.record("a-span", 0.0, 0.001)
    assert len(tr.spans()) == 1                 # unfiltered: everything
    assert tr.spans("not-hex!") == []           # malformed: nothing
    assert tr.spans("f" * 32) == []             # well-formed unknown
    got = tr.spans(tr.records()[0]["trace_id"])
    assert len(got) == 1                        # well-formed known
