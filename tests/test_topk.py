"""Exactness tests for the batched XOR top-k kernels vs a big-int oracle.

The oracle ranks by the true 160-bit XOR distance (ties broken by table
index), which is precisely the reference's ordering: bytewise
lexicographic distance compare (include/opendht/infohash.h:179-194) as
exercised by RoutingTable::findClosestNodes (src/routing_table.cpp:109-150).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opendht_tpu.ops import ids as K
from opendht_tpu.ops.xor_topk import xor_topk, xor_topk_chunked
from opendht_tpu.ops.sorted_table import sort_table, window_topk, lookup_topk


def _rand_raw(n, seed, cluster=0):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, 20), dtype=np.uint8)
    if cluster:
        # force many shared prefixes: copy the first `cluster` bytes around
        raw[: n // 2, :cluster] = raw[0, :cluster]
    return raw


def _oracle_topk(q_row, table_raw, k, valid=None):
    """top-k (distance, index) by true 160-bit XOR distance."""
    q = int.from_bytes(q_row.tobytes(), "big")
    entries = []
    for i, row in enumerate(table_raw):
        if valid is not None and not valid[i]:
            continue
        d = q ^ int.from_bytes(row.tobytes(), "big")
        entries.append((d, i))
    entries.sort()
    return entries[:k]


def _check_against_oracle(dist, idx, queries_raw, table_raw, k, valid=None):
    dist = np.asarray(dist)
    idx = np.asarray(idx)
    for qi in range(len(queries_raw)):
        want = _oracle_topk(queries_raw[qi], table_raw, k, valid)
        got_idx = idx[qi].tolist()
        want_idx = [w[1] for w in want]
        pad = k - len(want)
        assert got_idx == want_idx + [-1] * pad, f"query {qi}"
        for j, (wd, _) in enumerate(want):
            gd = int.from_bytes(K.ids_to_bytes(dist[qi, j]).tobytes(), "big")
            assert gd == wd, f"query {qi} slot {j}"


@pytest.mark.parametrize("k", [8, 16])
def test_xor_topk_exact(k):
    table_raw = _rand_raw(3000, 10)
    table_raw[100] = table_raw[50]  # duplicate id → tie broken by index
    q_raw = _rand_raw(48, 11)
    q_raw[0] = table_raw[7]  # distance-0 case
    dist, idx = xor_topk(
        jnp.asarray(K.ids_from_bytes(q_raw)),
        jnp.asarray(K.ids_from_bytes(table_raw)),
        k=k, tile=512,
    )
    _check_against_oracle(dist, idx, q_raw, table_raw, k)


def test_xor_topk_valid_mask_and_small_table():
    table_raw = _rand_raw(64, 12)
    valid = np.ones(64, bool)
    valid[::3] = False
    q_raw = _rand_raw(16, 13)
    dist, idx = xor_topk(
        jnp.asarray(K.ids_from_bytes(q_raw)),
        jnp.asarray(K.ids_from_bytes(table_raw)),
        k=8, tile=512, valid=jnp.asarray(valid),
    )
    _check_against_oracle(dist, idx, q_raw, table_raw, 8, valid)

    # fewer valid rows than k → -1 padding
    valid2 = np.zeros(64, bool)
    valid2[:3] = True
    dist2, idx2 = xor_topk(
        jnp.asarray(K.ids_from_bytes(q_raw)),
        jnp.asarray(K.ids_from_bytes(table_raw)),
        k=8, tile=16, valid=jnp.asarray(valid2),
    )
    _check_against_oracle(dist2, idx2, q_raw, table_raw, 8, valid2)


def test_xor_topk_chunked_matches():
    table_raw = _rand_raw(1000, 14)
    q_raw = _rand_raw(40, 15)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    t = jnp.asarray(K.ids_from_bytes(table_raw))
    d1, i1 = xor_topk(q, t, k=8, tile=256)
    d2, i2 = xor_topk_chunked(q, t, k=8, tile=256, q_chunk=7)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_sort_table():
    raw = _rand_raw(500, 16)
    valid = np.ones(500, bool)
    valid[7] = valid[100] = False
    ids = jnp.asarray(K.ids_from_bytes(raw))
    sorted_ids, perm, n_valid = sort_table(ids, jnp.asarray(valid))
    assert int(n_valid) == 498
    s = np.asarray(sorted_ids)
    p = np.asarray(perm)
    # valid prefix strictly sorted by byte order
    keys = [raw[p[i]].tobytes() for i in range(498)]
    assert keys == sorted(keys)
    # perm maps back to original rows
    for i in range(498):
        np.testing.assert_array_equal(s[i], K.ids_from_bytes(raw[p[i]]))
    assert (p[498:] == -1).all()


@pytest.mark.parametrize("cluster", [0, 8])
def test_window_topk_certified_matches_oracle(cluster):
    table_raw = _rand_raw(4096, 17, cluster=cluster)
    q_raw = _rand_raw(64, 18, cluster=0)
    q_raw[1] = table_raw[5]
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    dist, idx, cert = window_topk(sorted_ids, n_valid, q, k=8, window=64)
    cert = np.asarray(cert)
    assert cert.mean() > 0.9  # random ids: certificate nearly always holds
    p = np.asarray(perm)
    for qi in range(64):
        if not cert[qi]:
            continue
        want = _oracle_topk(q_raw[qi], table_raw, 8)
        got = [p[j] for j in np.asarray(idx[qi]) if j >= 0]
        assert got == [w[1] for w in want], f"query {qi}"


def test_window_topk_fallback_exact_under_adversarial_clustering():
    # half the table shares a 10-byte prefix → tiny windows must fail the
    # certificate rather than silently return wrong results
    table_raw = _rand_raw(2048, 19, cluster=10)
    q_raw = table_raw[:32].copy()  # queries inside the cluster
    q_raw[:, 19] ^= 0xFF
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    dist, idx, cert = lookup_topk(sorted_ids, n_valid, q, k=8, window=8)
    assert bool(np.asarray(cert).all())
    p = np.asarray(perm)
    for qi in range(32):
        want = _oracle_topk(q_raw[qi], table_raw, 8)
        got_sorted_idx = np.asarray(idx[qi])
        got = [p[j] for j in got_sorted_idx if j >= 0]
        want_d = [w[0] for w in want]
        got_d = [
            int.from_bytes(K.ids_to_bytes(np.asarray(dist[qi, j])).tobytes(), "big")
            for j in range(len(got))
        ]
        # distances must match the oracle exactly (indices may differ on ties
        # across sorted/original index spaces)
        assert got_d == want_d, f"query {qi}"


def test_window_topk_small_n_valid():
    # table smaller than window and smaller than k
    table_raw = _rand_raw(8, 20)
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    valid = jnp.asarray(np.array([True] * 5 + [False] * 3))
    sorted_ids, perm, n_valid = sort_table(ids, valid)
    q = jnp.asarray(K.ids_from_bytes(_rand_raw(4, 21)))
    dist, idx, cert = window_topk(sorted_ids, n_valid, q, k=8, window=16)
    assert bool(np.asarray(cert).all())  # window covers everything
    idx = np.asarray(idx)
    assert ((idx >= 0).sum(axis=1) == 5).all()


def test_prefix_lut_lower_bound_parity():
    """The 2^16-prefix LUT lower bound is bit-identical to the plain
    binary search, including on clustered tables where a LUT bucket
    overflows LUT_BUCKET_STEPS coverage (certificate catches those)."""
    from opendht_tpu.ops.sorted_table import build_prefix_lut

    rng = np.random.default_rng(77)
    raw = rng.integers(0, 256, size=(8192, 20), dtype=np.uint8)
    # adversarial cluster: 6000 rows share the top 16 bits; with the
    # shallow lut_steps=3 below, the in-bucket search cannot converge,
    # so lut-path windows are misplaced and must be caught uncertified
    raw[:6000, :2] = 0x41
    ids = jnp.asarray(K.ids_from_bytes(raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    lut = build_prefix_lut(sorted_ids, n_valid)
    q_raw = rng.integers(0, 256, size=(64, 20), dtype=np.uint8)
    q_raw[:32, :2] = 0x41                    # half the queries hit the cluster
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    d1, i1, c1 = window_topk(sorted_ids, n_valid, q, k=8, window=64)
    d2, i2, c2 = window_topk(sorted_ids, n_valid, q, k=8, window=64,
                             lut=lut, lut_steps=3)
    # the shallow search must leave some cluster queries uncertified —
    # this is the overflow path the certificate exists to catch
    assert not np.asarray(c2).all()
    # certified rows of either path must equal the exact oracle
    # (uncertified rows legitimately differ pre-fallback)
    da, _, _ = lookup_topk(sorted_ids, n_valid, q, k=8, window=64)
    cert1, cert2 = np.asarray(c1), np.asarray(c2)
    assert np.array_equal(np.asarray(d1)[cert1], np.asarray(da)[cert1])
    assert np.array_equal(np.asarray(d2)[cert2], np.asarray(da)[cert2])
    # lut and plain agree wherever both certify
    both = cert1 & cert2
    assert np.array_equal(np.asarray(i1)[both], np.asarray(i2)[both])


# ---------------------------------------------------------------------------
# expanded-table fast path (ops/sorted_table.expand_table / expanded_topk)
# ---------------------------------------------------------------------------

def _expanded_setup(table_raw, valid=None, bits=16):
    from opendht_tpu.ops.sorted_table import build_prefix_lut, expand_table
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    v = None if valid is None else jnp.asarray(valid)
    sorted_ids, perm, n_valid = sort_table(ids, v)
    lut = build_prefix_lut(sorted_ids, n_valid, bits=bits)
    T2 = expand_table(sorted_ids)
    return sorted_ids, perm, n_valid, lut, T2


def test_fused_gather_planar_matches_row_oracle():
    """The fused multi-row gather (the ONE table access of the round-
    fused search round, core/search.py) must agree with the full
    row-materialization oracle ``xor_topk.gather_rows`` on every
    in-range lane, for any rows shape, any limb count, and with the
    engine's -1 "absent" sentinel present (whose lanes the gather
    leaves as clipped garbage for the caller to mask — the oracle's
    all-ones sentinel marks exactly the lanes the contract excludes)."""
    from opendht_tpu.ops.sorted_table import fused_gather_planar
    from opendht_tpu.ops.xor_topk import gather_rows

    rng = np.random.default_rng(61)
    table = jnp.asarray(
        rng.integers(0, 2**32, size=(503, 5), dtype=np.uint32))
    table_t = table.T
    for shape in ((64,), (16, 24), (8, 3, 8)):
        rows = rng.integers(-1, 503, size=shape).astype(np.int32)
        rows.flat[0] = -1                       # always one absent lane
        rows.flat[-1] = 502
        want = np.asarray(gather_rows(table, jnp.asarray(rows)))
        ok = rows >= 0
        for limbs in (1, 2, 5):
            got = fused_gather_planar(table_t, jnp.asarray(rows), limbs)
            assert len(got) == limbs
            for l in range(limbs):
                np.testing.assert_array_equal(
                    np.asarray(got[l])[ok], want[..., l][ok],
                    err_msg=f"shape={shape} limb={l}")


def test_expanded_topk_rejects_misdeclared_planes():
    """ADVICE r5 finding 1: a 5-plane expansion read with planes=2
    aliases arithmetically (970 lanes % 2 == 0 → stride \"161\") and
    used to produce silently wrong certified windows.  The inferred
    stride is now validated against SUPPORTED_STRIDES, so every
    cross-planes misparse of every supported stride fails loudly —
    checked exhaustively below — and unregistered build strides are
    rejected at expansion time."""
    from opendht_tpu.ops.sorted_table import (SUPPORTED_STRIDES,
                                              expand_table,
                                              expand_table_chunked,
                                              expanded_topk)

    rng = np.random.default_rng(77)
    ids = jnp.asarray(rng.integers(0, 2**32, size=(512, 5),
                                   dtype=np.uint32))
    sorted_ids, _, n_valid = sort_table(ids)
    q = ids[:8]

    # the aliasing case from the advisory: 5-plane stride-64 as planes=2
    e5 = expand_table(sorted_ids, stride=64)
    with pytest.raises(ValueError, match="SUPPORTED_STRIDES"):
        expanded_topk(sorted_ids, e5, n_valid, q, select="fast2", planes=2)
    # the easy direction stays caught too (width not divisible)
    e2 = expand_table(sorted_ids, stride=64, limbs=2)
    with pytest.raises(ValueError, match="not a multiple"):
        expanded_topk(sorted_ids, e2, n_valid, q, planes=5)
    # unregistered stride refused at build time, both builders
    with pytest.raises(ValueError, match="SUPPORTED_STRIDES"):
        expand_table(sorted_ids, stride=20)
    with pytest.raises(ValueError, match="SUPPORTED_STRIDES"):
        expand_table_chunked(sorted_ids, stride=20)

    # the closed set really is misparse-free: no cross-planes read of
    # any supported stride infers another supported stride
    for s in SUPPORTED_STRIDES:
        for p1, p2 in ((5, 2), (2, 5)):
            width = p1 * (3 * s + 2)
            if width % p2:
                continue                      # caught by the modulo check
            erow2 = width // p2
            wlen2 = erow2 - 2
            assert not (wlen2 % 3 == 0 and wlen2 // 3 in SUPPORTED_STRIDES), \
                (s, p1, p2)


def test_expand_table_rows_cover_windows():
    """Row j of the expanded table is limb-planar sorted rows
    [64j-1, 64j+193), with zero sentinels at both ends."""
    from opendht_tpu.ops.sorted_table import (expand_table, EXPAND_STRIDE,
                                              _EROW)
    table_raw = _rand_raw(300, 40)
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    sorted_ids, _, _ = sort_table(ids)
    T2 = np.asarray(expand_table(sorted_ids))
    s = np.asarray(sorted_ids)
    NB = T2.shape[0]
    assert NB == -(-300 // EXPAND_STRIDE)
    padded = np.concatenate(
        [np.zeros((1, 5), np.uint32), s,
         np.zeros((_EROW,), np.uint32).repeat(5).reshape(-1, 5)])
    for j in range(NB):
        want = padded[64 * j: 64 * j + _EROW]          # [194, 5]
        got = T2[j].reshape(5, _EROW).T                # limb-planar → [194, 5]
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("select", ["fast3", "sort", "pallas"])
@pytest.mark.parametrize("bits", [16, 20])
def test_expanded_topk_certified_matches_oracle(select, bits):
    from opendht_tpu.ops.sorted_table import expanded_topk
    table_raw = _rand_raw(4096, 41)
    table_raw[100] = table_raw[50]            # duplicate id
    q_raw = _rand_raw(64, 42)
    q_raw[1] = table_raw[5]                   # distance-0 case
    valid = np.ones(4096, bool)
    valid[::7] = False
    sorted_ids, perm, n_valid, lut, T2 = _expanded_setup(
        table_raw, valid, bits=bits)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    dist, idx, cert = expanded_topk(sorted_ids, T2, n_valid, q, k=8,
                                    select=select, lut=lut)
    cert = np.asarray(cert)
    assert cert.mean() > 0.9
    p = np.asarray(perm)
    for qi in range(64):
        if not cert[qi]:
            continue
        want = _oracle_topk(q_raw[qi], table_raw, 8, valid)
        got = [p[j] for j in np.asarray(idx[qi]) if j >= 0]
        want_d = [w[0] for w in want]
        got_d = [
            int.from_bytes(K.ids_to_bytes(np.asarray(dist[qi, j])).tobytes(),
                           "big")
            for j in range(len(got))
        ]
        assert got_d == want_d, f"query {qi}"


@pytest.mark.parametrize("select", ["fast3", "pallas"])
def test_expanded_lookup_fallback_exact_under_clustering(select):
    """Adversarial shared prefixes overflow LUT buckets and windows; the
    certificate must catch every such query and lookup_topk's fallback
    must restore exactness."""
    table_raw = _rand_raw(2048, 43, cluster=10)
    q_raw = table_raw[:32].copy()
    q_raw[:, 19] ^= 0xFF
    sorted_ids, perm, n_valid, lut, T2 = _expanded_setup(table_raw)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    dist, idx, cert = lookup_topk(sorted_ids, n_valid, q, k=8, lut=lut,
                                  expanded=T2, select=select)
    assert bool(np.asarray(cert).all())
    for qi in range(32):
        want_d = [w[0] for w in _oracle_topk(q_raw[qi], table_raw, 8)]
        got_d = [
            int.from_bytes(K.ids_to_bytes(np.asarray(dist[qi, j])).tobytes(),
                           "big")
            for j in range(8)
        ]
        assert got_d == want_d, f"query {qi}"


def test_expanded_fast3_tie_certificate():
    """Ids sharing their top 64 bits make the fast3 (d0, d1) comparator
    ambiguous; those queries must come back uncertified (and exact via
    fallback), never silently mis-ordered."""
    from opendht_tpu.ops.sorted_table import expanded_topk
    rng = np.random.default_rng(44)
    table_raw = rng.integers(0, 256, size=(512, 20), dtype=np.uint8)
    # 16 ids with identical first 8 bytes, distinct tails
    table_raw[:16, :8] = table_raw[0, :8]
    q_raw = table_raw[:4].copy()              # queries inside the tie cluster
    q_raw[:, 12] ^= 0x55
    sorted_ids, perm, n_valid, lut, T2 = _expanded_setup(table_raw)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    _, _, cert = expanded_topk(sorted_ids, T2, n_valid, q, k=8,
                               select="fast3", lut=lut)
    assert not bool(np.asarray(cert).any())   # every tied query flagged
    # fallback restores exactness
    dist, idx, cert2 = lookup_topk(sorted_ids, n_valid, q, k=8, lut=lut,
                                   expanded=T2, select="fast3")
    assert bool(np.asarray(cert2).all())
    for qi in range(4):
        want_d = [w[0] for w in _oracle_topk(q_raw[qi], table_raw, 8)]
        got_d = [
            int.from_bytes(K.ids_to_bytes(np.asarray(dist[qi, j])).tobytes(),
                           "big")
            for j in range(8)
        ]
        assert got_d == want_d, f"query {qi}"


@pytest.mark.parametrize("select", ["fast3", "pallas"])
def test_expanded_topk_small_tables(select):
    from opendht_tpu.ops.sorted_table import expanded_topk
    for n, nv in [(8, 5), (64, 64), (70, 66), (200, 1)]:
        table_raw = _rand_raw(n, 45 + n)
        valid = np.arange(n) < nv
        sorted_ids, perm, n_valid, lut, T2 = _expanded_setup(table_raw, valid)
        q_raw = _rand_raw(33, 46 + n)
        q = jnp.asarray(K.ids_from_bytes(q_raw))
        dist, idx, cert = expanded_topk(sorted_ids, T2, n_valid, q, k=8,
                                        select=select, lut=lut)
        assert bool(np.asarray(cert).all()), (n, nv)
        idx = np.asarray(idx)
        assert ((idx >= 0).sum(axis=1) == min(nv, 8)).all(), (n, nv)
        p = np.asarray(perm)
        for qi in range(33):
            want = _oracle_topk(q_raw[qi], table_raw, 8, valid)
            got = [p[j] for j in idx[qi] if j >= 0]
            assert got == [w[1] for w in want], (n, nv, qi)


def test_expanded_fast2_idx_exact():
    """fast2 carries no distance limbs; the index set/order must still be
    exact where certified, ties must decertify, and lookup_topk's
    fallback must repair the rest."""
    from opendht_tpu.ops.sorted_table import expanded_topk
    table_raw = _rand_raw(4096, 60)
    q_raw = _rand_raw(64, 61)
    q_raw[1] = table_raw[5]
    valid = np.ones(4096, bool)
    valid[::5] = False
    sorted_ids, perm, n_valid, lut, T2 = _expanded_setup(table_raw, valid)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    dist, idx, cert = expanded_topk(sorted_ids, T2, n_valid, q, k=8,
                                    select="fast2", lut=lut)
    assert dist is None
    cert = np.asarray(cert)
    assert cert.mean() > 0.9
    p = np.asarray(perm)
    for qi in range(64):
        if not cert[qi]:
            continue
        want = _oracle_topk(q_raw[qi], table_raw, 8, valid)
        got = [p[j] for j in np.asarray(idx[qi]) if j >= 0]
        assert got == [w[1] for w in want], f"query {qi}"
    # tie cluster → decertify + fallback repairs
    table_raw2 = _rand_raw(512, 62)
    table_raw2[:16, :8] = table_raw2[0, :8]
    q2_raw = table_raw2[:4].copy(); q2_raw[:, 12] ^= 0x55
    s2, p2, nv2, lut2, T22 = _expanded_setup(table_raw2)
    q2 = jnp.asarray(K.ids_from_bytes(q2_raw))
    d2, i2, c2 = lookup_topk(s2, nv2, q2, k=8, lut=lut2, expanded=T22,
                             select="fast2")
    assert d2 is None and bool(np.asarray(c2).all())
    pp = np.asarray(p2)
    for qi in range(4):
        want = _oracle_topk(q2_raw[qi], table_raw2, 8)
        got = [pp[j] for j in np.asarray(i2[qi]) if j >= 0]
        assert got == [w[1] for w in want], f"tie query {qi}"


@pytest.mark.parametrize("stride", [32, 42, 64])
def test_expanded_topk_parametric_stride(stride):
    """expand_table generalizes over stride (window = 3·stride): every
    stride must stay exact on certified rows and the certificate must
    stay sound.  stride=32 (96-window — sorts in 128 padded lanes) is
    the headline-bench geometry (bench.py HEADLINE_STRIDE); 42 and 64
    are swept variants (42 was the round-2 headline)."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              expanded_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(31)
    table_raw = rng.integers(0, 256, size=(4096, 20), dtype=np.uint8)
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    valid = np.ones(4096, bool)
    valid[::7] = False
    sorted_ids, perm, n_valid = sort_table(ids, jnp.asarray(valid))
    lut = build_prefix_lut(sorted_ids, n_valid)
    exp = expand_table(sorted_ids, stride=stride)
    q_raw = rng.integers(0, 256, size=(128, 20), dtype=np.uint8)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    d_ref, i_ref = xor_topk(q, sorted_ids, k=16,
                            valid=jnp.arange(4096) < n_valid)
    # both the bounded positioning search and the LUT-only (0-step) mode
    for steps in (None, 0):
        d, i, c = expanded_topk(sorted_ids, exp, n_valid, q, k=16,
                                select="fast2", lut=lut, lut_steps=steps)
        assert d is None
        c_np = np.asarray(c)
        assert c_np.mean() > 0.9, (stride, steps)
        np.testing.assert_array_equal(np.asarray(i)[c_np],
                                      np.asarray(i_ref)[c_np])
    # and the full pipeline (device-side exact fallback) repairs the rest
    _, i_full, c_full = lookup_topk(sorted_ids, n_valid, q, k=16, lut=lut,
                                    expanded=exp, select="fast2")
    assert bool(np.asarray(c_full).all())
    np.testing.assert_array_equal(np.asarray(i_full), np.asarray(i_ref))


@pytest.mark.parametrize("stride", [32, 64])
def test_expanded_topk_two_plane_bitwise_identical(stride):
    """The 2-plane expansion (``expand_table(limbs=2)`` + ``planes=2``)
    must be BIT-IDENTICAL to the 5-plane fast2 path — idx and
    certificate both — across uniform, masked, clustered, and
    tie-heavy tables (round-4 verdict ask #2; the clamp argument in
    ``_window_certificate``: fast2's cp_k is already clamped at 64, so
    2-limb neighbor common-bits lose nothing)."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              expanded_topk, cascade_topk)
    from opendht_tpu.ops.xor_topk import xor_topk

    cases = []
    # uniform + invalid mask
    raw = _rand_raw(4096, 70)
    valid = np.ones(4096, bool); valid[::6] = False
    cases.append((raw, valid))
    # adversarial prefix cluster (windows misplace, certificates deny)
    cases.append((_rand_raw(2048, 71, cluster=8), None))
    # tie-heavy: many rows sharing their top 64 bits (fast2 tie check)
    raw_t = _rand_raw(1024, 72)
    raw_t[:64, :8] = raw_t[0, :8]
    cases.append((raw_t, None))
    # tiny n_valid (< one window)
    raw_s = _rand_raw(512, 73)
    valid_s = np.zeros(512, bool); valid_s[:5] = True
    cases.append((raw_s, valid_s))

    for raw, valid in cases:
        n = raw.shape[0]
        ids = jnp.asarray(K.ids_from_bytes(raw))
        v = None if valid is None else jnp.asarray(valid)
        sorted_ids, perm, n_valid = sort_table(ids, v)
        lut = build_prefix_lut(sorted_ids, n_valid)
        e5 = expand_table(sorted_ids, stride=stride)
        e2 = expand_table(sorted_ids, stride=stride, limbs=2)
        erow = 3 * stride + 2
        np.testing.assert_array_equal(np.asarray(e2),
                                      np.asarray(e5)[:, :2 * erow])
        q_raw = np.concatenate([_rand_raw(64, 74), raw[:16]], axis=0)
        q = jnp.asarray(K.ids_from_bytes(q_raw))
        for steps in (None, 0):
            d5, i5, c5 = expanded_topk(sorted_ids, e5, n_valid, q, k=8,
                                       select="fast2", lut=lut,
                                       lut_steps=steps)
            d2, i2, c2 = expanded_topk(sorted_ids, e2, n_valid, q, k=8,
                                       select="fast2", lut=lut,
                                       lut_steps=steps, planes=2)
            assert d2 is None
            np.testing.assert_array_equal(np.asarray(i5), np.asarray(i2))
            np.testing.assert_array_equal(np.asarray(c5), np.asarray(c2))
        # certified rows are exact vs the oracle
        _, i_ref = xor_topk(q, sorted_ids, k=8,
                            valid=jnp.arange(n) < n_valid)
        cm = np.asarray(c2)
        np.testing.assert_array_equal(np.asarray(i2)[cm],
                                      np.asarray(i_ref)[cm])
        # cascade with both expansions 2-plane matches the 5-plane cascade
        e5w = expand_table(sorted_ids)
        e2w = expand_table(sorted_ids, limbs=2)
        _, ic5, cc5 = cascade_topk(sorted_ids, e5, e5w, n_valid, q, lut,
                                   k=8, select="fast2")
        _, ic2, cc2 = cascade_topk(sorted_ids, e2, e2w, n_valid, q, lut,
                                   k=8, select="fast2", planes=2)
        np.testing.assert_array_equal(np.asarray(ic5), np.asarray(ic2))
        np.testing.assert_array_equal(np.asarray(cc5), np.asarray(cc2))

    # partial planes are fast2-only: other selects must refuse loudly
    with pytest.raises(ValueError):
        expanded_topk(sorted_ids, e2, n_valid, q, k=8, select="fast3",
                      lut=lut, planes=2)


def test_churn_lookup_narrow_delta_cascade_exact():
    """The stride-16 narrow-delta cascade (d_exp_wide + d_cap) must be
    exact vs the full-re-sort oracle — including when the narrow margin
    decertifies rows (they repair against the wide expansion, and any
    residual goes to the exact cond)."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              churn_lookup_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(81)
    N, D = 4096, 1024
    raw = _rand_raw(N, 82)
    sorted_ids, perm, n_valid = sort_table(jnp.asarray(K.ids_from_bytes(raw)))
    lut = build_prefix_lut(sorted_ids, n_valid)
    tomb = np.zeros((N + 31) // 32, np.uint32)
    dead = rng.choice(N, size=200, replace=False)
    np.bitwise_or.at(tomb, dead >> 5,
                     np.uint32(1) << (dead & 31).astype(np.uint32))
    # clustered delta: shared prefixes force narrow-window decertification
    d_raw = _rand_raw(D, 83, cluster=6)
    ds, dp, dnv = sort_table(jnp.asarray(K.ids_from_bytes(d_raw)))
    dlut = build_prefix_lut(ds, dnv)
    q_raw = np.concatenate([_rand_raw(96, 84), d_raw[:32]], axis=0)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    _, enc, cert = churn_lookup_topk(
        sorted_ids, expand_table(sorted_ids, stride=32, limbs=2), n_valid,
        jnp.asarray(tomb), ds, expand_table(ds, stride=16, limbs=2), dnv,
        q, lut=lut, d_lut=dlut,
        d_exp_wide=expand_table(ds, stride=64, limbs=2),
        k=8, select="fast2", lut_steps=0, planes=2, d_cap=64)
    assert bool(np.asarray(cert).all())
    live = np.ones(N, bool)
    live[dead] = False
    cat = jnp.concatenate([sorted_ids, ds], axis=0)
    cval = jnp.concatenate([jnp.asarray(live), jnp.arange(D) < dnv])
    _, i_ref = xor_topk(q, cat, k=8, valid=cval)
    np.testing.assert_array_equal(np.asarray(enc), np.asarray(i_ref))


def test_churn_lookup_two_plane_matches():
    """churn_lookup_topk with 2-plane base+delta expansions (fast2) is
    bit-identical to the 5-plane fast2 churn path and exact vs the
    full-re-sort oracle."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              churn_lookup_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(75)
    N, D = 4096, 256
    raw = _rand_raw(N, 76)
    ids = jnp.asarray(K.ids_from_bytes(raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    lut = build_prefix_lut(sorted_ids, n_valid)
    tomb = np.zeros((N + 31) // 32, np.uint32)
    dead = rng.choice(N, size=300, replace=False)
    np.bitwise_or.at(tomb, dead >> 5,
                     np.uint32(1) << (dead & 31).astype(np.uint32))
    d_raw = _rand_raw(D, 77)
    ds, dp, dnv = sort_table(jnp.asarray(K.ids_from_bytes(d_raw)))
    q = jnp.asarray(K.ids_from_bytes(_rand_raw(128, 78)))

    args5 = (sorted_ids, expand_table(sorted_ids, stride=32), n_valid,
             jnp.asarray(tomb), ds, expand_table(ds, stride=32), dnv, q)
    args2 = (sorted_ids, expand_table(sorted_ids, stride=32, limbs=2),
             n_valid, jnp.asarray(tomb), ds,
             expand_table(ds, stride=32, limbs=2), dnv, q)
    _, e5, c5 = churn_lookup_topk(*args5, lut=lut, k=8, select="fast2")
    _, e2, c2 = churn_lookup_topk(*args2, lut=lut, k=8, select="fast2",
                                  planes=2)
    np.testing.assert_array_equal(np.asarray(e5), np.asarray(e2))
    # oracle: full re-sort of (live base ∪ delta)
    live = np.ones(N, bool)
    live[dead] = False
    cat = jnp.concatenate([sorted_ids, ds], axis=0)
    cval = jnp.concatenate([jnp.asarray(live), jnp.arange(D) < dnv])
    _, i_ref = xor_topk(q, cat, k=8, valid=cval)
    np.testing.assert_array_equal(np.asarray(e2), np.asarray(i_ref))


def test_cascade_topk_two_stage_device_repair():
    """cascade_topk: stage-1 (stride-42 here; the headline bench uses
    stride 32) misses are repaired on device by the wide stride-64
    rescan; residual uncertified rows (cap overflow / adversarial) stay
    flagged and the host fallback path remains exact."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              cascade_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(33)
    table_raw = rng.integers(0, 256, size=(8192, 20), dtype=np.uint8)
    ids = jnp.asarray(K.ids_from_bytes(table_raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    lut = build_prefix_lut(sorted_ids, n_valid)
    e42 = expand_table(sorted_ids, stride=42)
    e64 = expand_table(sorted_ids, stride=64)
    q_raw = rng.integers(0, 256, size=(512, 20), dtype=np.uint8)
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    d_ref, i_ref = xor_topk(q, sorted_ids, k=16)

    d, i, c = cascade_topk(sorted_ids, e42, e64, n_valid, q, lut, k=16,
                           select="fast2")
    assert d is None
    c_np = np.asarray(c)
    assert c_np.mean() > 0.99
    np.testing.assert_array_equal(np.asarray(i)[c_np],
                                  np.asarray(i_ref)[c_np])

    # adversarial cluster: most stage-1 windows misplace AND overflow the
    # cap — flagged rows must stay flagged, certified rows stay exact
    t2 = rng.integers(0, 256, size=(4096, 20), dtype=np.uint8)
    t2[:3500, :10] = 0x5A
    ids2 = jnp.asarray(K.ids_from_bytes(t2))
    s2, p2, nv2 = sort_table(ids2)
    lut2 = build_prefix_lut(s2, nv2)
    q2_raw = t2[:400].copy(); q2_raw[:, 15] ^= 0x0F
    q2 = jnp.asarray(K.ids_from_bytes(q2_raw))
    d_ref2, i_ref2 = xor_topk(q2, s2, k=16)
    _, i2o, c2o = cascade_topk(s2, expand_table(s2, stride=42),
                               expand_table(s2, stride=64), nv2, q2, lut2,
                               k=16, select="fast2", cap=64)
    c2_np = np.asarray(c2o)
    np.testing.assert_array_equal(np.asarray(i2o)[c2_np],
                                  np.asarray(i_ref2)[c2_np])


@pytest.mark.parametrize("n,chunks", [(4096, 8), (4099, 4), (1000, 3)])
def test_expand_table_chunked_matches(n, chunks):
    """The chunked low-peak-memory builder must be bit-identical to
    expand_table on all real rows (trailing chunk-padding rows are
    zeros and never read — the jmax clamp is bounded by n_valid)."""
    from opendht_tpu.ops.sorted_table import (expand_table,
                                              expand_table_chunked,
                                              expanded_topk,
                                              build_prefix_lut)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(57 + n)
    raw = rng.integers(0, 256, size=(n, 20), dtype=np.uint8)
    sorted_ids, perm, n_valid = sort_table(jnp.asarray(K.ids_from_bytes(raw)))
    a = expand_table(sorted_ids)
    b = expand_table_chunked(sorted_ids, chunks=chunks)
    NB = a.shape[0]
    assert b.shape[0] >= NB and b.shape[1] == a.shape[1]
    np.testing.assert_array_equal(np.asarray(b)[:NB], np.asarray(a))
    # and the padded form gives exact lookups end to end
    q = jnp.asarray(K.ids_from_bytes(
        rng.integers(0, 256, size=(64, 20), dtype=np.uint8)))
    lut = build_prefix_lut(sorted_ids, n_valid)
    d, i, c = expanded_topk(sorted_ids, b, n_valid, q, k=8, lut=lut,
                            lut_steps=0)
    d_ref, i_ref = xor_topk(q, sorted_ids, k=8, valid=jnp.arange(n) < n_valid)
    c_np = np.asarray(c)
    np.testing.assert_array_equal(np.asarray(i)[c_np], np.asarray(i_ref)[c_np])
    assert c_np.mean() > 0.9


@pytest.mark.slow
def test_fuzz_kernel_geometries_certified_rows_exact():
    """Randomized sweep: random VALID counts (including < k and == k),
    random invalid fractions, duplicate ids, query hits, across strides
    and select modes — certified rows must ALWAYS equal the full-scan
    oracle, and lookup_topk must always repair to exactness.

    Shapes are FIXED (table slab 3000 rows, 64 queries; randomness
    lives in the valid mask / data) so the ~90 kernel invocations reuse
    a handful of compiles instead of recompiling per trial — the
    shape-per-trial version of this test spent ~5 min in XLA.
    """
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              expanded_topk, cascade_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(2026)
    NSLAB, NQ = 3000, 64
    for trial in range(10):
        n = int(rng.integers(3, NSLAB))
        kk = int(rng.choice([8, 16]))
        stride = int(rng.choice([24, 32, 42, 64]))
        raw = rng.integers(0, 256, size=(NSLAB, 20), dtype=np.uint8)
        if trial % 3 == 0:
            raw[: NSLAB // 3] = raw[0]        # duplicate ids
        valid = np.zeros(NSLAB, bool)
        valid[rng.permutation(NSLAB)[:n]] = True
        if trial % 4 == 1:
            valid &= rng.random(NSLAB) > 0.9  # very sparse
        ids = jnp.asarray(K.ids_from_bytes(raw))
        sorted_ids, perm, n_valid = sort_table(ids, jnp.asarray(valid))
        lut = build_prefix_lut(sorted_ids, n_valid)
        exp = expand_table(sorted_ids, stride=stride)
        q_raw = rng.integers(0, 256, size=(NQ, 20), dtype=np.uint8)
        q_raw[: NQ // 2] = raw[rng.integers(0, NSLAB, NQ // 2)]  # hits
        q = jnp.asarray(K.ids_from_bytes(q_raw))
        d_ref, i_ref = xor_topk(q, sorted_ids, k=kk,
                                valid=jnp.arange(NSLAB) < n_valid)
        for select in ("fast2", "fast3", "sort"):
            for steps in (None, 0):
                d, i, c = expanded_topk(sorted_ids, exp, n_valid, q, k=kk,
                                        select=select, lut=lut,
                                        lut_steps=steps)
                c_np = np.asarray(c)
                ctx = (trial, n, kk, stride, select, steps)
                np.testing.assert_array_equal(
                    np.asarray(i)[c_np], np.asarray(i_ref)[c_np],
                    err_msg=str(ctx))
                if d is not None:
                    np.testing.assert_array_equal(
                        np.asarray(d)[c_np], np.asarray(d_ref)[c_np],
                        err_msg=str(ctx))
            # full repair contract (device-cond fallback path)
            _, i_full, c_full = lookup_topk(sorted_ids, n_valid, q, k=kk,
                                            lut=lut, expanded=exp,
                                            select=select)
            assert bool(np.asarray(c_full).all()), ctx
            np.testing.assert_array_equal(np.asarray(i_full),
                                          np.asarray(i_ref), err_msg=str(ctx))
        # cascade with a second (wide) expansion
        if stride != 64:
            exp64 = expand_table(sorted_ids)
            d2, i2, c2 = cascade_topk(sorted_ids, exp, exp64, n_valid, q,
                                      lut, k=kk, select="fast2", cap=64)
            c2_np = np.asarray(c2)
            np.testing.assert_array_equal(
                np.asarray(i2)[c2_np], np.asarray(i_ref)[c2_np],
                err_msg=str((trial, "cascade")))


@pytest.mark.slow
@pytest.mark.parametrize("k", [8, 16])
@pytest.mark.parametrize("cap", [8, 64, 512])
def test_fuzz_cascade_cap_overflow_graceful(k, cap):
    """cascade_topk (the headline kernel) under adversarial clustering,
    across caps and both stage strides: rows neither stage certifies —
    including cap OVERFLOW, where more rows decertify than stage 2 can
    rescue — must come back certified=False (never silently wrong),
    certified rows must equal the oracle, the host fallback must
    restore exactness, and results must be deterministic (the
    duplicate-fill-row scatter writes are value-identical by
    construction — see cascade_topk's fill_value comment)."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, expand_table,
                                              cascade_topk)
    from opendht_tpu.ops.xor_topk import xor_topk
    rng = np.random.default_rng(4242)
    NSLAB, NQ = 3072, 64
    raw = rng.integers(0, 256, size=(NSLAB, 20), dtype=np.uint8)
    # 80% of rows share a 12-byte prefix: in-cluster neighbors agree on
    # ≥96 bits while fast2's cp lower bound clamps at 64, so NEITHER
    # stage can certify in-cluster queries — every one overflows any cap
    raw[: 4 * NSLAB // 5, :12] = raw[0, :12]
    ids = jnp.asarray(K.ids_from_bytes(raw))
    sorted_ids, perm, n_valid = sort_table(ids)
    lut = build_prefix_lut(sorted_ids, n_valid)
    exp64 = expand_table(sorted_ids)
    q_raw = raw[rng.integers(0, 4 * NSLAB // 5, NQ)].copy()
    q_raw[:, 19] ^= rng.integers(1, 255, NQ, dtype=np.uint8)  # near-hits
    q = jnp.asarray(K.ids_from_bytes(q_raw))
    d_ref, i_ref = xor_topk(q, sorted_ids, k=k)
    i_ref = np.asarray(i_ref)

    for stride in (24, 32):
        exp_s = expand_table(sorted_ids, stride=stride)
        _d, i1, c1 = cascade_topk(sorted_ids, exp_s, exp64, n_valid, q,
                                  lut, k=k, select="fast2", cap=cap)
        _d, i1b, c1b = cascade_topk(sorted_ids, exp_s, exp64, n_valid, q,
                                    lut, k=k, select="fast2", cap=cap)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i1b))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c1b))
        i1, c1 = np.array(i1), np.asarray(c1)
        ctx = (k, cap, stride)
        # the adversarial cluster defeats both stages (cap=8 is the
        # overflow case: more uncertified rows than stage 2 can rescue)
        assert (~c1).any(), ctx
        np.testing.assert_array_equal(i1[c1], i_ref[c1], err_msg=str(ctx))
        # graceful overflow: flagged rows repair exactly on the host
        bad = np.nonzero(~c1)[0]
        if len(bad):
            _fd, fi = xor_topk(q[bad], sorted_ids, k=k)
            i1[bad] = np.asarray(fi)
        np.testing.assert_array_equal(i1, i_ref, err_msg=str(ctx))
