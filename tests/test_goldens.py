"""Frozen wire-format goldens: our engine's bytes vs an independent
encoding of the reference protocol.

The .bin fixtures under tests/goldens/ were produced by
tests/goldens/make_goldens.py — a second msgpack implementation
(mini_msgpack.py, written from the msgpack spec, NOT python-msgpack)
transcribing the reference's pack calls (src/network_engine.cpp:677-1305,
include/opendht/value.h:470-511).  If our NetworkEngine's emitted bytes
drift from these files in any way — key order, int widths, bin headers,
field sets — these tests fail.  The reverse direction parses each golden
through ParsedMessage and checks full field recovery, i.e. we accept
exactly what a reference peer would send.

(The real C++ peer cannot be built here: cmake fails on missing
GnuTLS/msgpack-c dev packages — see make_goldens.py docstring.)
"""

import glob
import os

import pytest

from opendht_tpu.core.value import Field, Query, Select, Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net.engine import EngineCallbacks, NetworkEngine
from opendht_tpu.net.node import Node
from opendht_tpu.net.parsed_message import MessageType, ParsedMessage
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

GOLDENS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens")

MYID = bytes(range(20))
TARGET = b"\xaa" * 20
HASH = b"\xbb" * 20
TID = 0x01020304
SID = 0x05060709
TOKEN = bytes(range(0x10, 0x18))
CREATED = 1_700_000_000
VID = 42
ADDR = SockAddr("10.0.0.9", 4009)        # replies carry only the ip ("sa")
N4_BLOB = (b"\xc1" * 20 + b"\x0a\x00\x00\x01" + (4000).to_bytes(2, "big")
           + b"\xc2" * 20 + b"\x0a\x00\x00\x02" + (4001).to_bytes(2, "big"))
N6_BLOB = (b"\xd1" * 20 + b"\x00" * 15 + b"\x01" + (4002).to_bytes(2, "big"))

V1 = Value(b"hello world", type_id=3, value_id=VID)
V2 = Value(b"second value", type_id=0, value_id=43, user_type="text/plain")


def golden(name: str) -> bytes:
    with open(os.path.join(GOLDENS, name + ".bin"), "rb") as f:
        return f.read()


def make_engine(network: int = 0):
    sent = []
    eng = NetworkEngine(InfoHash(MYID), network,
                        lambda data, dst: sent.append(bytes(data)) or 0,
                        Scheduler(), EngineCallbacks())
    return eng, sent


def fixed_node(*tids) -> Node:
    node = Node(InfoHash.get("peer"), SockAddr("10.0.0.1", 4000))
    seq = list(tids)
    node.get_new_tid = lambda: seq.pop(0)
    return node


# ------------------------------------------------------------ emit == golden

def test_ping_req():
    eng, sent = make_engine()
    eng.send_ping(fixed_node(TID))
    assert sent[0] == golden("ping_req")


def test_ping_req_network():
    eng, sent = make_engine(network=7)
    eng.send_ping(fixed_node(TID))
    assert sent[0] == golden("ping_req_net7")


def test_pong_and_listen_confirmation():
    eng, sent = make_engine()
    eng.send_pong(ADDR, TID)
    eng.send_listen_confirmation(ADDR, TID)
    assert sent[0] == golden("pong")
    assert sent[1] == golden("pong")      # same layout (cpp:1119-1133)


def test_find_req():
    from opendht_tpu.utils import WANT4, WANT6
    eng, sent = make_engine()
    eng.send_find_node(fixed_node(TID), InfoHash(TARGET), want=WANT4 | WANT6)
    assert sent[0] == golden("find_req")


def test_get_req():
    eng, sent = make_engine()
    eng.send_get_values(fixed_node(TID), InfoHash(HASH), Query())
    assert sent[0] == golden("get_req")


def test_get_req_select():
    eng, sent = make_engine()
    q = Query(select=Select().field(Field.ID))
    eng.send_get_values(fixed_node(TID), InfoHash(HASH), q)
    assert sent[0] == golden("get_req_select")


def test_listen_req():
    eng, sent = make_engine()
    node = fixed_node(SID, TID)
    req = eng.send_listen(node, InfoHash(HASH), Query(), TOKEN, None,
                          socket_cb=lambda *a: None)
    assert req is not None
    assert sent[0] == golden("listen_req")


def test_announce_req():
    eng, sent = make_engine()
    eng.send_announce_value(fixed_node(TID), InfoHash(HASH), V1,
                            float(CREATED), TOKEN)
    assert sent[0] == golden("announce_req")


def test_refresh_req():
    eng, sent = make_engine()
    eng.send_refresh_value(fixed_node(TID), InfoHash(HASH), VID, TOKEN)
    assert sent[0] == golden("refresh_req")


def test_nodes_values_resp():
    eng, sent = make_engine()
    eng.send_nodes_values(ADDR, TID, N4_BLOB, N6_BLOB, [V1, V2], Query(),
                          TOKEN)
    assert sent[0] == golden("nodes_values")


def test_value_announced_resp():
    eng, sent = make_engine()
    eng.send_value_announced(ADDR, TID, VID)
    assert sent[0] == golden("value_announced")


def test_error_resp():
    eng, sent = make_engine()
    eng.send_error(ADDR, TID, 401, "Unauthorized", include_id=True)
    assert sent[0] == golden("error_unauthorized")


def test_value_parts_stream():
    eng, sent = make_engine()
    big = Value(bytes(range(256)) * 11, type_id=3, value_id=77)
    eng._send_value_parts(TID, [big.get_packed()], ADDR)
    assert b"".join(sent) == golden("value_parts")


# ------------------------------------------------------- parse(golden) == ok

def test_parse_ping():
    m = ParsedMessage.from_bytes(golden("ping_req"))
    assert m.type is MessageType.PING
    assert bytes(m.id) == MYID and m.tid == TID and m.ua == "RNG1"


def test_parse_find():
    m = ParsedMessage.from_bytes(golden("find_req"))
    assert m.type is MessageType.FIND_NODE
    assert bytes(m.target) == TARGET
    from opendht_tpu.utils import WANT4, WANT6
    assert m.want == WANT4 | WANT6


def test_parse_get_select():
    m = ParsedMessage.from_bytes(golden("get_req_select"))
    assert m.type is MessageType.GET_VALUES
    assert bytes(m.info_hash) == HASH
    assert m.query.select.get_selection() == [Field.ID]


def test_parse_listen():
    m = ParsedMessage.from_bytes(golden("listen_req"))
    assert m.type is MessageType.LISTEN
    assert m.token == TOKEN and m.socket_id == SID


def test_parse_announce():
    m = ParsedMessage.from_bytes(golden("announce_req"))
    assert m.type is MessageType.ANNOUNCE_VALUE
    assert m.token == TOKEN and m.created == CREATED
    assert len(m.values) == 1
    v = m.values[0]
    assert v.id == VID and v.type == 3 and v.data == b"hello world"


def test_parse_refresh():
    m = ParsedMessage.from_bytes(golden("refresh_req"))
    assert m.type is MessageType.REFRESH
    assert m.value_id == VID and m.token == TOKEN


def test_parse_nodes_values():
    m = ParsedMessage.from_bytes(golden("nodes_values"))
    assert m.nodes4_raw == N4_BLOB and m.nodes6_raw == N6_BLOB
    assert m.token == TOKEN
    assert [v.id for v in m.values] == [VID, 43]
    assert m.values[1].user_type == "text/plain"
    assert m.addr.ip is not None and m.addr.ip.packed == b"\x0a\x00\x00\x09"


def test_parse_error():
    m = ParsedMessage.from_bytes(golden("error_unauthorized"))
    assert m.type is MessageType.ERROR
    assert m.error_code == 401 and bytes(m.id) == MYID


def test_parse_value_parts_reassembly():
    """Feed the fragment stream through the engine's rx path after an
    announce that declared part sizes (network_engine.cpp:407-457)."""
    raw = golden("value_parts")
    # split packets: each starts with 0x83 fixmap(3); reparse via Unpacker
    from opendht_tpu.utils import unpack_stream
    frags = [ParsedMessage.from_obj(o) for o in unpack_stream(raw)]
    assert all(f.type is MessageType.VALUE_DATA for f in frags)
    assert [f.tid for f in frags] == [TID] * len(frags)
    blob = bytearray()
    for f in frags:
        for idx, part in f.value_parts.items():
            assert idx == 0
            off, data = part
            assert off == len(blob)
            blob.extend(data)
    v = Value.from_packed(bytes(blob))
    assert v.id == 77 and v.data == bytes(range(256)) * 11


def test_goldens_regeneration_is_stable():
    """make_goldens.py output matches the checked-in fixtures, so the
    generator and the frozen bytes can't drift apart silently."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "make_goldens", os.path.join(GOLDENS, "make_goldens.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fresh = mod.make_goldens()
    on_disk = {os.path.basename(p)[:-4]: open(p, "rb").read()
               for p in glob.glob(os.path.join(GOLDENS, "*.bin"))}
    assert fresh == on_disk
