"""Minimal msgpack *encoder*, written directly from the msgpack spec.

This is deliberately NOT python-msgpack (which the engine itself uses via
utils.pack_msg): the golden wire fixtures must come from an independent
second implementation so a shared encoding mistake can't validate itself.
Only the encodings msgpack-c emits for the reference's pack calls are
implemented, with the same smallest-width rules msgpack-c applies:

  pk.pack(int)        → positive fixint / uint8 / uint16 / uint32 / uint64
                        (negative: negative fixint / int8 / ...)
  pk.pack(std::string)→ fixstr / str8 / str16
  pk.pack_bin         → bin8 / bin16 / bin32
  pk.pack_map(n)      → fixmap / map16
  pk.pack_array(n)    → fixarray / array16
  pk.pack(bool)       → 0xc2 / 0xc3

spec: https://github.com/msgpack/msgpack/blob/master/spec.md
"""

import struct


def p_uint(n: int) -> bytes:
    if n < 0:
        return p_int(n)
    if n <= 0x7F:
        return bytes([n])
    if n <= 0xFF:
        return b"\xcc" + bytes([n])
    if n <= 0xFFFF:
        return b"\xcd" + struct.pack(">H", n)
    if n <= 0xFFFFFFFF:
        return b"\xce" + struct.pack(">I", n)
    return b"\xcf" + struct.pack(">Q", n)


def p_int(n: int) -> bytes:
    if n >= 0:
        return p_uint(n)
    if n >= -32:
        return struct.pack(">b", n)
    if n >= -128:
        return b"\xd0" + struct.pack(">b", n)
    if n >= -(1 << 15):
        return b"\xd1" + struct.pack(">h", n)
    if n >= -(1 << 31):
        return b"\xd2" + struct.pack(">i", n)
    return b"\xd3" + struct.pack(">q", n)


def p_str(s: str) -> bytes:
    b = s.encode("utf-8")
    if len(b) < 32:
        return bytes([0xA0 | len(b)]) + b
    if len(b) <= 0xFF:
        return b"\xd9" + bytes([len(b)]) + b
    return b"\xda" + struct.pack(">H", len(b)) + b


def p_bin(b: bytes) -> bytes:
    if len(b) <= 0xFF:
        return b"\xc4" + bytes([len(b)]) + b
    if len(b) <= 0xFFFF:
        return b"\xc5" + struct.pack(">H", len(b)) + b
    return b"\xc6" + struct.pack(">I", len(b)) + b


def p_map(n: int) -> bytes:
    """Map header only — caller appends n (key, value) encodings."""
    if n < 16:
        return bytes([0x80 | n])
    return b"\xde" + struct.pack(">H", n)


def p_array(n: int) -> bytes:
    """Array header only — caller appends n element encodings."""
    if n < 16:
        return bytes([0x90 | n])
    return b"\xdc" + struct.pack(">H", n)


def p_bool(v: bool) -> bytes:
    return b"\xc3" if v else b"\xc2"
