"""Generate the frozen wire-format golden fixtures.

Each golden is a byte-exact packet a reference peer emits/accepts, built
with the independent mini_msgpack encoder by transcribing the reference's
pack calls one for one (file:line cited per message).  The .bin files are
checked in; tests/test_goldens.py asserts our NetworkEngine emits these
exact bytes and parses them back.  Regenerate with::

    python tests/goldens/make_goldens.py

Interop context: building the reference C++ node in this environment was
attempted and is impossible — `cmake /root/reference -DOPENDHT_TOOLS=ON`
fails at configure with "Could NOT find GnuTLS (missing: GNUTLS_LIBRARY
GNUTLS_INCLUDE_DIR)"; msgpack-c and GnuTLS dev headers are not installed
and cannot be (no package installs).  These fixtures are the fallback
prescribed by the build plan: an independent encoding of the documented
wire layout (src/network_engine.cpp:677-1305, include/opendht/value.h).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from mini_msgpack import (  # noqa: E402
    p_array, p_bin, p_bool, p_int, p_map, p_str, p_uint,
)

HERE = os.path.dirname(os.path.abspath(__file__))

# ---------------------------------------------------------------- fixed inputs
MYID = bytes(range(20))                   # engine's own id
TARGET = b"\xaa" * 20                     # find target
HASH = b"\xbb" * 20                       # get/listen/announce key
TID = 0x01020304                          # TransId (big-endian bin4)
TID_BIN = b"\x01\x02\x03\x04"
SID = 0x05060709                          # listen socket id
SID_BIN = b"\x05\x06\x07\x09"
TOKEN = bytes(range(0x10, 0x18))          # 8-byte write token
SA4 = b"\x0a\x00\x00\x09"                 # 10.0.0.9 (reply "sa" = addr only)
CREATED = 1_700_000_000
VID = 42
NET = 7
AF_INET, AF_INET6 = 2, 10
AGENT = "RNG1"                            # network_engine.cpp:55

# two IPv4 nodes + one IPv6 node as compact SEND_NODES triples
# (bufferNodes, network_engine.cpp:1003-1034: id ‖ in_addr ‖ be16 port)
N4_BLOB = (b"\xc1" * 20 + b"\x0a\x00\x00\x01" + (4000).to_bytes(2, "big")
           + b"\xc2" * 20 + b"\x0a\x00\x00\x02" + (4001).to_bytes(2, "big"))
N6_BLOB = (b"\xd1" * 20 + b"\x00" * 15 + b"\x01"
           + (4002).to_bytes(2, "big"))


def kv(k: str, v: bytes) -> bytes:
    return p_str(k) + v


def outer(pairs, network: int = 0) -> bytes:
    """Trailer shared by every message: t, y, v[, n] after the body keys
    (network_engine.cpp:677-1305)."""
    return p_map(len(pairs) + (1 if network else 0)) + b"".join(pairs) + (
        kv("n", p_int(network)) if network else b"")


def trailer(tid_bin: bytes, y: str) -> list:
    return [kv("t", p_bin(tid_bin)), kv("y", p_str(y)),
            kv("v", p_str(AGENT))]


def value_plain(vid: int, type_id: int, data: bytes,
                user_type: str = "") -> bytes:
    """Unsigned Value: {id, dat:{body:{type,data[,utype]}}}
    (value.h:470-511)."""
    body = (p_map(2 + (1 if user_type else 0))
            + kv("type", p_int(type_id)) + kv("data", p_bin(data))
            + (kv("utype", p_str(user_type)) if user_type else b""))
    dat = p_map(1) + kv("body", body)
    return p_map(2) + kv("id", p_uint(vid)) + kv("dat", dat)


V1 = value_plain(VID, 3, b"hello world")
V2 = value_plain(43, 0, b"second value", user_type="text/plain")


def make_goldens() -> dict:
    g = {}

    # ping request (network_engine.cpp:677-695)
    body = p_map(1) + kv("id", p_bin(MYID))
    g["ping_req"] = outer([kv("a", body), kv("q", p_str("ping"))]
                          + trailer(TID_BIN, "q"))
    # same, non-zero network id appended (cpp:692-694)
    g["ping_req_net7"] = outer([kv("a", body), kv("q", p_str("ping"))]
                               + trailer(TID_BIN, "q"), network=NET)

    # pong / listen confirmation (cpp:715-731, 1119-1133)
    rbody = p_map(2) + kv("id", p_bin(MYID)) + kv("sa", p_bin(SA4))
    g["pong"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # find_node request with want [v4, v6] (cpp:738-768)
    abody = (p_map(3) + kv("id", p_bin(MYID)) + kv("target", p_bin(TARGET))
             + kv("w", p_array(2) + p_int(AF_INET) + p_int(AF_INET6)))
    g["find_req"] = outer([kv("a", abody), kv("q", p_str("find"))]
                          + trailer(TID_BIN, "q"))

    # get_values request, no query/want (cpp:772-808)
    abody = p_map(2) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
    g["get_req"] = outer([kv("a", abody), kv("q", p_str("get"))]
                         + trailer(TID_BIN, "q"))

    # get_values with a field-selection query {s:[Id], w:[]}
    # (cpp:787-790; Query/Select value.h:744-812, Field::Id == 1)
    q = p_map(2) + kv("s", p_array(1) + p_int(1)) + kv("w", p_array(0))
    abody = (p_map(3) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
             + kv("q", q))
    g["get_req_select"] = outer([kv("a", abody), kv("q", p_str("get"))]
                                + trailer(TID_BIN, "q"))

    # listen request (cpp:1068-1100)
    abody = (p_map(4) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
             + kv("token", p_bin(TOKEN)) + kv("sid", p_bin(SID_BIN)))
    g["listen_req"] = outer([kv("a", abody), kv("q", p_str("listen"))]
                            + trailer(TID_BIN, "q"))

    # announce (put) request, one inline value + created (cpp:1141-1175;
    # packValueHeader cpp:889-911 inlines each serialized value into the
    # "values" array)
    abody = (p_map(5) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
             + kv("values", p_array(1) + V1)
             + kv("c", p_uint(CREATED)) + kv("token", p_bin(TOKEN)))
    g["announce_req"] = outer([kv("a", abody), kv("q", p_str("put"))]
                              + trailer(TID_BIN, "q"))

    # refresh request (cpp:1200-1230)
    abody = (p_map(4) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
             + kv("vid", p_uint(VID)) + kv("token", p_bin(TOKEN)))
    g["refresh_req"] = outer([kv("a", abody), kv("q", p_str("refresh"))]
                             + trailer(TID_BIN, "q"))

    # nodes+values response: n4, n6, token, two inline values
    # (cpp:944-1000)
    rbody = (p_map(6) + kv("id", p_bin(MYID)) + kv("sa", p_bin(SA4))
             + kv("n4", p_bin(N4_BLOB)) + kv("n6", p_bin(N6_BLOB))
             + kv("token", p_bin(TOKEN))
             + kv("values", p_array(2) + V1 + V2))
    g["nodes_values"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # value announced response (cpp:1252-1262: id, vid, sa)
    rbody = (p_map(3) + kv("id", p_bin(MYID)) + kv("vid", p_uint(VID))
             + kv("sa", p_bin(SA4)))
    g["value_announced"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # error response with id (cpp:1267-1297: e, r, t, y, v)
    e = p_array(2) + p_int(401) + p_str("Unauthorized")
    rbody = p_map(1) + kv("id", p_bin(MYID))
    g["error_unauthorized"] = outer(
        [kv("e", e), kv("r", rbody)] + trailer(TID_BIN, "e"))

    # value parts stream (sendValueParts cpp:913-941): per fragment
    # map3 {y:"v", t, p:{<value index>: {o: offset, d: bin chunk}}},
    # MTU=1280-byte chunks of the serialized value
    blob = value_plain(77, 3, bytes(range(256)) * 11)   # > 2 MTUs long
    parts = []
    mtu, start, i = 1280, 0, 0
    while start < len(blob):
        end = min(start + mtu, len(blob))
        frag = (p_map(1) + p_uint(i)
                + (p_map(2) + kv("o", p_uint(start))
                   + kv("d", p_bin(blob[start:end]))))
        parts.append(outer([kv("y", p_str("v")), kv("t", p_bin(TID_BIN)),
                            kv("p", frag)]))
        start = end
    g["value_parts"] = b"".join(parts)

    # ---- conversation-flow goldens (two-node scripted exchanges;
    # tests/test_wire_conversations.py).  The responder side uses the
    # peer id the engine fixtures use: sha1("peer") — InfoHash::get is
    # SHA1 of the data (infohash.h:231-236, src/crypto.cpp:86-88).
    import hashlib
    B_ID = hashlib.sha1(b"peer").digest()

    # announce of an oversized value: packValueHeader switches the
    # "values" array to integer SIZES and streams the blobs as parts
    # (cpp:889-911; the parts bytes are exactly g["value_parts"] above)
    abody = (p_map(5) + kv("id", p_bin(MYID)) + kv("h", p_bin(HASH))
             + kv("values", p_array(1) + p_uint(len(blob)))
             + kv("c", p_uint(CREATED)) + kv("token", p_bin(TOKEN)))
    g["announce_big_req"] = outer([kv("a", abody), kv("q", p_str("put"))]
                                  + trailer(TID_BIN, "q"))

    # responder's confirmation for that announce (cpp:1252-1262)
    rbody = (p_map(3) + kv("id", p_bin(B_ID)) + kv("vid", p_uint(77))
             + kv("sa", p_bin(SA4)))
    g["value_announced_77"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # responder-side pong / listen confirmation (id = B, sa = A's addr)
    rbody = p_map(2) + kv("id", p_bin(B_ID)) + kv("sa", p_bin(SA4))
    g["pong_b"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # get-reply carrying the oversized value as sizes + parts — the
    # reverse-direction fragmentation (cpp:944-1000 values branch →
    # sendValueParts)
    rbody = (p_map(4) + kv("id", p_bin(B_ID)) + kv("sa", p_bin(SA4))
             + kv("token", p_bin(TOKEN))
             + kv("values", p_array(1) + p_uint(len(blob))))
    g["nodes_values_sizes"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    # the six DhtProtocolException codes (network_engine.h:49-79) as
    # error packets.  203/401/404 are emitted organically by the request
    # handlers (src/dht.cpp:2146,2243,2282,2357); 421/422/423 have no
    # send site in the reference (421 is parse-time drop, 422/423 are
    # thrown on the receiving side) — their packets exist so the parser
    # provably accepts any peer that does send them.
    def err(code: int, text: str, who: bytes) -> bytes:
        e = p_array(2) + p_int(code) + p_str(text)
        rbody = p_map(1) + kv("id", p_bin(who))
        return outer([kv("e", e), kv("r", rbody)] + trailer(TID_BIN, "e"))

    g["error_203_get"] = err(203, "Get_values with no info_hash", B_ID)
    g["error_401_put"] = err(401, "Put with wrong token", B_ID)
    g["error_404_refresh"] = err(
        404, "Access operation for unknown storage", B_ID)
    g["error_421"] = err(421, "Invalid transaction id size", B_ID)
    g["error_422"] = err(422, "Can't find transaction", B_ID)
    g["error_423"] = err(423, "Wrong node info buffer length", B_ID)

    # listen push-channel u-packets (tellListenerRefreshed/Expired,
    # cpp:186-245): note 't' here is a plain msgpack UINT of the socket
    # id — the one departure from the bin4 TransId trailer
    def u_packet(key: str, vids: list) -> bytes:
        body = (p_map(3) + kv("id", p_bin(B_ID)) + kv("token", p_bin(TOKEN))
                + kv(key, p_array(len(vids))
                     + b"".join(p_uint(v) for v in vids)))
        return outer([kv("u", body), kv("t", p_uint(SID)),
                      kv("y", p_str("r")), kv("v", p_str(AGENT))])

    g["listen_refreshed_u"] = u_packet("re", [VID, 43])
    g["listen_expired_u"] = u_packet("exp", [VID, 43])

    # reply with a corrupt n4 blob (25 bytes — not a multiple of the
    # 26-byte compact node triple): receivers must throw
    # WRONG_NODE_INFO_BUF_LEN locally (deserializeNodes, cpp:845-851)
    # and drop, not crash
    rbody = (p_map(3) + kv("id", p_bin(B_ID)) + kv("sa", p_bin(SA4))
             + kv("n4", p_bin(b"\xee" * 25)))
    g["nodes_corrupt_n4"] = outer([kv("r", rbody)] + trailer(TID_BIN, "r"))

    return g


def main() -> None:
    for name, data in make_goldens().items():
        path = os.path.join(HERE, name + ".bin")
        with open(path, "wb") as f:
            f.write(data)
        print(f"{name}.bin: {len(data)} bytes")


if __name__ == "__main__":
    main()
