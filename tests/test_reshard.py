"""Load-aware resharding (ISSUE-17): the traffic-weighted boundary
solver vs a scalar per-row oracle (incl. degenerate histograms), the
weighted shard state's bit-identity with the single-device engine, the
Snapshot serving path's hot swap with in-flight waves pinned to the
layout their launch captured, the Resharder state machine (sustain
hysteresis, windowed frame counter-evidence, cooldown, reason-labeled
skips), and the fold-attribution plumbing (keyspace ``_shard_edges``
arities, ``Dht._keyspace_shard_info`` re-reading boundaries from the
CURRENT snapshot after a swap)."""

import socket as _socket

import numpy as np
import pytest

import jax.numpy as jnp

from opendht_tpu.core.table import Snapshot
from opendht_tpu.keyspace import (
    BINS, KeyspaceConfig, KeyspaceObservatory, bin_edges_from_ids,
    bin_edges_uniform, fold_bins, _imbalance,
)
from opendht_tpu.ops.sorted_table import sort_table
from opendht_tpu.parallel import partition
from opendht_tpu.parallel.partition import (
    shard_table_state, solve_shard_boundaries, solve_shard_edges,
)
from opendht_tpu.parallel.sharded import make_mesh, tp_simulate_lookups
from opendht_tpu.core.search import simulate_lookups
from opendht_tpu.reshard import ReshardConfig, ReshardLayout, Resharder


# ------------------------------------------------------------------ solver

def _oracle_rows(bin_rows, bin_loads, t, load_weight):
    """Scalar oracle: expand every bin into per-row weights (uniform
    within the bin — the same assumption the solver and fold_bins
    make), cumsum, and scan for the smallest row count whose weight
    reaches i/t of the total."""
    bin_rows = np.asarray(bin_rows, np.int64)
    w = partition._blend_bin_weights(bin_rows, bin_loads, load_weight)
    row_w = []
    for b, r in enumerate(bin_rows):
        if r > 0:
            row_w.extend([w[b] / float(r)] * int(r))
    cum = np.cumsum(np.asarray(row_w, np.float64))
    W = float(cum[-1]) if cum.size else 0.0
    n = int(bin_rows.sum())
    out = []
    for i in range(1, int(t)):
        if W <= 0.0:
            out.append(0)
            continue
        T = W * i / float(t)
        r = 0
        while r < n and cum[r] < T - 1e-9:
            r += 1
        out.append(r + 1 if r < n else n)
    return np.maximum.accumulate(np.asarray(out, np.int64))


def test_solver_matches_scalar_oracle_property():
    """Randomized property sweep: solver == per-row oracle within one
    row (the only slack is within-bin rounding), always nondecreasing,
    always inside [0, n].  Loads are masked to OCCUPIED bins — weight
    attributed to a row-less bin has no row to snap to, a case covered
    separately below."""
    rng = np.random.default_rng(29)
    for trial in range(60):
        bins = int(rng.integers(4, 24))
        bin_rows = rng.integers(0, 9, size=bins).astype(np.int64)
        loads = rng.integers(0, 101, size=bins).astype(np.int64)
        loads[bin_rows == 0] = 0
        t = int(rng.choice([2, 3, 4, 8]))
        lam = float(rng.choice([0.0, 0.3, 0.9, 1.0]))
        got = solve_shard_boundaries(bin_rows, loads, t, load_weight=lam)
        want = _oracle_rows(bin_rows, loads, t, lam)
        n = int(bin_rows.sum())
        assert got.shape == (t - 1,), trial
        assert np.all(np.diff(got) >= 0), (trial, got)
        assert got.min() >= 0 and got.max() <= n, (trial, got, n)
        assert np.all(np.abs(got - want) <= 1), \
            (trial, got, want, bin_rows, loads, t, lam)


def test_solver_cold_table_is_exact_uniform():
    """Zero observed load (or load_weight=0) degrades EXACTLY to the
    row-uniform split ceil(i*n/t) — the seed behavior, bit-for-bit."""
    bin_rows = np.full(256, 64, np.int64)          # n = 16384
    n = int(bin_rows.sum())
    for t in (2, 3, 4, 8):
        want = np.asarray([-(-n * i // t) for i in range(1, t)], np.int64)
        cold = solve_shard_boundaries(bin_rows, np.zeros(256, np.int64), t)
        assert np.array_equal(cold, want), t
        lam0 = solve_shard_boundaries(
            bin_rows, np.arange(256, dtype=np.int64), t, load_weight=0.0)
        assert np.array_equal(lam0, want), t
    # ragged n: ceil, not floor
    ragged = np.zeros(8, np.int64)
    ragged[:3] = [3, 3, 1]                          # n = 7
    got = solve_shard_boundaries(ragged, np.zeros(8, np.int64), 4)
    assert np.array_equal(got, [2, 4, 6])


def test_solver_single_hot_bin_quarters_it():
    """All load in one bin with λ=1: every interior boundary lands
    INSIDE that bin's row range, splitting its rows ~equally."""
    bin_rows = np.full(256, 64, np.int64)
    loads = np.zeros(256, np.int64)
    loads[10] = 5000
    got = solve_shard_boundaries(bin_rows, loads, 4, load_weight=1.0)
    lo, hi = 10 * 64, 11 * 64
    assert np.array_equal(got, [lo + 16, lo + 32, lo + 48])
    assert np.all((got > lo) & (got < hi))


def test_solver_degenerate_histograms():
    """Empty bins, load on a row-less bin, t > occupied bins, all load
    in one shard's bins — monotone, in-range, never raises."""
    # load attributed to a bin with zero rows: nothing to snap to —
    # invariants still hold
    bin_rows = np.zeros(16, np.int64)
    bin_rows[[0, 15]] = [8, 8]
    loads = np.zeros(16, np.int64)
    loads[7] = 1000                                 # empty bin carries load
    got = solve_shard_boundaries(bin_rows, loads, 4, load_weight=0.9)
    assert np.all(np.diff(got) >= 0) and got.min() >= 0 and got.max() <= 16
    # t greater than occupied bins: boundaries may repeat, stay ordered
    bin_rows = np.zeros(256, np.int64)
    bin_rows[[3, 200]] = [2, 2]
    got = solve_shard_boundaries(
        bin_rows, np.zeros(256, np.int64), 8, load_weight=1.0)
    assert got.shape == (7,) and np.all(np.diff(got) >= 0)
    assert got.max() <= 4
    # all load inside what uniform would call one shard: λ=1 pulls
    # every boundary into the hot range
    bin_rows = np.full(64, 16, np.int64)
    loads = np.zeros(64, np.int64)
    loads[:8] = 100                                 # hot octant
    got = solve_shard_boundaries(bin_rows, loads, 4, load_weight=1.0)
    assert got.max() <= 8 * 16
    # an entirely empty table: all boundaries 0
    got = solve_shard_boundaries(
        np.zeros(16, np.int64), np.zeros(16, np.int64), 4)
    assert np.array_equal(got, [0, 0, 0])


def test_solve_shard_edges_cold_and_hot():
    """The fractional-edge form: cold == bin_edges_uniform exactly
    (virtual attribution stays the seed split); a single hot bin at
    λ=1 yields edges quartering that bin; refolding the histogram at
    the solved edges balances the loads."""
    for t in (2, 4, 8):
        cold = solve_shard_edges(np.zeros(256, np.int64), t)
        assert np.allclose(cold, bin_edges_uniform(t)), t
    loads = np.zeros(256, np.int64)
    loads[10] = 4000
    edges = solve_shard_edges(loads, 4, load_weight=1.0)
    assert np.allclose(edges, [10.25, 10.5, 10.75])
    # closed loop: refold at solved edges -> near-perfect balance
    loads = np.zeros(256, np.int64)
    loads[:64] = 100
    edges = solve_shard_edges(loads, 4, load_weight=0.9)
    post = _imbalance(fold_bins(loads, list(edges)))
    assert post is not None and post < 1.3
    assert _imbalance(fold_bins(loads, bin_edges_uniform(4))) > 2.0


# ------------------------------------------------ weighted state identity

@pytest.mark.parametrize("t", [2, 4])
def test_weighted_shard_state_bit_identical(t):
    """The tentpole pin: a traffic-weighted shard_table_state (rows
    moved to unequal ownership, per-shard LUTs, equal-capacity slabs)
    drives tp_simulate_lookups to EXACTLY the single-device engine's
    results — every output limb, every hop."""
    rng = np.random.default_rng(17)
    ids = rng.integers(0, 2 ** 32, size=(2048, 5), dtype=np.uint32)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = rng.integers(0, 2 ** 32, size=(16, 5), dtype=np.uint32)
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=9)

    n = int(n_valid)
    top = np.asarray(sorted_ids[:, 0]).astype(np.int64)
    edges_v = np.arange(1, 256, dtype=np.int64) << 24
    counts = np.searchsorted(top[:n], edges_v, side="left")
    bin_rows = np.diff(np.concatenate([[0], counts, [n]]))
    loads = np.zeros(256, np.int64)
    loads[:32] = 1000                               # hot low ring
    bnd = solve_shard_boundaries(bin_rows, loads, t, load_weight=0.9)
    uniform = np.asarray([-(-n * i // t) for i in range(1, t)], np.int64)
    assert not np.array_equal(bnd, uniform)         # genuinely skewed

    mesh = make_mesh(t, q=1, t=t)
    state = shard_table_state(mesh, np.asarray(sorted_ids), n_valid,
                              boundaries=bnd)
    assert state.boundaries is not None
    assert "shard_rows" in state.arrays
    out = tp_simulate_lookups(mesh, targets=targets, seed=9, state=state)
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]), err_msg=key)


def _mk_snapshot(rng, n=1500):
    ids = rng.integers(0, 2 ** 32, size=(n, 5), dtype=np.uint32)
    sorted_ids, perm, n_valid = sort_table(jnp.asarray(ids))
    return Snapshot(sorted_ids, np.asarray(perm), n_valid, 1, ("k", 0))


def _hot_layout(gen, t, edges=(8.0,)):
    loads = np.zeros(256, np.int64)
    loads[:32] = 1000
    return ReshardLayout(gen=gen, t=t, edges=tuple(edges),
                         bin_loads=loads, load_weight=0.9)


def test_snapshot_layout_serving_identity_and_inflight_pinning():
    """The serving-path half of the tentpole: a Snapshot answers
    IDENTICALLY unsharded, uniform-sharded, and reshard-layout-sharded
    — and a hot swap between launch and consume leaves the in-flight
    wave pinned to the operands + perm map its launch captured."""
    rng = np.random.default_rng(23)
    snap = _mk_snapshot(rng)
    q = rng.integers(0, 2 ** 32, size=(8, 5), dtype=np.uint32)
    ref_rows, ref_dist = snap.lookup(q)             # single-device path
    mesh = make_mesh(2, q=1, t=2)
    lay = _hot_layout(1, 2)

    # the weighted boundary really moves ownership off the midpoint
    n = int(snap.n_valid)
    rows = np.asarray(snap.reshard_boundary_rows(lay, 2))
    assert rows.shape == (1,) and int(rows[0]) != -(-n // 2)

    # uniform sharded == unsharded
    u_rows, u_dist = snap.lookup(q, mesh=mesh)
    np.testing.assert_array_equal(u_rows, ref_rows)
    np.testing.assert_array_equal(u_dist, ref_dist)

    # in-flight pinning: launch against the uniform state, swap the
    # layout in (rebuilds _tp_state + perm map), launch again — BOTH
    # pending waves consume to the reference answer
    pl_old = snap.lookup_launch(q, mesh=mesh)
    pl_new = snap.lookup_launch(q, mesh=mesh, layout=lay)
    for pl in (pl_old, pl_new):
        got_rows, got_dist = pl.consume()
        np.testing.assert_array_equal(got_rows, ref_rows)
        np.testing.assert_array_equal(got_dist, ref_dist)

    # steady state on the new layout, then a SECOND swap (gen bump,
    # different histogram): still bit-identical
    w_rows, w_dist = snap.lookup(q, mesh=mesh, layout=lay)
    np.testing.assert_array_equal(w_rows, ref_rows)
    np.testing.assert_array_equal(w_dist, ref_dist)
    loads2 = np.zeros(256, np.int64)
    loads2[200:232] = 500
    lay2 = ReshardLayout(gen=2, t=2, edges=(216.0,),
                         bin_loads=loads2, load_weight=0.9)
    w2_rows, w2_dist = snap.lookup(q, mesh=mesh, layout=lay2)
    np.testing.assert_array_equal(w2_rows, ref_rows)
    np.testing.assert_array_equal(w2_dist, ref_dist)


# ------------------------------------------------------- resharder machine

class _KS:
    """Scripted observatory stand-in."""

    def __init__(self, virtual_shards=4):
        self.imb = None
        self.loads = np.zeros(256, np.int64)
        self.loads[:64] = 100

        class _Cfg:
            pass

        self.cfg = _Cfg()
        self.cfg.virtual_shards = virtual_shards

    def imbalance(self):
        return self.imb

    def hist_window(self):
        return self.loads.copy()


class _Frames:
    enabled = True

    def __init__(self, frames):
        self._frames = frames

    def frames(self, a, b):
        return self._frames


def _mk_resharder(ks=None, **cfg_kw):
    cfg = ReshardConfig(period=0.0, rebalance_threshold=2.0, sustain=4.0,
                        min_interval=10.0, recover_ratio=0.8, **cfg_kw)
    clk = [0.0]
    rs = Resharder(cfg, keyspace=ks if ks is not None else _KS(),
                   shard_t=lambda: 0, clock=lambda: clk[0])
    return rs, clk


def test_resharder_full_sequence_swap_and_cooldown():
    ks = _KS()
    rs, clk = _mk_resharder(ks)
    assert rs.tick()["reason"] == "below-threshold"  # imbalance unknown
    ks.imb = 3.0
    clk[0] = 1.0
    assert rs.tick()["reason"] == "hysteresis"       # latch just armed
    clk[0] = 3.0
    assert rs.tick()["reason"] == "hysteresis"       # 2s < sustain 4s
    clk[0] = 5.5
    res = rs.tick()                                  # 4.5s sustained
    assert res["action"] == "swap" and res["gen"] == 1
    assert res["mode"] == "virtual" and res["t"] == 4
    assert res["imbalance_after"] < 1.3              # refolded histogram
    lay = rs.layout
    assert lay is not None and lay.t == 4 and len(lay.edges) == 3
    assert np.all(np.diff(lay.edges) > 0)
    # post-swap the latch restarts: immediate re-trigger is hysteresis,
    # then the cooldown holds even once sustain is met again
    clk[0] = 6.0
    assert rs.tick()["reason"] == "hysteresis"
    clk[0] = 10.5
    assert rs.tick()["reason"] == "cooldown"
    clk[0] = 16.0
    assert rs.tick()["gen"] == 2
    snap = rs.snapshot()
    assert snap["swaps"] == 2 and snap["ticks"] == 7
    assert snap["skips"]["below-threshold"] == 1
    assert snap["skips"]["hysteresis"] == 3
    assert snap["skips"]["cooldown"] == 1
    assert snap["layout"]["gen"] == 2


def test_resharder_transient_burst_causes_zero_swaps():
    """The ISSUE-17 hysteresis acceptance: a burst shorter than the
    sustain window never swaps — the skip counter advances with
    reason=hysteresis — and a later SUSTAINED overload does."""
    ks = _KS()
    rs, clk = _mk_resharder(ks)
    ks.imb = 5.0
    for now in (0.0, 1.0, 2.0):                      # 2s burst < 4s sustain
        clk[0] = now
        assert rs.tick()["reason"] == "hysteresis"
    ks.imb = 1.0                                     # below thr*recover
    for now in (3.0, 4.0):
        clk[0] = now
        assert rs.tick()["reason"] == "below-threshold"
    snap = rs.snapshot()
    assert snap["swaps"] == 0 and rs.layout is None
    assert snap["skips"]["hysteresis"] == 3
    # the latch fully reset: a new overload must sustain from scratch
    ks.imb = 5.0
    clk[0] = 5.0
    assert rs.tick()["reason"] == "hysteresis"
    clk[0] = 8.9
    assert rs.tick()["reason"] == "hysteresis"       # 3.9s < 4s
    clk[0] = 9.5
    assert rs.tick()["action"] == "swap"


def test_resharder_recover_band_holds_latch():
    """Oscillation inside the hysteresis band (below threshold, above
    threshold·recover_ratio) keeps the sustain clock running — the
    dip skips as below-threshold but does not restart attribution."""
    ks = _KS()
    rs, clk = _mk_resharder(ks)
    ks.imb = 3.0
    clk[0] = 0.0
    rs.tick()                                        # latch arms at 0
    ks.imb = 1.9                                     # > 2.0*0.8 = 1.6
    clk[0] = 2.0
    assert rs.tick()["reason"] == "below-threshold"
    ks.imb = 3.0
    clk[0] = 4.5
    assert rs.tick()["action"] == "swap"             # clock never reset


def test_resharder_windowed_frame_counter_evidence():
    """Frame samples inside the sustain window that dip below the
    threshold (or go unknown, -1) refute the latch — windowed
    evidence, not instants."""
    ks = _KS()
    rs, clk = _mk_resharder(ks)
    ks.imb = 3.0
    rs.set_history(_Frames([{"gauges": {"dht_shard_imbalance": 1.2}}]))
    clk[0] = 0.0
    rs.tick()
    clk[0] = 4.5
    res = rs.tick()
    assert res["reason"] == "hysteresis" and res["window_min"] == 1.2
    # unknown (-1) inside the window is counter-evidence too
    rs.set_history(_Frames([{"gauges": {"dht_shard_imbalance": -1.0}}]))
    clk[0] = 5.0
    assert rs.tick()["reason"] == "hysteresis"
    # corroborating frames let the swap through
    rs.set_history(_Frames([{"gauges": {"dht_shard_imbalance": 2.7}}]))
    clk[0] = 5.5
    assert rs.tick()["action"] == "swap"
    # an empty scan (delta-encoded frames: gauge unchanged) is NO
    # counter-evidence — the latch alone decides
    rs2, clk2 = _mk_resharder(_KS())
    rs2.keyspace.imb = 3.0
    rs2.set_history(_Frames([]))
    clk2[0] = 0.0
    rs2.tick()
    clk2[0] = 4.5
    assert rs2.tick()["action"] == "swap"


def test_resharder_disabled_and_swap_error_keep_layout():
    rs, clk = _mk_resharder(enabled=False)
    assert rs.tick()["reason"] == "disabled"
    assert rs.snapshot()["skips"]["disabled"] == 1

    ks = _KS()
    boom = {"n": 0}

    def on_swap(layout):
        boom["n"] += 1
        if boom["n"] == 1:
            raise RuntimeError("rebuild failed")
        return {"mode": "physical"}

    cfg = ReshardConfig(period=0.0, sustain=0.0, min_interval=0.0)
    clk = [10.0]
    rs = Resharder(cfg, keyspace=ks, shard_t=lambda: 0,
                   on_swap=on_swap, clock=lambda: clk[0])
    ks.imb = 3.0
    res = rs.tick()
    assert res == {"action": "skip", "reason": "error"}
    assert rs.layout is None and rs.snapshot()["gen"] == 0
    clk[0] = 11.0
    res = rs.tick()                                  # next tick recovers
    assert res["action"] == "swap" and res["mode"] == "physical"
    assert rs.layout.gen == 1


# --------------------------------------------------- attribution plumbing

def test_keyspace_shard_edges_arities():
    """_shard_edges accepts the legacy (t, ids) form and the reshard
    (t, bounds, virtual) form; float bounds are pre-folded bin edges,
    uint bounds are boundary ids."""
    # float fractional edges + explicit virtual flag
    obs = KeyspaceObservatory(
        KeyspaceConfig(),
        shard_info=lambda: (4, [10.5, 10.25, 10.75], True))
    t, edges, virtual = obs._shard_edges()
    assert (t, virtual) == (4, True)
    assert edges == [10.25, 10.5, 10.75]             # sorted defensively
    # legacy 2-tuple with boundary ids: virtual defaults False
    ids = np.zeros((3, 5), np.uint32)
    ids[:, 0] = [1 << 30, 2 << 30, 3 << 30]
    obs = KeyspaceObservatory(KeyspaceConfig(), shard_info=lambda: (4, ids))
    t, edges, virtual = obs._shard_edges()
    assert (t, virtual) == (4, False)
    assert edges == bin_edges_from_ids(ids)
    # 3-tuple ids with virtual override (mesh fell back mid-rebuild)
    obs = KeyspaceObservatory(KeyspaceConfig(),
                              shard_info=lambda: (4, ids, True))
    assert obs._shard_edges() == (4, bin_edges_from_ids(ids), True)
    # (t, None) still folds over the uniform split, flagged virtual
    obs = KeyspaceObservatory(KeyspaceConfig(), shard_info=lambda: (4, None))
    assert obs._shard_edges() == (4, bin_edges_uniform(4), True)


def _mk_dht(t=0):
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    from opendht_tpu.scheduler import Scheduler
    cfg = Config(resolve_mesh_t=t) if t else Config()
    return Dht(lambda data, addr: 0, config=cfg,
               scheduler=Scheduler(), has_v6=False)


def test_dht_shard_info_virtual_layout():
    """An unsharded node with an installed layout attributes at the
    layout's fractional edges (virtual=True) — the closed loop the
    3-node smoke drives; without one, the seed (0, None)."""
    dht = _mk_dht()
    assert dht._keyspace_shard_info() == (0, None)
    dht.reshard._layout = _hot_layout(1, 4, (10.25, 10.5, 10.75))
    dht.reshard._gen = 1
    t, edges, virtual = dht._keyspace_shard_info()
    assert (t, virtual) == (4, True)
    assert edges == [10.25, 10.5, 10.75]


def test_dht_shard_info_rereads_boundaries_from_current_snapshot():
    """Satellite (a): with a live mesh + layout, the boundary ids come
    from the CURRENT snapshot's solved rows — a swap (or a snapshot
    rebuild) moves the fold attribution immediately, and a snapshot
    taken BEFORE the swap keeps the loads it folded at its own tick
    (dict copies; frames are immutable deltas)."""
    dht = _mk_dht(4)
    cap = 1024
    base = np.zeros((cap, 5), np.uint32)
    base[:, 0] = (np.arange(cap, dtype=np.uint64)
                  * (2 ** 32 // cap)).astype(np.uint32)
    snap_a = Snapshot(jnp.asarray(base), np.arange(cap, dtype=np.int32),
                      cap, 1, ("k", 0))
    table = dht.tables[_socket.AF_INET]
    table._snap = snap_a

    # uniform seed behavior first (2-tuple, boundary rows 256/512/768)
    t, ids = dht._keyspace_shard_info()
    assert t == 4 and np.array_equal(np.asarray(ids), base[[256, 512, 768]])

    # pre-swap observatory tick: skewed traffic folded at uniform edges
    obs = KeyspaceObservatory(
        KeyspaceConfig(tick=0, sample_stride=1, min_observed=1),
        shard_info=dht._keyspace_shard_info)
    hot = np.zeros((256, 5), np.uint32)
    hot[:, 0] = np.asarray(
        np.random.default_rng(31).integers(0, 2 ** 30, 256), np.uint32)
    obs.observe_ids(hot)
    obs.tick()
    pre = obs.snapshot()["shards"]
    assert pre["virtual"] is False and pre["imbalance"] > 2.0

    # install a layout: boundaries re-read from the snapshot, skewed
    dht.reshard._layout = _hot_layout(1, 4)
    dht.reshard._gen = 1
    t, ids, virtual = dht._keyspace_shard_info()
    assert (t, virtual) == (4, False)
    want_rows = np.clip(
        np.asarray(snap_a.reshard_boundary_rows(dht.reshard._layout, 4),
                   np.int64), 0, cap - 1)
    assert np.array_equal(np.asarray(ids), base[want_rows])
    assert not np.array_equal(want_rows, [256, 512, 768])

    # post-swap tick follows the new edges; the pre-swap snapshot dict
    # still carries the loads folded at ITS tick
    obs.observe_ids(hot)
    obs.tick()
    post = obs.snapshot()["shards"]
    assert post["imbalance"] < pre["imbalance"]
    assert pre["imbalance"] > 2.0                    # unchanged copy

    # a REBUILT snapshot (different id density) re-derives the rows
    base_b = np.zeros((cap, 5), np.uint32)
    base_b[:, 0] = (np.arange(cap, dtype=np.uint64) ** 2
                    % (2 ** 32)).astype(np.uint32)
    base_b = base_b[np.argsort(base_b[:, 0], kind="stable")]
    snap_b = Snapshot(jnp.asarray(base_b), np.arange(cap, dtype=np.int32),
                      cap, 2, ("k", 0))
    table._snap = snap_b
    t, ids_b, virtual = dht._keyspace_shard_info()
    assert not np.array_equal(np.asarray(ids_b), np.asarray(ids))
    want_b = np.clip(
        np.asarray(snap_b.reshard_boundary_rows(dht.reshard._layout, 4),
                   np.int64), 0, cap - 1)
    assert np.array_equal(np.asarray(ids_b), base_b[want_b])


def test_dht_wires_resharder_and_surfaces():
    """The Dht builds a Resharder off Config.reshard, arms the tick on
    the scheduler, and the snapshot surface carries the counters the
    proxy / REPL / scanner expose."""
    dht = _mk_dht()
    assert dht.reshard is not None
    assert dht.reshard.cfg.enabled is True
    snap = dht.reshard.snapshot()
    for key in ("enabled", "gen", "ticks", "swaps", "skips", "threshold",
                "sustain", "min_interval", "load_weight", "layout"):
        assert key in snap, key
    assert snap["gen"] == 0 and snap["layout"] is None
    # the periodic job is armed on the node scheduler
    assert dht.reshard._sched is dht.scheduler
    assert dht.reshard._job is not None
