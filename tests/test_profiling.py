"""Kernel cost ledger (ISSUE-6): cost-model determinism, budget
round-trip vs live lowering, kernel bit-identity with the ledger
enabled, export surfaces (registry gauges + Prometheus exposition +
wave-span attrs), and the perf gate's injected-regression failure."""

import importlib.util
import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp                                      # noqa: E402

from opendht_tpu import profiling, telemetry, tracing        # noqa: E402
from opendht_tpu.testing.telemetry_smoke import parse_exposition  # noqa: E402

pytestmark = pytest.mark.quick

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS = os.path.join(ROOT, "perf_budgets.json")

#: the cheap representative subset most tests lower (the budgets
#: round-trip test lowers everything, once, into the shared cache)
SUBSET = ["expanded_topk", "fused_gather_planar", "maintenance_sweep",
          "simulate_lookups"]


def _load_ci_module(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "ci", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def ledger():
    led = profiling.get_ledger()
    led.enabled = True
    led.compute(SUBSET)
    yield led
    led.enabled = True


# ------------------------------------------------------------ determinism
def test_cost_model_deterministic(ledger):
    """Two lowerings of the same kernel at the same canonical shape
    agree exactly — the property that makes the budgets committable."""
    a = ledger.compute(["expanded_topk"])["expanded_topk"]
    b = ledger.compute(["expanded_topk"], force=True)["expanded_topk"]
    for field in ("flops", "bytes_accessed", "argument_bytes",
                  "output_bytes", "temp_bytes"):
        assert a[field] == b[field], field
    assert a["shape"] == b["shape"]


def test_every_spec_lowers(ledger):
    """No registered kernel spec may rot: every entry lowers without an
    error record (the gate fails CI on the same condition)."""
    out = ledger.compute(SUBSET)
    assert all("error" not in e for e in out.values()), out


# --------------------------------------------------- budgets + perf gate
def test_budgets_roundtrip_against_live_lowering():
    """The committed perf_budgets.json must round-trip against a live
    lowering on this host — exactly what ci/perf_gate.py enforces in
    CI, invoked through its real entry point."""
    assert os.path.exists(BUDGETS), "perf_budgets.json not committed"
    perf_gate = _load_ci_module("perf_gate")
    assert perf_gate.main(["--budgets", BUDGETS]) == 0


def test_budgets_carry_open_accelerator_bounds():
    """The three OPEN on-chip bounds ride the budget file as open
    entries with their settling commands pre-wired (ROADMAP item 3)."""
    with open(BUDGETS) as f:
        budgets = json.load(f)
    ob = budgets["open_bounds"]
    for key in ("wave_p50_ms_1024", "churny_static_ratio",
                "maintenance_sweep_config4"):
        assert ob[key]["open"] is True
        assert "settle" in ob[key] and ob[key]["settle"]
    assert set(budgets["kernels"]) == set(profiling.KERNEL_SPECS)


def test_perf_gate_fails_on_injected_cost_regression(tmp_path, capsys):
    """Doubling one kernel's budgeted HBM traffic (equivalently: the
    live kernel halving under an unchanged budget — the direction a
    real regression moves the live side) must fail the gate with a
    diff naming the kernel and field."""
    with open(BUDGETS) as f:
        budgets = json.load(f)
    budgets["kernels"]["expanded_topk"]["bytes_accessed"] /= 2.0
    p = tmp_path / "perf_budgets.json"
    p.write_text(json.dumps(budgets))
    perf_gate = _load_ci_module("perf_gate")
    assert perf_gate.main(["--budgets", str(p)]) == 1
    err = capsys.readouterr().err
    assert "expanded_topk.bytes_accessed" in err


def test_perf_gate_fails_on_shape_drift(tmp_path):
    """A silently moved canonical shape must not re-base the budget —
    the gate demands a deliberate --update instead."""
    with open(BUDGETS) as f:
        budgets = json.load(f)
    budgets["kernels"]["maintenance_sweep"]["shape"]["N"] += 1
    p = tmp_path / "perf_budgets.json"
    p.write_text(json.dumps(budgets))
    perf_gate = _load_ci_module("perf_gate")
    assert perf_gate.main(["--budgets", str(p)]) == 1


def test_perf_gate_timing_ceilings_warn_not_fail(tmp_path, capsys):
    """Wall-clock smoke records breaching their soft ceiling WARN and
    the gate still passes — shared-runner timing informs, cost gates."""
    rec_dir = tmp_path / "records"
    rec_dir.mkdir()
    (rec_dir / "exp_round_r6.json").write_text(
        json.dumps({"fused_ms_per_round": 1e9}))
    perf_gate = _load_ci_module("perf_gate")
    assert perf_gate.main(["--budgets", BUDGETS,
                           "--records", str(rec_dir)]) == 0
    out = capsys.readouterr().out
    assert "perf_gate WARN" in out and "fused_ms_per_round" in out


# -------------------------------------------------- kernel bit-identity
def test_kernels_bit_identical_with_ledger_enabled(ledger):
    """The shipping kernels' outputs must be byte-for-byte unchanged by
    computing + exporting the ledger and running the record_wave hook
    with a traced wave — the ledger observes, never participates."""
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (sort_table, expand_table,
                                              expanded_topk)
    ids = jax.random.bits(jax.random.PRNGKey(42), (2048, 5),
                          dtype=jnp.uint32)
    targets = jax.random.bits(jax.random.PRNGKey(43), (64, 5),
                              dtype=jnp.uint32)
    sorted_ids, _p, n_valid = sort_table(ids)
    expanded = expand_table(sorted_ids)

    ledger.enabled = False
    base_topk = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, targets, k=8))
    base_wave = jax.block_until_ready(
        simulate_lookups(sorted_ids, n_valid, targets, alpha=3, k=8))

    ledger.enabled = True
    ledger.compute(SUBSET)
    ledger.export_to_registry()
    tr = tracing.get_tracer()
    with tracing.activate(tracing.TraceContext.new_root()):
        led_wave = jax.block_until_ready(
            simulate_lookups(sorted_ids, n_valid, targets, alpha=3, k=8))
    led_topk = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, targets, k=8))

    for a, b in zip(jax.tree_util.tree_leaves(base_topk),
                    jax.tree_util.tree_leaves(led_topk)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for key in ("nodes", "dist", "hops", "converged"):
        assert np.array_equal(np.asarray(base_wave[key]),
                              np.asarray(led_wave[key])), key
    # and the traced wave actually carried the device-cost attrs
    waves = [s for s in tr.spans() if s["name"] == "dht.search.wave"]
    assert waves and "est_device_bytes" in waves[-1]["attrs"]


# ------------------------------------------------------- export surfaces
def test_export_gauges_and_exposition(ledger):
    reg = telemetry.MetricsRegistry()
    n = ledger.export_to_registry(reg)
    assert n >= len(SUBSET)
    snap = reg.snapshot()
    key = 'dht_kernel_bytes_accessed{kernel="expanded_topk"}'
    entry = ledger.compute(["expanded_topk"])["expanded_topk"]
    assert snap["gauges"][key] == entry["bytes_accessed"]
    series = parse_exposition(reg.prometheus())
    assert series[key] == entry["bytes_accessed"]
    assert 'dht_kernel_flops{kernel="maintenance_sweep"}' in series


def test_maybe_export_is_gated(monkeypatch):
    """A process that never computed the ledger (and didn't arm
    OPENDHT_TPU_LEDGER) must pay nothing on a metrics scrape."""
    monkeypatch.delenv("OPENDHT_TPU_LEDGER", raising=False)
    led = profiling.get_ledger()
    led.enabled = False            # simulate the never-computed state
    try:
        reg = telemetry.MetricsRegistry()
        assert profiling.maybe_export(reg) == 0
        assert not reg.snapshot()["gauges"]
    finally:
        led.enabled = True


def test_measure_and_roofline(ledger):
    out = ledger.measure(["fused_gather_planar"], reps=1)
    e = out["fused_gather_planar"]
    assert e["measured_s"] > 0
    rl = e["roofline"]
    assert rl["bound"] in ("memory", "compute")
    assert rl["hbm_pct_of_peak"] >= 0
    # the roofline identity: pct == 100 * bytes / (t * peak)
    peaks = profiling.platform_peaks()
    expect = 100.0 * e["bytes_accessed"] / e["measured_s"] \
        / peaks["hbm_bytes_per_s"]
    assert rl["hbm_pct_of_peak"] == pytest.approx(expect, rel=1e-3)


def test_wave_attrs_scaling_and_gating(ledger):
    entry = ledger.compute(["simulate_lookups"])["simulate_lookups"]
    w_c = entry["shape"]["W"]
    attrs = profiling.wave_attrs(2 * w_c, 3, 0.5)
    assert attrs["est_device_bytes"] == int(entry["bytes_accessed"] * 6)
    assert attrs["est_device_flops"] == int(entry["flops"] * 6)
    assert "est_hbm_pct_of_peak" in attrs
    ledger.enabled = False
    try:
        assert profiling.wave_attrs(2 * w_c, 3, 0.5) == {}
    finally:
        ledger.enabled = True
    # zero-round waves (empty table fast exit) attach nothing
    assert profiling.wave_attrs(w_c, 0, 0.5) == {}


def test_snapshot_folds_live_series(ledger):
    """The paired PR-3 histogram's p50 rides the snapshot next to the
    canonical cost, linking cost model to shipping latency."""
    reg = telemetry.get_registry()
    reg.histogram("dht_maintenance_sweep_seconds").observe(0.004)
    snap = ledger.snapshot()
    e = snap["maintenance_sweep"]
    assert e["series"] == "dht_maintenance_sweep_seconds"
    assert e["live_count"] >= 1 and e["live_p50_s"] > 0


# ------------------------------------------------------------ trajectory
def test_trajectory_committed_and_in_sync():
    """PERF_TRAJECTORY.json must exist and equal a fresh assembly of
    its sources (BENCH_r*/captures/TP_SCALING) — the same both-ways
    check ci/check_docs.py runs."""
    asm = _load_ci_module("assemble_trajectory")
    assert asm.main(["--check"]) == 0
    fresh = asm.build()
    claimed = [r for r in fresh["rounds"] if "superseded" not in r]
    assert len(claimed) >= 4
    assert all(r["vs_baseline"] for r in fresh["rounds"])
