"""core/op_cache.py coverage (ISSUE-11 satellite).

The listen-operation dedup caches (reference src/op_cache.{h,cpp}) were
an untested thin host port.  Pins: OpValueCache's cross-subscription
ref-counting and the cache_callback collapse wrapper, OpCache's
replay-on-attach / one-shot unsubscribe / 60 s listener-less linger
(inclusive expiry boundary — the virtual-clock live-lock fix), and
SearchCache's query-keyed op sharing, cancellation bookkeeping and
expiry sweep."""

from __future__ import annotations

import pytest

from opendht_tpu.core.op_cache import (OP_LINGER, OpCache, OpValueCache,
                                       SearchCache)
from opendht_tpu.core.value import Query, Select, Value, Where
from opendht_tpu.utils import TIME_MAX


def v(vid: int) -> Value:
    return Value(b"d%d" % vid, value_id=vid)


def sink():
    events = []

    def cb(vals, expired):
        events.append((sorted(x.id for x in vals), expired))
        return True
    return events, cb


# ------------------------------------------------------------ OpValueCache
def test_refcount_expires_only_when_all_sources_expire():
    events, cb = sink()
    ovc = OpValueCache(cb)
    # two network ops announce the same value: one add event, ref 2
    assert ovc.on_values_added([v(1)])
    assert ovc.on_values_added([v(1)])
    assert events == [([1], False)]
    # first expiry only decrements; the second releases it
    assert ovc.on_values_expired([v(1)])
    assert events == [([1], False)]
    assert ovc.get_by_id(1) is not None
    assert ovc.on_values_expired([v(1)])
    assert events == [([1], False), ([1], True)]
    assert ovc.get_by_id(1) is None and ovc.get_values() == []


def test_expire_of_unknown_value_is_noop():
    events, cb = sink()
    ovc = OpValueCache(cb)
    assert ovc.on_values_expired([v(9)])
    assert events == []


def test_false_return_unsubscribes_none_stays():
    returns = iter([None, False])
    ovc = OpValueCache(lambda vals, exp: next(returns))
    # None keeps the subscription (LocalListener.notify contract)...
    assert ovc.on_values_added([v(1)]) is True
    # ...only an explicit False unsubscribes
    assert ovc.on_values_added([v(2)]) is False


def test_cache_callback_collapses_duplicate_adds():
    events, cb = sink()
    wrapped = OpValueCache.cache_callback(cb)
    wrapped([v(1)], False)
    wrapped([v(1)], False)          # duplicate add: ref-counted, no event
    wrapped([v(1)], True)           # first expire: ref drops to 1
    assert events == [([1], False)]
    wrapped([v(1)], True)           # second expire releases
    assert events == [([1], False), ([1], True)]


# ----------------------------------------------------------------- OpCache
def test_add_listener_replays_cache_state():
    op = OpCache(now=0.0)
    op.on_value([v(1), v(2)], False)
    events, cb = sink()
    op.add_listener(7, cb, Query(), None, now=1.0)
    assert events == [([1, 2], False)]       # replay on attach
    op.on_value([v(3)], False)
    assert events[-1] == ([3], False)
    assert op.get_expiration() == TIME_MAX   # has listeners: never expires


def test_one_shot_listener_satisfied_from_cache_detaches():
    op = OpCache(now=0.0)
    op.on_value([v(1)], False)
    # a listener returning False is satisfied by the replay and must
    # not stay registered (op_cache.h:87-90)
    op.add_listener(7, lambda vals, exp: False, Query(), None, now=2.0)
    assert op.is_done()
    # linger clock anchored at the removal
    assert op.get_expiration() == 2.0 + OP_LINGER


def test_empty_cache_replay_fires_nothing_and_keeps_listener():
    events, cb = sink()
    op = OpCache(now=0.0)
    op.add_listener(7, cb, Query(), None, now=0.0)
    assert events == [] and not op.is_done()


def test_linger_window_and_inclusive_expiry_boundary():
    events, cb = sink()
    op = OpCache(now=0.0)
    op.add_listener(1, cb, Query(), None, now=0.0)
    assert not op.is_expired(1e9)            # listeners pin it alive
    assert op.remove_listener(1, now=100.0)
    assert not op.remove_listener(1, now=100.0)   # already gone
    assert op.is_done()
    assert not op.is_expired(100.0 + OP_LINGER - 0.001)
    # INCLUSIVE boundary: exp == now IS expired (strict '<' live-locked
    # a virtual clock that only advances between events)
    assert op.is_expired(100.0 + OP_LINGER)


def test_dispatch_unsubscribes_returning_false_mid_feed():
    op = OpCache(now=0.0, clock=lambda: 42.0)
    seen = []
    op.add_listener(1, lambda vals, exp: (seen.append(1), False)[-1],
                    Query(), None, now=0.0)
    op.on_value([v(1)], False)               # listener consumed + left
    assert seen == [1] and op.is_done()
    assert op.get_expiration() == 42.0 + OP_LINGER   # dispatch clock


# ------------------------------------------------------------- SearchCache
def test_listen_shares_one_network_op_per_query():
    sc = SearchCache()
    started = []

    def on_listen(q, vcb):
        started.append(q)
        return 100 + len(started)

    e1, cb1 = sink()
    e2, cb2 = sink()
    t1 = sc.listen(cb1, Query(), None, on_listen, now=0.0)
    t2 = sc.listen(cb2, Query(), None, on_listen, now=0.0)
    assert len(started) == 1                 # identical query: shared op
    assert t1 != t2 and len(sc) == 1
    assert sc.cancel_listen(t1, now=1.0)
    assert not sc.cancel_listen(t1, now=1.0)     # idempotent
    assert sc.cancel_listen(t2, now=2.0)
    # both listeners gone: the shared op lingers from the LAST removal
    assert sc.get_expiration() == 2.0 + OP_LINGER


def test_listen_routes_to_op_whose_query_satisfies():
    sc = SearchCache()
    started = []

    def on_listen(q, vcb):
        started.append(q)
        return len(started)

    wide = Query()                           # selects everything
    narrow = Query(Select(), Where().id(7))
    sc.listen(lambda *_: True, wide, None, on_listen, now=0.0)
    # the narrow query is satisfied by the wide op: no second network op
    sc.listen(lambda *_: True, narrow, None, on_listen, now=0.0)
    assert len(started) == 1
    # the REVERSE does not hold: a wide listen after a narrow one needs
    # its own op
    sc2 = SearchCache()
    started.clear()
    sc2.listen(lambda *_: True, narrow, None, on_listen, now=0.0)
    sc2.listen(lambda *_: True, wide, None, on_listen, now=0.0)
    assert len(started) == 2


def test_expire_drops_lingered_ops_and_cancels_tokens():
    sc = SearchCache()
    sc_tokens = []

    def on_listen(q, vcb):
        return 42

    t = sc.listen(lambda *_: True, Query(), None, on_listen, now=0.0)
    sc.cancel_listen(t, now=0.0)
    # before the linger elapses nothing expires
    nxt = sc.expire(OP_LINGER - 1.0, sc_tokens.append)
    assert sc_tokens == [] and len(sc) == 1 and nxt == OP_LINGER
    # at the boundary (inclusive) the op drops and its token cancels
    nxt = sc.expire(OP_LINGER, sc_tokens.append)
    assert sc_tokens == [42] and len(sc) == 0 and nxt == TIME_MAX


def test_cancel_all_tears_down_every_op():
    sc = SearchCache()
    cancelled = []
    # two DISJOINT narrow queries: neither satisfies the other, so each
    # starts its own network op
    sc.listen(lambda *_: True, Query(Select(), Where().id(3)), None,
              lambda q, cb: 1, now=0.0)
    sc.listen(lambda *_: True, Query(Select(), Where().id(4)), None,
              lambda q, cb: 2, now=0.0)
    assert len(sc) == 2
    sc.cancel_all(cancelled.append)
    assert sorted(cancelled) == [1, 2] and len(sc) == 0


def test_get_deduplicates_across_ops():
    sc = SearchCache()
    feeds = {}

    def on_listen(q, vcb):
        feeds[len(feeds) + 1] = vcb
        return len(feeds)

    sc.listen(lambda *_: True, Query(Select(), Where().id(1)), None,
              on_listen, now=0.0)
    sc.listen(lambda *_: True, Query(Select(), Where().id(2)), None,
              on_listen, now=0.0)
    feeds[1]([v(1), v(5)], False)
    feeds[2]([v(2), v(5)], False)            # value 5 seen by both ops
    got = sorted(x.id for x in sc.get())
    assert got == [1, 2, 5]
    assert sc.get_by_id(5) is not None
    assert sc.get_by_id(99) is None


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
