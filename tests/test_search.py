"""Batched iterative lookup engine tests: convergence, exactness of the
found set, determinism, and hop-count parity with the scalar reference
port (model of the reference's searchStep loop, src/dht.cpp:561-654)."""

import numpy as np
import pytest

import jax.numpy as jnp

from opendht_tpu.ops import ids as K
from opendht_tpu.ops.sorted_table import sort_table
from opendht_tpu.ops.xor_topk import xor_topk
from opendht_tpu.core.search import simulate_lookups, scalar_lookup


def _network(n, seed):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, (n, 20), dtype=np.uint8)
    ids = jnp.asarray(K.ids_from_bytes(raw))
    sorted_ids, _, n_valid = sort_table(ids)
    return sorted_ids, n_valid


def test_lookups_converge_and_find_closest():
    sorted_ids, n = _network(4000, 0)
    rng = np.random.default_rng(1)
    q_raw = rng.integers(0, 256, (64, 20), dtype=np.uint8)
    targets = jnp.asarray(K.ids_from_bytes(q_raw))
    out = simulate_lookups(sorted_ids, n, targets, seed=7)
    conv = np.asarray(out["converged"])
    hops = np.asarray(out["hops"])
    nodes = np.asarray(out["nodes"])
    assert conv.all()
    assert (hops >= 1).all() and (hops <= 30).all()

    # the found set must match the true global top-8 closely
    true_dist, true_idx = xor_topk(targets, sorted_ids, k=8)
    true_idx = np.asarray(true_idx)
    recall = np.mean([
        len(set(nodes[i]) & set(true_idx[i])) / 8 for i in range(64)
    ])
    assert recall >= 0.95, recall


def test_lookup_deterministic():
    sorted_ids, n = _network(1000, 2)
    rng = np.random.default_rng(3)
    targets = jnp.asarray(K.ids_from_bytes(
        rng.integers(0, 256, (16, 20), dtype=np.uint8)))
    a = simulate_lookups(sorted_ids, n, targets, seed=42)
    b = simulate_lookups(sorted_ids, n, targets, seed=42)
    np.testing.assert_array_equal(np.asarray(a["nodes"]), np.asarray(b["nodes"]))
    np.testing.assert_array_equal(np.asarray(a["hops"]), np.asarray(b["hops"]))
    c = simulate_lookups(sorted_ids, n, targets, seed=43)
    assert not np.array_equal(np.asarray(a["hops"]), np.asarray(c["hops"]))


def test_tiny_network():
    sorted_ids, n = _network(5, 4)
    rng = np.random.default_rng(5)
    targets = jnp.asarray(K.ids_from_bytes(
        rng.integers(0, 256, (8, 20), dtype=np.uint8)))
    out = simulate_lookups(sorted_ids, n, targets, seed=1)
    nodes = np.asarray(out["nodes"])
    # every real node should be found; padding is -1
    for row in nodes:
        assert set(row[row >= 0]) == {0, 1, 2, 3, 4}


def test_hop_parity_with_scalar_reference():
    sorted_ids, n = _network(5000, 6)
    ids_np = np.asarray(sorted_ids)
    n_int = int(n)
    rng = np.random.default_rng(7)
    q_raw = rng.integers(0, 256, (48, 20), dtype=np.uint8)
    targets = jnp.asarray(K.ids_from_bytes(q_raw))

    out = simulate_lookups(sorted_ids, n, targets, seed=8)
    hops_batched = np.asarray(out["hops"])

    hops_scalar = []
    for i in range(48):
        _, h, conv = scalar_lookup(ids_np, n_int, np.asarray(targets[i]),
                                   rng=np.random.default_rng(100 + i))
        assert conv
        hops_scalar.append(h)
    hops_scalar = np.array(hops_scalar)

    # same convergence law → medians within 2 rounds of each other
    assert abs(np.median(hops_batched) - np.median(hops_scalar)) <= 2, (
        np.median(hops_batched), np.median(hops_scalar))


@pytest.mark.slow
def test_scaling_hops_grow_logarithmically():
    m1 = []
    for nsize, seed in ((500, 8), (8000, 9)):
        sorted_ids, n = _network(nsize, seed)
        rng = np.random.default_rng(seed)
        targets = jnp.asarray(K.ids_from_bytes(
            rng.integers(0, 256, (32, 20), dtype=np.uint8)))
        out = simulate_lookups(sorted_ids, n, targets, seed=seed)
        assert np.asarray(out["converged"]).all()
        m1.append(np.median(np.asarray(out["hops"])))
    # bigger network needs ≥ as many hops, but only logarithmically more
    assert m1[1] >= m1[0]
    assert m1[1] - m1[0] <= 6


@pytest.mark.slow
def test_state_limbs_2_bitwise_identical():
    """state_limbs=2 (5-operand merge sorts ranking on the top 64
    distance bits) must be bitwise identical to the exact engine on
    random ids — distinct 160-bit ids tie on 64 bits with probability
    ~2^-58 per merge, so any divergence here is a bug, not a tie."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import simulate_lookups

    k1, k2 = jax.random.split(jax.random.PRNGKey(17))
    table = jax.random.bits(k1, (4096, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (128, 5), dtype=jnp.uint32)
    sorted_ids, _, n = sort_table(table)
    a = simulate_lookups(sorted_ids, n, targets, seed=9)
    b = simulate_lookups(sorted_ids, n, targets, seed=9, state_limbs=2)
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(a[key]), np.asarray(b[key]))


def test_guarded_lower_bound_exact_incl_tie64_tables():
    """_guarded_lower_bound's three tiers (64-bit search + one-compare
    correction / full-limb LUT search / full-depth search) must all be
    EXACT vs the reference full-width binary search — on random ids, on
    tables with adjacent top-64 duplicates (the tie64 guard's reason to
    exist), and on heavily clustered ids (LUT-bucket overflow)."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              _lower_bound)
    from opendht_tpu.core.search import _guarded_lower_bound

    rng = np.random.default_rng(64)

    def check(ids_np, probes_np, label):
        sorted_ids, _, n = sort_table(jnp.asarray(ids_np))
        lut = build_prefix_lut(sorted_ids, n)
        lower = _guarded_lower_bound(sorted_ids, n, lut)
        got = np.asarray(lower(jnp.asarray(probes_np)))
        want = np.asarray(_lower_bound(sorted_ids, jnp.asarray(probes_np),
                                       n))
        np.testing.assert_array_equal(got, want, err_msg=label)

    base = rng.integers(0, 2**32, size=(2048, 5), dtype=np.uint32)
    # probes: random + exact row hits + rows +/- 1 in the last limb
    probes = rng.integers(0, 2**32, size=(256, 5), dtype=np.uint32)
    probes[:64] = base[rng.integers(0, 2048, 64)]
    probes[64:96] = base[rng.integers(0, 2048, 32)]
    probes[64:96, 4] += 1
    probes[96:128] = base[rng.integers(0, 2048, 32)]
    probes[96:128, 4] -= 1
    check(base, probes, "random")

    dup = base.copy()
    dup[100:140, :2] = dup[100, :2]       # 40 rows share top 64 bits
    check(dup, probes, "tie64")
    dup2 = base.copy()
    dup2[:300] = dup2[0]                  # full duplicate ids
    check(dup2, probes, "full-dup")

    clus = base.copy()
    clus[:1800, 0] = 0x7777AAAA           # LUT bucket overflow
    p2 = probes.copy()
    p2[:128, 0] = 0x7777AAAA
    check(clus, p2, "clustered")


@pytest.mark.slow
def test_survivor_compaction_bitwise_identical():
    """compact_after packs post-cut stragglers into a narrow sub-batch;
    whenever the cap holds, results must be BITWISE identical to the
    plain engine (reply streams key on global query id + round).  Also
    exercises the cap-overflow safety net (tiny cap → full-width finish
    still converges everything)."""
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import simulate_lookups

    k1, k2 = jax.random.split(jax.random.PRNGKey(23))
    table = jax.random.bits(k1, (8192, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (256, 5), dtype=jnp.uint32)
    sorted_ids, _, n = sort_table(table)
    ref = simulate_lookups(sorted_ids, n, targets, seed=11, state_limbs=2)
    out = simulate_lookups(sorted_ids, n, targets, seed=11, state_limbs=2,
                           compact_after=4, compact_cap=256)  # cap == Q
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))
    # generous-but-partial cap: by round 4 fewer than half survive
    out2 = simulate_lookups(sorted_ids, n, targets, seed=11, state_limbs=2,
                            compact_after=4, compact_cap=192)
    if bool((np.asarray(ref["hops"]) <= 4).sum() >= 64):
        for key in ("nodes", "hops", "converged"):
            np.testing.assert_array_equal(np.asarray(out2[key]),
                                          np.asarray(ref[key]))
    # overflow: cap 8 cannot hold the survivors — the full-width safety
    # net resumes them AT THE CUT ROUND, replaying exactly the streams
    # the plain engine would have given them, so even overflow is
    # bitwise identical (and nobody's round budget is starved)
    out3 = simulate_lookups(sorted_ids, n, targets, seed=11, state_limbs=2,
                            compact_after=2, compact_cap=8)
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(out3[key]),
                                      np.asarray(ref[key]))


def test_engine_reply_stream_goldens():
    """The deterministic reply streams are pinned by committed goldens
    (tests/goldens/search_engine.json): the round-6 ROUND-FUSED engine
    (one fused [W·α·k] reply gather per round; block edges positioned
    from the carried candidate distance limb instead of a per-round
    peer gather) must reproduce the round-5 engine's outputs bit for
    bit — as must any future refactor, since wave streaming, survivor
    compaction, and tp-sharding all lean on stream determinism keyed
    by (seed, global query id, round)."""
    import hashlib
    import json
    import os

    with open(os.path.join(os.path.dirname(__file__), "goldens",
                           "search_engine.json")) as f:
        gold = json.load(f)
    rng = np.random.default_rng(1234)
    ids = rng.integers(0, 2**32, size=(4096, 5), dtype=np.uint32)
    targets = jnp.asarray(rng.integers(0, 2**32, size=(96, 5),
                                       dtype=np.uint32))
    sorted_ids, _, n = sort_table(jnp.asarray(ids))
    for tag, kw in (("lut_l5", {}), ("lut_l2", {"state_limbs": 2}),
                    ("exact_l5", {"block_mode": "exact"})):
        out = simulate_lookups(sorted_ids, n, targets, seed=99, **kw)
        h = hashlib.sha256()
        for key in ("nodes", "hops", "converged", "dist"):
            h.update(np.ascontiguousarray(np.asarray(out[key])).tobytes())
        assert h.hexdigest() == gold[tag]["sha256"], (
            tag, np.bincount(np.asarray(out["hops"]), minlength=12)[:12],
            gold[tag]["hops_hist"])
        np.testing.assert_array_equal(np.asarray(out["nodes"])[0],
                                      gold[tag]["nodes_row0"], err_msg=tag)
        assert int(np.asarray(out["converged"]).sum()) \
            == gold[tag]["converged"], tag


def test_lut_block_bounds_exact_up_to_lut_width():
    """_lut_block_bounds must equal the exact prefix-block edges for any
    prefix length <= the LUT width — on clustered tables too (the
    exactness claim is structural, not probabilistic: lut[p] counts
    rows below prefix p) — and clamp to the containing bucket beyond
    the width."""
    import numpy as np
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table, build_prefix_lut
    from opendht_tpu.core.search import _lut_block_bounds

    rng = np.random.default_rng(55)
    for cluster in (False, True):
        raw = rng.integers(0, 2**32, size=(4096, 5), dtype=np.uint32)
        if cluster:
            raw[:3000, 0] = raw[0, 0]          # one giant top-32 cluster
        s, _p, nv = sort_table(jnp.asarray(raw))
        bits = 16
        lut = build_prefix_lut(s, nv, bits=bits)
        s_np = np.asarray(s)
        top = s_np[:, 0]
        t0 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
        t0[:8] = s_np[:: 512, 0][:8]           # hit real prefixes too
        for L in (0, 1, 7, bits - 1, bits, bits + 3, 40, 160):
            Lc = min(L, bits)
            lo, ub = _lut_block_bounds(
                lut, jnp.asarray(t0), jnp.full((64,), L, jnp.int32))
            lo, ub = np.asarray(lo), np.asarray(ub)
            # oracle: count rows whose top-Lc bits match the target's
            shift = np.uint32(32 - Lc) if Lc else None
            for i in range(64):
                if Lc == 0:
                    want_lo, want_ub = 0, int(nv)
                else:
                    pfx = t0[i] >> shift
                    rows = top >> shift
                    want_lo = int(np.searchsorted(rows, pfx, side="left"))
                    want_ub = int(np.searchsorted(rows, pfx, side="right"))
                    want_ub = min(want_ub, int(nv))
                    want_lo = min(want_lo, int(nv))
                assert lo[i] == want_lo and ub[i] == want_ub, \
                    (cluster, L, i, lo[i], ub[i], want_lo, want_ub)
