"""Pallas lexicographic top-k selection kernel: exact parity with the
7-key lax.sort oracle (interpret mode on the CPU test tier; the same
kernel compiles on TPU — validated in bench runs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from opendht_tpu.ops.ids import xor_ids
from opendht_tpu.ops.pallas_select import lex_topk_select
from opendht_tpu.ops.sorted_table import sort_table, window_topk
from opendht_tpu.ops.xor_topk import xor_topk


@pytest.mark.parametrize("k", [1, 8, 14])
@pytest.mark.parametrize("w", [128, 256])
def test_matches_full_scan_oracle(k, w):
    rng = np.random.default_rng(k * 1000 + w)
    q = rng.integers(0, 2**32, size=(33, 5), dtype=np.uint32)
    t = rng.integers(0, 2**32, size=(w, 5), dtype=np.uint32)
    dist = xor_ids(jnp.asarray(q)[:, None, :], jnp.asarray(t)[None, :, :])
    idx = lex_topk_select(dist, jnp.zeros((33, w), jnp.int32), k=k,
                          interpret=True)
    _, i_ref = xor_topk(jnp.asarray(q), jnp.asarray(t), k=k)
    assert np.array_equal(np.asarray(idx), np.asarray(i_ref))


def test_invalid_rows_and_exhaustion():
    rng = np.random.default_rng(3)
    q = rng.integers(0, 2**32, size=(16, 5), dtype=np.uint32)
    t = rng.integers(0, 2**32, size=(128, 5), dtype=np.uint32)
    dist = xor_ids(jnp.asarray(q)[:, None, :], jnp.asarray(t)[None, :, :])
    inv = np.zeros((16, 128), np.int32)
    inv[:, 5:] = 1                        # only 5 valid rows, k=8
    idx = np.asarray(lex_topk_select(dist, jnp.asarray(inv), k=8,
                                     interpret=True))
    assert (idx[:, 5:] == -1).all()
    assert (idx[:, :5] >= 0).all() and (idx[:, :5] < 5).all()


def test_duplicate_ids_tie_break_by_position():
    rng = np.random.default_rng(4)
    q = rng.integers(0, 2**32, size=(8, 5), dtype=np.uint32)
    t = np.repeat(rng.integers(0, 2**32, size=(1, 5), dtype=np.uint32),
                  128, axis=0)
    dist = xor_ids(jnp.asarray(q)[:, None, :], jnp.asarray(t)[None, :, :])
    idx = np.asarray(lex_topk_select(dist, jnp.zeros((8, 128), jnp.int32),
                                     k=8, interpret=True))
    assert (idx == np.arange(8)).all()


def test_window_topk_pallas_vs_sort_paths():
    """The two selection engines inside window_topk are bit-identical."""
    rng = np.random.default_rng(5)
    t = rng.integers(0, 2**32, size=(1024, 5), dtype=np.uint32)
    q = rng.integers(0, 2**32, size=(64, 5), dtype=np.uint32)
    sorted_ids, perm, n_valid = sort_table(jnp.asarray(t))
    d1, i1, c1 = window_topk(sorted_ids, n_valid, jnp.asarray(q),
                             k=8, window=128, select="sort")
    d2, i2, c2 = window_topk(sorted_ids, n_valid, jnp.asarray(q),
                             k=8, window=128, select="pallas")
    assert np.array_equal(np.asarray(i1), np.asarray(i2))
    assert np.array_equal(np.asarray(d1), np.asarray(d2))
    assert np.array_equal(np.asarray(c1), np.asarray(c2))
