"""tools/compat_check.py must pass all scripted wire exchanges against
this package's own live node (round-4 verdict ask #8: the stage-4
interop acceptance, runnable today against ourselves and against a
reference C++ dhtnode the day one is reachable; ISSUE-4 added the
trace-context / unknown-top-level-key interop pair)."""

import pytest

from opendht_tpu.runtime.runner import DhtRunner
from opendht_tpu.tools.compat_check import N_CHECKS, run_checks

pytestmark = pytest.mark.quick


def test_compat_check_against_own_node():
    runner = DhtRunner()
    runner.run(0)
    try:
        results = run_checks("127.0.0.1", runner.get_bound_port(),
                             verbose=False)
    finally:
        runner.shutdown()
        runner.join()
    failed = [(n, d) for n, ok, d in results if not ok]
    assert len(results) == N_CHECKS and not failed, failed
