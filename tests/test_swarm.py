"""Device-resident swarm stepper tests (ISSUE-13 tentpole): the jitted
:func:`opendht_tpu.ops.swarm.swarm_step` pinned BIT-IDENTICAL to the
scalar-flavored numpy oracle across a full multi-phase FaultPlan,
determinism under a fixed seed, the admission-bounded poison plane,
closest-R parity with the shipping XOR top-k kernel, and the
storm → partition → heal invariant arc (lookup-success and
replica-coverage restored after healing)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opendht_tpu import chaos
from opendht_tpu.health import DEGRADED, HEALTHY, UNHEALTHY
from opendht_tpu.ops import swarm

pytestmark = pytest.mark.quick


def full_plan(seed=3):
    """Every phase kind: storm, recovery, asymmetric partition, poison."""
    return chaos.FaultPlan([
        chaos.Phase("storm", start=1.0, duration=3.0,
                    storm=chaos.Storm(leave_rate=0.2, join_rate=0.1)),
        chaos.Phase("lossy", start=1.0, duration=6.0,
                    rules=[chaos.LinkRule(name="wan", loss=0.2)]),
        chaos.Phase("split", start=5.0, duration=4.0,
                    partition=chaos.Partition(block=[("g0", "g1")])),
        chaos.Phase("poison", start=9.0, duration=3.0,
                    poison=chaos.Poison(victim="g1", per_bucket=8)),
        chaos.Phase("recover", start=12.0, duration=3.0,
                    storm=chaos.Storm(join_rate=0.5)),
    ], seed=seed)


# ------------------------------------------------------------ oracle pins
def test_step_bit_identical_to_host_oracle():
    """Device stepper == numpy oracle on every state array, metric and
    probe, through 16 ticks spanning every phase kind."""
    kw = dict(n_nodes=48, n_keys=8, n_groups=2, seed=5, sweep_sample=8)
    dev = swarm.SwarmSim(full_plan(), device=True, **kw)
    host = swarm.SwarmSim(full_plan(), device=False, **kw)
    for t in range(16):
        md, mh = dev.tick(), host.tick()
        assert md == {k: int(v) for k, v in mh.items()}, (t, md, mh)
        for k in swarm.STATE_KEYS:
            a, b = np.asarray(dev.state[k]), np.asarray(host.state[k])
            assert np.array_equal(a, b), (t, k)
        assert dev.probe() == host.probe(), t


def test_deterministic_under_seed():
    kw = dict(n_nodes=64, n_keys=8, n_groups=2, sweep_sample=8)
    a = swarm.SwarmSim(full_plan(), seed=11, **kw)
    b = swarm.SwarmSim(full_plan(), seed=11, **kw)
    c = swarm.SwarmSim(full_plan(), seed=12, **kw)
    ma, mb, mc = a.run(10), b.run(10), c.run(10)
    assert ma == mb
    assert ma != mc
    for k in swarm.STATE_KEYS:
        assert np.array_equal(np.asarray(a.state[k]),
                              np.asarray(b.state[k])), k


def test_occupancy_limbs_roundtrip():
    rng = np.random.default_rng(0)
    counts = rng.integers(0, 9, size=(17, swarm.ID_BITS)).astype(np.int32)
    packed = swarm._pack_occ(np, counts)
    assert packed.shape == (17, swarm.OCC_LIMBS)
    assert np.array_equal(swarm._unpack_occ(np, packed), counts)
    # device path agrees
    jpacked = swarm._pack_occ(jnp, jnp.asarray(counts))
    assert np.array_equal(np.asarray(jpacked), packed)
    assert np.array_equal(
        np.asarray(swarm._unpack_occ(jnp, jpacked)), counts)


def test_closest_r_matches_shipping_xor_topk_distances():
    """The stepper's 5-limb lexicographic closest-R selection returns
    the SAME distance set as the shipping ops/xor_topk kernel (index
    ties may order differently; the XOR distances must agree)."""
    from opendht_tpu.ops.xor_topk import xor_topk

    key = jax.random.PRNGKey(2)
    ids = jax.random.bits(key, (256, 5), jnp.uint32)
    queries = jax.random.bits(jax.random.PRNGKey(3), (7, 5), jnp.uint32)
    valid = np.ones((256,), bool)
    valid[::5] = False
    sel, sel_valid = swarm._closest_r(
        np, np.asarray(queries), np.asarray(ids), valid, 8)
    assert sel_valid.all()
    _d, idx = xor_topk(queries, ids, k=8, valid=jnp.asarray(valid))
    ours = np.asarray(queries)[:, None, :] ^ np.asarray(ids)[sel]
    theirs = np.asarray(queries)[:, None, :] ^ np.asarray(ids)[
        np.asarray(idx)]
    assert np.array_equal(np.sort(ours.view(np.uint32), axis=1),
                          np.sort(theirs.view(np.uint32), axis=1))


# --------------------------------------------------------- fault dynamics
def test_poison_admission_bounded_and_decays():
    """Attacker entries are admitted into at most the FREE slots of a
    victim bucket (full-bucket rejection) and evicted by the first
    successful maintenance pass after the poison phase ends."""
    plan = chaos.FaultPlan([
        chaos.Phase("poison", start=0.0, duration=4.0,
                    poison=chaos.Poison(victim="g1", per_bucket=8)),
    ])
    sim = swarm.SwarmSim(plan, n_nodes=64, n_keys=8, n_groups=2,
                         seed=9, sweep_sample=8)
    sim.tick()
    occ = swarm._unpack_occ(np, np.asarray(sim.state["occ"]))
    poi = swarm._unpack_occ(np, np.asarray(sim.state["poison"]))
    group = np.asarray(sim.state["group"])
    assert poi[group == 1].sum() > 0, "poison never admitted"
    # the admission invariant: honest + attacker never exceeds k
    assert int((occ + poi).max()) <= swarm.K_BUCKET
    # non-victims untouched
    assert poi[group == 0].sum() == 0
    # shallow buckets are FULL of honest nodes -> zero attacker entries
    # land there (the eclipse-resistance property)
    full = occ == swarm.K_BUCKET
    assert not (poi[full] > 0).any()
    sim.run(8)          # phase over; maintenance evicts the sybils
    poi = swarm._unpack_occ(np, np.asarray(sim.state["poison"]))
    assert poi.sum() == 0, "attacker occupancy survived the heal"


def test_storm_partition_heal_invariants_restore():
    """The acceptance arc: a join/leave storm plus an asymmetric
    partition-and-heal, with lookup-success and replica-coverage
    restored after healing."""
    plan = chaos.FaultPlan([
        chaos.Phase("storm", start=1.0, duration=3.0,
                    storm=chaos.Storm(leave_rate=0.10, join_rate=0.10)),
        chaos.Phase("refill", start=4.0, duration=3.0,
                    storm=chaos.Storm(join_rate=0.5)),
        chaos.Phase("split", start=8.0, duration=6.0,
                    partition=chaos.Partition(block=[("g0", "g1")],
                                              symmetric=True)),
    ], seed=3)
    sim = swarm.SwarmSim(plan, n_nodes=1024, n_keys=48, n_groups=2,
                         seed=5, sweep_sample=32, repub_every=2)
    hist = sim.run(22)
    assert hist[0]["verdict"] == HEALTHY
    during = hist[9:13]
    assert any(m["verdict"] in (DEGRADED, UNHEALTHY) for m in during), \
        [m["verdict"] for m in during]
    assert min(m["replica_coverage"] for m in during) < 0.75
    healed = hist[-1]
    assert healed["verdict"] == HEALTHY, healed
    assert healed["lookup_success"] >= 0.95
    assert healed["replica_coverage"] >= 0.95
    # storms actually churned the population
    assert sum(m["n_leave"] for m in hist) > 0
    assert sum(m["n_join"] for m in hist) > 0


def test_swarm_verdict_and_phase_flight_events():
    """Swarm verdicts ride the PR-9 flight-recorder ring: phase
    transitions and verdict flips are recorded as events."""
    from opendht_tpu import tracing
    tr = tracing.get_tracer()
    plan = chaos.FaultPlan([
        chaos.Phase("split", start=2.0, duration=4.0,
                    partition=chaos.Partition(block=[("g0", "g1")],
                                              symmetric=True)),
    ])
    sim = swarm.SwarmSim(plan, n_nodes=256, n_keys=16, n_groups=2,
                         seed=4, sweep_sample=16, repub_every=2)
    sim.run(10)
    phases = tr.events(name="chaos_phase")
    verdicts = tr.events(name="swarm_verdict")
    assert any("split" in e["attrs"].get("active", "")
               for e in phases), phases
    assert any(e["attrs"].get("to") in (DEGRADED, UNHEALTHY)
               for e in verdicts), verdicts
    from opendht_tpu import telemetry
    reg = telemetry.get_registry()
    snap = reg.snapshot()["gauges"]
    assert "dht_swarm_lookup_success" in snap
    assert "dht_swarm_replica_coverage" in snap


def test_params_at_derivation():
    plan = full_plan()
    group = np.array([0, 0, 1, 1], np.int32)
    p0 = swarm.params_at(plan, 0.0, 2, group)
    assert p0["reach"].all() and not p0["poison_on"]
    assert float(p0["loss"]) == 0.0
    p_split = swarm.params_at(plan, 6.0, 2, group)
    assert not p_split["reach"][0, 1] and p_split["reach"][1, 0], \
        "asymmetric partition must block one direction only"
    assert float(p_split["loss"]) > 0.0      # the lossy phase overlaps
    p_poison = swarm.params_at(plan, 9.5, 2, group)
    assert p_poison["poison_on"]
    assert np.array_equal(p_poison["poison_mask"], group == 1)
    p_end = swarm.params_at(plan, 20.0, 2, group)
    assert p_end["reach"].all() and not p_end["poison_on"]


def test_occupancy_gauge_rides_registry_and_history_frames():
    """ISSUE-15 satellite: the stepper's per-tick total replica-slot
    occupancy publishes as the dht_swarm_occupancy gauge (it was
    computed but dropped before), so the round-17 history ring — which
    samples every registry family — carries the storage-pressure
    series into soak frames and black-box bundles."""
    from opendht_tpu import telemetry
    from opendht_tpu.history import HistoryConfig, MetricsHistory

    reg = telemetry.get_registry()
    # earlier tests run sims on this shared registry; prime the gauges
    # to a sentinel so the sim's sets register as CHANGES in the
    # last-value-when-changed frame encoding
    reg.gauge("dht_swarm_occupancy").set(-12345.0)
    reg.gauge("dht_swarm_replica_coverage").set(-12345.0)
    clock = [0.0]
    rec = MetricsHistory(HistoryConfig(period=1.0, capacity=8),
                         registry=reg, clock=lambda: clock[0])
    rec.tick()                               # baseline
    plan = chaos.FaultPlan([])
    sim = swarm.SwarmSim(plan, n_nodes=128, n_keys=8, seed=6,
                         sweep_sample=16)
    m = sim.tick()
    assert m["occ_sum"] > 0
    snap = reg.snapshot()["gauges"]
    assert snap.get("dht_swarm_occupancy") == m["occ_sum"]
    clock[0] = 1.0
    f = rec.tick()
    assert f["gauges"]["dht_swarm_occupancy"] == m["occ_sum"]
    # coverage rides the same frame once the verdict tick computes it
    sim.run(2)
    clock[0] = 2.0
    f2 = rec.tick()
    assert "dht_swarm_replica_coverage" in f2["gauges"]
