"""Unit tests for the L0 host primitives: Scheduler, RateLimiter, SockAddr,
utils.  Mirrors the reference's implicit contracts (scheduler.h,
rate_limiter.h, sockaddr.h)."""

import math

from opendht_tpu.rate_limiter import RateLimiter
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.utils import TIME_MAX, pack_msg, unpack_msg
import pytest

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------- scheduler
def test_scheduler_runs_due_jobs_in_order():
    clk = FakeClock()
    s = Scheduler(clock=clk)
    order = []
    s.add(2.0, lambda: order.append("b"))
    s.add(1.0, lambda: order.append("a"))
    s.add(5.0, lambda: order.append("later"))
    clk.t = 3.0
    nxt = s.run()
    assert order == ["a", "b"]
    assert nxt == 5.0


def test_scheduler_cancel_and_edit():
    clk = FakeClock()
    s = Scheduler(clock=clk)
    hits = []
    j1 = s.add(1.0, lambda: hits.append(1))
    j2 = s.add(1.0, lambda: hits.append(2))
    j1.cancel()
    j2 = s.edit(j2, 10.0)
    clk.t = 2.0
    assert s.run() == 10.0
    assert hits == []
    clk.t = 10.0
    s.run()
    assert hits == [2]


def test_scheduler_self_reschedule_no_starvation():
    # a job that reschedules itself for "now" must not loop forever in run()
    clk = FakeClock()
    s = Scheduler(clock=clk)
    count = []

    def tick():
        count.append(1)
        s.add(s.time(), tick)

    s.add(0.0, tick)
    clk.t = 0.0
    s.run()
    assert len(count) == 1  # the re-added job waits for the next run


def test_scheduler_raising_job_does_not_lose_others():
    clk = FakeClock()
    s = Scheduler(clock=clk)
    hits = []

    def boom():
        raise RuntimeError("job failed")

    s.add(1.0, boom)
    s.add(1.0, lambda: hits.append("survivor"))
    clk.t = 2.0
    try:
        s.run()
    except RuntimeError:
        pass
    # the not-yet-run due job went back on the heap, not into the void
    s.run()
    assert hits == ["survivor"]


def test_scheduler_time_max_parks_job():
    s = Scheduler(clock=FakeClock())
    s.add(TIME_MAX, lambda: None)
    assert s.next_job_time() == TIME_MAX


# -------------------------------------------------------------- rate limiter
def test_rate_limiter_quota_and_window():
    rl = RateLimiter(quota=3, period=1.0)
    assert all(rl.limit(0.0) for _ in range(3))
    assert not rl.limit(0.5)      # quota spent inside window
    assert rl.limit(1.5)          # old records aged out
    assert rl.maintain(10.0) == 0
    assert rl.empty()


# ------------------------------------------------------------------ sockaddr
def test_sockaddr_basics():
    a = SockAddr("127.0.0.1", 4222)
    assert a.family == __import__("socket").AF_INET
    assert a.port == 4222 and a.is_loopback() and not a.is_global()
    b = SockAddr("::1", 4222)
    assert b.family == __import__("socket").AF_INET6 and b.is_loopback()
    assert SockAddr().family == __import__("socket").AF_UNSPEC
    assert not SockAddr()


def test_sockaddr_compact_roundtrip():
    for host, port, ln in [("192.168.1.7", 8080, 6), ("2001:db8::42", 443, 18)]:
        a = SockAddr(host, port)
        c = a.to_compact()
        assert len(c) == ln
        assert SockAddr.from_compact(c) == a


def test_sockaddr_ip_cmp_ignores_port():
    a = SockAddr("10.0.0.1", 1)
    b = SockAddr("10.0.0.1", 2)
    c = SockAddr("10.0.0.2", 1)
    assert a.ip_cmp(b) == 0 and a != b
    assert a.ip_cmp(c) < 0 and c.ip_cmp(a) > 0
    assert a.is_private()


def test_sockaddr_ordering_v4_before_v6():
    assert SockAddr("255.255.255.255", 1) < SockAddr("::", 1)


# --------------------------------------------------------------------- utils
def test_msgpack_helpers_roundtrip():
    obj = {"a": 1, "b": b"\x00\xff", "s": "héllo", "l": [1, 2, 3]}
    assert unpack_msg(pack_msg(obj)) == obj
    assert math.isinf(TIME_MAX)
