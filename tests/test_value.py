"""Value & query model tests — wire-layer round-trips, filters, the
SQL-ish query parser, satisfiability, and default type policies
(reference contracts: include/opendht/value.h, src/value.cpp,
default_types.cpp)."""

import msgpack
import pytest

from opendht_tpu.core.value import (
    Field, FieldValue, FieldValueIndex, Filters, Query, RawPublicKey, Select,
    TypeStore, Value, ValueType, Where, random_value_id, MAX_VALUE_SIZE,
)
from opendht_tpu.core.default_types import (
    DEFAULT_TYPES, DhtMessage, IceCandidates, ImMessage, IpServiceAnnouncement,
    TrustRequest, DHT_MESSAGE_TYPE, IP_SERVICE_ANNOUNCEMENT_TYPE,
)
from opendht_tpu.infohash import InfoHash
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


# --------------------------------------------------------------- wire layers
def test_plain_value_wire_roundtrip():
    v = Value(b"hello", type_id=3, value_id=0xDEADBEEF, user_type="x/y")
    v2 = Value.from_packed(v.get_packed())
    assert v2 == v
    assert v2.data == b"hello" and v2.type == 3 and v2.user_type == "x/y"
    assert not v2.is_signed() and not v2.is_encrypted()


def test_plain_value_wire_layout():
    """The outer map must be exactly {id, dat:{body:{type,data}}} — key
    set and nesting match the reference (value.h:470-511)."""
    v = Value(b"d", type_id=1, value_id=7)
    obj = msgpack.unpackb(v.get_packed(), raw=False)
    assert set(obj) == {"id", "dat"}
    assert obj["id"] == 7
    assert set(obj["dat"]) == {"body"}
    assert obj["dat"]["body"] == {"type": 1, "data": b"d"}


def test_signed_value_wire_roundtrip():
    v = Value(b"payload", type_id=3, value_id=42)
    v.owner = RawPublicKey(b"\x30\x82fake-der")
    v.seq = 5
    v.signature = b"sig-bytes"
    v.recipient = InfoHash.get("bob")
    obj = msgpack.unpackb(v.get_packed(), raw=False)
    assert set(obj["dat"]) == {"body", "sig"}
    assert obj["dat"]["body"]["seq"] == 5
    assert obj["dat"]["body"]["owner"] == b"\x30\x82fake-der"
    assert obj["dat"]["body"]["to"] == bytes(InfoHash.get("bob"))

    v2 = Value.from_packed(v.get_packed())
    assert v2.is_signed() and v2.seq == 5
    assert v2.owner.export_der() == b"\x30\x82fake-der"
    assert v2.recipient == InfoHash.get("bob")
    assert v2 == v


def test_encrypted_value_wire_roundtrip():
    v = Value(value_id=9)
    v.cypher = b"\x01\x02\x03ciphertext"
    obj = msgpack.unpackb(v.get_packed(), raw=False)
    assert obj["dat"] == v.cypher     # bin passthrough, no map
    v2 = Value.from_packed(v.get_packed())
    assert v2.is_encrypted() and v2.cypher == v.cypher and v2 == v


def test_malformed_value_raises():
    with pytest.raises(ValueError):
        Value.from_wire_obj({"id": 1})          # no dat
    with pytest.raises(ValueError):
        Value.from_wire_obj({"id": 1, "dat": {"body": {"type": 0}}})  # no data
    # signed body without sig
    with pytest.raises(ValueError):
        Value.from_wire_obj(
            {"id": 1, "dat": {"body": {"type": 0, "data": b"", "owner": b"k",
                                       "seq": 0}}})


def test_random_value_id_nonzero():
    assert all(random_value_id() != 0 for _ in range(64))


# ------------------------------------------------------------------- filters
def test_filter_chaining():
    va = Value(b"a", type_id=1, value_id=1)
    vb = Value(b"b", type_id=2, value_id=2)
    f = Filters.chain(Filters.value_type(1), Filters.id(1))
    assert f(va) and not f(vb)
    f_or = Filters.chain_or(Filters.id(1), Filters.id(2))
    assert f_or(va) and f_or(vb)
    assert Filters.apply(None, [va, vb]) == [va, vb]
    assert Filters.apply(Filters.value_type(2), [va, vb]) == [vb]
    assert Filters.chain(None, None) is None


# ------------------------------------------------------------ query language
def test_select_parse_and_wire():
    s = Select("SELECT id, seq")
    assert s.get_selection() == [Field.ID, Field.SEQ_NUM]
    s2 = Select.from_wire_obj(s.wire_obj())
    assert s2 == s
    assert Select("select user_type").get_selection() == [Field.USER_TYPE]
    assert Select("").empty()


def test_where_parse_filter_and_wire():
    w = Where("WHERE id=7, user_type=chat")
    vals = [Value(b"x", value_id=7, user_type="chat"),
            Value(b"y", value_id=7, user_type="mail"),
            Value(b"z", value_id=8, user_type="chat")]
    f = w.get_filter()
    assert [f(v) for v in vals] == [True, False, False]
    w2 = Where.from_wire_obj(w.wire_obj())
    assert w2 == w
    # quoted strings and owner hashes
    h = InfoHash.get("owner")
    w3 = Where(f'WHERE owner_pk={h}, user_type="a b"')
    assert FieldValue(Field.OWNER_PK, h) in w3.filters


def test_where_parse_error():
    with pytest.raises(ValueError):
        Where("WHERE nonsense=1")
    with pytest.raises(ValueError):
        Where("WHERE id=abc")          # non-numeric for a numeric field
    assert Where('WHERE seq="5"').filters[0].value == 5


def test_pack_fields_projection():
    v = Value(b"d", type_id=2, value_id=9, user_type="u")
    v.seq = 3
    row = v.pack_fields([Field.ID, Field.VALUE_TYPE, Field.OWNER_PK,
                         Field.SEQ_NUM, Field.USER_TYPE])
    assert row == [9, 2, bytes(20), 3, "u"]


def test_query_string_form_and_satisfiability():
    q = Query("SELECT id WHERE user_type=chat")
    assert q.select.get_selection() == [Field.ID]
    assert len(q.where.filters) == 1

    # satisfiability (src/value.cpp:505-519): a query asking for a subset
    # of restrictions/fields is satisfied by the broader cached query
    broad = Query(Select(), Where())              # everything, all fields
    narrow = Query("SELECT id WHERE id=4")
    assert narrow.where.is_satisfied_by(broad.where)
    assert Query(none=True).is_satisfied_by(narrow)
    # broad needs all fields; narrow's projection can't satisfy it
    assert not broad.select.is_satisfied_by(narrow.select)
    # same query satisfies itself
    assert narrow.is_satisfied_by(Query("SELECT id WHERE id=4"))


def test_query_wire_roundtrip():
    q = Query("SELECT id,seq WHERE value_type=3")
    q2 = Query.from_wire_obj(msgpack.unpackb(
        msgpack.packb(q.wire_obj(), use_bin_type=True), raw=False))
    assert q2 == q


def test_field_value_index_projection():
    v = Value(b"data", type_id=3, value_id=11, user_type="t")
    v.owner = RawPublicKey(b"derkey")
    v.seq = 2
    fvi = FieldValueIndex(v, Select("SELECT id, seq"))
    assert set(fvi.index) == {Field.ID, Field.SEQ_NUM}
    assert fvi.index[Field.ID].value == 11
    packed = fvi.pack_fields()
    back = FieldValueIndex.unpack_fields([Field.ID, Field.SEQ_NUM], packed)
    assert back.index[Field.SEQ_NUM].value == 2
    assert back.contained_in(fvi)

    full = FieldValueIndex(v, Select())
    assert len(full.index) == 5
    assert full.index[Field.OWNER_PK].value == v.owner.get_id()


# --------------------------------------------------------------------- types
def test_type_store_fallback():
    ts = TypeStore()
    for t in DEFAULT_TYPES:
        ts.register_type(t)
    assert ts.get_type(3).name == "IM message"
    assert ts.get_type(999) is ValueType.USER_DATA


def test_default_store_policy_size_cap():
    big = Value(b"x" * (MAX_VALUE_SIZE + 1))
    ok = Value(b"x")
    assert not ValueType.default_store_policy(InfoHash(), big, InfoHash(), None)
    assert ValueType.default_store_policy(InfoHash(), ok, InfoHash(), None)


def test_dht_message_policy_and_filter():
    key, frm = InfoHash.get("k"), InfoHash.get("f")
    good = DhtMessage("svc", b"m").to_value()
    empty = DhtMessage("", b"m").to_value()
    assert DhtMessage.store_policy(key, good, frm, None)
    assert not DhtMessage.store_policy(key, empty, frm, None)
    f = DhtMessage.service_filter("svc")
    assert f(good)
    assert not f(DhtMessage("other", b"m").to_value())


def test_ip_service_announcement_rewrites_to_sender():
    """Anti-spoof: the stored address must be the sender's IP with the
    announced port (default_types.cpp:68-82)."""
    ann = IpServiceAnnouncement(SockAddr("1.2.3.4", 5000)).to_value()
    sender = SockAddr("9.9.9.9", 1234)
    assert IpServiceAnnouncement.store_policy(InfoHash(), ann, InfoHash(), sender)
    stored = IpServiceAnnouncement.unpack(ann.data)
    assert stored.addr == SockAddr("9.9.9.9", 5000)
    # port 0 rejected
    zero = IpServiceAnnouncement(SockAddr("1.2.3.4", 0)).to_value()
    assert not IpServiceAnnouncement.store_policy(InfoHash(), zero, InfoHash(), sender)


def test_payload_roundtrips():
    im = ImMessage(1, "hi", 123, "text/plain")
    assert ImMessage.unpack(im.pack()).msg == "hi"
    tr = TrustRequest("svc", b"p", True)
    back = TrustRequest.unpack(tr.pack())
    assert back.service == "svc" and back.confirm
    ic = IceCandidates(7, b"ice")
    assert IceCandidates.unpack(ic.pack()).ice_data == b"ice"

    v = im.to_value()
    v.owner = RawPublicKey(b"k")
    v.recipient = InfoHash.get("to")
    m = ImMessage.from_value(v)
    assert m.from_id == RawPublicKey(b"k").get_id() and m.to == InfoHash.get("to")
