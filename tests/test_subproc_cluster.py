"""Out-of-process cluster tier (testing/subproc_cluster.py).

The reference drives whole clusters in separate processes remote-
controlled over stdin (DhtNetworkSubProcess, reference
python/tools/dht/network.py:42-281); these tests pin the TPU build's
analog: real UDP nodes in child processes, msgpack-stdin RPC, put/get
across the process boundary, and a churn scenario where an ENTIRE
child-process cluster is SIGKILLed and values survive on the peers
that remain.  Unlike the in-process thread clusters
(tests/test_cluster_tools.py), nothing here shares a GIL with the
nodes under test.
"""

import time

import pytest

from opendht_tpu.infohash import InfoHash
from opendht_tpu.core.value import Value
from opendht_tpu.runtime.runner import DhtRunner
from opendht_tpu.testing.subproc_cluster import ClusterSubProcess


pytestmark = pytest.mark.slow


def test_rpc_roundtrip_and_put_get_across_process():
    """Parent-side put via RPC, read back both via RPC and from a
    parent-process node bootstrapped into the child cluster."""
    with ClusterSubProcess(4, timeout=120.0) as c:
        assert len(c.ports) == 4 and len(set(c.ids)) == 4
        key = bytes(InfoHash.get("subproc-key"))
        assert c.put(key, b"hello-from-parent")
        assert b"hello-from-parent" in c.get(key)

        # cross the boundary with a live parent-process node too
        r = DhtRunner()
        r.run(port=0)
        r.bootstrap("127.0.0.1", c.ports[0])
        time.sleep(1.0)
        try:
            vals = r.get_sync(InfoHash(key), timeout=30.0) or []
            assert any(bytes(v.data) == b"hello-from-parent" for v in vals)
        finally:
            r.join()


def test_values_survive_killing_whole_child_cluster():
    """Two child-process clusters, interconnected; a value is announced
    across both; SIGKILLing cluster A (no goodbyes, every node gone at
    once) must leave the value retrievable from cluster B."""
    with ClusterSubProcess(5, timeout=120.0) as a:
        b = ClusterSubProcess(5, timeout=120.0)
        try:
            b.bootstrap("127.0.0.1", a.ports[0])
            time.sleep(2.0)                    # let the meshes interleave

            key = bytes(InfoHash.get("survives-cluster-death"))
            assert a.put(key, b"persistent")
            # the put announces to the 8 closest of ~10 nodes: with two
            # 5-node clusters at least one replica lands in B
            assert b"persistent" in b.get(key)

            a.kill()                           # whole cluster vanishes

            vals = b.get(key)
            assert b"persistent" in vals
        finally:
            if b.proc.poll() is None:
                b.quit()
