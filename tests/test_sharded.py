"""Multi-chip sharding tests on a virtual 8-device CPU mesh.

Validates that the sharded table-parallel top-k (all_gather merge over
the ``t`` axis) and the data-parallel iterative lookup produce exactly
the single-device results — the correctness contract of the ICI merge
(global top-k ⊆ union of per-shard top-ks).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opendht_tpu.ops.xor_topk import xor_topk
from opendht_tpu.ops.sorted_table import sort_table
from opendht_tpu.core.search import simulate_lookups
from opendht_tpu.parallel import (
    make_mesh, pad_to_multiple, sharded_xor_topk, sharded_lookup,
    sharded_sort_table, sharded_window_lookup, sharded_maintenance_sweep,
    dp_simulate_lookups, tp_simulate_lookups,
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _rand_ids(rng, n):
    return rng.integers(0, 2**32, size=(n, 5), dtype=np.uint32)


def test_mesh_shape(mesh):
    assert mesh.shape["q"] * mesh.shape["t"] == 8


def test_sharded_xor_topk_matches_single_device(mesh):
    rng = np.random.default_rng(7)
    table = _rand_ids(rng, 512)
    queries = _rand_ids(rng, 16 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    d_sh, i_sh = sharded_xor_topk(mesh, queries, table, k=8)

    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_xor_topk_with_invalid_rows(mesh):
    rng = np.random.default_rng(8)
    table = _rand_ids(rng, 256)
    valid = rng.random(256) > 0.3
    queries = _rand_ids(rng, 8 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8,
                            valid=jnp.asarray(valid))
    d_sh, i_sh = sharded_xor_topk(mesh, queries, table, k=8,
                                  valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_xor_topk_padded_table(mesh):
    """Tables whose row count isn't divisible by n_t are padded with
    invalid rows; results must be unchanged."""
    rng = np.random.default_rng(9)
    table = _rand_ids(rng, 301)   # not divisible by n_t=4 ⇒ real padding
    queries = _rand_ids(rng, 4 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    padded, n = pad_to_multiple(table, mesh.shape["t"])
    valid = np.arange(padded.shape[0]) < n
    d_sh, i_sh = sharded_xor_topk(mesh, queries, padded, k=8,
                                  valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_window_lookup_matches_full_scan(mesh):
    """Sorted-window fast path over shards returns the same *ids* (and
    distances) as the exact scan.  Row indices may differ under duplicate
    ids; random 160-bit ids make collisions impossible here, so indices
    must match too after mapping shard-sorted order back to rows."""
    rng = np.random.default_rng(10)
    table = _rand_ids(rng, 1024)
    queries = _rand_ids(rng, 8 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    d_sh, rows_sh = sharded_lookup(mesh, queries, table, k=8, window=64)
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
    np.testing.assert_array_equal(np.asarray(rows_sh), np.asarray(i_ref))


def test_sharded_sort_once_lookup_many(mesh):
    """The two-step API (sort once, look up many batches) matches the
    full-scan oracle for every batch — the amortized production path."""
    rng = np.random.default_rng(12)
    table = _rand_ids(rng, 512)
    sorted_ids, perm, n_valid = sharded_sort_table(mesh, table)
    for batch in range(3):
        queries = _rand_ids(rng, 8 * mesh.shape["q"])
        d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
        d_sh, rows = sharded_window_lookup(mesh, queries, sorted_ids, perm,
                                           n_valid, k=8, window=64)
        np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
        np.testing.assert_array_equal(np.asarray(rows), np.asarray(i_ref))


@pytest.mark.slow
def test_dp_simulate_matches_unsharded(mesh):
    """The data-parallel iterative lookup is bitwise identical to the
    single-device run (the reply model is counter-hashed, not
    device-dependent)."""
    rng = np.random.default_rng(11)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 16 * len(jax.devices()))

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=3)
    out = dp_simulate_lookups(mesh, sorted_ids, n_valid, targets, seed=3)

    np.testing.assert_array_equal(np.asarray(out["nodes"]), np.asarray(ref["nodes"]))
    np.testing.assert_array_equal(np.asarray(out["hops"]), np.asarray(ref["hops"]))
    np.testing.assert_array_equal(
        np.asarray(out["converged"]), np.asarray(ref["converged"]))


def test_tp_simulate_matches_unsharded(mesh):
    """The TABLE-SHARDED iterative lookup (sorted table P('t', None),
    positioning and row fetch each one psum over the t axis) is bitwise
    identical to the single-device engine — the contract that lets a
    table larger than one chip's HBM be *searched*, not just scanned
    (VERDICT round 2 item 1)."""
    rng = np.random.default_rng(13)
    ids = _rand_ids(rng, 4096)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 16 * mesh.shape["q"])

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=5)
    out = tp_simulate_lookups(mesh, np.asarray(sorted_ids), n_valid,
                              targets, seed=5)
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_tp_simulate_padded_table(mesh):
    """Row counts not divisible by n_t are padded; padding content is
    irrelevant by construction (rows >= n_valid are excluded from both
    distributed primitives) — zero padding, which sorts BEFORE real ids,
    must still give exact results."""
    rng = np.random.default_rng(14)
    ids = _rand_ids(rng, 1021)               # prime → real padding
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=2)
    padded, _ = pad_to_multiple(np.asarray(sorted_ids), mesh.shape["t"])
    out = tp_simulate_lookups(mesh, padded, n_valid, targets, seed=2)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_tp_simulate_clustered_ids(mesh):
    """Adversarially clustered ids overflow per-shard LUT buckets; the
    device-side soundness guard must drop to the full-depth search and
    still match the unsharded engine exactly."""
    rng = np.random.default_rng(15)
    ids = _rand_ids(rng, 2048)
    ids[:1500, 0] = 0x41414141               # 73% share the top 32 bits
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])
    targets[: 4 * mesh.shape["q"], 0] = 0x41414141   # half hit the cluster

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=6)
    out = tp_simulate_lookups(mesh, np.asarray(sorted_ids), n_valid,
                              targets, seed=6)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_sharded_expanded_lookup_matches_full_scan(mesh):
    """The per-shard expanded row-gather path (sharded_expand_table +
    expanded lookup) is exact vs the full-scan oracle — the headline
    kernel under table-parallel sharding."""
    from opendht_tpu.parallel import sharded_expand_table
    rng = np.random.default_rng(21)
    table = _rand_ids(rng, 1024)
    sorted_ids, perm, n_valid = sharded_sort_table(mesh, table)
    expanded, lut = sharded_expand_table(mesh, sorted_ids, n_valid)
    for batch in range(2):
        queries = _rand_ids(rng, 8 * mesh.shape["q"])
        d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
        d_sh, rows = sharded_window_lookup(mesh, queries, sorted_ids, perm,
                                           n_valid, k=8, expanded=expanded,
                                           lut=lut)
        np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
        np.testing.assert_array_equal(np.asarray(rows), np.asarray(i_ref))


@pytest.mark.slow
@pytest.mark.parametrize("q,t", [(1, 8), (4, 2), (8, 1)])
def test_tp_simulate_mesh_geometries(q, t):
    """The table-sharded engine must be exact for ANY mesh split — pure
    table-parallel (q=1), query-heavy (q=4,t=2), and the degenerate
    single-shard (t=1) all reduce to the same bit-exact results."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = make_mesh(8, q=q, t=t)
    rng = np.random.default_rng(40 + q)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * q)

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=4)
    out = tp_simulate_lookups(m, np.asarray(sorted_ids), n_valid,
                              targets, seed=4)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_sharded_maintenance_sweep_matches_single_device(mesh):
    """The round-10 maintenance sweep over a row-sharded table must be
    BIT-IDENTICAL to the single-device radix kernel: occupancy psum and
    staleness pmax are exact under resharding, and the refresh targets
    come from the same replicated threefry stream."""
    from opendht_tpu.ops import radix

    rng = np.random.default_rng(55)
    N = 4096
    ids = _rand_ids(rng, N)
    self_id = _rand_ids(rng, 1).reshape(-1)
    valid = rng.random(N) > 0.1
    # a mix of replied and never-replied rows (the never-replied-is-
    # stale rule must survive the shard split)
    last = np.where(rng.random(N) > 0.3,
                    rng.uniform(1.0, 100.0, N), 0.0).astype(np.float32)
    key = jax.random.PRNGKey(9)
    now, age = 700.0, 600.0

    ref = radix.maintenance_sweep(
        jnp.asarray(self_id), jnp.asarray(ids), jnp.asarray(valid),
        jnp.asarray(last), now, age, key)
    got = sharded_maintenance_sweep(mesh, self_id, ids, valid, last,
                                    now, age, key)
    for a, b, name in zip(got, ref, ("counts", "last", "stale", "targets")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


# ---------------------------------------------------------------------------
# Round 13: declarative partition layer + row-sharded geometry sweep
# ---------------------------------------------------------------------------

def test_match_partition_rules_names_and_scalars():
    """Rule matching follows /-joined leaf names, first hit wins, and
    scalar leaves never partition regardless of rule."""
    from jax.sharding import PartitionSpec as P
    from opendht_tpu.parallel import partition

    tree = {"sorted_ids": np.zeros((8, 5), np.uint32),
            "local_lut": np.zeros((2, 9), np.int32),
            "block_lut": np.zeros((17,), np.int32),
            "n_valid": np.int32(7),
            "nested": {"targets": np.zeros((4, 5), np.uint32)}}
    specs = partition.match_partition_rules(partition.TABLE_AXIS_RULES, tree)
    assert specs["sorted_ids"] == P("t", None)
    assert specs["local_lut"] == P("t", None)
    assert specs["block_lut"] == P()
    assert specs["n_valid"] == P()               # scalar guard
    assert specs["nested"]["targets"] == P("q", None)
    with pytest.raises(ValueError, match="no partition rule"):
        partition.match_partition_rules(
            [(r"^only_this$", P("t"))], {"other": np.zeros((4,))})


def test_shard_and_gather_fns_roundtrip(mesh):
    """shard fn places a host array straight onto its shards (per-device
    bytes = N/t rows — the whole point of the layout); gather fn
    returns the exact original."""
    from opendht_tpu.parallel import partition

    rng = np.random.default_rng(70)
    tree = {"sorted_ids": _rand_ids(rng, 64 * mesh.shape["t"])}
    specs = partition.match_partition_rules(partition.TABLE_AXIS_RULES, tree)
    shard_fns, gather_fns = partition.make_shard_and_gather_fns(mesh, specs)
    placed = shard_fns["sorted_ids"](tree["sorted_ids"])
    shard = placed.addressable_shards[0].data
    assert shard.shape[0] == 64 * mesh.shape["t"] // mesh.shape["t"]
    assert shard.nbytes == placed.nbytes // mesh.shape["t"]
    np.testing.assert_array_equal(gather_fns["sorted_ids"](placed),
                                  tree["sorted_ids"])
    # placement is idempotent: re-sharding an already-placed array is
    # the identity (the Snapshot resolve cache depends on this)
    assert shard_fns["sorted_ids"](placed) is placed


def test_shard_table_state_block_lut_is_global(mesh):
    """The replicated block LUT assembled from per-shard psums must
    equal build_prefix_lut over the whole table — the bit-identity
    basis for the zero-collective in-loop block edges."""
    from opendht_tpu.ops.sorted_table import build_prefix_lut
    from opendht_tpu.parallel import shard_table_state

    rng = np.random.default_rng(71)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    state = shard_table_state(mesh, np.asarray(sorted_ids), n_valid)
    ref = build_prefix_lut(sorted_ids, jnp.asarray(n_valid, jnp.int32),
                           bits=state.block_bits)
    np.testing.assert_array_equal(np.asarray(state.arrays["block_lut"]),
                                  np.asarray(ref))
    assert state.table_bytes_per_shard() == 2048 // mesh.shape["t"] * 20


def test_shard_table_state_casts_dtype(mesh):
    """A non-uint32 id table must be cast before placement — the limb
    kernels silently mis-rank on int64 otherwise (review finding)."""
    rng = np.random.default_rng(74)
    ids = _rand_ids(rng, 1024)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=7)
    out = tp_simulate_lookups(mesh, np.asarray(sorted_ids).astype(np.int64),
                              n_valid, targets, seed=7)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_tp_simulate_with_prebuilt_state(mesh):
    """The state= fast path (table placed once, reused across waves)
    returns exactly what the raw-array path returns."""
    from opendht_tpu.parallel import shard_table_state

    rng = np.random.default_rng(72)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=9)
    state = shard_table_state(mesh, np.asarray(sorted_ids), n_valid)
    for _ in range(2):                    # second wave reuses everything
        out = tp_simulate_lookups(mesh, targets=targets, seed=9, state=state)
        for key in ("nodes", "hops", "converged", "dist"):
            np.testing.assert_array_equal(np.asarray(out[key]),
                                          np.asarray(ref[key]))


@pytest.mark.parametrize("q,t", [(1, 2), (2, 2), (1, 4), (4, 1)])
def test_row_sharded_geometry_sweep(q, t):
    """ISSUE-8 satellite: every entry point — iterative lookup,
    window-lookup, xor-topk, maintenance sweep — pinned bit-identical
    to single-device across q×t splits on the ROW-SHARDED table,
    including ragged N (pad rows land on the last shard) and an
    ALL-INVALID shard."""
    if len(jax.devices()) < q * t:
        pytest.skip(f"needs {q * t} virtual devices")
    from opendht_tpu.ops import radix
    m = make_mesh(q * t, q=q, t=t)
    rng = np.random.default_rng(60 + 4 * q + t)
    N_ragged = 1021                       # prime → real padding
    ids = _rand_ids(rng, N_ragged)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    padded, _ = pad_to_multiple(np.asarray(sorted_ids), t * 4)
    targets = _rand_ids(rng, 8 * q)

    # iterative engine on the ragged row-sharded table
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=8)
    out = tp_simulate_lookups(m, padded, n_valid, targets, seed=8)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]), err_msg=key)

    # full-scan + window top-k with an entirely invalid shard: valid
    # rows only in the first global quarter, so on t=4 the later
    # shards hold zero valid rows
    table = _rand_ids(rng, 64 * t * 4)
    valid = np.zeros(table.shape[0], bool)
    valid[:table.shape[0] // 4] = True
    queries = _rand_ids(rng, 8 * q)
    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8,
                            valid=jnp.asarray(valid))
    d_sh, i_sh = sharded_xor_topk(m, queries, table, k=8,
                                  valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
    d_w, rows_w = sharded_lookup(m, queries, table, k=8, window=32,
                                 valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(rows_w), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_w), np.asarray(d_ref))

    # maintenance sweep on the same all-invalid-shard layout
    self_id = _rand_ids(rng, 1).reshape(-1)
    last = rng.uniform(1.0, 100.0, table.shape[0]).astype(np.float32)
    key = jax.random.PRNGKey(31)
    ref_m = radix.maintenance_sweep(
        jnp.asarray(self_id), jnp.asarray(table), jnp.asarray(valid),
        jnp.asarray(last), 700.0, 600.0, key)
    got_m = sharded_maintenance_sweep(m, self_id, table, valid, last,
                                      700.0, 600.0, key)
    for a, b, name in zip(got_m, ref_m, ("counts", "last", "stale",
                                         "targets")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_snapshot_lookup_sharded_matches_unsharded(mesh):
    """The t-sharded snapshot resolve (config.resolve_mesh_t wiring,
    core/table.py Snapshot.lookup mesh=) returns exactly the
    single-device resolve — rows and distances."""
    from opendht_tpu.core.table import NodeTable
    from opendht_tpu.infohash import InfoHash

    rng = np.random.default_rng(73)
    nt = NodeTable(InfoHash.get_random(), capacity=512)
    now = 100.0
    for i in range(300):
        nt.insert(InfoHash.get_random(), ("10.0.0.%d" % (i % 250), 4222),
                  now=now, confirm=2)
    snap = nt.snapshot(now)
    q = _rand_ids(rng, 16)
    rows_ref, dist_ref = snap.lookup(q, k=8)
    rows_sh, dist_sh = snap.lookup(q, k=8, mesh=mesh)
    np.testing.assert_array_equal(rows_sh, rows_ref)
    np.testing.assert_array_equal(dist_sh, dist_ref)
    # second call reuses the cached placed shards (no re-pad, no copy)
    rows_sh2, _ = snap.lookup(q, k=8, mesh=mesh)
    np.testing.assert_array_equal(rows_sh2, rows_ref)


def test_dht_resolve_mesh_knob(mesh):
    """config.resolve_mesh_t builds the (q=1, t) mesh lazily; 0 keeps
    the unsharded path; an over-sized t degrades with a warning, never
    fails."""
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht

    d0 = Dht(lambda data, addr: 0, Config())
    assert d0.resolve_mesh() is None and d0.resolve_mesh_t() == 1
    d4 = Dht(lambda data, addr: 0, Config(resolve_mesh_t=4))
    m = d4.resolve_mesh()
    assert m is not None and m.shape["t"] == 4 and m.shape["q"] == 1
    assert d4.resolve_mesh_t() == 4
    assert d4.wave_builder.snapshot()["table_shard_t"] == 4
    d_big = Dht(lambda data, addr: 0, Config(resolve_mesh_t=512))
    assert d_big.resolve_mesh() is None and d_big.resolve_mesh_t() == 1


def test_sharded_maintenance_sweep_padded_table(mesh):
    """Invalid pad rows (the pad_to_multiple contract) contribute to no
    bucket and no staleness."""
    from opendht_tpu.ops import radix

    rng = np.random.default_rng(56)
    ids = _rand_ids(rng, 1000)
    self_id = _rand_ids(rng, 1).reshape(-1)
    last = rng.uniform(1.0, 100.0, 1000).astype(np.float32)
    padded, n = pad_to_multiple(ids, mesh.shape["t"] * 256)
    valid = np.arange(padded.shape[0]) < n
    last_p, _ = pad_to_multiple(last, mesh.shape["t"] * 256)
    key = jax.random.PRNGKey(10)

    ref = radix.maintenance_sweep(
        jnp.asarray(self_id), jnp.asarray(ids),
        jnp.ones(1000, bool), jnp.asarray(last), 700.0, 600.0, key)
    got = sharded_maintenance_sweep(mesh, self_id, padded, valid, last_p,
                                    700.0, 600.0, key)
    for a, b, name in zip(got, ref, ("counts", "last", "stale", "targets")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)
