"""Multi-chip sharding tests on a virtual 8-device CPU mesh.

Validates that the sharded table-parallel top-k (all_gather merge over
the ``t`` axis) and the data-parallel iterative lookup produce exactly
the single-device results — the correctness contract of the ICI merge
(global top-k ⊆ union of per-shard top-ks).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from opendht_tpu.ops.xor_topk import xor_topk
from opendht_tpu.ops.sorted_table import sort_table
from opendht_tpu.core.search import simulate_lookups
from opendht_tpu.parallel import (
    make_mesh, pad_to_multiple, sharded_xor_topk, sharded_lookup,
    sharded_sort_table, sharded_window_lookup, sharded_maintenance_sweep,
    dp_simulate_lookups, tp_simulate_lookups,
)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(8)


def _rand_ids(rng, n):
    return rng.integers(0, 2**32, size=(n, 5), dtype=np.uint32)


def test_mesh_shape(mesh):
    assert mesh.shape["q"] * mesh.shape["t"] == 8


def test_sharded_xor_topk_matches_single_device(mesh):
    rng = np.random.default_rng(7)
    table = _rand_ids(rng, 512)
    queries = _rand_ids(rng, 16 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    d_sh, i_sh = sharded_xor_topk(mesh, queries, table, k=8)

    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_xor_topk_with_invalid_rows(mesh):
    rng = np.random.default_rng(8)
    table = _rand_ids(rng, 256)
    valid = rng.random(256) > 0.3
    queries = _rand_ids(rng, 8 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8,
                            valid=jnp.asarray(valid))
    d_sh, i_sh = sharded_xor_topk(mesh, queries, table, k=8,
                                  valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_xor_topk_padded_table(mesh):
    """Tables whose row count isn't divisible by n_t are padded with
    invalid rows; results must be unchanged."""
    rng = np.random.default_rng(9)
    table = _rand_ids(rng, 301)   # not divisible by n_t=4 ⇒ real padding
    queries = _rand_ids(rng, 4 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    padded, n = pad_to_multiple(table, mesh.shape["t"])
    valid = np.arange(padded.shape[0]) < n
    d_sh, i_sh = sharded_xor_topk(mesh, queries, padded, k=8,
                                  valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(i_sh), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))


def test_sharded_window_lookup_matches_full_scan(mesh):
    """Sorted-window fast path over shards returns the same *ids* (and
    distances) as the exact scan.  Row indices may differ under duplicate
    ids; random 160-bit ids make collisions impossible here, so indices
    must match too after mapping shard-sorted order back to rows."""
    rng = np.random.default_rng(10)
    table = _rand_ids(rng, 1024)
    queries = _rand_ids(rng, 8 * mesh.shape["q"])

    d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
    d_sh, rows_sh = sharded_lookup(mesh, queries, table, k=8, window=64)
    np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
    np.testing.assert_array_equal(np.asarray(rows_sh), np.asarray(i_ref))


def test_sharded_sort_once_lookup_many(mesh):
    """The two-step API (sort once, look up many batches) matches the
    full-scan oracle for every batch — the amortized production path."""
    rng = np.random.default_rng(12)
    table = _rand_ids(rng, 512)
    sorted_ids, perm, n_valid = sharded_sort_table(mesh, table)
    for batch in range(3):
        queries = _rand_ids(rng, 8 * mesh.shape["q"])
        d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
        d_sh, rows = sharded_window_lookup(mesh, queries, sorted_ids, perm,
                                           n_valid, k=8, window=64)
        np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
        np.testing.assert_array_equal(np.asarray(rows), np.asarray(i_ref))


@pytest.mark.slow
def test_dp_simulate_matches_unsharded(mesh):
    """The data-parallel iterative lookup is bitwise identical to the
    single-device run (the reply model is counter-hashed, not
    device-dependent)."""
    rng = np.random.default_rng(11)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 16 * len(jax.devices()))

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=3)
    out = dp_simulate_lookups(mesh, sorted_ids, n_valid, targets, seed=3)

    np.testing.assert_array_equal(np.asarray(out["nodes"]), np.asarray(ref["nodes"]))
    np.testing.assert_array_equal(np.asarray(out["hops"]), np.asarray(ref["hops"]))
    np.testing.assert_array_equal(
        np.asarray(out["converged"]), np.asarray(ref["converged"]))


def test_tp_simulate_matches_unsharded(mesh):
    """The TABLE-SHARDED iterative lookup (sorted table P('t', None),
    positioning and row fetch each one psum over the t axis) is bitwise
    identical to the single-device engine — the contract that lets a
    table larger than one chip's HBM be *searched*, not just scanned
    (VERDICT round 2 item 1)."""
    rng = np.random.default_rng(13)
    ids = _rand_ids(rng, 4096)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 16 * mesh.shape["q"])

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=5)
    out = tp_simulate_lookups(mesh, np.asarray(sorted_ids), n_valid,
                              targets, seed=5)
    for key in ("nodes", "hops", "converged", "dist"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_tp_simulate_padded_table(mesh):
    """Row counts not divisible by n_t are padded; padding content is
    irrelevant by construction (rows >= n_valid are excluded from both
    distributed primitives) — zero padding, which sorts BEFORE real ids,
    must still give exact results."""
    rng = np.random.default_rng(14)
    ids = _rand_ids(rng, 1021)               # prime → real padding
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=2)
    padded, _ = pad_to_multiple(np.asarray(sorted_ids), mesh.shape["t"])
    out = tp_simulate_lookups(mesh, padded, n_valid, targets, seed=2)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_tp_simulate_clustered_ids(mesh):
    """Adversarially clustered ids overflow per-shard LUT buckets; the
    device-side soundness guard must drop to the full-depth search and
    still match the unsharded engine exactly."""
    rng = np.random.default_rng(15)
    ids = _rand_ids(rng, 2048)
    ids[:1500, 0] = 0x41414141               # 73% share the top 32 bits
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * mesh.shape["q"])
    targets[: 4 * mesh.shape["q"], 0] = 0x41414141   # half hit the cluster

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=6)
    out = tp_simulate_lookups(mesh, np.asarray(sorted_ids), n_valid,
                              targets, seed=6)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_sharded_expanded_lookup_matches_full_scan(mesh):
    """The per-shard expanded row-gather path (sharded_expand_table +
    expanded lookup) is exact vs the full-scan oracle — the headline
    kernel under table-parallel sharding."""
    from opendht_tpu.parallel import sharded_expand_table
    rng = np.random.default_rng(21)
    table = _rand_ids(rng, 1024)
    sorted_ids, perm, n_valid = sharded_sort_table(mesh, table)
    expanded, lut = sharded_expand_table(mesh, sorted_ids, n_valid)
    for batch in range(2):
        queries = _rand_ids(rng, 8 * mesh.shape["q"])
        d_ref, i_ref = xor_topk(jnp.asarray(queries), jnp.asarray(table), k=8)
        d_sh, rows = sharded_window_lookup(mesh, queries, sorted_ids, perm,
                                           n_valid, k=8, expanded=expanded,
                                           lut=lut)
        np.testing.assert_array_equal(np.asarray(d_sh), np.asarray(d_ref))
        np.testing.assert_array_equal(np.asarray(rows), np.asarray(i_ref))


@pytest.mark.slow
@pytest.mark.parametrize("q,t", [(1, 8), (4, 2), (8, 1)])
def test_tp_simulate_mesh_geometries(q, t):
    """The table-sharded engine must be exact for ANY mesh split — pure
    table-parallel (q=1), query-heavy (q=4,t=2), and the degenerate
    single-shard (t=1) all reduce to the same bit-exact results."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    m = make_mesh(8, q=q, t=t)
    rng = np.random.default_rng(40 + q)
    ids = _rand_ids(rng, 2048)
    sorted_ids, _, n_valid = sort_table(jnp.asarray(ids))
    targets = _rand_ids(rng, 8 * q)

    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=4)
    out = tp_simulate_lookups(m, np.asarray(sorted_ids), n_valid,
                              targets, seed=4)
    for key in ("nodes", "hops", "converged"):
        np.testing.assert_array_equal(np.asarray(out[key]),
                                      np.asarray(ref[key]))


def test_sharded_maintenance_sweep_matches_single_device(mesh):
    """The round-10 maintenance sweep over a row-sharded table must be
    BIT-IDENTICAL to the single-device radix kernel: occupancy psum and
    staleness pmax are exact under resharding, and the refresh targets
    come from the same replicated threefry stream."""
    from opendht_tpu.ops import radix

    rng = np.random.default_rng(55)
    N = 4096
    ids = _rand_ids(rng, N)
    self_id = _rand_ids(rng, 1).reshape(-1)
    valid = rng.random(N) > 0.1
    # a mix of replied and never-replied rows (the never-replied-is-
    # stale rule must survive the shard split)
    last = np.where(rng.random(N) > 0.3,
                    rng.uniform(1.0, 100.0, N), 0.0).astype(np.float32)
    key = jax.random.PRNGKey(9)
    now, age = 700.0, 600.0

    ref = radix.maintenance_sweep(
        jnp.asarray(self_id), jnp.asarray(ids), jnp.asarray(valid),
        jnp.asarray(last), now, age, key)
    got = sharded_maintenance_sweep(mesh, self_id, ids, valid, last,
                                    now, age, key)
    for a, b, name in zip(got, ref, ("counts", "last", "stale", "targets")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_sharded_maintenance_sweep_padded_table(mesh):
    """Invalid pad rows (the pad_to_multiple contract) contribute to no
    bucket and no staleness."""
    from opendht_tpu.ops import radix

    rng = np.random.default_rng(56)
    ids = _rand_ids(rng, 1000)
    self_id = _rand_ids(rng, 1).reshape(-1)
    last = rng.uniform(1.0, 100.0, 1000).astype(np.float32)
    padded, n = pad_to_multiple(ids, mesh.shape["t"] * 256)
    valid = np.arange(padded.shape[0]) < n
    last_p, _ = pad_to_multiple(last, mesh.shape["t"] * 256)
    key = jax.random.PRNGKey(10)

    ref = radix.maintenance_sweep(
        jnp.asarray(self_id), jnp.asarray(ids),
        jnp.ones(1000, bool), jnp.asarray(last), 700.0, 600.0, key)
    got = sharded_maintenance_sweep(mesh, self_id, padded, valid, last_p,
                                    700.0, 600.0, key)
    for a, b, name in zip(got, ref, ("counts", "last", "stale", "targets")):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)
