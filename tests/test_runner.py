"""DhtRunner integration tests over real localhost UDP sockets —
the analog of the reference tests/dhtrunnertester.cpp (2 real nodes,
bootstrap, blocking get sees put :30-57) plus the listen test the
reference left as a TODO (:60-62), and a signed-put through identities."""

import time

import pytest

from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime.config import NodeStatus
from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig


def wait_for(pred, timeout=20.0, step=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


@pytest.fixture
def two_nodes():
    a, b = DhtRunner(), DhtRunner()
    a.run(0)
    b.run(0)
    b.bootstrap("127.0.0.1", a.get_bound_port())
    yield a, b
    a.join()
    b.join()


def test_ipv6_dual_stack_put_get():
    """Dual-stack runners bootstrap over ::1 and serve values on the v6
    family (every table/search is duplicated per family, dht.h:370-381)."""
    import socket
    a, b = DhtRunner(), DhtRunner()
    a.run(0, ipv6=True)
    b.run(0, ipv6=True)
    def v6_up(r):
        return (r._sock6 is not None
                or (r._udp is not None and r._udp.has_v6))
    if not (v6_up(a) and v6_up(b)):
        a.join(); b.join()
        pytest.skip("no IPv6 loopback available")
    try:
        b.bootstrap("::1", a.get_bound_port())
        assert wait_for(lambda: b.get_status(socket.AF_INET6)
                        is NodeStatus.CONNECTED)
        key = InfoHash.get("v6key")
        assert b.put_sync(key, Value(b"over-six"), timeout=20.0)
        vals = a.get_sync(key, timeout=20.0)
        assert any(v.data == b"over-six" for v in vals)
    finally:
        a.join()
        b.join()


def test_ipv6_python_fallback_put_get():
    """v6 with the native engine DISABLED: the Python-socket fallback
    path must keep serving dual-stack on its own (VERDICT r5 ask 7's
    'Python fallback preserved' clause — the native v6 path is covered
    by test_native.py and test_ipv6_dual_stack_put_get)."""
    import socket
    a, b = DhtRunner(), DhtRunner()
    a.run(0, RunnerConfig(native_engine=False), ipv6=True)
    b.run(0, RunnerConfig(native_engine=False), ipv6=True)
    assert a._udp is None and b._udp is None     # really on Python sockets
    if a._sock6 is None or b._sock6 is None:
        a.join(); b.join()
        pytest.skip("no IPv6 loopback available")
    try:
        b.bootstrap("::1", a.get_bound_port())
        assert wait_for(lambda: b.get_status(socket.AF_INET6)
                        is NodeStatus.CONNECTED)
        key = InfoHash.get("v6-python-fallback")
        assert b.put_sync(key, Value(b"six sans native"), timeout=20.0)
        vals = a.get_sync(key, timeout=20.0)
        assert any(v.data == b"six sans native" for v in vals)
    finally:
        a.join()
        b.join()


def test_bootstrap_connects(two_nodes):
    a, b = two_nodes
    assert a.get_bound_port() > 0 and b.get_bound_port() > 0
    assert wait_for(lambda: a.get_status() is NodeStatus.CONNECTED
                    and b.get_status() is NodeStatus.CONNECTED), \
        f"never connected: a={a.get_status()} b={b.get_status()}"


def test_put_get(two_nodes):
    a, b = two_nodes
    assert wait_for(lambda: b.get_status() is NodeStatus.CONNECTED)
    key = InfoHash.get("testkey")
    assert b.put_sync(key, Value(b"yo"), timeout=20.0)
    vals = a.get_sync(key, timeout=20.0)
    assert any(v.data == b"yo" for v in vals)


def test_listen(two_nodes):
    a, b = two_nodes
    assert wait_for(lambda: a.get_status() is NodeStatus.CONNECTED
                    and b.get_status() is NodeStatus.CONNECTED)
    key = InfoHash.get("listenkey")
    heard = []
    token_fut = a.listen(key, lambda vals, expired:
                         heard.extend(v.data for v in vals
                                      if not expired) or True)
    token_fut.result(10.0)
    b.put(key, Value(b"pushed value"))
    assert wait_for(lambda: b"pushed value" in heard, 20.0), \
        "listener never heard the remote put"
    a.cancel_listen(key, token_fut)


def test_many_nodes_converge():
    runners = []
    try:
        seed = DhtRunner()
        seed.run(0)
        runners.append(seed)
        for _ in range(4):
            r = DhtRunner()
            r.run(0)
            r.bootstrap("127.0.0.1", seed.get_bound_port())
            runners.append(r)
        assert wait_for(lambda: all(r.get_status() is NodeStatus.CONNECTED
                                    for r in runners), 30.0)
        key = InfoHash.get("multi")
        assert runners[2].put_sync(key, Value(b"over the mesh"), timeout=20.0)
        vals = runners[4].get_sync(key, timeout=20.0)
        assert any(v.data == b"over the mesh" for v in vals)
        stats = runners[0].get_node_stats()
        assert stats.good_nodes >= 1
    finally:
        for r in runners:
            r.join()


def test_identity_signed_put():
    # the one runner test that NEEDS the crypto wheel; importing it here
    # (not at module top) keeps the rest of this file runnable in
    # minimal containers, like the identity-less runner itself
    crypto = pytest.importorskip("opendht_tpu.crypto")
    ida = crypto.generate_identity("runner-a", key_length=1024)
    idb = crypto.generate_identity("runner-b", key_length=1024)
    a, b = DhtRunner(), DhtRunner()
    try:
        a.run(0, RunnerConfig(identity=ida))
        b.run(0, RunnerConfig(identity=idb))
        b.bootstrap("127.0.0.1", a.get_bound_port())
        assert wait_for(lambda: b.get_status() is NodeStatus.CONNECTED)
        key = InfoHash.get("signed-runner")
        import concurrent.futures
        fut = concurrent.futures.Future()
        b.put_signed(key, Value(b"signed over udp"),
                     lambda ok, ns: fut.done() or fut.set_result(ok))
        assert fut.result(30.0)
        vals = a.get_sync(key, timeout=20.0)
        assert any(v.data == b"signed over udp" and v.check_signature()
                   for v in vals)
    finally:
        a.join()
        b.join()


def test_join_idempotent():
    r = DhtRunner()
    r.run(0)
    r.join()
    r.join()
    assert not r.is_running()


def test_prio_ops_cannot_starve_normal_ops():
    """Starvation regression (round 12): sustained prio traffic — every
    pump finds the prio queue non-empty again — must not indefinitely
    defer normal ops.  Before the fix, ``_loop``'s elif skipped the
    normal queue whenever prio ops were pending, so a prio source that
    re-arms each pump (bootstrap ping storms, stats polls) deferred
    every get/put/listen forever.  The fairness bound: each pump drains
    prio first, then the eligible normal backlog."""
    r = DhtRunner()
    r.run(0, RunnerConfig(threaded=False))
    try:
        order = []
        r._post(lambda dht: order.append("normal"))

        def rearm(dht):
            order.append("prio")
            r._post(rearm, prio=True)     # the queue is never observed empty

        r._post(rearm, prio=True)
        for _ in range(4):
            r.loop()
        assert "normal" in order, \
            "normal op starved behind sustained prio traffic"
        # prio keeps strict precedence within its pump
        assert order.index("prio") < order.index("normal")
    finally:
        r.join()


def test_normal_ops_still_gated_while_bootstrapping():
    """The fairness fix must not weaken the reference's gating: while a
    bootstrap attempt is in flight (disconnected + bootstrapping),
    normal ops stay queued; prio ops run (dhtrunner.cpp:393-398)."""
    r = DhtRunner()
    r.run(0, RunnerConfig(threaded=False))
    try:
        r._bootstraping = True            # simulate the bootstrap thread
        ran = []
        r._post(lambda dht: ran.append("normal"))
        r._post(lambda dht: ran.append("prio"), prio=True)
        r.loop()
        assert ran == ["prio"], ran
        r._bootstraping = False
        r.loop()
        assert ran == ["prio", "normal"], ran
    finally:
        r.join()
