"""Round-10 batched maintenance sweep: the fused bucket-refresh device
pass, the calendar-binned republish planner, and their exact agreement
with the per-key / per-bucket scalar paths they replaced
(↔ Dht::bucketMaintenance src/dht.cpp:1780-1838,
Dht::dataPersistence/maintainStorage src/dht.cpp:1840-1900,
RoutingTable::randomId src/routing_table.cpp:67-85)."""

import socket

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opendht_tpu.infohash import InfoHash
from opendht_tpu.core.table import NodeTable, NODE_EXPIRE_TIME, TARGET_NODES
from opendht_tpu.core.value import Value, ValueType
from opendht_tpu.ops import ids as K
from opendht_tpu.ops import radix
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.runtime.dht import (MAX_STORAGE_MAINTENANCE_EXPIRE_TIME,
                                     STORAGE_CALENDAR_QUANTUM)
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu import telemetry

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

AF = socket.AF_INET


def _rand_hash(rng):
    return InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))


# ----------------------------------------------------------- device kernel

def _scalar_sweep(me, hashes, valid, last_reply, now, age):
    """Per-bucket scalar oracle with the reference's never-replied-is-
    stale rule (Bucket::time = time_point::min())."""
    counts = np.zeros(160, np.int32)
    last = np.full(160, -np.inf)
    for i, h in enumerate(hashes):
        if not valid[i]:
            continue
        b = min(InfoHash.common_bits(me, h), 159)
        counts[b] += 1
        if last_reply[i] > 0:
            last[b] = max(last[b], last_reply[i])
    stale = (counts > 0) & (last < now - age)
    return counts, last, stale


def test_maintenance_sweep_matches_scalar_oracle():
    rng = np.random.default_rng(11)
    me = _rand_hash(rng)
    hashes = [_rand_hash(rng) for _ in range(128)]
    # a guaranteed never-replied-only bucket: one peer differing at bit 0
    # with last_reply == 0 (all the random peers land in other buckets
    # with overwhelming probability is NOT assumed — the oracle decides)
    valid = rng.random(128) > 0.15
    last_reply = np.where(rng.random(128) > 0.4,
                          rng.uniform(1.0, 100.0, 128), 0.0)
    now, age = 700.0, 600.0
    self_l = jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1)
    ids = jnp.asarray(K.ids_from_hashes(hashes))
    counts, last, stale, targets = radix.maintenance_sweep(
        self_l, ids, jnp.asarray(valid),
        jnp.asarray(last_reply, jnp.float32), now, age,
        jax.random.PRNGKey(5))
    w_counts, w_last, w_stale = _scalar_sweep(
        me, hashes, valid, last_reply.astype(np.float32), now, age)
    np.testing.assert_array_equal(np.asarray(counts), w_counts)
    np.testing.assert_array_equal(np.asarray(stale), w_stale)
    got_last = np.asarray(last)
    for b in range(160):
        if np.isfinite(w_last[b]):
            assert got_last[b] == pytest.approx(w_last[b])
        else:
            assert not np.isfinite(got_last[b])
    # targets land inside their bucket's range for every bucket
    raw = K.ids_to_bytes(np.asarray(targets))
    for b in range(160):
        h = InfoHash(raw[b].tobytes())
        assert InfoHash.common_bits(me, h) == b

    # fused sweep == the standalone kernels it fuses
    np.testing.assert_array_equal(
        np.asarray(counts),
        np.asarray(radix.bucket_counts(self_l, ids, jnp.asarray(valid))))
    np.testing.assert_array_equal(
        np.asarray(last),
        np.asarray(radix.bucket_last_seen(
            self_l, ids, jnp.asarray(valid),
            jnp.asarray(last_reply, jnp.float32))))


def test_bucket_last_seen_never_replied_is_stale():
    """ISSUE-5 satellite: the device kernel now honors the reference's
    never-replied ⇒ stale-from-birth rule — a bucket whose only peers
    have last_reply == 0 reads -inf, exactly like the host oracle
    (the old kernel read 0.0 there and diverged from
    NodeTable.stale_buckets)."""
    rng = np.random.default_rng(12)
    me = _rand_hash(rng)
    # two peers in bucket 0 (first bit differs), neither ever replied
    peers = []
    while len(peers) < 2:
        h = _rand_hash(rng)
        if InfoHash.common_bits(me, h) == 0:
            peers.append(h)
    ids = jnp.asarray(K.ids_from_hashes(peers))
    last = np.asarray(radix.bucket_last_seen(
        jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1), ids,
        jnp.ones(2, bool), jnp.zeros(2, jnp.float32)))
    assert last[0] == -np.inf
    # and a replied peer lifts it
    last2 = np.asarray(radix.bucket_last_seen(
        jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1), ids,
        jnp.ones(2, bool), jnp.asarray([0.0, 42.0], jnp.float32)))
    assert last2[0] == pytest.approx(42.0)


def test_node_table_sweep_matches_stale_buckets():
    """NodeTable.maintenance_sweep (one fused launch) returns the same
    stale set as stale_buckets, including never-replied buckets."""
    rng = np.random.default_rng(13)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=128)
    replied = rng.integers(0, 256, (40, 20), dtype=np.uint8)
    hearsay = rng.integers(0, 256, (40, 20), dtype=np.uint8)
    t.bulk_load(K.ids_from_bytes(replied), now=100.0, replied=True)
    t.bulk_load(K.ids_from_bytes(hearsay), now=100.0, replied=False)
    for now in (101.0, 100.0 + NODE_EXPIRE_TIME + 1, 5000.0):
        want = t.stale_buckets(now)
        stale, targets = t.maintenance_sweep(now)
        np.testing.assert_array_equal(stale, want)
        assert targets.shape == (len(stale), 5)
        raw = K.ids_to_bytes(targets)
        for j, b in enumerate(stale):
            assert InfoHash.common_bits(
                me, InfoHash(raw[j].tobytes())) == b
    # shortly after load, only the hearsay-only (never-replied) buckets
    # are stale — and there is at least one at these sizes
    stale, _ = t.maintenance_sweep(101.0)
    replied_buckets = {int(t._bucket[t.row_of(InfoHash(replied[i].tobytes()))])
                       for i in range(40)
                       if t.row_of(InfoHash(replied[i].tobytes())) is not None
                       and t._time_reply[
                           t.row_of(InfoHash(replied[i].tobytes()))] > 0}
    assert set(stale.tolist()).isdisjoint(replied_buckets)


def test_refresh_targets_threads_reusable_key():
    """With no explicit key the table splits ONE reusable PRNG key per
    call (no fresh PRNGKey mint per tick) — consecutive calls give
    fresh targets, still inside the right buckets."""
    rng = np.random.default_rng(14)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=32)
    buckets = np.array([0, 1, 5, 42])
    a = t.refresh_targets(buckets)
    key_after_first = t._maint_key
    b = t.refresh_targets(buckets)
    assert t._maint_key is not key_after_first      # threaded, not reused
    assert not np.array_equal(a, b)
    for arr in (a, b):
        raw = K.ids_to_bytes(arr)
        for j, bk in enumerate(buckets):
            assert InfoHash.common_bits(me, InfoHash(raw[j].tobytes())) == bk
    # explicit keys still honored (deterministic)
    c = t.refresh_targets(buckets, jax.random.PRNGKey(1))
    d = t.refresh_targets(buckets, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(c, d)


# --------------------------------------------------- responsibility predicate

def _make_dht(clock=None, maintain=True):
    sched = Scheduler(clock=clock) if clock is not None else None
    cfg = Config()
    cfg.maintain_storage = maintain
    sent = []
    dht = Dht(lambda data, addr: sent.append((data, addr)) or 0,
              config=cfg, scheduler=sched, has_v6=False)
    return dht, sent


def _fill_table(dht, rng, n, now=None):
    table = dht.tables[AF]
    now = dht.scheduler.time() if now is None else now
    added = 0
    while added < n:
        h = _rand_hash(rng)
        if table.insert(h, SockAddr("10.0.0.%d" % (added % 250 + 1),
                                    4000 + added),
                        now=now, confirm=2) is not None:
            added += 1
    return table


def _scalar_republish_decision(dht, key, af):
    """The exact decision body of Dht._maintain_storage (src/dht.cpp:
    1854-1900): republish iff closest nodes exist and the farthest of
    them is XOR-closer to the key than we are."""
    nodes = dht.find_closest_nodes(key, af)
    if not nodes:
        return False
    return key.xor_cmp(nodes[-1].id, dht.myid) < 0


def test_republish_predicate_matches_scalar():
    rng = np.random.default_rng(15)
    dht, _ = _make_dht()
    _fill_table(dht, rng, 40)
    keys = [_rand_hash(rng) for _ in range(64)]
    # keys AT a table node's id and AT our own id: xor distance 0 rows
    # and the tie-sensitive boundary
    table = dht.tables[AF]
    keys.append(table.id_of(next(iter(table._row_of.values()))))
    keys.append(dht.myid)
    got = dht._republish_predicate(keys, AF)
    want = [_scalar_republish_decision(dht, k, AF) for k in keys]
    assert got == want
    assert any(got), "no key ever due — the comparison is vacuous"


def test_republish_predicate_small_and_empty_tables():
    rng = np.random.default_rng(16)
    # empty table: nobody closer exists — no republish, family keeps
    # responsibility (the scalar path `continue`s)
    dht, _ = _make_dht()
    keys = [_rand_hash(rng) for _ in range(5)]
    assert dht._republish_predicate(keys, AF) == [False] * 5
    # table smaller than k: the LAST VALID node decides (not the -1
    # padded k-th row).  The boundary meta-assertion needs a FIXED
    # node id: _make_dht's random id intermittently put every seeded
    # key on the same side of the decision at small n (flaky in CI)
    # while the parity assertion itself held.
    for n in (1, 3, TARGET_NODES - 1):
        dht, _ = _make_dht()
        dht.myid = InfoHash.get(f"maint-predicate-node-{n}")
        _fill_table(dht, rng, n)
        keys = [_rand_hash(rng) for _ in range(32)]
        got = dht._republish_predicate(keys, AF)
        want = [_scalar_republish_decision(dht, k, AF) for k in keys]
        assert got == want, f"n={n}"
        assert any(got) and not all(got), f"n={n}: boundary not exercised"


def test_republish_predicate_ignores_addrless_rows():
    """The scalar path builds Node objects, which silently drops rows
    whose addr is unknown — the batched predicate must apply the same
    filter before picking its k-th node."""
    rng = np.random.default_rng(17)
    dht, _ = _make_dht()
    table = _fill_table(dht, rng, 12)
    # strip addresses from half the rows
    for row in list(table._row_of.values())[::2]:
        table._addrs[row] = None
    keys = [_rand_hash(rng) for _ in range(32)]
    got = dht._republish_predicate(keys, AF)
    want = [_scalar_republish_decision(dht, k, AF) for k in keys]
    assert got == want


# --------------------------------------------------- calendar-binned sweep

def test_calendar_sweep_republishes_exactly_on_maintenance_time():
    """Discrete-event boundary (the `<` vs `<=` comment in
    _data_persistence): a driver whose clock lands EXACTLY on
    maintenance_time must republish and reschedule."""
    clock = {"t": 1000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"])
    rng = np.random.default_rng(18)
    _fill_table(dht, rng, 24)
    # long-lived type so values survive past the republish horizon
    dht.types.register_type(ValueType(7, "long", expiration=3600.0))
    # a key we stay responsible for, so the swept value is kept (a key
    # whose 8 closest are all closer than us would migrate + clear)
    key = next(k for k in (_rand_hash(rng) for _ in range(256))
               if not _scalar_republish_decision(dht, k, AF))
    v = Value(b"keep me", value_id=3)
    v.type = 7
    assert dht.storage_store(key, v, clock["t"])
    st = dht.store[key]
    mt = st.maintenance_time
    assert mt == 1000.0 + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME
    reg = telemetry.get_registry()
    due0 = reg.counter("dht_maintenance_due_keys_total").value
    # land exactly on the due time (a multiple of the calendar quantum,
    # so the bin job is due at this very instant too)
    assert mt % STORAGE_CALENDAR_QUANTUM == 0
    clock["t"] = mt
    dht.scheduler.run()
    assert st.maintenance_time == mt + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME, \
        "key landing exactly on maintenance_time was not republished"
    assert reg.counter("dht_maintenance_due_keys_total").value > due0
    # the value survived (it expires at t+3600, long past the sweep)
    assert dht.get_local(key)


def test_calendar_sweep_announces_when_no_longer_responsible():
    clock = {"t": 2000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"])
    rng = np.random.default_rng(19)
    _fill_table(dht, rng, 40)
    dht.types.register_type(ValueType(7, "long", expiration=3600.0))
    # pick keys the predicate marks due (all 8 closest closer than us)
    keys = [k for k in (_rand_hash(rng) for _ in range(64))
            if _scalar_republish_decision(dht, k, AF)][:4]
    assert keys, "table too small to ever lose responsibility"
    for key in keys:
        v = Value(b"migrate", value_id=9)
        v.type = 7
        assert dht.storage_store(key, v, clock["t"])
        dht.store[key].maintenance_time = clock["t"]        # force due
    announced = dht._storage_maintenance_batched(keys)
    assert announced == len(keys)
    # not responsible in the only family → local copies were cleared
    for key in keys:
        assert not dht.get_local(key)


def test_scheduler_heap_o1_in_stored_keys():
    """The round-10 planner: per-key _data_persistence/_expire_storage
    jobs are gone — K stored keys cost O(occupied calendar bins) heap
    entries, not O(K)."""
    clock = {"t": 5000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"])
    base = len(dht.scheduler._heap)
    n = 1000
    for i in range(n):
        assert dht.storage_store(InfoHash.get(f"cal-{i}"),
                                 Value(b"v", value_id=1), clock["t"])
    grown = len(dht.scheduler._heap) - base
    # all keys share one expiry bin + one maintenance bin (same store
    # instant); a generous band still catches any per-key scheduling
    assert grown <= 8, \
        f"heap grew {grown} entries for {n} stored keys (per-key jobs?)"
    assert len(dht.store) == n


def test_calendar_never_republishes_listen_created_storage():
    """The reference arms dataPersistence ONLY for storages created by
    storageStore (dht.cpp:1193-1228); a listen-created storage that
    later receives values must not be republish-swept — and in
    particular must NOT be cleared by a not-responsible decision."""
    clock = {"t": 4000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"])
    rng = np.random.default_rng(21)
    _fill_table(dht, rng, 40)
    dht.types.register_type(ValueType(7, "long", expiration=3600.0))
    # a key we are NOT responsible for (the clear-risk case)
    key = next(k for k in (_rand_hash(rng) for _ in range(256))
               if _scalar_republish_decision(dht, k, AF))
    dht.listen(key, lambda vals, expired: True)     # creates the storage
    st = dht.store[key]
    assert not st.maintenance_armed
    v = Value(b"listener copy", value_id=4)
    v.type = 7
    assert dht.storage_store(key, v, clock["t"])    # existing-st branch
    assert not st.maintenance_armed
    reg = telemetry.get_registry()
    due0 = reg.counter("dht_maintenance_due_keys_total").value
    # drive past maintenance_time (st was created with it = creation
    # time) and fire the expiry bin
    clock["t"] += 600.0 + 2 * STORAGE_CALENDAR_QUANTUM
    dht.scheduler.run()
    assert reg.counter("dht_maintenance_due_keys_total").value == due0
    assert dht.get_local(key), "listen-created storage was swept away"


def test_calendar_fire_survives_raising_listener():
    """A raising local-listener callback mid-bin must not drop the rest
    of the bin's keys (the per-key jobs lost only the raising key)."""
    clock = {"t": 6000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"], maintain=False)
    keys = [InfoHash.get(f"bin-{i}") for i in range(8)]
    for key in keys:
        assert dht.storage_store(key, Value(b"v", value_id=1), clock["t"])
    # a listener whose expiry push raises, on the lexicographically
    # FIRST key so the failure hits before the rest of the bin
    first = sorted(keys, key=bytes)[0]

    def boom(vals, expired):
        if expired:
            raise RuntimeError("listener exploded")
        return True
    dht.listen(first, boom)
    clock["t"] += 600.0 + STORAGE_CALENDAR_QUANTUM
    with pytest.raises(RuntimeError):
        dht.scheduler.run()
    # the untouched keys were re-binned — the next tick expires them
    clock["t"] += STORAGE_CALENDAR_QUANTUM
    dht.scheduler.run()
    for key in keys:
        if key != first:
            assert not dht.get_local(key), "re-binned key never expired"


def test_calendar_expires_values():
    """Value expiry rides the same calendar: a stored value is swept at
    (or within one quantum after) its expiration, listeners told."""
    clock = {"t": 3000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"], maintain=False)
    key = InfoHash.get("ephemeral")
    assert dht.storage_store(key, Value(b"gone", value_id=2), clock["t"])
    heard = []
    dht.listen(key, lambda vals, expired:
               heard.extend((v.data, expired) for v in vals) or True)
    assert (b"gone", False) in heard
    # default type expiry is 10 min; step past it plus one bin
    clock["t"] = 3000.0 + 600.0 + STORAGE_CALENDAR_QUANTUM
    dht.scheduler.run()
    assert not dht.get_local(key)
    assert (b"gone", True) in heard, "expiry never pushed to the listener"


# --------------------------------------------------- fused bucket refresh

def test_bucket_maintenance_fires_batched_finds():
    clock = {"t": 100.0}
    dht, sent = _make_dht(clock=lambda: clock["t"], maintain=False)
    rng = np.random.default_rng(20)
    _fill_table(dht, rng, 30, now=100.0)
    reg = telemetry.get_registry()
    sweeps0 = reg.counter("dht_maintenance_sweeps_total").value
    finds0 = reg.counter("dht_maintenance_refresh_sent_total").value
    # nothing stale yet → a sweep runs but nothing is sent
    assert dht._bucket_maintenance(AF) is False
    assert reg.counter("dht_maintenance_sweeps_total").value == sweeps0 + 1
    # age every bucket past the 10-min rule → refresh finds hit the wire
    clock["t"] = 100.0 + NODE_EXPIRE_TIME + 1
    dht.scheduler.sync_time()
    n_wire0 = len(sent)
    assert dht._bucket_maintenance(AF) is True
    assert len(sent) > n_wire0, "refresh find_nodes never hit the wire"
    assert reg.counter(
        "dht_maintenance_refresh_sent_total").value > finds0


def test_direct_data_persistence_does_not_enroll_unarmed_storage():
    """A direct _data_persistence call on a listen-created (unarmed)
    storage republishes once but must NOT enroll the key in the
    recurring calendar sweep — storage_store owns arming."""
    clock = {"t": 7000.0}
    dht, _ = _make_dht(clock=lambda: clock["t"])
    rng = np.random.default_rng(22)
    _fill_table(dht, rng, 40)
    dht.types.register_type(ValueType(7, "long", expiration=3600.0))
    key = next(k for k in (_rand_hash(rng) for _ in range(256))
               if not _scalar_republish_decision(dht, k, AF))
    dht.listen(key, lambda vals, expired: True)
    st = dht.store[key]
    v = Value(b"copy", value_id=5)
    v.type = 7
    assert dht.storage_store(key, v, clock["t"])
    assert not st.maintenance_armed
    clock["t"] = st.maintenance_time + 1
    dht.scheduler.sync_time()
    dht._data_persistence(key)                      # explicit one-shot
    assert not st.maintenance_armed, \
        "direct _data_persistence permanently enrolled an unarmed storage"
    reg = telemetry.get_registry()
    due_after_direct = reg.counter("dht_maintenance_due_keys_total").value
    # the calendar entry the one-shot added must keep SKIPPING the
    # unarmed key at every subsequent fire
    clock["t"] = st.maintenance_time + STORAGE_CALENDAR_QUANTUM
    dht.scheduler.run()
    assert reg.counter(
        "dht_maintenance_due_keys_total").value == due_after_direct
    assert dht.get_local(key)
