"""SecureDht overlay tests: signed/encrypted puts over the virtual network,
certificate discovery, and the secure store/edit policies
(↔ reference src/securedht.cpp behavior; no direct reference test exists for
this layer beyond python binding smoke tests, so coverage here is broader)."""

import pytest

from opendht_tpu import crypto
from opendht_tpu.core.value import Filters, Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime.config import Config
from opendht_tpu.runtime.secure_dht import (
    CERTIFICATE_TYPE, SecureDht, secure_node_id)

from opendht_tpu.testing import VirtualNet

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


@pytest.fixture(scope="module")
def identities():
    # module-scoped: RSA keygen is the slow part
    return [crypto.generate_identity(f"node{i}", key_length=1024)
            for i in range(3)]


def make_secure_net(identities, n_plain: int = 4):
    """A virtual net with `n_plain` plain nodes + one SecureDht per
    identity, all connected."""
    net = VirtualNet()
    seed = net.add_node()
    for _ in range(n_plain - 1):
        net.add_node()
    secured = []
    for ident in identities:
        d = net.add_node(Config(node_id=secure_node_id(ident.second)))
        secured.append(SecureDht(d, ident))
    net.bootstrap_all(seed)
    assert net.run(90, net.all_connected), "virtual net never connected"
    return net, secured


def test_put_signed_get_verified(identities):
    net, (a, b, _) = make_secure_net(identities)
    key = InfoHash.get("signed-key")
    v = Value(b"signed payload")

    done = {}
    a.put_signed(key, v, lambda ok, ns: done.update(ok=ok))
    assert net.run(90, lambda: "ok" in done), "put_signed never completed"
    assert done["ok"]
    assert v.seq >= 0 and v.is_signed()

    got = []
    b.get(key, lambda vals: got.extend(vals) or True)
    assert net.run(60, lambda: got), "get never saw the signed value"
    assert got[0].data == b"signed payload"
    assert got[0].is_signed() and got[0].check_signature()
    assert got[0].owner.get_id() == a.get_id()
    # sender's key was cached during verification
    assert b.get_public_key(a.get_id()) is not None


def test_put_signed_bumps_seq(identities):
    net, (a, b, _) = make_secure_net(identities)
    key = InfoHash.get("seq-key")
    v1 = Value(b"version one")
    v1.id = 7
    done1 = {}
    a.put_signed(key, v1, lambda ok, ns: done1.update(ok=ok))
    assert net.run(90, lambda: "ok" in done1) and done1["ok"]
    seq1 = v1.seq

    v2 = Value(b"version two")
    v2.id = 7
    done2 = {}
    a.put_signed(key, v2, lambda ok, ns: done2.update(ok=ok))
    assert net.run(90, lambda: "ok" in done2) and done2["ok"]
    assert v2.seq > seq1

    # the network converges on the newer version
    got = []
    b.get(key, lambda vals: got.extend(vals) or True,
          f=Filters.id_filter(7))
    assert net.run(60, lambda: got)
    assert all(v.data == b"version two" for v in got)


def test_put_encrypted_only_recipient_reads(identities):
    net, (a, b, c) = make_secure_net(identities)
    key = InfoHash.get("encrypted-key")
    v = Value(b"for bob only")

    done = {}
    a.put_encrypted(key, b.get_id(), v, lambda ok, ns: done.update(ok=ok))
    assert net.run(120, lambda: "ok" in done), "put_encrypted never completed"
    assert done["ok"]

    got_b, got_c = [], []
    b.get(key, lambda vals: got_b.extend(vals) or True)
    assert net.run(60, lambda: got_b), "recipient never decrypted the value"
    assert got_b[0].data == b"for bob only"
    assert got_b[0].owner.get_id() == a.get_id()

    # third party can't open it: the encrypted value is dropped
    state = {}
    c.get(key, lambda vals: got_c.extend(vals) or True,
          done_cb=lambda ok, ns: state.update(done=True))
    assert net.run(60, lambda: "done" in state)
    assert not got_c

    # but the raw (unwrapped) dht sees the cypher blob — it was stored
    raw = []
    c._dht.get(key, lambda vals: raw.extend(vals) or True)
    assert net.run(60, lambda: raw)
    assert raw[0].is_encrypted()


def test_find_certificate(identities):
    net, (a, b, _) = make_secure_net(identities)
    # a's constructor published its certificate; let it announce
    net.settle(5.0)
    found = []
    b.find_certificate(a.get_id(), found.append)
    assert net.run(90, lambda: found), "find_certificate never returned"
    assert found[0] is not None
    assert found[0].get_id() == a.get_id()
    # second lookup hits the cache synchronously
    again = []
    b.find_certificate(a.get_id(), again.append)
    assert again and again[0].get_id() == a.get_id()


def test_certificate_type_policy(identities):
    """CERTIFICATE_TYPE store policy: only at the matching key."""
    ident = identities[0]
    v = Value(ident.second.pack())
    v.type = CERTIFICATE_TYPE.id
    ok_key = ident.second.get_id()
    bad_key = InfoHash.get("not the key")
    assert CERTIFICATE_TYPE.store_policy(ok_key, v, None, None)
    assert not CERTIFICATE_TYPE.store_policy(bad_key, v, None, None)


def test_secure_store_policy_rejects_bad_signature(identities):
    net, (a, b, _) = make_secure_net(identities)
    key = InfoHash.get("tamper-key")
    v = Value(b"authentic")
    v.seq = 0
    v.sign(identities[0].first)
    v.data = b"tampered!!"           # invalidates the signature

    # push the tampered value through the plain dht put path
    done = {}
    a._dht.put(key, v, lambda ok, ns: done.update(ok=ok))
    net.run(90, lambda: "ok" in done)

    got = []
    b.get(key, lambda vals: got.extend(vals) or True)
    state = {}
    b.get(key, lambda vals: True, lambda ok, ns: state.update(done=True))
    assert net.run(60, lambda: "done" in state)
    assert not got, "tampered signed value should never be stored/surfaced"


def test_edit_policy_requires_increasing_seq(identities):
    net, secured = make_secure_net(identities, n_plain=2)
    a = secured[0]
    # exercise the secure edit policy directly on a plain node's type store
    vt = a._dht.types.get_type(Value(b"").type)   # USER_DATA secured
    key = InfoHash.get("edit")
    old = Value(b"old")
    old.seq = 5
    old.sign(identities[0].first)
    new_ok = Value(b"new")
    new_ok.seq = 6
    new_ok.sign(identities[0].first)
    new_stale = Value(b"stale")
    new_stale.seq = 4
    new_stale.sign(identities[0].first)
    other = Value(b"other owner")
    other.seq = 7
    other.sign(identities[1].first)

    assert vt.edit_policy(key, old, new_ok, None, None)
    assert not vt.edit_policy(key, old, new_stale, None, None)
    assert not vt.edit_policy(key, old, other, None, None)
    # same seq + identical body may be re-announced
    same = Value(b"old")
    same.seq = 5
    same.sign(identities[0].first)
    assert vt.edit_policy(key, old, same, None, None)
