"""Device ID kernels vs the scalar host oracle.

The host :class:`opendht_tpu.infohash.InfoHash` implements the reference
semantics (include/opendht/infohash.h) scalar-wise; these tests check the
vectorized uint32-limb kernels produce bit-identical results, including
the exact vectors from tests/infohashtester.cpp.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opendht_tpu.infohash import InfoHash
from opendht_tpu.ops import ids as K

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


def _rand_hashes(n, seed):
    rng = np.random.default_rng(seed)
    raw = rng.integers(0, 256, size=(n, 20), dtype=np.uint8)
    # sprinkle structured cases: zeros, shared prefixes, single bits
    raw[0] = 0
    raw[1] = 0
    raw[1][19] = 0x10
    raw[2] = 0
    raw[2][0] = 0x01
    if n > 4:
        raw[4] = raw[3]  # exact duplicate
    if n > 6:
        raw[6][:10] = raw[5][:10]  # long shared prefix
    return [InfoHash(bytes(r)) for r in raw], raw


def test_bytes_roundtrip():
    hashes, raw = _rand_hashes(64, 0)
    limbs = K.ids_from_bytes(raw)
    assert limbs.shape == (64, 5)
    assert limbs.dtype == np.uint32
    back = K.ids_to_bytes(limbs)
    np.testing.assert_array_equal(back, raw)
    limbs2 = K.ids_from_hashes(hashes)
    np.testing.assert_array_equal(limbs, limbs2)


def test_lex_ordering_matches_bytes():
    hashes, raw = _rand_hashes(128, 1)
    a = jnp.asarray(K.ids_from_bytes(raw))
    b = jnp.roll(a, 1, axis=0)
    hb = hashes[-1:] + hashes[:-1]
    want_lt = np.array([x._b < y._b for x, y in zip(hashes, hb)])
    want_cmp = np.array([InfoHash.cmp(x, y) for x, y in zip(hashes, hb)])
    np.testing.assert_array_equal(np.asarray(K.lex_lt(a, b)), want_lt)
    np.testing.assert_array_equal(np.asarray(K.lex_cmp(a, b)), want_cmp)
    np.testing.assert_array_equal(
        np.asarray(K.lex_eq(a, b)), np.array([x == y for x, y in zip(hashes, hb)])
    )


def test_xor_cmp_parity_including_cpp_vectors():
    # exact vectors from tests/infohashtester.cpp:125-138
    null_h = InfoHash()
    min_h = InfoHash("0000000000000000000000000000000000000010")
    max_h = InfoHash("0100000000000000000000000000000000000000")
    triples = [
        (min_h, null_h, max_h, -1),
        (min_h, max_h, null_h, 1),
        (min_h, min_h, max_h, -1),
        (min_h, max_h, min_h, 1),
        (null_h, min_h, max_h, -1),
        (null_h, max_h, min_h, 1),
        (max_h, null_h, min_h, -1),  # circular distance
        (max_h, min_h, null_h, 1),
    ]
    s = K.ids_from_hashes([t[0] for t in triples])
    a = K.ids_from_hashes([t[1] for t in triples])
    b = K.ids_from_hashes([t[2] for t in triples])
    got = np.asarray(K.xor_cmp(jnp.asarray(s), jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(got, [t[3] for t in triples])

    # property test vs scalar oracle
    hashes, raw = _rand_hashes(200, 2)
    rng = np.random.default_rng(3)
    idx = rng.integers(0, len(hashes), size=(500, 3))
    s = jnp.asarray(K.ids_from_bytes(raw[idx[:, 0]]))
    a = jnp.asarray(K.ids_from_bytes(raw[idx[:, 1]]))
    b = jnp.asarray(K.ids_from_bytes(raw[idx[:, 2]]))
    got = np.asarray(K.xor_cmp(s, a, b))
    want = np.array(
        [hashes[i].xor_cmp(hashes[j], hashes[k]) for i, j, k in idx]
    )
    np.testing.assert_array_equal(got, want)


def test_common_bits_parity():
    # cpp vectors (tests/infohashtester.cpp:114-122)
    null_h = InfoHash()
    min_h = InfoHash("0000000000000000000000000000000000000010")
    max_h = InfoHash("0100000000000000000000000000000000000000")
    pairs = [(null_h, null_h, 160), (null_h, min_h, 155), (null_h, max_h, 7), (min_h, max_h, 7)]
    a = jnp.asarray(K.ids_from_hashes([p[0] for p in pairs]))
    b = jnp.asarray(K.ids_from_hashes([p[1] for p in pairs]))
    np.testing.assert_array_equal(np.asarray(K.common_bits(a, b)), [p[2] for p in pairs])

    hashes, raw = _rand_hashes(100, 4)
    rng = np.random.default_rng(5)
    idx = rng.integers(0, len(hashes), size=(400, 2))
    a = jnp.asarray(K.ids_from_bytes(raw[idx[:, 0]]))
    b = jnp.asarray(K.ids_from_bytes(raw[idx[:, 1]]))
    got = np.asarray(K.common_bits(a, b))
    want = np.array([InfoHash.common_bits(hashes[i], hashes[j]) for i, j in idx])
    np.testing.assert_array_equal(got, want)


def test_lowbit_parity():
    # cpp vectors (tests/infohashtester.cpp:104-111)
    vec = [
        (InfoHash(), -1),
        (InfoHash("0000000000000000000000000000000000000010"), 155),
        (InfoHash("0100000000000000000000000000000000000000"), 7),
    ]
    a = jnp.asarray(K.ids_from_hashes([v[0] for v in vec]))
    np.testing.assert_array_equal(np.asarray(K.lowbit(a)), [v[1] for v in vec])

    hashes, raw = _rand_hashes(300, 6)
    a = jnp.asarray(K.ids_from_bytes(raw))
    got = np.asarray(K.lowbit(a))
    want = np.array([h.lowbit() for h in hashes])
    np.testing.assert_array_equal(got, want)


def test_get_set_bit_parity():
    hashes, raw = _rand_hashes(64, 7)
    a = jnp.asarray(K.ids_from_bytes(raw))
    rng = np.random.default_rng(8)
    bits = rng.integers(0, 160, size=64)
    got = np.asarray(K.get_bit(a, jnp.asarray(bits)))
    want = np.array([h.get_bit(int(b)) for h, b in zip(hashes, bits)])
    np.testing.assert_array_equal(got, want)

    vals = rng.integers(0, 2, size=64).astype(bool)
    got_set = K.set_bit(a, jnp.asarray(bits), jnp.asarray(vals))
    want_set = np.stack(
        [K.ids_from_bytes(bytes(h.set_bit(int(b), bool(v))))[0]
         for h, b, v in zip(hashes, bits, vals)]
    )
    np.testing.assert_array_equal(np.asarray(got_set), want_set)


def test_bit_kernels():
    x = jnp.asarray(
        np.array([0, 1, 2, 3, 0x80000000, 0xFFFFFFFF, 0x00010000], dtype=np.uint32)
    )
    np.testing.assert_array_equal(np.asarray(K.popcount32(x)), [0, 1, 1, 2, 1, 32, 1])
    np.testing.assert_array_equal(np.asarray(K.clz32(x)), [32, 31, 30, 30, 0, 0, 15])
    np.testing.assert_array_equal(np.asarray(K.ctz32(x)), [32, 0, 1, 0, 31, 0, 16])


def test_jit_and_vmap_compat():
    hashes, raw = _rand_hashes(32, 9)
    a = jnp.asarray(K.ids_from_bytes(raw))
    b = jnp.flip(a, axis=0)
    jcb = jax.jit(K.common_bits)
    np.testing.assert_array_equal(np.asarray(jcb(a, b)), np.asarray(K.common_bits(a, b)))
    vlow = jax.vmap(K.lowbit)
    np.testing.assert_array_equal(
        np.asarray(vlow(a.reshape(4, 8, 5))), np.asarray(K.lowbit(a)).reshape(4, 8)
    )


def test_random_ids_shape_dtype():
    out = K.random_ids(jax.random.key(0), 16)
    assert out.shape == (16, 5)
    assert out.dtype == jnp.uint32
