"""Python API surface parity with the reference binding
(python/opendht.pyx class list) plus NodeSet behavior."""

import opendht_tpu as o
import pytest

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


PYX_SURFACE = [
    "Certificate", "DhtConfig", "DhtRunner", "Identity", "IndexValue",
    "InfoHash", "ListenToken", "Node", "NodeEntry", "NodeSet", "Pht",
    "PrivateKey", "PublicKey", "Query", "Select", "SockAddr", "TrustList",
    "Value", "VerifyResult", "Where",
]
# the crypto-backed subset resolves lazily and needs the optional
# ``cryptography`` wheel (opendht_tpu/__init__.py _LAZY_EXPORTS)
PYX_SURFACE_CRYPTO = frozenset({
    "Certificate", "DhtRunner", "Identity", "PrivateKey", "PublicKey",
    "TrustList", "VerifyResult",
})


def test_pyx_class_surface_present():
    # the non-crypto surface must exist on EVERY host — that is the
    # lazy-import contract — so it is asserted unconditionally...
    missing = [n for n in PYX_SURFACE
               if n not in PYX_SURFACE_CRYPTO and not hasattr(o, n)]
    assert not missing, missing
    # ...and only the crypto-backed names skip where the wheel is absent
    pytest.importorskip("cryptography")
    missing = [n for n in PYX_SURFACE_CRYPTO if not hasattr(o, n)]
    assert not missing, missing


def test_nodeset_sorted_semantics():
    ns = o.NodeSet()
    ids = [o.InfoHash.get(s) for s in ("x", "y", "z")]
    assert ns.insert(ids[1])
    assert not ns.insert(ids[1])            # duplicate: map semantics
    ns.extend([(ids[0], None), o.NodeEntry(ids[2])])
    assert len(ns) == 3
    ordered = [e.id for e in ns]
    assert ordered == sorted(ids, key=bytes)
    assert ns.first() == ordered[0] and ns.last() == ordered[-1]
    assert ids[0] in ns
    assert str(ns).count("\n") == 2
