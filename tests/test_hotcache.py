"""Hot-key serving cache + adaptive replication (ISSUE-11,
opendht_tpu/hotcache.py + ops/cache_probe.py).

Pins the tentpole's contracts: the batched XOR-compare probe kernel
against its bit-exact host oracle (single-device AND the t-sharded
twin), the admission/eviction/invalidation state machine keyed off the
keyspace observatory tick, the serve-from-cache fast path (a hot get
completes without the ``[Q]`` lookup launch; cache-on == cache-off
values; batching-off takes the identical decision), put-then-get
freshness, the replica widen/narrow decision vs a scalar oracle, the
degrade-only health signal + dhtmon gate contracts, and kernels
bit-identical with the cache active."""

from __future__ import annotations

import socket as _socket

import numpy as np
import pytest

from opendht_tpu import telemetry
from opendht_tpu.core.value import Value
from opendht_tpu.hotcache import HotCacheConfig, HotValueCache
from opendht_tpu.infohash import InfoHash
from opendht_tpu.ops.cache_probe import cache_probe, probe_host
from opendht_tpu.ops.ids import ids_from_hashes
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.runtime.live_search import SEARCH_NODES, TARGET_NODES
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

AF = _socket.AF_INET


# ------------------------------------------------------------ test helpers
def make_dht(clock, n_nodes=12, **cfg_kw):
    """A v4-only Dht on a virtual clock with a populated table and a
    swallow-everything transport (the test_wave_builder harness)."""
    cfg = Config(**cfg_kw)
    dht = Dht(lambda data, addr: 0, config=cfg,
              scheduler=Scheduler(clock=lambda: clock["t"]),
              has_v6=False)
    rng = np.random.default_rng(1234)
    table = dht.tables[AF]
    added = 0
    while added < n_nodes:
        h = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        if table.insert(h, SockAddr("10.9.0.%d" % (added + 1), 4500),
                        now=clock["t"], confirm=2) is not None:
            added += 1
    return dht


def warm(dht, key, observations=40):
    """Drive the observatory hot rule for ``key`` and tick so the cache
    admits it (needs a locally stored value to be store-backed)."""
    for _ in range(observations):
        dht.keyspace.observe_hashes([key])
    dht.keyspace.tick()


def top_entry(key, estimate=100, hot=True):
    return {"key": bytes(key).hex(), "_key": bytes(key),
            "estimate": estimate, "share": 0.5, "hot": hot}


def fresh_registry(monkeypatch):
    reg = telemetry.MetricsRegistry()
    reg.enabled = True
    monkeypatch.setattr(telemetry, "_registry", reg, raising=False)
    monkeypatch.setattr(telemetry, "get_registry", lambda: reg)
    return reg


# ============================================================ probe kernel
def test_probe_kernel_matches_host_oracle():
    """Membership + slot from the device XOR-compare EQUAL the numpy
    mirror over members, non-members, duplicates and invalid slots."""
    rng = np.random.default_rng(7)
    cache_ids = rng.integers(0, 2**32, (64, 5), dtype=np.uint32)
    valid = np.ones(64, bool)
    valid[50:] = False                      # tail slots invalid
    targets = np.concatenate([
        cache_ids[[3, 17, 3, 49]],          # members (one duplicated)
        cache_ids[[55]],                    # id present but slot invalid
        rng.integers(0, 2**32, (9, 5), dtype=np.uint32),   # misses
    ])
    dh, ds = cache_probe(cache_ids, valid, targets)
    hh, hs = probe_host(cache_ids, valid, targets)
    assert np.array_equal(np.asarray(dh), hh)
    assert np.array_equal(np.asarray(ds), hs)
    assert list(hh[:4]) == [True] * 4 and list(hs[:4]) == [3, 17, 3, 49]
    assert not hh[4]                        # invalid slot never matches
    assert not hh[5:].any()


def test_probe_empty_and_single_target():
    rng = np.random.default_rng(8)
    cache_ids = np.zeros((16, 5), np.uint32)
    valid = np.zeros(16, bool)
    targets = rng.integers(0, 2**32, (5, 5), dtype=np.uint32)
    dh, ds = cache_probe(cache_ids, valid, targets)
    assert not np.asarray(dh).any() and (np.asarray(ds) == -1).all()
    # an all-zero target against an all-zero INVALID table still misses
    dh, _ = cache_probe(cache_ids, valid, np.zeros((1, 5), np.uint32))
    assert not np.asarray(dh).any()


def test_sharded_probe_twin_bit_identical():
    """tp twin == single-device probe == host oracle, incl. ragged Q
    (pad rows sliced off)."""
    from opendht_tpu.parallel.sharded import (make_mesh,
                                              sharded_cache_probe)
    rng = np.random.default_rng(9)
    cache_ids = rng.integers(0, 2**32, (32, 5), dtype=np.uint32)
    valid = rng.random(32) < 0.8
    mesh = make_mesh(4, q=1, t=4)
    for q in (1, 5, 64):                    # ragged and aligned widths
        targets = np.concatenate([
            cache_ids[rng.integers(0, 32, max(1, q // 2))],
            rng.integers(0, 2**32, (q - max(1, q // 2), 5),
                         dtype=np.uint32),
        ])[:q]
        hh, hs = probe_host(cache_ids, valid, targets)
        sh, ss = sharded_cache_probe(mesh, cache_ids, valid, targets)
        assert np.array_equal(sh, hh) and np.array_equal(ss, hs), q


# ===================================================== cache state machine
def test_admission_eviction_and_window(monkeypatch):
    fresh_registry(monkeypatch)
    store = {}
    now = {"t": 0.0}
    hc = HotValueCache(HotCacheConfig(capacity=4, entry_ttl=10.0),
                       local_values=lambda kb: store.get(kb, []),
                       clock=lambda: now["t"])
    k1, k2 = InfoHash.get("hc-a"), InfoHash.get("hc-b")
    store[bytes(k1)] = [Value(b"a", value_id=1)]
    # k1 has local values -> admitted; k2 has none -> hot but uncached
    hc.on_keyspace_tick([top_entry(k1), top_entry(k2)])
    snap = hc.snapshot()
    assert snap["occupancy"] == 1
    assert [e["key"] for e in snap["entries"]] == [bytes(k1).hex()]
    assert all(e["store_backed"] for e in snap["entries"])
    assert hc.is_hot(k1) and hc.is_hot(k2)
    assert hc.wants(k2) and not hc.wants(k1)
    # serving k1 hits; k2 (uncached) misses
    assert [v.data for v in hc.serve_one(k1)] == [b"a"]
    assert hc.serve_one(k2) is None
    # window rolls on the next tick: 1 hit / 2 probes
    hc.on_keyspace_tick([top_entry(k1), top_entry(k2)])
    assert hc.hit_ratio() == 0.5
    # decay: k1 drops out of the hot set -> evicted, narrow
    hc.on_keyspace_tick([])
    assert hc.snapshot()["occupancy"] == 0
    assert not hc.is_hot(k1)
    assert hc.hit_ratio() is None           # empty window = unknown


def test_offer_fill_on_get_and_ttl_expiry(monkeypatch):
    fresh_registry(monkeypatch)
    now = {"t": 0.0}
    hc = HotValueCache(HotCacheConfig(entry_ttl=5.0),
                       local_values=lambda kb: [],
                       clock=lambda: now["t"])
    k = InfoHash.get("hc-offer")
    assert not hc.offer(k, [Value(b"x", value_id=1)])   # not hot yet
    hc.on_keyspace_tick([top_entry(k)])
    assert hc.wants(k)
    assert hc.offer(k, [Value(b"x", value_id=1)])
    assert not hc.offer(k, [Value(b"y", value_id=2)])   # already cached
    assert [v.id for v in hc.serve_one(k)] == [1]
    # no store backing: the entry expires after entry_ttl on a tick
    now["t"] = 6.0
    hc.on_keyspace_tick([top_entry(k)])
    assert hc.snapshot()["occupancy"] == 0


def test_capacity_bound_keeps_hottest(monkeypatch):
    fresh_registry(monkeypatch)
    store = {}
    hc = HotValueCache(HotCacheConfig(capacity=2),
                       local_values=lambda kb: store.get(kb, []),
                       clock=lambda: 0.0)
    keys = [InfoHash.get("hc-cap-%d" % i) for i in range(4)]
    for k in keys:
        store[bytes(k)] = [Value(b"v", value_id=1)]
    # estimate order: keys[0] hottest
    hc.on_keyspace_tick([top_entry(k, estimate=100 - i)
                         for i, k in enumerate(keys)])
    snap = hc.snapshot()
    assert snap["occupancy"] == 2
    kept = set(e["key"] for e in snap["entries"])
    assert kept == {bytes(keys[0]).hex(), bytes(keys[1]).hex()}


def test_invalidate_drops_entry_and_counts(monkeypatch):
    fresh_registry(monkeypatch)
    store = {}
    hc = HotValueCache(HotCacheConfig(),
                       local_values=lambda kb: store.get(kb, []),
                       clock=lambda: 0.0)
    k = InfoHash.get("hc-inv")
    store[bytes(k)] = [Value(b"v", value_id=1)]
    hc.on_keyspace_tick([top_entry(k)])
    assert hc.snapshot()["occupancy"] == 1
    assert hc.invalidate(k)
    assert not hc.invalidate(k)             # idempotent
    snap = hc.snapshot()
    assert snap["occupancy"] == 0 and snap["invalidations"] == 1
    # the key is STILL hot: the next tick re-admits from the store
    hc.on_keyspace_tick([top_entry(k)])
    assert hc.snapshot()["occupancy"] == 1


def test_probe_wave_counts_only_eligible(monkeypatch):
    fresh_registry(monkeypatch)
    store = {}
    hc = HotValueCache(HotCacheConfig(),
                       local_values=lambda kb: store.get(kb, []),
                       clock=lambda: 0.0)
    k_hot, k_cold = InfoHash.get("hc-el-a"), InfoHash.get("hc-el-b")
    store[bytes(k_hot)] = [Value(b"v", value_id=1)]
    hc.on_keyspace_tick([top_entry(k_hot)])
    served = hc.probe_wave([k_hot, k_cold, k_hot], [True, True, False])
    assert served[0] is not None and [v.id for v in served[0]] == [1]
    assert served[1] is None
    assert served[2] is None                # hit, but INELIGIBLE: not served
    snap = hc.snapshot()
    assert snap["hits"] == 1 and snap["misses"] == 1


def test_probe_go_dark_on_device_failure(monkeypatch):
    fresh_registry(monkeypatch)
    store = {}
    hc = HotValueCache(HotCacheConfig(),
                       local_values=lambda kb: store.get(kb, []),
                       clock=lambda: 0.0)
    k = InfoHash.get("hc-dark")
    store[bytes(k)] = [Value(b"v", value_id=1)]
    hc.on_keyspace_tick([top_entry(k)])
    import opendht_tpu.ops.cache_probe as cp

    def boom(*a, **kw):
        raise RuntimeError("device gone")
    monkeypatch.setattr(cp, "cache_probe", boom)
    served = hc.probe_wave([k], [True])
    assert served == [None]                 # wave proceeds unchanged
    assert not hc.enabled and not hc.active()
    assert hc.snapshot() == {"enabled": False} or \
        hc.snapshot().get("enabled") is False
    assert hc.hit_ratio() is None and hc.serve_one(k) is None
    assert hc.replica_k(k) == hc.cfg.base_k  # dark cache never widens


def test_disabled_cache_registers_no_series(monkeypatch):
    reg = fresh_registry(monkeypatch)
    HotValueCache(HotCacheConfig(enabled=False), clock=lambda: 0.0)
    assert not any(k.startswith("dht_cache") for k in
                   reg.snapshot()["gauges"])


# ======================================================== Dht integration
def spy_batched(dht):
    # the launch seam covers both pipeline depths (round 20): the
    # depth-1 sync path delegates to it and the pipeline dispatches
    # through it directly — a cache-served get must skip BOTH
    calls = []
    orig = dht.find_closest_nodes_launch

    def wrapper(targets, af, count=8):
        calls.append((len(targets), af, count))
        return orig(targets, af, count)

    dht.find_closest_nodes_launch = wrapper
    return calls


def warmed_dht(clock, **cfg_kw):
    """Dht with a locally-stored hot key admitted into the cache."""
    dht = make_dht(clock, **cfg_kw)
    hot = InfoHash.get("hot-int")
    assert dht.storage_store(hot, Value(b"hv", value_id=7), clock["t"])
    warm(dht, hot)
    assert dht.hotcache.snapshot()["occupancy"] == 1
    return dht, hot


def test_cache_served_get_skips_lookup_launch():
    clock = {"t": 1000.0}
    dht, hot = warmed_dht(clock, ingest_fill_target=64,
                          ingest_deadline=0.002)
    calls = spy_batched(dht)
    got, done = [], []
    dht.get(hot, get_cb=lambda vals: got.extend(vals) or True,
            done_cb=lambda ok, ns: done.append(ok))
    dht.scheduler.run()
    clock["t"] += 0.0025
    dht.scheduler.run()                     # deadline wave: probe serves
    assert done == [True]
    assert [v.data for v in got] == [b"hv"]
    assert calls == [], "hot get still joined a lookup launch: %r" % calls
    snap = dht.hotcache.snapshot()
    assert snap["hits"] == 1 and snap["misses"] == 0
    # the search completed and is reusable
    sr = dht.searches[AF][hot]
    assert sr.done and not sr.callbacks


def test_cache_on_off_values_equivalent():
    """The value set a cache-served get delivers equals what the
    cache-off node delivers for the same key/table (the live-cluster
    halves of this pin run in testing/cache_smoke.py)."""
    def run(enabled: bool):
        clock = {"t": 2000.0}
        cfg = {}
        dht = make_dht(clock)
        dht.config.cache.enabled = enabled
        if not enabled:
            dht.hotcache.cfg.enabled = False
        hot = InfoHash.get("hot-eq")
        assert dht.storage_store(hot, Value(b"ev", value_id=3),
                                 clock["t"])
        warm(dht, hot)
        got = []
        dht.get(hot, get_cb=lambda vals: got.extend(vals) or True)
        dht.scheduler.run()
        clock["t"] += 0.0025
        dht.scheduler.run()
        return set((v.id, bytes(v.data)) for v in got)

    assert run(True) == run(False) == {(3, b"ev")}


def test_announce_and_listen_never_cache_served():
    clock = {"t": 3000.0}
    dht, hot = warmed_dht(clock)
    calls = spy_batched(dht)
    # a put's search carries an announce: NOT eligible — the refill
    # must resolve real nodes
    dht.put(hot, Value(b"nv", value_id=9))
    dht.scheduler.run()
    clock["t"] += 0.0025
    dht.scheduler.run()
    assert any(c[2] == SEARCH_NODES for c in calls), \
        "announce refill never launched"
    sr = dht.searches[AF][hot]
    assert sr.announce and not dht._cache_eligible(sr)
    # a listen search is not eligible either
    calls.clear()
    key2 = InfoHash.get("hot-int-2")
    dht.listen(key2, lambda vals, exp: True)
    sr2 = dht.searches[AF][key2]
    assert sr2.listeners and not dht._cache_eligible(sr2)


def test_put_invalidates_cached_entry():
    clock = {"t": 4000.0}
    dht, hot = warmed_dht(clock)
    assert dht.hotcache.snapshot()["occupancy"] == 1
    dht.put(hot, Value(b"v2", value_id=8))
    snap = dht.hotcache.snapshot()
    assert snap["occupancy"] == 0 and snap["invalidations"] >= 1
    # the local store now has BOTH values; the next get delivers them
    # (full path — no stale single-value cache hit)
    got = []
    dht.get(hot, get_cb=lambda vals: got.extend(vals) or True)
    assert set(v.id for v in got) == {7, 8}


def test_batching_off_serve_one_same_decision():
    clock = {"t": 5000.0}
    dht, hot = warmed_dht(clock, ingest_batching="off")
    assert not dht.wave_builder.enabled
    calls = spy_batched(dht)
    got, done = [], []
    dht.get(hot, get_cb=lambda vals: got.extend(vals) or True,
            done_cb=lambda ok, ns: done.append(ok))
    assert done == [True] and [v.data for v in got] == [b"hv"]
    assert calls == []                      # no per-op launch either
    # the host-dict decision == the device probe's (same source of
    # truth; the probe kernel itself is pinned vs probe_host above)
    hc = dht.hotcache
    with hc._lock:
        if hc._dirty or hc._ids_dev is None:
            hc._rebuild_device_locked()
    hh, _ = probe_host(np.asarray(hc._ids_dev), np.asarray(hc._valid_dev),
                       ids_from_hashes([hot]))
    assert bool(hh[0]) == (hc.serve_one(hot) is not None)


def test_wave_results_bit_identical_with_cache_active():
    clock = {"t": 6000.0}
    dht, hot = warmed_dht(clock, n_nodes=24)
    targets = [InfoHash.get("bit-%d" % i) for i in range(16)]
    base = dht.find_closest_nodes_batched(targets, AF, SEARCH_NODES)
    dht.hotcache.probe_wave(targets + [hot], [True] * 17)
    after = dht.find_closest_nodes_batched(targets, AF, SEARCH_NODES)
    assert [[n.id for n in row] for row in base] == \
        [[n.id for n in row] for row in after]


def test_offer_token_rejects_mid_get_invalidation(monkeypatch):
    """Review finding: a get in flight across a put must not re-seed
    the stale pre-put values — invalidate bumps the key's freshness
    token even when nothing is cached, and an offer carrying the older
    token is rejected."""
    fresh_registry(monkeypatch)
    hc = HotValueCache(HotCacheConfig(), local_values=lambda kb: [],
                       clock=lambda: 0.0)
    k = InfoHash.get("hc-token")
    hc.on_keyspace_tick([top_entry(k)])
    tok = hc.offer_token(k)
    assert hc.invalidate(k) is False        # uncached — but the seq bumps
    assert not hc.offer(k, [Value(b"stale", value_id=1)], token=tok)
    assert hc.snapshot()["occupancy"] == 0
    # a fresh token (captured after the put) is accepted
    assert hc.offer(k, [Value(b"fresh", value_id=2)],
                    token=hc.offer_token(k))
    assert [v.id for v in hc.serve_one(k)] == [2]


def test_listen_joining_queued_refill_not_swallowed():
    """Review finding: eligibility decided at submit must be RE-CHECKED
    at serve time — a listen joining the search while its refill sits
    in the wave queue would otherwise have the refill swallowed by a
    cache hit, leaving the search with zero candidates."""
    clock = {"t": 12000.0}
    dht, hot = warmed_dht(clock, ingest_fill_target=64,
                          ingest_deadline=0.002)
    dht.get(hot, get_cb=lambda vals: True)
    sr = dht.searches[AF][hot]
    assert sr.refill_pending
    dht.listen(hot, lambda vals, exp: True)     # joins the SAME search
    assert sr.listeners and not dht._cache_eligible(sr)
    for _ in range(3):                          # fire + re-ridden refill
        clock["t"] += 0.0025
        dht.scheduler.run()
    assert len(sr.nodes) > 0, \
        "queued refill was swallowed by the cache hit"
    assert not sr.expired and sr.listeners


def test_quiet_observatory_ticks_still_roll_cache_window():
    """Review finding: a fully-idle observatory tick (nothing observed,
    window decayed to zero) must still notify subscribers, or the
    cache's windowed hit ratio freezes at its last value and the
    degrade-only health signal never clears."""
    clock = {"t": 13000.0}
    dht, hot = warmed_dht(clock)
    # a miss-heavy window
    dht.hotcache.serve_one(InfoHash.get("q-miss-1"))
    dht.hotcache.serve_one(InfoHash.get("q-miss-2"))
    dht.keyspace.tick()                         # rolls: ratio 0.0
    assert dht.hotcache.hit_ratio() == 0.0
    # decay the window to quiet, then tick with NOTHING observed — the
    # not-dirty path must still notify, rolling the ratio to unknown
    for _ in range(40):
        dht.keyspace.tick()
    # the live accumulator decayed to quiet (the published
    # window_total retains the last SCORED window by design)
    assert dht.keyspace._window_total == 0
    assert dht.hotcache.hit_ratio() is None, \
        "idle ticks froze the hit-ratio window"


# ======================================================== replica widening
def test_replica_k_widens_and_narrows_vs_scalar_oracle():
    clock = {"t": 7000.0}
    dht, hot = warmed_dht(clock)
    cold = InfoHash.get("cold-rk")
    # scalar oracle: k = 16 iff the key is in the observatory hot set
    hot_set = set(dht.keyspace.snapshot()["hot_keys"])

    def oracle(key):
        return 16 if bytes(key).hex() in hot_set else 8

    assert dht._replica_k(hot) == oracle(hot) == 16
    assert dht._replica_k(cold) == oracle(cold) == 8
    # narrow on decay: an empty tick clears the hot set
    dht.hotcache.on_keyspace_tick([])
    assert dht._replica_k(hot) == 8


def test_republish_predicate_widened_matches_scalar_oracle():
    """The ONE widened resolve (max(ks)) gives EVERY key the same
    decision as a per-key scalar resolve at its own k — the top-k
    prefix property, pinned over mixed 8/16 replica sets."""
    clock = {"t": 8000.0}
    dht = make_dht(clock, n_nodes=40)
    keys = [InfoHash.get("rp-%d" % i) for i in range(12)]
    ks = [16 if i % 3 == 0 else 8 for i in range(12)]
    got = dht._republish_predicate(keys, AF, ks)
    for key, k_i, decision in zip(keys, ks, got):
        nodes = dht.find_closest_nodes(key, AF, k_i)
        want = bool(nodes) and key.xor_cmp(nodes[-1].id, dht.myid) < 0
        assert decision == want, (key, k_i)
    # uniform base-k ks is bit-identical to the legacy no-ks call
    assert dht._republish_predicate(keys, AF) == \
        dht._republish_predicate(keys, AF, [TARGET_NODES] * len(keys))


def test_announce_walk_capacity_widens_and_narrows():
    clock = {"t": 9000.0}
    dht, hot = warmed_dht(clock)
    dht.put(hot, Value(b"w", value_id=5))
    sr = dht.searches[AF][hot]
    dht._search_send_announce(sr)
    assert sr.capacity == 16 + (SEARCH_NODES - TARGET_NODES)
    # decay -> narrow back on the next announce pass
    dht.hotcache.on_keyspace_tick([])
    dht._search_send_announce(sr)
    assert sr.capacity == SEARCH_NODES


def test_storage_maintenance_counts_widened_keys(monkeypatch):
    reg = fresh_registry(monkeypatch)
    clock = {"t": 10000.0}
    dht = make_dht(clock, maintain_storage=True)
    hot = InfoHash.get("maint-hot")
    dht.storage_store(hot, Value(b"m", value_id=2), clock["t"])
    warm(dht, hot)
    st = dht.store[hot]
    st.maintenance_time = clock["t"]        # force due NOW
    dht._storage_maintenance_batched([hot])
    assert reg.counter("dht_cache_republish_widened_total").value == 1


# ===================================================== surfaces and gates
def test_health_signal_is_miss_fraction_and_degrade_only():
    from opendht_tpu.health import (DEFAULT_SIGNAL_THRESHOLDS,
                                    HealthConfig, NodeHealth)
    assert "cache_hit_ratio" in DEFAULT_SIGNAL_THRESHOLDS
    assert "cache_hit_ratio" in HealthConfig().degrade_only
    clock = {"t": 11000.0}
    dht, hot = warmed_dht(clock)
    nh = NodeHealth(dht)
    assert nh.evaluator.providers["cache_hit_ratio"]() is None  # no window
    dht.hotcache.serve_one(hot)             # 1 hit
    dht.hotcache.serve_one(InfoHash.get("miss-h"))   # 1 miss
    dht.hotcache.on_keyspace_tick(
        [top_entry(hot)])                   # roll the window
    assert nh.evaluator.providers["cache_hit_ratio"]() == \
        pytest.approx(0.5)                  # miss fraction = 1 - ratio


def test_dhtmon_min_cache_hit_contract(monkeypatch):
    """-1/absent never violates (matching --max-imbalance); a known
    ratio below the gate does, and the worst (min) node decides."""
    from opendht_tpu.tools import dhtmon

    def fake_scrapes(series_list):
        it = iter(series_list)

        def scrape(ep, timeout=10.0):
            return {"endpoint": ep, "ready": True, "verdict": "healthy",
                    "health": {}, "series": next(it)}
        return scrape

    eps = ["a:1", "b:2"]
    # absent + unknown(-1): no violation
    monkeypatch.setattr(dhtmon.hm, "scrape_node", fake_scrapes(
        [{}, {'dht_cache_hit_ratio{node="n"}': -1.0}]))
    viol, doc = dhtmon.run_checks(eps, min_cache_hit=0.9)
    assert viol == [] and doc["cache_hit"]["min"] is None
    # worst node below the gate: violation names it
    monkeypatch.setattr(dhtmon.hm, "scrape_node", fake_scrapes(
        [{'dht_cache_hit_ratio{node="n"}': 0.95},
         {'dht_cache_hit_ratio{node="n"}': 0.4}]))
    viol, doc = dhtmon.run_checks(eps, min_cache_hit=0.9)
    assert len(viol) == 1 and "b:2" in viol[0]
    assert doc["cache_hit"]["min"] == pytest.approx(0.4)
    # both above: green
    monkeypatch.setattr(dhtmon.hm, "scrape_node", fake_scrapes(
        [{'dht_cache_hit_ratio{node="n"}': 0.95},
         {'dht_cache_hit_ratio{node="n"}': 0.92}]))
    viol, _doc = dhtmon.run_checks(eps, min_cache_hit=0.9)
    assert viol == []


def test_scanner_snapshot_has_cache_section():
    from opendht_tpu.tools.dhtscanner import topology_snapshot

    class FakeRunner:
        def get_node_id(self):
            return InfoHash.get("scan-cache")

        def get_bound_port(self):
            return 0

        def get_cache(self):
            return {"enabled": True, "occupancy": 1}

        def get_keyspace(self):
            return {"enabled": False}

        def get_health(self):
            return {"verdict": "unknown"}

        def get_metrics(self):
            return {}

        def get_node_stats(self, af):
            raise OSError

        def get_flight_recorder(self, limit=None):
            return {"events": []}

    snap = topology_snapshot(FakeRunner())
    assert snap["cache"] == {"enabled": True, "occupancy": 1}


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
