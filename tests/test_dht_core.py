"""End-to-end tests of the Dht node core over the virtual network.

Mirrors the reference's integration tier (tests/dhtrunnertester.cpp:30-62:
bootstrap, blocking get sees put, listen) plus deeper protocol checks the
reference leaves to manual tools: token auth, value expiry push, query
projection, per-node storage behavior."""

import socket

import pytest

from opendht_tpu import InfoHash
from opendht_tpu.core.value import Query, Select, Value, Where, Field
from opendht_tpu.runtime import Config, Dht, NodeStatus
from opendht_tpu.sockaddr import SockAddr

from opendht_tpu.testing import VirtualNet

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


def make_net(n: int, **kw) -> VirtualNet:
    net = VirtualNet(**kw)
    seed = net.add_node()
    for _ in range(n - 1):
        net.add_node()
    net.bootstrap_all(seed)
    return net


def test_two_nodes_connect():
    net = make_net(2)
    assert net.run(30, net.all_connected), "nodes never connected"


def test_put_get_roundtrip():
    net = make_net(5)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("hello")
    val = Value(b"some data payload")

    put_state = {}
    nodes[1].put(key, val, lambda ok, ns: put_state.update(ok=ok))
    assert net.run(60, lambda: "ok" in put_state), "put never completed"
    assert put_state["ok"]

    got = []
    done = {}
    nodes[3].get(key, lambda vals: got.extend(vals) or True,
                 lambda ok, ns: done.update(ok=ok))
    assert net.run(60, lambda: "ok" in done), "get never completed"
    assert done["ok"]
    assert any(v.data == b"some data payload" for v in got)


def test_get_missing_key_completes_empty():
    net = make_net(3)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    got, done = [], {}
    nodes[2].get(InfoHash.get("nothing here"),
                 lambda vals: got.extend(vals) or True,
                 lambda ok, ns: done.update(ok=ok))
    assert net.run(60, lambda: "ok" in done)
    assert got == []


def test_listen_sees_remote_put():
    net = make_net(5)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("chatroom")

    heard = []
    token = nodes[2].listen(key, lambda vals, expired:
                            heard.extend((v.data, expired) for v in vals)
                            or True)
    assert token
    net.settle(5)

    nodes[4].put(key, Value(b"first message"))
    assert net.run(60, lambda: (b"first message", False) in heard), \
        "listener never heard the put"

    assert nodes[2].cancel_listen(key, token)


def test_listen_sees_expiry():
    net = make_net(4)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("ephemeral")

    heard = []
    nodes[1].listen(key, lambda vals, expired:
                    heard.extend((v.data, expired) for v in vals) or True)
    net.settle(5)
    nodes[3].put(key, Value(b"gone soon"))
    assert net.run(60, lambda: (b"gone soon", False) in heard)
    # default ValueType expiry is 10 minutes; storage hosts push 'expired'
    assert net.run(15 * 60, lambda: (b"gone soon", True) in heard), \
        "expiry was never pushed to the listener"


def test_query_projection():
    net = make_net(4)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("queried")
    val = Value(b"queried payload", user_type="test/1")
    val.seq = 3

    done = {}
    nodes[1].put(key, val, lambda ok, ns: done.update(ok=ok))
    assert net.run(60, lambda: "ok" in done) and done["ok"]

    fields = []
    qdone = {}
    nodes[2].query(key, lambda fs: fields.extend(fs) or True,
                   lambda ok, ns: qdone.update(ok=ok),
                   Query(Select().field(Field.ID).field(Field.SEQ_NUM)))
    assert net.run(60, lambda: "ok" in qdone)
    assert any(fv.index.get(Field.SEQ_NUM) is not None
               and fv.index[Field.SEQ_NUM].value == 3 for fv in fields)


def test_value_stored_on_closest_nodes():
    net = make_net(8)
    assert net.run(120, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("replicated")
    done = {}
    nodes[0].put(key, Value(b"replica"), lambda ok, ns: done.update(ok=ok))
    assert net.run(60, lambda: "ok" in done) and done["ok"]
    holders = sum(1 for d in nodes if d.get_local(key))
    # k=8 net of 8 nodes: every (or nearly every) node should hold it
    assert holders >= 6


def test_wrong_token_announce_rejected():
    net = make_net(2)
    assert net.run(30, net.all_connected)
    a, b = net.nodes.values()
    key = InfoHash.get("locked")
    node_b = a.engine.cache.get_node(b.myid, b.bound_addr,
                                     a.scheduler.time(), confirm=False)
    a.engine.send_announce_value(node_b, key, Value(b"x", value_id=7),
                                 None, b"\0" * 32)
    net.settle(5)
    assert not b.get_local(key), "announce with bad token was stored"


def test_local_listener_immediate_replay():
    net = make_net(2)
    assert net.run(30, net.all_connected)
    a = next(iter(net.nodes.values()))
    key = InfoHash.get("local")
    a.storage_store(key, Value(b"preexisting", value_id=1),
                    a.scheduler.time())
    heard = []
    a.listen(key, lambda vals, expired: heard.extend(v.data for v in vals)
             or True)
    assert b"preexisting" in heard


def test_network_size_estimate_grows():
    net = make_net(10)
    assert net.run(120, net.all_connected)
    # let bucket/neighbourhood maintenance rounds spread the peer set
    net.settle(600)
    est = [d.network_size_estimate() for d in net.nodes.values()]
    assert all(e >= 8 for e in est), est


def test_status_lifecycle():
    net = VirtualNet()
    solo = net.add_node()
    assert solo.get_status() is NodeStatus.DISCONNECTED
    other = net.add_node()
    other.insert_node(solo.myid, solo.bound_addr)
    assert other.get_status() in (NodeStatus.CONNECTING, NodeStatus.CONNECTED)
    # no explicit ping: discovery waits for the idle maintenance cadence
    # (confirmNodes every 60-180 s, dht.cpp:1957-1962)
    assert net.run(400, net.all_connected)


def test_export_import_values():
    net = make_net(2)
    assert net.run(30, net.all_connected)
    a, b = net.nodes.values()
    key = InfoHash.get("exported")
    a.storage_store(key, Value(b"persisted", value_id=5), a.scheduler.time())
    exported = a.export_values()
    assert exported
    b.import_values(exported)
    vals = b.get_local(key)
    assert vals and vals[0].data == b"persisted"


def test_repeated_put_fires_done_cb():
    """Regression: a second put of an already-announced value completes via
    a synchronous callback from _announce; the done_cb must still fire."""
    net = make_net(5)
    assert net.run(60, net.all_connected)
    nodes = list(net.nodes.values())
    key = InfoHash.get("again")
    val = Value(b"same value twice")
    val.id = 42

    first = {}
    nodes[2].put(key, val, lambda ok, ns: first.update(ok=ok))
    assert net.run(60, lambda: "ok" in first), "first put never completed"
    assert first["ok"]

    second = {}
    nodes[2].put(key, val, lambda ok, ns: second.update(ok=ok))
    assert net.run(60, lambda: "ok" in second), "second put lost its done_cb"
    assert second["ok"]


def test_status_debounce_no_self_rescheduling_loop():
    """The debounced status recheck must never re-enter the window
    logic when its job fires: float rounding can make
    ``(last + 1.0) - last < 1.0``, and the re-entered branch would
    re-schedule the job at its own (already due) time — an infinite
    loop at a frozen virtual clock (caught at 5M events/0.5 virtual s
    by the hop-parity protocol leg)."""
    from opendht_tpu.scheduler import Scheduler

    # a time where float addition rounds (t + 1.0) - t below 1.0
    t0 = 3.0359290344407412
    clock = {"t": t0}
    sched = Scheduler(clock=lambda: clock["t"])
    dht = Dht(lambda data, addr: 0, scheduler=sched, has_v6=False)
    ticks = {"n": 0}
    orig = dht._status_tick

    def counted(af):
        ticks["n"] += 1
        return orig(af)

    dht._status_tick = counted
    af = socket.AF_INET
    dht._update_status(af, debounce=True)      # full check: checked = t0
    dht._update_status(af, debounce=True)      # in-window: schedules tick
    clock["t"] = t0 + 1.0                      # may round under t0+1.0-t0
    for _ in range(50):
        sched.run()
        dht._update_status(af, debounce=True)
    assert ticks["n"] <= 3, f"runaway recheck loop: {ticks['n']} ticks"
