"""Conversation-level wire goldens: scripted two-node exchanges.

Where tests/test_goldens.py freezes single packets, these tests replay
whole FLOWS through two real NetworkEngines wired back-to-back,
asserting the transcript bytes in BOTH directions against goldens from
the independent mini_msgpack encoder (tests/goldens/make_goldens.py),
plus the protocol behavior at each end:

- fragmented >600 B values: announce (A→B parts + reassembly) and get
  (B→A parts + reassembly) — sendValueParts/partial-message paths,
  /root/reference/src/network_engine.cpp:889-941, 431-457;
- all six DhtProtocolException codes (network_engine.h:49-79): 203,
  401, 404 emitted organically by request handlers and acted on by the
  requester (401→announce resend rearm, 404→refresh error cb,
  dht.cpp:2090-2112); 421 = parse-time drop, 422 = unknown-tid local
  throw, 423 = corrupt node blob local throw — none may crash or emit;
- 'sa' NAT address echo round-trip (insertAddr, cpp:636-645 →
  onReportedAddr);
- netid-mismatch silent drop (cpp:426-429) and the requester's expiry;
- listen push-channel u-packets with re/exp id lists (cpp:186-245),
  including the uint (not bin4) 't' those two messages use.
"""

import os

import pytest

from opendht_tpu.core.value import Query, Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net.engine import (DhtProtocolException, EngineCallbacks,
                                    NetworkEngine, RequestAnswer)
from opendht_tpu.net.parsed_message import MessageType
from opendht_tpu.net.request import RequestState
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

GOLDENS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens")

MYID = bytes(range(20))                  # A (requester) id
B_ID = InfoHash.get("peer")              # responder id = sha1("peer")
HASH = b"\xbb" * 20
TID = 0x01020304
SID = 0x05060709
TOKEN = bytes(range(0x10, 0x18))
CREATED = 1_700_000_000
A_ADDR = SockAddr("10.0.0.9", 4009)
B_ADDR = SockAddr("10.0.0.1", 4000)
BIG = Value(bytes(range(256)) * 11, type_id=3, value_id=77)   # 2816 B packed


def golden(name: str) -> bytes:
    with open(os.path.join(GOLDENS, name + ".bin"), "rb") as f:
        return f.read()


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t


class Pair:
    """Two engines joined by a byte-duplex recording wire.  ``deliver``
    controls whether bytes are forwarded (False = record only, for the
    drop tests)."""

    def __init__(self, net_a: int = 0, net_b: int = 0, cbs_b=None,
                 cbs_a=None):
        self.clock = _Clock()
        self.a_out: list = []
        self.b_out: list = []
        self.deliver = True               # False = queue; flush() delivers
        self._pending: list = []
        self.cbs_a = cbs_a or EngineCallbacks()
        self.cbs_b = cbs_b or EngineCallbacks()
        self.a = NetworkEngine(InfoHash(MYID), net_a, self._send_a,
                               Scheduler(clock=self.clock), self.cbs_a)
        self.b = NetworkEngine(B_ID, net_b, self._send_b,
                               Scheduler(clock=self.clock), self.cbs_b)

    def _send_a(self, data, dst) -> int:
        self.a_out.append(bytes(data))
        if self.deliver:
            self.b.process_message(bytes(data), A_ADDR)
        else:
            self._pending.append(("b", bytes(data)))
        return 0

    def _send_b(self, data, dst) -> int:
        self.b_out.append(bytes(data))
        if self.deliver:
            self.a.process_message(bytes(data), B_ADDR)
        else:
            self._pending.append(("a", bytes(data)))
        return 0

    def flush(self) -> None:
        """Deliver queued packets (deferred mode) until the wire is
        quiet — packets sent during delivery are delivered too."""
        while self._pending:
            to, data = self._pending.pop(0)
            if to == "b":
                self.b.process_message(data, A_ADDR)
            else:
                self.a.process_message(data, B_ADDR)

    def node_b(self, *tids):
        """A's cache Node for B with a pinned tid sequence — requests
        must live on the cache node so B's replies find them."""
        n = self.a.cache.get_node(B_ID, B_ADDR, self.clock(), confirm=True)
        seq = list(tids)
        n.get_new_tid = lambda: seq.pop(0)
        return n


def split_parts(raw: bytes) -> list:
    """Split a concatenated value_parts golden into packets (each is a
    standalone msgpack map)."""
    from opendht_tpu.utils import unpack_stream
    from opendht_tpu.utils import pack_msg
    return [pack_msg(o) for o in unpack_stream(raw)]


# ------------------------------------------------- fragmentation both ways

def test_conv_big_announce_fragments_and_reassembles():
    got = {}

    def on_announce(node, h, token, values, created):
        got.update(h=bytes(h), token=token, values=values, created=created)
        return RequestAnswer()

    p = Pair(cbs_b=EngineCallbacks(on_announce=on_announce))
    done = []
    req = p.a.send_announce_value(p.node_b(TID), InfoHash(HASH), BIG,
                                  float(CREATED), TOKEN,
                                  on_done=lambda r, a: done.append(a))
    # A→B transcript: the sizes-announce then the MTU parts stream
    assert p.a_out[0] == golden("announce_big_req")
    assert b"".join(p.a_out[1:]) == golden("value_parts")
    # B reassembled the full value before dispatching on_announce
    assert got["h"] == HASH and got["token"] == TOKEN
    assert got["created"] == CREATED
    assert len(got["values"]) == 1
    assert got["values"][0].id == 77 and got["values"][0].data == BIG.data
    # B confirmed with value_announced(77); A's request completed
    assert p.b_out == [golden("value_announced_77")]
    assert req.state is RequestState.COMPLETED
    assert done and done[0].vid == 77


def test_conv_big_get_reply_fragments_and_reassembles():
    def on_get(node, h, want, query):
        return RequestAnswer(ntoken=TOKEN, values=[BIG])

    p = Pair(cbs_b=EngineCallbacks(on_get_values=on_get))
    answers = []
    req = p.a.send_get_values(p.node_b(TID), InfoHash(HASH), Query(),
                              on_done=lambda r, a: answers.append(a))
    assert p.a_out == [golden("get_req")]
    # B→A: sizes-reply + the same MTU parts stream (reverse direction)
    assert p.b_out[0] == golden("nodes_values_sizes")
    assert b"".join(p.b_out[1:]) == golden("value_parts")
    assert req.state is RequestState.COMPLETED
    assert answers and answers[0].ntoken == TOKEN
    assert [v.id for v in answers[0].values] == [77]
    assert answers[0].values[0].data == BIG.data


# --------------------------------------------------------- six error codes

def _raising(exc):
    def cb(*a, **kw):
        raise exc
    return cb


def test_conv_error_203_get_no_infohash():
    exc = DhtProtocolException(DhtProtocolException.NON_AUTHORITATIVE_INFORMATION,
                               DhtProtocolException.GET_NO_INFOHASH)
    p = Pair(cbs_b=EngineCallbacks(on_get_values=_raising(exc)))
    req = p.a.send_get_values(p.node_b(TID), InfoHash(b"\x00" * 20), Query())
    assert p.b_out == [golden("error_203_get")]
    # 203 on a get is recorded but not special-cased: the request stays
    # pending (only 401-announce/listen and 404-refresh rearm/notify)
    assert req.state is RequestState.PENDING


def test_conv_error_401_put_wrong_token_rearms_announce():
    exc = DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                               DhtProtocolException.PUT_WRONG_TOKEN)
    errors = []
    p = Pair(cbs_b=EngineCallbacks(on_announce=_raising(exc)),
             cbs_a=EngineCallbacks(
                 on_error=lambda r, e: errors.append((r, e.code))))
    p.deliver = False         # real wires have latency: the error must
    v = Value(b"hello world", type_id=3, value_id=42)   # arrive AFTER
    req = p.a.send_announce_value(p.node_b(TID), InfoHash(HASH), v,
                                  float(CREATED), b"bad-token!")
    p.flush()                 # sendto() returns, not inside it
    assert p.b_out == [golden("error_401_put")]
    # requester side: 401 on an announce rearms the request for resend
    # with a fresh token (network_engine.cpp:536-554; dht.cpp:2090-2112)
    assert errors == [(req, 401)]
    assert req.last_try == float("-inf")


def test_conv_error_404_refresh_unknown_storage():
    exc = DhtProtocolException(DhtProtocolException.NOT_FOUND,
                               DhtProtocolException.STORAGE_NOT_FOUND)
    errors = []
    p = Pair(cbs_b=EngineCallbacks(on_refresh=_raising(exc)),
             cbs_a=EngineCallbacks(
                 on_error=lambda r, e: errors.append(e.code)))
    p.a.send_refresh_value(p.node_b(TID), InfoHash(HASH), 42, TOKEN)
    assert p.b_out == [golden("error_404_refresh")]
    assert errors == [404]


def test_conv_error_421_truncated_tid_is_parse_dropped():
    """A packet whose bin 't' is not 4 bytes fails tid parsing and is
    dropped before dispatch — the reference's parse-drop path
    (processMessage catch, cpp:418-424; 421 has no send site)."""
    p = Pair()
    bad = golden("ping_req").replace(b"t\xc4\x04\x01\x02\x03\x04",
                                     b"t\xc4\x03\x01\x02\x03")
    assert bad != golden("ping_req")
    p.b.process_message(bad, A_ADDR)
    assert p.b_out == []                  # no pong, no error — dropped


def test_conv_error_422_unknown_tid_reply_swallowed():
    """A reply for a transaction A never issued raises UNKNOWN_TID
    locally (cpp:521) — no error packet goes out for non-requests."""
    p = Pair()
    p.a.process_message(golden("value_announced_77"), B_ADDR)
    assert p.a_out == []
    # and receiving a peer-sent 422 error packet parses fine too
    p.a.process_message(golden("error_422"), B_ADDR)
    assert p.a_out == []


def test_conv_error_423_corrupt_node_blob_dropped():
    """A find reply whose n4 blob is not a multiple of 26 bytes throws
    WRONG_NODE_INFO_BUF_LEN during deserializeNodes (cpp:845-851); the
    request must not complete and nothing is emitted in response."""
    p = Pair()
    p.deliver = False                     # hand-deliver the corrupt reply
    req = p.a.send_find_node(p.node_b(TID), InfoHash(b"\xaa" * 20))
    p.a_out.clear()
    p.a.process_message(golden("nodes_corrupt_n4"), B_ADDR)
    assert p.a_out == []
    assert req.state is RequestState.PENDING


def test_parse_all_six_error_codes():
    """Every DhtProtocolException code round-trips through the parser
    with the sender id recovered."""
    from opendht_tpu.net.parsed_message import ParsedMessage
    for name, code in (("error_203_get", 203), ("error_401_put", 401),
                       ("error_404_refresh", 404), ("error_421", 421),
                       ("error_422", 422), ("error_423", 423)):
        m = ParsedMessage.from_bytes(golden(name))
        assert m.type is MessageType.ERROR
        assert m.error_code == code
        assert bytes(m.id) == bytes(B_ID)


# ------------------------------------------------------------ sa NAT echo

def test_conv_sa_echo_roundtrip():
    """B echoes A's source address in the pong's 'sa'; A surfaces it via
    on_reported_addr — the NAT discovery loop."""
    reported = []
    p = Pair(cbs_a=EngineCallbacks(
        on_reported_addr=lambda i, a: reported.append((bytes(i), a))))
    req = p.a.send_ping(p.node_b(TID))
    assert p.a_out == [golden("ping_req")]
    assert p.b_out == [golden("pong_b")]
    assert req.state is RequestState.COMPLETED
    (rid, addr), = reported
    assert rid == bytes(B_ID)
    assert addr.ip is not None and addr.ip.packed == b"\x0a\x00\x00\x09"


# ------------------------------------------------------- netid mismatch

def test_conv_netid_mismatch_drop_and_expiry():
    """B (network 7) silently drops A's (network 0) ping — no reply, no
    error — and A's request expires after its 3×1 s attempts."""
    p = Pair(net_a=0, net_b=7)
    expired = []
    req = p.a.send_ping(p.node_b(TID),
                        on_expired=lambda r, done: expired.append(done))
    assert p.a_out == [golden("ping_req")]
    assert p.b_out == []                  # dropped before dispatch
    for _ in range(8):                    # drive A's retry schedule
        p.clock.t += 1.0
        p.a.scheduler.run()
    assert req.state is RequestState.EXPIRED
    assert expired and expired[-1] is True


# -------------------------------------------------- listen u push channel

def test_conv_listen_u_packets_refreshed_and_expired():
    p = Pair(cbs_b=EngineCallbacks(
        on_listen=lambda n, h, t, s, q: RequestAnswer()))
    pushed = []
    req = p.a.send_listen(p.node_b(SID, TID), InfoHash(HASH), Query(),
                          TOKEN, None,
                          socket_cb=lambda node, msg: pushed.append(msg))
    assert p.a_out == [golden("listen_req")]
    assert p.b_out == [golden("pong_b")]  # listen confirmation layout
    assert req.state is RequestState.COMPLETED

    # B pushes refreshed / expired id lists over the socket channel
    node_a = p.b.cache.get_node(InfoHash(MYID), A_ADDR, p.clock(),
                                confirm=True)
    p.b_out.clear()
    p.b.tell_listener_refreshed(node_a, SID, InfoHash(HASH), TOKEN, [42, 43])
    p.b.tell_listener_expired(node_a, SID, InfoHash(HASH), TOKEN, [42, 43])
    assert p.b_out == [golden("listen_refreshed_u"),
                       golden("listen_expired_u")]
    assert [m.refreshed_values for m in pushed] == [[42, 43], []]
    assert [m.expired_values for m in pushed] == [[], [42, 43]]
