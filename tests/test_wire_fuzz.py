"""Adversarial wire fuzz (round-4 verdict ask #7).

The conversation goldens (tests/test_wire_conversations.py) cover
well-formed flows and the six protocol error codes; this tier throws
MALFORMED traffic at the full ingress path — truncated / bit-flipped /
type-confused msgpack, hostile fragment sequences, tid collisions — and
asserts the engine (a) never raises out of ``process_message``,
(b) leaks no partial-reassembly state once the RX timeouts pass, and
(c) keeps rate-limiting intact under a malformed-packet flood.

Reference surfaces under test: the decode path
(src/parsed_message.h:126-310), the ingress dispatch
(src/network_engine.cpp:403-489), and the partial-message maintenance
(src/network_engine.cpp:1293-1305).
"""

import random
import socket

import msgpack
import pytest

from opendht_tpu.core.value import MAX_VALUE_SIZE, Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net import EngineCallbacks, NetworkEngine
from opendht_tpu.net.engine import RX_MAX_PACKET_TIME
from opendht_tpu.net.parsed_message import pack_tid
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

pytestmark = pytest.mark.quick

SRC = SockAddr("203.0.113.7", 4444)      # public (non-martian) test addr
SRC2 = SockAddr("203.0.113.8", 4444)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_engine(max_req_per_sec=1600):
    clock = FakeClock()
    sched = Scheduler(clock=clock)
    sent = []
    eng = NetworkEngine(InfoHash.get("fuzz-target"), 0,
                        lambda data, dst: sent.append((data, dst)) or 0,
                        sched, EngineCallbacks(),
                        max_req_per_sec=max_req_per_sec)
    return eng, clock, sent


def engine_state_clean(eng):
    """No partial buffers, no stuck anonymous requests."""
    return len(eng._partials) == 0


def well_formed_samples():
    """A set of valid packets to mutate (one per message family)."""
    ih = bytes(InfoHash.get("h"))
    nid = bytes(InfoHash.get("peer"))
    samples = [
        {"a": {"id": nid}, "q": "ping", "t": pack_tid(1), "y": "q",
         "v": "RNG1"},
        {"a": {"id": nid, "target": ih, "w": [socket.AF_INET]},
         "q": "find", "t": pack_tid(2), "y": "q", "v": "RNG1"},
        {"a": {"id": nid, "h": ih}, "q": "get", "t": pack_tid(3), "y": "q",
         "v": "RNG1"},
        {"a": {"id": nid, "h": ih, "token": b"tok", "sid": pack_tid(9)},
         "q": "listen", "t": pack_tid(4), "y": "q", "v": "RNG1"},
        {"a": {"id": nid, "h": ih, "token": b"tok",
               "values": [Value(b"data").wire_obj()]},
         "q": "put", "t": pack_tid(5), "y": "q", "v": "RNG1"},
        {"r": {"id": nid, "n4": b"\x00" * 26, "token": b"tok"},
         "t": pack_tid(6), "y": "r", "v": "RNG1"},
        {"e": [401, "Unauthorized"], "t": pack_tid(7), "y": "e",
         "v": "RNG1"},
        {"u": {"id": nid, "re": [1, 2]}, "t": pack_tid(8), "y": "u",
         "v": "RNG1"},
    ]
    return [msgpack.packb(s, use_bin_type=True) for s in samples]


def test_truncated_packets_never_crash():
    eng, clock, _ = make_engine()
    for pkt in well_formed_samples():
        for cut in range(len(pkt)):
            eng.process_message(pkt[:cut], SRC)
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def test_bitflipped_packets_never_crash():
    eng, clock, _ = make_engine()
    rng = random.Random(5)
    for pkt in well_formed_samples():
        for _ in range(200):
            b = bytearray(pkt)
            for _ in range(rng.randrange(1, 4)):
                b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
            eng.process_message(bytes(b), SRC)
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def test_type_confused_fields_never_crash():
    """Valid msgpack, hostile types: ints where bins are expected, maps
    where lists are, huge ints, deep nesting, wrong-size tids."""
    eng, clock, _ = make_engine()
    nid = bytes(InfoHash.get("peer"))
    deep: object = 0
    for _ in range(60):
        deep = [deep]
    hostile = [
        {"a": {"id": 42}, "q": "ping", "t": pack_tid(1), "y": "q"},
        {"a": {"id": nid}, "q": "ping", "t": b"\x01\x02", "y": "q"},
        {"a": {"id": nid}, "q": "ping", "t": b"\x01" * 64, "y": "q"},
        {"a": {"id": nid}, "q": "ping", "t": 2 ** 63, "y": "q"},
        {"a": {"id": nid, "target": b"\x01" * 3}, "q": "find",
         "t": pack_tid(2), "y": "q"},
        {"a": {"id": nid, "w": {"4": True}}, "q": "find", "t": pack_tid(2),
         "y": "q"},
        {"a": {"id": nid, "values": {"0": "x"}}, "q": "put", "h": 7,
         "t": pack_tid(3), "y": "q"},
        {"a": {"id": nid, "h": nid[:20], "values": [2 ** 40]}, "q": "put",
         "t": pack_tid(3), "y": "q"},
        {"a": {"id": nid, "q": deep}, "q": "get", "t": pack_tid(4),
         "y": "q"},
        {"e": "not-a-list", "t": pack_tid(5), "y": "e"},
        {"e": [], "t": pack_tid(5), "y": "e"},
        {"e": [{}, []], "t": pack_tid(5), "y": "e"},
        {"r": {"id": nid, "sa": b"\x00" * 7}, "t": pack_tid(6), "y": "r"},
        {"r": {"id": nid, "fields": {"v": [1, 2]}}, "t": pack_tid(6),
         "y": "r"},
        {"r": {"id": nid, "fields": {"f": ["zz"], "v": 3}},
         "t": pack_tid(6), "y": "r"},
        {"u": {"id": nid, "re": "xy"}, "t": pack_tid(7), "y": "u"},
        {"u": {"id": nid, "exp": [{}, []]}, "t": pack_tid(7), "y": "u"},
        {"y": "z", "t": pack_tid(8)},
        {"q": "unknown-verb", "t": pack_tid(8), "y": "q",
         "a": {"id": nid}},
        [1, 2, 3],
        "just a string",
        12345,
        {"p": "not-a-map", "t": pack_tid(9), "y": "v"},
        {"p": {0: {"o": "x", "d": 5}}, "t": pack_tid(9), "y": "v"},
        {"p": {"idx": {"o": 0, "d": b"x"}}, "t": pack_tid(9), "y": "v"},
    ]
    for obj in hostile:
        try:
            data = msgpack.packb(obj, use_bin_type=True)
        except Exception:
            continue
        eng.process_message(data, SRC)
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def _announce(tid, total, nid, ih):
    """A put announcing one oversized value of ``total`` bytes."""
    return msgpack.packb(
        {"a": {"id": nid, "h": ih, "token": b"tok", "values": [total]},
         "q": "put", "t": pack_tid(tid), "y": "q", "v": "RNG1"},
        use_bin_type=True)


def _part(tid, index, offset, chunk):
    return msgpack.packb(
        {"p": {index: {"o": offset, "d": chunk}}, "t": pack_tid(tid),
         "y": "v", "v": "RNG1"}, use_bin_type=True)


def test_hostile_fragment_sequences():
    """Out-of-order offsets, overlapping chunks, oversized totals, parts
    from the wrong IP, unsolicited parts, huge indexes — no crash, no
    leak, and rate limiting stays live."""
    eng, clock, _ = make_engine()
    nid = bytes(InfoHash.get("peer"))
    ih = bytes(InfoHash.get("h"))

    # unsolicited part (no announce): dropped + rate-limit charged
    eng.process_message(_part(77, 0, 0, b"x" * 100), SRC)
    assert not eng._partials

    # oversized total: the size entry is skipped entirely
    eng.process_message(_announce(78, MAX_VALUE_SIZE + 33, nid, ih), SRC)
    assert 78 not in eng._partials

    # good announce then hostile parts
    eng.process_message(_announce(80, 1000, nid, ih), SRC)
    assert 80 in eng._partials
    eng.process_message(_part(80, 0, 500, b"y" * 100), SRC)     # o-o-o: drop
    assert len(eng._partials[80].msg.value_parts[0][1]) == 0
    eng.process_message(_part(80, 0, 0, b"y" * 100), SRC2)      # wrong ip
    assert len(eng._partials[80].msg.value_parts[0][1]) == 0
    eng.process_message(_part(80, 5, 0, b"y" * 100), SRC)       # bad index
    eng.process_message(_part(80, 2 ** 40, 0, b"y"), SRC)       # huge index
    eng.process_message(_part(80, 0, 0, b"y" * 200), SRC)       # progress
    assert len(eng._partials[80].msg.value_parts[0][1]) == 200
    eng.process_message(_part(80, 0, 100, b"y" * 50), SRC)      # overlap: drop
    assert len(eng._partials[80].msg.value_parts[0][1]) == 200

    # a colliding announce on the SAME tid from another ip must not
    # hijack or clobber the existing buffer
    eng.process_message(_announce(80, 400, nid, ih), SRC2)
    assert eng._partials[80].from_addr.same_ip(SRC)
    assert eng._partials[80].msg.value_parts[0][0] == 1000

    # stalled reassembly expires: no leak
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def test_fragment_completion_after_fuzz_still_works():
    """A well-formed fragmented put completes even while interleaved
    with hostile parts (state isolation)."""
    got = []
    clock = FakeClock()
    sched = Scheduler(clock=clock)
    cbs = EngineCallbacks()
    cbs.on_announce = lambda node, h, token, values, created: got.extend(
        values)
    eng = NetworkEngine(InfoHash.get("tgt"), 0, lambda d, a: 0, sched, cbs)
    nid = bytes(InfoHash.get("peer"))
    ih = bytes(InfoHash.get("h"))
    payload = bytes(range(256)) * 4                      # 1 KiB value
    v = Value(payload)
    packed = v.get_packed()
    eng.process_message(_announce(90, len(packed), nid, ih), SRC)
    half = len(packed) // 2
    eng.process_message(_part(90, 0, half, packed[half:]), SRC)   # o-o-o
    eng.process_message(_part(90, 0, 0, b"\xff" * 3), SRC2)       # wrong ip
    eng.process_message(_part(90, 0, 0, packed[:half]), SRC)
    eng.process_message(_part(90, 1, 0, b"zz"), SRC)              # bad idx
    eng.process_message(_part(90, 0, half, packed[half:]), SRC)
    assert len(got) == 1 and got[0].data == payload
    assert engine_state_clean(eng)


def test_rate_limit_survives_malformed_flood():
    """A flood of malformed + well-formed requests from one IP is capped
    at the per-IP budget; a second IP still gets service."""
    pings = []
    clock = FakeClock()
    sched = Scheduler(clock=clock)
    cbs = EngineCallbacks()
    cbs.on_ping = lambda node: pings.append(node)
    eng = NetworkEngine(InfoHash.get("tgt"), 0, lambda d, a: 0, sched, cbs,
                        max_req_per_sec=160)            # per-IP budget 20
    nid = bytes(InfoHash.get("peer"))
    ping = msgpack.packb({"a": {"id": nid}, "q": "ping", "t": pack_tid(1),
                          "y": "q", "v": "RNG1"}, use_bin_type=True)
    rng = random.Random(9)
    for i in range(400):
        if i % 2:
            b = bytearray(ping)
            b[rng.randrange(len(b))] ^= 0xFF
            eng.process_message(bytes(b), SRC)
        else:
            eng.process_message(ping, SRC)
    assert 0 < len(pings) <= 20          # per-IP cap held under the flood
    n_first = len(pings)
    eng.process_message(ping, SRC2)      # another ip is not starved
    assert len(pings) == n_first + 1


def test_tid_collisions_between_request_and_fragment():
    """A fragment stream must not be disturbed by queries reusing the
    same tid, and replies with colliding tids to unknown requests raise
    only the protocol error (not a crash)."""
    sent = []
    clock = FakeClock()
    sched = Scheduler(clock=clock)
    eng = NetworkEngine(InfoHash.get("tgt"), 0,
                        lambda d, a: sent.append((d, a)) or 0, sched,
                        EngineCallbacks())
    nid = bytes(InfoHash.get("peer"))
    ih = bytes(InfoHash.get("h"))
    eng.process_message(_announce(50, 1000, nid, ih), SRC)
    assert 50 in eng._partials
    # a ping reusing tid 50 — unrelated, must process fine
    eng.process_message(msgpack.packb(
        {"a": {"id": nid}, "q": "ping", "t": pack_tid(50), "y": "q"},
        use_bin_type=True), SRC)
    assert 50 in eng._partials           # stream untouched
    # a reply with tid 50 (no matching request) → UNKNOWN_TID error sent
    n0 = len(sent)
    eng.process_message(msgpack.packb(
        {"r": {"id": nid}, "t": pack_tid(50), "y": "r"},
        use_bin_type=True), SRC)
    assert 50 in eng._partials
    assert len(sent) == n0               # replies never trigger error sends
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def test_unknown_toplevel_keys_parse_and_interop():
    """ISSUE-4 wire compat: a msgpack map with unknown top-level keys —
    including a hostile multi-KB fake trace blob — must parse cleanly,
    be served like any well-formed request, and never echo the blob.
    This is exactly what a pre-trace parser sees from a tracing peer
    (the ``tr`` key is 'unknown' to it), so it doubles as the
    old-parser interop proof."""
    pings = []
    sent = []
    clock = FakeClock()
    sched = Scheduler(clock=clock)
    cbs = EngineCallbacks()
    cbs.on_ping = lambda node: pings.append(node)
    eng = NetworkEngine(InfoHash.get("tgt"), 0,
                        lambda d, a: sent.append(d) or 0, sched, cbs)
    nid = bytes(InfoHash.get("peer"))
    blob = b"\xbb" * 262144
    pkt = msgpack.packb(
        {"a": {"id": nid}, "q": "ping", "t": pack_tid(1), "y": "q",
         "v": "RNG1", "zz_future": blob, "another_unknown": [1, {"x": 2}],
         "tr": blob},                       # oversized trace blob too
        use_bin_type=True)
    eng.process_message(pkt, SRC)
    assert len(pings) == 1                  # served normally
    assert len(sent) == 1                   # pong went out
    assert blob[:64] not in sent[0]         # nothing echoed
    assert len(sent[0]) < 256               # reply is the normal pong
    assert engine_state_clean(eng)


def test_hostile_trace_blobs_never_crash_or_record():
    """Every malformed shape of the ``tr`` key decodes to None (no
    span recorded, no crash); only the exact 16B/8B/int shape yields a
    context."""
    from opendht_tpu import tracing
    from opendht_tpu.net.parsed_message import ParsedMessage

    nid = bytes(InfoHash.get("peer"))
    hostile_trs = [
        b"\xaa" * (1 << 20),                       # 1 MiB blob
        "a string", 12345, [1, 2, 3],
        {},                                        # empty map
        {"i": b"\x01" * 15, "s": b"\x02" * 8, "f": 1},       # short i
        {"i": b"\x01" * 17, "s": b"\x02" * 8, "f": 1},       # long i
        {"i": b"\x01" * 16, "s": b"\x02" * 7, "f": 1},       # short s
        {"i": b"\x01" * 16, "s": b"\x02" * (1 << 16), "f": 1},
        {"i": b"\x01" * 16, "s": b"\x02" * 8, "f": "x"},     # bad flags
        {"i": 42, "s": b"\x02" * 8, "f": 1},                 # int id
        {"i": b"\x00" * 16, "s": b"\x02" * 8, "f": 1},       # zero id
        {"i": b"\x01" * 16, "s": b"\x02" * 8, "f": 1,
         **{"k%d" % i: i for i in range(20)}},               # fat map
    ]
    for tr in hostile_trs:
        pkt = msgpack.packb(
            {"a": {"id": nid}, "q": "ping", "t": pack_tid(1), "y": "q",
             "tr": tr}, use_bin_type=True)
        msg = ParsedMessage.from_bytes(pkt)
        assert msg.trace_ctx is None, repr(tr)[:60]
    # the one well-formed shape decodes
    good = {"i": b"\x01" * 16, "s": b"\x02" * 8, "f": 1}
    pkt = msgpack.packb(
        {"a": {"id": nid}, "q": "ping", "t": pack_tid(1), "y": "q",
         "tr": good}, use_bin_type=True)
    msg = ParsedMessage.from_bytes(pkt)
    assert msg.trace_ctx is not None and msg.trace_ctx.sampled
    assert msg.trace_ctx.to_wire() == good
    # and an engine processing a flood of hostile-tr requests records
    # no server spans (unsampled/undecodable) and stays clean
    tracer = tracing.get_tracer()
    tracer.clear()
    eng, clock, _ = make_engine()
    for tr in hostile_trs:
        try:
            data = msgpack.packb(
                {"a": {"id": nid}, "q": "ping", "t": pack_tid(2),
                 "y": "q", "tr": tr}, use_bin_type=True)
        except Exception:
            continue
        eng.process_message(data, SRC)
    assert not [s for s in tracer.spans() if s["kind"] == "server"]
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)


def test_pre_trace_packet_bytes_unchanged():
    """With no ambient trace context, outgoing queries are byte-for-byte
    what a pre-trace build emits — no ``tr`` key, so a pre-trace golden
    parser (and the reference) sees identical packets."""
    eng, clock, sent = make_engine()
    node = eng.cache.get_node(InfoHash.get("peer"), SRC, 0.0, confirm=True)
    eng.send_ping(node)
    assert sent
    obj = msgpack.unpackb(sent[0][0], raw=False)
    assert "tr" not in obj
    assert set(obj) <= {"a", "q", "t", "y", "v", "n"}


def test_random_garbage_corpus():
    """Pure random byte strings (seeded) across a spread of lengths."""
    eng, clock, _ = make_engine()
    rng = random.Random(1234)
    for n in (0, 1, 2, 3, 7, 16, 64, 600, 1280, 4096):
        for _ in range(50):
            eng.process_message(rng.randbytes(n), SRC)
    clock.t += RX_MAX_PACKET_TIME + 1
    eng.scheduler.run()
    assert engine_state_clean(eng)
