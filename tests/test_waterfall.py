"""Per-op latency waterfall (round 19, opendht_tpu/waterfall.py): the
always-on stage profiler, the per-op sum≈end-to-end decomposition pin,
exemplar-stamped hot buckets, the degrade-only stage_budget health
signal, the OPEN-bound tracker, and the dhtmon/REPL/export surfaces."""

from __future__ import annotations

import json
import re
import socket as _socket
import time

import numpy as np

from opendht_tpu import health, telemetry, waterfall
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.runtime.live_search import SEARCH_NODES
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.waterfall import (DEFAULT_STAGE_BUDGETS, OPEN_BOUND_KEYS,
                                   STAGE_ALIASES, STAGES, OpenBoundTracker,
                                   StageProfiler, WaterfallConfig)

AF = _socket.AF_INET

#: per-op decomposition tolerance (the acceptance-criteria pin): the
#: recorded stages are non-overlapping sub-intervals of the op's
#: admission→scatter wall-clock, so their sum can never exceed it, and
#: the unattributed remainder — the wave-assembly glue (grouping loop,
#: target-array build, metric writes), all host-side — must stay a
#: small fraction of the op (floored for CPU scheduling jitter)
SUM_TOL_FRAC = 0.5
SUM_TOL_FLOOR_S = 0.100


def _profiler(**cfg_kw) -> StageProfiler:
    return StageProfiler(WaterfallConfig(**cfg_kw),
                         reg=telemetry.MetricsRegistry())


def make_dht(clock, n_nodes=12, **cfg_kw):
    """The wave-builder test harness: v4-only Dht on a virtual clock
    with a populated table and a swallow-everything transport."""
    cfg = Config(**cfg_kw)
    dht = Dht(lambda data, addr: 0, config=cfg,
              scheduler=Scheduler(clock=lambda: clock["t"]),
              has_v6=False)
    rng = np.random.default_rng(1234)
    table = dht.tables[AF]
    added = 0
    while added < n_nodes:
        h = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        if table.insert(h, SockAddr("10.9.0.%d" % (added + 1), 4500),
                        now=clock["t"], confirm=2) is not None:
            added += 1
    return dht


# ========================================================== unit: profiler
def test_observe_disabled_is_noop():
    p = _profiler(enabled=False)
    p.observe("queue_wait", 1.0)
    p.record_op("get", {"queue_wait": 1.0}, 1.0)
    assert p.snapshot()["stages"]["queue_wait"]["count"] == 0
    assert p.ops() == []
    assert not p.enabled


def test_exemplar_rides_the_landing_bucket():
    p = _profiler()
    tid = "ab" * 16
    p.observe("device_launch", 0.004, exemplar=tid)
    d = p.snapshot()["stages"]["device_launch"]
    assert d["count"] == 1
    assert d["exemplars"], "hot bucket lost its exemplar"
    le, value, got = d["exemplars"][0]
    assert value == 0.004 and got == tid and le >= 0.004


def test_first_launch_true_exactly_once_per_group():
    p = _profiler()
    assert p.first_launch((AF, 8))
    assert not p.first_launch((AF, 8))
    assert p.first_launch((AF, 16))      # a new group shape compiles again
    assert not p.first_launch((AF, 16))


def test_record_op_ring_bounded():
    p = _profiler(op_ring=4)
    for i in range(10):
        p.record_op("get", {"queue_wait": 0.001}, 0.002, trace_id="%02x" % i)
    ops = p.ops()
    assert len(ops) == 4
    assert [o["trace_id"] for o in ops] == ["06", "07", "08", "09"]
    assert all("t" in o for o in ops)


def test_folded_flamegraph_lines():
    p = _profiler()
    p.observe("queue_wait", 0.001)
    p.observe("device_launch", 0.005)    # alias lands in device_wait
    out = p.folded()
    assert out.endswith("\n")
    for ln in out.strip().splitlines():
        assert re.fullmatch(r"dht;op;[a-z_]+ \d+", ln), ln
    assert "dht;op;queue_wait 1000" in out
    # folded emits canonical stages only — the round-22 alias resolves
    assert "dht;op;device_wait 5000" in out
    assert "device_launch" not in out
    assert _profiler().folded() == ""    # nothing observed, nothing folded


def test_stage_budget_windowed_worst_ratio():
    p = _profiler()
    assert p.stage_budget() is None          # nothing observed
    for _ in range(5):
        p.observe("queue_wait", 0.001)       # well under the 20 ms budget
    r = p.stage_budget()
    assert r is not None and r < 1.0
    # the window consumed those samples: a quiet interval is unknown,
    # not a replay of boot history
    assert p.stage_budget() is None
    for _ in range(5):
        p.observe("queue_wait", 10 * DEFAULT_STAGE_BUDGETS["queue_wait"])
    assert p.stage_budget() > 1.0
    # below the min-event floor the signal stays unknown (one slow
    # wave at boot is not a trend)
    p.observe("queue_wait", 1.0)
    assert p.stage_budget() is None


def test_stage_budget_excludes_device_compile():
    p = _profiler()
    for _ in range(8):
        p.observe("device_compile", 500.0)   # way past any budget
    assert p.stage_budget() is None


def test_configure_rebounds_ring_and_budgets():
    p = _profiler()
    p.record_op("get", {}, 0.001)
    p.configure(WaterfallConfig(op_ring=2, budgets={"queue_wait": 9.0}))
    assert p.budgets["queue_wait"] == 9.0
    assert p.budgets["rpc_wait"] == DEFAULT_STAGE_BUDGETS["rpc_wait"]
    for i in range(5):
        p.record_op("get", {}, 0.001)
    assert len(p.ops()) == 2


# ================================================= integration: wave path
def test_wave_stages_advance_and_ops_sum_to_end_to_end():
    """One coalesced wave through the live wave builder: queue_wait /
    device stage / scatter_back all advance on the GLOBAL profiler,
    and every per-op record's stage sum ≈ its end-to-end wall-clock
    within the pinned tolerance (rpc_wait excluded by construction —
    it overlaps the device stages)."""
    wf = waterfall.get_profiler()
    wf.configure(WaterfallConfig())
    base = {s: wf._h[s].count for s in STAGES}
    t0 = time.time()

    clock = {"t": 5000.0}
    dht = make_dht(clock, ingest_fill_target=4, ingest_deadline=5.0)
    for i in range(4):
        dht.get(InfoHash.get(f"wf-sum-{i}"))
    dht.scheduler.run()

    assert wf._h["queue_wait"].count >= base["queue_wait"] + 4
    dev = (wf._h["device_compile"].count + wf._h["device_wait"].count
           - base["device_compile"] - base["device_wait"])
    assert dev >= 1
    assert wf._h["scatter_back"].count >= base["scatter_back"] + 1

    # the GLOBAL op ring may already be full from earlier tests, so
    # the 4 new records are asserted by wall-clock stamp, not length
    recs = wf.ops()[-4:]
    assert len(recs) == 4 and all(o["t"] >= t0 for o in recs), recs
    assert all(o["kind"] == "refill" for o in recs), recs
    for o in recs:
        s = sum(o["stages"].values())
        assert "rpc_wait" not in o["stages"]
        assert s <= o["end_to_end"] + 1e-6, (s, o)
        gap = o["end_to_end"] - s
        assert gap <= max(SUM_TOL_FLOOR_S,
                          SUM_TOL_FRAC * o["end_to_end"]), o


def test_wave_compile_execute_split_per_group():
    """The FIRST timed launch of a (family, k) group lands in
    device_compile; the second identical wave lands in
    device_launch."""
    wf = waterfall.get_profiler()
    wf.configure(WaterfallConfig())
    wf._compiled.clear()
    c0 = wf._h["device_compile"].count
    l0 = wf._h["device_launch"].count
    clock = {"t": 6000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=5.0)
    for i in range(2):
        dht.get(InfoHash.get(f"wf-split-a{i}"))
    dht.scheduler.run()
    assert wf._h["device_compile"].count == c0 + 1
    assert wf._h["device_launch"].count == l0
    for i in range(2):
        dht.get(InfoHash.get(f"wf-split-b{i}"))
    dht.scheduler.run()
    assert wf._h["device_compile"].count == c0 + 1
    assert wf._h["device_launch"].count == l0 + 1


def test_results_bit_identical_profiler_on_vs_off():
    """The profiler only observes: the wave's resolved node rows are
    identical with it enabled and disabled."""
    wf = waterfall.get_profiler()
    targets = [InfoHash.get(f"wf-ident-{i}") for i in range(5)]

    def run_wave(enabled: bool):
        wf.configure(WaterfallConfig(enabled=enabled))
        clock = {"t": 7000.0}
        dht = make_dht(clock, ingest_fill_target=5, ingest_deadline=5.0)
        got = []
        for t in targets:
            dht.wave_builder.submit(t, AF, SEARCH_NODES,
                                    lambda nodes: got.append(nodes))
        dht.scheduler.run()
        return [[n.id for n in row] for row in got]

    try:
        on = run_wave(True)
        off = run_wave(False)
    finally:
        wf.configure(WaterfallConfig())
    assert on == off


def test_config_plumbs_through_dht():
    """Config.waterfall reconfigures the process-global profiler at
    node construction (last node wins, like the shared registry)."""
    wf = waterfall.get_profiler()
    clock = {"t": 8000.0}
    try:
        make_dht(clock, waterfall=WaterfallConfig(enabled=False,
                                                  op_ring=7))
        assert wf is waterfall.get_profiler()
        assert not wf.enabled
        assert wf._ops.maxlen == 7
    finally:
        wf.configure(WaterfallConfig())


# ====================================================== health + export
def test_stage_budget_health_signal_registered_degrade_only():
    assert health.DEFAULT_SIGNAL_THRESHOLDS["stage_budget"] == (1.0, 2.0)
    assert "stage_budget" in health.HealthConfig().degrade_only
    clock = {"t": 9000.0}
    dht = make_dht(clock, n_nodes=4)
    nh = health.NodeHealth(dht)
    assert "stage_budget" in nh.evaluator.providers
    # unknown (None) when the window has no new samples — never trips
    wf = waterfall.get_profiler()
    wf.stage_budget()                        # consume any prior window
    assert nh.evaluator.providers["stage_budget"]() is None


def test_profiler_publishes_budget_gauges_on_its_registry():
    """The stage budgets export as gauges from construction (and track
    a reconfigure) on the profiler's OWN registry — NOT via
    profiling.maybe_export, which must stay a no-op for ledger-off
    processes (test_maybe_export_is_gated)."""
    reg = telemetry.MetricsRegistry()
    p = StageProfiler(reg=reg)
    g = reg.snapshot()["gauges"]
    for stage in STAGES:
        key = 'dht_stage_budget_seconds{stage="%s"}' % stage
        assert key in g, sorted(g)
        assert g[key] == p.budgets[stage]
    p.configure(WaterfallConfig(budgets={"queue_wait": 0.5}))
    g = reg.snapshot()["gauges"]
    assert g['dht_stage_budget_seconds{stage="queue_wait"}'] == 0.5


def test_snapshot_shape_and_quantiles():
    p = _profiler()
    for v in (0.001, 0.002, 0.004, 0.008):
        p.observe("rpc_wait", v)
    doc = json.loads(json.dumps(p.snapshot()))   # JSON-able
    assert doc["enabled"] is True
    # canonical stages plus the one-release alias mirror (round 22)
    assert set(doc["stages"]) == set(STAGES) | set(STAGE_ALIASES)
    assert doc["stages"]["device_launch"]["alias_of"] == "device_wait"
    rw = doc["stages"]["rpc_wait"]
    assert rw["count"] == 4
    assert rw["p50"] is not None and rw["p99"] >= rw["p50"]
    assert doc["budgets"]["rpc_wait"] == DEFAULT_STAGE_BUDGETS["rpc_wait"]


# ======================================================= OPEN-bound tracker
def test_open_bound_keys_match_perf_budgets():
    """The tracker serves exactly the six ``open: true`` entries —
    a renamed budget entry fails loudly here, not silently."""
    with open(waterfall._repo_budgets_path()) as fh:
        doc = json.load(fh)
    want = {k for k, v in doc["open_bounds"].items() if v.get("open")}
    assert want == set(OPEN_BOUND_KEYS)
    t = OpenBoundTracker(reg=telemetry.MetricsRegistry())
    assert set(t.bounds) == want


def test_open_bound_gauges_live_from_boot_with_sentinel():
    reg = telemetry.MetricsRegistry()
    t = OpenBoundTracker(reg=reg)
    assert t.platform == "cpu" and t.status == "unsettled"
    out = t.refresh()
    g = reg.snapshot()["gauges"]
    for key in OPEN_BOUND_KEYS:
        series = 'dht_open_bound{key="%s",status="unsettled"}' % key
        assert series in g, sorted(g)
        assert g[series] == -1.0             # no measurement yet
        assert out[key]["value"] is None


def test_open_bound_measurements_track_live_series():
    reg = telemetry.MetricsRegistry()
    t = OpenBoundTracker(reg=reg)
    for _ in range(8):
        reg.histogram("dht_search_wave_seconds", mode="single",
                      wave="1024").observe(0.004)
        reg.histogram("dht_search_wave_seconds", mode="tp").observe(0.020)
        reg.histogram("dht_churn_lookup_seconds").observe(0.010)
        reg.histogram("dht_maintenance_sweep_seconds").observe(0.003)
        reg.histogram("dht_op_seconds", op="get").observe(0.002)
    reg.histogram("dht_ingest_wave_occupancy").observe(6.0)
    reg.histogram("dht_ingest_wave_occupancy").observe(2.0)
    out = t.refresh()
    ms = out["wave_p50_ms_1024"]["value"]
    assert ms is not None and 0.5 <= ms <= 10.0
    assert out["shard_wave_10m"]["value"] > ms
    assert out["maintenance_sweep_config4"]["value"] is not None
    assert out["ingest_wave_occupancy"]["value"] == 4.0
    assert out["cache_flood_p50"]["value"] is not None
    ratio = out["churny_static_ratio"]["value"]
    assert ratio is not None and ratio > 0
    g = reg.snapshot()["gauges"]
    assert g['dht_open_bound{key="ingest_wave_occupancy",'
             'status="unsettled"}'] == 4.0


def test_open_bound_settling_record_roundtrip(tmp_path):
    """A CPU run writes the full settling-record shape with
    status="unsettled" — the machinery CI exercises long before an
    accelerator sees it."""
    reg = telemetry.MetricsRegistry()
    t = OpenBoundTracker(reg=reg)
    assert t.write_record(str(tmp_path)) is None   # nothing measured yet
    reg.histogram("dht_search_wave_seconds", mode="single").observe(0.004)
    t.refresh()
    path = t.write_record(str(tmp_path))
    assert path is not None
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["name"] == "open_bounds"
    assert doc["platform"] == "cpu" and doc["status"] == "unsettled"
    assert set(doc["bounds"]) == {"wave_p50_ms_1024"}
    b = doc["bounds"]["wave_p50_ms_1024"]
    assert b["status"] == "unsettled" and b["value"] > 0
    assert b["metric"] and b["settle"]


def test_open_bound_tracker_ticks_on_scheduler(tmp_path, monkeypatch):
    monkeypatch.setenv("OPENDHT_TPU_SMOKE_RECORD_DIR", str(tmp_path))
    reg = telemetry.MetricsRegistry()
    clock = {"t": 100.0}
    sched = Scheduler(clock=lambda: clock["t"])
    t = OpenBoundTracker(reg=reg)
    reg.histogram("dht_op_seconds", op="get").observe(0.002)
    t.attach(sched, period=1.0)
    clock["t"] += 1.5
    sched.run()
    assert (tmp_path / "open_bounds.json").exists()
    g = reg.snapshot()["gauges"]
    assert g['dht_open_bound{key="cache_flood_p50",'
             'status="unsettled"}'] > 0
    clock["t"] += 1.5                        # the tick reschedules itself
    sched.run()


# ============================================================ dhtmon gate
def test_dhtmon_stage_p95_reader_handles_both_label_orders():
    from opendht_tpu.tools.dhtmon import _stage_p95s
    series = {}
    for le, n in (("0.001", 2), ("0.01", 8), ("+Inf", 8)):
        series['dht_stage_seconds_bucket{le="%s",stage="queue_wait"}'
               % le] = float(n)
    for le, n in (("0.05", 3), ("+Inf", 4)):
        series['dht_stage_seconds_bucket{stage="rpc_wait",le="%s"}'
               % le] = float(n)
    series["dht_op_seconds_bucket{le=\"1\"}"] = 9.0     # ignored
    p = _stage_p95s(series)
    assert set(p) == {"queue_wait", "rpc_wait"}
    assert 0.001 < p["queue_wait"] <= 0.01
    assert p["rpc_wait"] <= 0.05


def test_dhtmon_max_stage_spec_validation():
    from opendht_tpu.tools import dhtmon
    assert dhtmon.main(["--nodes", "127.0.0.1:1", "--max-stage",
                        "bogus=1.0"]) == 2
    assert dhtmon.main(["--nodes", "127.0.0.1:1", "--max-stage",
                        "queue_wait"]) == 2
    assert dhtmon.main(["--nodes", "127.0.0.1:1", "--max-stage",
                        "queue_wait=notanumber"]) == 2


# ===================================================== scanner sections
def test_scanner_snapshot_has_waterfall_and_chaos_sections():
    """dhtscanner --json surfaces the per-op waterfall and the chaos
    counters (round-19 satellite): the ``waterfall`` section IS the
    node's get_profile() doc, the ``chaos`` section filters the
    ``dht_chaos_*`` counters off get_metrics()."""
    import json as _json

    from opendht_tpu.runtime.runner import DhtRunner
    from opendht_tpu.tools.dhtscanner import topology_snapshot

    r = DhtRunner()
    try:
        r.run(0)
        snap = topology_snapshot(r)
        wfs = snap["waterfall"]
        assert wfs["enabled"] is True
        assert set(wfs["stages"]) == set(STAGES) | set(STAGE_ALIASES)
        assert "open_bounds" in wfs
        assert set(wfs["open_bounds"]["bounds"]) == set(OPEN_BOUND_KEYS)
        chaos = snap["chaos"]
        assert isinstance(chaos, dict)
        assert all(k.startswith("dht_chaos_") for k in chaos)
        _json.dumps(snap)                     # the --json surface
    finally:
        r.join()
