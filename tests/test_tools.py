"""Tools-layer tests: argv/identity helpers and the non-interactive
pieces of dhtnode/dhtchat/dhtscanner (the interactive REPL is driven in
CI-style smoke runs, not here)."""

import os

import pytest

from opendht_tpu import crypto
from opendht_tpu.infohash import InfoHash
from opendht_tpu.tools.common import (load_identity, make_arg_parser,
                                      parse_bootstrap, save_identity)
from opendht_tpu.tools.dhtnode import to_hash


def test_parse_bootstrap_forms():
    assert parse_bootstrap("") is None
    assert parse_bootstrap("host") == ("host", 4222)
    assert parse_bootstrap("host:4000") == ("host", 4000)
    assert parse_bootstrap("[2001:db8::1]:4000") == ("2001:db8::1", 4000)
    assert parse_bootstrap("[2001:db8::1]") == ("2001:db8::1", 4222)
    assert parse_bootstrap("2001:db8::1") == ("2001:db8::1", 4222)


def test_to_hash_hex_vs_text():
    h = InfoHash.get("x")
    assert to_hash(h.hex()) == h                 # 40-hex passes through
    assert to_hash("some words") == InfoHash.get("some words")


def test_identity_save_load(tmp_path):
    ident = crypto.generate_identity("tools-test", key_length=1024)
    prefix = str(tmp_path / "id")
    save_identity(ident, prefix)
    assert os.path.exists(prefix + ".pem")
    assert os.path.exists(prefix + ".crt")
    loaded = load_identity(prefix)
    assert loaded is not None
    assert loaded.second.get_id() == ident.second.get_id()
    # loaded key can still sign for the same public key
    sig = loaded.first.sign(b"data")
    assert ident.first.public_key().check_signature(b"data", sig)


def test_state_save_load_roundtrip(tmp_path):
    """Checkpoint/resume: nodes+values exported to a file come back on a
    fresh runner (↔ exportNodes/exportValues persistence, SURVEY §5)."""
    import time
    from opendht_tpu.core.value import Value
    from opendht_tpu.runtime.config import NodeStatus
    from opendht_tpu.runtime.runner import DhtRunner
    from opendht_tpu.tools.common import load_state, save_state

    a, b, c = DhtRunner(), DhtRunner(), None
    try:
        a.run(0)
        b.run(0)
        b.bootstrap("127.0.0.1", a.get_bound_port())
        deadline = time.monotonic() + 20.0
        while (b.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() < deadline):
            time.sleep(0.05)
        key = InfoHash.get("state-key")
        assert b.put_sync(key, Value(b"persisted"), timeout=20.0)
        path = str(tmp_path / "state.mp")
        save_state(b, path)
        b.join()

        c = DhtRunner()
        c.run(0)
        n_nodes, n_keys = load_state(c, path)
        assert n_nodes >= 1 and n_keys >= 1
        vals = c.get_sync(key, timeout=20.0)
        assert any(v.data == b"persisted" for v in vals)
    finally:
        a.join()
        b.join()
        if c is not None:
            c.join()


def test_arg_parser_defaults():
    args = make_arg_parser("t").parse_args([])
    assert args.port == 0 and args.bootstrap == "" and not args.identity
    args = make_arg_parser("t").parse_args(
        ["-p", "4222", "-b", "h:1", "-i", "--proxyserver", "8080"])
    assert (args.port, args.bootstrap, args.identity, args.proxyserver) == \
        (4222, "h:1", True, 8080)
