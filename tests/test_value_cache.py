"""core/value_cache.py coverage (ISSUE-11 satellite).

The listen-side per-(node, query) value cache (reference
src/value_cache.h) was an untested thin host port while it became one
of the building blocks the round-16 hot-key serving layer sits next
to.  Pins the contracts hotcache/live_search rely on: add/refresh/
expire event dispatch through the one callback, the refreshed/expired
id lists from value-update packets, next-expiration scheduling, the
standalone expiry sweep, clear(), and the MAX_VALUES oldest-evicted
cap."""

from __future__ import annotations

import pytest

from opendht_tpu.core.value import TypeStore, Value, ValueType
from opendht_tpu.core.value_cache import MAX_VALUES, ValueCache
from opendht_tpu.utils import TIME_MAX


def collector():
    events = []
    return events, lambda vals, expired: events.append(
        (sorted(v.id for v in vals), expired))


def types_with(expiration: float) -> TypeStore:
    ts = TypeStore()
    ts.register_type(ValueType(0, "t", expiration))
    return ts


def v(vid: int) -> Value:
    return Value(b"d%d" % vid, value_id=vid)


def test_add_then_expire_dispatches_through_callback():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    nxt = vc.on_values([v(1), v(2)], (), (), ts, now=100.0)
    assert events == [([1, 2], False)]
    assert nxt == 110.0                      # next expiration scheduled
    assert sorted(x.id for x in vc.get_values()) == [1, 2]
    # sweep at the expiration: both expire, cache empties, TIME_MAX
    events.clear()
    nxt = vc.expire_values(now=110.0)
    assert events == [([1, 2], True)]
    assert nxt == TIME_MAX and len(vc) == 0


def test_readd_refreshes_instead_of_duplicating():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    vc.on_values([v(1)], (), (), ts, now=0.0)
    events.clear()
    # same id again: refreshed (no add event), expiration extended
    nxt = vc.on_values([v(1)], (), (), ts, now=5.0)
    assert events == [] and nxt == 15.0
    assert vc.expire_values(now=10.0) == 15.0   # survived the old slot
    assert len(vc) == 1


def test_refreshed_id_list_extends_expiration():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    vc.on_values([v(1), v(2)], (), (), ts, now=0.0)
    events.clear()
    # peer refreshed id 1 only; id 2 keeps its original deadline
    nxt = vc.on_values((), [1], (), ts, now=8.0)
    assert nxt == 10.0                       # id 2 is next
    assert events == []
    events.clear()
    nxt = vc.expire_values(now=10.0)
    assert events == [([2], True)]
    assert nxt == 18.0                       # refreshed id 1 remains
    # refreshing an unknown id is a silent no-op (value_cache.h:96)
    assert vc.on_values((), [99], (), ts, now=11.0) == 18.0


def test_expired_id_list_fires_expired_event():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    vc.on_values([v(1), v(2)], (), (), ts, now=0.0)
    events.clear()
    nxt = vc.on_values((), (), [1], ts, now=1.0)
    assert events == [([1], True)]
    assert nxt == 10.0 and len(vc) == 1
    # expiring an unknown id emits nothing
    events.clear()
    vc.on_values((), (), [42], ts, now=1.0)
    assert events == []


def test_one_update_orders_adds_before_expiries():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    vc.on_values([v(1)], (), (), ts, now=0.0)
    events.clear()
    # one packet: new value 2, expired id 1 — two callbacks, adds first
    vc.on_values([v(2)], (), [1], ts, now=1.0)
    assert events == [([2], False), ([1], True)]


def test_max_values_cap_evicts_oldest_created():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(1e6)
    # fill to cap with strictly increasing created stamps
    for i in range(MAX_VALUES):
        vc.on_values([v(i + 1)], (), (), ts, now=float(i))
    assert len(vc) == MAX_VALUES
    events.clear()
    # two over cap in one update: the two OLDEST-created drop, and the
    # eviction is reported as an expiration through the callback
    vc.on_values([v(MAX_VALUES + 1), v(MAX_VALUES + 2)], (), (), ts,
                 now=float(MAX_VALUES))
    assert len(vc) == MAX_VALUES
    adds, drops = events
    assert adds == ([MAX_VALUES + 1, MAX_VALUES + 2], False)
    assert drops == ([1, 2], True)
    assert vc.get_values()                   # newest retained
    ids = set(x.id for x in vc.get_values())
    assert 1 not in ids and 2 not in ids and MAX_VALUES + 2 in ids


def test_clear_flushes_everything_as_expired():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = types_with(10.0)
    vc.on_values([v(1), v(2)], (), (), ts, now=0.0)
    events.clear()
    vc.clear()
    assert events == [([1, 2], True)]
    assert len(vc) == 0
    # clearing an empty cache fires nothing
    events.clear()
    vc.clear()
    assert events == []


def test_callbackless_cache_still_tracks_state():
    vc = ValueCache(None)
    ts = types_with(10.0)
    nxt = vc.on_values([v(1)], (), (), ts, now=0.0)
    assert nxt == 10.0 and len(vc) == 1
    assert vc.expire_values(now=10.0) == TIME_MAX and len(vc) == 0


def test_mixed_type_expirations_schedule_earliest():
    events, cb = collector()
    vc = ValueCache(cb)
    ts = TypeStore()
    ts.register_type(ValueType(0, "short", 5.0))
    ts.register_type(ValueType(7, "long", 50.0))
    long_v = Value(b"L", type_id=7, value_id=2)
    nxt = vc.on_values([v(1), long_v], (), (), ts, now=0.0)
    assert nxt == 5.0
    events.clear()
    assert vc.on_values((), (), (), ts, now=5.0) == 50.0
    assert events == [([1], True)]


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
