"""Wall-clock soak tier (round-5 verdict ask 6, ISSUE 2 satellite).

The reference runs as a long-lived daemon (tools/dhtnode.cpp:480-545);
before this tier nothing here ran longer than a test.  A real-UDP
cluster sustains puts/gets/listens while nodes churn (join/leave) for
``OPENDHT_TPU_SOAK_SECS`` wall seconds (default 60; set it to 600+ for
the full ≥10-minute soak the verdict asked for), then asserts the
properties a daemon needs and a functional test cannot see:

- **bounded RSS growth**: the process RSS after warm-up must not keep
  climbing — leaked values/listeners/partial buffers show up here
  first (expiry sweeps: src/dht.cpp:1916-1927);
- **scheduler-queue stability**: lazy-cancelled jobs must not
  accumulate in any node's heap (opendht_tpu/scheduler.py's lazy
  deletion relies on the run loop draining stale entries);
- **listener / partial-buffer cleanup**: after the load stops and
  listeners are cancelled, every engine's reassembly buffer and
  listener map must drain (the fuzz tier checks cleanup after
  timeouts; this checks it under sustained load).

Prints one resource-report line (the verdict's ask) whether or not the
assertions trip.
"""

from __future__ import annotations

import concurrent.futures
import gc
import os
import socket
import time

import numpy as np
import pytest

# identity-less runners need no `cryptography` wheel (the lazy crypto
# binding in runtime/runner.py), so the soak runs in minimal containers
from opendht_tpu.infohash import InfoHash
from opendht_tpu.core.value import Value
from opendht_tpu.runtime.config import NodeStatus
from opendht_tpu.runtime.runner import DhtRunner

pytestmark = pytest.mark.slow

SOAK_SECS = float(os.environ.get("OPENDHT_TPU_SOAK_SECS", "60"))
N_STABLE = 4


def _rss_mb() -> float:
    """Current VmRSS in MiB (Linux procfs; 0.0 when unreadable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def _sched_len(runner: DhtRunner) -> int:
    return len(runner._dht._dht.scheduler._heap)


def _wait(pred, timeout=30.0, step=0.05) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return False


def test_scheduler_heap_o1_under_permanent_puts():
    """Round-10 soak guard: with the calendar-binned storage sweep,
    the scheduler heap must stay O(1) in the stored-key count — 10k
    puts may not cost 10k+ per-key republish/expiry heap entries (the
    pre-round-10 behavior).  Uses the PR-3 stale-entry gauge to assert
    lazy-deletion debt stays bounded too."""
    import socket as _socket

    from opendht_tpu import telemetry
    from opendht_tpu.runtime import Config, Dht
    from opendht_tpu.runtime.dht import STORAGE_CALENDAR_QUANTUM
    from opendht_tpu.scheduler import Scheduler
    from opendht_tpu.sockaddr import SockAddr

    clock = {"t": 10_000.0}
    cfg = Config()
    cfg.maintain_storage = True
    dht = Dht(lambda data, addr: 0, config=cfg,
              scheduler=Scheduler(clock=lambda: clock["t"]), has_v6=False)
    rng = np.random.default_rng(77)
    table = dht.tables[_socket.AF_INET]
    added = 0
    while added < 24:
        h = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        if table.insert(h, SockAddr("10.1.0.%d" % (added + 1), 4500),
                        now=clock["t"], confirm=2) is not None:
            added += 1

    n_keys = 10_000
    base = len(dht.scheduler._heap)
    for i in range(n_keys):
        assert dht.storage_store(InfoHash.get(f"perm-{i}"),
                                 Value(b"soak", value_id=1), clock["t"])
    grown = len(dht.scheduler._heap) - base
    # every key stored this tick shares ONE expiry bin and ONE
    # republish bin — the heap growth is bins, not keys
    assert grown <= 8, \
        f"{grown} heap entries for {n_keys} stored keys — per-key jobs?"
    assert len(dht.store) == n_keys

    # drive several republish horizons; the heap must stay bounded by
    # occupied calendar bins while every key keeps cycling
    peak = 0
    for _ in range(3):
        clock["t"] += 600.0 + STORAGE_CALENDAR_QUANTUM
        dht.scheduler.run()
        peak = max(peak, len(dht.scheduler._heap))
    assert peak < base + 200, \
        f"heap peaked at {peak} across republish horizons"
    stale = telemetry.get_registry().gauge(
        "dht_scheduler_stale_entries").value
    assert stale < 1000, f"stale-entry debt grew to {stale}"


def test_soak_cluster_resources():
    runners = []

    def spawn(bootstrap_port=None):
        r = DhtRunner()
        r.run(0)
        if bootstrap_port:
            r.bootstrap("127.0.0.1", bootstrap_port)
        runners.append(r)
        return r

    stats = {"puts": 0, "gets": 0, "listen_hits": 0, "churned": 0,
             "get_misses": 0, "op_timeouts": 0}
    rss0 = None
    sched_max = 0
    try:
        hub = spawn()
        for _ in range(N_STABLE - 1):
            spawn(hub.get_bound_port())
        assert _wait(lambda: all(
            r.get_status() is NodeStatus.CONNECTED for r in runners[1:])), \
            "cluster never connected"

        # standing listeners on fixed keys — puts during the soak must
        # keep flowing through them
        listen_keys = [InfoHash.get(f"soak-listen-{i}") for i in range(3)]
        tokens = []
        for i, key in enumerate(listen_keys):
            tokens.append(runners[1].listen(
                key, lambda vals, exp: (
                    stats.__setitem__(
                        "listen_hits", stats["listen_hits"] + len(vals))
                    or True)))

        churner = spawn(hub.get_bound_port())
        rng = np.random.default_rng(17)
        put_keys: list = []

        gc.collect()
        warm_end = time.monotonic() + min(10.0, SOAK_SECS * 0.25)
        t_end = time.monotonic() + SOAK_SECS
        next_churn = time.monotonic() + max(8.0, SOAK_SECS / 6)
        i = 0
        while time.monotonic() < t_end:
            i += 1
            key = (listen_keys[i % 3] if i % 5 == 0
                   else InfoHash.get(f"soak-{i}"))
            src = runners[1 + (i % (len(runners) - 1))]
            # futures.TimeoutError is only an alias of the builtin from
            # 3.11 — catch both so an op stall is data, not a crash
            try:
                if src.put_sync(key, Value(b"soak-%d" % i), timeout=20.0):
                    stats["puts"] += 1
                    put_keys.append(key)
            except (TimeoutError, concurrent.futures.TimeoutError):
                stats["op_timeouts"] += 1
            if put_keys and i % 3 == 0:
                k = put_keys[int(rng.integers(0, len(put_keys)))]
                try:
                    vals = hub.get_sync(k, timeout=20.0)
                    stats["gets"] += 1
                    if not vals:
                        stats["get_misses"] += 1
                except (TimeoutError, concurrent.futures.TimeoutError):
                    stats["op_timeouts"] += 1
            if time.monotonic() >= next_churn:
                # node churn: retire the churner, join a fresh one
                churner.join()
                runners.remove(churner)
                churner = spawn(hub.get_bound_port())
                stats["churned"] += 1
                next_churn = time.monotonic() + max(8.0, SOAK_SECS / 6)
            now = time.monotonic()
            if now >= warm_end:
                if rss0 is None:
                    gc.collect()
                    rss0 = _rss_mb()
                sched_max = max(sched_max, *(
                    _sched_len(r) for r in runners))

        assert stats["puts"] > 0 and stats["gets"] > 0, \
            f"soak did no work: {stats}"
        assert stats["listen_hits"] > 0, "standing listeners never fired"

        # ---- cleanup under load: cancel listeners, let queues settle
        for key, tok in zip(listen_keys, tokens):
            runners[1].cancel_listen(key, tok)
        time.sleep(2.0)
        gc.collect()
        rss_end = _rss_mb()

        for r in runners:
            dht = r._dht._dht
            # reassembly buffers drain (RX_MAX_PACKET_TIME is 10 s; the
            # soak's last fragmented value is older than the settle +
            # the next periodic sweep on any live node)
            assert _wait(lambda d=dht: len(d.engine._partials) == 0,
                         timeout=15.0), "partial-message buffer leaked"
        assert len(runners[1]._listeners) == 0, \
            "runner listener records leaked after cancel_listen"

        # scheduler heaps scale with LIVE STATE — every stored value
        # legitimately schedules expiry/republish jobs until it ages
        # out, so the bound is per stored value (measured ~5-8 heap
        # entries per put across node count), not a constant: a
        # constant would fail the advertised ≥10-minute soak on bound
        # arithmetic while a real leak (cancelled jobs accumulating
        # super-linearly) still blows the per-op envelope.
        assert sched_max < 1500 + 20 * stats["puts"], \
            f"scheduler queues grew super-linearly: max {sched_max} " \
            f"over {stats['puts']} puts"

        # bounded RSS growth after warm-up, same per-op envelope logic:
        # stored values own real memory until expiry, so allow a
        # generous per-put allowance on top of a fixed band (CPU jax
        # keeps compiling host-scan helpers early on); a per-op leak at
        # soak rates blows far past it, and the printed report line
        # makes slow drifts visible across runs.
        growth = (rss_end - rss0) if (rss0 and rss_end) else 0.0
        limit = 120.0 + 0.25 * stats["puts"]
        assert growth < limit, \
            f"RSS grew {growth:.1f} MiB over the soak (from " \
            f"{rss0:.1f}, limit {limit:.0f})"
    finally:
        report = (f"soak report: {SOAK_SECS:.0f}s, nodes={len(runners)} "
                  f"(+{stats['churned']} churned), puts={stats['puts']} "
                  f"gets={stats['gets']} (miss {stats['get_misses']}, "
                  f"timeouts {stats['op_timeouts']}) "
                  f"listen_hits={stats['listen_hits']}, "
                  f"rss {0.0 if rss0 is None else rss0:.0f}->"
                  f"{_rss_mb():.0f} MiB, sched-q max {sched_max}")
        print("\n" + report)
        for r in runners:
            try:
                r.join()
            except Exception:
                pass


def test_soak_burst_ingest():
    """Round-12 burst phase: spike traffic ~10x the steady rate for a
    few seconds through the continuous-batching wave builder and assert
    the properties the ISSUE names — the admission queue drains back to
    its (empty) baseline, no op sat in the queue longer than the
    deadline knob plus one wave period, and RSS stays bounded through
    the spike.  The deadline is widened to 50 ms here so host thread-
    scheduling jitter (single-digit ms on a loaded CI box) stays small
    against the bound being asserted."""
    from opendht_tpu import telemetry
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.runner import RunnerConfig

    DEADLINE = 0.05
    reg = telemetry.get_registry()
    reg.reset()
    runners = []
    try:
        for i in range(3):
            r = DhtRunner()
            r.run(0, RunnerConfig(dht_config=Config(
                ingest_deadline=DEADLINE)))
            if runners:
                r.bootstrap("127.0.0.1", runners[0].get_bound_port())
            runners.append(r)
        assert _wait(lambda: all(
            n.get_status() is NodeStatus.CONNECTED for n in runners[1:])), \
            "burst cluster never connected"
        src = runners[1]

        # ---- steady state: serial ops, one in flight at a time
        steady_end = time.monotonic() + 3.0
        steady_ops = 0
        while time.monotonic() < steady_end:
            src.put_sync(InfoHash.get(f"burst-steady-{steady_ops}"),
                         Value(b"steady", value_id=1), timeout=20.0)
            steady_ops += 1
            time.sleep(0.05)
        steady_rate = steady_ops / 3.0
        gc.collect()
        rss_before = _rss_mb()

        # ---- burst: ~10x the steady rate, async, from threads
        burst_n = max(int(steady_rate * 10 * 3.0), 60)
        done = []
        import threading
        all_done = threading.Event()

        def on_done(ok, ns):
            done.append(ok)
            if len(done) >= burst_n:
                all_done.set()

        def fire(lo, hi):
            for i in range(lo, hi):
                if i % 3 == 0:
                    src.get(InfoHash.get(f"burst-steady-{i % 17}"),
                            done_cb=on_done)
                else:
                    src.put(InfoHash.get(f"burst-{i}"),
                            Value(b"burst", value_id=2), done_cb=on_done)
        n_threads = 8
        per = -(-burst_n // n_threads)
        threads = [threading.Thread(target=fire,
                                    args=(t * per, min((t + 1) * per,
                                                       burst_n)))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all_done.wait(60.0), \
            f"burst ops stalled: {len(done)}/{burst_n} completed"

        # ---- queue depth returns to baseline (empty) after the spike
        depth = reg.gauge("dht_ingest_queue_depth")
        assert _wait(lambda: depth.value == 0, timeout=10.0), \
            f"ingest queue did not drain: depth {depth.value}"

        # ---- no op exceeded the deadline knob by more than one wave
        # period (deadline + the slowest observed wave launch).  The
        # log2 histogram rounds up: use the top bucket's LOWER edge as
        # the conservative observed max so bucket granularity cannot
        # fail a compliant run.
        qh = reg.histogram("dht_ingest_queue_seconds").to_dict()
        assert qh["count"] > 0, "no queue-wait samples recorded"
        observed_max_lb = qh["buckets"][-1][0] / 2.0
        wh = reg.histogram("dht_ingest_wave_seconds").to_dict()
        wave_max = wh["buckets"][-1][0] if wh["buckets"] else 0.0
        bound = DEADLINE + (DEADLINE + wave_max) + 0.02
        assert observed_max_lb <= bound, (
            f"an op sat >= {observed_max_lb * 1e3:.1f} ms in the ingest "
            f"queue (bound {bound * 1e3:.1f} ms = deadline + one wave "
            f"period + sched slack)")

        # ---- coalescing actually happened during the burst
        occ = reg.histogram("dht_ingest_wave_occupancy")
        assert occ.count > 0 and occ.sum / occ.count > 1.0, \
            "burst did not coalesce (mean occupancy <= 1)"

        # ---- RSS bounded through the spike
        gc.collect()
        growth = _rss_mb() - rss_before
        limit = 80.0 + 0.25 * burst_n
        assert growth < limit, \
            f"RSS grew {growth:.1f} MiB over a {burst_n}-op burst " \
            f"(limit {limit:.0f})"
        print(f"\nburst report: steady {steady_rate:.1f} ops/s, burst "
              f"{burst_n} ops, waves {occ.count}, mean occupancy "
              f"{occ.sum / max(occ.count, 1):.2f}, max queue-wait >= "
              f"{observed_max_lb * 1e3:.1f} ms (bound "
              f"{bound * 1e3:.1f}), rss +{growth:.1f} MiB")
    finally:
        for r in runners:
            r.join()
