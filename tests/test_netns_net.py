"""Real-kernel tier: DHT traffic across Linux network namespaces.

Closes the round-4 "real-kernel network tier" gap to the extent this
kernel allows: two cluster subprocesses in separate namespaces, a seed
node in the root namespace, IP forwarding between cluster subnets —
a put in one namespace is read from the other, every packet crossing
two real veth devices and the kernel forwarding path (reference
topology: python/tools/dht/virtual_network_builder.py).  Loss/delay
shaping stays environment-blocked (no sch_netem in this kernel) and is
probed, not assumed.
"""

import secrets

import pytest

from opendht_tpu.testing.netns_net import (NetnsClusterNet, netem_available,
                                           netns_available)

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not netns_available(),
                       reason="netns/veth not available on this kernel"),
]


def test_put_get_across_real_kernel_namespaces():
    from opendht_tpu import DhtRunner

    seed = DhtRunner()
    seed.run(0)                       # binds 0.0.0.0 → reachable on
    port = seed.get_bound_port()      # every veth gateway address
    net = NetnsClusterNet()
    seed_alive = True
    try:
        a = net.add_cluster(4)
        b = net.add_cluster(4)
        a.bootstrap(net.gateway_addr(0), port)
        b.bootstrap(net.gateway_addr(1), port)

        key = secrets.token_bytes(20)
        payload = b"netns-tier-" + secrets.token_hex(8).encode()
        assert a.put(key, payload)
        vals = b.get(key)
        assert payload in vals, (vals, "cross-namespace get missed")

        # Now FORCE the forwarded a<->b path: with the root-namespace
        # seed gone, a second put/get can only succeed if cluster-b
        # nodes reach cluster-a nodes directly across the two veth
        # subnets through kernel forwarding (8 cluster nodes > the
        # seedless minimum; the first round-trip above warmed the
        # cross-cluster routing tables).
        seed.shutdown()
        seed.join()
        seed_alive = False
        key2 = secrets.token_bytes(20)
        payload2 = b"netns-fwd-" + secrets.token_hex(8).encode()
        assert a.put(key2, payload2)
        vals2 = b.get(key2)
        assert payload2 in vals2, \
            (vals2, "cross-cluster forwarding path not exercised")

        # the clusters really live on distinct kernel subnets
        assert net.cluster_addr(0) != net.cluster_addr(1)
    finally:
        net.close()
        if seed_alive:
            seed.shutdown()
            seed.join()


def test_netem_probe_is_recorded():
    """The loss/delay half of the reference tier needs sch_netem; this
    probe documents the environment bound rather than silently skipping
    (if the kernel ever gains netem, this test will flag that the tier
    can now be extended)."""
    avail = netem_available()                   # probe must not crash
    assert avail in (True, False)
    if avail:
        pytest.skip("netem IS available here — extend the tier with "
                    "loss/delay shaping (see netns_net.py docstring)")
