"""Host InfoHash unit tests — ports the reference's CppUnit suite
(reference: tests/infohashtester.cpp:38-138) plus extras."""

import pytest

from opendht_tpu.infohash import InfoHash, PkId

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


def test_constructors():
    # tests/infohashtester.cpp:38-74
    null_hash = InfoHash()
    assert len(null_hash) == 20
    assert not null_hash

    too_short = bytes([0, 1, 2, 3, 4, 5, 6, 7, 8])
    h = InfoHash(too_short)
    assert len(h) == 20
    assert h.hex() == "0000000000000000000000000000000000000000"

    enough = bytes([1, 2, 3, 4, 5, 6, 7, 8, 9, 10] * 2)
    h = InfoHash(enough)
    assert bytes(h) == enough

    too_long = enough + b"\xb0"
    h = InfoHash(too_long)
    assert bytes(h) == enough

    h2 = InfoHash("0102030405060708090A0102030405060708090A")
    assert bytes(h2) == enough

    # malformed hex → null (reference parses via sscanf, yielding garbage-
    # tolerant behavior; we specify null)
    assert not InfoHash("zz02030405060708090A0102030405060708090A")


def test_comparators():
    # tests/infohashtester.cpp:77-101
    null_hash = InfoHash()
    min_hash = InfoHash("0000000000000000000000000000000000111110")
    max_hash = InfoHash("0111110000000000000000000000000000000000")

    assert min_hash == min_hash
    assert min_hash == InfoHash("0000000000000000000000000000000000111110")
    assert not (min_hash == max_hash)
    assert min_hash != max_hash
    assert null_hash < min_hash
    assert null_hash < max_hash
    assert min_hash < max_hash
    assert not (min_hash < null_hash)
    assert not (max_hash < min_hash)
    assert not (min_hash < min_hash)
    assert bool(max_hash)
    assert not bool(null_hash)


def test_lowbit():
    # tests/infohashtester.cpp:104-111
    assert InfoHash().lowbit() == -1
    assert InfoHash("0000000000000000000000000000000000000010").lowbit() == 155
    assert InfoHash("0100000000000000000000000000000000000000").lowbit() == 7


def test_common_bits():
    # tests/infohashtester.cpp:114-122
    null_hash = InfoHash()
    min_hash = InfoHash("0000000000000000000000000000000000000010")
    max_hash = InfoHash("0100000000000000000000000000000000000000")
    assert InfoHash.common_bits(null_hash, null_hash) == 160
    assert InfoHash.common_bits(null_hash, min_hash) == 155
    assert InfoHash.common_bits(null_hash, max_hash) == 7
    assert InfoHash.common_bits(min_hash, max_hash) == 7


def test_xor_cmp():
    # tests/infohashtester.cpp:125-138 (includes circular-distance cases)
    null_hash = InfoHash()
    min_hash = InfoHash("0000000000000000000000000000000000000010")
    max_hash = InfoHash("0100000000000000000000000000000000000000")
    assert min_hash.xor_cmp(null_hash, max_hash) == -1
    assert min_hash.xor_cmp(max_hash, null_hash) == 1
    assert min_hash.xor_cmp(min_hash, max_hash) == -1
    assert min_hash.xor_cmp(max_hash, min_hash) == 1
    assert null_hash.xor_cmp(min_hash, max_hash) == -1
    assert null_hash.xor_cmp(max_hash, min_hash) == 1
    assert max_hash.xor_cmp(null_hash, min_hash) == -1
    assert max_hash.xor_cmp(min_hash, null_hash) == 1


def test_get_and_bits():
    h = InfoHash.get("hello")
    # SHA1("hello")
    assert h.hex() == "aaf4c61ddcc5e8a2dabede0f3b482cd9aea9434d"
    assert h.get_bit(0) == bool(h[0] & 0x80)
    flipped = h.set_bit(0, not h.get_bit(0))
    assert flipped.get_bit(0) != h.get_bit(0)
    assert flipped.set_bit(0, h.get_bit(0)) == h

    p = PkId.get(b"hello")
    assert len(p) == 32  # SHA256 for 32-byte ids (src/crypto.cpp:208-227)


def test_random_and_roundtrip():
    a = InfoHash.get_random()
    b = InfoHash.get_random()
    assert a != b  # 2^-160 failure probability
    assert InfoHash(a.hex()) == a
    assert InfoHash.from_int(a.to_int()) == a
    assert 0.0 <= a.to_float() < 1.0


def test_xor():
    a = InfoHash.get_random()
    assert not a.xor(a)
    assert a.xor(InfoHash()) == a
