"""Storage-layer tests: Storage refresh-or-insert/expire/quota buckets,
ValueCache add/refresh/expire semantics, OpCache/SearchCache listen dedup
(reference contracts: src/storage.h, value_cache.h, op_cache.{h,cpp})."""

from opendht_tpu.core.op_cache import OpCache, OpValueCache, SearchCache, OP_LINGER
from opendht_tpu.core.storage import (
    MAX_VALUES, NODE_EXPIRE_TIME, Storage, StorageBucket,
)
from opendht_tpu.core.listener import Listener
from opendht_tpu.core.value import Query, TypeStore, Value, ValueType
from opendht_tpu.core.value_cache import ValueCache
from opendht_tpu.infohash import InfoHash
from opendht_tpu.utils import TIME_MAX
import pytest

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick

KEY = InfoHash.get("key")


def val(vid, data=b"x", type_id=0):
    return Value(data, value_id=vid, type_id=type_id)


# ------------------------------------------------------------------- Storage
def test_store_insert_and_refresh():
    st = Storage()
    v1 = val(1, b"aaaa")
    slot, d = st.store(KEY, v1, created=10.0, expiration=100.0)
    assert slot is not None and d.values_diff == 1 and d.size_diff == 4
    assert st.total_size == 4 and st.value_count() == 1

    # same object again: pure refresh, no change reported
    slot2, d2 = st.store(KEY, v1, created=20.0, expiration=100.0)
    assert slot2 is None and d2.values_diff == 0 and d2.size_diff == 0
    assert st.values[0].created == 20.0

    # same id, new object: replace, size diff reported
    v1b = val(1, b"aaaaaaaa")
    slot3, d3 = st.store(KEY, v1b, created=30.0, expiration=200.0)
    assert slot3 is not None and d3.size_diff == 4 and d3.values_diff == 0
    assert st.get_by_id(1).data == b"aaaaaaaa" and st.total_size == 8


def test_store_cap():
    st = Storage()
    for i in range(MAX_VALUES):
        st.store(KEY, val(i + 1), 0.0, 100.0)
    slot, d = st.store(KEY, val(MAX_VALUES + 1), 0.0, 100.0)
    assert slot is None and d.values_diff == 0
    assert st.value_count() == MAX_VALUES


def test_expire_partitions_and_notifies():
    st = Storage()
    st.store(KEY, val(1, b"aa"), 0.0, 50.0)
    st.store(KEY, val(2, b"bbb"), 0.0, 150.0)
    size_diff, expired = st.expire(KEY, now=100.0)
    assert size_diff == -2
    assert [v.id for v in expired] == [1]
    assert st.value_count() == 1 and st.total_size == 3


def test_expire_drops_stale_remote_listeners():
    st = Storage()
    node = object()
    st.listeners[node] = {1: Listener(0.0, Query()), 2: Listener(90.0, Query())}
    st.expire(KEY, now=NODE_EXPIRE_TIME + 50.0)
    assert list(st.listeners[node]) == [2]
    st.expire(KEY, now=NODE_EXPIRE_TIME + 200.0)
    assert node not in st.listeners


def test_remove_and_clear():
    st = Storage()
    st.store(KEY, val(1, b"aa"), 0.0, 100.0)
    st.store(KEY, val(2, b"bbb"), 0.0, 100.0)
    d = st.remove(KEY, 1)
    assert d.size_diff == -2 and d.values_diff == -1
    d2 = st.clear()
    assert d2.size_diff == -3 and d2.values_diff == -1
    assert st.empty()


def test_storage_bucket_quota_tracking():
    b = StorageBucket()
    st = Storage()
    v1, v2 = val(1, b"aaaa"), val(2, b"bb")
    st.store(KEY, v1, 0.0, 50.0, bucket=b)
    st.store(KEY, v2, 0.0, 100.0, bucket=b)
    assert b.size == 6
    assert b.get_oldest() == (KEY, 1)          # earliest expiration
    st.expire(KEY, now=60.0)                   # v1 expires → erased from bucket
    assert b.size == 2 and b.get_oldest() == (KEY, 2)
    st.remove(KEY, 2)
    assert b.size == 0 and b.get_oldest() is None


# ---------------------------------------------------------------- ValueCache
def _collector():
    events = []
    return events, lambda vals, expired: events.append(
        (sorted(v.id for v in vals), expired))


def test_value_cache_add_refresh_expire():
    types = TypeStore()
    types.register_type(ValueType(1, "t", expiration=100.0))
    events, cb = _collector()
    vc = ValueCache(cb)

    nxt = vc.on_values([val(1, type_id=1), val(2, type_id=1)], (), (), types, now=0.0)
    assert events == [([1, 2], False)]
    assert nxt == 100.0

    # peer refreshes id 1 → no event, expiration extended
    events.clear()
    vc.on_values((), [1], (), types, now=50.0)
    assert events == []

    # sweep at t=120: id 2 (exp 100) dies, id 1 (exp 150) survives
    nxt = vc.expire_values(now=120.0)
    assert events == [([2], True)]
    assert nxt == 150.0 and len(vc) == 1

    # peer-side explicit expire
    events.clear()
    vc.on_values((), (), [1], types, now=130.0)
    assert events == [([1], True)]
    assert len(vc) == 0


def test_value_cache_clear_signals_expired():
    events, cb = _collector()
    vc = ValueCache(cb)
    vc.on_values([val(5)], (), (), TypeStore(), now=0.0)
    events.clear()
    vc.clear()
    assert events == [([5], True)]


# ------------------------------------------------------------------ OpCaches
def test_op_value_cache_refcounting():
    events, cb = _collector()
    c = OpValueCache(cb)
    v = val(1)
    c.on_value([v], False)          # ref 1 → new
    c.on_value([v], False)          # ref 2 → no event
    assert events == [([1], False)]
    events.clear()
    c.on_value([v], True)           # ref 1 → no event
    assert events == []
    c.on_value([v], True)           # ref 0 → expired
    assert events == [([1], True)]


def test_op_cache_replay_and_linger():
    op = OpCache(now=0.0)
    got = []
    op.on_value([val(1), val(2)], False)
    op.add_listener(1, lambda vals, exp: got.append([v.id for v in vals]) or True,
                    None, None)
    assert got == [[1, 2]]          # replay on attach
    assert op.get_expiration() == TIME_MAX
    op.remove_listener(1, now=10.0)
    assert op.is_done()
    assert op.get_expiration() == 10.0 + OP_LINGER
    assert not op.is_expired(now=10.0 + OP_LINGER - 1)
    assert op.is_expired(now=10.0 + OP_LINGER + 1)


def test_op_cache_false_return_unsubscribes():
    op = OpCache(now=0.0)
    got = []

    def once(vals, exp):
        got.append([v.id for v in vals])
        return False                     # one-shot listener

    # empty cache → no replay fires, listener stays armed
    op.add_listener(1, once, None, None)
    assert not op.is_done() and got == []
    # first real batch satisfies and unsubscribes it
    op.on_value([val(1)], False)
    assert got == [[1]] and op.is_done()

    # a one-shot attaching to a warm cache is satisfied from replay
    op.add_listener(2, once, None, None)
    assert got == [[1], [1]] and op.is_done()

    # a None-returning (plain Python) callback stays subscribed
    op.add_listener(3, lambda vals, exp: got.append("keep"), None, None)
    op.on_value([val(2)], False)
    assert not op.is_done() and got[-2:] == ["keep", "keep"]


def test_op_cache_feed_survives_linger():
    """A push arriving during the listener-less linger must not tear down
    the network op; a re-listen then sees the fresh value."""
    clk = [0.0]
    sc = SearchCache(clock=lambda: clk[0])
    feeds = []
    tok = sc.listen(lambda v, e: True, Query(), None,
                    lambda q, cb: feeds.append(cb) or 1, now=0.0)
    sc.cancel_listen(tok, now=0.0)
    clk[0] = 5.0
    assert feeds[0]([val(7)], False) is True     # op stays subscribed
    got = []
    sc.listen(lambda v, e: got.append([x.id for x in v]), Query(), None,
              lambda q, cb: feeds.append(cb) or 2, now=5.0)
    assert len(feeds) == 1                       # reused, not re-subscribed
    assert got == [[7]]                          # fresh value replayed


def test_op_value_cache_none_return_keeps_subscription():
    c = OpValueCache(lambda vals, exp: None)     # plain Python callback
    assert c.on_value([val(1)], False) is True
    assert c.on_value([val(1)], True) is True


def test_field_value_index_contained_in_compares_values():
    from opendht_tpu.core.value import FieldValueIndex, Select
    a = FieldValueIndex(val(1), Select("SELECT id"))
    b = FieldValueIndex(val(2), Select("SELECT id"))
    a2 = FieldValueIndex(val(1), Select("SELECT id"))
    assert not a.contained_in(b)
    assert a.contained_in(a2)


def test_search_cache_dedups_network_ops():
    sc = SearchCache()
    started = []

    def on_listen(q, cb):
        started.append(q)
        return 100 + len(started)

    q = Query()
    t1 = sc.listen(lambda v, e: True, q, None, on_listen, now=0.0)
    t2 = sc.listen(lambda v, e: True, Query(), None, on_listen, now=0.0)
    assert len(started) == 1        # second listen satisfied by the first op
    assert t1 != t2

    # a *narrower* query is satisfied by the broad one → still one op
    sc.listen(lambda v, e: True, Query("WHERE id=5"), None, on_listen, now=0.0)
    assert len(started) == 1

    # cancel both listeners on op 1; after linger the op expires
    cancelled = []
    sc.cancel_listen(t1, now=1.0)
    sc.cancel_listen(t2, now=1.0)
    nxt = sc.expire(now=1.0, on_cancel=cancelled.append)
    assert cancelled == []          # still lingering (third listener active)
    assert nxt <= 1.0 + OP_LINGER or nxt == TIME_MAX


def test_search_cache_expires_idle_ops():
    sc = SearchCache()
    tok = sc.listen(lambda v, e: True, Query(), None, lambda q, cb: 42, now=0.0)
    sc.cancel_listen(tok, now=0.0)
    cancelled = []
    sc.expire(now=OP_LINGER + 1.0, on_cancel=cancelled.append)
    assert cancelled == [42]
    assert len(sc) == 0


def test_search_cache_get_merges_ops():
    sc = SearchCache()
    caps = {}

    def on_listen(q, cb):
        caps[len(caps)] = cb
        return len(caps)

    sc.listen(lambda v, e: True, Query("SELECT id"), None, on_listen, now=0.0)
    sc.listen(lambda v, e: True, Query("WHERE id=1"), None, on_listen, now=0.0)
    assert len(caps) == 2           # neither query satisfies the other
    caps[0]([val(1)], False)
    caps[1]([val(2)], False)
    assert sorted(v.id for v in sc.get()) == [1, 2]
    assert sc.get_by_id(2).id == 2
