"""Continuous-batching ingest (round 12, runtime/wave_builder.py).

Pins the wave builder's contract: live search refills coalesce into
shared ``find_closest_nodes_batched`` launches (fill- OR
deadline-triggered), the ``ingest_batching="off"`` escape hatch is
result-equivalent to the per-op dispatch path, backpressure sheds NEW
ops at admission (counted) and never an in-flight search, and the
PR-3/PR-4 observability spine sees every wave (occupancy/time-in-queue
histograms, per-op trace spans linked to the carrying wave span).
"""

from __future__ import annotations

import random
import socket as _socket
import time

import numpy as np

from opendht_tpu import telemetry, tracing
from opendht_tpu.core.value import Value
from opendht_tpu.infohash import InfoHash
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.runtime.live_search import SEARCH_NODES
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

AF = _socket.AF_INET


def make_dht(clock, n_nodes=12, **cfg_kw):
    """A v4-only Dht on a virtual clock with a populated table and a
    swallow-everything transport (deterministic peer ids)."""
    cfg = Config(**cfg_kw)
    dht = Dht(lambda data, addr: 0, config=cfg,
              scheduler=Scheduler(clock=lambda: clock["t"]),
              has_v6=False)
    rng = np.random.default_rng(1234)
    table = dht.tables[AF]
    added = 0
    while added < n_nodes:
        h = InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
        if table.insert(h, SockAddr("10.9.0.%d" % (added + 1), 4500),
                        now=clock["t"], confirm=2) is not None:
            added += 1
    return dht


def spy_batched(dht):
    """Wrap dht.find_closest_nodes_launch, recording (Q, af, k) per
    underlying device resolve AT DISPATCH.  The launch seam is the one
    both pipeline depths share: find_closest_nodes_batched (the depth-1
    path) delegates to it, and the depth-2+ pipeline dispatches through
    it directly."""
    calls = []
    orig = dht.find_closest_nodes_launch

    def wrapper(targets, af, count=8):
        calls.append((len(targets), af, count))
        return orig(targets, af, count)

    dht.find_closest_nodes_launch = wrapper
    return calls


def _occ(reg=None):
    return (reg or telemetry.get_registry()).histogram(
        "dht_ingest_wave_occupancy")


def test_fill_trigger_coalesces_concurrent_ops():
    """fill_target ops queued in one pump ride ONE [Q] launch."""
    clock = {"t": 1000.0}
    dht = make_dht(clock, ingest_fill_target=4, ingest_deadline=5.0)
    calls = spy_batched(dht)
    occ0 = _occ().count
    done = []
    for i in range(4):
        dht.get(InfoHash.get(f"wave-fill-{i}"),
                done_cb=lambda ok, ns: done.append(ok))
    assert not calls, "refills must queue, not dispatch per-op"
    assert dht.wave_builder.pending() == 4
    dht.scheduler.run()          # fill target pulled the trigger to now
    assert calls == [(4, AF, SEARCH_NODES)], calls
    assert dht.wave_builder.pending() == 0
    occ = _occ()
    assert occ.count == occ0 + 1
    # every search got its candidates and is stepping
    for i in range(4):
        sr = dht.searches[AF][InfoHash.get(f"wave-fill-{i}")]
        assert not sr.refill_pending and len(sr.nodes) > 0


def test_deadline_trigger_fires_partial_wave():
    """Below the fill target, the oldest entry's deadline fires the
    wave — a trickle op is never stranded."""
    clock = {"t": 2000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002)
    calls = spy_batched(dht)
    dht.get(InfoHash.get("wave-dl-a"))
    dht.get(InfoHash.get("wave-dl-b"))
    dht.scheduler.run()
    assert not calls, "deadline not reached: no launch yet"
    clock["t"] += 0.0025
    dht.scheduler.run()
    assert calls == [(2, AF, SEARCH_NODES)]


def test_off_path_is_result_equivalent():
    """batching="off" resolves synchronously through the identical
    per-op launch: same rows, same order, as the batched wave and as a
    direct find_closest_nodes_batched call."""
    clock = {"t": 3000.0}
    off = make_dht(clock, ingest_batching="off")
    assert not off.wave_builder.enabled
    targets = [InfoHash.get(f"equiv-{i}") for i in range(5)]
    got = []
    for t in targets:
        off.wave_builder.submit(t, AF, SEARCH_NODES,
                                lambda nodes: got.append(nodes))
    assert len(got) == 5, "off path must resolve synchronously"
    direct = off.find_closest_nodes_batched(targets, AF, SEARCH_NODES)
    assert [[n.id for n in row] for row in got] == \
        [[n.id for n in row] for row in direct]

    # and the batched path returns the same candidate rows (same table
    # content, same kernel) once its wave fires
    on = make_dht(clock, ingest_fill_target=5, ingest_deadline=5.0)
    got_on = []
    for t in targets:
        on.wave_builder.submit(t, AF, SEARCH_NODES,
                               lambda nodes: got_on.append(nodes))
    on.scheduler.run()
    assert [[n.id for n in row] for row in got_on] == \
        [[n.id for n in row] for row in direct]


def test_admission_shed_on_full_queue_counted():
    """Over ingest_queue_max, a NEW op is refused at admission with a
    counted drop; queued (in-flight) lookups are untouched."""
    clock = {"t": 4000.0}
    dht = make_dht(clock, ingest_queue_max=2, ingest_fill_target=64,
                   ingest_deadline=5.0)
    reg = telemetry.get_registry()
    shed_c = reg.counter("dht_ingest_sheds_total", op="get",
                         reason="queue_full")
    shed0 = shed_c.value
    results = []
    dht.get(InfoHash.get("shed-a"), done_cb=lambda ok, ns:
            results.append(("a", ok)))
    dht.get(InfoHash.get("shed-b"), done_cb=lambda ok, ns:
            results.append(("b", ok)))
    assert dht.wave_builder.pending() == 2
    dht.get(InfoHash.get("shed-c"), done_cb=lambda ok, ns:
            results.append(("c", ok)))
    assert ("c", False) in results, "shed op must fail fast at admission"
    assert shed_c.value == shed0 + 1
    assert dht.wave_builder.pending() == 2, \
        "a shed op must not enqueue work"
    # a shed listen returns the None sentinel (no subscription leaked;
    # distinct from the pre-existing 0 = satisfied-by-local-values stop)
    assert dht.listen(InfoHash.get("shed-l"),
                      lambda vals, exp: True) is None
    # the queued ops still complete when their wave fires
    dht.scheduler.run()
    clock["t"] += 6.0
    dht.scheduler.run()
    for key in ("shed-a", "shed-b"):
        sr = dht.searches[AF][InfoHash.get(key)]
        assert len(sr.nodes) > 0


def test_admission_rate_limiter_quota():
    """ingest_admit_per_sec rides the same sliding-window RateLimiter
    as the net engine's ingress quotas."""
    clock = {"t": 5000.0}
    dht = make_dht(clock, ingest_admit_per_sec=2, ingest_deadline=5.0)
    results = []
    for i in range(3):
        dht.get(InfoHash.get(f"quota-{i}"),
                done_cb=lambda ok, ns, _i=i: results.append((_i, ok)))
    assert (2, False) in results
    assert dht.wave_builder.pending() == 2
    clock["t"] += 1.1              # window slides: admissions resume
    dht.scheduler.sync_time()
    dht.get(InfoHash.get("quota-late"),
            done_cb=lambda ok, ns: results.append(("late", ok)))
    assert ("late", False) not in results
    assert dht.wave_builder.pending() == 3


def test_pending_refill_defers_bad_node_expiry():
    """A step before the wave lands must not expire the (legitimately
    empty) search: 0 >= min(0, MAX) is suspended while refill_pending."""
    clock = {"t": 6000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002)
    dht.get(InfoHash.get("pending-expire"))
    sr = dht.searches[AF][InfoHash.get("pending-expire")]
    assert sr.refill_pending and not sr.nodes
    dht._search_step(sr)
    assert not sr.expired, \
        "search expired before its coalesced refill landed"
    clock["t"] += 0.0025
    dht.scheduler.run()
    assert not sr.refill_pending and len(sr.nodes) > 0 and not sr.expired


def test_per_op_trace_spans_link_to_wave_span():
    """Each carried op gets a dht.ingest.op span under ITS trace,
    linked to the dht.search.wave (mode="ingest") span of the wave
    that carried it (ISSUE tentpole observability)."""
    clock = {"t": 7000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=5.0)
    tr = tracing.get_tracer()
    roots = [tracing.TraceContext.new_root() for _ in range(2)]
    for i, ctx in enumerate(roots):
        with tracing.activate(ctx):
            dht.get(InfoHash.get(f"trace-{i}"))
    dht.scheduler.run()
    spans = tr.dump()["spans"]
    waves = [s for s in spans if s["name"] == "dht.search.wave"
             and s["attrs"].get("mode") == "ingest"
             and s["attrs"].get("occupancy") == 2]
    assert waves, "no ingest-mode wave span recorded"
    wave = waves[-1]
    op_spans = [s for s in spans if s["name"] == "dht.ingest.op"
                and s["attrs"].get("wave_span") == wave["span_id"]]
    assert len(op_spans) == 2
    got_traces = {s["trace_id"] for s in op_spans}
    want_traces = {c.trace_hex for c in roots}
    assert got_traces == want_traces, \
        "op spans must live in the originating ops' traces"


def test_virtualnet_put_get_equivalence_on_vs_off():
    """End-to-end pin: the same virtual cluster + workload returns the
    same values and lands them on the same storers with batching on and
    off (the acceptance-criteria equivalence, in-process twin of the
    burst-ingest CI smoke)."""
    from opendht_tpu.testing.virtual_net import VirtualNet

    def run(batching: str):
        random.seed(99)
        net = VirtualNet(seed=7)
        cfg = lambda i: Config(  # noqa: E731
            node_id=InfoHash.get(f"wb-eq-node-{i}"),
            ingest_batching=batching)
        nodes = [net.add_node(cfg(i)) for i in range(6)]
        for n in nodes[1:]:
            net.bootstrap_node(n, nodes[0])
        net.run(max_time=30.0)
        key = InfoHash.get("wb-eq-key")
        done = {}
        nodes[1].put(key, Value(b"wb-equivalence", value_id=7),
                     lambda ok, ns: done.setdefault("put", ok))
        net.run(max_time=30.0)
        got = []
        nodes[2].get(key, get_cb=lambda vals: got.extend(vals) or True,
                     done_cb=lambda ok, ns: done.setdefault("get", ok))
        net.run(max_time=30.0)
        storers = sorted(bytes(d.myid).hex()
                         for d in net.storers_of(key))
        return (done, sorted(v.data for v in got), storers)

    done_on, vals_on, storers_on = run("on")
    done_off, vals_off, storers_off = run("off")
    assert done_on.get("put") and done_off.get("put")
    assert vals_on == vals_off == [b"wb-equivalence"]
    assert storers_on == storers_off


def test_snapshot_surfaces_ingest_state():
    clock = {"t": 8000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=5.0)
    dht.get(InfoHash.get("snap-a"))
    dht.get(InfoHash.get("snap-b"))
    dht.scheduler.run()
    snap = dht.wave_builder.snapshot()
    assert snap["batching"] == "on"
    assert snap["waves"] >= 1
    assert snap["occupancy_mean"] >= 1.0
    assert snap["queue_depth"] == 0
    # round 20: the pipeline state is part of the ops surface
    assert snap["pipeline_depth"] == 2
    assert snap["inflight"] == 0, "host-scan waves drain inline"
    assert snap["inflight_peak"] >= 1
    # the series the proxy /stats route exports are registered
    prom = telemetry.get_registry().prometheus()
    for series in ("dht_ingest_queue_depth", "dht_ingest_wave_occupancy",
                   "dht_ingest_queue_seconds", "dht_ingest_waves_total",
                   "dht_ingest_pipeline_inflight",
                   "dht_ingest_pipeline_inflight_peak"):
        assert series in prom, series


def test_scanner_snapshot_has_ingest_section():
    """dhtscanner --json surfaces the wave builder's live state (round
    12 ops surface) — runs crypto-less via the lazy tools.common
    import, like the soak harness."""
    from opendht_tpu.runtime.runner import DhtRunner
    from opendht_tpu.tools.dhtscanner import topology_snapshot

    r = DhtRunner()
    try:
        r.run(0)
        snap = topology_snapshot(r)
        ing = snap["ingest"]
        assert ing["batching"] == "on"
        for field in ("queue_depth", "queue_max", "waves",
                      "occupancy_p50", "occupancy_p95",
                      "queue_seconds_p95", "sheds", "fill_target",
                      "deadline_s", "pipeline_depth", "inflight",
                      "inflight_peak"):
            assert field in ing, field
        assert ing["pipeline_depth"] == 2
    finally:
        r.join()


def test_submit_from_sibling_due_job_same_sweep():
    """Review regression: Scheduler.run() nulls job.time on every due
    job BEFORE executing the sweep, so a submit() issued from another
    due job (a search step's refill) while the wave deadline job is in
    the same sweep must not crash _arm (it compared t < None) — and the
    wave that fires later in the sweep must carry the new entry too."""
    clock = {"t": 9000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002)
    calls = spy_batched(dht)
    got = []
    dht.wave_builder.submit(InfoHash.get("sweep-a"), AF, SEARCH_NODES,
                            lambda nodes: got.append("a"))
    # a sibling job due EARLIER in the same sweep submits mid-sweep,
    # while the wave job's heap entry already has time = None
    dht.scheduler.add(clock["t"] + 0.001, lambda: dht.wave_builder.submit(
        InfoHash.get("sweep-b"), AF, SEARCH_NODES,
        lambda nodes: got.append("b")))
    clock["t"] += 0.0025
    dht.scheduler.run()
    assert got == ["a", "b"], got
    assert calls and calls[0][0] == 2, calls
    assert dht.wave_builder.pending() == 0


def test_runner_listen_shed_resolves_zero_no_record():
    """Review regression: a backend listen shed at ingest admission
    must resolve the runner future to the 0 sentinel WITHOUT
    registering a runner listener record (a proxy hot-swap would
    otherwise faithfully re-subscribe a subscription that never
    existed)."""
    from opendht_tpu.runtime import Config
    from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig

    r = DhtRunner()
    try:
        # queue_max=0 sheds every new op at admission
        r.run(0, RunnerConfig(dht_config=Config(ingest_queue_max=0)))
        fut = r.listen(InfoHash.get("shed-runner-listen"),
                       lambda vals, exp: True)
        assert fut.result(10.0) == 0
        assert len(r._listeners) == 0, "shed listen leaked a record"
    finally:
        r.join()


def test_failed_launch_requeues_then_exhausts():
    """Review regression: a transient device error on a wave launch
    must NOT fail the carried (already admitted) searches — entries
    re-queue for later waves; only after the retry budget is spent do
    they scatter empty (persistent failure)."""
    clock = {"t": 10_000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002)
    from opendht_tpu.runtime.wave_builder import _LAUNCH_RETRIES
    from opendht_tpu import telemetry
    fail = {"n": 0}
    orig = dht.find_closest_nodes_launch

    def flaky(targets, af, count=8):
        if fail["n"] > 0:
            fail["n"] -= 1
            raise RuntimeError("transient device error")
        return orig(targets, af, count)

    # the launch seam covers both pipeline depths (see spy_batched)
    dht.find_closest_nodes_launch = flaky
    failures = telemetry.get_registry().counter(
        "dht_ingest_wave_failures_total")
    f0 = failures.value

    # one transient failure: the retry wave succeeds and the search
    # gets its candidates — the op is never failed
    fail["n"] = 1
    got = []
    dht.wave_builder.submit(InfoHash.get("retry-ok"), AF, SEARCH_NODES,
                            lambda nodes: got.append(nodes))
    for _ in range(_LAUNCH_RETRIES + 1):
        clock["t"] += 0.0025
        dht.scheduler.sync_time()
        dht.scheduler.run()
    assert got and len(got[0]) > 0, "retry wave never delivered"
    assert failures.value == f0 + 1

    # persistent failure: after the retry budget the entry scatters
    # empty (the search then expires honestly)
    fail["n"] = _LAUNCH_RETRIES + 1
    got2 = []
    dht.wave_builder.submit(InfoHash.get("retry-dead"), AF, SEARCH_NODES,
                            lambda nodes: got2.append(nodes))
    for _ in range(_LAUNCH_RETRIES + 2):
        clock["t"] += 0.0025
        dht.scheduler.sync_time()
        dht.scheduler.run()
    assert got2 == [[]], got2
    assert dht.wave_builder.pending() == 0


# ================================================== round 20: pipeline
class _FakeHandle:
    """Stand-in BatchedResolve with controllable readiness — lets the
    tests hold a wave in flight deterministically (a real host-scan
    resolve is ready the moment it is launched)."""

    def __init__(self, results, *, ok=False, fail=False):
        self._results = results
        self.ok = ok
        self.fail = fail
        self.shard_t = 1

    def ready(self):
        return self.ok

    def consume(self):
        if self.fail:
            raise RuntimeError("transient device error at consume")
        return self._results


def fake_launch(dht, *, ok=False, fail=False):
    """Replace the launch seam with deferred fake handles; returns the
    handle list for later readiness flips."""
    handles = []

    def launch(targets, af, count=8):
        h = _FakeHandle([[] for _ in targets], ok=ok, fail=fail)
        handles.append(h)
        return h

    dht.find_closest_nodes_launch = launch
    return handles


def _pump(dht, clock, dt=0.0025):
    clock["t"] += dt
    dht.scheduler.sync_time()
    dht.scheduler.run()


def test_pipeline_holds_two_waves_inflight_and_drains_fifo():
    """The tentpole shape: wave N+1 fills and launches while wave N is
    still on device (in-flight gauge peaks at the pipeline depth), and
    the drainer scatters strictly oldest-first once results land."""
    clock = {"t": 20_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002)
    assert dht.wave_builder.pipeline_depth == 2
    handles = fake_launch(dht)
    reg = telemetry.get_registry()
    got = []
    roots = [tracing.TraceContext.new_root() for _ in range(4)]
    for i, name in enumerate(("w1-a", "w1-b")):
        with tracing.activate(roots[i]):
            dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                    lambda nodes, n=name: got.append(n))
    dht.scheduler.run()
    assert len(handles) == 1 and got == [], "wave 1 must stay in flight"
    assert dht.wave_builder.snapshot()["inflight"] == 1
    for i, name in enumerate(("w2-a", "w2-b")):
        with tracing.activate(roots[2 + i]):
            dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                    lambda nodes, n=name: got.append(n))
    _pump(dht, clock)
    assert len(handles) == 2 and got == [], "wave 2 overlaps wave 1"
    assert reg.snapshot()["gauges"]["dht_ingest_pipeline_inflight"] == 2
    assert dht.wave_builder.inflight_peak == 2
    for h in handles:
        h.ok = True
    _pump(dht, clock)
    assert got == ["w1-a", "w1-b", "w2-a", "w2-b"], got
    assert dht.wave_builder.snapshot()["inflight"] == 0
    assert reg.snapshot()["gauges"]["dht_ingest_pipeline_inflight"] == 0
    # the per-wave pipeline_slot attr: wave 1 launched into an empty
    # pipeline (slot 0), wave 2 behind one in-flight wave (slot 1)
    tr = tracing.get_tracer()
    waves = [s for s in tr.dump()["spans"]
             if s["name"] == "dht.search.wave"
             and s["attrs"].get("mode") == "ingest"
             and s["attrs"].get("occupancy") == 2]
    slots = [s["attrs"].get("pipeline_slot") for s in waves[-2:]]
    assert slots == [0, 1], slots


def test_depth1_knob_is_exact_prepipeline_path():
    """ingest_pipeline_depth=1 never defers: the wave launches and
    scatters synchronously inside its fire, through the batched entry
    point, with nothing in flight afterwards."""
    clock = {"t": 21_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=5.0,
                   ingest_pipeline_depth=1)
    assert dht.wave_builder.pipeline_depth == 1
    calls = spy_batched(dht)
    got = []
    for name in ("d1-a", "d1-b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append((n, nodes)))
    dht.scheduler.run()
    assert calls == [(2, AF, SEARCH_NODES)]
    assert [n for n, _ in got] == ["d1-a", "d1-b"]
    assert all(len(nodes) > 0 for _, nodes in got)
    snap = dht.wave_builder.snapshot()
    assert snap["pipeline_depth"] == 1 and snap["inflight"] == 0


def test_depth_validated_ge_1():
    clock = {"t": 21_500.0}
    dht = make_dht(clock, ingest_pipeline_depth=0)
    assert dht.wave_builder.pipeline_depth == 1
    dht = make_dht(clock, ingest_pipeline_depth=-3)
    assert dht.wave_builder.pipeline_depth == 1


def test_depth2_results_identical_to_depth1_and_off():
    """The bit-identity pin on the resolve surface: the same targets
    through depth 2, depth 1 and batching off return identical node
    rows in identical order."""
    clock = {"t": 22_000.0}
    targets = [InfoHash.get(f"d-eq-{i}") for i in range(5)]

    def resolve(**cfg_kw):
        dht = make_dht(clock, ingest_fill_target=5, ingest_deadline=5.0,
                       **cfg_kw)
        got = []
        for t in targets:
            dht.wave_builder.submit(t, AF, SEARCH_NODES,
                                    lambda nodes: got.append(nodes))
        dht.scheduler.run()
        assert len(got) == 5
        return [[n.id for n in row] for row in got]

    r2 = resolve(ingest_pipeline_depth=2)
    r1 = resolve(ingest_pipeline_depth=1)
    roff = resolve(ingest_batching="off")
    assert r2 == r1 == roff


def test_virtualnet_put_get_equivalence_depth2_vs_depth1():
    """End-to-end pin of the tentpole's non-negotiable: the same
    virtual cluster + workload returns the same values, listener
    deliveries and storers at pipeline depth 2 and depth 1 (the off
    switch)."""
    from opendht_tpu.testing.virtual_net import VirtualNet

    def run(depth: int):
        random.seed(99)
        net = VirtualNet(seed=7)
        cfg = lambda i: Config(  # noqa: E731
            node_id=InfoHash.get(f"wb-pd-node-{i}"),
            ingest_pipeline_depth=depth)
        nodes = [net.add_node(cfg(i)) for i in range(6)]
        for n in nodes[1:]:
            net.bootstrap_node(n, nodes[0])
        net.run(max_time=30.0)
        key = InfoHash.get("wb-pd-key")
        done = {}
        heard = []
        nodes[3].listen(key, lambda vals, exp:
                        heard.extend(v.data for v in vals if not exp)
                        or True)
        net.run(max_time=30.0)
        nodes[1].put(key, Value(b"wb-pipeline", value_id=7),
                     lambda ok, ns: done.setdefault("put", ok))
        net.run(max_time=30.0)
        got = []
        nodes[2].get(key, get_cb=lambda vals: got.extend(vals) or True,
                     done_cb=lambda ok, ns: done.setdefault("get", ok))
        net.run(max_time=30.0)
        storers = sorted(bytes(d.myid).hex() for d in net.storers_of(key))
        return (done, sorted(v.data for v in got), sorted(heard), storers)

    done2, vals2, heard2, storers2 = run(2)
    done1, vals1, heard1, storers1 = run(1)
    assert done2.get("put") and done1.get("put")
    assert vals2 == vals1 == [b"wb-pipeline"]
    assert heard2 == heard1 == [b"wb-pipeline"]
    assert storers2 == storers1


def test_requeue_failed_restores_oldest_first():
    """Round-20 satellite regression (wave_builder requeue ordering):
    a failed launch re-queues its entries AHEAD of entries submitted
    by an earlier group's scatter in the same fire — appending them
    left a newer entry at _pending[0], whose t_enq anchors the
    deadline trigger, silently deferring the oldest retried op."""
    clock = {"t": 23_000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002,
                   ingest_pipeline_depth=1)
    orig = dht.find_closest_nodes_launch
    fail = {"k8": 1}

    def flaky(targets, af, count=8):
        if count == 8 and fail["k8"] > 0:
            fail["k8"] -= 1
            raise RuntimeError("transient device error")
        return orig(targets, af, count)

    dht.find_closest_nodes_launch = flaky
    got = []
    # group (AF, SEARCH_NODES) scatters first and submits a NEWER entry
    # from its callback; group (AF, 8) then fails its launch
    dht.wave_builder.submit(
        InfoHash.get("rq-first"), AF, SEARCH_NODES,
        lambda nodes: (got.append("first"), dht.wave_builder.submit(
            InfoHash.get("rq-newer"), AF, SEARCH_NODES,
            lambda n2: got.append("newer"))))
    dht.wave_builder.submit(InfoHash.get("rq-oldest"), AF, 8,
                            lambda nodes: got.append("oldest"))
    _pump(dht, clock)
    pend = list(dht.wave_builder._pending)
    assert [e.target for e in pend] == \
        [InfoHash.get("rq-oldest"), InfoHash.get("rq-newer")], \
        "retried entry must re-join ahead of newer submissions"
    assert pend[0].retries == 1
    _pump(dht, clock)
    assert sorted(got) == ["first", "newer", "oldest"]
    assert dht.wave_builder.pending() == 0


def test_mid_pipeline_consume_failure_requeues_without_drop_or_reorder():
    """A launch failure mid-pipeline (wave N−1's consume raises while
    wave N is in flight) re-queues wave N−1's entries oldest-first and
    leaves wave N untouched — nothing dropped, nothing reordered."""
    clock = {"t": 24_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002)
    handles = fake_launch(dht)
    reg = telemetry.get_registry()
    failures = reg.counter("dht_ingest_wave_failures_total")
    f0 = failures.value
    got = []
    for name in ("f1-a", "f1-b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    dht.scheduler.run()
    for name in ("f2-a", "f2-b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    _pump(dht, clock)
    assert len(handles) == 2
    handles[0].fail = True            # wave 1 dies at consume
    handles[1].ok = True              # wave 2 is fine
    _pump(dht, clock)
    # wave 2 scattered; wave 1's entries re-queued in submit order
    assert got == ["f2-a", "f2-b"], got
    assert failures.value == f0 + 1
    pend = list(dht.wave_builder._pending)
    assert [e.target for e in pend] == \
        [InfoHash.get("f1-a"), InfoHash.get("f1-b")]
    assert all(e.retries == 1 for e in pend)
    # the retry wave (a fresh launch) delivers — no drop
    for h in handles:
        h.ok, h.fail = True, False
    _pump(dht, clock)
    for h in handles:
        h.ok = True
    _pump(dht, clock)
    assert got == ["f2-a", "f2-b", "f1-a", "f1-b"], got
    assert dht.wave_builder.pending() == 0


def test_consume_retries_exhaustion_scatters_empty():
    """_LAUNCH_RETRIES exhaustion through the pipelined consume path
    still scatters empty honestly (the depth-1 twin lives in
    test_failed_launch_requeues_then_exhausts)."""
    from opendht_tpu.runtime.wave_builder import _LAUNCH_RETRIES
    clock = {"t": 25_000.0}
    dht = make_dht(clock, ingest_fill_target=64, ingest_deadline=0.002)
    fake_launch(dht, ok=True, fail=True)   # every consume raises
    got = []
    dht.wave_builder.submit(InfoHash.get("exhaust-pd"), AF, SEARCH_NODES,
                            lambda nodes: got.append(nodes))
    for _ in range(_LAUNCH_RETRIES + 2):
        _pump(dht, clock)
    assert got == [[]], got
    assert dht.wave_builder.pending() == 0
    assert dht.wave_builder.snapshot()["inflight"] == 0


def test_waterfall_stage_sum_holds_with_deferred_drain():
    """Async dispatch keeps the waterfall's pinned invariant: for a
    wave drained on a LATER pump than its launch, every per-op record's
    stage sum stays ≤ end-to-end (the stages are disjoint sub-intervals
    — device cost is dispatch + blocking wait, not the in-flight wall
    window)."""
    from opendht_tpu import waterfall
    from opendht_tpu.waterfall import WaterfallConfig
    wf = waterfall.get_profiler()
    wf.configure(WaterfallConfig())
    t0 = time.time()
    clock = {"t": 26_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002)
    handles = fake_launch(dht)
    got = []
    for name in ("wf-pd-a", "wf-pd-b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    dht.scheduler.run()
    assert handles and got == []
    handles[0].ok = True
    _pump(dht, clock)
    assert got == ["wf-pd-a", "wf-pd-b"]
    recs = [o for o in wf.ops() if o["t"] >= t0]
    assert len(recs) >= 2, recs
    for o in recs[-2:]:
        s = sum(o["stages"].values())
        assert "rpc_wait" not in o["stages"]
        assert s <= o["end_to_end"] + 1e-6, (s, o)


def test_proxy_hotswap_resubscribe_exempt_from_admission():
    """Review regression: enable_proxy re-registers established
    listeners on the new backend under WaveBuilder.exempt() — a full
    admission queue at swap time must not shed subscriptions that were
    already admitted when created."""
    clock = {"t": 11_000.0}
    dht = make_dht(clock, ingest_queue_max=0)   # sheds every NEW op
    assert dht.wave_builder.admit("get") is False
    with dht.wave_builder.exempt():
        assert dht.wave_builder.admit("listen") is True
        tok = dht.listen(InfoHash.get("exempt-l"), lambda v, e: True)
        assert tok, "exempted listen was shed"
    assert dht.wave_builder.admit("get") is False
