"""Protocol-level hop validation: the live iterative search vs the
batched simulator.

Round 1's hop-parity test compared the batched engine against a scalar
walker over the *same synthetic reply model* — validating the
vectorization but not the model.  Here the simulator's hop prediction is
checked against the real protocol path: cold-start lookups on a live
virtual-UDP cluster (fresh observer node, empty table, one bootstrap
seed — the same shape the simulator models), with per-search discovery
generations tracked through actual SEND_NODES replies
(live_search.SearchNode.depth).

This validation caught two real defects when first run:

1. Dht._on_new_node gated search insertion on routing-table admission,
   so once buckets filled, nodes discovered in replies never reached the
   searches — lookups "converged" in 1 hop onto stale sets with 0-2/8
   recall of the true closest nodes.  (The reference offers every newly
   heard node to searches even when its bucket is full,
   routing_table.cpp:254-261.)
2. The simulator's terminal reply model sampled the target neighborhood
   uniformly instead of answering with the closest known set, inflating
   predicted hops ~2x at small N.

After both fixes: live recall is ~8/8 and live/simulated hop medians
agree within ~1 at matched N (live p50 2-2.5 vs sim p50 3 at N=128 and
N=512).
"""

import os
import socket

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # quick tier: -m 'not slow'

from opendht_tpu import InfoHash
from opendht_tpu.testing import VirtualNet


def live_cold_start(n_nodes: int, n_lookups: int, seed: int = 7,
                    converge: str = "protocol"):
    """Cold-start gets by fresh observers against an n_nodes virtual-UDP
    network.  Returns (hops, recall) lists.

    ``converge``: "protocol" = bootstrap chatter + maintenance settle
    (the original path — O(N·virtual-seconds) of event processing);
    "seeded" = ``VirtualNet.seed_converged`` installs the k-bucket
    steady state directly (the round-5 path that un-gates the 8192
    point and adds 16384 — test_seeded_equals_protocol_convergence
    pins that both produce the same lookup behavior)."""
    import random
    rng = random.Random(seed)
    net = VirtualNet()
    seed_node = net.add_node()
    for _ in range(n_nodes - 1):
        net.add_node()
    if converge == "seeded":
        net.seed_converged()
    else:
        net.bootstrap_all(seed_node)
        assert net.run(240, net.all_connected), "cluster never converged"
        # let table maintenance refresh liveness so replies reflect a
        # converged network (stale tables degrade reply quality)
        net.settle(60)
    ids = [d.get_node_id() for d in net.nodes.values()]

    hops, recall = [], []
    for i in range(n_lookups):
        obs = net.add_node()
        net.bootstrap_node(obs, seed_node)
        target = InfoHash(bytes(rng.getrandbits(8) for _ in range(20)))
        done = {}
        # issue the get IMMEDIATELY (no connectivity wait): the search
        # must boot from the single seed like the simulator's cold-start
        # model, not from a maintenance-warmed routing table
        obs.get(target, lambda vals: True,
                lambda ok, ns: done.update(ok=ok))
        assert net.run(60, lambda: "ok" in done), "get never completed"
        sr = obs._searches_of(socket.AF_INET).get(target)
        h = sr.current_hops()
        assert h is not None
        hops.append(h)
        true8 = {bytes(x) for x in
                 sorted(ids, key=lambda n: bytes(target.xor(n)))[:8]}
        found = {bytes(sn.node.id) for sn in sr.nodes[:8]}
        recall.append(len(found & true8))
        net.remove_node(obs)
    return hops, recall


def sim_hops(n_nodes: int, n_lookups: int, seed: int = 3):
    import jax
    import jax.numpy as jnp
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import sort_table

    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    table = jax.random.bits(k1, (n_nodes, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (n_lookups, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = sort_table(table)
    out = simulate_lookups(sorted_ids, n_valid, targets)  # alpha=4, k=8
    assert bool(np.asarray(out["converged"]).all())
    return np.asarray(out["hops"]).tolist()


@pytest.mark.parametrize("n_nodes", [128, 512])
def test_live_vs_simulator_hop_parity(n_nodes):
    live, recall = live_cold_start(n_nodes, n_lookups=8)
    sim = sim_hops(n_nodes, n_lookups=512)
    p50_live = float(np.median(live))
    p50_sim = float(np.median(sim))
    assert abs(p50_live - p50_sim) <= 1.5, \
        f"live p50 {p50_live} (hops {live}) vs sim p50 {p50_sim}"
    assert p50_live >= 1 and p50_sim >= 1
    # the live lookups must actually find the global closest set — this
    # is the assertion that exposed the _on_new_node admission bug
    assert float(np.median(recall)) >= 7, (recall, live)


# -- the seeded-convergence shortcut and its validation ----------------------

def test_seeded_equals_protocol_convergence():
    """``seed_converged`` must be behaviorally equivalent to protocol
    convergence: cold-start lookups over a 512-node cluster converged
    both ways must agree on hop medians and recall.  This is what
    licenses using the seeded path for the big points below."""
    live_p, recall_p = live_cold_start(512, n_lookups=8,
                                       converge="protocol")
    live_s, recall_s = live_cold_start(512, n_lookups=8, converge="seeded")
    assert abs(float(np.median(live_p)) - float(np.median(live_s))) <= 1.0, \
        (live_p, live_s)
    assert float(np.median(recall_s)) >= 7 and \
        float(np.median(recall_p)) >= 7


# -- decades up: 2K / 8K / 16K / 32K live clusters ---------------------------
#
# Metric note: the live engine is not round-synchronized, so it reports
# the max DISCOVERY DEPTH of the final candidate set; the simulator
# counts QUERY ROUNDS until the first-k all replied, which is >= depth+1
# (nodes discovered in the last generation must still be queried — the
# terminal confirmation round).  The principled comparison is therefore
# sim_rounds vs live_depth + 1.  Measured sweep (round 6, 6 lookups per
# size, seeded convergence):  N=256: live 2 / sim 3;  1024: 2 / 3;
# 2048: 2 / 4;  4096: 2 / 4;  8192: 3 / 4;  16384: 2-3 / 4;
# 32768: 2-3 / 4 — live+1 tracks sim within 1 hop at every size, with
# the simulator on the conservative (over-estimating) side, so the
# north-star N=10M "p50 7 hops" claim is an upper bound interpolated
# through live-measured points spanning 256..32768, not a bare model
# extrapolation.  The 32768 point runs UN-GATED now (round 5 parked it
# behind RUN_XL_CLUSTER; measured ~160 s seeded — a slow-tier point,
# not a 90-minute one); RUN_XL_CLUSTER instead enables a 65536 point,
# the next decade, gated because a 64K-node in-process cluster is
# host-sized, not suite-sized.

@pytest.mark.slow
@pytest.mark.parametrize("n_nodes", [2048, 8192, 16384, 32768] + (
    [65536] if os.environ.get("RUN_XL_CLUSTER") else []))
def test_live_vs_simulator_hop_parity_at_scale(n_nodes):
    live, recall = live_cold_start(n_nodes, n_lookups=6,
                                   converge="seeded")
    sim = sim_hops(n_nodes, n_lookups=512)
    p50_live_rounds = float(np.median(live)) + 1   # depth → rounds
    p50_sim = float(np.median(sim))
    assert abs(p50_sim - p50_live_rounds) <= 1.0, \
        f"sim p50 {p50_sim} vs live rounds {p50_live_rounds} ({live})"
    # the simulator must stay on the conservative side: its rounds may
    # exceed the live critical path, never undercut it by more than the
    # tolerance above
    assert p50_sim >= p50_live_rounds - 0.5
    assert float(np.median(recall)) >= 7, (recall, live)
