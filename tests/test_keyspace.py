"""Keyspace traffic observatory (ISSUE-10): count-min sketch accuracy
vs an exact host-side Counter oracle, heavy-hitter recall on Zipf(1.1)
traffic, decay windowing, the psum-merged tp twin's bit-identity,
histogram folding / imbalance attribution, the health signal, and the
kernels-bit-identical-with-the-sketch-on pin."""

import collections
import json

import numpy as np
import pytest

import jax

from opendht_tpu import telemetry, tracing
from opendht_tpu.infohash import InfoHash
from opendht_tpu.keyspace import (
    BINS, KeyspaceConfig, KeyspaceObservatory, bin_edges_from_ids,
    bin_edges_uniform, fold_bins,
)
from opendht_tpu.ops import sketch as sk
from opendht_tpu.ops.ids import ids_from_hashes, ids_to_bytes


def _zipf_stream(pool_n=512, total=20000, a=1.1, seed=0):
    """Deterministic Zipf(a) stream over a fixed id pool: (pool ids
    uint32 [pool_n, 5], per-draw pool indices [total], Counter)."""
    rng = np.random.default_rng(seed)
    pool = rng.integers(0, 2 ** 32, size=(pool_n, 5), dtype=np.uint32)
    ranks = np.arange(1, pool_n + 1)
    p = 1.0 / ranks ** a
    p /= p.sum()
    idx = rng.choice(pool_n, size=total, p=p)
    return pool, idx, collections.Counter(idx.tolist())


def _hex_of(pool, k):
    return ids_to_bytes(pool[k]).tobytes().hex()


# ------------------------------------------------------------ sketch kernels

def test_hash_columns_host_mirror_and_range():
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 2 ** 32, size=(256, 5), dtype=np.uint32)
    cols = np.asarray(sk.hash_columns(ids))
    assert cols.shape == (256, sk.SKETCH_DEPTH)
    assert cols.min() >= 0 and cols.max() < sk.SKETCH_WIDTH
    # the numpy mirror (same constants, same wrapping) agrees exactly
    assert np.array_equal(cols, sk.hash_columns_host(ids))
    # rows hash independently: identical ids, different columns per row
    assert len({tuple(cols[0])}) == 1 and len(set(cols[0])) > 1


def test_sketch_geometry_validation():
    with pytest.raises(ValueError):
        sk.sketch_init(depth=0)
    with pytest.raises(ValueError):
        sk.sketch_init(width=1000)          # not a power of two
    with pytest.raises(ValueError):
        s, h = sk.sketch_init()
        sk.sketch_decay(s, h, 1.5)


def test_count_min_oracle_bounds():
    """The classic CMS guarantees vs the exact Counter oracle: never
    an underestimate, and the overestimate stays within a small
    multiple of T/width for EVERY pool key (eps = e/width bound, wide
    margin at depth 4)."""
    pool, idx, true = _zipf_stream()
    T = len(idx)
    s, h = sk.sketch_init()
    for i in range(0, T, 64):
        s, h = sk.sketch_update(s, h, pool[idx[i:i + 64]])
    est = np.asarray(sk.sketch_query(s, pool))
    excess = []
    for k in range(pool.shape[0]):
        t = true.get(k, 0)
        assert int(est[k]) >= t, "CMS underestimated key %d" % k
        excess.append(int(est[k]) - t)
    bound = 8 * T / sk.SKETCH_WIDTH
    assert max(excess) <= bound, (max(excess), bound)
    # histogram total and per-bin placement are exact
    hist = np.asarray(h)
    assert int(hist.sum()) == T
    want = np.zeros(BINS, np.int64)
    for i in idx:
        want[int(pool[i, 0] >> 24)] += 1
    assert np.array_equal(hist, want)


def test_sharded_sketch_update_bit_identical():
    """The tp twin (per-shard partial sketches merged via one psum
    pair) equals the single-device update EXACTLY, including a ragged
    batch that needs weight-0 padding."""
    from opendht_tpu.parallel.sharded import make_mesh, sharded_sketch_update
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 2 ** 32, size=(101, 5), dtype=np.uint32)
    s, h = sk.sketch_init()
    s1, h1 = sk.sketch_update(s, h, ids)
    for t in (2, 4):
        mesh = make_mesh(t, q=1, t=t)
        s2, h2 = sharded_sketch_update(mesh, s, h, ids)
        assert np.array_equal(np.asarray(s1), np.asarray(s2)), t
        assert np.array_equal(np.asarray(h1), np.asarray(h2)), t


# --------------------------------------------------------------- observatory

def test_topk_recall_zipf():
    """ISSUE-10 acceptance: top-K recall >= 0.9 on Zipf(1.1) traffic —
    measured vs the exact oracle, at the production sampling stride."""
    pool, idx, true = _zipf_stream()
    obs = KeyspaceObservatory(KeyspaceConfig(tick=0))     # stride 8 default
    for i in range(0, len(idx), 64):
        obs.observe_ids(pool[idx[i:i + 64]])
    obs.tick()
    got = set(t["key"] for t in obs.top_keys())
    want = set(_hex_of(pool, k) for k, _ in true.most_common(8))
    recall = len(got & want) / 8
    assert recall >= 0.9, (recall, got, want)
    # the top estimate matches the oracle count exactly on this stream
    top0 = obs.top_keys()[0]
    assert top0["key"] == _hex_of(pool, true.most_common(1)[0][0])
    assert top0["estimate"] >= true.most_common(1)[0][1]


def test_decay_windows_out_old_traffic():
    """Counts are windowed, not lifetime: a key hot before several
    decay ticks ranks below a freshly hot key."""
    rng = np.random.default_rng(11)
    pool = rng.integers(0, 2 ** 32, size=(2, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, decay=0.25, sample_stride=1))
    obs.observe_ids(np.repeat(pool[:1], 256, axis=0))
    obs.tick()
    assert obs.top_keys()[0]["key"] == _hex_of(pool, 0)
    for _ in range(3):
        obs.observe_ids(np.repeat(pool[1:], 64, axis=0))
        obs.tick()
    top = obs.top_keys()
    assert top[0]["key"] == _hex_of(pool, 1), top
    # the old key's windowed estimate decayed geometrically
    old = [t for t in top if t["key"] == _hex_of(pool, 0)]
    assert not old or old[0]["estimate"] < 256 * 0.25 ** 2


def test_hot_key_emerged_event_once():
    """A key newly crossing the hot rule emits hot_key_emerged; while
    it STAYS hot no duplicate event is emitted."""
    tr = tracing.get_tracer()
    rng = np.random.default_rng(13)
    pool = rng.integers(0, 2 ** 32, size=(1, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, decay=1.0, sample_stride=1, hot_min_count=16),
        node="hot-test")

    def my_events():
        return [e for e in tr.events(name="hot_key_emerged")
                if e["node"] == "hot-test"]
    before = len(my_events())
    obs.observe_ids(np.repeat(pool, 64, axis=0))
    obs.tick()
    assert len(my_events()) == before + 1
    ev = my_events()[-1]
    assert ev["attrs"]["key"] == _hex_of(pool, 0)
    assert ev["attrs"]["estimate"] >= 64
    obs.observe_ids(np.repeat(pool, 64, axis=0))
    obs.tick()
    assert len(my_events()) == before + 1      # still hot, no re-emit


def test_snapshot_window_consistent_with_top():
    """Review finding: the published window_total must be the window
    the top-K was SCORED against (pre-decay) — decaying the accumulator
    before snapshot made estimate 2x the reported window at decay=0.5
    and the published share contradict estimate/window_total."""
    rng = np.random.default_rng(23)
    pool = rng.integers(0, 2 ** 32, size=(1, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, decay=0.5, sample_stride=1, hot_min_count=16))
    obs.observe_ids(np.repeat(pool, 100, axis=0))
    snap = obs.tick()
    assert snap["window_total"] == pytest.approx(100.0)
    assert snap["top"][0]["estimate"] <= snap["window_total"]
    assert snap["top"][0]["share"] == pytest.approx(
        snap["top"][0]["estimate"] / snap["window_total"], abs=1e-3)
    # the internal accumulator still decays (windowing unchanged)
    assert obs._window_total == pytest.approx(50.0)


def test_snapshot_json_and_gauges():
    rng = np.random.default_rng(17)
    pool = rng.integers(0, 2 ** 32, size=(64, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=16), node="snap-test")
    for _ in range(3):
        obs.observe_ids(pool)
    obs.tick()
    snap = obs.snapshot()
    json.dumps(snap)                            # JSON-able
    assert snap["enabled"] and snap["observed_total"] == 192
    assert len(snap["hist"]) == BINS
    assert snap["shards"]["virtual"] and snap["shards"]["n"] == 8
    assert snap["shards"]["imbalance"] is not None
    reg = telemetry.get_registry()
    assert reg.gauge("dht_shard_imbalance", node="snap-test").value \
        == pytest.approx(snap["shards"]["imbalance"], rel=1e-4)
    assert reg.gauge("dht_keyspace_occupied_bins",
                     node="snap-test").value == snap["occupied_bins"]


def test_disabled_observatory_is_inert():
    obs = KeyspaceObservatory(KeyspaceConfig(enabled=False))
    obs.observe_ids(np.zeros((4, 5), np.uint32))
    obs.note_stored(InfoHash.get("nope"))
    snap = obs.tick()
    assert snap["enabled"] is False
    assert snap["observed_total"] == 0 and snap["top"] == []


def test_note_stored_flushes_without_waves():
    """Stored-key puts buffered with NO wave traffic still reach the
    sketch on the tick (idle-node flush), AND the flushed keys join
    the heavy-hitter candidate set — a hot stored key on a put-only
    node must be detectable exactly like one riding a wave (review
    finding: the tick flush updated the sketch but skipped candidate
    admission, so top-K stayed empty whatever the flood)."""
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=1))
    keys = [InfoHash.get("stored-%d" % i) for i in range(5)]
    for k in keys:
        obs.note_stored(k)
    obs.tick()
    snap = obs.snapshot()
    assert snap["observed_total"] == 5
    est = np.asarray(sk.sketch_query(obs._sketch, ids_from_hashes(keys)))
    # post-decay estimates: each key was observed once, then decayed
    assert all(int(e) >= 0 for e in est)
    assert int(np.asarray(obs._hist_host).sum()) == 5
    # a put-only single-key flood surfaces as hot on the SAME tick
    obs2 = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=1, hot_min_count=8),
        node="putonly")
    hot = InfoHash.get("put-only-hot")
    for _ in range(64):
        obs2.note_stored(hot)
    snap2 = obs2.tick()
    assert snap2["hot_keys"] == [bytes(hot).hex()]
    assert snap2["top"][0]["estimate"] >= 64


# --------------------------------------------------- folding / imbalance

def test_note_stored_buffer_bounded():
    """Review finding: with ``tick=0`` and no wave traffic nothing
    drains the pending-store buffer — it must stay bounded
    (drop-oldest keeps the recent keys for a windowed observatory)."""
    obs = KeyspaceObservatory(KeyspaceConfig(tick=0, store_buffer=8))
    keys = [InfoHash.get("bounded-%d" % i) for i in range(20)]
    for k in keys:
        obs.note_stored(k)
    assert len(obs._pending_store) == 8
    assert obs._pending_store == [bytes(k) for k in keys[-8:]]


def test_fold_bins_uniform_and_concentrated():
    hist = np.ones(BINS, np.int64)
    loads = fold_bins(hist, bin_edges_uniform(8))
    assert len(loads) == 8 and all(x == pytest.approx(32.0) for x in loads)
    hist = np.zeros(BINS, np.int64)
    hist[3] = 100                              # one bin -> one shard
    loads = fold_bins(hist, bin_edges_uniform(8))
    assert loads[0] == pytest.approx(100.0) and sum(loads[1:]) == 0
    # imbalance = max/mean = 8 for a single-shard flood
    from opendht_tpu.keyspace import _imbalance
    assert _imbalance(loads) == pytest.approx(8.0)


def test_fold_bins_fractional_edges_conserve():
    hist = np.zeros(BINS, np.int64)
    hist[0] = 10
    # an edge mid-bin apportions by keyspace overlap
    loads = fold_bins(hist, [0.5])
    assert loads == [pytest.approx(5.0), pytest.approx(5.0)]
    rng = np.random.default_rng(23)
    hist = rng.integers(0, 50, size=BINS).astype(np.int64)
    for edges in (bin_edges_uniform(3), [10.25, 99.9, 200.0]):
        loads = fold_bins(hist, edges)
        assert sum(loads) == pytest.approx(float(hist.sum()))


def test_bin_edges_from_ids():
    # boundary id at exactly half the ring -> edge at BINS/2
    half = np.array([[0x80000000, 0, 0, 0, 0]], np.uint32)
    assert bin_edges_from_ids(half) == [pytest.approx(BINS / 2)]
    # 20-byte id form accepted too
    raw = np.frombuffer(b"\x40" + b"\x00" * 19, np.uint8)[None]
    assert bin_edges_from_ids(raw) == [pytest.approx(BINS / 4)]


def test_shard_info_overrides_virtual_split():
    """A live shard_info provider (t, boundary ids) replaces the
    uniform virtual split with the table's actual row boundaries."""
    boundary = np.array([[0x80000000, 0, 0, 0, 0]], np.uint32)
    obs = KeyspaceObservatory(
        KeyspaceConfig(tick=0, sample_stride=1, min_observed=1),
        shard_info=lambda: (2, boundary))
    # all traffic in the LOW half of the ring
    ids = np.zeros((64, 5), np.uint32)
    ids[:, 0] = np.arange(64, dtype=np.uint32)      # tiny top bytes
    obs.observe_ids(ids)
    obs.tick()
    snap = obs.snapshot()
    assert snap["shards"]["t"] == 2 and not snap["shards"]["virtual"]
    assert snap["shards"]["n"] == 2
    assert snap["shards"]["imbalance"] == pytest.approx(2.0)
    assert snap["shards"]["loads"][1] == 0.0
    # a live mesh whose shard_info FALLS BACK (no snapshot / partial
    # fill -> boundary_ids None) folds over the uniform split and must
    # report virtual=True, not pass it off as real-shard attribution
    # (review finding)
    obs2 = KeyspaceObservatory(
        KeyspaceConfig(tick=0, sample_stride=1, min_observed=1),
        shard_info=lambda: (4, None))
    obs2.observe_ids(ids)
    obs2.tick()
    snap2 = obs2.snapshot()
    assert snap2["shards"]["t"] == 4 and snap2["shards"]["virtual"]
    assert snap2["shards"]["n"] == 4


# ----------------------------------------------------------- health signal

def test_health_shard_imbalance_signal():
    """The shard_imbalance provider feeds the round-14 evaluator: a
    lopsided observatory degrades the verdict; unknown (below
    min_observed) neither trips nor clears.  The level is CAPPED at
    degraded (HealthConfig.degrade_only) — load balance is capacity
    planning, not liveness, and a republish bin's legitimately
    concentrated self-neighborhood traffic must not 503 /healthz
    (review finding)."""
    from opendht_tpu.health import HealthConfig, HealthEvaluator
    val = {"v": None}
    ev = HealthEvaluator(HealthConfig(),
                         registry=telemetry.MetricsRegistry(),
                         providers={"shard_imbalance": lambda: val["v"]})
    rep = ev.tick()
    assert rep["signals"]["shard_imbalance"]["unknown"] is True
    assert rep["verdict"] == "healthy"          # unknown never trips
    val["v"] = 7.5                              # >= 6.0 — capped at degraded
    rep = ev.tick()
    assert rep["signals"]["shard_imbalance"]["level"] == "degraded"
    assert rep["verdict"] == "degraded"
    assert "shard_imbalance" in rep["causes"]
    val["v"] = 1.2
    rep = ev.tick()
    assert rep["signals"]["shard_imbalance"]["level"] == "healthy"
    # the cap is configuration, not hard-coding: an operator who wants
    # imbalance to gate readiness can clear degrade_only
    ev2 = HealthEvaluator(HealthConfig(degrade_only=()),
                          registry=telemetry.MetricsRegistry(),
                          providers={"shard_imbalance": lambda: 7.5})
    assert ev2.tick()["verdict"] == "unhealthy"


# --------------------------------------------- kernels stay bit-identical

def test_kernels_bit_identical_with_sketch_on():
    """The acceptance pin: a lookup wave returns the same arrays with
    the observatory observing between launches (the sketch is a
    separate launch — it can never perturb the resolve kernels)."""
    from opendht_tpu.ops.sorted_table import (build_prefix_lut,
                                              default_lut_bits, lookup_topk,
                                              sort_table)
    key = jax.random.PRNGKey(29)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (4096, 5), dtype=jax.numpy.uint32)
    q = jax.random.bits(k2, (128, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = sort_table(table)
    lut = build_prefix_lut(sorted_ids, n_valid, bits=default_lut_bits(4096))
    base = jax.block_until_ready(
        lookup_topk(sorted_ids, n_valid, q, k=8, lut=lut))
    obs = KeyspaceObservatory(KeyspaceConfig(tick=0, sample_stride=1))
    obs.observe_ids(np.asarray(q))
    obs.tick()
    after = jax.block_until_ready(
        lookup_topk(sorted_ids, n_valid, q, k=8, lut=lut))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(after)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_device_failure_goes_dark_not_stale(monkeypatch):
    """Review finding: a device failure mid-tick must clear the
    published products — the health signal reads imbalance() every
    period, and a stale ratio would hold the node unhealthy on no
    evidence.  The gauges flip to unknown (-1) too."""
    rng = np.random.default_rng(31)
    pool = rng.integers(0, 2 ** 32, size=(64, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=16), node="dark-test")
    for _ in range(3):
        obs.observe_ids(pool)
    obs.tick()
    assert obs.imbalance() is not None and obs.top_keys()

    def boom(*a, **kw):
        raise RuntimeError("device gone")
    monkeypatch.setattr(sk, "sketch_query", boom)
    obs.observe_ids(pool)               # queue more traffic
    obs.tick()                          # re-score fails -> dark
    assert obs.enabled is False
    assert obs.imbalance() is None
    assert obs.top_keys() == []
    snap = obs.snapshot()
    assert snap["enabled"] is False and snap["top"] == []
    assert snap["shards"]["imbalance"] is None
    reg = telemetry.get_registry()
    assert reg.gauge("dht_shard_imbalance", node="dark-test").value == -1.0
    assert reg.gauge("dht_hotkey_count", node="dark-test").value == 0
    # and a later observe is a no-op, not a crash
    monkeypatch.undo()
    obs.observe_ids(pool)
    assert obs.snapshot()["enabled"] is False


def test_store_flush_device_failure_goes_dark(monkeypatch):
    """Review finding: on an idle put-only node the tick's pending-
    store flush is the SOLE device call — it must go dark on failure
    exactly like observe_ids, not leave the last window published
    forever.  The decay launch rides the same contract."""
    rng = np.random.default_rng(37)
    pool = rng.integers(0, 2 ** 32, size=(64, 5), dtype=np.uint32)
    obs = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=16), node="dark-flush")
    for _ in range(3):
        obs.observe_ids(pool)
    obs.tick()
    assert obs.imbalance() is not None and obs.top_keys()

    def boom(*a, **kw):
        raise RuntimeError("device gone")
    monkeypatch.setattr(sk, "sketch_update", boom)
    obs.note_stored(InfoHash.get("idle-node-put"))
    obs.tick()                          # flush fails -> dark
    assert obs.enabled is False
    assert obs.imbalance() is None
    assert obs.top_keys() == []
    snap = obs.snapshot()
    assert snap["enabled"] is False and snap["top"] == []
    reg = telemetry.get_registry()
    assert reg.gauge("dht_shard_imbalance", node="dark-flush").value == -1.0

    # decay-launch failure: same go-dark, published products cleared
    monkeypatch.undo()                  # un-break sketch_update first
    obs2 = KeyspaceObservatory(KeyspaceConfig(
        tick=0, sample_stride=1, min_observed=16), node="dark-decay")
    for _ in range(3):
        obs2.observe_ids(pool)
    monkeypatch.setattr(sk, "sketch_decay", boom)
    obs2.tick()                         # re-score ok, decay fails -> dark
    assert obs2.enabled is False and obs2.imbalance() is None
    assert obs2.top_keys() == [] and obs2.snapshot()["enabled"] is False


def test_backend_unavailable_downgrades_and_mirrors_agree(monkeypatch):
    """The module docstring promises keyspace.py imports no jax at
    module scope and a failed backend downgrades to a disabled
    observatory (never raising into the node); the constant mirrors
    that replaced the module-level ops.ids import are cross-checked at
    device init."""
    import ast
    import inspect
    from opendht_tpu import keyspace

    # no module-scope ops/jax import: keyspace.py stays import-light
    tree = ast.parse(inspect.getsource(keyspace))
    for node in tree.body:
        if isinstance(node, ast.Import):
            assert not any(a.name.startswith("jax") for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            assert "ops" not in (node.module or "") and \
                (node.module or "") != "jax"

    # a backend failure at first observe downgrades, never raises
    def boom(*a, **kw):
        raise RuntimeError("no backend")
    monkeypatch.setattr(sk, "sketch_init", boom)
    obs = KeyspaceObservatory(KeyspaceConfig())
    obs.observe_ids(np.zeros((4, 5), np.uint32))
    assert obs.enabled is False
    assert obs.snapshot()["enabled"] is False
    assert obs.tick()["enabled"] is False

    # the mirrors really do match the ops modules
    from opendht_tpu.ops import ids as _ids
    assert (sk.BINS, _ids.HASH_BYTES, _ids.N_LIMBS) == (
        keyspace.BINS, keyspace.HASH_BYTES, keyspace.N_LIMBS)


def test_shard_info_sparse_table_falls_back_to_uniform():
    """Review finding: with a live resolve mesh but an empty/sparse
    snapshot (n_valid <= shard_n), the boundary rows would all clamp
    to one id — degenerate edges faking an imbalance of t on uniform
    traffic.  _keyspace_shard_info must fall back to (t, None) (the
    uniform ring split) instead."""
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    from opendht_tpu.scheduler import Scheduler
    import socket as _socket

    dht = Dht(lambda data, addr: 0,
              config=Config(resolve_mesh_t=4),
              scheduler=Scheduler(), has_v6=False)
    # no snapshot yet -> no boundary ids either
    t, ids = dht._keyspace_shard_info()
    assert t == 4 and ids is None
    # a snapshot over a near-empty table: still the uniform fallback
    from opendht_tpu.sockaddr import SockAddr
    table = dht.tables[_socket.AF_INET]
    now = dht.scheduler.time()
    for i in range(2):
        table.insert(InfoHash.get("sparse-%d" % i),
                     SockAddr("127.0.0.1", 4000 + i), now, confirm=2)
    table.snapshot(now)
    t, ids = dht._keyspace_shard_info()
    assert t == 4 and ids is None
    # unsharded config reports (0, None) — the virtual split
    dht2 = Dht(lambda data, addr: 0, config=Config(),
               scheduler=Scheduler(), has_v6=False)
    assert dht2._keyspace_shard_info() == (0, None)


def test_shard_info_partial_fill_falls_back_to_uniform():
    """Review finding: a PARTIALLY-filled table — any boundary row
    ``s*shard_n`` at or past ``n_valid`` — must also fall back to the
    uniform split: a clamped boundary makes zero-width trailing shards
    that report fill level as traffic imbalance (uniform traffic on a
    30%-full cap reads ~cap/n_valid, enough to trip the health degrade
    threshold on a healthy node)."""
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    from opendht_tpu.scheduler import Scheduler
    import socket as _socket

    dht = Dht(lambda data, addr: 0, config=Config(resolve_mesh_t=4),
              scheduler=Scheduler(), has_v6=False)
    cap = 1024
    base = np.zeros((cap, 5), np.uint32)
    base[:, 0] = (np.arange(cap, dtype=np.uint64)
                  * (2 ** 32 // cap)).astype(np.uint32)

    class _Snap:
        sorted_ids = base

        def __init__(self, n):
            self.n_valid = n

    table = dht.tables[_socket.AF_INET]
    # half-full: boundary rows 512 and 768 would clamp -> uniform
    table._snap = _Snap(512)
    assert dht._keyspace_shard_info() == (4, None)
    # 30%-full: reported ~cap/n_valid before the fix -> uniform
    table._snap = _Snap(300)
    assert dht._keyspace_shard_info() == (4, None)
    # just past the last boundary: the ACTUAL first-row ids serve
    table._snap = _Snap(769)
    t, ids = dht._keyspace_shard_info()
    assert t == 4
    assert np.array_equal(np.asarray(ids), base[[256, 512, 768]])
