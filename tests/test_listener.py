"""Wave-scale listen/push (ISSUE-20, opendht_tpu/listeners.py +
ops/listener_match.py) and first unit coverage for core/listener.py.

Pins the tentpole's contracts: the batched XOR-equality match kernel
against its bit-exact numpy oracle (single-device AND the t-sharded
twin at t∈{2,4}), the incremental limb packer against the canonical
``ids_from_bytes``, the table's append+tombstone+compact slot
discipline + TTL sweep + capacity overflow, the buffering fast path
(an idle table never taxes a put), go-dark-on-device-failure (whole
buffer handed back — a delivery can be late, never lost), the
``listen_batching="off"`` escape hatch (no table, no metrics, exact
synchronous path), and batched == off RESULT EQUIVALENCE on a real
Dht: same values, same per-listener order, one coalesced dispatch per
wave per listener (the satellite-2 announce loops ride the same seam).
Satellite 1 adds the Listener/LocalListener lifecycle tier: token
allocation, refresh, callback dispatch order on expiry (remote before
local), filter semantics, and cancel-while-pending."""

from __future__ import annotations

import socket as _socket

import numpy as np
import pytest

from opendht_tpu import telemetry
from opendht_tpu.core.listener import Listener, LocalListener
from opendht_tpu.core.value import Query, Select, Value, Where
from opendht_tpu.infohash import InfoHash
from opendht_tpu.listeners import ListenerTable, ListenerTableConfig
from opendht_tpu.net.node import Node
from opendht_tpu.ops.ids import ids_from_bytes
from opendht_tpu.ops.listener_match import (LISTENER_CAPACITY,
                                            listener_match, match_host)
from opendht_tpu.runtime import Config, Dht
from opendht_tpu.scheduler import Scheduler
from opendht_tpu.sockaddr import SockAddr

AF = _socket.AF_INET


# ------------------------------------------------------------ test helpers
def fresh_registry(monkeypatch):
    reg = telemetry.MetricsRegistry()
    reg.enabled = True
    monkeypatch.setattr(telemetry, "_registry", reg, raising=False)
    monkeypatch.setattr(telemetry, "get_registry", lambda: reg)
    return reg


def make_dht(clock, **cfg_kw):
    """A v4-only Dht on a virtual clock with a swallow-everything
    transport (the test_hotcache harness)."""
    cfg = Config(**cfg_kw)
    return Dht(lambda data, addr: 0, config=cfg,
               scheduler=Scheduler(clock=lambda: clock["t"]),
               has_v6=False)


def make_table(monkeypatch, clock=None, live=None, **cfg_kw):
    """Standalone table on a dict clock with recorded flush requests."""
    fresh_registry(monkeypatch)
    clock = clock if clock is not None else {"t": 0.0}
    armed = []
    t = ListenerTable(
        ListenerTableConfig(**cfg_kw),
        live_count=(live.get if live is not None else None),
        clock=lambda: clock["t"],
        request_flush=armed.append)
    return t, clock, armed


def kb(name: str) -> bytes:
    return bytes(InfoHash.get(name))


# ============================================================ match kernel
def test_match_kernel_vs_host_oracle():
    """Membership + slot from the device XOR-equality match EQUAL the
    numpy mirror over members, duplicates, tombstoned slots and
    misses."""
    rng = np.random.default_rng(20)
    table = rng.integers(0, 2**32, (128, 5), dtype=np.uint32)
    valid = np.ones(128, bool)
    valid[100:] = False                     # tombstoned tail
    stored = np.concatenate([
        table[[5, 41, 5, 99]],              # members (one duplicated)
        table[[111]],                       # id present but tombstoned
        rng.integers(0, 2**32, (11, 5), dtype=np.uint32),  # misses
    ])
    dh, ds = listener_match(table, valid, stored)
    hh, hs = match_host(table, valid, stored)
    assert np.array_equal(np.asarray(dh), hh)
    assert np.array_equal(np.asarray(ds), hs)
    assert list(hh[:4]) == [True] * 4 and list(hs[:4]) == [5, 41, 5, 99]
    assert not hh[4]                        # tombstone never matches
    assert not hh[5:].any() and (hs[5:] == -1).all()


def test_match_empty_table_and_default_capacity():
    rng = np.random.default_rng(21)
    table = np.zeros((LISTENER_CAPACITY, 5), np.uint32)
    valid = np.zeros(LISTENER_CAPACITY, bool)
    stored = rng.integers(0, 2**32, (7, 5), dtype=np.uint32)
    dh, ds = listener_match(table, valid, stored)
    assert not np.asarray(dh).any() and (np.asarray(ds) == -1).all()
    # an all-zero key against the all-zero INVALID table still misses
    dh, _ = listener_match(table, valid, np.zeros((1, 5), np.uint32))
    assert not np.asarray(dh).any()


def test_pack_matches_ids_from_bytes():
    """The table's incremental one-key limb packer is bit-identical to
    the canonical ``ops.ids.ids_from_bytes`` (the kernel compares the
    two representations, so drift = silent total miss)."""
    rng = np.random.default_rng(22)
    for _ in range(16):
        key = bytes(rng.integers(0, 256, 20, dtype=np.uint8))
        canon = np.asarray(ids_from_bytes(key)).reshape(5)
        assert np.array_equal(ListenerTable._pack(key), canon), key.hex()


@pytest.mark.parametrize("t", [2, 4])
def test_sharded_match_twin_bit_identical(t):
    """tp twin == single-device match == host oracle at t∈{2,4},
    incl. ragged widths (pad rows sliced off)."""
    from opendht_tpu.parallel.sharded import (make_mesh,
                                              sharded_listener_match)
    rng = np.random.default_rng(23)
    table = rng.integers(0, 2**32, (64, 5), dtype=np.uint32)
    valid = rng.random(64) < 0.8
    mesh = make_mesh(t, q=1, t=t)
    for s in (1, 5, 64):                    # ragged and aligned widths
        stored = np.concatenate([
            table[rng.integers(0, 64, max(1, s // 2))],
            rng.integers(0, 2**32, (s - max(1, s // 2), 5),
                         dtype=np.uint32),
        ])[:s]
        hh, hs = match_host(table, valid, stored)
        sh, ss = sharded_listener_match(mesh, table, valid, stored)
        assert np.array_equal(sh, hh) and np.array_equal(ss, hs), s


# ========================================================= table mechanics
def test_table_insert_tombstone_compact(monkeypatch):
    t, clock, _ = make_table(monkeypatch, capacity=4, compact_min=64)
    for n in ("a", "b", "c", "d"):
        t.sync_key(kb(n), 1)
    assert t.tracked() == 4
    t.sync_key(kb("b"), 0)                  # tombstone, not re-pack
    snap = t.snapshot()
    assert snap["occupancy"] == 3 and snap["tombstones"] == 1
    assert snap["compactions"] == 0
    # a 5th key needs the tombstoned lane: compaction re-packs live
    # rows and the insert lands
    t.sync_key(kb("e"), 1)
    snap = t.snapshot()
    assert snap["occupancy"] == 4 and snap["tombstones"] == 0
    assert snap["compactions"] == 1 and snap["overflow"] == 0
    # the re-packed table still matches: buffered puts for live keys
    # hit, the tombstoned key misses
    for n in ("a", "b", "e"):
        assert t.note_stored(kb(n), Value(b"x"), True)
    out = dict(t.flush())
    assert set(out) == {kb("a"), kb("e")}


def test_table_overflow_and_promotion(monkeypatch):
    t, clock, _ = make_table(monkeypatch, capacity=2)
    t.sync_key(kb("a"), 1)
    t.sync_key(kb("b"), 1)
    t.sync_key(kb("c"), 1)                  # past capacity -> overflow
    snap = t.snapshot()
    assert snap["occupancy"] == 2 and snap["overflow"] == 1
    # overflow keys are host-matched: capacity bounds device memory,
    # never correctness
    for n in ("a", "c", "zzz-miss"):
        assert t.note_stored(kb(n), Value(b"x"), True)
    out = dict(t.flush())
    assert set(out) == {kb("a"), kb("c")}
    # a freed slot promotes an overflow key back onto the device table
    t.sync_key(kb("a"), 0)
    snap = t.snapshot()
    assert snap["occupancy"] == 2 and snap["overflow"] == 0
    assert t.tracked() == 2


def test_table_ttl_sweep_recounts_stale_entries(monkeypatch):
    live = {kb("keep"): 2, kb("drop"): 0}
    t, clock, _ = make_table(monkeypatch, live=live, entry_ttl=10.0)
    t.sync_key(kb("keep"), 1)
    t.sync_key(kb("drop"), 1)
    clock["t"] = 15.0                       # both entries stale
    assert t.note_stored(kb("keep"), Value(b"x"), True)
    out = dict(t.flush())                   # sweep runs at flush
    # 'keep' still has live listeners -> refreshed and delivered;
    # 'drop' has none (silent remote expiry) -> tombstoned
    assert set(out) == {kb("keep")}
    snap = t.snapshot()
    assert snap["occupancy"] == 1
    assert [e["key"] for e in snap["entries"]] == [kb("keep").hex()]
    assert snap["entries"][0]["ttl_s"] == 10.0   # refreshed at t=15


def test_note_stored_fast_path_and_deadline(monkeypatch):
    t, clock, armed = make_table(monkeypatch, flush_deadline=0.02,
                                 buffer_max=2)
    # nobody listens on ANY key: drop without buffering or arming (the
    # <1% overhead capture rides on this)
    assert t.note_stored(kb("x"), Value(b"v"), True)
    assert t.pending() == 0 and armed == []
    # with one tracked key, every put buffers; the FIRST arms the
    # deadline, hitting buffer_max arms an immediate flush
    t.sync_key(kb("a"), 1)
    assert t.note_stored(kb("a"), Value(b"v1"), True)
    assert armed == [0.02]
    assert t.note_stored(kb("b"), Value(b"v2"), True)
    assert armed == [0.02, 0.0]
    assert t.pending() == 2
    # per-key arrival order is preserved through flush
    assert t.note_stored(kb("a"), Value(b"v3"), False)
    out = dict(t.flush())
    assert [(v.data, nv) for v, nv in out[kb("a")]] == [
        (b"v1", True), (b"v3", False)]
    assert kb("b") not in out               # no listener -> dropped
    assert t.pending() == 0


def test_go_dark_returns_whole_buffer(monkeypatch):
    """Device failure mid-match: the ENTIRE buffer comes back for host
    delivery (late, never lost), the table disables and reports
    unknown, and note_stored refuses from then on (synchronous path)."""
    t, clock, _ = make_table(monkeypatch)
    reg = telemetry.get_registry()
    t.sync_key(kb("a"), 1)
    assert t.note_stored(kb("a"), Value(b"v1"), True)
    assert t.note_stored(kb("not-listened"), Value(b"v2"), True)

    def boom(*a, **kw):
        raise RuntimeError("device lost")
    monkeypatch.setattr("opendht_tpu.ops.listener_match.listener_match",
                        boom)
    out = dict(t.flush())
    assert set(out) == {kb("a"), kb("not-listened")}   # host fallback
    assert not t.enabled
    snap = t.snapshot()
    assert snap["dark"] and snap["occupancy"] == -1
    assert reg.gauge("dht_listener_occupancy").value == -1.0
    t.frame_tick()
    assert reg.gauge("dht_listener_lag_p95").value == -1.0
    assert t.note_stored(kb("a"), Value(b"v3"), True) is False
    assert t.flush() == []                  # nothing silently retained


def test_batching_off_no_table_no_metrics(monkeypatch):
    reg = fresh_registry(monkeypatch)
    t = ListenerTable(ListenerTableConfig(), batching="off")
    assert not t.enabled
    assert t.note_stored(kb("a"), Value(b"v"), True) is False
    t.sync_key(kb("a"), 1)                  # no-op, no crash
    assert t.tracked() == 0
    assert t.snapshot() == {"enabled": False, "batching": "off"}
    # the round-14 rule: an off component registers NO metric series
    snap = reg.snapshot()
    assert not any(n.startswith("dht_listener")
                   for section in snap.values() for n in section)


def test_frame_tick_rolls_lag_window(monkeypatch):
    t, clock, _ = make_table(monkeypatch, flush_deadline=5.0)
    reg = telemetry.get_registry()
    t.sync_key(kb("a"), 1)
    t.note_stored(kb("a"), Value(b"v"), True)
    clock["t"] = 0.25                       # buffered 0.25s ago
    assert t.flush()
    t.frame_tick()
    assert t.lag_p95() == pytest.approx(0.25)
    assert reg.gauge("dht_listener_lag_p95").value == pytest.approx(0.25)
    t.frame_tick()                          # empty window -> unknown
    assert t.lag_p95() is None
    assert reg.gauge("dht_listener_lag_p95").value == -1.0


# ================================== satellite 1: core/listener.py lifecycle
def test_listener_refresh_updates_time_and_query():
    q1, q2 = Query(Select(), Where()), Query(Select(), Where())
    l = Listener(10.0, q1, sid=7)
    assert (l.time, l.query, l.sid) == (10.0, q1, 7)
    l.refresh(42.0, q2)
    assert (l.time, l.query, l.sid) == (42.0, q2, 7)


def test_local_listener_notify_filter_and_unsubscribe():
    got = []
    ret = {"v": None}
    l = LocalListener(None, lambda v: v.data != b"reject",
                      lambda vals, exp: got.append(
                          ([v.data for v in vals], exp)) or ret["v"])
    # the filter applies per value; an all-filtered batch short-circuits
    # to 'stay subscribed' without invoking the callback
    assert l.notify([Value(b"reject")], False) is True
    assert got == []
    # None (the usual Python default) stays subscribed; only an
    # explicit False unsubscribes
    assert l.notify([Value(b"ok"), Value(b"reject")], False) is True
    assert got == [([b"ok"], False)]
    ret["v"] = False
    assert l.notify([Value(b"ok2")], True) is False
    assert got[-1] == ([b"ok2"], True)


def test_listen_token_allocation_and_cancel(monkeypatch):
    fresh_registry(monkeypatch)
    clock = {"t": 0.0}
    dht = make_dht(clock)
    key = InfoHash.get("tokens")
    t1 = dht.listen(key, lambda vals, exp: True)
    t2 = dht.listen(key, lambda vals, exp: True)
    assert t1 and t2 and t1 != t2           # distinct live tokens
    st = dht.store[key]
    assert len(st.local_listeners) == 2
    assert dht.listener_table.tracked() == 1    # one KEY, two listeners
    assert dht.cancel_listen(key, t1) is True
    assert dht.cancel_listen(key, t1) is False  # double-cancel
    assert dht.cancel_listen(key, 424242) is False
    assert len(st.local_listeners) == 1
    assert dht.listener_table.tracked() == 1    # still one live listener
    assert dht.cancel_listen(key, t2) is True
    assert dht.listener_table.tracked() == 0    # row tombstoned


def test_expiry_dispatch_order_remote_then_local(monkeypatch):
    """_expire_store_one pushes the expiry to REMOTE (node, sid)
    listeners first, then local callbacks with expired=True (the
    reference's Dht::expireStore order)."""
    fresh_registry(monkeypatch)
    clock = {"t": 0.0}
    dht = make_dht(clock, listen_batching="off")
    key = InfoHash.get("expiring")
    order = []
    dht.listen(key, lambda vals, exp:
               order.append(("local", [v.data for v in vals], exp))
               or True)
    peer = Node(InfoHash.get("peer"), SockAddr("10.9.9.9", 4000))
    monkeypatch.setattr(
        dht.engine, "tell_listener",
        lambda node, sid, k, want, tok, c4, c6, vs, q:
        order.append(("push", [v.data for v in vs])))
    monkeypatch.setattr(
        dht.engine, "tell_listener_expired",
        lambda node, sid, k, tok, vids:
        order.append(("expired-push", list(vids))))
    dht._storage_add_listener(key, peer, 3, Query(Select(), Where()))
    dht.storage_store(key, Value(b"gone", value_id=9), clock["t"])
    assert order == [("local", [b"gone"], False), ("push", [b"gone"])]
    order.clear()
    # keep the remote listener FRESH past the value's expiry (a stale
    # one is silently dropped by Storage.expire before the push loop)
    clock["t"] = 300.0
    dht.scheduler.sync_time()
    dht._storage_add_listener(key, peer, 3, Query(Select(), Where()))
    clock["t"] = 650.0                      # value (600s type TTL) expired
    dht.scheduler.sync_time()
    dht._expire_store_one(key, dht.store[key])
    assert order == [("expired-push", [9]),
                     ("local", [b"gone"], True)]


def test_cancel_while_pending_no_delivery(monkeypatch):
    """A put buffered behind the batched match is NOT delivered to a
    listener cancelled before the flush — the tombstoned row misses,
    exactly like the synchronous path would find no listener."""
    fresh_registry(monkeypatch)
    clock = {"t": 0.0}
    dht = make_dht(clock)
    key = InfoHash.get("cancel-pending")
    heard = []
    tok = dht.listen(key, lambda vals, exp: heard.append(vals) or True)
    dht.storage_store(key, Value(b"pending"), clock["t"])
    assert dht.listener_table.pending() == 1
    assert dht.cancel_listen(key, tok)
    clock["t"] += 1.0
    dht.periodic(None, None)                # deadline flush fires
    assert dht.listener_table.pending() == 0
    assert heard == []


# ===================================== batched == off result equivalence
def drive_deliveries(monkeypatch, batching: str):
    """One node, one filtered local listener + one remote (node, sid)
    listener, six stored puts (the _on_announce shape: a burst of
    storage_store calls) -> (local deliveries, remote dispatches)."""
    fresh_registry(monkeypatch)
    clock = {"t": 0.0}
    dht = make_dht(clock, listen_batching=batching)
    key = InfoHash.get("equivalence")
    local = []
    dht.listen(key, lambda vals, exp:
               local.append([v.data for v in vals]) or True,
               f=lambda v: v.data != b"filtered")
    told = []
    monkeypatch.setattr(
        dht.engine, "tell_listener",
        lambda node, sid, k, want, tok, c4, c6, vs, q:
        told.append([v.data for v in vs]))
    peer = Node(InfoHash.get("peer"), SockAddr("10.9.9.8", 4001))
    dht._storage_add_listener(key, peer, 5, Query(Select(), Where()))
    payloads = [b"v0", b"filtered", b"v2", b"v3", b"v4", b"v5"]
    for i, data in enumerate(payloads):
        dht.storage_store(key, Value(data, value_id=i + 1), clock["t"])
    clock["t"] += 1.0
    dht.periodic(None, None)                # batched: deadline flush
    return local, told


def test_batched_equals_off_same_values_same_order(monkeypatch):
    on_local, on_told = drive_deliveries(monkeypatch, "on")
    off_local, off_told = drive_deliveries(monkeypatch, "off")
    flat = lambda batches: [d for b in batches for d in b]  # noqa: E731
    # RESULT EQUIVALENCE: same values, same per-listener order...
    assert flat(on_local) == flat(off_local) == [
        b"v0", b"v2", b"v3", b"v4", b"v5"]
    assert flat(on_told) == flat(off_told) == [
        b"v0", b"filtered", b"v2", b"v3", b"v4", b"v5"]
    # ...but ONE coalesced dispatch per wave per listener instead of
    # one per put (the satellite-2 announce-loop batching rides here:
    # a k-value announce is exactly this storage_store burst)
    assert len(on_local) == 1 and len(on_told) == 1
    assert len(off_local) == 5 and len(off_told) == 6


def test_batched_metrics_advance(monkeypatch):
    reg = fresh_registry(monkeypatch)
    clock = {"t": 0.0}
    dht = make_dht(clock)
    key = InfoHash.get("metrics")
    dht.listen(key, lambda vals, exp: True)
    dht.storage_store(key, Value(b"a", value_id=1), clock["t"])
    dht.storage_store(key, Value(b"b", value_id=2), clock["t"])
    clock["t"] += 1.0
    dht.periodic(None, None)
    snap = dht.listener_table.snapshot()
    assert snap["flushes"] == 1 and snap["matches"] == 1
    assert snap["deliveries"] == 1 and snap["values_delivered"] == 2
    names = reg.snapshot()
    assert any(n.startswith("dht_listener_match_seconds")
               for n in names["histograms"])
    assert any(n.startswith("dht_listener_delivery_seconds")
               for n in names["histograms"])


def test_config_knobs_exposed():
    cfg = Config()
    assert cfg.listen_batching == "on"
    assert cfg.listeners.enabled is True
    assert cfg.listeners.capacity == 1024
    assert cfg.listeners.entry_ttl == 600.0
    assert cfg.listeners.flush_deadline == 0.01
    cfg2 = Config()
    assert cfg2.listeners is not cfg.listeners   # default_factory, shared
