"""Routing layer tests: radix bucket kernels + NodeTable k-bucket
semantics (reference behavior: src/routing_table.cpp, src/node_cache.cpp,
include/opendht/node.h)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from opendht_tpu.infohash import InfoHash
from opendht_tpu.ops import ids as K
from opendht_tpu.ops import radix
from opendht_tpu.core.table import (

    NodeTable, NODE_GOOD_TIME, TARGET_NODES,
)

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


def _rand_hash(rng):
    return InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))


# ---------------------------------------------------------------- radix ops

def test_bucket_of_matches_scalar():
    rng = np.random.default_rng(0)
    me = _rand_hash(rng)
    hashes = [_rand_hash(rng) for _ in range(200)]
    # include very close ids
    close = me.set_bit(159, not me.get_bit(159))
    hashes.append(close)
    ids = jnp.asarray(K.ids_from_hashes(hashes))
    got = np.asarray(radix.bucket_of(
        jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1), ids))
    want = np.array([
        min(InfoHash.common_bits(me, h), 159) for h in hashes
    ])
    np.testing.assert_array_equal(got, want)


def test_bucket_counts_and_last_seen():
    rng = np.random.default_rng(1)
    me = _rand_hash(rng)
    hashes = [_rand_hash(rng) for _ in range(64)]
    ids = jnp.asarray(K.ids_from_hashes(hashes))
    valid = np.ones(64, bool)
    valid[10] = False
    ts = rng.uniform(0, 100, 64)
    self_l = jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1)
    counts = np.asarray(radix.bucket_counts(self_l, ids, jnp.asarray(valid)))
    want = np.zeros(160, np.int32)
    for i, h in enumerate(hashes):
        if valid[i]:
            want[min(InfoHash.common_bits(me, h), 159)] += 1
    np.testing.assert_array_equal(counts, want)
    assert counts.sum() == 63

    last = np.asarray(radix.bucket_last_seen(
        self_l, ids, jnp.asarray(valid), jnp.asarray(ts)))
    for b in range(160):
        sel = [ts[i] for i, h in enumerate(hashes)
               if valid[i] and min(InfoHash.common_bits(me, h), 159) == b]
        if sel:
            assert last[b] == pytest.approx(max(sel))


def test_random_id_in_bucket():
    rng = np.random.default_rng(2)
    me = _rand_hash(rng)
    self_l = jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1)
    buckets = jnp.asarray(np.array([0, 1, 7, 31, 32, 100, 158, 159]))
    out = radix.random_id_in_bucket(self_l, buckets, jax.random.key(3))
    raw = K.ids_to_bytes(np.asarray(out))
    for j, b in enumerate(np.asarray(buckets)):
        h = InfoHash(raw[j].tobytes())
        assert InfoHash.common_bits(me, h) == b, f"bucket {b}"


def test_estimate_network_size_order_of_magnitude():
    rng = np.random.default_rng(4)
    me = _rand_hash(rng)
    for n in (64, 4096):
        raw = rng.integers(0, 256, (n, 20), dtype=np.uint8)
        est = int(radix.estimate_network_size(
            jnp.asarray(K.ids_from_bytes(bytes(me))).reshape(-1),
            jnp.asarray(K.ids_from_bytes(raw)),
            jnp.ones(n, bool), k=8,
        ))
        assert n / 4 <= est <= n * 4, (n, est)


# ---------------------------------------------------------------- NodeTable

def test_insert_dedupe_and_liveness():
    rng = np.random.default_rng(5)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=16)
    h = _rand_hash(rng)
    row = t.insert(h, ("1.2.3.4", 4222), now=100.0, confirm=0)
    assert row is not None and len(t) == 1
    assert not t.is_good(row, 100.0)          # never replied
    row2 = t.insert(h, ("1.2.3.4", 4222), now=101.0, confirm=2)
    assert row2 == row and len(t) == 1        # dedupe
    assert t.is_good(row, 101.0)
    assert not t.is_good(row, 101.0 + NODE_GOOD_TIME + 1)  # aged out
    # own id never inserted
    assert t.insert(me, None, now=1.0) is None


def test_bucket_capacity_and_replacement():
    rng = np.random.default_rng(6)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=16)
    # 9 nodes in bucket 0 (first bit differs from me)
    nodes = []
    while len(nodes) < 9:
        h = _rand_hash(rng)
        if InfoHash.common_bits(me, h) == 0:
            nodes.append(h)
    rows = [t.insert(h, i, now=10.0, confirm=2) for i, h in enumerate(nodes[:8])]
    assert all(r is not None for r in rows)
    # bucket full of live nodes → 9th rejected, kept as candidate
    assert t.insert(nodes[8], 8, now=10.0, confirm=2) is None
    assert len(t) == 8
    # expire one → next insert replaces it
    t.on_expired(nodes[0])
    r9 = t.insert(nodes[8], 8, now=11.0, confirm=2)
    assert r9 is not None and len(t) == 8
    assert t.row_of(nodes[0]) is None
    # removing a node promotes the bucket's cached candidate
    extra = None
    while extra is None:
        h = _rand_hash(rng)
        if InfoHash.common_bits(me, h) == 0:
            extra = h
    assert t.insert(extra, 99, now=12.0, confirm=2) is None   # cached
    t.remove(nodes[1])
    assert t.row_of(extra) is not None


def test_auth_errors_expire():
    rng = np.random.default_rng(7)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=16)
    h = _rand_hash(rng)
    row = t.insert(h, None, now=1.0, confirm=2)
    for _ in range(3):
        t.on_auth_error(h)
    assert not t.is_good(row, 1.0)
    t.clear_bad()
    assert t.row_of(h) is None


def test_find_closest_matches_oracle_and_growth():
    rng = np.random.default_rng(8)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=8)          # force growth
    hashes, rows = [], {}
    for i in range(300):
        h = _rand_hash(rng)
        r = t.insert(h, i, now=50.0, confirm=2)
        if r is not None:
            hashes.append(h)
            rows[bytes(h)] = r
    # k-bucket admission: random ids concentrate in shallow buckets, so
    # only ~k·log2(N/k) of the 300 are admitted
    assert 24 <= len(t) <= 120

    targets = [_rand_hash(rng) for _ in range(20)]
    got_rows, got_dist = t.find_closest(targets, k=8, now=60.0)
    for qi, tgt in enumerate(targets):
        ti = tgt.to_int()
        want = sorted(hashes, key=lambda h: ti ^ h.to_int())[:8]
        got = [t.id_of(int(r)) for r in got_rows[qi] if r >= 0]
        assert got == want, f"target {qi}"


def test_find_closest_good_mask():
    rng = np.random.default_rng(9)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=64)
    good, stale = [], []
    for i in range(20):
        h = _rand_hash(rng)
        t.insert(h, i, now=1000.0, confirm=2)
        good.append(h)
    for i in range(20):
        h = _rand_hash(rng)
        t.insert(h, i, now=1000.0, confirm=0)   # never replied → not good
        stale.append(h)
    tgt = _rand_hash(rng)
    rows, _ = t.find_closest([tgt], k=8, now=1001.0, mask="good")
    ids = {bytes(t.id_of(int(r))) for r in rows[0] if r >= 0}
    assert ids <= {bytes(h) for h in good}
    assert len(ids) == 8


def test_bulk_load_revives_expired():
    """ADVICE r5 finding 3: ``_row_of`` also holds expired rows, and
    bulk_load's dedup used to skip them — a re-seeded expired peer
    stayed dead forever while ``insert(confirm=2)`` would revive it.
    Now only LIVE known ids are dropped: with replied=True an expired
    id revives (insert(confirm=2) semantics, no duplicate row); with
    replied=False the re-sighting is hearsay and only refreshes
    time_seen."""
    rng = np.random.default_rng(33)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=64)
    raw = rng.integers(0, 256, (20, 20), dtype=np.uint8)
    ids = K.ids_from_bytes(raw)
    t.bulk_load(ids, now=10.0)
    assert len(t) == 20
    dead = InfoHash(raw[3].tobytes())
    t.on_expired(dead)
    row = t.row_of(dead)
    assert t._expired[row]
    # hearsay re-sight: time_seen refreshes, the row stays dead
    t.bulk_load(ids[3:4], now=20.0, replied=False)
    assert t._expired[row] and t._time_seen[row] == 20.0
    # replied re-seed (dup of a live id + the expired one + a fresh id):
    # revives in place, dedupes the live, adds only the fresh — and the
    # caller's address lands on the revived row like insert() would
    # store it (a revived peer with a stale/None addr is unservable in
    # closest-node replies)
    fresh = rng.integers(0, 256, (1, 20), dtype=np.uint8)
    batch = np.concatenate([np.asarray(ids[2:5]), K.ids_from_bytes(fresh)])
    t.bulk_load(batch, now=30.0,
                addrs=[("10.0.0.2", 4222), ("10.0.0.3", 4223),
                       ("10.0.0.4", 4224), ("10.0.0.9", 4229)])
    assert len(t) == 21
    assert t.row_of(dead) == row and not t._expired[row]
    assert t._time_reply[row] == 30.0
    assert t._addrs[row] == ("10.0.0.3", 4223)
    # the revived peer serves again in closest-node reads
    rows, _ = t.find_closest([dead], k=1, now=31.0)
    assert int(rows[0][0]) == row


def test_bulk_load_and_maintenance():
    rng = np.random.default_rng(10)
    me = _rand_hash(rng)
    t = NodeTable(me, capacity=64)
    raw = rng.integers(0, 256, (500, 20), dtype=np.uint8)
    t.bulk_load(K.ids_from_bytes(raw), now=100.0)
    assert len(t) == 500
    est = t.network_size_estimate()
    assert 100 <= est <= 2000

    # everything last seen at t=100 → all occupied buckets stale at t=1000
    stale = t.stale_buckets(1000.0)
    occ = np.nonzero(t.bucket_occupancy())[0]
    np.testing.assert_array_equal(stale, occ)
    # nothing stale shortly after
    assert len(t.stale_buckets(101.0)) == 0

    targets = t.refresh_targets(stale[:4], jax.random.key(0))
    for j, b in enumerate(stale[:4]):
        h = InfoHash(K.ids_to_bytes(targets[j]).tobytes())
        assert InfoHash.common_bits(me, h) == b

    exported = t.export_nodes(now=200.0)
    assert len(exported) == 500
