"""Pipeline utilization observatory (round 22, pipeline_observatory.py).

Pins the observatory's contract: per-wave lifecycle edges fold into a
closed busy/bubble ledger (Σ busy + Σ attributed bubbles == observed
window, checked against a host-side scalar oracle), every device-idle
gap is attributed to exactly one cause, the occupancy gauge windows on
the history-frame cadence, lane records export one Perfetto pid per
lane, and — the failure-path guarantee — launch-retry requeues,
mid-drain device errors and reshard swaps between waves all close
their lane slices (no orphan open intervals) with the right bubble
cause, at every pipeline depth.
"""

from __future__ import annotations

import socket as _socket

import pytest

from opendht_tpu import telemetry
from opendht_tpu.infohash import InfoHash
from opendht_tpu.pipeline_observatory import (
    BUBBLE_CAUSES,
    STARVED_CAUSES,
    PipelineObservatory,
    PipelineObservatoryConfig,
)
from opendht_tpu.runtime.live_search import SEARCH_NODES

from test_wave_builder import _pump, fake_launch, make_dht

AF = _socket.AF_INET


def make_obs(**cfg_kw):
    """Observatory on a fake clock and a private registry."""
    clock = {"t": 100.0}
    obs = PipelineObservatory(PipelineObservatoryConfig(**cfg_kw),
                              registry=telemetry.MetricsRegistry(),
                              clock=lambda: clock["t"])
    return obs, clock


def run_wave(obs, clock, *, fill_wait=0.0, device=0.05, drain=0.002,
             n=8, gen=0, slot=0):
    """One full lifecycle through the edge API; returns the seq."""
    obs.note_fill_start(clock["t"])
    clock["t"] += fill_wait
    t_fill = obs.take_fill(clock["t"])
    seq = obs.on_dispatch(t_fill, clock["t"], n, AF, 8, slot, gen)
    clock["t"] += device
    obs.on_device_done(seq, clock["t"])
    clock["t"] += drain
    obs.on_scatter_done(seq, clock["t"])
    return seq


# ==================================================== unit: the ledger
def test_account_closed_against_scalar_oracle():
    """The acceptance oracle: replay a scripted edge sequence and track
    busy/idle intervals with independent scalar arithmetic — the
    observatory's ledger must attribute every second of the observed
    window (Σ busy + Σ bubbles == span, no double count, no leak)."""
    obs, clock = make_obs()
    oracle_busy = 0.0
    oracle_gaps = 0.0
    t_start = clock["t"]
    last_idle = clock["t"]

    # waves with varying fill/device/drain geometry and idle gaps
    script = [(0.010, 0.050, 0.002, 0.000),
              (0.001, 0.020, 0.001, 0.030),
              (0.040, 0.005, 0.004, 0.015),
              (0.002, 0.100, 0.000, 0.000)]
    for fill_wait, device, drain, idle in script:
        clock["t"] += idle            # device sits idle before the fill
        obs.note_fill_start(clock["t"])
        clock["t"] += fill_wait
        t_fill = obs.take_fill(clock["t"])
        oracle_gaps += clock["t"] - last_idle   # idle closed at dispatch
        seq = obs.on_dispatch(t_fill, clock["t"], 4, AF, 8, 0, 0)
        clock["t"] += device
        obs.on_device_done(seq, clock["t"])
        oracle_busy += device
        last_idle = clock["t"]
        clock["t"] += drain
        obs.on_scatter_done(seq, clock["t"])

    acct = obs.account()
    assert acct["open_waves"] == 0
    assert acct["span_s"] == pytest.approx(last_idle - t_start, abs=1e-12)
    assert acct["busy_s"] == pytest.approx(oracle_busy, abs=1e-12)
    assert sum(acct["bubble_s"].values()) == pytest.approx(
        oracle_gaps, abs=1e-12)
    # the closure pin: every second attributed, none twice
    assert acct["attributed_s"] == pytest.approx(acct["span_s"], abs=1e-9)


def test_account_closed_with_overlapping_waves():
    """Depth-2 shape: two waves overlap on device — busy time is the
    union (no double count), and the ledger still closes exactly."""
    obs, clock = make_obs()
    t_start = clock["t"]
    obs.note_fill_start(clock["t"])
    clock["t"] += 0.004
    t_fill = obs.take_fill(clock["t"])
    s1 = obs.on_dispatch(t_fill, clock["t"], 4, AF, 8, 0, 0)
    clock["t"] += 0.010               # wave 2 dispatches mid-flight
    obs.note_fill_start(clock["t"])
    clock["t"] += 0.002
    t_fill2 = obs.take_fill(clock["t"])
    s2 = obs.on_dispatch(t_fill2, clock["t"], 4, AF, 8, 1, 0)
    clock["t"] += 0.020
    obs.on_device_done(s1, clock["t"])
    clock["t"] += 0.015
    obs.on_device_done(s2, clock["t"])  # busy 0.004 .. now, one interval
    t_idle = clock["t"]
    clock["t"] += 0.003
    obs.on_scatter_done(s1, clock["t"])
    obs.on_scatter_done(s2, clock["t"])
    acct = obs.account()
    assert acct["open_waves"] == 0
    assert acct["busy_s"] == pytest.approx(t_idle - t_start - 0.004,
                                           abs=1e-12)
    assert acct["bubble_s"]["fill_slow"] == pytest.approx(0.004, abs=1e-12)
    assert acct["attributed_s"] == pytest.approx(acct["span_s"], abs=1e-9)


# ============================================ unit: bubble attribution
def test_bubble_cause_fill_slow_vs_queue_empty():
    """The fill-geometry split: a gap dominated by batching time is
    fill_slow; a gap dominated by no-work time is queue_empty."""
    obs, clock = make_obs()
    run_wave(obs, clock)              # establish an idle edge
    # long fill, short empty → fill_slow
    clock["t"] += 0.001
    run_wave(obs, clock, fill_wait=0.050)
    assert obs.account()["bubble_n"]["fill_slow"] >= 1
    # long empty, short fill → queue_empty
    before = obs.account()["bubble_n"]["queue_empty"]
    clock["t"] += 0.200
    run_wave(obs, clock, fill_wait=0.001)
    assert obs.account()["bubble_n"]["queue_empty"] == before + 1


def test_bubble_cause_flags_and_priority():
    """Explicit pipeline events outrank the fill geometry, and retry
    outranks everything (the failure owns the gap it opened)."""
    obs, clock = make_obs()
    run_wave(obs, clock)
    clock["t"] += 0.010
    obs.note_backpressure()
    run_wave(obs, clock, fill_wait=0.001)
    assert obs.account()["bubble_n"]["drain_backpressure"] == 1
    clock["t"] += 0.010
    obs.note_launch_retry()
    obs.note_backpressure()           # retry wins the tie
    run_wave(obs, clock, fill_wait=0.001)
    assert obs.account()["bubble_n"]["launch_retry"] == 1
    assert obs.account()["bubble_n"]["drain_backpressure"] == 1


def test_bubble_cause_reshard_swap_and_cache_served():
    obs, clock = make_obs()
    run_wave(obs, clock, gen=0)
    clock["t"] += 0.010
    run_wave(obs, clock, gen=3)       # generation moved between waves
    assert obs.account()["bubble_n"]["reshard_swap"] == 1
    clock["t"] += 0.010
    obs.note_cache_served(clock["t"] - 0.001, 5)
    clock["t"] += 0.005
    run_wave(obs, clock, gen=3)
    assert obs.account()["bubble_n"]["cache_served"] == 1
    # flags are one-shot: the next gap classifies fresh
    clock["t"] += 0.200
    run_wave(obs, clock, gen=3, fill_wait=0.001)
    assert obs.account()["bubble_n"]["queue_empty"] >= 1


def test_bubble_histograms_and_top_cause_gauge():
    reg = telemetry.MetricsRegistry()
    clock = {"t": 50.0}
    obs = PipelineObservatory(PipelineObservatoryConfig(), registry=reg,
                              clock=lambda: clock["t"])
    run_wave(obs, clock)              # idle edge at device_done, then
    clock["t"] += 1.0                 # 0.002 drain + 1.0 + 0.001 fill
    run_wave(obs, clock, fill_wait=0.001)   # big queue_empty bubble
    h = reg.histogram("dht_pipeline_bubble_seconds", cause="queue_empty")
    assert h.count == 1 and h.sum == pytest.approx(1.003, abs=1e-9)
    g = reg.gauge("dht_pipeline_bubble_top_cause")
    assert g.value == BUBBLE_CAUSES.index("queue_empty")


# ======================================= unit: occupancy and overlap
def test_occupancy_windows_on_frame_checkpoints():
    """Checkpoints bound the occupancy window: an idle boot hour ages
    out once frames advance past window_s — the gauge reports current
    behaviour, not lifetime history."""
    obs, clock = make_obs(window_s=10.0)
    run_wave(obs, clock, device=1.0)  # 1 s busy...
    clock["t"] += 100.0               # ...then a long dark age
    obs.on_frame()
    lifetime = obs.occupancy()
    assert lifetime is not None and lifetime < 0.02
    # a fully-busy recent window, checkpointed each "frame"
    for _ in range(10):
        run_wave(obs, clock, device=1.0, drain=0.0)
        obs.on_frame()
    occ = obs.occupancy()
    assert occ is not None and occ > 0.9, occ


def test_occupancy_gauge_unknown_until_first_wave():
    reg = telemetry.MetricsRegistry()
    obs = PipelineObservatory(PipelineObservatoryConfig(), registry=reg)
    assert reg.gauge("dht_pipeline_occupancy").value == -1.0
    assert obs.occupancy() is None


def test_overlap_ratio_serial_vs_pipelined():
    """Serial waves sweep to ~1.0; overlapped spans exceed 1.0 — the
    always-on successor to the one-shot pipeline_overlap capture."""
    obs, clock = make_obs()
    for _ in range(3):
        run_wave(obs, clock, fill_wait=0.001)
        clock["t"] += 0.001
    obs.on_frame()
    serial = obs.snapshot()["overlap_ratio"]
    assert 0.9 <= serial <= 1.01, serial

    obs2, clock2 = make_obs()
    # two waves whose [fill, done] spans overlap heavily
    obs2.note_fill_start(clock2["t"])
    t_f = obs2.take_fill(clock2["t"])
    s1 = obs2.on_dispatch(t_f, clock2["t"], 4, AF, 8, 0, 0)
    clock2["t"] += 0.005
    obs2.note_fill_start(clock2["t"])
    t_f2 = obs2.take_fill(clock2["t"])
    s2 = obs2.on_dispatch(t_f2, clock2["t"], 4, AF, 8, 1, 0)
    clock2["t"] += 0.050
    obs2.on_device_done(s1, clock2["t"])
    obs2.on_device_done(s2, clock2["t"])
    obs2.on_scatter_done(s1, clock2["t"])
    obs2.on_scatter_done(s2, clock2["t"])
    assert obs2.snapshot()["overlap_ratio"] > 1.5


# ============================================== unit: collapse signal
def test_collapse_unknown_then_tracks_starved_share():
    obs, clock = make_obs()
    assert obs.collapse() is None     # no baseline yet
    run_wave(obs, clock)
    clock["t"] += 0.010
    obs.note_launch_retry()
    run_wave(obs, clock, fill_wait=0.001)
    clock["t"] += 0.010
    v = obs.collapse()
    assert v is not None and 0.0 < v <= 1.0
    # a quiet window is unknown, never healthy-by-default
    clock["t"] += 5.0
    assert obs.collapse() is None


def test_collapse_ignores_healthy_idleness():
    """queue_empty / cache_served are not starvation: a trickle-load
    window full of them reports ~0, not a degrade."""
    assert set(STARVED_CAUSES).isdisjoint({"queue_empty", "cache_served"})
    obs, clock = make_obs()
    obs.collapse()                    # arm the baseline
    run_wave(obs, clock)
    clock["t"] += 1.0
    run_wave(obs, clock, fill_wait=0.001)   # queue_empty bubble
    v = obs.collapse()
    assert v == pytest.approx(0.0, abs=1e-9)


# =========================================== unit: lane export surface
def test_lane_records_one_pid_per_lane_and_span_links():
    from opendht_tpu import tracing
    obs, clock = make_obs()
    seq = run_wave(obs, clock)
    obs.on_scatter_done(seq, clock["t"])  # idempotent: already closed
    # a second wave closed with a linked dht.search.wave span
    clock["t"] += 0.010
    obs.note_fill_start(clock["t"])
    t_f = obs.take_fill(clock["t"])
    s2 = obs.on_dispatch(t_f, clock["t"], 4, AF, 8, 1, 2)
    clock["t"] += 0.020
    obs.on_device_done(s2, clock["t"])
    obs.on_scatter_done(s2, clock["t"], trace="ab" * 16, span="cd" * 8)

    recs = obs.lane_records()
    assert {r["node"] for r in recs} == \
        {"lane:fill", "lane:device", "lane:drain"}
    by_wave = {}
    for r in recs:
        by_wave.setdefault(r["attrs"]["wave_seq"], []).append(r)
    assert all(len(v) == 3 for v in by_wave.values())
    linked = [r for r in recs if r["attrs"]["wave_seq"] == s2]
    assert all(r["attrs"]["wave_trace_id"] == "ab" * 16 for r in linked)
    assert all(r["attrs"]["reshard_gen"] == 2 for r in linked)
    # span ids are unique across lanes; trace groups a wave's slices
    assert len({r["span_id"] for r in recs}) == len(recs)

    trace = obs.chrome_trace()
    evs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(evs) == len(recs)
    meta = {e["args"]["name"]: e["pid"]
            for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {"lane:fill", "lane:device", "lane:drain"} <= set(meta)
    assert len({meta[n] for n in
                ("lane:fill", "lane:device", "lane:drain")}) == 3
    assert tracing is not None


def test_cache_served_wave_exports_fill_lane_only():
    obs, clock = make_obs()
    obs.note_fill_start(clock["t"])
    clock["t"] += 0.004
    t_f = obs.take_fill(clock["t"])
    obs.note_cache_served(t_f, 7)
    recs = obs.lane_records()
    assert [r["node"] for r in recs] == ["lane:fill"]
    assert recs[0]["attrs"]["cache_served"] is True
    assert recs[0]["attrs"]["entries"] == 7


def test_ring_bounded_and_disabled_is_noop():
    obs, clock = make_obs(ring=4)
    for _ in range(10):
        run_wave(obs, clock, fill_wait=0.001)
        clock["t"] += 0.001
    assert obs.snapshot()["ring"] == 4

    reg = telemetry.MetricsRegistry()
    off = PipelineObservatory(PipelineObservatoryConfig(enabled=False),
                              registry=reg)
    off.note_fill_start(1.0)
    assert off.take_fill(2.0) is None
    assert off.on_dispatch(None, 2.0, 4, AF, 8, 0, 0) == -1
    off.on_device_done(-1, 3.0)
    off.on_scatter_done(-1, 3.0)
    assert off.snapshot() == {"enabled": False}
    assert off.occupancy() is None and off.collapse() is None
    assert reg.gauge("dht_pipeline_occupancy").value == -1.0


# ============================== integration: failure-path lifecycles
DEPTHS = (1, 2, 4)


def _obs_of(dht):
    return dht.wave_builder.observatory


@pytest.mark.parametrize("depth", DEPTHS)
def test_launch_retry_requeue_closes_lanes(depth):
    """A consume failure requeues the entries — the failed wave's lane
    slices must close (no orphan open intervals) and the retry wave's
    idle gap is attributed launch_retry."""
    clock = {"t": 30_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002,
                   ingest_pipeline_depth=depth)
    handles = fake_launch(dht, ok=True, fail=True)   # consume raises
    got = []
    for name in ("lr-a", "lr-b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    _pump(dht, clock)
    obs = _obs_of(dht)
    assert len(obs._open) == 0, "failed wave leaked an open interval"
    acct = obs.account()
    assert acct["open_waves"] == 0
    # let the retry wave through
    for h in handles:
        h.fail = False
    for _ in range(4):
        _pump(dht, clock)
    assert sorted(got) == ["lr-a", "lr-b"]
    assert len(obs._open) == 0
    assert obs.account()["bubble_n"]["launch_retry"] >= 1
    # the ledger still closes across the failure
    a = obs.account()
    assert a["attributed_s"] == pytest.approx(a["span_s"], abs=1e-6)


@pytest.mark.parametrize("depth", (2, 4))
def test_mid_drain_device_error_closes_lanes(depth):
    """Wave N−1 dies at consume while wave N is still in flight: the
    dead wave's slices close at the requeue, the live wave's at its
    own scatter — the timeline never holds an orphan."""
    clock = {"t": 31_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002,
                   ingest_pipeline_depth=depth)
    handles = fake_launch(dht)
    got = []
    for name in ("md-1a", "md-1b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    dht.scheduler.run()
    for name in ("md-2a", "md-2b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    _pump(dht, clock)
    assert len(handles) == 2
    obs = _obs_of(dht)
    assert len(obs._open) == 2        # both legitimately in flight
    handles[0].fail = True            # wave 1 dies mid-drain
    handles[1].ok = True
    _pump(dht, clock)
    assert got == ["md-2a", "md-2b"]
    assert len(obs._open) == 0, "mid-drain failure leaked an interval"
    for _ in range(4):
        # flip EVERY handle each pump — the retry wave makes new ones
        for h in handles:
            h.ok, h.fail = True, False
        _pump(dht, clock)
    assert sorted(got) == ["md-1a", "md-1b", "md-2a", "md-2b"]
    assert len(obs._open) == 0
    assert obs.account()["bubble_n"]["launch_retry"] >= 1


class _FakeLayout:
    def __init__(self, gen):
        self.gen = gen


class _FakeReshard:
    def __init__(self, gen):
        self.layout = _FakeLayout(gen)


@pytest.mark.parametrize("depth", DEPTHS)
def test_reshard_swap_between_waves_attributed(depth):
    """A boundary-generation hot swap between waves owns the idle gap
    it opens: the next dispatch classifies reshard_swap, and both
    waves' lanes close normally."""
    clock = {"t": 32_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002,
                   ingest_pipeline_depth=depth)
    fake_launch(dht, ok=True)
    dht.reshard = _FakeReshard(0)
    got = []
    for name in ("rs-1a", "rs-1b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    for _ in range(3):
        _pump(dht, clock)
    obs = _obs_of(dht)
    assert len(obs._open) == 0
    dht.reshard = _FakeReshard(5)     # hot swap between waves
    for name in ("rs-2a", "rs-2b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes, n=name: got.append(n))
    for _ in range(3):
        _pump(dht, clock)
    assert sorted(got) == ["rs-1a", "rs-1b", "rs-2a", "rs-2b"]
    assert len(obs._open) == 0
    assert obs.account()["bubble_n"]["reshard_swap"] == 1
    ring = [w for w in obs._ring if w.gen == 5]
    assert ring and all(w.t_done >= w.t_avail >= w.t_dispatch
                        for w in ring)


# ===================== satellite 2: windowed in-flight peak regression
def test_inflight_peak_windows_on_frame_tick():
    """The peak gauge must report the high-water of the CURRENT
    history-frame window (max of the two live windows so it never
    blinks to 0 at a frame edge), not a boot-time spike forever."""
    clock = {"t": 33_000.0}
    dht = make_dht(clock, ingest_fill_target=2, ingest_deadline=0.002,
                   ingest_pipeline_depth=2)
    handles = fake_launch(dht)
    reg = telemetry.get_registry()
    g = reg.gauge("dht_ingest_pipeline_inflight_peak")
    for name in ("pk-1a", "pk-1b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes: None)
    dht.scheduler.run()
    for name in ("pk-2a", "pk-2b"):
        dht.wave_builder.submit(InfoHash.get(name), AF, SEARCH_NODES,
                                lambda nodes: None)
    _pump(dht, clock)
    assert dht.wave_builder.inflight_peak == 2
    assert g.value == 2.0
    for h in handles:
        h.ok = True
    _pump(dht, clock)                 # drained: inflight back to 0
    # first frame edge: previous window's peak (2) still visible
    dht.wave_builder.frame_tick()
    assert g.value == 2.0
    assert dht.wave_builder.pipeline_snapshot()["inflight_peak"] == 2
    # second frame edge with no new waves: the spike has aged out
    dht.wave_builder.frame_tick()
    assert g.value == 0.0
    assert dht.wave_builder.snapshot()["inflight_peak"] == 0
    # and frame_tick feeds the observatory's occupancy checkpoints
    assert len(_obs_of(dht)._ckpts) >= 2


def test_history_frame_hook_drives_frame_tick():
    """runner.py wires WaveBuilder.frame_tick as a history frame hook;
    the History side of that seam: hooks fire once per committed frame
    and a raising hook is swallowed (observability never kills the
    recorder)."""
    from opendht_tpu.history import MetricsHistory

    reg = telemetry.MetricsRegistry()
    clock = {"t": 40_000.0}
    h = MetricsHistory(registry=reg, clock=lambda: clock["t"])
    seen = []
    h.add_frame_hook(lambda frame: seen.append(frame))
    h.add_frame_hook(lambda frame: 1 / 0)   # must not break the tick
    reg.counter("dht_test_ticks_total").inc()
    h.tick()                          # first tick: baseline, no frame
    assert seen == []
    clock["t"] += 1.0
    reg.counter("dht_test_ticks_total").inc()
    frame = h.tick()
    assert frame is not None
    assert len(seen) == 1 and seen[0] is frame
    clock["t"] += 1.0
    h.tick()
    assert len(seen) == 2


# ========================================= health signal registration
def test_health_signal_registered_degrade_only():
    from opendht_tpu.health import DEFAULT_SIGNAL_THRESHOLDS, HealthConfig
    assert "pipeline_occupancy" in DEFAULT_SIGNAL_THRESHOLDS
    lo, hi = DEFAULT_SIGNAL_THRESHOLDS["pipeline_occupancy"]
    assert 0.0 < lo < hi <= 1.0
    assert "pipeline_occupancy" in HealthConfig().degrade_only
