"""Per-peer network observatory unit tests (round 23, ISSUE-19):
the RFC 6298 estimator math, the adaptive-RTO clamp and its
behaviour-equivalence pin (zero samples / knob off / ledger disabled
=> exactly the fixed MAX_RESPONSE_TIME, including an engine-level
retransmit-schedule pin), both halves of Karn's algorithm (sampling
rule + exponential backoff), LRU eviction parking gauges at the -1
unknown sentinel, flap-transition mirroring of the reference's Node
liveness rules, the fail_signal floor, the snapshot document shape,
the wiremap assembler's skew/violation contract and the
``dhtmon --max-peer-fail`` worst-link / unknown-never-violates gate."""

from types import SimpleNamespace

import pytest

from opendht_tpu import telemetry
from opendht_tpu.infohash import InfoHash
from opendht_tpu.net.node import MAX_RESPONSE_TIME, Node
from opendht_tpu.peers import _FIXED_PATIENCE, PeerLedger, PeersConfig
from opendht_tpu.sockaddr import SockAddr
from opendht_tpu.testing import wiremap_assembler as wma
from opendht_tpu.tools import dhtmon

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class FakePeer:
    """Duck-typed net.Node: the ledger reads id/addr and the liveness
    pair (expired / is_good)."""

    def __init__(self, pid="feedc0de" + "0" * 32, addr="10.0.0.1:4000",
                 good=True):
        self.id = pid
        self.addr = addr
        self.expired = False
        self.good = good

    def is_good(self, now):
        return self.good


def _req(peer, attempts=1, mtype="get", nbytes=64):
    return SimpleNamespace(node=peer, attempt_count=attempts,
                           type=SimpleNamespace(value=mtype),
                           msg=b"x" * nbytes)


def _ledger(node="t", **kw):
    clock = FakeClock()
    reg = telemetry.MetricsRegistry()
    led = PeerLedger(PeersConfig(**kw), node=node, clock=clock,
                     registry=reg)
    return led, clock, reg


def _row(led, peer):
    for p in led.snapshot()["peers"]:
        if p["id"] == peer.id:
            return p
    return None


# ----------------------------------------------------------- RFC 6298
def test_rfc6298_estimator_math():
    """First sample seeds srtt=rtt, rttvar=rtt/2; every later sample
    applies the 7/8 / 3/4 EWMA coefficients exactly."""
    led, _, _ = _ledger()
    p = FakePeer()
    led.on_request_completed(_req(p), 0.100)
    row = _row(led, p)
    assert row["srtt"] == pytest.approx(0.100)
    assert row["rttvar"] == pytest.approx(0.050)
    assert row["samples"] == 1
    led.on_request_completed(_req(p), 0.200)
    row = _row(led, p)
    # rttvar <- 0.75*0.05 + 0.25*|0.1 - 0.2|; srtt <- 0.875*0.1 + 0.125*0.2
    assert row["rttvar"] == pytest.approx(0.0625)
    assert row["srtt"] == pytest.approx(0.1125)
    assert row["samples"] == 2


def test_rtt_histogram_per_peer():
    led, _, reg = _ledger()
    p = FakePeer()
    led.on_request_completed(_req(p), 0.010)
    led.on_request_completed(_req(p), 0.020)
    series = reg.series("dht_peer_rtt_seconds")
    assert len(series) == 1
    (h,) = series.values()
    assert h.count == 2


# ------------------------------------- the behaviour-equivalence pin
def test_rto_pin_zero_samples_knob_off_disabled():
    """The acceptance pin: with zero RTT samples, the knob off, or the
    ledger disabled, rto() is EXACTLY the fixed MAX_RESPONSE_TIME."""
    p = FakePeer()
    # adaptive on, peer never seen
    led, _, _ = _ledger(adaptive_rto=True)
    assert led.rto(p) == MAX_RESPONSE_TIME
    # adaptive on, peer tracked but zero samples — even after timeouts
    # bumped the Karn backoff (backoff must not steer no-sample peers)
    led.on_send(p, "get", 64)
    led.on_retransmit(_req(p, attempts=2))
    led.on_request_expired(_req(p, attempts=3))
    assert _row(led, p)["backoff"] == 2
    assert led.rto(p) == MAX_RESPONSE_TIME
    # knob off: the ledger still measures, the timer never moves
    led, _, _ = _ledger(adaptive_rto=False)
    led.on_request_completed(_req(p), 0.5)
    assert _row(led, p)["srtt"] == pytest.approx(0.5)
    assert led.rto(p) == MAX_RESPONSE_TIME
    assert _row(led, p)["rto"] == MAX_RESPONSE_TIME
    # master switch off: no tracking at all
    led, _, _ = _ledger(enabled=False, adaptive_rto=True)
    led.on_send(p, "get", 64)
    led.on_request_completed(_req(p), 0.5)
    assert led.rto(p) == MAX_RESPONSE_TIME
    snap = led.snapshot()
    assert snap["tracked"] == 0 and snap["enabled"] is False


def test_adaptive_rto_formula_and_clamps():
    led, _, _ = _ledger(adaptive_rto=True)
    p = FakePeer()
    led.on_request_completed(_req(p), 0.100)
    # srtt + 4*rttvar = 0.1 + 4*0.05
    assert led.rto(p) == pytest.approx(0.300)
    # a 2 ms peer clamps up to rto_min
    led, _, _ = _ledger(adaptive_rto=True)
    led.on_request_completed(_req(p), 0.002)
    assert led.rto(p) == pytest.approx(0.25)
    # a multi-second estimate clamps to the default ceiling: the fixed
    # path's total 3 x MAX_RESPONSE_TIME patience
    led, _, _ = _ledger(adaptive_rto=True)
    led.on_request_completed(_req(p), 2.0)
    assert led.rto(p) == pytest.approx(_FIXED_PATIENCE)
    # the strict escape-hatch clamp: rto_max = 1.0
    led, _, _ = _ledger(adaptive_rto=True, rto_max=1.0)
    led.on_request_completed(_req(p), 2.0)
    assert led.rto(p) == pytest.approx(1.0)


# ------------------------------------------------- Karn's algorithm
def test_karn_backoff_doubles_and_resets():
    led, _, _ = _ledger(adaptive_rto=True)
    p = FakePeer()
    led.on_request_completed(_req(p), 0.100)   # base RTO 0.3
    led.on_retransmit(_req(p, attempts=2))
    assert led.rto(p) == pytest.approx(0.600)
    led.on_retransmit(_req(p, attempts=3))
    assert led.rto(p) == pytest.approx(1.200)
    # a final request expiry keeps backing off
    led.on_request_expired(_req(p, attempts=3))
    assert _row(led, p)["backoff"] == 3
    assert led.rto(p) == pytest.approx(2.400)
    # ...until the ceiling
    led.on_retransmit(_req(p, attempts=2))
    assert led.rto(p) == pytest.approx(_FIXED_PATIENCE)
    # the exponent caps at 8 no matter how many timeouts pile up
    for _ in range(20):
        led.on_request_expired(_req(p, attempts=3))
    assert _row(led, p)["backoff"] == 8
    # one clean sample (un-retransmitted attempt) ends the backoff
    # (the repeat sample also decays rttvar: 0.1 + 4*0.0375 clamps
    # up to rto_min)
    led.on_request_completed(_req(p, attempts=1), 0.100)
    assert _row(led, p)["backoff"] == 0
    assert led.rto(p) == pytest.approx(0.25)


def test_karn_sampling_rule_and_spurious_counting():
    """A reply after a retransmit is ambiguous: no RTT sample, and the
    extra attempts are counted as spurious retransmits (the reply was
    already in flight)."""
    led, _, reg = _ledger()
    p = FakePeer()
    led.on_request_completed(_req(p, attempts=3), 0.100)
    row = _row(led, p)
    assert row["samples"] == 0 and row["srtt"] is None
    assert row["spurious_retransmits"] == 2
    assert row["completed"] == 1
    (c,) = reg.series("dht_peer_spurious_retransmits_total").values()
    assert c.value == 2
    # a retransmitted completion must NOT reset the backoff either
    led.on_request_expired(_req(p, attempts=3))
    led.on_request_completed(_req(p, attempts=2), None)
    assert _row(led, p)["backoff"] == 1
    # a clean completion with no measurable RTT: counted, not sampled
    led.on_request_completed(_req(p, attempts=1), None)
    assert _row(led, p)["samples"] == 0
    assert _row(led, p)["completed"] == 3


# ------------------------------------------------------ LRU eviction
def test_lru_eviction_parks_gauges_at_unknown():
    led, _, reg = _ledger(capacity=2)
    a = FakePeer(pid="aaaa" * 10, addr="10.0.0.1:1")
    b = FakePeer(pid="bbbb" * 10, addr="10.0.0.2:2")
    c = FakePeer(pid="cccc" * 10, addr="10.0.0.3:3")
    led.on_request_completed(_req(a), 0.1)     # a has a live srtt gauge
    led.on_send(b, "get", 10)
    led.on_send(a, "get", 10)                  # LRU touch: a is newest
    led.on_send(c, "get", 10)                  # evicts b, NOT a
    snap = led.snapshot()
    assert snap["tracked"] == 2 and snap["evicted"] == 1
    assert {p["id"] for p in snap["peers"]} == {a.id, c.id}
    (ev,) = reg.series("dht_peer_evicted_total").values()
    assert ev.value == 1
    assert reg.series("dht_peer_tracked")[next(
        iter(reg.series("dht_peer_tracked")))].value == 2.0
    # now evict a: its srtt gauge (0.1) must park at the -1 sentinel
    # every per-peer reader treats as unknown
    led.on_send(b, "get", 10)
    g = [m for k, m in reg.series("dht_peer_srtt_seconds").items()
         if dict(k).get("peer", "").startswith("aaaaaaaa@")]
    assert len(g) == 1 and g[0].value == -1.0


# ------------------------------------------------- status flaps
def test_flap_transitions_mirror_node_liveness():
    led, _, reg = _ledger()
    p = FakePeer(good=True)
    led.on_send(p, "get", 10)
    assert _row(led, p)["status"] == "good"
    assert _row(led, p)["flaps"] == 0
    p.good = False
    led.on_send(p, "get", 10)
    row = _row(led, p)
    assert row["status"] == "dubious" and row["flaps"] == 1
    assert row["transitions"] == {"good->dubious": 1}
    p.expired = True
    led.on_received(p, "reply", 10)
    row = _row(led, p)
    assert row["status"] == "expired" and row["flaps"] == 2
    assert row["transitions"]["dubious->expired"] == 1
    (c,) = reg.series("dht_peer_flaps_total").values()
    assert c.value == 2


# --------------------------------------------------- fail signal
def test_fail_signal_floor_and_worst_link():
    led, _, _ = _ledger(min_signal_events=4)
    p = FakePeer(pid="dddd" * 10, addr="10.0.0.4:4")
    for _ in range(2):
        led.on_send(p, "get", 10)
    led.on_request_expired(_req(p, attempts=3))
    led.on_request_expired(_req(p, attempts=3))
    # 2/2 expired but only 2 requests: below the signal floor
    assert led.fail_signal() is None
    assert _row(led, p)["fail_ratio"] == pytest.approx(1.0)
    for _ in range(2):
        led.on_send(p, "get", 10)
    led.on_request_completed(_req(p), 0.01)
    led.on_request_completed(_req(p), 0.01)
    assert led.fail_signal() == pytest.approx(0.5)
    # the signal is the WORST qualifying link, not an average
    q = FakePeer(pid="eeee" * 10, addr="10.0.0.5:5")
    for _ in range(4):
        led.on_send(q, "get", 10)
        led.on_request_completed(_req(q), 0.01)
    assert led.fail_signal() == pytest.approx(0.5)
    # the gauge parks at -1 below the floor (dhtmon's unknown contract)
    led2, _, reg2 = _ledger(min_signal_events=8)
    led2.on_send(p, "get", 10)
    led2.on_request_expired(_req(p, attempts=3))
    (g,) = reg2.series("dht_peer_fail_ratio").values()
    assert g.value == -1.0


# ---------------------------------------------------- doc surfaces
def test_snapshot_shape_and_recency_order():
    led, clock, _ = _ledger()
    a = FakePeer(pid="aaaa" * 10, addr="10.0.0.1:1")
    b = FakePeer(pid="bbbb" * 10, addr="10.0.0.2:2")
    led.on_send(a, "get", 100)
    clock.t += 5.0
    led.on_received(b, "reply", 200)
    snap = led.snapshot()
    for key in ("enabled", "node", "time", "adaptive_rto", "rto_min",
                "rto_max", "capacity", "tracked", "evicted",
                "fail_signal", "peers"):
        assert key in snap, key
    assert snap["node"] == "t" and snap["time"] == clock.t
    # most recently touched first (the REPL / scanner print order)
    assert [p["id"] for p in snap["peers"]] == [b.id, a.id]
    row = snap["peers"][1]
    for key in ("id", "addr", "peer", "srtt", "rttvar", "rto",
                "samples", "backoff", "sent", "completed", "expired",
                "cancelled", "attempt_timeouts", "spurious_retransmits",
                "fail_ratio", "bytes_in", "bytes_out", "msgs_in",
                "status", "flaps", "transitions", "first_seen",
                "last_seen"):
        assert key in row, key
    assert row["bytes_out"] == {"get": 100}
    assert snap["peers"][0]["bytes_in"] == {"reply": 200}
    assert snap["peers"][0]["msgs_in"] == 1


def test_bytes_by_type_and_cancelled():
    led, _, reg = _ledger()
    p = FakePeer()
    led.on_send(p, "get", 100)
    led.on_send(p, "put", 300)
    led.on_received(p, "reply", 200)
    led.on_received(p, "reply", 0)      # reassembled: size unknown
    led.on_request_cancelled(_req(p))
    row = _row(led, p)
    assert row["bytes_out"] == {"get": 100, "put": 300}
    assert row["bytes_in"] == {"reply": 200}
    assert row["msgs_in"] == 2 and row["cancelled"] == 1
    series = reg.series("dht_peer_bytes_total")
    by_dir = {}
    for key, c in series.items():
        labels = dict(key)
        assert "direction" in labels and "type" in labels
        by_dir.setdefault(labels["direction"], 0)
        by_dir[labels["direction"]] += c.value
    assert by_dir == {"out": 400, "in": 200}


def test_runner_get_peers_degrades_before_run():
    """The GET /peers spine degrades to {"enabled": False} on a
    runner that is not running — and the wiremap assembler treats
    that as a missing ledger, not a crash."""
    from opendht_tpu.runtime.runner import DhtRunner
    r = DhtRunner()
    assert r.get_peers() == {"enabled": False}
    wm = wma.assemble_wiremap([r])
    assert wm["nodes"] == [] and wm["edges"] == []
    assert wm["violations"] == ["source 0: no per-peer ledger"]


# ------------------------------------- engine-level equivalence pin
def _blackhole_schedule(adaptive):
    """Send one ping into a black hole and return the clock times of
    every (re)transmission under fine-grained stepping."""
    from test_net_engine import Net
    net = Net()
    a = net.make_engine("alice", 1)
    sent_at = []
    a._send_fn = lambda data, dst: sent_at.append(round(net.clock.t, 6)) or 0
    if adaptive is not None:
        a.peers = PeerLedger(PeersConfig(adaptive_rto=adaptive),
                             node="alice", clock=net.clock,
                             registry=telemetry.MetricsRegistry())
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    a.send_ping(node)
    for _ in range(40):
        net.advance(0.25)
    return sent_at


def test_engine_schedule_identical_with_zero_samples():
    """The acceptance pin at the engine seam: with the ledger attached
    and adaptive_rto ON but zero RTT samples, the retransmit schedule
    is step-for-step identical to the no-ledger engine."""
    bare = _blackhole_schedule(None)
    fixed = _blackhole_schedule(False)
    adaptive = _blackhole_schedule(True)
    assert len(bare) == 3               # MAX_ATTEMPT_COUNT
    assert fixed == bare
    assert adaptive == bare


def test_engine_adaptive_rto_consulted_after_sample():
    """With a fast RTT sample banked, the engine retransmits off the
    per-peer RTO (rto_min-clamped 0.25 s) instead of waiting the fixed
    1.0 s — the knob actually steers the scheduler."""
    from test_net_engine import Net
    net = Net()
    a = net.make_engine("alice", 1)
    sent_at = []
    a._send_fn = lambda data, dst: sent_at.append(round(net.clock.t, 6)) or 0
    led = PeerLedger(PeersConfig(adaptive_rto=True), node="alice",
                     clock=net.clock, registry=telemetry.MetricsRegistry())
    a.peers = led
    node = Node(InfoHash.get("bob"), SockAddr("10.0.0.9", 1234))
    led.on_request_completed(_req(node), 0.002)    # srtt 2 ms -> RTO 0.25
    req = a.send_ping(node)
    assert req.rto == pytest.approx(0.25)
    for _ in range(8):
        net.advance(0.25)
    assert len(sent_at) >= 2, sent_at
    assert sent_at[1] - sent_at[0] <= 0.5 + 1e-9, sent_at


# ------------------------------------------------ wiremap assembler
def _peers_doc(node, peers, t=100.0, **extra):
    doc = {"enabled": True, "node": node, "time": t, "tracked":
           len(peers), "evicted": 0, "adaptive_rto": False,
           "peers": peers}
    doc.update(extra)
    return doc


def _edge_doc(pid, first, last, fail=None, **extra):
    d = {"id": pid, "addr": "10.0.0.9:9", "peer": pid[:8] + "@x",
         "first_seen": first, "last_seen": last, "fail_ratio": fail}
    d.update(extra)
    return d


def test_wiremap_from_ledgers_edges_and_attribution():
    lA, _, _ = _ledger(node="A")
    lB, _, _ = _ledger(node="B")
    pb = FakePeer(pid="B", addr="10.0.0.2:2")
    pc = FakePeer(pid="C", addr="10.0.0.3:3")     # outside the map
    pa = FakePeer(pid="A", addr="10.0.0.1:1")
    lA.on_send(pb, "get", 10)
    lA.on_request_expired(_req(pb, attempts=3))
    lA.on_request_completed(_req(pb), 0.01)
    lA.on_send(pc, "get", 10)
    lB.on_send(pa, "get", 10)
    lB.on_request_completed(_req(pa), 0.01)
    wm = wma.assemble_wiremap([lA, lB])
    assert wm["violations"] == []
    assert {n["id"] for n in wm["nodes"]} == {"A", "B"}
    assert len(wm["edges"]) == 3
    ab = wma.find_edge(wm, "A", "B")
    assert ab is not None and ab["known"] is True
    assert ab["fail_ratio"] == pytest.approx(0.5)
    ac = wma.find_edge(wm, "A", "C")
    assert ac is not None and ac["known"] is False
    assert wma.find_edge(wm, "B", "C") is None
    # rank excludes unknown-metric edges; worst is the lossy one
    ranked = wma.rank_edges(wm, "fail_ratio")
    assert [e["dst"] for e in ranked] == ["B", "A"]
    worst = wma.worst_edge(wm, "fail_ratio")
    assert worst["src"] == "A" and worst["dst"] == "B"
    # every edge is unknown on a metric nobody has -> worst is None
    assert wma.worst_edge(wm, "no_such_metric") is None


def test_wiremap_skew_adjustment_and_violations():
    # node A runs 10 s ahead of the scraper's wall clock
    docA = _peers_doc("A", [_edge_doc("B", 50.0, 99.0, fail=0.5)],
                      t=100.0, scraped_at=90.0, endpoint="a:1")
    wm = wma.assemble_wiremap([docA])
    assert wm["violations"] == []
    assert wm["skew"]["A"] == pytest.approx(10.0)
    (e,) = wm["edges"]
    assert e["last_seen_adj"] == pytest.approx(89.0)
    assert e["first_seen_adj"] == pytest.approx(40.0)
    # a peer row stamped after its own snapshot: REPORTED, never
    # dropped (a post-mortem tool must degrade, not lie)
    docB = _peers_doc("B", [_edge_doc("A", 50.0, 100.2)], t=100.0)
    wm = wma.assemble_wiremap([docB])
    assert len(wm["edges"]) == 1
    assert any("after its own snapshot" in v for v in wm["violations"])
    # first_seen > last_seen
    docC = _peers_doc("C", [_edge_doc("A", 60.0, 50.0)], t=100.0)
    wm = wma.assemble_wiremap([docC])
    assert any("first_seen" in v for v in wm["violations"])
    # a disabled/absent ledger is a reported violation, with the
    # healthy sources still assembled
    wm = wma.assemble_wiremap([{"enabled": False}, docA])
    assert wm["violations"] == ["source 0: no per-peer ledger"]
    assert len(wm["nodes"]) == 1


# ------------------------------------------- dhtmon --max-peer-fail
def _fake_scraper(series_by_ep):
    def scrape(ep, timeout=10.0):
        return {"endpoint": ep, "ready": True, "verdict": "ok",
                "health": {}, "series": dict(series_by_ep[ep])}
    return scrape


def test_dhtmon_max_peer_fail_worst_link_gate(monkeypatch):
    series = {
        "n1": {'dht_peer_fail_ratio{node="n1",peer="p1@x"}': 0.4,
               'dht_peer_fail_ratio{node="n1",peer="p2@x"}': -1.0},
        "n2": {'dht_peer_fail_ratio{node="n2",peer="p3@x"}': 0.1},
    }
    monkeypatch.setattr(dhtmon.hm, "scrape_node", _fake_scraper(series))
    eps = ["n1", "n2"]
    violations, doc = dhtmon.run_checks(eps, max_peer_fail=0.5)
    assert violations == []
    assert doc["peer_fail"]["max"] == pytest.approx(0.4)
    violations, doc = dhtmon.run_checks(eps, max_peer_fail=0.3)
    assert len(violations) == 1 and "n1" in violations[0]
    assert "peer fail ratio" in violations[0]
    # the gate is per-link worst, not an average: 0.25 would pass a
    # mean but the single 0.4 link must trip it
    violations, _doc = dhtmon.run_checks(eps, max_peer_fail=0.25)
    assert len(violations) == 1
    # the gate only exists when asked for
    _violations, doc = dhtmon.run_checks(eps)
    assert "peer_fail" not in doc


def test_dhtmon_max_peer_fail_unknown_never_violates(monkeypatch):
    # every gauge parked/absent: ledger off, evicted, or below the
    # signal floor — unknown must never violate, even at threshold 0
    series = {
        "n1": {'dht_peer_fail_ratio{node="n1",peer="p1@x"}': -1.0},
        "n2": {},
    }
    monkeypatch.setattr(dhtmon.hm, "scrape_node", _fake_scraper(series))
    violations, doc = dhtmon.run_checks(["n1", "n2"], max_peer_fail=0.0)
    assert violations == []
    assert doc["peer_fail"]["max"] is None
    assert all(p["peer_fail"] is None
               for p in doc["peer_fail"]["per_node"])
