"""Flight data recorder (round 17): the metrics history ring, windowed
queries, spill bounds, black-box bundles, the health engine's
one-delta-codepath integration, and the cluster timeline assembler."""

import json
import os
import time

import pytest

from opendht_tpu import health, history, telemetry
from opendht_tpu.history import (HistoryConfig, MetricsHistory,
                                 build_bundle, frames_to_series)
from opendht_tpu.testing import health_monitor as hm
from opendht_tpu.testing import timeline_assembler as ta


def _recorder(capacity=8, **kw):
    reg = telemetry.MetricsRegistry()
    clock = [0.0]
    cfg = HistoryConfig(period=1.0, capacity=capacity, **kw)
    h = MetricsHistory(cfg, registry=reg, clock=lambda: clock[0])
    return reg, clock, h


# ================================================================= ring
def test_first_tick_is_baseline_only():
    """The first tick must not report the node's whole lifetime as one
    frame — it establishes the cumulative baseline and appends
    nothing."""
    reg, clock, h = _recorder()
    reg.counter("h_boot_total").inc(1000)     # pre-recorder lifetime
    assert h.tick() is None
    assert h.frames() == []
    clock[0] = 1.0
    reg.counter("h_boot_total").inc(3)
    f = h.tick()
    assert f["counters"]["h_boot_total"] == 3     # not 1003


def test_ring_bounded_oldest_evicted():
    reg, clock, h = _recorder(capacity=8)
    c = reg.counter("h_flood_total")
    h.tick()
    for i in range(80):                      # 10x capacity
        clock[0] += 1.0
        c.inc(1)
        h.tick()
    frames = h.frames()
    assert len(frames) == 8
    # oldest evicted: the survivors are the NEWEST 8
    assert [f["seq"] for f in frames] == list(range(73, 81))


def test_counter_reset_and_gauge_last_value():
    reg, clock, h = _recorder()
    c = reg.counter("h_reset_total")
    g = reg.gauge("h_gauge")
    c.inc(5)
    g.set(2.0)
    h.tick()
    clock[0] = 1.0
    c.inc(2)
    f1 = h.tick()
    assert f1["counters"]["h_reset_total"] == 2
    assert "h_gauge" not in f1["gauges"]      # unchanged → not recorded
    clock[0] = 2.0
    reg.reset()                               # counters rewind in place
    c.inc(4)
    g.set(9.0)
    f2 = h.tick()
    # post-reset the new cumulative IS the window's events
    assert f2["counters"]["h_reset_total"] == 4
    assert f2["gauges"]["h_gauge"] == 9.0     # changed → last-value


def test_windowed_rate_and_quantile():
    reg, clock, h = _recorder(capacity=16)
    c = reg.counter("h_ops_total", op="get", ok="true")
    hist = reg.histogram("h_sec", op="get")
    h.tick()
    for i in range(6):
        clock[0] += 1.0
        c.inc(10)
        hist.observe(0.5 if i < 3 else 8.0)
        h.tick()
    # family AND exact-series matching
    assert h.counter_delta("h_ops_total", 0.0, 6.0) == 60
    assert h.counter_delta('h_ops_total{ok="true",op="get"}',
                           3.0, 6.0) == 30
    assert h.rate("h_ops_total", 2.0, 6.0) == pytest.approx(10.0)
    # the early window saw only 0.5 s observations, the late only 8 s
    assert h.quantile("h_sec", 0.5, 0.0, 3.0) <= 0.5
    assert h.quantile("h_sec", 0.5, 3.0, 6.0) > 4.0
    # no coverage → None (the round-14 "window not computable" contract)
    assert h.counter_delta("h_ops_total", 100.0, 200.0) is None
    assert h.quantile("h_sec", 0.5, 100.0, 200.0) is None
    assert h.rate("h_ops_total", 100.0, 200.0) is None
    # limit contract: 0 means NONE, not "unlimited" (review finding —
    # the proxy routes accept 0 and must get an empty page)
    assert h.frames(limit=0) == []
    assert len(h.frames(limit=2)) == 2


def test_default_capacity_covers_slow_slo_window():
    """The default ring must retain at least the health engine's slow
    SLO window x its 1.25 keep slack at the default periods — a
    shorter ring silently truncates slow-burn windows to partial
    totals (review finding)."""
    hc = HistoryConfig()
    slo = health.HealthConfig()
    assert hc.capacity * hc.period >= slo.slow_window * 1.25


def test_frames_json_roundtrip():
    """Frames survive JSON (proxy route, bundle files, spill segments):
    bucket keys stringify and the query readers re-normalize."""
    reg, clock, h = _recorder()
    hist = reg.histogram("h_rt_sec")
    h.tick()
    clock[0] = 1.0
    hist.observe(2.0)
    h.tick()
    rt = json.loads(json.dumps(h.frames()))
    s = frames_to_series(rt)
    assert any("h_rt_sec_bucket" in k for k in s)
    q = ta.window_series(ta.assemble_timeline([rt]))
    assert q == s


# ================================================================ spill
def test_spill_segments_bounded(tmp_path):
    reg, clock, h = _recorder(
        capacity=16, spill_dir=str(tmp_path / "spill"),
        spill_segment_frames=4, spill_max_segments=3)
    c = reg.counter("h_spill_total")
    h.tick()
    for i in range(10 * 16):                  # 10x the ring capacity
        clock[0] += 1.0
        c.inc(1)
        h.tick()
    assert len(h.frames()) == 16              # ring bounded
    assert h.spill_segments <= 3              # disk bounded
    spilled = h.spilled_frames()
    assert 0 < len(spilled) <= 3 * 4
    # oldest-evicted on disk too: the retained segments are the newest
    assert spilled[-1]["seq"] == h.frames()[-1]["seq"]
    assert spilled == sorted(spilled, key=lambda f: f["seq"])


def test_spill_failure_disables_not_kills(tmp_path):
    bad = tmp_path / "blocked"
    bad.write_text("a file, not a dir")
    reg, clock, h = _recorder(capacity=8, spill_dir=str(bad),
                              spill_segment_frames=2,
                              spill_max_segments=2)
    c = reg.counter("h_sf_total")
    h.tick()
    for _ in range(6):
        clock[0] += 1.0
        c.inc(1)
        assert h.tick() is not None           # ring keeps recording
    assert h.meta()["spill"]["active"] is False


# ============================================== one-delta-codepath pins
def test_frames_equal_scrape_diff():
    """The dhtmon pin (round-17 satellite): windowed invariants over
    history frames equal the scrape-diff-scrape evaluation of the same
    interval, through the SAME invariant code
    (lookup_success/cluster_quantile over one summed series map)."""
    from opendht_tpu.testing.telemetry_smoke import parse_exposition

    reg, clock, h = _recorder(capacity=32)
    ok = reg.counter("dht_ops_total", op="get", ok="true")
    bad = reg.counter("dht_ops_total", op="get", ok="false")
    hist = reg.histogram("dht_op_seconds", op="get")
    h.tick()
    baseline = parse_exposition(reg.prometheus())     # scrape #1
    for i in range(5):
        clock[0] += 1.0
        ok.inc(9)
        bad.inc(1)
        hist.observe(0.25)
        hist.observe(3.0)
        h.tick()
    after = parse_exposition(reg.prometheus())        # scrape #2
    diffed = {k: max(v - baseline.get(k, 0.0), 0.0)
              for k, v in after.items()}
    from_frames = frames_to_series(h.frames(0.0, 5.0))
    assert hm.lookup_success(from_frames) == hm.lookup_success(diffed)
    assert hm.cluster_quantile(from_frames, "get", 0.95) == \
        hm.cluster_quantile(diffed, "get", 0.95)


def test_health_reads_through_history():
    """Satellite: with a recorder attached the evaluator keeps NO
    private window state, and an induced availability burn trips the
    same latch the private-window evaluator trips on identical
    traffic."""
    reg = telemetry.MetricsRegistry()
    clock = [0.0]
    cfg = health.HealthConfig(fast_window=10.0, slow_window=30.0,
                              min_events=4)
    h = MetricsHistory(HistoryConfig(period=1.0, capacity=64),
                       registry=reg, clock=lambda: clock[0])
    ev_h = health.HealthEvaluator(cfg, registry=reg,
                                  clock=lambda: clock[0], history=h)
    ev_p = health.HealthEvaluator(cfg, registry=reg,
                                  clock=lambda: clock[0])
    ok = reg.counter("dht_ops_total", op="get", ok="true")
    bad = reg.counter("dht_ops_total", op="get", ok="false")

    def step(n_ok, n_bad):
        clock[0] += 1.0
        ok.inc(n_ok)
        bad.inc(n_bad)
        h.tick()                      # recorder ticks before health
        return ev_h.tick(), ev_p.tick()

    for _ in range(3):
        rh, rp = step(20, 0)
    assert rh["slo"]["get_availability"]["level"] == "healthy"
    # history evaluator holds no private window state
    assert all(len(st.win._h) == 0 for st in ev_h._slos)
    for _ in range(3):
        rh, rp = step(0, 20)          # total outage → fast burn
    assert rh["slo"]["get_availability"]["level"] == "unhealthy"
    assert rp["slo"]["get_availability"]["level"] == "unhealthy"
    assert rh["verdict"] == rp["verdict"] == "unhealthy"
    # recovery rolls the failure out of BOTH windows (slow = 30 s, so
    # run well past it) on both paths
    for _ in range(40):
        rh, rp = step(20, 0)
    assert rh["slo"]["get_availability"]["level"] == "healthy"
    assert rp["slo"]["get_availability"]["level"] == "healthy"


def test_health_transition_hook_and_bundle():
    """The on_transition hook fires once per verdict change and the
    black-box bundle built there embeds the frames that show the burn
    — the evidence survives the incident."""
    reg = telemetry.MetricsRegistry()
    clock = [0.0]
    h = MetricsHistory(HistoryConfig(period=1.0, capacity=64,
                                     retain_bundles=2),
                       registry=reg, clock=lambda: clock[0])
    cfg = health.HealthConfig(fast_window=10.0, min_events=4)
    ev = health.HealthEvaluator(cfg, registry=reg,
                                clock=lambda: clock[0], history=h)
    captured = []

    def hook(prev, new, report):
        if new == health.UNHEALTHY:
            b = build_bundle(reason="health_transition", history=h,
                             health=report)
            b["transition"] = {"from": prev, "to": new,
                               "causes": report["causes"]}
            h.store_bundle(b)
            captured.append(b)

    ev.on_transition = hook
    bad = reg.counter("dht_ops_total", op="get", ok="false")
    for _ in range(4):
        clock[0] += 1.0
        bad.inc(10)
        h.tick()
        ev.tick()
    assert len(captured) == 1                 # one transition, one bundle
    b = captured[0]
    assert b["kind"] == history.BUNDLE_KIND
    assert b["transition"]["to"] == "unhealthy"
    assert "get_availability" in b["transition"]["causes"]
    # the burn is visible IN the bundle's frames (the transition fires
    # on the first tripping tick, so at least that tick's failures are
    # already retained)
    burn = sum(f["counters"].get(
        'dht_ops_total{ok="false",op="get"}', 0)
        for f in b["history"]["frames"])
    assert burn >= 10
    assert h.bundles() == [b]
    json.loads(json.dumps(b))                 # bundle is one JSON artifact


# ============================================================ timeline
def test_dhtmon_since_rejects_non_positive():
    """--since 0 (or negative) must refuse loudly instead of silently
    evaluating since-boot cumulative counters — the exact failure mode
    --since exists to prevent (review finding).  Exit code 2 through
    the CLI."""
    from opendht_tpu.tools import dhtmon
    for bad in (0.0, -5.0):
        with pytest.raises(ValueError):
            dhtmon.run_checks(["127.0.0.1:1"], min_success=0.99,
                              since=bad)
    rc = dhtmon.main(["--nodes", "127.0.0.1:1", "--min-success",
                      "0.99", "--since", "0"])
    assert rc == 2
    # runners-only invocations have no GET /history: a silent skip
    # would report a windowed gate passed when nothing was evaluated
    with pytest.raises(ValueError):
        dhtmon.run_checks(runners=[object()], min_success=0.99,
                          since=60.0)


def test_timeline_skew_and_monotonicity():
    now = time.time()

    def mkframe(seq, t, n_ok):
        return {"seq": seq, "t": t, "mono": float(seq), "dur": 1.0,
                "counters": {'dht_ops_total{ok="true",op="get"}': n_ok},
                "gauges": {}, "hist": {}}

    # node A scraped with a +2 s clock skew; node B clean
    doc_a = {"node_id": "aa", "enabled": True, "time": now + 2.0,
             "scraped_at": now,
             "frames": [mkframe(1, now + 0.5, 5), mkframe(2, now + 1.5, 5)]}
    doc_b = {"node_id": "bb", "enabled": True, "time": now,
             "scraped_at": now,
             "frames": [mkframe(1, now - 1.0, 3), mkframe(2, now, 3)]}
    tl = ta.assemble_timeline([doc_a, doc_b])
    assert tl["skew"]["aa"] == pytest.approx(2.0)
    assert tl["skew"]["bb"] == pytest.approx(0.0)
    assert not tl["violations"]
    # skew-adjusted merge interleaves correctly: a's first frame lands
    # at now-1.5 adjusted, before b's first at now-1.0
    order = [(f["node"], f["seq"]) for f in tl["frames"]]
    assert order == [("aa", 1), ("bb", 1), ("aa", 2), ("bb", 2)]
    s = ta.window_series(tl)
    assert s['dht_ops_total{ok="true",op="get"}'] == 16
    # monotonicity violations are REPORTED, not dropped
    doc_bad = {"node_id": "cc", "enabled": True,
               "frames": [mkframe(5, now, 1), mkframe(4, now - 9, 1)]}
    tl2 = ta.assemble_timeline([doc_bad])
    assert len(tl2["frames"]) == 2
    assert any("seq" in v for v in tl2["violations"])
    assert any("before its predecessor" in v for v in tl2["violations"])


def test_timeline_accepts_bundles_with_events():
    reg, clock, h = _recorder()
    c = reg.counter("h_tl_total")
    h.tick()
    clock[0] = 1.0
    c.inc(2)
    h.tick()
    b = build_bundle(reason="on_demand", node_id="dd", history=h)
    b["flight_recorder"]["events"] = [
        {"ev": "health_transition", "t": time.time(),
         "node": "dd", "attrs": {"from": "healthy", "to": "unhealthy"}}]
    tl = ta.assemble_timeline([b])
    assert tl["nodes"] == ["dd"]
    assert len(tl["frames"]) == 1
    evs = ta.find_events(tl, "health_transition")
    assert len(evs) == 1 and evs[0]["attrs"]["to"] == "unhealthy"


def test_timeline_single_source_gets_default_node_name():
    """A lone raw frame list (single-node cluster, no node_id anywhere)
    assembles under the positional default name — not a crash, not an
    anonymous ''."""
    frames = [{"seq": 1, "t": 10.0, "counters": {"x_total": 1},
               "gauges": {}, "hist": {}},
              {"seq": 2, "t": 11.0, "counters": {"x_total": 2},
               "gauges": {}, "hist": {}}]
    tl = ta.assemble_timeline([frames])
    assert tl["nodes"] == ["source-0"]
    assert [f["node"] for f in tl["frames"]] == ["source-0"] * 2
    assert tl["skew"] == {"source-0": 0.0}
    assert not tl["violations"]
    assert tl["span"] == [10.0, 11.0]
    # unskewed: adjusted time is the original time
    assert [f["t_adj"] for f in tl["frames"]] == [10.0, 11.0]


def test_timeline_empty_histories():
    """No sources / sources with no frames: an EMPTY timeline, not an
    exception — span None so callers can tell 'nothing' from 't=0'."""
    tl = ta.assemble_timeline([])
    assert tl == {"nodes": [], "frames": [], "events": [], "skew": {},
                  "violations": [], "span": None}
    doc = {"node_id": "ee", "enabled": True, "frames": []}
    tl2 = ta.assemble_timeline([doc, []])
    assert tl2["nodes"] == ["ee", "source-1"]
    assert tl2["frames"] == [] and tl2["span"] is None
    assert not tl2["violations"]
    assert ta.window_series(tl2) == {}


def test_timeline_non_monotonic_dip_reports_once_and_keeps_frames():
    """One backwards time jump past CLOCK_SLACK reports exactly ONE
    violation — the high-water comparison keeps a recovered clock from
    cascading a violation per subsequent frame — and every frame stays
    in the merged timeline (report, don't drop).  Jitter inside
    CLOCK_SLACK is not a violation."""
    def mk(seq, t):
        return {"seq": seq, "t": t, "counters": {"y_total": 1},
                "gauges": {}, "hist": {}}
    doc = {"node_id": "ff",
           "frames": [mk(1, 20.0), mk(2, 19.0), mk(3, 20.5)]}
    tl = ta.assemble_timeline([doc])
    assert len(tl["frames"]) == 3, "violating frames must be retained"
    assert len(tl["violations"]) == 1, tl["violations"]
    assert "before its predecessor" in tl["violations"][0]
    # summed series still counts every retained frame
    assert ta.window_series(tl)["y_total"] == 3
    # scheduling jitter within the slack: clean
    ok = {"node_id": "gg",
          "frames": [mk(1, 20.0), mk(2, 20.0 - ta.CLOCK_SLACK / 2)]}
    assert not ta.assemble_timeline([ok])["violations"]


# ======================================================= live runner glue
def test_runner_history_and_bundle_surfaces():
    """One live node: the recorder ticks on the scheduler, GET-style
    surfaces report frames, and dump_bundle embeds every section."""
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig
    from opendht_tpu.infohash import InfoHash

    cfg = Config(node_id=InfoHash.get("history-live-node"))
    cfg.history.period = 0.05
    cfg.health.period = 0.05
    r = DhtRunner()
    r.run(0, RunnerConfig(dht_config=cfg))
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(r.get_history().get("frames", [])) >= 3:
                break
            time.sleep(0.05)
        doc = r.get_history()
        assert doc["enabled"] and len(doc["frames"]) >= 3
        # at this short period the runner RAISES capacity so the ring
        # still covers the health engine's time-bounded slow window
        # (frame-count bound vs time bound — review finding)
        import math
        assert doc["capacity"] >= math.ceil(
            cfg.health.slow_window * 1.25 / cfg.history.period)
        assert "time" in doc and "mono" in doc
        assert len(r.get_history(limit=2)["frames"]) == 2
        # windowed filter uses the recorder clock
        assert r.get_history(since=0.01)["frames"]
        b = r.dump_bundle()
        assert b["kind"] == history.BUNDLE_KIND
        assert b["history"]["frames"]
        assert b["node_id"] == r.get_node_id().hex()
        for section in ("health", "metrics", "keyspace", "cache",
                        "flight_recorder"):
            assert section in b
        json.loads(json.dumps(b))
        # a lone node IS unhealthy (disconnected): the boot transition
        # unknown -> unhealthy auto-captures a bundle — the hook fires
        # on a live scheduler tick, not just in unit harnesses
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not r.get_bundles():
            time.sleep(0.05)
        bs = r.get_bundles()
        assert bs, "boot transition captured no bundle"
        assert bs[0]["reason"] == "health_transition"
        assert bs[0]["transition"]["to"] == "unhealthy"
        assert r.dump_bundle()["auto_captures"]
    finally:
        r.join()


def test_runner_history_disabled():
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig
    from opendht_tpu.infohash import InfoHash

    cfg = Config(node_id=InfoHash.get("history-off-node"))
    cfg.history.period = 0.0
    r = DhtRunner()
    r.run(0, RunnerConfig(dht_config=cfg))
    try:
        assert r.get_history() == {"enabled": False, "frames": []}
        assert r.get_bundles() == []
        b = r.dump_bundle()                   # bundles still assemble
        assert b["history"]["enabled"] is False
        # the health engine fell back to its private windows
        assert r._health.evaluator.history is None
    finally:
        r.join()
