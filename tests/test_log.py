"""Logger subsystem tests (↔ reference log_enable.h per-hash filter and
sink plumbing)."""

import logging

from opendht_tpu.infohash import InfoHash
from opendht_tpu.log import DhtLogger
import pytest

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(record.getMessage())


def _capturing_logger(name):
    lg = DhtLogger(name)
    cap = _Capture()
    lg._swap_handler(cap)
    return lg, cap


def test_disable_restores_logger_state():
    import logging as _l
    name = "t.restore"
    base = _l.getLogger(name)
    base.setLevel(_l.WARNING)
    lg = DhtLogger(name)
    assert base.level == _l.WARNING          # construction mutates nothing
    lg.set_sink_file("/dev/null")
    assert base.level == _l.DEBUG and not base.propagate
    lg.disable()
    assert base.level == _l.WARNING and base.propagate


def test_streams_reach_sink():
    lg, cap = _capturing_logger("t.streams")
    lg.e("err %d", 1)
    lg.w("warn %s", "x")
    lg.d("dbg")
    assert cap.lines == ["err 1", "warn x", "dbg"]


def test_per_hash_filter():
    lg, cap = _capturing_logger("t.filter")
    h1, h2 = InfoHash.get("one"), InfoHash.get("two")
    lg.set_filter(h1)
    lg.d("about one", h=h1)
    lg.d("about two", h=h2)
    lg.d("untagged")
    assert cap.lines == ["about one"]
    lg.set_filter(None)
    lg.d("untagged 2")
    assert cap.lines == ["about one", "untagged 2"]


def test_file_sink(tmp_path):
    lg = DhtLogger("t.file")
    path = str(tmp_path / "dht.log")
    lg.set_sink_file(path)
    lg.w("to the file")
    lg.disable()
    with open(path) as f:
        content = f.read()
    assert "to the file" in content and "WARN" in content
