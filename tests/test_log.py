"""Logger subsystem tests (↔ reference log_enable.h per-hash filter and
sink plumbing)."""

import logging

from opendht_tpu.infohash import InfoHash
from opendht_tpu.log import DhtLogger
import pytest

pytestmark = pytest.mark.quick  # sub-minute smoke tier: -m quick


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        self.lines.append(record.getMessage())


def _capturing_logger(name):
    lg = DhtLogger(name)
    cap = _Capture()
    lg._swap_handler(cap)
    return lg, cap


def test_disable_restores_logger_state():
    import logging as _l
    name = "t.restore"
    base = _l.getLogger(name)
    base.setLevel(_l.WARNING)
    lg = DhtLogger(name)
    assert base.level == _l.WARNING          # construction mutates nothing
    lg.set_sink_file("/dev/null")
    assert base.level == _l.DEBUG and not base.propagate
    lg.disable()
    assert base.level == _l.WARNING and base.propagate


def test_streams_reach_sink():
    lg, cap = _capturing_logger("t.streams")
    lg.e("err %d", 1)
    lg.w("warn %s", "x")
    lg.d("dbg")
    assert cap.lines == ["err 1", "warn x", "dbg"]


def test_per_hash_filter():
    lg, cap = _capturing_logger("t.filter")
    h1, h2 = InfoHash.get("one"), InfoHash.get("two")
    lg.set_filter(h1)
    lg.d("about one", h=h1)
    lg.d("about two", h=h2)
    lg.d("untagged")
    assert cap.lines == ["about one"]
    lg.set_filter(None)
    lg.d("untagged 2")
    assert cap.lines == ["about one", "untagged 2"]


def test_filter_applies_to_core_runtime_records():
    """ISSUE-3 satellite: ``set_filter`` must govern records emitted by
    the core runtime loggers (children of "opendht_tpu"), exactly as the
    docstring promises — tagged records for the filtered key pass,
    records tagged with another key AND untagged records are suppressed,
    and clearing the filter restores everything."""
    lg, cap = _capturing_logger("opendht_tpu.t_core")
    core = logging.getLogger("opendht_tpu.t_core.dht")   # child module
    h1, h2 = InfoHash.get("one"), InfoHash.get("two")

    lg.set_filter(h1)
    core.warning("[search %s] expired", "one",
                 extra={"dht_hash": bytes(h1)})          # tagged, match
    core.warning("[search %s] expired", "two",
                 extra={"dht_hash": bytes(h2)})          # tagged, other
    core.warning("untagged core record")                 # untagged
    assert cap.lines == ["[search one] expired"]

    lg.set_filter(None)
    core.warning("untagged core record 2")
    assert cap.lines[-1] == "untagged core record 2"


def test_tagged_call_sites_carry_dht_hash():
    """The audited runtime call sites must actually tag their records:
    drive one (_on_error's token flush) through a real Dht and assert
    the record filters by the node id."""
    from opendht_tpu.net.engine import DhtProtocolException
    from opendht_tpu.net.request import Request
    from opendht_tpu.net.parsed_message import MessageType
    from opendht_tpu.net.node import Node
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    from opendht_tpu.sockaddr import SockAddr

    lg, cap = _capturing_logger("opendht_tpu")
    try:
        dht = Dht(lambda d, a: 0, Config(node_id=InfoHash.get("self")),
                  has_v4=True, has_v6=False)
        node_id = InfoHash.get("flushed-peer")
        node = Node(node_id, SockAddr("10.0.0.7", 4007))
        req = Request(MessageType.ANNOUNCE_VALUE, 1, node, b"", None, None)

        lg.set_filter(InfoHash.get("some-other-key"))
        dht._on_error(req, DhtProtocolException(
            DhtProtocolException.UNAUTHORIZED))
        assert cap.lines == []                  # suppressed: other key

        lg.set_filter(node_id)
        req2 = Request(MessageType.ANNOUNCE_VALUE, 2, node, b"", None, None)
        dht._on_error(req2, DhtProtocolException(
            DhtProtocolException.UNAUTHORIZED))
        assert any("token flush" in ln for ln in cap.lines)
    finally:
        lg.disable()


def test_file_sink(tmp_path):
    lg = DhtLogger("t.file")
    path = str(tmp_path / "dht.log")
    lg.set_sink_file(path)
    lg.w("to the file")
    lg.disable()
    with open(path) as f:
        content = f.read()
    assert "to the file" in content and "WARN" in content
