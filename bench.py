"""Headline benchmark — batched findClosestNodes on one chip.

BASELINE.json config 2: Q InfoHash queries × N node ids → exact top-16
XOR-closest, via the expanded-table row-gather lookup
(opendht_tpu/ops/sorted_table.py: expand_table + expanded_topk).  The
baseline is the reference's scalar algorithm — walk a lexicographically
sorted map outward from lower_bound picking the XOR-closer side each
step (NodeCache::getCachedNodes, /root/reference/src/node_cache.cpp:41-74) —
timed in-process on the host CPU over the same table.

Timing methodology (honest-by-construction): the per-batch time is the
*slope* of a device-serialized rep chain — one jitted program runs the
full lookup R times in a lax.while_loop whose trip count is a traced
scalar (one executable serves every R; the dynamic bound rules out
unrolling and cross-rep CSE), each rep's queries perturbed by the
loop index so XLA cannot elide or overlap reps, and the per-batch time
is (t[R2] - t[R1]) / (R2 - R1).  This cancels every constant cost
(dispatch, tunnel round-trip, completion-poll quantum) and counts only
real device execution.  Earlier rounds timed pipelined dispatches and
trusted block_until_ready(), which on a tunneled device returns before
execution completes — that inflated throughput up to ~100×
(BENCH_r01.json's 127M lookups/s/chip was such an artifact; the honest
figure for that same kernel is ~1M).
"""

import bisect
import json
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                          cascade_topk, default_lut_bits,
                                          expand_table, expanded_topk)
from opendht_tpu.ops.xor_topk import xor_topk

K = 16


def scalar_closest(sorted_ints, q, k):
    """Reference algorithm: outward walk from the insertion point,
    XOR-closer side first (node_cache.cpp:41-74)."""
    n = len(sorted_ints)
    i = bisect.bisect_left(sorted_ints, q)
    lo, hi = i - 1, i
    out = []
    while len(out) < k and (lo >= 0 or hi < n):
        if lo < 0:
            out.append(sorted_ints[hi]); hi += 1
        elif hi >= n:
            out.append(sorted_ints[lo]); lo -= 1
        elif (sorted_ints[lo] ^ q) < (sorted_ints[hi] ^ q):
            out.append(sorted_ints[lo]); lo -= 1
        else:
            out.append(sorted_ints[hi]); hi += 1
    return out


def best_of(fn, tries: int = 3):
    """Best wall-clock of ``tries`` calls to ``fn()`` — only valid for
    host-side work (the native baseline) or already-slope-timed chains;
    never for timing raw device dispatches (see module docstring)."""
    best = None
    for _ in range(tries):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


# body function object -> jitted rep chain.  Bounded FIFO: the jitted
# chain g closes over `body`, so a WeakKeyDictionary would never
# collect (value → key strong ref); instead old entries are evicted
# once the cache exceeds the cap, which frees per-call lambdas (e.g. a
# sweep loop creating a fresh body per width) and their executables in
# long-running bench processes.
_CHAIN_CACHE: dict = {}
_CHAIN_CACHE_MAX = 32


def chain_slope(body, example, *consts, r1: int = 2, r2: int = 8,
                tries: int = 3, samples: int = 0):
    """Per-rep device time of ``body`` via the serialized-chain slope:
    jit a dynamic-trip-count rep loop and return
    (t[r2] - t[r1]) / (r2 - r1).  Cancels dispatch, tunnel round-trip,
    and completion-poll constants — see module docstring.

    With ``samples`` > 0, measures that many independent slope samples
    on the SAME compiled chain and returns ``(median, lo, hi)`` —
    the run-to-run range the docs quote (README/PARITY numbers must sit
    inside the captured range; ci/check_docs.py enforces it).

    ``body(x, *consts) -> f32 scalar`` must consume its result into the
    returned scalar; ``example`` is the input batch (uint32 limbs).  The
    input is XORed with the full rep index here, so every rep is a
    distinct computation XLA cannot elide or CSE.

    Pass every large array the body reads (tables, LUTs, …) through
    ``consts`` — closing over a concrete jax.Array embeds it as an HLO
    *constant*, and the remote-compile tunnel then serializes the whole
    table into the compile request (measured: a closed-over 480 MB
    expanded table pushed one compile past 20 minutes; as an argument
    it adds nothing).

    The jitted rep chain is cached per ``body`` IDENTITY: repeated
    calls with the same body function object (e.g. a per-wave latency
    histogram sweeping many same-shape inputs) reuse one executable —
    a fresh inner ``jax.jit`` per call would retrace and recompile
    every time, which on the remote-compile tunnel costs minutes per
    sample.
    """
    g = _CHAIN_CACHE.get(body)
    if g is None:
        @jax.jit
        def g(x, reps, *a):
            def cond(c):
                return c[0] < reps
            def step(c):
                i, acc = c
                return i + 1, acc + body(x ^ i.astype(x.dtype), *a)
            # while_loop with a *traced* trip count: one executable
            # serves every rep count (the second compile would
            # otherwise dominate multi-minute workloads on the
            # remote-compile tunnel), and the dynamic bound forbids
            # unrolling/CSE across reps by construction
            return lax.while_loop(cond, step,
                                  (jnp.int32(0),
                                   jnp.zeros((), jnp.float32)))[1]
        while len(_CHAIN_CACHE) >= _CHAIN_CACHE_MAX:
            _CHAIN_CACHE.pop(next(iter(_CHAIN_CACHE)))
        _CHAIN_CACHE[body] = g

    for attempt in range(3):                      # compile + warm; the
        try:                                      # remote-compile tunnel
            float(g(example, jnp.int32(r2), *consts))   # flakes transiently
            break
        except Exception:
            if attempt == 2:
                raise
            time.sleep(5)
    def timed(reps):
        return best_of(lambda: float(g(example, jnp.int32(reps), *consts)),
                       tries)

    if samples:
        def collect(a, b):
            vals = []
            for _ in range(samples):
                s = (timed(b) - timed(a)) / (b - a)
                if s > 0:
                    vals.append(s)
            return vals

        vals = collect(r1, r2)
        if not vals:
            # widen once (same escape hatch as the scalar path) before
            # failing: noisy hosts can swamp a shallow separation
            vals = collect(4 * r1, 4 * r2)
        if not vals:
            raise RuntimeError("chain_slope: no positive slope sample even "
                               f"at reps {4 * r1}/{4 * r2}; workload below "
                               "noise floor — raise r1/r2")
        vals.sort()
        return vals[len(vals) // 2], vals[0], vals[-1]

    per = (timed(r2) - timed(r1)) / (r2 - r1)
    if per <= 0:
        # jitter swamped the rep separation — widen once, then fail
        # loudly rather than publish a nonsensical number
        per = (timed(4 * r2) - timed(4 * r1)) / (4 * (r2 - r1))
        if per <= 0:
            raise RuntimeError(
                f"chain_slope non-positive ({per!r}) even at reps "
                f"{4 * r1}/{4 * r2}; workload too small for the noise "
                f"floor — raise r1/r2")
    return per


# Headline kernel geometry, selected by the round-3 per-stage profile
# (python bench.py --profile on the v5e; all chain-slope, N=1M Q=131K,
# cascade totals include the on-device stage-2 repair):
#   stride 64 (192-window, pads to 256 lanes in the sort): 23.6 ms
#   stride 42 (126-window, pads to 128 — half the comparator traffic
#              AND half the row-gather bytes): 9.3 ms, stage-1 cert
#              0.99997 (4 repairs/batch)
#   stride 32 (96-window, SAME 128-lane padded sort, smaller gather):
#              cascade 6.97 ms, stage-1 cert 0.9987 (164 repairs ≤ cap)
#   stride 24 (72-window): stage-1 cert 0.974 → 3.4K repairs swamp
#              stage 2; cascade 8.3 ms — past the optimum (recorded
#              negative result)
#   positioning: LUT-only (0 search steps) loses nothing at 20 LUT bits
#              on 1M rows (max bucket ~8 ≪ the window margin) and
#              removes ~2.5 ms of serialized element-gather steps.
# Round 5 (2-plane expansions — expand_table limbs=2 — cut the row
# gather 60% and moved the headline 17.86M → 21.6M) re-swept the
# strides hunting the verdict's ≥25M (benchmarks/exp_headline_r5.py):
#   stride 16 (48-window, 64-lane sorts): stage-1 alone 2.9 ms BUT
#              cert 0.798 at k=16 — 26K misses/batch flood the repair
#              stage, cascade 32.7 ms.  NEGATIVE.
#   stride 24: cert 0.974, cascade 9.3 ms.  NEGATIVE (as in round 3).
#   stride 32: cascade 5.7 ms — still the optimum.  The k=16 result
#              set needs ~full stride-32 margins to certify, so the
#              remaining cost is irreducibly the 128-lane in-window
#              sort + gather; ≥25M was not reached and the measured
#              reason is this certification/sort-width trade.
# The timed kernel is cascade_topk at stride 32 with a 256-row repair
# cap: uncertified rows are selected on device and re-looked-up against
# the wide stride-64 expansion in the same call (a full-scan fallback
# at Q=128 costs 520 ms — the tiled scan serializes ~245 tiny sorts —
# so the cascade is both the honest and the fast design).
HEADLINE_STRIDE = 32
HEADLINE_CAP = 256


def measure(samples: int = 5) -> dict:
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    N = 1_000_000 if on_accel else 100_000
    Q = 131_072 if on_accel else 8_192
    lut_bits = default_lut_bits(N)

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(
        build_prefix_lut(sorted_ids, n_valid, bits=lut_bits))
    # 2-PLANE expansions (round 5): the fast2 sort + clamped certificate
    # consume limb planes 0-1 only, so the gathered row carries 2 planes
    # instead of 5 — 60% off the dominant row-gather traffic,
    # bit-identical results (tests/test_topk.py pins it)
    exp_fast = jax.block_until_ready(
        expand_table(sorted_ids, stride=HEADLINE_STRIDE, limbs=2))
    exp_wide = jax.block_until_ready(expand_table(sorted_ids, limbs=2))

    def lookup(q, sorted_ids, exp_fast, exp_wide, n_valid, lut):
        # fast2 = the findClosestNodes contract (nodes, not distances):
        # the sort carries 4 operands instead of 7 (sort cost is linear
        # in operand count); cascade_topk includes the on-device repair
        # of the ~164/131K rows the stride-32 window fails to certify
        # (HEADLINE_CAP bounds the repair batch)
        d, idx, c = cascade_topk(sorted_ids, exp_fast, exp_wide, n_valid,
                                 q, lut, k=K, select="fast2",
                                 cap=HEADLINE_CAP, planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    if not on_accel:               # CI smoke: shallow chain, fewer samples
        samples = min(samples, 2)
    r1, r2 = (8, 64) if on_accel else (2, 8)
    per_batch, dt_lo, dt_hi = chain_slope(
        lookup, queries, sorted_ids, exp_fast, exp_wide, n_valid, lut,
        r1=r1, r2=r2, samples=samples)
    rate = Q / per_batch

    # certificate fraction: stage 1 alone, and after the cascade (the
    # timed path); any residual uncertified row would go to the host
    # exact fallback — count it honestly
    _, _, cert1 = jax.block_until_ready(
        expanded_topk(sorted_ids, exp_fast, n_valid, queries, k=K,
                      select="fast2", lut=lut, lut_steps=0, planes=2))
    _, i2, cert = jax.block_until_ready(
        cascade_topk(sorted_ids, exp_fast, exp_wide, n_valid, queries,
                     lut, k=K, select="fast2", cap=HEADLINE_CAP, planes=2))
    cert_np = np.asarray(cert)
    cert_frac = float(cert_np.mean())
    stage2_rows = int((~np.asarray(cert1)).sum())
    n_uncert = int((~cert_np).sum())

    # exactness vs the full-scan oracle: the timed cascade must return
    # the oracle's node order on every certified row (residual
    # uncertified rows go to lookup_topk's host fallback — none occur on
    # uniform tables), and the fuller fast3 path the distances too
    # (fast3 needs all 5 planes — built transiently for the check only)
    exp_fast5 = expand_table(sorted_ids, stride=HEADLINE_STRIDE)
    d3, i3, _ = jax.block_until_ready(
        expanded_topk(sorted_ids, exp_fast5, n_valid, queries[:256], k=K,
                      lut=lut, lut_steps=0))
    del exp_fast5
    d_ref, i_ref = xor_topk(queries[:256], sorted_ids, k=K,
                            valid=jnp.arange(N) < n_valid)
    c256 = cert_np[:256]
    exact = bool(np.array_equal(np.asarray(i2[:256])[c256],
                                np.asarray(i_ref)[c256])
                 and np.array_equal(np.asarray(i3), np.asarray(i_ref))
                 and np.array_equal(np.asarray(d3), np.asarray(d_ref)))
    if stage2_rows:
        # the cascade-repaired rows specifically must match the oracle
        bad_rows = np.nonzero(~np.asarray(cert1))[0]
        _, i_bad = xor_topk(queries[bad_rows], sorted_ids, k=K,
                            valid=jnp.arange(N) < n_valid)
        exact = exact and bool(np.array_equal(
            np.asarray(i2)[bad_rows][cert_np[bad_rows]],
            np.asarray(i_bad)[cert_np[bad_rows]]))

    # scalar CPU baseline on the same sorted table
    def pack160(rows):
        """uint32[...,5] limb rows (big-endian limb order) → python ints."""
        return [
            (int(r[0]) << 128) | (int(r[1]) << 96) | (int(r[2]) << 64)
            | (int(r[3]) << 32) | int(r[4])
            for r in np.asarray(rows)
        ]

    sorted_ints = pack160(sorted_ids)
    q_ints = pack160(queries[:64])
    t0 = time.perf_counter()
    for q in q_ints:
        scalar_closest(sorted_ints, q, K)
    scalar_rate = len(q_ints) / (time.perf_counter() - t0)

    out = {
        "metric": f"batched findClosestNodes top-{K}, {Q} queries x {N} ids "
                  f"({platform}); two-stage cascade, device-serialized "
                  f"chain slope (median of {samples}), "
                  f"{per_batch * 1e3:.1f} ms/batch incl. on-device repair "
                  f"of {stage2_rows} rows, certified {cert_frac:.5f}, "
                  f"exact={exact}",
        "value": round(rate, 1),
        "unit": "lookups/s/chip",
        "vs_baseline": round(rate / scalar_rate, 2),
    }
    # full capture (value + run-to-run range) for the docs: README/PARITY
    # quote this file verbatim and ci/check_docs.py enforces agreement
    capture = dict(out)
    capture.update({
        "ms_per_batch": round(per_batch * 1e3, 2),
        "ms_range": [round(dt_lo * 1e3, 2), round(dt_hi * 1e3, 2)],
        "rate_range": [round(Q / dt_hi, 1), round(Q / dt_lo, 1)],
        "certified": cert_frac,
        "stage2_rows": stage2_rows,
        "residual_uncertified": n_uncert,
        "stride": HEADLINE_STRIDE,
        "planes": 2,
        "lut_bits": lut_bits,
        "N": N, "Q": Q, "k": K,
    })
    try:
        if on_accel:
            with open(os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "bench_capture.json"),
                    "w") as f:
                json.dump(capture, f, indent=1)
    except OSError:
        pass
    return out


def profile(N: int = None, Q: int = None) -> list:
    """Per-stage chain-slope breakdown of the headline lookup kernel,
    plus candidate variants (window stride, positioning depth).  Each
    stage is timed as its own device-serialized rep chain; stage deltas
    locate the wall-clock (positioning / row gather / in-window select /
    certificate).  Prints one JSON line per measurement.
    """
    from opendht_tpu.ops.sorted_table import _lower_bound

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    N = N or (1_000_000 if on_accel else 100_000)
    Q = Q or (131_072 if on_accel else 8_192)
    lut_bits = default_lut_bits(N)

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(
        build_prefix_lut(sorted_ids, n_valid, bits=lut_bits))
    # 2-plane expansions — the shipped headline geometry (round 5)
    exp64 = jax.block_until_ready(expand_table(sorted_ids, limbs=2))
    exp32 = jax.block_until_ready(
        expand_table(sorted_ids, stride=32, limbs=2))
    exp32_5 = jax.block_until_ready(expand_table(sorted_ids, stride=32))

    out = []

    def stage(name, body, *consts, r1=2, r2=8):
        dt = chain_slope(body, queries, *consts, r1=r1, r2=r2)
        rec = {"stage": name, "ms_per_batch": round(dt * 1e3, 3),
               "lookups_per_s": round(Q / dt, 1)}
        print(json.dumps(rec), flush=True)
        out.append(rec)
        return dt

    def pos_body(steps):
        def body(q, sorted_ids, n_valid, lut):
            p = _lower_bound(sorted_ids, q, n_valid, lut=lut,
                             lut_steps=steps)
            return jnp.sum(p.astype(jnp.float32))
        return body

    stage("pos lut%d steps=6" % lut_bits, pos_body(6),
          sorted_ids, n_valid, lut)
    stage("pos lut%d steps=0" % lut_bits, pos_body(0),
          sorted_ids, n_valid, lut)

    def gather_body(stride):
        def body(q, sorted_ids, n_valid, lut, expanded):
            p = _lower_bound(sorted_ids, q, n_valid, lut=lut, lut_steps=0)
            NB = expanded.shape[0]
            j = jnp.clip((p - stride) // stride, 0, NB - 1)
            rows = jnp.take(expanded, j, axis=0)
            return jnp.sum(rows, dtype=jnp.uint32).astype(jnp.float32)
        return body

    stage("pos0 + row gather s=64", gather_body(64),
          sorted_ids, n_valid, lut, exp64)
    stage("pos0 + row gather s=32", gather_body(32),
          sorted_ids, n_valid, lut, exp32)

    def full_body(select, steps, planes):
        def body(q, sorted_ids, expanded, n_valid, lut):
            d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                      select=select, lut=lut,
                                      lut_steps=steps, planes=planes)
            return (jnp.sum(c.astype(jnp.float32))
                    + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)
        return body

    for name, expd, steps, select, planes in [
        ("full fast2 s=64 steps=0 planes=2", exp64, 0, "fast2", 2),
        ("full fast2 s=32 steps=6 planes=2", exp32, 6, "fast2", 2),
        ("full fast2 s=32 steps=0 planes=2", exp32, 0, "fast2", 2),
        ("full fast2 s=32 steps=0 planes=5 (pre-r5)", exp32_5, 0,
         "fast2", 5),
        ("full fast3 s=32 steps=0", exp32_5, 0, "fast3", 5),
    ]:
        stage(name, full_body(select, steps, planes), sorted_ids, expd,
              n_valid, lut)
        _, _, c = jax.block_until_ready(
            expanded_topk(sorted_ids, expd, n_valid, queries, k=K,
                          select=select, lut=lut, lut_steps=steps,
                          planes=planes))
        rec = {"stage": "certified fraction", "value":
               float(np.asarray(c).mean())}
        print(json.dumps(rec), flush=True)
        out.append(rec)

    # the full headline pipeline (stage-1 fast path + on-device repair)
    def casc_body(q, sorted_ids, e32, e64, n_valid, lut):
        d, idx, c = cascade_topk(sorted_ids, e32, e64, n_valid, q, lut,
                                 k=K, select="fast2", cap=HEADLINE_CAP,
                                 planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    r1c, r2c = (8, 64) if on_accel else (2, 8)
    stage("cascade s=32 cap=%d (headline)" % HEADLINE_CAP, casc_body,
          sorted_ids, exp32, exp64, n_valid, lut, r1=r1c, r2=r2c)
    return out


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--profile", action="store_true",
                   help="per-stage kernel breakdown instead of the headline")
    p.add_argument("-N", type=int, default=0)
    p.add_argument("-Q", type=int, default=0)
    args = p.parse_args(argv)
    if args.profile:
        profile(args.N or None, args.Q or None)
    else:
        print(json.dumps(measure()))


if __name__ == "__main__":
    main()
