"""Headline benchmark — batched findClosestNodes on one chip.

BASELINE.json config 2: Q InfoHash queries × N node ids → exact top-16
XOR-closest, via the expanded-table row-gather lookup
(opendht_tpu/ops/sorted_table.py: expand_table + expanded_topk).  The
baseline is the reference's scalar algorithm — walk a lexicographically
sorted map outward from lower_bound picking the XOR-closer side each
step (NodeCache::getCachedNodes, /root/reference/src/node_cache.cpp:41-74) —
timed in-process on the host CPU over the same table.

Timing methodology (honest-by-construction): the per-batch time is the
*slope* of a device-serialized rep chain — one jitted program runs the
full lookup R times in a lax.while_loop whose trip count is a traced
scalar (one executable serves every R; the dynamic bound rules out
unrolling and cross-rep CSE), each rep's queries perturbed by the
loop index so XLA cannot elide or overlap reps, and the per-batch time
is (t[R2] - t[R1]) / (R2 - R1).  This cancels every constant cost
(dispatch, tunnel round-trip, completion-poll quantum) and counts only
real device execution.  Earlier rounds timed pipelined dispatches and
trusted block_until_ready(), which on a tunneled device returns before
execution completes — that inflated throughput up to ~100×
(BENCH_r01.json's 127M lookups/s/chip was such an artifact; the honest
figure for that same kernel is ~1M).
"""

import bisect
import json
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                          default_lut_bits, expand_table,
                                          expanded_topk)
from opendht_tpu.ops.xor_topk import xor_topk

K = 16


def scalar_closest(sorted_ints, q, k):
    """Reference algorithm: outward walk from the insertion point,
    XOR-closer side first (node_cache.cpp:41-74)."""
    n = len(sorted_ints)
    i = bisect.bisect_left(sorted_ints, q)
    lo, hi = i - 1, i
    out = []
    while len(out) < k and (lo >= 0 or hi < n):
        if lo < 0:
            out.append(sorted_ints[hi]); hi += 1
        elif hi >= n:
            out.append(sorted_ints[lo]); lo -= 1
        elif (sorted_ints[lo] ^ q) < (sorted_ints[hi] ^ q):
            out.append(sorted_ints[lo]); lo -= 1
        else:
            out.append(sorted_ints[hi]); hi += 1
    return out


def best_of(fn, tries: int = 3):
    """Best wall-clock of ``tries`` calls to ``fn()`` — only valid for
    host-side work (the native baseline) or already-slope-timed chains;
    never for timing raw device dispatches (see module docstring)."""
    best = None
    for _ in range(tries):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def chain_slope(body, example, *consts, r1: int = 2, r2: int = 8,
                tries: int = 3):
    """Per-rep device time of ``body`` via the serialized-chain slope:
    jit a dynamic-trip-count rep loop and return
    (t[r2] - t[r1]) / (r2 - r1).  Cancels dispatch, tunnel round-trip,
    and completion-poll constants — see module docstring.

    ``body(x, *consts) -> f32 scalar`` must consume its result into the
    returned scalar; ``example`` is the input batch (uint32 limbs).  The
    input is XORed with the full rep index here, so every rep is a
    distinct computation XLA cannot elide or CSE.

    Pass every large array the body reads (tables, LUTs, …) through
    ``consts`` — closing over a concrete jax.Array embeds it as an HLO
    *constant*, and the remote-compile tunnel then serializes the whole
    table into the compile request (measured: a closed-over 480 MB
    expanded table pushed one compile past 20 minutes; as an argument
    it adds nothing).
    """
    @jax.jit
    def g(x, reps, *a):
        def cond(c):
            return c[0] < reps
        def step(c):
            i, acc = c
            return i + 1, acc + body(x ^ i.astype(x.dtype), *a)
        # while_loop with a *traced* trip count: one executable serves
        # every rep count (the second compile would otherwise dominate
        # multi-minute workloads on the remote-compile tunnel), and the
        # dynamic bound forbids unrolling/CSE across reps by construction
        return lax.while_loop(cond, step,
                              (jnp.int32(0), jnp.zeros((), jnp.float32)))[1]

    for attempt in range(3):                      # compile + warm; the
        try:                                      # remote-compile tunnel
            float(g(example, jnp.int32(r2), *consts))   # flakes transiently
            break
        except Exception:
            if attempt == 2:
                raise
            time.sleep(5)
    def timed(reps):
        return best_of(lambda: float(g(example, jnp.int32(reps), *consts)),
                       tries)

    per = (timed(r2) - timed(r1)) / (r2 - r1)
    if per <= 0:
        # jitter swamped the rep separation — widen once, then fail
        # loudly rather than publish a nonsensical number
        per = (timed(4 * r2) - timed(4 * r1)) / (4 * (r2 - r1))
        if per <= 0:
            raise RuntimeError(
                f"chain_slope non-positive ({per!r}) even at reps "
                f"{4 * r1}/{4 * r2}; workload too small for the noise "
                f"floor — raise r1/r2")
    return per


def measure() -> dict:
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    N = 1_000_000 if on_accel else 100_000
    Q = 131_072 if on_accel else 8_192
    lut_bits = default_lut_bits(N)

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(
        build_prefix_lut(sorted_ids, n_valid, bits=lut_bits))
    expanded = jax.block_until_ready(expand_table(sorted_ids))

    def lookup(q, sorted_ids, expanded, n_valid, lut):
        # fast2 = the findClosestNodes contract (nodes, not distances):
        # the sort carries 4 operands instead of 7 (sort cost is linear
        # in operand count), with a conservative certificate
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    per_batch = chain_slope(lookup, queries, sorted_ids, expanded, n_valid,
                            lut)
    rate = Q / per_batch

    # exactness + certificate fraction vs the full-scan oracle: the timed
    # fast2 path must return the oracle's node set/order, and the fuller
    # fast3 path the oracle's distances too
    _, i2, cert = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, queries, k=K,
                      select="fast2", lut=lut))
    cert_frac = float(np.asarray(cert).mean())
    d3, i3, _ = jax.block_until_ready(
        expanded_topk(sorted_ids, expanded, n_valid, queries[:256], k=K,
                      lut=lut))
    d_ref, i_ref = xor_topk(queries[:256], sorted_ids, k=K,
                            valid=jnp.arange(N) < n_valid)
    # fast2 rows are only exact where certified (uncertified rows are
    # repaired by lookup_topk's fallback — that is the stated contract);
    # comparing uncertified rows here would flag a spurious inexactness
    c256 = np.asarray(cert[:256])
    exact = bool(np.array_equal(np.asarray(i2[:256])[c256],
                                np.asarray(i_ref)[c256])
                 and np.array_equal(np.asarray(i3), np.asarray(i_ref))
                 and np.array_equal(np.asarray(d3), np.asarray(d_ref)))

    # scalar CPU baseline on the same sorted table
    def pack160(rows):
        """uint32[...,5] limb rows (big-endian limb order) → python ints."""
        return [
            (int(r[0]) << 128) | (int(r[1]) << 96) | (int(r[2]) << 64)
            | (int(r[3]) << 32) | int(r[4])
            for r in np.asarray(rows)
        ]

    sorted_ints = pack160(sorted_ids)
    q_ints = pack160(queries[:64])
    t0 = time.perf_counter()
    for q in q_ints:
        scalar_closest(sorted_ints, q, K)
    scalar_rate = len(q_ints) / (time.perf_counter() - t0)

    return {
        "metric": f"batched findClosestNodes top-{K}, {Q} queries x {N} ids "
                  f"({platform}); device-serialized chain slope, "
                  f"{per_batch * 1e3:.1f} ms/batch, certified "
                  f"{cert_frac:.4f}, exact={exact}",
        "value": round(rate, 1),
        "unit": "lookups/s/chip",
        "vs_baseline": round(rate / scalar_rate, 2),
    }


def main():
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
