"""Headline benchmark — batched findClosestNodes on one chip.

BASELINE.json config 2: Q InfoHash queries × N node ids → exact top-16
XOR-closest, via the sorted-table window kernel
(opendht_tpu/ops/sorted_table.py).  The baseline is the reference's
scalar algorithm — walk a lexicographically sorted map outward from
lower_bound picking the XOR-closer side each step
(NodeCache::getCachedNodes, /root/reference/src/node_cache.cpp:41-74) —
timed in-process on the host CPU over the same table.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import bisect
import json
import time

import numpy as np
import jax
import jax.numpy as jnp

from opendht_tpu.ops.sorted_table import sort_table, window_topk
from opendht_tpu.ops.xor_topk import xor_topk

K = 16
WINDOW = 256


def scalar_closest(sorted_ints, q, k):
    """Reference algorithm: outward walk from the insertion point,
    XOR-closer side first (node_cache.cpp:41-74)."""
    n = len(sorted_ints)
    i = bisect.bisect_left(sorted_ints, q)
    lo, hi = i - 1, i
    out = []
    while len(out) < k and (lo >= 0 or hi < n):
        if lo < 0:
            out.append(sorted_ints[hi]); hi += 1
        elif hi >= n:
            out.append(sorted_ints[lo]); lo -= 1
        elif (sorted_ints[lo] ^ q) < (sorted_ints[hi] ^ q):
            out.append(sorted_ints[lo]); lo -= 1
        else:
            out.append(sorted_ints[hi]); hi += 1
    return out


def main():
    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)
    N = 1_000_000 if on_accel else 100_000
    Q = 131_072 if on_accel else 8_192
    CHUNK = 16_384 if on_accel else 4_096

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(sort_table(table))

    def run_all():
        outs = []
        for s in range(0, Q, CHUNK):
            d, idx, cert = window_topk(sorted_ids, n_valid,
                                       queries[s:s + CHUNK], k=K, window=WINDOW)
            outs.append((d, idx, cert))
        return jax.block_until_ready(outs)

    # the device path (and the axon tunnel in particular) warms up over
    # the first dispatches and throughput drifts in phases over minutes;
    # warm thoroughly, run a longer rep train, and report the MEDIAN as
    # the headline (reproducible run-to-run) with best alongside —
    # round-1 reported best-of-10 and drifted ~15% vs the driver capture
    for _ in range(5):
        outs = run_all()           # compile + warm
    rates = []
    for _ in range(16):
        t0 = time.perf_counter()
        outs = run_all()
        dt = time.perf_counter() - t0
        rates.append(Q / dt)
    rate = float(np.median(rates))
    best = max(rates)

    cert_frac = float(np.mean([np.asarray(c).mean() for _, _, c in outs]))

    # exactness spot-check vs the full-scan oracle
    d_ref, i_ref = xor_topk(queries[:256], sorted_ids, k=K,
                            valid=jnp.arange(N) < n_valid)
    d_win = outs[0][0][:256]
    exact = bool(np.array_equal(np.asarray(d_win), np.asarray(d_ref)))

    # scalar CPU baseline on the same sorted table
    def pack160(rows):
        """uint32[...,5] limb rows (big-endian limb order) → python ints."""
        return [
            (int(r[0]) << 128) | (int(r[1]) << 96) | (int(r[2]) << 64)
            | (int(r[3]) << 32) | int(r[4])
            for r in np.asarray(rows)
        ]

    sorted_ints = pack160(sorted_ids)
    q_ints = pack160(queries[:64])
    t0 = time.perf_counter()
    for q in q_ints:
        scalar_closest(sorted_ints, q, K)
    scalar_rate = len(q_ints) / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": f"batched findClosestNodes top-{K}, {Q} queries x {N} ids "
                  f"({platform}); median of 16 (best {round(best, 1)}), "
                  f"certified {cert_frac:.4f}, exact={exact}",
        "value": round(rate, 1),
        "unit": "lookups/s/chip",
        "vs_baseline": round(rate / scalar_rate, 2),
    }))


if __name__ == "__main__":
    main()
