"""Wave double-buffering experiment (ask 1 follow-on).

The round body is a serial chain of issue-bound gathers feeding
VPU-bound sorts; advancing two INDEPENDENT half-waves inside ONE loop
body gives XLA freedom to overlap one wave's gathers with the other's
sorts (two separate while-ops would serialize).  Measures a fixed
10-round loop at width 2W vs the same loop advancing two W-wide
states, equal total work.

NEGATIVE RESULT (v5e, N=10M, 2W=65536, measured 2026-08-01): single
148.3 ms vs pair 157.1 ms — XLA's static TPU schedule serializes the
two independent streams rather than overlapping gather with sort, and
the split only loses batch efficiency.  Double-buffering waves is not
a lever on this hardware; recorded so it isn't retried.

The round body below is a deliberate FROZEN COPY of the engine state
machine as measured — do not sync it with later core/search.py
changes; the recorded numbers correspond to exactly this body (same
policy as exp_round_r5.py).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.ids import N_LIMBS, clz32
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)
    from opendht_tpu.core import search as SE

    _U32 = jnp.uint32
    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 100_000
    W = 32_768 if on_accel else 512            # half width (single = 2W)
    NL, ALPHA, S, K = 2, 3, 14, 8
    R = ALPHA * K
    ROUNDS = 10

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (2 * W, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    n = jnp.asarray(n_valid, jnp.int32)

    def make_wave(halves):
        def wave(targets, sorted_ids, lut):
            sorted_t = sorted_ids.T

            def gather_planar(rows, limbs=N_LIMBS):
                cl = jnp.clip(rows, 0, N - 1).reshape(-1)
                g = jnp.take(sorted_t[:limbs], cl, axis=1)
                return [g[l].reshape(rows.shape) for l in range(limbs)]

            def block_bounds(t0, L):
                return SE._lut_block_bounds(lut, t0, L)

            def reply_gather(tgt, qidx, x_rows, round_no, seed_u):
                x0 = gather_planar(x_rows, 1)[0]
                b = clz32(x0 ^ tgt[:, 0:1])
                lo, ub = block_bounds(tgt[:, 0:1], b + 1)
                size = jnp.maximum(ub - lo, 0)
                qi = qidx.astype(_U32)[:, None, None]
                ai = jnp.arange(ALPHA, dtype=_U32)[None, :, None]
                ji = jnp.arange(K, dtype=_U32)[None, None, :]
                ctr = (((round_no.astype(_U32) * _U32(tgt.shape[0]) + qi)
                        * _U32(ALPHA) + ai) * _U32(K) + ji) ^ seed_u
                h = SE._mix32(ctr)
                blk = lo[..., None] + (
                    h % jnp.maximum(size[..., None], 1).astype(_U32)
                ).astype(jnp.int32)
                rows = jnp.where((size[..., None] >= K), blk, 0)
                rows = jnp.where((x_rows >= 0)[..., None], rows, -1)
                return rows.reshape(tgt.shape[0], R)

            def merge(tgt, cand_node, cand_l, queried, new_rows):
                Wd = tgt.shape[0]
                new_l = gather_planar(new_rows, NL)
                node = jnp.concatenate([cand_node, new_rows], axis=1)
                d_l = [jnp.concatenate(
                    [cand_l[l], new_l[l] ^ tgt[:, l:l + 1]], axis=1)
                    for l in range(NL)]
                qd = jnp.concatenate(
                    [queried, jnp.zeros((Wd, R), jnp.int32)], axis=1)
                inv = (node < 0).astype(jnp.int32)
                big = jnp.uint32(0xFFFFFFFF)
                d_l = [jnp.where(inv == 0, dl, big) for dl in d_l]
                out = lax.sort((inv,) + tuple(d_l) + (node, 1 - qd),
                               dimension=1, num_keys=3 + NL)
                node_s = out[1 + NL]
                dup = jnp.concatenate(
                    [jnp.zeros((Wd, 1), bool),
                     (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)],
                    axis=1)
                inv2 = jnp.where(dup, 1, out[0])
                out2 = lax.sort(
                    (inv2,) + tuple(out[1:1 + NL]) + (node_s, out[2 + NL]),
                    dimension=1, num_keys=2 + NL)
                present = out2[0][:, :S] == 0
                node_f = jnp.where(present, out2[1 + NL][:, :S], -1)
                d_f = [jnp.where(present, out2[1 + l][:, :S], big)
                       for l in range(NL)]
                qd_f = (1 - out2[2 + NL])[:, :S] * present
                return node_f, d_f, qd_f

            def init_state(tgt, seed_u):
                Q = tgt.shape[0]
                qidx = jnp.arange(Q, dtype=jnp.int32)
                boot = jnp.full((Q, ALPHA), -1, jnp.int32).at[:, 0].set(
                    (SE._mix32(qidx.astype(_U32) ^ seed_u)
                     % jnp.maximum(n, 1).astype(_U32)).astype(jnp.int32))
                cand = jnp.full((Q, S), -1, jnp.int32)
                cl = [jnp.full((Q, S), 0xFFFFFFFF, _U32) for _ in range(NL)]
                qd = jnp.zeros((Q, S), jnp.int32)
                first = reply_gather(tgt, qidx, boot, jnp.int32(0), seed_u)
                return merge(tgt, cand, cl, qd, first) + (qidx, seed_u)

            def advance(tgt, st, rnd):
                cand, cl, qd, qidx, seed_u = st
                can = (cand >= 0) & (qd == 0)
                rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
                sel = can & (rank <= ALPHA)
                x_rows = jnp.stack(
                    [jnp.max(jnp.where(sel & (rank == j + 1), cand, -1),
                             axis=1) for j in range(ALPHA)], axis=1)
                new_rows = reply_gather(tgt, qidx, x_rows, rnd + 1, seed_u)
                qd = jnp.where(sel, 1, qd)
                cand, cl, qd = merge(tgt, cand, cl, qd, new_rows)
                return (cand, cl, qd, qidx, seed_u)

            if halves == 1:
                st = init_state(targets, _U32(1))

                def body(rnd, st):
                    return advance(targets, st, rnd)

                st = lax.fori_loop(0, ROUNDS, body, st)
                return jnp.sum(st[0][:, :K].astype(jnp.float32)) * 1e-9
            ta, tb = targets[:W], targets[W:]
            sa = init_state(ta, _U32(1))
            sb = init_state(tb, _U32(2))

            def body(rnd, st):
                sa, sb = st
                return (advance(ta, sa, rnd), advance(tb, sb, rnd))

            sa, sb = lax.fori_loop(0, ROUNDS, body, (sa, sb))
            return (jnp.sum(sa[0][:, :K].astype(jnp.float32))
                    + jnp.sum(sb[0][:, :K].astype(jnp.float32))) * 1e-9
        return wave

    for name, halves in (("single 2W=%d" % (2 * W), 1),
                         ("pair 2x W=%d one loop" % W, 2)):
        dt = chain_slope(make_wave(halves), targets, sorted_ids, lut,
                         r1=1, r2=4)
        print(json.dumps({"stage": name, "ms": round(dt * 1e3, 2),
                          "per_round_ms": round(dt * 1e3 / ROUNDS, 2),
                          "lookups_per_s": round(2 * W / dt, 1)}),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
