"""Per-peer ledger on-cost on the 8192-wave search round (round 23).

The round-23 acceptance gate: with the per-peer observatory ledgering
a full synthetic request-lifecycle stream — per wave, 256 request
lifecycles (send / receive / complete-with-RTT-sample) spread over 32
peers, every one driving the Jacobson/Karels estimator, the status
refresh and the gauge writes — the 8192-wave iterative-search round
must cost < 1% over the ledger-disabled run.  Every hook is host-side
O(1) dict/float arithmetic under one lock and the ledger never
composes packets or touches the device, so the expectation is
noise-level.  Measured with the shared paired-delta estimator
(``driver_common.paired_delta``) and committed as
``captures/peers_overhead.json``.

The driver also pins the wave outputs bit-identical between a
ledger-on trip and a ledger-off trip (the "wire bytes and kernels stay
bit-identical with the ledger enabled" acceptance line — the ledger is
pure observation on the send/receive path), and asserts the timed
trips left a coherent ledger (every peer tracked, every clean sample
counted, srtt converged onto the fed RTT band).

Usage::

    python benchmarks/exp_peers_r23.py --save      # writes capture
    python benchmarks/exp_peers_r23.py --smoke     # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from types import SimpleNamespace

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

N_PEERS = 32
LIFECYCLES_PER_WAVE = 256


class _Peer:
    """Duck-typed net.Node stand-in: the ledger reads id/addr and the
    liveness pair (expired / is_good)."""

    __slots__ = ("id", "addr", "expired")

    def __init__(self, i: int):
        self.id = "benchpeer%04d" % i
        self.addr = "10.0.0.%d:4222" % (i + 1)
        self.expired = False

    def is_good(self, now: float) -> bool:
        return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    dc.add_paired_delta_args(p)
    p.add_argument("--save", action="store_true",
                   help="write captures/peers_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert ledger overhead < 5%% (generous CI band; "
                        "the committed capture documents the tight "
                        "number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)
    from opendht_tpu.peers import PeerLedger, PeersConfig

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(23)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()
    reg.enabled = True                      # telemetry ON in both modes
    led = {"on": PeerLedger(PeersConfig(enabled=True), node="bench",
                            clock=time.time, registry=reg),
           "off": PeerLedger(PeersConfig(enabled=False), node="bench",
                             clock=time.time, registry=reg)}
    peers = [_Peer(i) for i in range(N_PEERS)]
    reqs = [SimpleNamespace(node=peers[i % N_PEERS],
                            type=SimpleNamespace(value="get"),
                            msg=b"x" * 120, attempt_count=1)
            for i in range(LIFECYCLES_PER_WAVE)]

    def trip(mode: str) -> float:
        # the per-request seam sequence the engine fires
        # (_send_request / _process / set_done), around the same kernel
        ledger = led[mode]
        t0 = time.perf_counter()
        for i, req in enumerate(reqs):
            ledger.on_send(req.node, "get", 120)
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        for i, req in enumerate(reqs):
            ledger.on_received(req.node, "reply", 160)
            # a deterministic 2-6 ms RTT band: every completion drives
            # the RFC 6298 estimator + histogram + gauge writes
            ledger.on_request_completed(req, 0.002 + (i % 32) * 0.000125)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    # bit-identity: a ledger-on trip and a ledger-off trip return the
    # same arrays (the ledger is pure observation — it never composes
    # packets or touches the device)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    trip("on")
    profiled = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(profiled)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the peer ledger enabled"
    del base, profiled

    pd = dc.paired_delta(trip, args.reps, modes=("off", "on"))

    # ledger sanity: the timed "on" trips were tracked end to end
    snap = led["on"].snapshot()
    assert snap["tracked"] == N_PEERS, snap["tracked"]
    per_peer = LIFECYCLES_PER_WAVE // N_PEERS
    row = snap["peers"][0]
    assert row["samples"] >= per_peer * args.reps, row
    assert 0.002 <= row["srtt"] <= 0.006, \
        "srtt failed to converge onto the fed band: %r" % row["srtt"]
    assert led["off"].snapshot()["tracked"] == 0, \
        "disabled ledger tracked peers"

    rec_doc = {
        "name": "peers_overhead",
        "value": round(pd["on_pct"], 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_on": round(pd["med_ms"]["on"], 3),
        "wave_ms_off": round(pd["med_ms"]["off"], 3),
        "peers": N_PEERS,
        "lifecycles_per_wave": LIFECYCLES_PER_WAVE,
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips "
                "(driver_common.paired_delta): 256 request lifecycles "
                "per wave over 32 peers (send/receive/complete, every "
                "completion a clean Karn sample driving the RFC 6298 "
                "estimator + per-peer histogram + gauge writes) vs the "
                "ledger disabled; same executable, telemetry on in "
                "both modes; wave outputs pinned bit-identical",
    }
    dc.emit(rec_doc)

    if args.save:
        dc.write_capture("peers_overhead", rec_doc)

    if args.smoke and pd["on_pct"] >= 5.0:
        print("peer-ledger overhead %.2f%% exceeds the 5%% smoke band"
              % pd["on_pct"], file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
