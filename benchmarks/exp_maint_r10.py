"""Batched maintenance sweep attribution + the CI maintenance smoke
(round 10 tentpole, BASELINE config 4's workload).

Before round 10 the maintenance path was the last scalar hot path:
``Dht::bucketMaintenance`` parity re-derived staleness per bucket and
sampled refresh targets in separate launches, and ``dataPersistence``
parity paid one single-target ``find_closest_nodes`` launch — a batch
of 1 through the full 128-lane padding tax — plus one scheduler heap
entry PER STORED KEY.  Round 10 fuses the table sweep into one device
pass (``ops/radix.maintenance_sweep``) and bins due keys into calendar
buckets that republish through ONE batched closest-k resolve
(``runtime/dht.py _storage_maintenance_batched``).

Two modes:

``--smoke`` (the CI entry): boots a 3-node real-UDP cluster, pins the
fused sweep bit-identical to the host stale set on the LIVE routing
table, forces a bucket-maintenance pass (ages every reply clock past
the 10-min rule) and a due republish, then asserts the
``dht_maintenance_*`` counters advanced and the refresh find_nodes
actually hit the wire (``dht_net_requests_sent_total{type="find"}``).

Full mode: CPU full-vs-per-key attribution on the config-4 shape —

  sweep_fused        ONE maintenance_sweep launch over the [N,5] ids
  sweep_split        the same statistics as three separate launches
                     (counts + last_seen + targets — the pre-fusion
                     device form)
  sweep_host_ms      the deleted host ``np.maximum.at`` staleness
                     reduction, wall-timed (host code — wall clock is
                     honest here, unlike device dispatches)
  republish_batched  closest-8 + the still-responsible predicate for
                     ALL K due keys in one lookup_topk call
  republish_per_key  the batch-1 launch the scalar path paid, slope-
                     measured and extrapolated ×K (stated as such in
                     the capture)

``--capture maint_sweep`` writes captures/maint_sweep.json.  The
config-4 accelerator number (10M-id sweep + 100K-key republish
planning) is OPEN until an accelerator session runs:

  python benchmarks/exp_maint_r10.py --capture maint_sweep
  python benchmarks/baseline_configs.py -c 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def _on_node(runner, fn, timeout=20.0):
    """Run ``fn(dht_core)`` on the runner's node thread and return its
    result — table/storage mutations must not race the packet loop."""
    done = threading.Event()
    box = {}

    def op(sdht):
        try:
            box["r"] = fn(sdht._dht)
        except Exception as e:            # noqa: BLE001 — re-raised below
            box["e"] = e
        finally:
            done.set()

    runner._post_node(op, prio=True)
    if not done.wait(timeout):
        raise TimeoutError("posted node op never ran")
    if "e" in box:
        raise box["e"]
    return box.get("r")


def _counter(metrics, name):
    return sum(v for k, v in metrics.get("counters", {}).items()
               if k == name or k.startswith(name + "{"))


def smoke() -> int:
    import socket as _socket

    from opendht_tpu.core.table import NODE_EXPIRE_TIME
    from opendht_tpu.core.value import Value
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.runtime.config import Config, NodeStatus
    from opendht_tpu.runtime.runner import DhtRunner, RunnerConfig

    def runner_cfg():
        cfg = Config()
        cfg.maintain_storage = True
        return RunnerConfig(dht_config=cfg)

    nodes = [DhtRunner() for _ in range(3)]
    try:
        nodes[0].run(0, runner_cfg())
        for n in nodes[1:]:
            n.run(0, runner_cfg())
            n.bootstrap("127.0.0.1", nodes[0].get_bound_port())
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0:
            if all(n.get_status() is NodeStatus.CONNECTED
                   for n in nodes[1:]):
                break
            time.sleep(0.05)
        else:
            print("SMOKE FAIL: cluster never connected")
            return 1

        before = nodes[0].get_metrics()

        # ---- forced bucket refresh -----------------------------------
        def force_refresh(dht):
            table = dht.tables[_socket.AF_INET]
            rows = table._time_reply > 0
            # age every reply clock past the 10-min rule
            table._time_reply[rows] -= NODE_EXPIRE_TIME + 60.0
            now = dht.scheduler.time()
            # fused sweep bit-identical to the host-visible stale set on
            # the LIVE table, not just on synthetic fixtures
            stale, targets = table.maintenance_sweep(now)
            assert np.array_equal(stale, table.stale_buckets(now)), \
                "fused sweep diverged from stale_buckets on a live table"
            assert targets.shape == (len(stale), 5)
            return len(stale), dht._bucket_maintenance(_socket.AF_INET)

        n_stale, sent = _on_node(nodes[0], force_refresh)
        if not (n_stale > 0 and sent):
            print(f"SMOKE FAIL: forced refresh sent nothing "
                  f"(stale={n_stale}, sent={sent})")
            return 1

        # ---- forced republish ----------------------------------------
        key = InfoHash.get("maint-smoke")

        def force_republish(dht):
            now = dht.scheduler.time()
            assert dht.storage_store(key, Value(b"republish", value_id=1),
                                     now)
            dht.store[key].maintenance_time = now     # due immediately
            dht._data_persistence(key)
            return dht.store[key].maintenance_time > now

        if not _on_node(nodes[0], force_republish):
            print("SMOKE FAIL: due key was not rescheduled by the sweep")
            return 1

        after = nodes[0].get_metrics()
        checks = {
            "dht_maintenance_sweeps_total": 1,
            "dht_maintenance_refresh_sent_total": 1,
            "dht_maintenance_due_keys_total": 1,
            'dht_net_requests_sent_total{type="find"}': 1,
        }
        for name, min_delta in checks.items():
            delta = _counter(after, name) - _counter(before, name)
            if delta < min_delta:
                print(f"SMOKE FAIL: {name} advanced {delta} (< {min_delta})")
                return 1
        finds = (_counter(after, 'dht_net_requests_sent_total{type="find"}')
                 - _counter(before,
                            'dht_net_requests_sent_total{type="find"}'))
        refresh = (_counter(after, "dht_maintenance_refresh_sent_total")
                   - _counter(before, "dht_maintenance_refresh_sent_total"))
        if finds < refresh:
            print(f"SMOKE FAIL: {refresh} refreshes claimed but only "
                  f"{finds} find requests left the engine")
            return 1
        assert after.get("gauges", {}).get(
            "dht_maintenance_calendar_bins", 0) >= 1
        print(f"maintenance smoke ok: {n_stale} stale buckets refreshed, "
              f"{finds} find_nodes on the wire, counters advanced")
        return 0
    finally:
        for n in nodes:
            try:
                n.join()
            except Exception:
                pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="real-UDP cluster maintenance smoke (CI entry)")
    p.add_argument("-N", type=int, default=0, help="table ids")
    p.add_argument("-K", type=int, default=0, help="due republish keys")
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json with the attribution")
    args = p.parse_args(argv)

    if args.smoke:
        return smoke()

    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops import radix
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits, expand_table,
                                              lookup_topk)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (10_000_000 if on_accel else 1_000_000)
    K = args.K or (100_000 if on_accel else 4_096)
    rng = np.random.default_rng(10)
    ids = rng.integers(0, 2 ** 32, size=(N, 5), dtype=np.uint32)
    self_id = rng.integers(0, 2 ** 32, size=(5,), dtype=np.uint32)
    valid = np.ones(N, bool)
    # half the table never replied (the staleness rule's hard case)
    last = np.where(rng.random(N) > 0.5,
                    rng.uniform(1.0, 500.0, N), 0.0).astype(np.float32)
    due = rng.integers(0, 2 ** 32, size=(K, 5), dtype=np.uint32)
    now, age = 1200.0, 600.0
    prng = jax.random.PRNGKey(4)

    # ---- table sweep -----------------------------------------------------
    def sweep_fused(x, self_id, valid, last, prng):
        c, l, s, t = radix.maintenance_sweep(self_id, x, valid, last,
                                             now, age, prng)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(jnp.where(jnp.isfinite(l), l, 0.0)) * 1e-9
                + jnp.sum(s.astype(jnp.float32))
                + jnp.sum(t.astype(jnp.float32)) * 1e-9)

    def sweep_split(x, self_id, valid, last, prng):
        c = radix.bucket_counts(self_id, x, valid)
        l = radix.bucket_last_seen(self_id, x, valid, last)
        t = radix.random_id_in_bucket(
            self_id, jnp.arange(radix.ID_BITS, dtype=jnp.int32), prng)
        s = (c > 0) & (l < now - age)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(jnp.where(jnp.isfinite(l), l, 0.0)) * 1e-9
                + jnp.sum(s.astype(jnp.float32))
                + jnp.sum(t.astype(jnp.float32)) * 1e-9)

    r1, r2 = (8, 32) if on_accel else (2, 6)
    sweep_args = (jnp.asarray(ids), jnp.asarray(self_id),
                  jnp.asarray(valid), jnp.asarray(last), prng)
    dt_fused = chain_slope(sweep_fused, *sweep_args, r1=r1, r2=r2)
    dt_split = chain_slope(sweep_split, *sweep_args, r1=r1, r2=r2)

    # the host np.maximum.at staleness reduction this round deleted
    # (host code — wall clock is honest, no device dispatch involved)
    bkt = np.minimum(np.asarray(radix.bucket_of(
        jnp.asarray(self_id), jnp.asarray(ids))), radix.MAX_BUCKET)
    t0 = time.perf_counter()
    hl = np.full(radix.ID_BITS, -np.inf)
    rows = valid & (last > 0)
    np.maximum.at(hl, bkt[rows], last[rows])
    dt_host = time.perf_counter() - t0

    # ---- republish planning ---------------------------------------------
    sorted_ids, _perm, n_valid = jax.block_until_ready(
        sort_table(jnp.asarray(ids)))
    expanded = expand_table(sorted_ids)
    lut = build_prefix_lut(sorted_ids, n_valid,
                           bits=default_lut_bits(N))

    def _lex_less(a, b):
        # 160-bit lexicographic a < b over [.., 5] uint32 limbs
        lt = jnp.zeros(a.shape[:-1], bool)
        eq = jnp.ones(a.shape[:-1], bool)
        for limb in range(5):
            lt = lt | (eq & (a[..., limb] < b[..., limb]))
            eq = eq & (a[..., limb] == b[..., limb])
        return lt

    def republish_batched(q, sorted_ids, expanded, n_valid, lut, self_id):
        # closest-8 for EVERY due key + the still-responsible predicate
        # (k-th closest XOR-closer to the key than we are) in one call
        dist, idx, cert = lookup_topk(sorted_ids, n_valid, q, k=8,
                                      expanded=expanded, lut=lut)
        self_dist = q ^ self_id[None, :]
        do = _lex_less(dist[:, -1, :], self_dist)
        return (jnp.sum(do.astype(jnp.float32))
                + jnp.sum(cert.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    rep_args = (sorted_ids, expanded, n_valid, lut, jnp.asarray(self_id))
    dt_rep = chain_slope(republish_batched, jnp.asarray(due), *rep_args,
                         r1=r1, r2=r2)
    # the scalar path's cost: ONE key per launch (the full lane-padding
    # tax), slope-measured at batch 1 and extrapolated ×K
    pr1, pr2 = (32, 256) if on_accel else (4, 16)
    dt_one = chain_slope(republish_batched, jnp.asarray(due[:1]), *rep_args,
                         r1=pr1, r2=pr2)

    by = {
        "N": N, "K": K,
        "sweep_fused_ms": round(dt_fused * 1e3, 3),
        "sweep_split_ms": round(dt_split * 1e3, 3),
        "sweep_host_maximum_at_ms": round(dt_host * 1e3, 3),
        "republish_batched_ms": round(dt_rep * 1e3, 3),
        "republish_per_key_ms_each": round(dt_one * 1e3, 4),
        "republish_per_key_extrapolated_ms": round(dt_one * K * 1e3, 1),
        "republish_amortization_x": round(dt_one * K / dt_rep, 1),
        "sweep_ids_per_s": round(N / dt_fused, 1),
    }
    print(json.dumps(by), flush=True)

    if args.capture:
        out = {
            "metric": ("batched maintenance sweep, config-4 workload: "
                       "fused bucket sweep (occupancy+staleness+targets, "
                       "one launch over %d ids) + republish planning "
                       "(closest-8 + responsibility predicate for %d due "
                       "keys in one lookup_topk call), platform=%s; "
                       "value = fused sweep + batched republish ms; the "
                       "per-key figure is a batch-1 slope extrapolated "
                       "x%d, stated as such" % (
                           N, K, jax.devices()[0].platform, K)),
            "value": round((dt_fused + dt_rep) * 1e3, 3),
            "unit": "ms/maintenance-round (%s)" % jax.devices()[0].platform,
            "vs_baseline": by["republish_amortization_x"],
            "bound": by,
        }
        if not on_accel:
            out["accelerator_target"] = (
                "the config-4 accelerator number (10M-id sweep + 100K-key "
                "republish planning in one pass) is OPEN: this capture is "
                "cpu, and the 128-lane padding tax the batched resolve "
                "amortizes exists only in TPU tiled layout.  Settle it "
                "with the two commands in this driver's docstring on an "
                "accelerator session.")
        dc.write_capture(args.capture, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
