"""Continuous-batching ingest attribution (round-12 tentpole,
runtime/wave_builder.py): per-op amortization of coalesced vs per-op
dispatch, measured on the LIVE lookup path.

Before round 12 every live get/put/listen resolved its search refill
through ``find_closest_nodes_batched([one target])`` — one device
launch per op, padded to the full lane width, plus the per-launch host
scatter (row→Node conversion).  The wave builder coalesces a pump's
worth of refills into one ``[Q]`` launch.  This driver measures exactly
that trade on CPU, through the SHIPPING ``Dht.find_closest_nodes_batched``
entry point (device launch + host scatter, the whole per-op cost the
builder amortizes):

  per_op       Q separate [1]-target resolves (the batching="off"
               dispatch), wall per op
  coalesced    ONE [Q]-target resolve (the wave the builder launches
               at its fill target), wall per op
  amortization per_op / coalesced

``--capture ingest_wave`` writes captures/ingest_wave.json; README
quotes the amortization and both per-op figures under
``<!-- capture:ingest_wave -->`` (ci/check_docs.py enforces the quotes
both directions).  The on-chip occupancy/latency number is OPEN —
the 128-lane padding tax this amortizes is a TPU tiled-layout effect,
so the CPU figure under-states it.  Settle on an accelerator session:

  python benchmarks/exp_ingest_r12.py --capture ingest_wave
  python -m opendht_tpu.testing.ingest_smoke

(the fourth OPEN entry in perf_budgets.json, ``ingest_wave_occupancy``.)
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def _build_dht(n: int, n_targets: int, seed: int = 31):
    """A v4-only Dht over a swallow-everything transport with an
    n-row bulk-loaded, addr-servable table — the live resolve's exact
    substrate."""
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.runtime import Config, Dht
    from opendht_tpu.scheduler import Scheduler
    from opendht_tpu.sockaddr import SockAddr

    clock = {"t": 1000.0}
    dht = Dht(lambda data, addr: 0, config=Config(),
              scheduler=Scheduler(clock=lambda: clock["t"]), has_v6=False)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 2 ** 32, size=(n, 5), dtype=np.uint32)
    dht.tables[next(iter(dht.tables))].bulk_load(
        ids, now=clock["t"], addrs=SockAddr("10.7.0.1", 4222))
    targets = [InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
               for _ in range(n_targets)]
    return dht, targets


def _measure(dht, targets, q: int, k: int, reps: int):
    """Median wall seconds per op for the per-op and coalesced forms
    over ``reps`` disjoint Q-target waves each."""
    import socket as _socket
    af = _socket.AF_INET

    # warm both compiled shapes out of the measurement
    dht.find_closest_nodes_batched(targets[:1], af, k)
    dht.find_closest_nodes_batched(targets[:q], af, k)

    per_op, coalesced = [], []
    for r in range(reps):
        wave = targets[r * q:(r + 1) * q]       # disjoint per rep
        assert len(wave) == q
        t0 = time.perf_counter()
        for t in wave:
            dht.find_closest_nodes_batched([t], af, k)
        per_op.append((time.perf_counter() - t0) / q)
        t0 = time.perf_counter()
        out = dht.find_closest_nodes_batched(wave, af, k)
        coalesced.append((time.perf_counter() - t0) / q)
        assert len(out) == q
    return float(np.median(per_op)), float(np.median(coalesced))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=65536, help="table rows")
    p.add_argument("-Q", type=int, default=64,
                   help="wave width (the fill target)")
    p.add_argument("-k", type=int, default=14,
                   help="refill k (live_search.SEARCH_NODES)")
    p.add_argument("--reps", type=int, default=9)
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json")
    p.add_argument("--smoke", action="store_true",
                   help="small-shape CI form: assert coalescing still "
                        "amortizes (>2x) without the full shape")
    args = p.parse_args(argv)

    import jax

    n, q, reps = ((8192, 16, 5) if args.smoke
                  else (args.N, args.Q, args.reps))
    dht, targets = _build_dht(n, n_targets=q * reps)
    per_op_s, coalesced_s, = _measure(dht, targets, q, args.k, reps)
    amort = per_op_s / coalesced_s if coalesced_s > 0 else float("inf")

    rec = dc.emit({
        "driver": "exp_ingest_r12",
        "N": n, "Q": q, "k": args.k,
        "per_op_us": round(per_op_s * 1e6, 2),
        "coalesced_us_per_op": round(coalesced_s * 1e6, 2),
        "ingest_amortization_x": round(amort, 1),
        "platform": jax.default_backend(),
    })

    if args.smoke:
        assert amort > 2.0, (
            "coalesced dispatch no longer amortizes: %.2fx" % amort)
        print("ingest amortization smoke ok: %.1fx" % amort)
        return 0

    if args.capture:
        dc.write_capture(args.capture, {
            "metric": ("continuous-batching ingest, live resolve path: "
                       "Q separate [1]-target find_closest_nodes_batched "
                       "dispatches (the batching=off per-op path) vs ONE "
                       "[Q]-target wave (the builder's fill-target "
                       "launch), device launch + host scatter included, "
                       "platform=cpu; value = per-op amortization factor"),
            "value": round(amort, 1),
            "unit": "x per-op amortization (cpu)",
            "bound": {
                "N": n, "Q": q, "k": args.k,
                "per_op_us": rec["per_op_us"],
                "coalesced_us_per_op": rec["coalesced_us_per_op"],
                "ingest_amortization_x": round(amort, 1),
            },
            "accelerator_target": (
                "the on-chip occupancy/latency number is OPEN "
                "(perf_budgets.json ingest_wave_occupancy): cpu has no "
                "128-lane padding tax, so this amortization under-states "
                "the TPU figure.  Settle with the two commands in this "
                "driver's docstring on an accelerator session."),
        })
    return 0


if __name__ == "__main__":
    sys.exit(main())
