"""Round-18 chaos-plane driver: a >=50k-simulated-node swarm stepping a
scripted join/leave storm plus an asymmetric partition-and-heal
entirely on device — ONE ``ops/swarm.py swarm_step`` launch per tick —
with the lookup-success and replica-coverage invariants asserted
degraded during the cut and RESTORED after healing, deterministic under
the fixed seed (the ISSUE-13 acceptance scenario).

Full mode commits ``captures/swarm_storm.json`` (per-tick invariant
timeline + wall-clock per tick on this host); ``--smoke`` runs the same
arc at S=4096 for CI (and feeds the perf gate's timing_soft record).

Usage::

    python benchmarks/exp_chaos_r18.py                # full: S=50000
    python benchmarks/exp_chaos_r18.py --smoke        # CI arc at S=4096
"""

from __future__ import annotations

import argparse
import sys
import time

from driver_common import emit, write_capture  # noqa: E402 (sys.path)


def storm_plan():
    """The ISSUE-13 acceptance arc: join/leave storm, then an
    ASYMMETRIC partition (g0→g1 blocked, g1→g0 open — the one-way
    routing failure a symmetric cut never exercises) that heals when
    its phase ends."""
    from opendht_tpu import chaos
    return chaos.FaultPlan([
        chaos.Phase("storm", start=1.0, duration=3.0,
                    storm=chaos.Storm(leave_rate=0.10, join_rate=0.10)),
        chaos.Phase("refill", start=4.0, duration=3.0,
                    storm=chaos.Storm(join_rate=0.5)),
        chaos.Phase("split", start=8.0, duration=6.0,
                    partition=chaos.Partition(block=[("g0", "g1")])),
    ], seed=3)


def run_arc(n_nodes: int, *, n_keys: int, sweep: int, ticks: int,
            seed: int = 5):
    from opendht_tpu.ops.swarm import SwarmSim

    sim = SwarmSim(storm_plan(), n_nodes=n_nodes, n_keys=n_keys,
                   n_groups=2, seed=seed, sweep_sample=sweep,
                   repub_every=2)
    rows = []
    for i in range(ticks):
        t0 = time.perf_counter()
        m = sim.tick()
        tick_ms = (time.perf_counter() - t0) * 1e3
        m.update(sim.probe())
        m["tick_ms"] = round(tick_ms, 3)
        rows.append(m)
    return rows


def check_arc(rows) -> None:
    assert rows[0]["verdict"] == "healthy", rows[0]
    cut = rows[9:13]
    assert any(r["verdict"] != "healthy" for r in cut), \
        "partition never degraded the invariants"
    last = rows[-1]
    assert last["verdict"] == "healthy", last
    assert last["lookup_success"] >= 0.95, last
    assert last["replica_coverage"] >= 0.95, last
    assert sum(r["n_leave"] for r in rows) > 0
    assert sum(r["n_join"] for r in rows) > 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("-S", "--nodes", type=int, default=50_000)
    p.add_argument("-K", "--keys", type=int, default=64)
    p.add_argument("-M", "--sweep", type=int, default=32)
    p.add_argument("--ticks", type=int, default=22)
    p.add_argument("--seed", type=int, default=5)
    p.add_argument("--smoke", action="store_true",
                   help="CI arc at S=4096 (no capture write)")
    args = p.parse_args(argv)

    import jax

    if args.smoke:
        rows = run_arc(4096, n_keys=48, sweep=args.sweep, ticks=args.ticks,
                       seed=args.seed)
        check_arc(rows)
        # determinism: the same seed replays the identical storm
        rows2 = run_arc(4096, n_keys=48, sweep=args.sweep,
                        ticks=args.ticks, seed=args.seed)
        strip = [{k: v for k, v in r.items() if k != "tick_ms"}
                 for r in rows]
        strip2 = [{k: v for k, v in r.items() if k != "tick_ms"}
                  for r in rows2]
        assert strip == strip2, "swarm storm not deterministic under seed"
        emit({"mode": "smoke", "n_nodes": 4096,
              "swarm_tick_ms": round(
                  sorted(r["tick_ms"] for r in rows)[len(rows) // 2], 3),
              "final_lookup_success": rows[-1]["lookup_success"],
              "final_replica_coverage": rows[-1]["replica_coverage"]})
        print("exp_chaos_r18 --smoke: OK (deterministic, invariants "
              "restored after heal)")
        return 0

    rows = run_arc(args.nodes, n_keys=args.keys, sweep=args.sweep,
                   ticks=args.ticks, seed=args.seed)
    check_arc(rows)
    ticks_ms = sorted(r["tick_ms"] for r in rows)
    cut = rows[9:13]
    rec = {
        "driver": "exp_chaos_r18",
        "platform": jax.devices()[0].platform,
        # headline row for ci/assemble_trajectory.py's captures section
        "metric": ("p50 swarm_step wall-clock per tick, %d-node storm"
                   % args.nodes),
        "unit": "ms",
        "value": round(ticks_ms[len(ticks_ms) // 2], 3),
        "n_nodes": args.nodes,
        "n_keys": args.keys,
        "sweep_sample": args.sweep,
        "ticks": args.ticks,
        "seed": args.seed,
        "tick_ms_p50": round(ticks_ms[len(ticks_ms) // 2], 3),
        "tick_ms_max": round(ticks_ms[-1], 3),
        "min_success_during_cut": min(r["lookup_success"] for r in cut),
        "min_coverage_during_cut": min(r["replica_coverage"]
                                       for r in cut),
        "final_lookup_success": rows[-1]["lookup_success"],
        "final_replica_coverage": rows[-1]["replica_coverage"],
        "model_err_mean": round(sum(r["model_err"] for r in rows)
                                / len(rows), 2),
        "timeline": [{k: r[k] for k in
                      ("n_alive", "lookup_success", "replica_coverage",
                       "verdict")} for r in rows],
    }
    emit({k: v for k, v in rec.items() if k != "timeline"})
    write_capture("swarm_storm", rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
