"""Shared fixture builders for the churn benchmark drivers.

exp_churn_r5.py, exp_churn2_r5.py and exp_churn_r7.py all need the same
scaffolding — a sorted+expanded+LUT'd random base table, a query wave,
a delta slab with pre-built sorted/expanded/LUT structures, and the
per-round mutation arrays (tombstone word writes + delta appends) in
the idempotent form the chain-slope methodology requires.  Before
round 7 each driver rebuilt these inline (ISSUE 2 satellite 1); this
module is the single definition.

Device-array building imports jax lazily inside each function so the
drivers keep controlling platform selection (jax.config.update before
first backend use — see ci/run_ci.sh's heredoc note).
"""

from __future__ import annotations

import numpy as np


def sizes(on_accel: bool, *, dcap: int = 0):
    """The canonical churn-bench shape: (N table rows, Q wave width,
    DCAP delta-slab capacity).  65536 is the measured accelerator
    optimum for DCAP (round-5 sweep; see baseline_configs.config6)."""
    N = 10_000_000 if on_accel else 200_000
    Q = 131_072 if on_accel else 8_192
    return N, Q, dcap or (65_536 if on_accel else 8_192)


def build_base(N: int, Q: int, *, seed: int = 7, limbs: int = 2):
    """Random sorted base table + query wave + serving structures.

    Returns a dict with device arrays ``sorted_ids`` [N,5],
    ``expanded`` (``limbs``-plane stride-64 expansion), ``lut``,
    ``n_valid``, ``queries`` [Q,5], plus ``key3`` (a spare PRNG key for
    driver-specific extras, e.g. the exactness-sample batch).
    """
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table)

    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    del table
    expanded = jax.block_until_ready(expand_table(sorted_ids, limbs=limbs))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    return {"sorted_ids": sorted_ids, "expanded": expanded, "lut": lut,
            "n_valid": n_valid, "queries": queries, "key3": k3}


def build_mutations(N: int, DCAP: int, E: int, *, seed: int = 70,
                    fill_frac: float = 0.5):
    """Host-side churn state for one idempotent timed round: a delta
    slab ``fill_frac`` full, E new ids staged for the round's append,
    E tombstone word writes (values precomputed so chain reps are
    idempotent — required by the slope methodology), and an all-zero
    tombstone base.

    Returns a dict of device arrays ``tomb_base`` [ceil(N/32)],
    ``widx``/``wval`` [E] (word indices + post-write values),
    ``dslab`` [DCAP,5], ``new_ids`` [E,5], ``nd0`` (int, rows live
    before the append), ``nd_after`` (int32 scalar, rows live after).
    """
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    nwords = (N + 31) // 32
    dslab_np = rng.integers(0, 2**32, size=(DCAP, 5), dtype=np.uint32)
    nd0 = int(DCAP * fill_frac)
    new_ids = rng.integers(0, 2**32, size=(E, 5), dtype=np.uint32)
    widx = rng.integers(0, nwords, size=E, dtype=np.int64)
    return {"tomb_base": jnp.zeros((nwords,), jnp.uint32),
            "widx": jnp.asarray(widx),
            "wval": jnp.zeros((E,), jnp.uint32),
            "dslab": jnp.asarray(dslab_np),
            "new_ids": jnp.asarray(new_ids),
            "nd0": nd0, "nd_after": jnp.int32(nd0 + E)}


def build_delta_structs(dslab, n_live, *, strides=(16, 64), limbs: int = 2):
    """Pre-built serving structures for a delta slab state (the
    no-rebuild variants and the static comparators): sorted slab, one
    expansion per requested stride, and the delta LUT.

    Returns (d_sorted, [expansion per stride], d_lut, d_n_valid).
    """
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table)

    DCAP = dslab.shape[0]
    ds, _dp, dnv = jax.block_until_ready(
        sort_table(dslab, jnp.arange(DCAP) < n_live))
    exps = [jax.block_until_ready(expand_table(ds, stride=s, limbs=limbs))
            for s in strides]
    dlut = jax.block_until_ready(
        build_prefix_lut(ds, dnv, bits=default_lut_bits(DCAP)))
    return ds, exps, dlut, dnv


def random_delta_slab(DCAP: int, *, seed: int):
    """A standalone random [DCAP, 5] delta slab as a device array (the
    exp_churn_r5 per-capacity sweep)."""
    import jax
    import jax.numpy as jnp
    return jax.random.bits(jax.random.PRNGKey(seed), (DCAP, 5),
                           dtype=jnp.uint32)
