"""Table-sharded iterative search: collective-volume + scaling evidence.

Verdict-r3 ask #5: make "O(queries), never O(table)" a MEASURED table.
On an 8-virtual-device CPU mesh (the same environment the driver's
``dryrun_multichip`` uses — real multi-chip hardware is not available
here) this driver, for n_t ∈ {1, 2, 4, 8} with n_q = 8/n_t on a fixed
global table:

1. compiles ``parallel.build_tp_lookup`` and EXTRACTS the collectives
   from the compiled HLO — op kind, output shape, bytes — so the wire
   volume per hop is read off the actual executable, not just the
   analytic model (round 13: ONE in-loop reply-row merge psum; block
   edges are local reads of the replicated global block LUT and
   positioning is a one-shot psum — opendht_tpu/parallel/sharded.py
   build_tp_lookup);
2. checks the per-hop collective bytes scale with the QUERY batch and
   are independent of the table shard size (the whole point of the
   design: a bigger table costs no more wire);
3. records relative wall-clock per call.  CPU-mesh wall-clock measures
   compute + memory only — virtual devices share one host, so this is
   a scaling-shape indicator, NOT an ICI latency measurement (stated
   in the artifact).

Writes ``TP_SCALING.json`` at the repo root (next to the MULTICHIP
artifacts) and prints one JSON line per geometry.  Usage::

    python benchmarks/tp_scaling.py [-N 262144] [-Q 4096]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_DTYPE_BYTES = {"u32": 4, "s32": 4, "f32": 4, "u64": 8, "s64": 8,
                "u16": 2, "s16": 2, "u8": 1, "s8": 1, "pred": 1,
                "bf16": 2, "f64": 8}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(shapes: str) -> int:
    total = 0
    for dt, dims in _SHAPE_RE.findall(shapes):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


_COLLECTIVE_LINE_RE = re.compile(
    r"=\s*(\(?[a-z0-9]+\[[^=]*?)\s*"
    r"(all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)"
    r"\(")


def collectives_of(hlo_text: str) -> dict:
    """Collectives in the compiled module, attributed IN-LOOP (execute
    once per hop) vs ONE-SHOT (once per call).

    Not every collective runs per hop: the engine issues psums before
    the while-loop (initial positioning + the bootstrap round) and one
    after (the final 5-limb id reconstruction) — core/search.py:259,
    339-352, 463 — so counting the whole module as per-hop overstates
    wire volume ~2×.  Attribution reads each instruction's ``op_name``
    metadata, which carries the full trace path: collectives lowered
    from inside the hop loop are tagged ``…/while/body/…``.
    """
    per_hop, one_shot = [], []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_LINE_RE.search(line)
        if not m:
            continue
        rec = {"op": m.group(2), "bytes": _shape_bytes(m.group(1))}
        (per_hop if "/while/body/" in line else one_shot).append(rec)
    return {"per_hop": per_hop, "one_shot": one_shot}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-N", type=int, default=262_144)
    p.add_argument("-Q", type=int, default=4_096)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args(argv)

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import ALPHA, SEARCH_NODES
    from opendht_tpu.parallel.partition import shard_table_state
    from opendht_tpu.parallel.sharded import build_tp_lookup

    devs = np.array(jax.devices())
    assert len(devs) == 8, devs
    N, Q = args.N, args.Q
    MAX_HOPS = 48
    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    nv = jnp.asarray(n_valid, jnp.int32)

    rows = []
    ref_nodes = None
    for n_t in (1, 2, 4, 8):
        n_q = 8 // n_t
        mesh = Mesh(devs.reshape(n_q, n_t), ("q", "t"))
        shard_n = N // n_t
        # round 13: the table state (sorted rows + per-shard LUT +
        # replicated global block LUT) is built ONCE per geometry by
        # the declarative layer and passed as operands — in-loop
        # collectives drop to the single reply-row merge psum
        state = shard_table_state(mesh, sorted_ids, nv)
        fn = build_tp_lookup(mesh, shard_n, Q, 8, ALPHA, SEARCH_NODES,
                             MAX_HOPS, state_limbs=2)
        a = state.arrays
        t_pl = jax.device_put(targets, NamedSharding(mesh, P("q", None)))
        seed = jnp.int32(1)
        op_args = (a["sorted_ids"], a["local_lut"], a["block_lut"],
                   a["n_valid"], t_pl, seed)

        # keep the AOT executable: compiling once for as_text() and
        # again through the jit cache would double the driver's compile
        # time (the executions below go through `compiled` directly)
        compiled = fn.lower(*op_args).compile()
        hlo = compiled.as_text()
        attributed = collectives_of(hlo)
        colls = attributed["per_hop"]
        per_hop = sum(c["bytes"] for c in colls)
        one_shot = sum(c["bytes"] for c in attributed["one_shot"])
        by_kind: dict = {}
        for c in colls:
            by_kind[c["op"]] = by_kind.get(c["op"], 0) + c["bytes"]

        out = jax.block_until_ready(compiled(*op_args))
        nodes = np.asarray(out["nodes"])
        if ref_nodes is None:
            ref_nodes = nodes
        else:
            np.testing.assert_array_equal(nodes, ref_nodes)     # bit-identical
        best = None
        for _ in range(args.reps):
            t0 = time.perf_counter()
            jax.block_until_ready(compiled(*op_args))
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)

        hops = np.asarray(out["hops"])
        # HLO is SPMD — one program per device — so instruction output
        # bytes are PER-DEVICE volumes.  Normalizing by the device's
        # local query slice (q_local = Q / n_q) gives the invariant the
        # design claims: bytes per query per hop per device do not grow
        # with the table shard (or with n_t), only with queries.
        q_local = Q // n_q
        row = {
            "n_t": n_t, "n_q": n_q, "shard_rows": shard_n, "Q": Q, "N": N,
            "collective_sites_in_loop": len(colls),
            "collective_sites_one_shot": len(attributed["one_shot"]),
            "collective_bytes_per_hop_per_device": per_hop,
            "collective_bytes_one_shot_per_device": one_shot,
            "collective_bytes_by_kind": by_kind,
            "bytes_per_local_query_per_hop": round(per_hop / q_local, 1),
            "p50_hops": int(np.percentile(hops, 50)),
            "converged": float(np.asarray(out["converged"]).mean()),
            "wallclock_s": round(best, 4),
            "lookups_per_s_virtual": round(Q / best, 1),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)

    artifact = {
        "metric": "tp_simulate_lookups collective volume + scaling, "
                  "8 virtual CPU devices (mesh q x t), fixed table",
        "note": "collective bytes read from the compiled HLO, attributed "
                "in-loop (once per hop of the while-loop body's call "
                "graph) vs one-shot (positioning before / id "
                "reconstruction after the loop); wall-clock on a "
                "virtual CPU mesh indicates scaling shape only — "
                "virtual devices share one host, ICI is not modeled. "
                "Results bit-identical across every geometry.",
        "rows": rows,
    }
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "TP_SCALING.json"), "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"wrote": "TP_SCALING.json",
                      "geometries": len(rows)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
