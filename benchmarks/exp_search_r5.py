"""Round-5 experiment driver for the iterative engine (ask 1).

Measures, on the real chip, the levers the round-4 verdict names:
round-count distribution, wave-width sweep, survivor-compaction cuts.
Temporary exploration tool; the winning configuration lands in
core/search.py + baseline_configs.py with its numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-N", type=int, default=0)
    p.add_argument("--widths", type=str, default="8192,16384,32768")
    p.add_argument("--cuts", type=str, default="0,8,10")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)
    from opendht_tpu.core import search as SE

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (10_000_000 if on_accel else 100_000)
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    # round-count distribution at W=16384 (hops ≈ rounds for converged)
    W0 = 16_384 if on_accel else 1_024
    tg = jax.random.bits(k2, (W0, 5), dtype=jnp.uint32)
    out = jax.block_until_ready(SE.simulate_lookups(
        sorted_ids, n_valid, tg, alpha=3, k=8, lut=lut, state_limbs=2))
    hops = np.asarray(out["hops"])
    print(json.dumps({
        "stage": "hops dist W=%d" % W0,
        "p50": int(np.percentile(hops, 50)),
        "p90": int(np.percentile(hops, 90)),
        "p99": int(np.percentile(hops, 99)),
        "max": int(hops.max()),
        "mean": round(float(hops.mean()), 2),
        "converged": float(np.asarray(out["converged"]).mean()),
    }), flush=True)

    def make_body(compact_after, compact_cap):
        def body(t, sorted_ids, n_valid, lut):
            o = SE.simulate_lookups(sorted_ids, n_valid, t, alpha=3, k=8,
                                    lut=lut, state_limbs=2,
                                    compact_after=compact_after,
                                    compact_cap=compact_cap)
            return (jnp.sum(o["hops"].astype(jnp.float32))
                    + jnp.sum(o["converged"].astype(jnp.float32)))
        return body

    widths = [int(w) for w in args.widths.split(",") if w]
    cuts = [int(c) for c in args.cuts.split(",") if c != ""]
    for W in widths:
        t = jax.random.bits(jax.random.PRNGKey(100 + W), (W, 5),
                            dtype=jnp.uint32)
        for cut in cuts:
            ca = None if cut == 0 else cut
            cc = 0 if cut == 0 else max(256, W // 8)
            dt = chain_slope(make_body(ca, cc), t, sorted_ids, n_valid, lut,
                             r1=1, r2=4)
            print(json.dumps({
                "stage": "wave W=%d cut=%s cap=%d" % (W, ca, cc),
                "ms": round(dt * 1e3, 2),
                "lookups_per_s": round(W / dt, 1),
            }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
