"""Round-5 headline-geometry experiments (ask 2 follow-up).

With the 2-plane expansion landed (17.9M → 21.5M), the in-window sort
is the next dominant term.  Measures, per stride (16/24/32):
stage-1 certification fraction, plain fast2 slope, cascade slope with a
cap sized to the measured miss count; plus isolated sort and row-gather
stage costs.  Exploration tool — winners land in bench.py with numbers.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope, K
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits, expand_table,
                                              expanded_topk, cascade_topk)

    on_accel = jax.devices()[0].platform != "cpu"
    N = 1_000_000 if on_accel else 100_000
    Q = 131_072 if on_accel else 8_192
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    e64 = jax.block_until_ready(expand_table(sorted_ids, limbs=2))

    def report(stage, dt, extra=None):
        rec = {"stage": stage, "ms": round(dt * 1e3, 3),
               "lookups_per_s": round(Q / dt, 1)}
        if extra:
            rec.update(extra)
        print(json.dumps(rec), flush=True)

    # isolated sort cost vs padded lane count (the dominant term):
    # [Q, wlen] 3-operand num_keys=3 vs num_keys=2-stable
    for wlen in (48, 96):
        d0 = jax.random.bits(jax.random.PRNGKey(1), (Q, wlen),
                             dtype=jnp.uint32)
        d1 = jax.random.bits(jax.random.PRNGKey(2), (Q, wlen),
                             dtype=jnp.uint32)
        gr = jnp.broadcast_to(jnp.arange(wlen, dtype=jnp.int32)[None, :],
                              (Q, wlen))

        def s3(q, d0, d1, gr):
            o = lax.sort((d0 ^ q[:, :1], d1, gr), dimension=1, num_keys=3)
            return jnp.sum(o[2][:, :K].astype(jnp.float32))

        def s2(q, d0, d1, gr):
            o = lax.sort((d0 ^ q[:, :1], d1, gr), dimension=1, num_keys=2,
                         is_stable=True)
            return jnp.sum(o[2][:, :K].astype(jnp.float32))

        for name, body in (("sort3", s3), ("sort2stable", s2)):
            dt = chain_slope(body, queries, d0, d1, gr, r1=8, r2=64)
            report(f"{name} wlen={wlen}", dt)

    for stride in (16, 24, 32):
        e2 = jax.block_until_ready(
            expand_table(sorted_ids, stride=stride, limbs=2))
        _, _, c1 = jax.block_until_ready(
            expanded_topk(sorted_ids, e2, n_valid, queries, k=K,
                          select="fast2", lut=lut, lut_steps=0, planes=2))
        miss = int((~np.asarray(c1)).sum())

        def f2(q, sorted_ids, e2, n_valid, lut):
            d, i, c = expanded_topk(sorted_ids, e2, n_valid, q, k=K,
                                    select="fast2", lut=lut, lut_steps=0,
                                    planes=2)
            return (jnp.sum(c.astype(jnp.float32))
                    + jnp.sum(i[:, 0].astype(jnp.float32)) * 1e-9)

        dt = chain_slope(f2, queries, sorted_ids, e2, n_valid, lut,
                         r1=8, r2=64)
        report(f"fast2 s={stride} planes=2", dt,
               {"stage1_miss": miss, "cert": 1 - miss / Q})

        cap = 256
        while cap < 3 * miss and cap < Q:
            cap *= 2

        def casc(q, sorted_ids, e2, e64, n_valid, lut):
            d, i, c = cascade_topk(sorted_ids, e2, e64, n_valid, q, lut,
                                   k=K, select="fast2", cap=cap, planes=2)
            return (jnp.sum(c.astype(jnp.float32))
                    + jnp.sum(i[:, 0].astype(jnp.float32)) * 1e-9)

        dt = chain_slope(casc, queries, sorted_ids, e2, e64, n_valid, lut,
                         r1=8, r2=64)
        _, _, cc = jax.block_until_ready(
            cascade_topk(sorted_ids, e2, e64, n_valid, queries, lut,
                         k=K, select="fast2", cap=cap, planes=2))
        report(f"cascade s={stride} cap={cap} planes=2", dt,
               {"residual_uncert": int((~np.asarray(cc)).sum())})
        del e2
    return 0


if __name__ == "__main__":
    sys.exit(main())
