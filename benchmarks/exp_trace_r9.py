"""Tracing on-cost on the 8192-wave search round (round 9 tentpole).

The ISSUE-4 acceptance gate: with distributed tracing sampled-on (a
root trace context active around the wave — the recipe PARITY gives
for settling the OPEN bounds), the 8192-wave iterative-search round
must cost < 3% over the tracer-disabled run — inside the band
`captures/telemetry_overhead.json` established — and with sampling
OFF (tracer enabled but no context active, the production idle state)
the cost must be unmeasurable (< 0.5%).  The instrumentation is
host-side only: the wave/round spans are recorded from the envelope's
already-measured elapsed AFTER the compiled computation returns, so
the expectation is noise-level; this driver measures both modes and
commits the result as ``captures/trace_overhead.json``.

Methodology: all modes run the SAME compiled executable, interleaved
over ``--reps`` trips with the mode ORDER ROTATING per rep (a fixed
order aliases against periodic background load on shared hosts), and
the committed pair is the MEDIAN OF PER-REP PAIRED differences —
each rep holds all three modes inside a ~3 s window, so pairing
cancels load drift on any longer timescale, where per-mode aggregates
on this host ride a ~±0.8% neighbor-noise floor.  Telemetry stays ON
in every mode (its cost is the r8 capture's number); only the tracer
toggles.  Mode deltas go through ``telemetry.snapshot_diff``
to assert the instrumentation actually fired (sampled mode) or stayed
silent (disabled mode).

Usage::

    python benchmarks/exp_trace_r9.py --save        # writes capture
    python benchmarks/exp_trace_r9.py --smoke       # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/trace_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert sampled overhead < 10%% (generous CI "
                        "band; the committed capture documents the "
                        "tight numbers)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry, tracing
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(9)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    tr = tracing.get_tracer()
    reg = telemetry.get_registry()
    reg.enabled = True                      # telemetry ON in every mode

    # wave spans are context-gated (core/search.py record_wave): the
    # sampled mode activates a fresh root per trip — the full traced
    # path, activation included — while "unsampled" is the production
    # idle state (tracer enabled, no ambient context, nothing records)
    def set_mode(mode: str) -> None:
        tr.enabled = mode != "off"

    def trip(mode: str) -> float:
        set_mode(mode)
        ctx = (tracing.TraceContext.new_root() if mode == "sampled"
               else None)
        t0 = time.perf_counter()
        with tracing.activate(ctx):
            out = simulate_lookups(sorted_ids, n_valid, targets,
                                   alpha=3, k=8, lut=lut, state_limbs=2)
            jax.block_until_ready(out)
        return time.perf_counter() - t0

    # shared warmup: one executable serves all modes
    for mode in ("sampled", "unsampled", "off"):
        trip(mode)

    # instrumentation sanity via snapshot_diff + the ring
    tr.clear()
    before = reg.snapshot()
    trip("sampled")
    d = telemetry.snapshot_diff(before, reg.snapshot())
    waves = [s for s in tr.spans() if s["name"] == "dht.search.wave"]
    assert waves, "sampled mode recorded no wave span"
    assert any(k.startswith("dht_search_wave_seconds")
               for k in d["histograms"]), "telemetry envelope silent"
    tr.clear()
    trip("unsampled")
    assert not tr.spans(), "unsampled mode recorded spans"

    # mode order ROTATES per rep: a fixed order aliases against periodic
    # background load on shared hosts (one run measured the do-less
    # "unsampled" mode 9% dearer than "sampled" purely from load landing
    # on the same slot every rep); rotation decorrelates it
    times: dict = {"off": [], "unsampled": [], "sampled": []}
    order = ["off", "unsampled", "sampled"]
    for i in range(args.reps):
        for mode in order[i % 3:] + order[:i % 3]:
            times[mode].append(trip(mode))
    set_mode("sampled")

    # headline pair = MEDIAN OF PER-REP PAIRED relative differences:
    # each rep runs all three modes within a ~3 s window, so the paired
    # per-rep delta cancels background-load drift on any longer
    # timescale — per-mode aggregate medians/mins on this shared host
    # ride a ~±0.8% neighbor-noise floor and repeatedly measured the
    # do-less "unsampled" mode ABOVE "sampled" (physically impossible
    # as signal).  Per-mode medians stay in the record so that floor
    # is visible next to the paired estimate.
    on_pct = float(np.median([(s - o) / o for s, o in
                              zip(times["sampled"], times["off"])])) * 100
    off_pct = float(np.median([(u - o) / o for u, o in
                               zip(times["unsampled"], times["off"])])) * 100
    med = {m: float(np.median(v) * 1e3) for m, v in times.items()}
    rec = {
        "name": "trace_overhead",
        "value": round(on_pct, 3),
        "unit": "percent",
        "sampling_off_pct": round(off_pct, 3),
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_sampled": round(med["sampled"], 3),
        "wave_ms_unsampled": round(med["unsampled"], 3),
        "wave_ms_disabled": round(med["off"], 3),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips (per-mode "
                "medians also recorded): traced (root context active, "
                "wave+round spans recorded) / enabled-but-untraced vs "
                "tracer disabled (host-side envelope only; same "
                "executable; telemetry on in all modes)",
    }
    dc.emit(rec)

    if args.save:
        dc.write_capture("trace_overhead", rec)

    if args.smoke and on_pct >= 10.0:
        print("trace overhead %.2f%% exceeds the 10%% smoke band"
              % on_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
