"""Flight-data-recorder on-cost on the 8192-wave search round (round 17).

The round-17 acceptance gate: with a :class:`~opendht_tpu.history.
MetricsHistory` ticking once per wave (a far HIGHER cadence than the
production 1 Hz scheduler tick against ~100 ms waves — deliberately
conservative) AND the on-disk spill armed, the 8192-wave
iterative-search round must cost < 1% over the recorder-free run.  The
recorder is host-side snapshot subtraction only — it walks the registry
families, deltas counters/histogram buckets against the previous tick
and appends one bounded frame; it never touches the device — so the
expectation is noise-level.  Measured with the round-9 paired-delta
methodology and committed as ``captures/history_overhead.json``.

Methodology: both modes run the SAME compiled executable, interleaved
over ``--reps`` trips with the mode order rotating per rep, and the
committed number is the MEDIAN OF PER-REP PAIRED differences (pairing
cancels background-load drift on shared hosts; per-mode medians stay in
the record so the noise floor is visible).  The driver also pins the
wave outputs bit-identical between a ticked+spilled trip and an
untouched trip — the "kernels stay bit-identical with the history tick
+ spill on" acceptance line, checked again in tests/test_history.py.

Usage::

    python benchmarks/exp_history_r17.py --save     # writes capture
    python benchmarks/exp_history_r17.py --smoke    # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/history_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert recorder overhead < 5%% (generous CI "
                        "band; the committed capture documents the "
                        "tight number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.history import HistoryConfig, MetricsHistory
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(17)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()
    reg.enabled = True                      # telemetry ON in both modes
    spill_dir = tempfile.mkdtemp(prefix="odt-history-spill-")
    import atexit
    import shutil
    atexit.register(shutil.rmtree, spill_dir, ignore_errors=True)
    rec = MetricsHistory(
        HistoryConfig(period=1.0, capacity=512, spill_dir=spill_dir,
                      spill_segment_frames=64, spill_max_segments=4),
        registry=reg)
    # give the recorder live series to delta over, as a serving node
    # would have: op counters advance once per wave
    ops_true = reg.counter("dht_ops_total", op="get", ok="true")
    op_hist = reg.histogram("dht_op_seconds", op="get")

    def trip(mode: str) -> float:
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        jax.block_until_ready(out)
        if mode == "ticked":
            ops_true.inc(W)
            op_hist.observe(0.01)
            rec.tick()
        return time.perf_counter() - t0

    # shared warmup: one executable serves both modes
    for mode in ("ticked", "off"):
        trip(mode)

    # bit-identity: a ticked+spilled trip and an untouched trip return
    # the same arrays (the recorder never touches the device)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    rec.tick()
    ticked = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(ticked)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the history tick enabled"
    del base, ticked

    # round 19: the rotation-interleaved loop + paired-median math moved
    # to the ONE shared estimator every overhead driver uses
    pd = dc.paired_delta(trip, args.reps, modes=("off", "ticked"))

    # recorder sanity: the timed ticks' frames carry the per-wave deltas
    assert rec.frames(), "recorder appended no frames"
    assert any('dht_ops_total{ok="true",op="get"}' in f["counters"]
               for f in rec.frames())

    on_pct = pd["on_pct"]
    med = pd["med_ms"]
    rec_doc = {
        "name": "history_overhead",
        "value": round(on_pct, 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_ticked": round(med["ticked"], 3),
        "wave_ms_off": round(med["off"], 3),
        "frames_recorded": len(rec.frames()),
        "spill_segments": rec.spill_segments,
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips: flight data "
                "recorder ticking once per wave (full-registry delta "
                "frame + on-disk spill armed, live op counters "
                "advancing) vs no recorder; same executable, "
                "telemetry on in both modes; wave outputs pinned "
                "bit-identical",
    }
    dc.emit(rec_doc)

    if args.save:
        dc.write_capture("history_overhead", rec_doc)

    if args.smoke and on_pct >= 5.0:
        print("history overhead %.2f%% exceeds the 5%% smoke band"
              % on_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
