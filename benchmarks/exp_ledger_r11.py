"""Kernel-cost-ledger on-cost on the 8192-wave search round (round 11).

The ISSUE-6 acceptance gate: with the kernel cost ledger computed and
its one hot-path-adjacent hook live (``profiling.wave_attrs`` inside
``core/search.py record_wave`` — the device-cost attributes folded onto
traced ``dht.search.wave`` spans), the 8192-wave iterative-search round
must cost < 1% over the ledger-disabled run.  The ledger lowers
SEPARATE canonical-shape kernel instances once per process — the
shipping executables are untouched (pinned bit-identical in
tests/test_profiling.py) — so the steady-state expectation is a dict
lookup + a handful of float ops per wave, i.e. noise-level; this
driver measures it and commits ``captures/ledger_overhead.json``.

Methodology: exp_trace_r9's paired-delta estimator verbatim — both
modes run the SAME compiled executable with tracing sampled-on (a root
context active, so record_wave takes its fullest path in both arms)
and telemetry on; the ONLY toggle is ``KernelLedger.enabled`` (the
off-arm short-circuits ``computed()`` exactly like a process that
never computed the ledger).  Trips interleave with the mode order
rotating per rep, and the committed number is the MEDIAN OF PER-REP
PAIRED relative differences, which cancels background-load drift on
any timescale longer than one rep (~2 s window).

Usage::

    python benchmarks/exp_ledger_r11.py --save     # writes capture
    python benchmarks/exp_ledger_r11.py --smoke    # CI band check (<5%)
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/ledger_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert ledger overhead < 5%% (generous CI band; "
                        "the committed capture documents the tight "
                        "number against the <1%% acceptance)")
    dc.add_profile_arg(p)
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import profiling, telemetry, tracing
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    k1, k2 = jax.random.split(jax.random.PRNGKey(11))
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()
    reg.enabled = True
    tr = tracing.get_tracer()
    tr.enabled = True

    # the wave_attrs scaling source: only the simulate_lookups entry is
    # consulted on the hot path, so the overhead arm computes just it
    # (the full ledger is a superset of cached dicts — identical lookup)
    led = profiling.get_ledger()
    led.compute(["simulate_lookups"])

    def trip(mode: str) -> float:
        led.enabled = mode == "ledger"
        ctx = tracing.TraceContext.new_root()
        t0 = time.perf_counter()
        with tracing.activate(ctx):
            out = simulate_lookups(sorted_ids, n_valid, targets,
                                   alpha=3, k=8, lut=lut, state_limbs=2)
            jax.block_until_ready(out)
        return time.perf_counter() - t0

    # shared warmup: one executable serves both modes
    trip("ledger")
    trip("off")

    # instrumentation sanity: the ledger arm must actually attach the
    # cost attrs to the wave span, the off arm must not
    tr.clear()
    trip("ledger")
    waves = [s for s in tr.spans() if s["name"] == "dht.search.wave"]
    assert waves and "est_device_bytes" in waves[-1]["attrs"], \
        "ledger mode recorded no device-cost attrs on the wave span"
    tr.clear()
    trip("off")
    waves = [s for s in tr.spans() if s["name"] == "dht.search.wave"]
    assert waves and "est_device_bytes" not in waves[-1]["attrs"], \
        "off mode leaked device-cost attrs"

    times: dict = {"off": [], "ledger": []}
    order = ["off", "ledger"]
    with dc.profile_ctx(args.profile):
        for i in range(args.reps):
            for mode in order[i % 2:] + order[:i % 2]:
                times[mode].append(trip(mode))
    led.enabled = True

    on_pct = float(np.median([(s - o) / o for s, o in
                              zip(times["ledger"], times["off"])])) * 100
    med = {m: float(np.median(v) * 1e3) for m, v in times.items()}
    rec = {
        "name": "ledger_overhead",
        "value": round(on_pct, 3),
        "unit": "percent",
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_ledger": round(med["ledger"], 3),
        "wave_ms_off": round(med["off"], 3),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips: kernel cost "
                "ledger computed + wave_attrs live on the traced "
                "record_wave path vs KernelLedger.enabled=False (same "
                "executable; telemetry + tracing sampled-on in both "
                "modes — only the ledger hook toggles)",
    }
    dc.emit(rec)

    if args.save:
        dc.write_capture("ledger_overhead", rec)

    if args.smoke and on_pct >= 5.0:
        print("ledger overhead %.2f%% exceeds the 5%% smoke band"
              % on_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
