"""Load-aware resharding acceptance driver (ISSUE-17, round 21).

The t-sharded table splits the sorted id space into ~equal ROW slices
(parallel/partition.py), which a Zipf-skewed workload defeats: the
shards owning the hot keys serve most of the traffic while the rest
idle.  Round 21 closes the loop — the keyspace observatory's 256-bin
load histogram feeds ``solve_shard_boundaries`` /
``solve_shard_edges`` (blended with row counts by
``rebalance_load_weight``) and the node hot-swaps the shard state at
the solved traffic-weighted boundaries (row movement + per-shard LUT
rebuild, never a re-sort).

This driver measures exactly that trade at ``t ∈ {2, 4}`` under a
Zipf(1.1) stream whose hot keys concentrate in the low ring:

  before    the histogram folded at the UNIFORM ring split — the
            max/mean per-shard load the seed layout serves
  after     the SAME histogram refolded at the solved edges
            (λ = 0.9) — what the ``dht_shard_imbalance`` gauge
            converges to after the swap
  swap_ms   wall-clock of the serving-path state rebuild
            (core/table.py ``Snapshot._shard_state`` with a layout:
            host row movement + declarative placement), the cost a
            swap adds to the NEXT wave
  build_ms  the tp engine-state rebuild (``shard_table_state`` with
            boundaries: row movement + the weighted per-shard LUT
            rebuild launch — the ``reshard_state_build`` cost-gate
            kernel)

Bit-identity is asserted in the same run, both halves of the
acceptance pin: the weighted engine state drives
``tp_simulate_lookups`` to the single-device engine's exact outputs,
and the Snapshot serving path answers identically unsharded /
uniform-sharded / layout-sharded — INCLUDING a wave launched before
the swap and consumed after it (the round-20 pipeline's in-flight
case).

``--capture reshard_balance`` writes captures/reshard_balance.json;
README/PARITY quote the t=4 imbalance drop under
``<!-- capture:reshard_balance -->`` (ci/check_docs.py enforces the
quotes both directions).  ``--smoke`` is the CI form: small table,
asserts before > 2.0 and after < 1.3 at t=4, both bit-identity pins,
and a generous swap-latency band via the perf gate's timing records.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

ZIPF_A = 1.1
LOAD_WEIGHT = 0.9
#: hot pool keys land spread over this many low-ring bins, so the
#: uniform split concentrates them on shard 0 at t<=4 (256/t bins per
#: shard) while the solver still has within-range structure to cut
HOT_BINS = 32
HOT_RANKS = 96


def _zipf_hist(pool_n: int, total: int, seed: int = 41) -> np.ndarray:
    """The 256-bin load histogram of a Zipf(1.1) stream over a pool
    whose top-ranked keys live in the low ring (bins 0..HOT_BINS-1) —
    the shape the keyspace observatory hands the rebalance tick."""
    rng = np.random.default_rng(seed)
    top_byte = rng.integers(0, 256, size=pool_n).astype(np.int64)
    top_byte[:HOT_RANKS] = np.arange(HOT_RANKS) % HOT_BINS
    ranks = np.arange(1, pool_n + 1)
    p = 1.0 / ranks ** ZIPF_A
    p /= p.sum()
    draws = rng.choice(pool_n, size=total, p=p)
    return np.bincount(top_byte[draws], minlength=256).astype(np.int64)


def _bin_rows(sorted_ids, n: int) -> np.ndarray:
    top = np.asarray(sorted_ids[:, 0]).astype(np.int64)
    edges_v = np.arange(1, 256, dtype=np.int64) << 24
    counts = np.searchsorted(top[:n], edges_v, side="left")
    return np.diff(np.concatenate([[0], counts, [n]]))


def _measure_t(t: int, hist, sorted_ids, perm, n_valid, queries,
               reps: int) -> dict:
    import jax
    import jax.numpy as jnp
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.core.table import Snapshot
    from opendht_tpu.keyspace import bin_edges_uniform, fold_bins, _imbalance
    from opendht_tpu.parallel.partition import (
        shard_table_state, solve_shard_boundaries, solve_shard_edges)
    from opendht_tpu.parallel.sharded import make_mesh, tp_simulate_lookups
    from opendht_tpu.reshard import ReshardLayout

    n = int(n_valid)
    loads_before = fold_bins(hist, bin_edges_uniform(t))
    imb_before = _imbalance(loads_before)
    edges = solve_shard_edges(hist, t, load_weight=LOAD_WEIGHT)
    loads_after = fold_bins(hist, list(edges))
    imb_after = _imbalance(loads_after)

    mesh = make_mesh(t, q=1, t=t)
    bnd = solve_shard_boundaries(_bin_rows(sorted_ids, n), hist, t,
                                 load_weight=LOAD_WEIGHT)

    # ---- engine-state bit-identity (tp twin vs single device) + the
    # weighted LUT-rebuild launch cost
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(queries),
                           seed=9)
    build_ms = []
    state = None
    for _ in range(max(reps, 1) + 1):           # first rep warms compile
        t0 = time.perf_counter()
        state = shard_table_state(mesh, np.asarray(sorted_ids), n_valid,
                                  boundaries=bnd)
        jax.block_until_ready(state.arrays["local_lut"])
        build_ms.append((time.perf_counter() - t0) * 1e3)
    out = tp_simulate_lookups(mesh, targets=queries, seed=9, state=state)
    bit_identical = all(
        np.array_equal(np.asarray(out[k2]), np.asarray(ref[k2]))
        for k2 in ("nodes", "hops", "converged", "dist"))

    # ---- serving-path identity across the swap (in-flight pinned) +
    # the swap's host cost (row movement + placement)
    lay = ReshardLayout(gen=1, t=t, edges=tuple(float(e) for e in edges),
                        bin_loads=np.asarray(hist, np.int64),
                        load_weight=LOAD_WEIGHT)
    snap = Snapshot(sorted_ids, np.asarray(perm), n_valid, 1, ("k", 0))
    ref_rows, ref_dist = snap.lookup(queries)
    pl_old = snap.lookup_launch(queries, mesh=mesh)          # pre-swap wave
    pl_new = snap.lookup_launch(queries, mesh=mesh, layout=lay)  # the swap
    inflight_identical = True
    for pl in (pl_old, pl_new):
        rows_i, dist_i = pl.consume()
        inflight_identical &= (np.array_equal(rows_i, ref_rows)
                               and np.array_equal(dist_i, ref_dist))
    swap_ms = []
    for _ in range(max(reps, 1)):
        snap._tp_state = None                   # force the rebuild
        snap._reshard_rows = None
        t0 = time.perf_counter()
        placed, _ph = snap._shard_state(mesh, lay)
        jax.block_until_ready(placed["sorted_ids"])
        swap_ms.append((time.perf_counter() - t0) * 1e3)

    return {
        "imbalance_before": round(float(imb_before), 4),
        "imbalance_after": round(float(imb_after), 4),
        "loads_before": [round(float(x), 1) for x in loads_before],
        "loads_after": [round(float(x), 1) for x in loads_after],
        "boundaries": [int(x) for x in bnd],
        "uniform_rows": [-(-n * i // t) for i in range(1, t)],
        "swap_ms": round(float(np.median(swap_ms)), 3),
        "build_ms": round(float(np.median(build_ms[1:])), 3),
        "bit_identical": bool(bit_identical),
        "inflight_identical": bool(inflight_identical),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=16384, help="table rows")
    p.add_argument("-Q", type=int, default=64, help="lookup batch")
    p.add_argument("--draws", type=int, default=120000,
                   help="Zipf stream length")
    p.add_argument("--pool", type=int, default=256, help="Zipf key pool")
    p.add_argument("--reps", type=int, default=9,
                   help="swap-timing reps (median)")
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json")
    p.add_argument("--smoke", action="store_true",
                   help="CI form: small table, acceptance asserts + "
                        "generous swap-latency band")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table

    n_rows, q_n, draws, reps = ((4096, 16, 40000, 3) if args.smoke
                                else (args.N, args.Q, args.draws,
                                      args.reps))
    hist = _zipf_hist(args.pool, draws)
    rng = np.random.default_rng(43)
    ids = rng.integers(0, 2 ** 32, size=(n_rows, 5), dtype=np.uint32)
    sorted_ids, perm, n_valid = sort_table(jnp.asarray(ids))
    queries = rng.integers(0, 2 ** 32, size=(q_n, 5), dtype=np.uint32)

    results = {}
    for t in (2, 4):
        if len(jax.devices()) < t:
            print("exp_reshard_r17: skipping t=%d (%d devices)"
                  % (t, len(jax.devices())))
            continue
        results["t%d" % t] = r = _measure_t(
            t, hist, sorted_ids, perm, n_valid, queries, reps)
        print("t=%d: imbalance %.2f -> %.2f (swap %.2f ms, state build "
              "%.2f ms, bit_identical=%s, inflight=%s)"
              % (t, r["imbalance_before"], r["imbalance_after"],
                 r["swap_ms"], r["build_ms"], r["bit_identical"],
                 r["inflight_identical"]))

    rec = {
        "driver": "exp_reshard_r17",
        "N": n_rows, "Q": q_n, "zipf_a": ZIPF_A, "draws": draws,
        "pool": args.pool, "load_weight": LOAD_WEIGHT,
    }
    rec.update(results)
    if "t4" in results:
        r4 = results["t4"]
        rec["swap_ms"] = r4["swap_ms"]
        # trajectory headline (ci/assemble_trajectory.py convention):
        # the t=4 rebalance factor under the Zipf flood
        rec["metric"] = (
            "load-aware resharding: max/mean shard load imbalance of a "
            "Zipf(%.1f) stream folded at the uniform t=4 split vs the "
            "solved traffic-weighted edges (lambda=%.1f), N=%d, "
            "platform=cpu; value = before/after rebalance factor"
            % (ZIPF_A, LOAD_WEIGHT, n_rows))
        rec["unit"] = "x imbalance reduction, t=4 (cpu)"
        rec["value"] = round(
            r4["imbalance_before"] / r4["imbalance_after"], 2)
    dc.emit(dict(rec))

    for key, r in results.items():
        assert r["bit_identical"], \
            "%s: weighted state diverged from the single-device engine" \
            % key
        assert r["inflight_identical"], \
            "%s: an in-flight wave was remapped across the swap" % key
    if args.smoke or args.capture:
        r4 = results.get("t4")
        assert r4 is not None, \
            "t=4 needs >=4 devices (CI sets " \
            "--xla_force_host_platform_device_count=8)"
        assert r4["imbalance_before"] > 2.0, \
            "Zipf flood read balanced on the uniform split: %r" % (r4,)
        assert r4["imbalance_after"] < 1.3, \
            "solved boundaries left the load imbalanced: %r" % (r4,)

    if args.capture:
        dc.write_capture(args.capture, rec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
