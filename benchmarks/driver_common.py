"""Shared scaffolding for the ``benchmarks/exp_*`` drivers.

Every driver used to hand-roll the same four things — the repo-root
``sys.path`` insert, JSON record printing, ``captures/<name>.json``
writing, and chain-slope reporting — seven copies that drifted
independently (the round-10 driver wrote captures with a trailing
newline, the round-8 one without; half the drivers could not feed the
CI perf gate because their records never hit disk).  This module is
the one copy, and it adds the two hooks the kernel cost ledger's CI
gate rides on:

- :func:`emit` — print one JSON record AND (when
  ``$OPENDHT_TPU_SMOKE_RECORD_DIR`` is set, as ``ci/run_ci.sh`` does)
  merge it into ``<dir>/<driver>.json`` so ``ci/perf_gate.py`` can
  soft-check the smoke timings after the suite ran — one schema for
  every driver's records.
- :func:`profile_ctx` — optional programmatic ``jax.profiler.trace``
  capture around a measured region (``--profile DIR`` via
  :func:`add_profile_arg`), the device-timeline complement to the
  ledger's cost model: host spans (telemetry), wire spans (tracing)
  and XLA device traces then align in one Perfetto load.

Importing this module puts the repo root on ``sys.path`` (the drivers
live in ``benchmarks/`` which is inserted by each driver's two-line
header), so ``from opendht_tpu import ...`` works however the driver
is launched — CLI, heredoc, or ``spec_from_file_location``.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

CAPTURES = os.path.join(ROOT, "captures")


def driver_name(fallback: str = "driver") -> str:
    """The emitting driver's module name (``exp_round_r6`` …) — the
    smoke-record key ``perf_gate``'s ``timing_soft`` entries name.

    Resolved by walking the call stack for the nearest frame that lives
    in this benchmarks/ directory, NOT from ``__main__``: ci/run_ci.sh
    invokes the drivers via ``python - <<PY`` + spec_from_file_location,
    where ``__main__.__file__`` is ``<stdin>`` and the record would
    land under a name no ``timing_soft`` entry ever matches."""
    here = os.path.dirname(os.path.abspath(__file__))
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        base = os.path.basename(fn)
        if (fn and base != "driver_common.py" and not fn.startswith("<")
                and os.path.dirname(os.path.abspath(fn)) == here):
            return os.path.splitext(base)[0]
        f = f.f_back
    main = sys.modules.get("__main__")
    mf = getattr(main, "__file__", None)
    if mf and not mf.startswith("<"):
        return os.path.splitext(os.path.basename(mf))[0]
    return fallback


def emit(rec: dict, name: str | None = None) -> dict:
    """Print ``rec`` as one JSON line (the drivers' existing contract)
    and merge it into the smoke-record file when the CI record dir is
    armed.  Records carrying a ``stage`` key accumulate under a
    ``stages`` map keyed by stage name (so profile_search's six slope
    records all survive in one document); stage-less records merge at
    the top level.  ``perf_gate.check_timing`` looks fields up in both
    places."""
    print(json.dumps(rec), flush=True)
    rec_dir = os.environ.get("OPENDHT_TPU_SMOKE_RECORD_DIR")
    if rec_dir:
        try:
            os.makedirs(rec_dir, exist_ok=True)
            path = os.path.join(rec_dir, (name or driver_name()) + ".json")
            merged = {}
            if os.path.exists(path):
                with open(path) as f:
                    merged = json.load(f)
            if "stage" in rec:
                merged.setdefault("stages", {})[str(rec["stage"])] = rec
            else:
                merged.update(rec)
            with open(path, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
        except Exception:
            pass                # records are advisory; never kill a bench
    return rec


def write_capture(name: str, rec: dict) -> str:
    """Write ``captures/<name>.json`` (the check_docs-enforced artifact
    form: indent=1 + trailing newline, the one the round-10 driver
    settled on)."""
    os.makedirs(CAPTURES, exist_ok=True)
    path = os.path.join(CAPTURES, name + ".json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(f"capture written: {path}")
    return path


def slope_record(stage: str, dt_s: float, **extra) -> dict:
    """One chain-slope measurement as the record schema every driver
    prints: stage name + ms, rounded the way the docs quote them."""
    rec = {"stage": stage, "ms": round(dt_s * 1e3, 3)}
    rec.update(extra)
    return rec


def add_paired_delta_args(parser, reps: int = 15) -> None:
    """The shared CLI surface of :func:`paired_delta` (round 19): every
    overhead driver grew its own ``--reps`` copy; ``--stages`` prints
    the per-stage latency waterfall next to the headline delta so 'the
    overhead moved' and 'WHERE the time goes' come from one run."""
    parser.add_argument("--reps", type=int, default=reps,
                        help="timed trips per mode (interleaved)")
    parser.add_argument("--stages", action="store_true",
                        help="print the per-stage waterfall decomposition "
                             "(dht_stage_seconds p50/p95 + budgets) next "
                             "to the paired delta")


def paired_delta(trip, reps: int, modes=("off", "on")) -> dict:
    """The round-9 paired-delta overhead methodology, extracted to ONE
    copy (round 19 — five drivers had drifted hand-rolled loops): both
    modes run the SAME compiled executable via ``trip(mode) -> seconds``,
    one shared warmup pass per mode, then ``reps`` trips per mode
    interleaved with the mode order rotating per rep (pairing cancels
    background-load drift on shared hosts).  Returns::

        {"on_pct":  median of per-rep (instrumented-baseline)/baseline,
         "med_ms":  {mode: median trip ms},   # the noise floor, visible
         "times":   {mode: [seconds, ...]}}

    ``modes[0]`` is the baseline, ``modes[1]`` the instrumented mode."""
    import numpy as np

    order = list(modes)
    times = {m: [] for m in order}
    for m in order:                          # shared warmup
        trip(m)
    for i in range(reps):
        for m in order[i % len(order):] + order[:i % len(order)]:
            times[m].append(trip(m))
    base, instr = order[0], order[1]
    on_pct = float(np.median(
        [(s - o) / o for s, o in zip(times[instr], times[base])])) * 100
    return {
        "on_pct": on_pct,
        "med_ms": {m: float(np.median(v) * 1e3)
                   for m, v in times.items()},
        "times": times,
    }


def print_stage_waterfall(snapshot: dict) -> None:
    """Human-readable per-stage table off a ``StageProfiler.snapshot()``
    — what ``--stages`` (see :func:`add_paired_delta_args`) prints."""
    print("%-16s %8s %10s %10s %10s" % ("stage", "count", "p50 ms",
                                        "p95 ms", "budget ms"))
    budgets = snapshot.get("budgets", {})
    for stage, d in snapshot.get("stages", {}).items():
        if not d.get("count"):
            continue
        fmt = lambda v: "-" if v is None else "%.3f" % (v * 1e3)  # noqa: E731
        print("%-16s %8d %10s %10s %10.1f"
              % (stage, d["count"], fmt(d.get("p50")), fmt(d.get("p95")),
                 budgets.get(stage, 0.0) * 1e3))


def add_profile_arg(parser) -> None:
    parser.add_argument(
        "--profile", default="", metavar="DIR",
        help="wrap the measured region in a programmatic "
             "jax.profiler.trace capture written to DIR (load in "
             "ui.perfetto.dev; aligns with the telemetry span "
             "TraceAnnotations and the ledger's cost model)")


@contextlib.contextmanager
def profile_ctx(profile_dir: str):
    """``with profile_ctx(args.profile): <measured region>`` — a no-op
    when the flag is empty or the profiler is unavailable (minimal
    containers), a full XLA device-trace capture otherwise."""
    if not profile_dir:
        yield
        return
    try:
        import jax
        prof = jax.profiler.trace(profile_dir)
    except Exception as e:
        print(f"profiler capture unavailable ({e}); running unprofiled")
        yield
        return
    with prof:
        yield
    print(f"jax.profiler trace written to {profile_dir}")
