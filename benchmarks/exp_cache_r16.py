"""Hot-cache probe on-cost on the 8192-wave search round (round 16).

The ISSUE-11 acceptance gate: with the hot-value cache ACTIVE (a full
64-entry device id table) and the batched XOR-compare probe
(``ops/cache_probe.py``) running over every wave's full ``[W]`` target
batch — all MISSES, the worst case: the probe buys nothing and every
target still rides the lookup — the 8192-wave iterative-search round
must cost < 1% over the cache-free run.  Production probes Q<=64-id
ingest waves, so this is a far HIGHER duty cycle than the wave builder
ever pays; a hit only makes the economics better (it removes a whole
lookup).  Measured with the round-9 paired-delta methodology
(benchmarks/exp_trace_r9.py) and committed as
``captures/cache_overhead.json``.

Methodology: both modes run the SAME compiled wave executable,
interleaved over ``--reps`` trips with the mode order rotating per rep,
and the committed number is the MEDIAN OF PER-REP PAIRED differences
(pairing cancels background-load drift on shared hosts).  The driver
also pins the wave outputs bit-identical between a probed and an
untouched trip — the "kernels stay bit-identical with the cache
enabled" acceptance line, checked again in tests/test_hotcache.py.

Usage::

    python benchmarks/exp_cache_r16.py --save     # writes capture
    python benchmarks/exp_cache_r16.py --smoke    # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/cache_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert observed overhead < 5%% (generous CI "
                        "band; the committed capture documents the "
                        "tight number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.core.value import Value
    from opendht_tpu.hotcache import HotCacheConfig, HotValueCache
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.ops.ids import ids_to_bytes
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(16)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    raw = ids_to_bytes(np.asarray(targets))
    target_hashes = [InfoHash(raw[i].tobytes()) for i in range(W)]
    eligible = [True] * W

    telemetry.get_registry().enabled = True      # telemetry ON in both modes
    cache = HotValueCache(HotCacheConfig())
    # fill the table to capacity with DISJOINT hot keys (deterministic
    # names, none of them a wave target): every probe is the all-miss
    # worst case against a full device table
    cache.on_keyspace_tick([
        {"_key": bytes(InfoHash.get("cache-r16-hot-%d" % i)),
         "estimate": 1000 - i, "share": 0.1, "hot": True}
        for i in range(cache.cfg.capacity)])
    # on_keyspace_tick admits nothing without local values — seed
    # entries through offer() instead (the fill-on-get path)
    for i in range(cache.cfg.capacity):
        cache.offer(InfoHash.get("cache-r16-hot-%d" % i),
                    [Value(b"x", value_id=i + 1)])
    assert cache.active() and \
        cache.snapshot()["occupancy"] == cache.cfg.capacity

    def trip(mode: str) -> float:
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        if mode == "probed":
            served = cache.probe_wave(target_hashes, eligible)
            assert not any(v is not None for v in served)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    # shared warmup: one executable serves both modes (and the probe
    # kernel compiles outside the timed region)
    for mode in ("probed", "off"):
        trip(mode)

    # bit-identity: a probed trip and an untouched trip return the same
    # arrays (the probe is a SEPARATE launch over separate operands —
    # it never touches the wave computation)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    cache.probe_wave(target_hashes, eligible)
    probed = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(probed)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the cache probe enabled"
    del base, probed

    times: dict = {"off": [], "probed": []}
    order = ["off", "probed"]
    for i in range(args.reps):
        for mode in order[i % 2:] + order[:i % 2]:
            times[mode].append(trip(mode))

    on_pct = float(np.median([(s - o) / o for s, o in
                              zip(times["probed"], times["off"])])) * 100
    med = {m: float(np.median(v) * 1e3) for m, v in times.items()}
    rec = {
        "name": "cache_overhead",
        "value": round(on_pct, 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "cache_capacity": cache.cfg.capacity,
        "wave_ms_probed": round(med["probed"], 3),
        "wave_ms_off": round(med["off"], 3),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips: the hot-cache "
                "probe (one batched XOR-compare launch of the FULL [W] "
                "target batch against a full %d-entry device table, "
                "all misses — the worst case, where the probe buys "
                "nothing) vs no cache; same executable, telemetry on "
                "in both modes; wave outputs pinned bit-identical"
                % cache.cfg.capacity,
    }
    dc.emit(rec)

    if args.save:
        dc.write_capture("cache_overhead", rec)

    if args.smoke and on_pct >= 5.0:
        print("cache-probe overhead %.2f%% exceeds the 5%% smoke band"
              % on_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
