"""Round-body cost attribution for the iterative engine (ask 1).

The stage-chain profile (profile_search.py) measures pieces in
isolation, where XLA's loop-invariant hoisting can elide work it cannot
elide inside the real wave; the numbers did not reconcile with the
measured wave.  Here each variant runs the REAL round body in a
fixed-trip ``fori_loop`` (10 rounds, no convergence exit) with one
piece disabled, so (full − variant) attributes cost inside the real
compiled loop, fusion effects included.  Exploration tool.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.ids import N_LIMBS
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)
    from opendht_tpu.core import search as SE

    _U32 = jnp.uint32
    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 100_000
    W = 16_384 if on_accel else 1_024
    NL, ALPHA, S, K = 2, 3, 14, 8
    R = ALPHA * K
    ROUNDS = 10

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets0 = jax.random.bits(k2, (W, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    n = jnp.asarray(n_valid, jnp.int32)

    def make_wave(variant):
        def wave(targets, sorted_ids, lut):
            lower = SE._guarded_lower_bound(sorted_ids, n, lut)
            sorted_t = sorted_ids.T

            def gather_planar(rows, limbs=N_LIMBS):
                cl = jnp.clip(rows, 0, N - 1).reshape(-1)
                g = jnp.take(sorted_t[:limbs], cl, axis=1)
                return [g[l].reshape(rows.shape) for l in range(limbs)]

            Q = targets.shape[0]
            seed_u = _U32(1)
            q_index = jnp.arange(Q, dtype=jnp.int32)
            pos_t_full = lower(targets)

            def reply_gather(tgt, pt, qidx, x_rows, round_no):
                Wd = tgt.shape[0]
                if variant == "no_xl_gather":
                    b = jnp.full((Wd, x_rows.shape[1]), 8, jnp.int32)
                else:
                    x_l = gather_planar(x_rows, N_LIMBS)
                    t_l = [tgt[:, l:l + 1] for l in range(N_LIMBS)]
                    b = SE._common_bits_planar(x_l, t_l)
                if variant == "no_block_bounds":
                    lo = jnp.zeros_like(b)
                    ub = jnp.full_like(b, 1 << 20)
                else:
                    prefix_len = jnp.clip(b + 1, 0, SE.ID_BITS)
                    lo, ub = SE._prefix_block_bounds(
                        lower, n, tgt[:, None, :].repeat(x_rows.shape[1], 1),
                        prefix_len)
                size = jnp.maximum(ub - lo, 0)
                qi = qidx.astype(_U32)[:, None, None]
                ai = jnp.arange(x_rows.shape[1], dtype=_U32)[None, :, None]
                ji = jnp.arange(K, dtype=_U32)[None, None, :]
                ctr = (((round_no.astype(_U32) * _U32(Q) + qi) * _U32(ALPHA)
                        + ai) * _U32(K) + ji) ^ seed_u
                h = SE._mix32(ctr)
                blk = lo[..., None] + (
                    h % jnp.maximum(size[..., None], 1).astype(_U32)
                ).astype(jnp.int32)
                base = jnp.clip(pt[:, None, None] - R // 2, 0,
                                jnp.maximum(n - R, 0))
                fb = jnp.clip(base + (ai * _U32(K) + ji).astype(jnp.int32),
                              0, jnp.maximum(n - 1, 0))
                rows = jnp.where((size[..., None] >= K), blk, fb)
                rows = jnp.where((x_rows >= 0)[..., None], rows, -1)
                return rows.reshape(Wd, R)

            def merge(tgt, cand_node, cand_l, queried, new_rows):
                Wd = tgt.shape[0]
                if variant == "no_reply_gather":
                    new_l = [jnp.zeros((Wd, R), _U32) for _ in range(NL)]
                else:
                    new_l = gather_planar(new_rows, NL)
                node = jnp.concatenate([cand_node, new_rows], axis=1)
                d_l = [jnp.concatenate(
                    [cand_l[l], new_l[l] ^ tgt[:, l:l + 1]], axis=1)
                    for l in range(NL)]
                qd = jnp.concatenate([queried,
                                      jnp.zeros((Wd, R), jnp.int32)], axis=1)
                inv = (node < 0).astype(jnp.int32)
                big = jnp.uint32(0xFFFFFFFF)
                d_l = [jnp.where(inv == 0, dl, big) for dl in d_l]
                out = lax.sort((inv,) + tuple(d_l) + (node, 1 - qd),
                               dimension=1, num_keys=3 + NL)
                inv_s, node_s = out[0], out[1 + NL]
                qd_s = 1 - out[2 + NL]
                if variant == "no_dedup_sort":
                    present = inv_s[:, :S] == 0
                    node_f = jnp.where(present, node_s[:, :S], -1)
                    d_f = [jnp.where(present, out[1 + l][:, :S], big)
                           for l in range(NL)]
                    qd_f = qd_s[:, :S] * present
                    return node_f, d_f, qd_f
                dup = jnp.concatenate(
                    [jnp.zeros((Wd, 1), bool),
                     (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)],
                    axis=1)
                inv2 = jnp.where(dup, 1, inv_s)
                out2 = lax.sort(
                    (inv2,) + tuple(out[1:1 + NL]) + (node_s, 1 - qd_s),
                    dimension=1, num_keys=2 + NL)
                present = out2[0][:, :S] == 0
                node_f = jnp.where(present, out2[1 + NL][:, :S], -1)
                d_f = [jnp.where(present, out2[1 + l][:, :S], big)
                       for l in range(NL)]
                qd_f = (1 - out2[2 + NL])[:, :S] * present
                return node_f, d_f, qd_f

            boot = jnp.full((Q, ALPHA), -1, jnp.int32).at[:, 0].set(
                (SE._mix32(q_index.astype(_U32) ^ seed_u)
                 % jnp.maximum(n, 1).astype(_U32)).astype(jnp.int32))
            cand_node = jnp.full((Q, S), -1, jnp.int32)
            cand_l = [jnp.full((Q, S), 0xFFFFFFFF, _U32) for _ in range(NL)]
            queried = jnp.zeros((Q, S), jnp.int32)
            first = reply_gather(targets, pos_t_full, q_index, boot,
                                 jnp.int32(0))
            cand_node, cand_l, queried = merge(targets, cand_node, cand_l,
                                               queried, first)

            def body(rnd, state):
                cand_node, cand_l, queried = state
                can = (cand_node >= 0) & (queried == 0)
                rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
                sel = can & (rank <= ALPHA)
                if variant == "no_alpha_select":
                    x_rows = cand_node[:, :ALPHA]
                else:
                    x_rows = jnp.stack(
                        [jnp.max(jnp.where(sel & (rank == j + 1),
                                           cand_node, -1), axis=1)
                         for j in range(ALPHA)], axis=1)
                new_rows = reply_gather(targets, pos_t_full, q_index,
                                        x_rows, rnd + 1)
                queried = jnp.where(sel, 1, queried)
                cand_node, cand_l, queried = merge(
                    targets, cand_node, cand_l, queried, new_rows)
                return cand_node, cand_l, queried

            cand_node, cand_l, queried = lax.fori_loop(
                0, ROUNDS, body, (cand_node, cand_l, queried))
            return (jnp.sum(cand_node[:, :K].astype(jnp.float32)) * 1e-9
                    + jnp.sum(queried.astype(jnp.float32)) * 1e-9)
        return wave

    variants = ["full", "no_dedup_sort", "no_reply_gather",
                "no_block_bounds", "no_xl_gather", "no_alpha_select"]
    base = None
    for v in variants:
        dt = chain_slope(make_wave(v), targets0, sorted_ids, lut,
                         r1=1, r2=4)
        rec = {"variant": v, "ms": round(dt * 1e3, 2),
               "ms_per_round": round(dt * 1e3 / ROUNDS, 3)}
        if v == "full":
            base = dt
        elif base:
            rec["saves_ms"] = round((base - dt) * 1e3, 2)
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
