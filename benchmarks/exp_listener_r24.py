"""Listener-table amortization + on-cost on the wave round (round 24).

The ISSUE-20 acceptance gates, two captures from one driver:

1. ``captures/listener_match.json`` — the AMORTIZATION claim.  The
   dhtchat shape: ONE hot key with L subscribed listeners and an
   S=64-put wave flooding it.  The pre-round-24 host path dispatches
   per put — walk the key's listener records and invoke every callback
   with ``[value]``, S×L dispatches per wave (the exact
   ``_storage_changed`` synchronous body).  The batched path buffers
   the wave, answers membership with ONE ``listener_match`` launch and
   dispatches ONE coalesced callback per listener with the wave's
   whole value batch — L dispatches.  Committed: the per-listener
   per-wave cost SLOPE of both modes over L∈{1k,10k,100k} (linear fit)
   — batched must sit far below host (it coalesces S dispatches into
   one), plus the raw match-launch latency at table sizes
   L∈{1k,10k,100k} (the on-chip scaling row toward the OPEN
   million-listener bound, perf_budgets.json ``listener_wave_1m``).

2. ``captures/listener_overhead.json`` — the ON-COST claim.  With the
   table ACTIVE at full capacity (1024 live rows) and every wave
   paying the worst case — 64 buffered stored puts, all MISSES (the
   match launch buys nothing), one flush per trip — the 8192-wave
   iterative-search round must cost < 1% over the table-free run.
   Round-9 paired-delta methodology (exp_trace_r9/exp_cache_r16):
   interleaved trips, rotating mode order, median of per-rep paired
   differences; wave outputs pinned bit-identical (the match launch
   runs over separate operands and never touches the wave
   computation).

Usage::

    python benchmarks/exp_listener_r24.py --save     # writes captures
    python benchmarks/exp_listener_r24.py --smoke    # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

S_WAVE = 64                        # canonical ingest fill target


def measure_amortization(Ls, reps: int) -> dict:
    """Per-wave delivery cost, host per-put dispatch vs batched
    coalesced dispatch, at L listeners on one hot key."""
    import jax
    from opendht_tpu.core.listener import LocalListener
    from opendht_tpu.core.value import Value
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.listeners import ListenerTable, ListenerTableConfig
    from opendht_tpu.ops.listener_match import listener_match

    key = bytes(InfoHash.get("listener-r24-hot"))
    values = [Value(b"msg-%03d" % i, value_id=i + 1) for i in range(S_WAVE)]
    rows = []
    for L in Ls:
        sink = []
        cb = sink.append
        listeners = [LocalListener(None, None, lambda vs, exp: cb(len(vs)))
                     for _ in range(L)]

        def host_wave() -> float:
            # the synchronous _storage_changed body, per put: collect
            # the matching callbacks, dispatch [value] to each
            t0 = time.perf_counter()
            for v in values:
                cbs = []
                for l in listeners:
                    if l.filter is None or l.filter(v):
                        cbs.append(l.get_cb)
                for f in cbs:
                    f([v], False)
            return time.perf_counter() - t0

        table = ListenerTable(ListenerTableConfig())
        table.sync_key(key, L)

        def batched_wave() -> float:
            # buffer the wave, ONE match launch, ONE coalesced
            # dispatch per listener (the flush_listener_wave body)
            t0 = time.perf_counter()
            for v in values:
                table.note_stored(key, v, True)
            for kb, items in table.flush():
                new_vals = [v for v, nv in items if nv]
                cbs = []
                for l in listeners:
                    vs = ([v for v in new_vals if l.filter(v)]
                          if l.filter is not None else new_vals)
                    if vs:
                        cbs.append((l.get_cb, vs))
                for f, vs in cbs:
                    f(vs, False)
            return time.perf_counter() - t0

        host_wave(); batched_wave()          # warmup (jit the match)
        host = [host_wave() for _ in range(reps)]
        bat = [batched_wave() for _ in range(reps)]
        assert sink, "no deliveries dispatched"
        rows.append({"L": L,
                     "host_ms": round(float(np.median(host)) * 1e3, 3),
                     "batched_ms": round(float(np.median(bat)) * 1e3, 3)})

    # per-listener per-wave slope, linear fit over the measured L range
    Lv = np.array([r["L"] for r in rows], float)
    slope = {}
    for mode in ("host", "batched"):
        y = np.array([r["%s_ms" % mode] for r in rows], float) * 1e-3
        slope[mode] = float(np.polyfit(Lv, y, 1)[0]) * 1e9   # ns/listener

    # raw match-launch latency vs TABLE size (the device-scaling row):
    # a full [L, 5] id table against the canonical S=64 wave, all miss
    launch_rows = []
    rng = np.random.default_rng(24)
    stored = rng.integers(0, 2**32, (S_WAVE, 5), dtype=np.uint32)
    for L in Ls:
        ids = rng.integers(0, 2**32, (L, 5), dtype=np.uint32)
        valid = np.ones(L, bool)
        jax.block_until_ready(listener_match(ids, valid, stored))  # compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(listener_match(ids, valid, stored))
            ts.append(time.perf_counter() - t0)
        launch_rows.append({"L": L,
                            "match_ms": round(float(np.median(ts)) * 1e3,
                                              4)})
    return {"rows": rows, "launch_rows": launch_rows,
            "host_slope_ns_per_listener": round(slope["host"], 1),
            "batched_slope_ns_per_listener": round(slope["batched"], 1)}


def measure_overhead(N: int, W: int, reps: int) -> dict:
    """Paired-delta on-cost of an ACTIVE full table + per-wave all-miss
    flush on the 8192-wave search round (the exp_cache_r16 harness)."""
    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.core.value import Value
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.listeners import ListenerTable, ListenerTableConfig
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    key = jax.random.PRNGKey(24)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    telemetry.get_registry().enabled = True   # telemetry ON in both modes
    lt = ListenerTable(ListenerTableConfig())
    # fill to capacity with DISJOINT listened keys (none a wave put):
    # every flush is the all-miss worst case against a full table
    for i in range(lt.cfg.capacity):
        lt.sync_key(bytes(InfoHash.get("listener-r24-sub-%d" % i)), 1)
    assert lt.tracked() == lt.cfg.capacity
    puts = [(bytes(InfoHash.get("listener-r24-put-%d" % i)),
             Value(b"x", value_id=i + 1)) for i in range(S_WAVE)]

    def trip(mode: str) -> float:
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        if mode == "listener":
            for kb, v in puts:
                lt.note_stored(kb, v, True)
            delivered = lt.flush()
            assert delivered == []           # all miss: nothing delivered
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    for mode in ("listener", "off"):         # shared warmup
        trip(mode)

    # bit-identity: a trip with the buffered flush and an untouched
    # trip return the same arrays (separate launch, separate operands)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for kb, v in puts:
        lt.note_stored(kb, v, True)
    lt.flush()
    probed = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(probed)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the listener table active"
    del base, probed

    times: dict = {"off": [], "listener": []}
    order = ["off", "listener"]
    for i in range(reps):
        for mode in order[i % 2:] + order[:i % 2]:
            times[mode].append(trip(mode))
    on_pct = float(np.median([(s - o) / o for s, o in
                              zip(times["listener"], times["off"])])) * 100
    med = {m: float(np.median(v) * 1e3) for m, v in times.items()}
    return {"on_pct": on_pct, "capacity": lt.cfg.capacity,
            "wave_ms_listener": round(med["listener"], 3),
            "wave_ms_off": round(med["off"], 3)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/listener_match.json + "
                        "captures/listener_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="scaled-down run asserting overhead < 5%% and "
                        "batched slope < host slope (generous CI band; "
                        "the committed captures document the tight "
                        "numbers against the <1%% / ≪ acceptances)")
    args = p.parse_args(argv)

    import jax
    on_accel = jax.devices()[0].platform != "cpu"
    platform = jax.devices()[0].platform

    if args.smoke:
        Ls, reps_a = (1_000, 10_000), 3
        N = args.N or 65_536
        reps_o = min(args.reps, 7)
    else:
        Ls, reps_a = (1_000, 10_000, 100_000), 5
        N = args.N or (1_000_000 if on_accel else 131_072)
        reps_o = args.reps

    amort = measure_amortization(Ls, reps_a)
    rec_match = {
        "name": "listener_match",
        "value": amort["batched_slope_ns_per_listener"],
        "unit": "ns_per_listener_per_wave",
        "host_slope_ns_per_listener":
            amort["host_slope_ns_per_listener"],
        "batched_slope_ns_per_listener":
            amort["batched_slope_ns_per_listener"],
        "slope_ratio": round(
            amort["host_slope_ns_per_listener"]
            / max(amort["batched_slope_ns_per_listener"], 1e-9), 1),
        "wave_puts": S_WAVE,
        "rows": amort["rows"],
        "launch_rows": amort["launch_rows"],
        "platform": platform,
        "note": "dhtchat shape: one hot key, L subscribed listeners, "
                "an S=%d-put wave.  host = the pre-round-24 synchronous "
                "_storage_changed body (per put, walk + dispatch [value] "
                "to every listener: S×L dispatches/wave); batched = "
                "buffer the wave, ONE listener_match launch, ONE "
                "coalesced callback per listener with the value batch "
                "(L dispatches/wave).  Slopes are linear fits of "
                "per-wave cost over L; launch_rows time the raw [%d, L] "
                "match launch vs table size (the scaling row toward the "
                "listener_wave_1m OPEN bound)" % (S_WAVE, S_WAVE),
    }
    dc.emit(rec_match)

    over = measure_overhead(N, args.W, reps_o)
    rec_over = {
        "name": "listener_overhead",
        "value": round(over["on_pct"], 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": args.W, "N": N, "reps": reps_o,
        "listener_capacity": over["capacity"],
        "wave_ms_listener": over["wave_ms_listener"],
        "wave_ms_off": over["wave_ms_off"],
        "platform": platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips: per trip the "
                "ACTIVE table (full %d-entry device id table) buffers "
                "%d stored puts and runs one all-miss flush launch — "
                "the worst case, where the match buys nothing — vs no "
                "table; same executable, telemetry on in both modes; "
                "wave outputs pinned bit-identical"
                % (over["capacity"], S_WAVE),
    }
    dc.emit(rec_over)

    if args.save:
        dc.write_capture("listener_match", rec_match)
        dc.write_capture("listener_overhead", rec_over)

    if args.smoke:
        ok = True
        if over["on_pct"] >= 5.0:
            print("listener-table overhead %.2f%% exceeds the 5%% smoke "
                  "band" % over["on_pct"], file=sys.stderr)
            ok = False
        if not (amort["batched_slope_ns_per_listener"]
                < amort["host_slope_ns_per_listener"]):
            print("batched slope %.1f ns/listener not below host slope "
                  "%.1f" % (amort["batched_slope_ns_per_listener"],
                            amort["host_slope_ns_per_listener"]),
                  file=sys.stderr)
            ok = False
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
