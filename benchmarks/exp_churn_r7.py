"""Churn-round attribution for the LANE-PACKED merge (round 7
tentpole) + the CI churn-merge smoke.

Same fixed-composition (full − variant) methodology as
exp_churn2_r5.py: each variant runs the REAL churn round body — one
device call absorbing E tombstone word writes + E delta appends, the
delta re-sort/expand/LUT, and a Q-query wave through
``churn_lookup_topk`` — with one piece changed, so differences
attribute cost with fusion effects included.  The variants:

  packed      the round at the forced packed width (128//k queries per
              128-lane physical row, ops/sorted_table.
              packed_churn_merge — what merge_pack="auto" resolves to
              on TPU; forced here so the packing is measured on every
              platform)
  unpacked    merge_pack=1 — the pre-round-7 row-per-query merge;
              (unpacked − packed) is the measured lane-packing win at
              this shape, the number VERDICT r5 weak #1 asked for
  no_merge    base lookup + delta cascade, results consumed but never
              merged; (full − no_merge) bounds the whole merge stage
  no_rebuild  pre-built delta structures; (full − no_rebuild) is the
              per-round delta re-sort/expand/LUT cost
  static      same-shape plain lookup, no churn structures — the
              denominator of the churny/static ratio

Unlike exp_round_r6.py's hand-mirrored engine body, the merge under
test here IS the shipping kernel — ``--smoke`` asserts
BIT-IDENTITY of merge_pack="auto" vs merge_pack=1 through
``churn_lookup_topk`` itself (fast3 full-limb keys AND the fast2
top-64 + tie-repair form, on a ragged Q), then a generous 1.5×
regression band on the packed round (min of 2 chain-slope samples per
side, the exp_round_r6 flake filter).  The committed property sweep
(tests/test_table_churn.py::test_packed_merge_bit_identical_sweep)
covers pack width × tombstone density × n_valid edges; the smoke
re-proves the shipping default at CI time and gates the round's
latency.

A full run's numbers feed ``captures/churn_packed.json`` (--capture):
per-variant ms, the packed-vs-unpacked delta, and churny/static under
both merge modes on this platform.  The accelerator target
(churny/static ≥ 0.6×, ISSUE 2) is settled only by an accelerator
session running:

  python benchmarks/exp_churn_r7.py --capture churn_packed
  python benchmarks/baseline_configs.py -c 6     # auto-saves config6

(the second auto-saves captures/config6.json on accelerator runs and
the README/PARITY churn quotes then update from the artifacts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # churn_fixtures + driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

VARIANTS = ("packed", "unpacked", "no_merge", "no_rebuild")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="small-shape CI smoke: packed-vs-unpacked "
                        "bit-identity + regression band only")
    p.add_argument("-N", type=int, default=0, help="base table rows")
    p.add_argument("-Q", type=int, default=0, help="lookup wave width")
    p.add_argument("--dcap", type=int, default=0, help="delta capacity")
    p.add_argument("-E", type=int, default=0, help="mutations per round")
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json with the attribution")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table,
        churn_lookup_topk, expanded_topk, cascade_topk)
    import churn_fixtures as FX

    on_accel = jax.devices()[0].platform != "cpu"
    if args.smoke:
        # ragged Q on purpose: Q % 16 != 0 exercises the sentinel-slot
        # tail of the packed rows through the compiled kernel
        N, Q, DCAP, E = (args.N or 65_536), (args.Q or 4_097), \
            (args.dcap or 4_096), (args.E or 128)
    else:
        N, Q, DCAP = FX.sizes(on_accel, dcap=args.dcap)
        if args.N:
            N = args.N
        if args.Q:
            Q = args.Q
        E = args.E or 256
    K = 8
    d_bits = default_lut_bits(DCAP)

    base = FX.build_base(N, Q, limbs=2)
    sorted_ids, expanded = base["sorted_ids"], base["expanded"]
    lut, n_valid, queries = base["lut"], base["n_valid"], base["queries"]

    mut = FX.build_mutations(N, DCAP, E)
    tomb_base, widx, wval = mut["tomb_base"], mut["widx"], mut["wval"]
    dslab, new_ids = mut["dslab"], mut["new_ids"]
    nd0, nd_after = mut["nd0"], mut["nd_after"]

    ds0, (de0, dew0), dlut0, _dnv0 = FX.build_delta_structs(
        dslab.at[nd0:nd0 + E].set(new_ids), nd0 + E, strides=(16, 64))

    def make_round(variant):
        def round_body(q, sorted_ids, expanded, lut, n_valid, tomb_base,
                       widx, wval, dslab, new_ids, nd_after,
                       ds0, de0, dew0, dlut0):
            tomb = tomb_base.at[widx].set(wval)
            if variant == "no_rebuild":
                ds, de, dew, dlut, dnv = ds0, de0, dew0, dlut0, nd_after
            else:
                ds_slab = lax.dynamic_update_slice(
                    dslab, new_ids, (jnp.int32(nd0), 0))
                dvalid = jnp.arange(DCAP) < nd_after
                ds, _dp, dnv = sort_table(ds_slab, dvalid)
                de = expand_table(ds, stride=16, limbs=2)
                dew = expand_table(ds, stride=64, limbs=2)
                dlut = build_prefix_lut(ds, dnv, bits=d_bits)
            if variant == "no_merge":
                # both sides' lookups run and are consumed, but the
                # merge (the packed sort + unpack) never happens
                _d, enc_b, cert_b = expanded_topk(
                    sorted_ids, expanded, n_valid, q, k=K, select="fast2",
                    lut=lut, lut_steps=0, planes=2, tomb_bits=tomb)
                _dd, enc_d, cert_d = cascade_topk(
                    ds, de, dew, dnv, q, dlut, k=K, select="fast2",
                    cap=4096, planes=2, fast2_limbs=True)
                return (jnp.sum(cert_b.astype(jnp.float32))
                        + jnp.sum(cert_d.astype(jnp.float32))
                        + jnp.sum(enc_b[:, 0].astype(jnp.float32)) * 1e-9
                        + jnp.sum(enc_d[:, 0].astype(jnp.float32)) * 1e-9)
            # force the packed width so the attribution measures the
            # packing on EVERY platform ("auto" resolves to unpacked
            # off-TPU — the backend split this driver's numbers set)
            mp = 1 if variant == "unpacked" else 128 // K
            _dist, enc, cert = churn_lookup_topk(
                sorted_ids, expanded, n_valid, tomb, ds, de, dnv, q,
                lut=lut, d_lut=dlut, d_exp_wide=dew, k=K, select="fast2",
                lut_steps=0, planes=2, d_cap=4096, merge_pack=mp)
            return (jnp.sum(cert.astype(jnp.float32))
                    + jnp.sum(enc[:, 0].astype(jnp.float32)) * 1e-9)
        return round_body

    def static_body(q, sorted_ids, expanded, lut, n_valid):
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  select="fast2", lut=lut, lut_steps=0,
                                  planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(idx[:, 0].astype(jnp.float32)) * 1e-9)

    if args.smoke:
        # 1) packed vs unpacked bit-identity through the SHIPPING
        # kernel, both merge key forms, ragged Q, live tombstones
        tomb = tomb_base.at[widx].set(wval)
        common = dict(lut=lut, d_lut=dlut0, k=K)
        for sel, kw in (("fast2", dict(d_exp_wide=dew0, lut_steps=0,
                                       planes=2, d_cap=4096)),
                        ("fast3", dict())):
            exp_sel = expanded if sel == "fast2" else expand_table(sorted_ids)
            de_sel = de0 if sel == "fast2" else expand_table(ds0, stride=32)
            d1, e1, _ = churn_lookup_topk(
                sorted_ids, exp_sel, n_valid, tomb, ds0, de_sel, nd_after,
                queries, select=sel, merge_pack=1, **common, **kw)
            d2, e2, _ = churn_lookup_topk(
                sorted_ids, exp_sel, n_valid, tomb, ds0, de_sel, nd_after,
                queries, select=sel, merge_pack=128 // K, **common, **kw)
            if not np.array_equal(np.asarray(e1), np.asarray(e2)) or (
                    d1 is not None
                    and not np.array_equal(np.asarray(d1), np.asarray(d2))):
                print(f"SMOKE FAIL: packed merge diverges from unpacked "
                      f"({sel}, Q={Q})")
                return 1
        # 2) regression band: min of 2 slope samples per side filters
        # one-sided host-load stalls (the exp_round_r6 pattern)
        wp, wu = make_round("packed"), make_round("unpacked")
        cargs = (queries, sorted_ids, expanded, lut, n_valid, tomb_base,
                 widx, wval, dslab, new_ids, nd_after, ds0, de0, dew0,
                 dlut0)
        dts_p = [chain_slope(wp, *cargs, r1=1, r2=3) for _ in range(2)]
        dts_u = [chain_slope(wu, *cargs, r1=1, r2=3) for _ in range(2)]
        dt_p, dt_u = min(dts_p), min(dts_u)
        print(json.dumps({
            "smoke": True, "N": N, "Q": Q, "DCAP": DCAP,
            "packed_ms": round(dt_p * 1e3, 3),
            "unpacked_ms": round(dt_u * 1e3, 3),
            "samples_ms": [round(d * 1e3, 2) for d in dts_p + dts_u],
            "bit_identical": True}), flush=True)
        if dt_p > 1.5 * dt_u:
            print(f"SMOKE FAIL: packed churn round {dt_p * 1e3:.2f} ms > "
                  f"1.5x unpacked {dt_u * 1e3:.2f} ms (min of 2 each)")
            return 1
        print("churn-merge smoke ok")
        return 0

    cargs = (queries, sorted_ids, expanded, lut, n_valid, tomb_base,
             widx, wval, dslab, new_ids, nd_after, ds0, de0, dew0, dlut0)
    r1, r2 = (2, 8) if on_accel else (2, 6)
    recs = []
    for v in VARIANTS:
        dt = chain_slope(make_round(v), *cargs, r1=r1, r2=r2)
        recs.append({"variant": v, "ms": round(dt * 1e3, 3)})
        print(json.dumps(recs[-1]), flush=True)
    static_dt = chain_slope(static_body, queries, sorted_ids, expanded,
                            lut, n_valid, r1=r1, r2=r2)
    recs.append({"variant": "static", "ms": round(static_dt * 1e3, 3)})
    print(json.dumps(recs[-1]), flush=True)

    by = {r["variant"]: r["ms"] for r in recs}
    bound = {
        "platform": jax.devices()[0].platform,
        "N": N, "Q": Q, "DCAP": DCAP, "E": E, "k": K,
        "merge_pack_auto": 128 // K,
        # the tentpole's number: what the lane packing saves per round
        "packing_saves_ms": round(by["unpacked"] - by["packed"], 3),
        "merge_stage_ms": round(by["packed"] - by["no_merge"], 3),
        "delta_rebuild_ms": round(by["packed"] - by["no_rebuild"], 3),
        "churny_vs_static_packed": round(by["static"] / by["packed"], 4),
        "churny_vs_static_unpacked": round(by["static"] / by["unpacked"],
                                           4),
    }
    print(json.dumps({"bound": bound}), flush=True)
    if args.capture:
        out = {
            "metric": ("lane-packed churn merge attribution, full-minus-"
                       "variant over the real round body (tombstone "
                       "writes + delta rebuild + churn_lookup_topk), "
                       "Q=%d x N=%d, DCAP=%d, E=%d, k=%d, platform=%s; "
                       "packed vs unpacked merge bit-identity asserted "
                       "through the shipping kernel; value = packed "
                       "round ms (device round only — host prep and "
                       "amortized compaction excluded, unlike config6's "
                       "sustained figure)"
                       % (Q, N, DCAP, E, K, jax.devices()[0].platform)),
            "value": by["packed"],
            "unit": "ms/round (%s)" % jax.devices()[0].platform,
            "vs_baseline": bound["churny_vs_static_packed"],
            "variants": recs,
            "bound": bound,
        }
        if not on_accel:
            out["accelerator_target"] = (
                "churny/static >= 0.6x (ISSUE 2) is OPEN: this capture "
                "is cpu, and the 128-lane padding tax the packed merge "
                "amortizes exists only in TPU tiled layout — on cpu the "
                "slot-segmented sort is expected ~neutral (the "
                "packing_saves_ms field records the measured value).  "
                "Settle it with the two commands in this driver's "
                "docstring on an accelerator session.")
        dc.write_capture(args.capture, out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
