"""Churn-round decomposition (ask 5: config6 ≥0.85× static).

With 2-plane expansions the static lookup dropped to ~10 ms/131K wave,
exposing the delta side as ~2/3 of the churn round.  This measures each
round component on the chip so the rebuild targets the measured cost:
per-round delta re-sort/expand/LUT at several slab tiers, the delta
window lookup at stride 32 vs 16, the 2k merge sort row- vs
column-oriented, and the tombstone overhead on the base side.

Base-table scaffolding comes from benchmarks/churn_fixtures.py (shared
with exp_churn2_r5.py / exp_churn_r7.py since round 7).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # churn_fixtures + driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import (
        sort_table, build_prefix_lut, default_lut_bits, expand_table,
        expanded_topk)
    import churn_fixtures as FX

    on_accel = jax.devices()[0].platform != "cpu"
    N, Q, _dcap = FX.sizes(on_accel)
    K = 8
    base = FX.build_base(N, Q, limbs=2)
    sorted_ids, exp2 = base["sorted_ids"], base["expanded"]
    lut, n_valid, queries = base["lut"], base["n_valid"], base["queries"]
    nwords = (N + 31) // 32
    tomb = jnp.zeros((nwords,), jnp.uint32)

    def report(name, dt):
        print(json.dumps({"stage": name, "ms": round(dt * 1e3, 3)}),
              flush=True)

    # base lookup with and without tombstones
    def base_body(q, sorted_ids, exp2, n_valid, lut):
        d, i, c = expanded_topk(sorted_ids, exp2, n_valid, q, k=K,
                                select="fast2", lut=lut, lut_steps=0,
                                planes=2)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(i[:, 0].astype(jnp.float32)) * 1e-9)

    def base_tomb(q, sorted_ids, exp2, n_valid, lut, tomb):
        d, i, c = expanded_topk(sorted_ids, exp2, n_valid, q, k=K,
                                select="fast2", lut=lut, lut_steps=0,
                                planes=2, tomb_bits=tomb)
        return (jnp.sum(c.astype(jnp.float32))
                + jnp.sum(i[:, 0].astype(jnp.float32)) * 1e-9)

    report("base lookup (static)", chain_slope(
        base_body, queries, sorted_ids, exp2, n_valid, lut, r1=4, r2=16))
    report("base lookup + tomb", chain_slope(
        base_tomb, queries, sorted_ids, exp2, n_valid, lut, tomb,
        r1=4, r2=16))

    for DCAP in (262_144, 65_536, 16_384):
        if not on_accel and DCAP > 65_536:
            continue
        dslab = FX.random_delta_slab(DCAP, seed=100 + DCAP)
        nd = jnp.int32(DCAP // 2)
        d_bits = default_lut_bits(DCAP)

        # per-round delta rebuild: sort + expand + lut
        def rebuild(q, dslab, nd, stride):
            dvalid = jnp.arange(DCAP) < (nd ^ (q[0, 0] & 1).astype(jnp.int32))
            ds, _dp, dnv = sort_table(dslab, dvalid)
            de = expand_table(ds, stride=stride, limbs=2)
            dl = build_prefix_lut(ds, dnv, bits=d_bits)
            return (ds[0, 0].astype(jnp.float32) * 1e-9
                    + de[0, 0].astype(jnp.float32) * 1e-9
                    + dl[1].astype(jnp.float32) * 1e-9)

        for stride in (32, 16):
            dt = chain_slope(
                (lambda s: lambda q, dslab, nd: rebuild(q, dslab, nd, s))(
                    stride),
                queries, dslab, nd, r1=4, r2=16)
            report(f"delta rebuild D={DCAP} s={stride}", dt)

        # delta window lookup
        ds, _dp, dnv = jax.block_until_ready(
            sort_table(dslab, jnp.arange(DCAP) < nd))
        dl = jax.block_until_ready(build_prefix_lut(ds, dnv, bits=d_bits))
        for stride in (32, 16):
            de = jax.block_until_ready(
                expand_table(ds, stride=stride, limbs=2))

            def dlook(q, ds, de, dnv, dl):
                d, i, c = expanded_topk(ds, de, dnv, q, k=K,
                                        select="fast2", lut=dl,
                                        lut_steps=0, planes=2)
                return (jnp.sum(c.astype(jnp.float32))
                        + jnp.sum(i[:, 0].astype(jnp.float32)) * 1e-9)

            dt = chain_slope(dlook, queries, ds, de, dnv, dl, r1=4, r2=16)
            _, _, cert = jax.block_until_ready(
                expanded_topk(ds, de, dnv, queries, k=K, select="fast2",
                              lut=dl, lut_steps=0, planes=2))
            report(f"delta lookup D={DCAP} s={stride} "
                   f"cert={float(np.asarray(cert).mean()):.5f}", dt)
            del de

    # the 2k merge sort: row-wise [Q, 2k] vs transposed [2k, Q]
    km = jax.random.split(jax.random.PRNGKey(9), 3)
    m0 = jax.random.bits(km[0], (Q, 2 * K), dtype=jnp.uint32)
    m1 = jax.random.bits(km[1], (Q, 2 * K), dtype=jnp.uint32)
    enc = jax.random.bits(km[2], (Q, 2 * K), dtype=jnp.uint32) \
        .astype(jnp.int32)

    def merge_row(q, m0, m1, enc):
        o = lax.sort((m0 ^ q[:, :1], m1, enc), dimension=1, num_keys=3)
        return jnp.sum(o[2][:, :K].astype(jnp.float32)) * 1e-9

    def merge_col(q, m0t, m1t, enct):
        o = lax.sort((m0t ^ q[:, 0][None, :], m1t, enct), dimension=0,
                     num_keys=3)
        return jnp.sum(o[2][:K].astype(jnp.float32)) * 1e-9

    report("merge sort [Q,16] row", chain_slope(
        merge_row, queries, m0, m1, enc, r1=64, r2=512))
    report("merge sort [16,Q] col", chain_slope(
        merge_col, queries, m0.T, m1.T, enc.T, r1=64, r2=512))
    return 0


if __name__ == "__main__":
    sys.exit(main())
