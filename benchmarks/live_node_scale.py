"""Live protocol node at DEVICE scale (round-4 verdict ask #3).

One real ``Dht`` node, bulk-loaded with an N-row table (default 1M —
far past the ``HOST_SCAN_MAX_ROWS`` host-scan threshold,
core/table.py:62), serving a concurrent burst of ``find``/``get``
requests over real localhost UDP from a client engine.  Every reply's
closest-node set is resolved through the full stack:

    UDP → NetworkEngine.process_message → Dht._on_find_node/_on_get_values
        → NodeTable.find_closest → Snapshot/ChurnView.lookup (DEVICE)

The run asserts the device path was actually taken (table size over the
host-scan threshold, a built snapshot whose version matches the table,
and a device-lookup call count equal to the burst), then reports
end-to-end served requests/s — the number quoted in README
(<!-- capture:live_node -->).  ``--batched`` additionally measures the
server-side batched resolve path (``find_closest_nodes_batched``) that
a wave of concurrent lookups shares in one device call.

Usage::  python benchmarks/live_node_scale.py [-N 1000000] [-Q 512]
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import select
import socket
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-N", type=int, default=0, help="table rows")
    p.add_argument("-Q", type=int, default=512, help="burst size")
    p.add_argument("--batched", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="measure the server-side batched resolve "
                        "(--no-batched for the per-packet leg only)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu.core import table as table_mod
    from opendht_tpu.core.value import Query
    from opendht_tpu.infohash import InfoHash
    from opendht_tpu.net.engine import EngineCallbacks, NetworkEngine
    from opendht_tpu.runtime.config import Config
    from opendht_tpu.runtime.dht import Dht
    from opendht_tpu.scheduler import Scheduler
    from opendht_tpu.sockaddr import SockAddr

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 100_000)
    Q = args.Q

    # ---- server: a real Dht node over a real UDP socket ----------------
    ssock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    ssock.bind(("127.0.0.1", 0))
    sport = ssock.getsockname()[1]
    ssock.setblocking(False)

    dht = Dht(lambda data, dst: ssock.sendto(data, (str(dst.ip), dst.port))
              and 0,
              Config(max_req_per_sec=1_000_000), has_v6=False)
    table = dht.tables[socket.AF_INET]

    rng = np.random.default_rng(11)
    ids = rng.integers(0, 2 ** 32, size=(N, 5), dtype=np.uint32)
    t0 = time.perf_counter()
    table.bulk_load(ids, dht.scheduler.time(),
                    addrs=SockAddr("10.1.2.3", 4567))
    load_dt = time.perf_counter() - t0
    dht.warmup()                      # compile + build the device snapshot
    snap0 = table._snap
    assert snap0 is not None and len(table) > table_mod.HOST_SCAN_MAX_ROWS

    # count every device lookup through the snapshot/churn view
    lookups = {"n": 0, "q": 0}
    for cls in (table_mod.Snapshot, table_mod.ChurnView):
        orig = cls.lookup

        def counted(self, queries, *, _orig=orig, **kw):
            lookups["n"] += 1
            lookups["q"] += int(np.asarray(queries).shape[0])
            return _orig(self, queries, **kw)

        cls.lookup = counted

    stop = threading.Event()

    def serve():
        while not stop.is_set():
            r, _, _ = select.select([ssock], [], [], 0.02)
            if not r:
                dht.periodic(None, None)
                continue
            try:
                data, addr = ssock.recvfrom(64 * 1024)
            except OSError:
                continue
            dht.periodic(data, SockAddr(addr[0], addr[1]))

    th = threading.Thread(target=serve, daemon=True)
    th.start()

    # ---- client: raw engine bursting find + get requests ---------------
    csock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    csock.bind(("127.0.0.1", 0))
    csock.setblocking(False)
    ceng = NetworkEngine(InfoHash.get("live-scale-client"), 0,
                         lambda data, dst: csock.sendto(
                             data, (str(dst.ip), dst.port)) and 0,
                         Scheduler(), EngineCallbacks())
    peer = SockAddr("127.0.0.1", sport)
    node = ceng.cache.get_node(dht.myid, peer, time.monotonic(),
                               confirm=True)

    done = []
    lookups["n"] = lookups["q"] = 0
    t0 = time.perf_counter()
    for i in range(Q):
        tgt = InfoHash.get(b"burst-" + secrets.token_bytes(8))
        if i % 2:
            ceng.send_find_node(node, tgt, want=1,
                                on_done=lambda r, a: done.append(a))
        else:
            ceng.send_get_values(node, tgt, Query(), want=1,
                                 on_done=lambda r, a: done.append(a))
    # CPU-backend per-dispatch overhead is ~0.2 s/request; the tunneled
    # TPU round-trip tens of ms — budget generously, the measure is the
    # achieved rate, not the deadline
    deadline = time.monotonic() + max(30.0, Q * (0.3 if on_accel else 1.2))
    while len(done) < Q and time.monotonic() < deadline:
        ceng.scheduler.run()
        r, _, _ = select.select([csock], [], [], 0.02)
        if r:
            try:
                data, addr = csock.recvfrom(64 * 1024)
            except OSError:
                continue
            ceng.process_message(data, SockAddr(addr[0], addr[1]))
    dt = time.perf_counter() - t0
    stop.set()
    th.join()

    n_nodes = sum(len(a.nodes4) for a in done)
    dev_calls, dev_q = lookups["n"], lookups["q"]
    ok_device = (dev_calls >= len(done)
                 and table._snap is not None
                 and table._snap.version == table._version)

    out = {
        "metric": "live node, %d-row table over real UDP: %d/%d "
                  "find+get requests served end-to-end (device lookups: "
                  "%d calls / %d queries; snapshot v%d == table v%d; "
                  "host-scan threshold %d; bulk load %.1fs).  NOTE: on "
                  "this host the device is a TUNNELED TPU — each "
                  "single-query dispatch pays the tunnel round-trip "
                  "(~0.5 s), which bounds the per-request rate; the "
                  "batched resolve below is the design point (one "
                  "device call per wave)"
                  % (len(table), len(done), Q, dev_calls, dev_q,
                     table._snap.version, table._version,
                     table_mod.HOST_SCAN_MAX_ROWS, load_dt),
        "value": round(len(done) / dt, 1),
        "unit": "requests/s",
        "device_path": bool(ok_device),
        "replies_with_nodes": n_nodes,
        "vs_baseline": None,
    }
    print(json.dumps(out), flush=True)

    ok_batched = True
    if args.batched:
        # server-side batched resolve: one device call for a whole wave
        targets = [InfoHash.get(b"wave-%d" % i) for i in range(4096)]
        # warm at the SAME query-batch shape — a different Q is a
        # different XLA program, and timing it measures the (remote)
        # compile, not the resolve
        dht.find_closest_nodes_batched(targets, socket.AF_INET)
        t0 = time.perf_counter()                     # warmed: steady rate
        res = dht.find_closest_nodes_batched(targets, socket.AF_INET)
        bdt = time.perf_counter() - t0
        ok_batched = all(len(r) == 8 for r in res)
        out2 = {
            "metric": "live node batched resolve: 4096 targets through "
                      "Dht.find_closest_nodes_batched in one device call "
                      "(%d-row table)" % len(table),
            "value": round(len(targets) / bdt, 1),
            "unit": "lookups/s",
            "all_answered": ok_batched,
            "vs_baseline": None,
        }
        print(json.dumps(out2), flush=True)
        try:
            from benchmarks.baseline_configs import save_capture
            # the quotable value is the batched resolve — the per-packet
            # rate on THIS host measures the device tunnel, not the stack
            cap = dict(out2)
            cap["metric"] = out["metric"] + " || " + out2["metric"]
            cap["requests_per_s"] = out["value"]
            cap["served"] = len(done)
            cap["burst"] = Q
            save_capture("live_node", cap)
        except Exception:
            pass
    return 0 if (len(done) > 0 and ok_device and ok_batched) else 1


if __name__ == "__main__":
    sys.exit(main())
