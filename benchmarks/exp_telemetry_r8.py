"""Telemetry on-cost on the 8192-wave search round (round 8 tentpole).

The ISSUE-3 acceptance gate: with the unified telemetry spine ON (the
default), the 8192-wave iterative-search round must cost < 3% over the
registry-disabled run.  The instrumentation is host-side only — a
``perf_counter`` span around ``block_until_ready``, one histogram
observe per wave + a bulk ``observe_many`` over the [W] hops vector —
so the expectation is noise-level; this driver measures it and commits
the result as ``captures/telemetry_overhead.json``.

Methodology: both modes run the SAME compiled executable (the wrapper
dispatches to the identical jit — compiled once, shared), interleaved
A/B/A/B over ``--reps`` trips with a median-of-trips on each side, so
thermal/background drift cancels instead of loading one side.  The
capture stores the overhead as ``value`` (percent) plus both medians;
``ci/check_docs.py`` pins the README quote to it.

Usage::

    python benchmarks/exp_telemetry_r8.py --save        # writes capture
    python benchmarks/exp_telemetry_r8.py --smoke       # CI band check
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    p.add_argument("--reps", type=int, default=15,
                   help="timed trips per mode (interleaved)")
    p.add_argument("--save", action="store_true",
                   help="write captures/telemetry_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert overhead < 10%% (generous CI band; the "
                        "committed capture documents the tight number)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(8)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()

    def trip(enabled: bool) -> float:
        reg.enabled = enabled
        t0 = time.perf_counter()
        out = simulate_lookups(sorted_ids, n_valid, targets,
                               alpha=3, k=8, lut=lut, state_limbs=2)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    # shared warmup: one executable serves both modes (the wrapper only
    # changes the host envelope), plus first-transfer of the hops vector
    trip(True)
    trip(False)

    on, off = [], []
    for _ in range(args.reps):
        off.append(trip(False))
        on.append(trip(True))
    reg.enabled = True

    on_ms = float(np.median(on) * 1e3)
    off_ms = float(np.median(off) * 1e3)
    overhead_pct = (on_ms - off_ms) / off_ms * 100.0
    rec = {
        "name": "telemetry_overhead",
        "value": round(overhead_pct, 3),
        "unit": "percent",
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_on": round(on_ms, 3),
        "wave_ms_off": round(off_ms, 3),
        "platform": jax.devices()[0].platform,
        "note": "median 8192-wave search round, telemetry enabled vs "
                "disabled (host-side envelope only; same executable)",
    }
    dc.emit(rec)

    if args.save:
        dc.write_capture("telemetry_overhead", rec)

    if args.smoke and overhead_pct >= 10.0:
        print("telemetry overhead %.2f%% exceeds the 10%% smoke band"
              % overhead_pct, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
