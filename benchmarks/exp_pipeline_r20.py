"""Async double-buffered wave pipeline (round-20 tentpole,
runtime/wave_builder.py): paired-delta of ``ingest_pipeline_depth=2``
vs ``=1`` under sustained ingest on a device-scale table.

Round 12 coalesced a pump's worth of live refills into one ``[Q]``
lookup launch, but the launch itself stayed synchronous: the wave
builder blocked inside ``find_closest_nodes_batched`` until the device
returned, then paid the host scatter (row→Node materialization +
callback delivery) with the device idle.  Round 20 splits every layer
of the resolve into ``launch()``/``consume()`` (core/table.py
``PendingLookup``, runtime/dht.py ``BatchedResolve``) and keeps
``ingest_pipeline_depth`` waves in flight: wave N computes on the
device while wave N+1 fills from the admission queue and wave N−1's
scatter drains on the host.

This driver measures exactly that trade, through the SHIPPING
``WaveBuilder`` (``submit()`` + scheduler pumps — the live ingest
loop, not a synthetic harness):

  depth1    one wave in flight: fire = launch → block → scatter
            (the exact pre-round-20 serial path, via the escape hatch)
  depth2    double-buffered: wave N−1's scatter overlaps wave N's
            device time (``trip`` = submit W waves of Q ops, wall
            seconds until every callback delivered)

Methodology is driver_common.paired_delta (interleaved reps, shared
warmup, per-rep pairing cancels background-load drift).  Bit-identity
is asserted in the same run: depth 2 must deliver per-op node lists
identical to depth 1 over the same bulk-loaded table.

``--capture pipeline_overlap`` writes captures/pipeline_overlap.json;
README/PARITY quote the overlap figure under
``<!-- capture:pipeline_overlap -->`` (ci/check_docs.py enforces the
quotes both directions).  ``--smoke`` is the CI form: small shapes,
bit-identity + a deterministic ≥2-waves-in-flight machinery check
(slow-ready handle wrapper) + a generous timing band.
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)          # driver_common
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)

AF = socket.AF_INET


def _build_dht(n: int, depth: int, q: int, seed: int = 31):
    """A v4-only Dht over a swallow-everything transport with an n-row
    bulk-loaded, addr-servable table and the wave builder configured to
    fire at fill target ``q`` with pipeline depth ``depth``."""
    from opendht_tpu.runtime import Config, Dht
    from opendht_tpu.scheduler import Scheduler
    from opendht_tpu.sockaddr import SockAddr

    clock = {"t": 1000.0}
    cfg = Config(ingest_fill_target=q, ingest_deadline=0.002,
                 ingest_pipeline_depth=depth)
    dht = Dht(lambda data, addr: 0, config=cfg,
              scheduler=Scheduler(clock=lambda: clock["t"]), has_v6=False)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 2 ** 32, size=(n, 5), dtype=np.uint32)
    dht.tables[next(iter(dht.tables))].bulk_load(
        ids, now=clock["t"], addrs=SockAddr("10.7.0.1", 4222))
    return dht, clock


def _targets(n_targets: int, seed: int = 77):
    from opendht_tpu.infohash import InfoHash
    rng = np.random.default_rng(seed)
    return [InfoHash(bytes(rng.integers(0, 256, 20, dtype=np.uint8)))
            for _ in range(n_targets)]


def _run_waves(dht, clock, targets, q: int, k: int, waves: int):
    """Submit ``waves`` waves of ``q`` ops through the shipping
    ``WaveBuilder`` and pump the scheduler until every callback fires.
    Returns (wall_seconds, per-op node lists in submission order)."""
    wb = dht.wave_builder
    out = [None] * (waves * q)
    done = {"n": 0}

    def cb_for(i):
        def cb(nodes):
            out[i] = nodes
            done["n"] += 1
        return cb

    t0 = time.perf_counter()
    for w in range(waves):
        for j in range(q):
            i = w * q + j
            wb.submit(targets[i], AF, k, cb_for(i))
        dht.scheduler.run()          # fill target pulled the trigger
        clock["t"] += 1e-4
        dht.scheduler.sync_time()
    guard = time.perf_counter() + 120
    while done["n"] < waves * q:     # tail: drain the in-flight waves
        clock["t"] += 0.002          # past any drainer re-poll deadline
        dht.scheduler.sync_time()
        dht.scheduler.run()
        if time.perf_counter() > guard:
            raise RuntimeError("pipeline drain stalled: %d/%d delivered"
                               % (done["n"], waves * q))
    dt = time.perf_counter() - t0
    assert all(r is not None for r in out)
    return dt, out


def _ids(results):
    return [[n.id for n in nodes] for nodes in results]


class _SlowReady:
    """Handle wrapper that reports not-ready on its first poll — makes
    the ≥2-waves-in-flight smoke assertion deterministic on hosts where
    the real device result materializes before the next fire."""

    def __init__(self, handle):
        self._h = handle
        self.shard_t = handle.shard_t
        self._polls = 0

    def ready(self):
        self._polls += 1
        return self._polls > 1 and self._h.ready()

    def consume(self):
        return self._h.consume()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=65536, help="table rows")
    p.add_argument("-Q", type=int, default=64,
                   help="wave width (the fill target)")
    p.add_argument("-k", type=int, default=14,
                   help="refill k (live_search.SEARCH_NODES)")
    p.add_argument("--waves", type=int, default=24,
                   help="waves per timed trip (sustained ingest)")
    dc.add_paired_delta_args(p, reps=9)
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json")
    p.add_argument("--smoke", action="store_true",
                   help="CI form: small shapes, bit-identity + "
                        "in-flight machinery + generous timing band")
    args = p.parse_args(argv)

    import jax

    n, q, waves, reps = ((8192, 16, 6, 3) if args.smoke
                         else (args.N, args.Q, args.waves, args.reps))
    k = args.k
    targets = _targets(waves * q)

    dhts = {}
    for depth in (1, 2):
        dhts[depth] = _build_dht(n, depth, q)

    # ---- bit-identity: depth 2 must deliver depth 1's exact results
    _, r1 = _run_waves(*dhts[1], targets, q, k, waves)
    _, r2 = _run_waves(*dhts[2], targets, q, k, waves)
    assert _ids(r1) == _ids(r2), (
        "depth-2 pipeline diverged from depth-1 results")
    snap2 = dhts[2][0].wave_builder.snapshot()

    # ---- paired delta: wall per trip, depth1 baseline
    def trip(mode):
        depth = 1 if mode == "depth1" else 2
        dt, _ = _run_waves(*dhts[depth], targets, q, k, waves)
        return dt

    pd = dc.paired_delta(trip, reps, modes=("depth1", "depth2"))
    overlap_pct = -pd["on_pct"]      # + = depth2 faster (overlap won)

    # ---- the stage-histogram evidence: one extra trip per mode with
    # before/after dht_stage_seconds{stage=} deltas.  The device stage
    # is measured at CONSUME (dispatch + blocking wait) since round 20,
    # so depth 2's device_launch mean shrinks by exactly the compute
    # that elapsed while the host filled the next wave — the overlap,
    # visible in the histograms themselves.
    from opendht_tpu import waterfall

    def _stage_counts():
        snap = waterfall.get_profiler().snapshot()["stages"]
        return {s: (d.get("count", 0), d.get("sum", 0.0))
                for s, d in snap.items()}

    stage_delta = {}
    for depth in (1, 2):
        before = _stage_counts()
        _run_waves(*dhts[depth], targets, q, k, waves)
        after = _stage_counts()
        stage_delta[depth] = {
            s: {"ops": c1 - c0,
                "mean_ms": round((s1 - s0) / (c1 - c0) * 1e3, 4)}
            for s, (c1, s1) in after.items()
            for c0, s0 in [before.get(s, (0, 0.0))] if c1 > c0}

    def _dev_ms(depth):
        d = stage_delta[depth]
        return (d.get("device_launch") or d.get("device_compile")
                or {"mean_ms": 0.0})["mean_ms"]

    rec = dc.emit({
        "driver": "exp_pipeline_r20",
        "N": n, "Q": q, "k": k, "waves": waves,
        "depth1_ms": round(pd["med_ms"]["depth1"], 3),
        "depth2_ms": round(pd["med_ms"]["depth2"], 3),
        "pipeline_overlap_pct": round(overlap_pct, 1),
        "device_stage_ms_depth1": _dev_ms(1),
        "device_stage_ms_depth2": _dev_ms(2),
        "inflight_peak": snap2.get("inflight_peak", 0),
        "bit_identical": True,
        "platform": jax.default_backend(),
    })

    if args.smoke:
        # machinery: a slow-ready handle makes the double-buffer hold
        # two waves in flight deterministically
        sdht, sclock = _build_dht(n, 2, q)   # same table seed → same rows
        real = sdht.find_closest_nodes_launch
        sdht.find_closest_nodes_launch = (
            lambda t, af, c: _SlowReady(real(t, af, c)))
        _, rs = _run_waves(sdht, sclock, targets, q, k, waves)
        ssnap = sdht.wave_builder.snapshot()
        assert ssnap["inflight_peak"] >= 2, (
            "pipeline never held 2 waves in flight: %r" % (ssnap,))
        assert _ids(rs) == _ids(r1), (
            "deferred-drain results diverged from depth-1")
        # band: the pipeline must not regress sustained ingest (generous
        # bound — CI hosts are noisy; the full-shape figure is captured)
        assert pd["med_ms"]["depth2"] <= pd["med_ms"]["depth1"] * 1.6, (
            "depth-2 pipeline regressed sustained ingest: %r" % pd["med_ms"])
        print("pipeline smoke ok: overlap %+.1f%%, inflight_peak %d"
              % (overlap_pct, ssnap["inflight_peak"]))
        return 0

    if args.capture:
        dc.write_capture(args.capture, {
            "metric": ("async double-buffered wave pipeline, live ingest "
                       "path: wall per trip of %d sustained Q=%d waves "
                       "through the shipping WaveBuilder (submit + "
                       "scheduler pumps, device launch + host scatter + "
                       "callback delivery), ingest_pipeline_depth=2 vs "
                       "the depth=1 serial escape hatch, paired-delta "
                       "interleaved reps, platform=cpu; value = %% wall "
                       "reduction from overlap" % (waves, q)),
            "value": round(overlap_pct, 1),
            "unit": "% wall reduction, depth 2 vs depth 1 (cpu)",
            "bound": {
                "N": n, "Q": q, "k": k, "waves": waves,
                "depth1_ms": rec["depth1_ms"],
                "depth2_ms": rec["depth2_ms"],
                "pipeline_overlap_pct": rec["pipeline_overlap_pct"],
                "inflight_peak": rec["inflight_peak"],
                "bit_identical": True,
            },
            # dht_stage_seconds deltas for one trip per mode — the
            # device stage is timed at consume, so the depth-2 shrink
            # vs depth 1 IS the compute hidden under host fill time
            "stages_depth1": stage_delta[1],
            "stages_depth2": stage_delta[2],
            "accelerator_target": (
                "cpu overlap is bounded by the host-side scatter "
                "fraction (XLA CPU compute and the Python scatter share "
                "cores); on TPU the device stage is genuinely off-host, so "
                "the double-buffer hides the entire scatter+fill cost under "
                "device time.  Settle on an accelerator session: python "
                "benchmarks/exp_pipeline_r20.py --capture "
                "pipeline_overlap"),
        })
    return 0


if __name__ == "__main__":
    sys.exit(main())
