"""Per-stage chain-slope profile of the iterative lookup engine.

The config-3 wave (core/search.py simulate_lookups) is a while-loop of
rounds; this driver times each round *component* as its own
device-serialized chain so the next optimization targets the measured
dominator, the method that produced round 3's 63K→171K (profile →
rebuild the dominant stage).  Stages replicate the engine's round
pieces with the same primitives (single-device gather/lower exactly as
simulate_lookups builds them — core/search.py:481-553); the full-wave
number ties the decomposition back to config 3.

Usage::  python benchmarks/profile_search.py [-N 10000000] [-W 16384]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-N", type=int, default=0)
    p.add_argument("-W", type=int, default=0, help="wave width")
    p.add_argument("--stages", type=str, default="",
                   help="comma-separated subset (s1,s2,s3,s4,s5,wave); "
                        "empty = all")
    args = p.parse_args(argv)
    want = set(args.stages.split(",")) if args.stages else None

    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.ids import N_LIMBS
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)
    from opendht_tpu.core import search as SE

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (10_000_000 if on_accel else 100_000)
    W = args.W or (16_384 if on_accel else 1_024)
    NL = 2                                  # state_limbs=2 (config3 default)
    ALPHA, S, K = 3, 14, 8
    R = ALPHA * K

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    n = jnp.asarray(n_valid, jnp.int32)

    # The primitives simulate_lookups injects (search.py:535-551) are
    # built INSIDE each stage body from argument arrays: a closure over
    # the concrete 200 MB table / 64 MB LUT would embed them as HLO
    # constants and the remote-compile tunnel serializes constants into
    # the compile request — measured to wedge a compile indefinitely
    # (chain_slope's docstring records the same trap).
    def make_prims(si, l):
        lower = SE._guarded_lower_bound(si, n, l)
        st = si.T

        def gather_planar(rows, limbs=N_LIMBS):
            flat = jnp.clip(rows, 0, N - 1).reshape(-1)
            g = jnp.take(st[:limbs], flat, axis=1)
            return [g[x].reshape(rows.shape) for x in range(limbs)]
        return lower, gather_planar

    def stage(name, body, *consts, r1=2, r2=8):
        """One chain-slope measurement; a flaky remote-compile tunnel
        must not kill the remaining stages."""
        if want is not None and name.split()[0] not in want:
            return None
        try:
            dt = chain_slope(body, targets, *consts, r1=r1, r2=r2)
        except Exception as e:                      # record and continue
            print(json.dumps({"stage": name, "error": str(e)[:200]}),
                  flush=True)
            return None
        rec = {"stage": name, "ms": round(dt * 1e3, 3)}
        print(json.dumps(rec), flush=True)
        return dt

    # representative per-round operands
    rng = np.random.default_rng(0)
    x_rows = jnp.asarray(rng.integers(0, N, size=(W, ALPHA), dtype=np.int32))
    new_rows = jnp.asarray(rng.integers(0, N, size=(W, R), dtype=np.int32))
    cand_node = jnp.asarray(rng.integers(0, N, size=(W, S), dtype=np.int32))
    cand_l = [jax.random.bits(jax.random.PRNGKey(7 + l), (W, S),
                              dtype=jnp.uint32) for l in range(NL)]
    queried = jnp.asarray((rng.random((W, S)) < 0.5).astype(np.int32))

    # s1: positioning of the full wave (runs once per wave)
    def s1(q, si, l):
        lower, _ = make_prims(si, l)
        return jnp.sum(lower(q).astype(jnp.float32))
    stage("s1 lower(targets) [once/wave]", s1, sorted_ids, lut, r1=4, r2=16)

    # s2: the per-round positioning load — prefix block bounds run ONE
    # batched lower over [2*W*alpha] rows (search.py:86-110)
    def s2(q, xr, si, l):
        lower, gather_planar = make_prims(si, l)
        x_l = gather_planar(xr, N_LIMBS)
        t_l = [q[:, x:x + 1] for x in range(N_LIMBS)]
        b = SE._common_bits_planar(x_l, t_l)
        lo, ub = SE._prefix_block_bounds(
            lower, n, q[:, None, :].repeat(ALPHA, 1),
            jnp.clip(b + 1, 0, SE.ID_BITS))
        return jnp.sum((ub - lo).astype(jnp.float32))
    stage("s2 reply positioning (2*W*alpha lower)", s2, x_rows,
          sorted_ids, lut)

    # s3: reply id gather [W, R] x NL planes (the merge's new-candidate
    # distance fetch).  The gather indices are perturbed by q so the
    # stage consumes the rep-perturbed input — chain_slope's
    # anti-elision contract (an un-consumed q lets XLA hoist the whole
    # body out of the rep loop and the slope measures a scalar add)
    def s3(q, nr, si, l):
        _, gather_planar = make_prims(si, l)
        nr2 = (nr + (q[:, :1].astype(jnp.int32) & 1)) % N
        g = gather_planar(nr2, NL)
        return sum(jnp.sum(x.astype(jnp.float32)) * 1e-9 for x in g)
    stage("s3 reply gather [W,R] x %d limbs" % NL, s3, new_rows,
          sorted_ids, lut)

    # s4: the two merge sorts (insert + dedupe, search.py:298-337)
    def s4(q, cn, ql, nr, si, l, *cl):
        _, gather_planar = make_prims(si, l)
        cl = list(cl)
        new_l = gather_planar(nr, NL)
        node = jnp.concatenate([cn, nr], axis=1)
        d_l = [jnp.concatenate([cl[l], new_l[l] ^ q[:, l:l + 1]], axis=1)
               for l in range(NL)]
        qd = jnp.concatenate([ql, jnp.zeros((W, R), jnp.int32)], axis=1)
        inv = (node < 0).astype(jnp.int32)
        from jax import lax
        out = lax.sort((inv,) + tuple(d_l) + (node, 1 - qd),
                       dimension=1, num_keys=3 + NL)
        node_s = out[1 + NL]
        dup = jnp.concatenate(
            [jnp.zeros((W, 1), bool),
             (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)],
            axis=1)
        inv2 = jnp.where(dup, 1, out[0])
        out2 = lax.sort((inv2,) + tuple(out[1:1 + NL]) + (node_s, out[2 + NL]),
                        dimension=1, num_keys=2 + NL)
        return jnp.sum(out2[1 + NL][:, :S].astype(jnp.float32)) * 1e-9
    stage("s4 merge sorts (2x [W,%d])" % (S + R), s4, cand_node, queried,
          new_rows, sorted_ids, lut, *cand_l)

    # s5: candidate alpha-selection (masked max-reductions); cn is
    # perturbed by q for the same anti-elision reason as s3
    def s5(q, cn, ql):
        cn = cn + (q[:, :1].astype(jnp.int32) & 1)
        can = (cn >= 0) & (ql == 0)
        rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
        sel = can & (rank <= ALPHA)
        xr = jnp.stack([jnp.max(jnp.where(sel & (rank == j + 1), cn, -1),
                                axis=1) for j in range(ALPHA)], axis=1)
        return jnp.sum(xr.astype(jnp.float32)) * 1e-9
    stage("s5 alpha-select reductions", s5, cand_node, queried,
          r1=8, r2=64)

    # full wave for reference (ties the decomposition to config 3)
    def wave(q, si, nv, l):
        o = SE.simulate_lookups(si, nv, q, alpha=ALPHA, k=K, lut=l,
                                state_limbs=NL)
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))
    dt = stage("wave simulate_lookups [W=%d]" % W, wave, sorted_ids,
               n_valid, lut, r1=1, r2=4)
    if dt is not None:
        hops_out = jax.block_until_ready(SE.simulate_lookups(
            sorted_ids, n_valid, targets, alpha=ALPHA, k=K, lut=lut,
            state_limbs=NL))
        p50 = int(np.percentile(np.asarray(hops_out["hops"]), 50))
        print(json.dumps({"stage": "summary", "wave_ms": round(dt * 1e3, 2),
                          "p50_hops": p50,
                          "lookups_per_s": round(W / dt, 1)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
