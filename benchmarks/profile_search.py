"""Per-stage chain-slope profile of the ROUND-FUSED iterative engine.

The config-3 wave (core/search.py simulate_lookups) is a while-loop of
rounds; this driver times each round *component* as its own
device-serialized chain so the next optimization targets the measured
dominator — the method that produced round 3's 63K→171K (profile →
rebuild the dominant stage).  Stages mirror the ROUND-6 fused round
body (core/search.py _lookup_engine): the per-round positioning search
the pre-round-5 engine carried (85% of the round, exp_round_r5.py) is
GONE — reply blocks are positioned from the carried candidate distance
limb through one stacked LUT read — so the decomposition is now

    s1  lower(targets)            once per wave (bootstrap positioning)
    s2  alpha-select + carried-d0 masked max-reductions (per round)
    s3  stacked LUT block-bounds  one [2,...] take for both edges
    s4  fused reply gather        ONE [W·α·k] × NL-plane table gather —
                                  the round's only table access
    s5  merge sorts               2× [W, S+R] lax.sort (insert + dedupe)
    wave                          full simulate_lookups (ties the
                                  decomposition back to config 3)

Stages use the same primitives the engine injects (built inside each
stage body from argument arrays — a closure over the concrete table
would embed it as an HLO constant and wedge the remote-compile tunnel;
see bench.chain_slope's docstring).  ``--smoke`` (the ci/run_ci.sh
entry) runs the full decomposition at a small shape and fails on any
stage erroring or the wave slope exceeding a generous ceiling — a
stage-level compile break or order-of-magnitude stall fails CI without
the full bench.  The cost-model complement (deterministic per-kernel
flops/bytes this driver's stages move) is the kernel ledger:
``python -c "from opendht_tpu import profiling;
print(profiling.get_ledger().compute())"`` or the ``kernels`` REPL
command; ``ci/perf_gate.py`` gates it.

Usage::  python benchmarks/profile_search.py [-N 10000000] [-W 16384]
         python benchmarks/profile_search.py --smoke     # CI entry
         python benchmarks/profile_search.py --profile /tmp/prof
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0)
    p.add_argument("-W", type=int, default=0, help="wave width")
    p.add_argument("--stages", type=str, default="",
                   help="comma-separated subset (s1,s2,s3,s4,s5,wave); "
                        "empty = all")
    p.add_argument("--smoke", action="store_true",
                   help="small-shape CI smoke: every stage must produce "
                        "a slope and the wave must stay under a generous "
                        "ceiling")
    dc.add_profile_arg(p)
    args = p.parse_args(argv)
    want = set(args.stages.split(",")) if args.stages else None

    import jax
    import jax.numpy as jnp
    from jax import lax
    from bench import chain_slope
    from opendht_tpu.ops.ids import N_LIMBS, clz32
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits,
                                              fused_gather_planar)
    from opendht_tpu.core import search as SE

    on_accel = jax.devices()[0].platform != "cpu"
    if args.smoke:
        N = args.N or 65_536
        W = args.W or 1_024
    else:
        N = args.N or (10_000_000 if on_accel else 100_000)
        W = args.W or (16_384 if on_accel else 1_024)
    NL = 2                                  # state_limbs=2 (config3 default)
    ALPHA, S, K = 3, 14, 8
    R = ALPHA * K
    _U32 = jnp.uint32

    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jnp.uint32)
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table
    n = jnp.asarray(n_valid, jnp.int32)

    # The primitives simulate_lookups injects are built INSIDE each
    # stage body from argument arrays: a closure over the concrete
    # table / LUT would embed them as HLO constants and the
    # remote-compile tunnel serializes constants into the compile
    # request — measured to wedge a compile indefinitely (chain_slope's
    # docstring records the same trap).
    def make_prims(si, l):
        lower = SE._guarded_lower_bound(si, n, l)
        st = si.T

        def gather_planar(rows, limbs=N_LIMBS):
            return fused_gather_planar(st, rows, limbs)
        return lower, gather_planar

    failures = []
    results = {}

    def stage(name, body, *consts, r1=2, r2=8):
        """One chain-slope measurement; a flaky remote-compile tunnel
        must not kill the remaining stages (but --smoke fails on it)."""
        sid = name.split()[0]
        if want is not None and sid not in want:
            return None
        try:
            dt = chain_slope(body, targets, *consts, r1=r1, r2=r2)
        except Exception as e:                      # record and continue
            dc.emit({"stage": name, "error": str(e)[:200]},
                    name="profile_search")
            failures.append(sid)
            return None
        results[sid] = dt
        dc.emit(dc.slope_record(name, dt), name="profile_search")
        return dt

    # representative per-round operands
    rng = np.random.default_rng(0)
    new_rows = jnp.asarray(rng.integers(0, N, size=(W, R), dtype=np.int32))
    cand_node = jnp.asarray(rng.integers(0, N, size=(W, S), dtype=np.int32))
    cand_l = [jax.random.bits(jax.random.PRNGKey(7 + l), (W, S),
                              dtype=jnp.uint32) for l in range(NL)]
    queried = jnp.asarray((rng.random((W, S)) < 0.5).astype(np.int32))

    with dc.profile_ctx(args.profile):
        # s1: positioning of the full wave (runs ONCE per wave — the
        # bootstrap; the fused round body has no positioning search)
        def s1(q, si, l):
            lower, _ = make_prims(si, l)
            return jnp.sum(lower(q).astype(jnp.float32))
        stage("s1 lower(targets) [once/wave]", s1, sorted_ids, lut,
              r1=4, r2=16)

        # s2: alpha-selection + the carried-d0 reductions (the round-6
        # fusion: the queried peers' top distance limb rides the same
        # masked max-reductions instead of a table gather); cn is
        # perturbed by q — chain_slope's anti-elision contract
        def s2(q, cn, ql, *cl):
            cn = cn + (q[:, :1].astype(jnp.int32) & 1)
            can = (cn >= 0) & (ql == 0)
            rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
            sel = can & (rank <= ALPHA)
            xr = jnp.stack([jnp.max(jnp.where(sel & (rank == j + 1), cn, -1),
                                    axis=1) for j in range(ALPHA)], axis=1)
            xd = jnp.stack([jnp.max(jnp.where(sel & (rank == j + 1), cl[0],
                                              _U32(0)), axis=1)
                            for j in range(ALPHA)], axis=1)
            return (jnp.sum(xr.astype(jnp.float32))
                    + jnp.sum(xd.astype(jnp.float32))) * 1e-9
        stage("s2 alpha-select + carried-d0 reductions", s2, cand_node,
              queried, *cand_l, r1=8, r2=64)

        # s3: the stacked LUT block-bounds read — BOTH edges of every
        # queried peer's prefix block in one [2, ...] take
        # (search.py _lut_block_bounds), all the positioning the fused
        # round does.  The carried d0 stands in for the candidate state,
        # perturbed by q (anti-elision).
        def s3(q, l, *cl):
            x_d0 = cl[0][:, :ALPHA] + (q[:, :1] & _U32(1))
            b = clz32(x_d0)
            lo, ub = SE._lut_block_bounds(l, q[:, 0:1], b + 1)
            return jnp.sum((ub - lo).astype(jnp.float32))
        stage("s3 stacked LUT block-bounds read", s3, lut, *cand_l,
              r1=8, r2=64)

        # s4: the fused reply gather — ONE [W·R] × NL-plane take through
        # the transposed table, the round's only table access.  Indices
        # perturbed by q so the stage consumes the rep-perturbed input.
        def s4(q, nr, si, l):
            _, gather_planar = make_prims(si, l)
            nr2 = (nr + (q[:, :1].astype(jnp.int32) & 1)) % N
            g = gather_planar(nr2, NL)
            return sum(jnp.sum(x.astype(jnp.float32)) * 1e-9 for x in g)
        stage("s4 fused reply gather [W,%d] x %d limbs" % (R, NL), s4,
              new_rows, sorted_ids, lut)

        # s5: the two merge sorts (insert + dedupe — search.py merge())
        def s5(q, cn, ql, nr, si, l, *cl):
            _, gather_planar = make_prims(si, l)
            cl = list(cl)
            new_l = gather_planar(nr, NL)
            node = jnp.concatenate([cn, nr], axis=1)
            d_l = [jnp.concatenate([cl[i], new_l[i] ^ q[:, i:i + 1]], axis=1)
                   for i in range(NL)]
            qd = jnp.concatenate([ql, jnp.zeros((W, R), jnp.int32)], axis=1)
            inv = (node < 0).astype(jnp.int32)
            out = lax.sort((inv,) + tuple(d_l) + (node, 1 - qd),
                           dimension=1, num_keys=3 + NL)
            node_s = out[1 + NL]
            dup = jnp.concatenate(
                [jnp.zeros((W, 1), bool),
                 (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)],
                axis=1)
            inv2 = jnp.where(dup, 1, out[0])
            out2 = lax.sort((inv2,) + tuple(out[1:1 + NL])
                            + (node_s, out[2 + NL]),
                            dimension=1, num_keys=2 + NL)
            return jnp.sum(out2[1 + NL][:, :S].astype(jnp.float32)) * 1e-9
        stage("s5 merge sorts (2x [W,%d])" % (S + R), s5, cand_node,
              queried, new_rows, sorted_ids, lut, *cand_l)

        # full wave for reference (ties the decomposition to config 3)
        def wave(q, si, nv, l):
            o = SE.simulate_lookups(si, nv, q, alpha=ALPHA, k=K, lut=l,
                                    state_limbs=NL)
            return (jnp.sum(o["hops"].astype(jnp.float32))
                    + jnp.sum(o["converged"].astype(jnp.float32)))
        dt = stage("wave simulate_lookups [W=%d]" % W, wave, sorted_ids,
                   n_valid, lut, r1=1, r2=4)

    if dt is not None:
        hops_out = jax.block_until_ready(SE.simulate_lookups(
            sorted_ids, n_valid, targets, alpha=ALPHA, k=K, lut=lut,
            state_limbs=NL))
        p50 = int(np.percentile(np.asarray(hops_out["hops"]), 50))
        dc.emit({"stage": "summary", "wave_ms": round(dt * 1e3, 2),
                 "p50_hops": p50, "N": N, "W": W,
                 "lookups_per_s": round(W / dt, 1)},
                name="profile_search")

    if args.smoke:
        ran = set(results)
        need = ({"s1", "s2", "s3", "s4", "s5", "wave"} if want is None
                else want)
        missing = sorted((need - ran) | set(failures))
        if missing:
            print("SMOKE FAIL: stages errored or missing: %s" % missing,
                  file=sys.stderr)
            return 1
        if "wave" in results and results["wave"] * 1e3 > 3000.0:
            print("SMOKE FAIL: wave slope %.0f ms exceeds the 3000 ms "
                  "smoke ceiling" % (results["wave"] * 1e3),
                  file=sys.stderr)
            return 1
        print("profile_search smoke ok (%d stages)" % len(results))
    return 0


if __name__ == "__main__":
    sys.exit(main())
