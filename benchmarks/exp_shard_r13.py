"""Round-13 driver: row-sharded table scaling — 10M+ ids across a mesh.

The tentpole claim of the round is that the iterative search engine's
servable table now scales with the mesh instead of one chip's HBM: the
sorted table, its positioning LUT and validity are ROW-SHARDED over the
``t`` axis (parallel/partition.py ``shard_table_state``), each shard
holds ~N/t rows, and the steady-state hop costs exactly ONE collective
of O(queries·k) bytes.  This driver makes each piece a measured,
committed number on the virtual CPU mesh (real multi-chip hardware is
not available here — wall-clock indicates scaling shape only, stated in
the artifact):

- scaling curve N ∈ {1M, 4M, 10M} × t ∈ {1, 2, 4}: per-shard resident
  table bytes (read off the PLACED array's own shards — exactly
  N_pad/t·5·4 B, asserted against the (1+ε) bound), the compiled
  program's ``memory_analysis()`` argument/temp bytes, the in-loop
  collective sites + bytes/query/hop read from the compiled HLO
  (benchmarks/tp_scaling.py ``collectives_of``), and the wave
  wall-clock;
- bit-identity: every t-sharded wave is compared against the
  single-device engine on the same targets — including the 10M-id
  t=4 geometry, a table that could not even be SERVED replicated
  before this round (the acceptance shape);
- ``--capture shard_scale`` commits ``captures/shard_scale.json``;
  the on-chip 10M-id wave latency rides ``perf_budgets.json`` as the
  fifth OPEN bound (``shard_wave_10m``) with this driver as its
  settling command.

``--smoke`` is the CI shape (ci/run_ci.sh): one t-sharded wave on the
8-device mesh, asserting (1) the compiled HLO's in-loop
collective-site count and bytes/query/hop EQUAL the committed
TP_SCALING.json values — drift fails in BOTH directions, (2) the
per-shard table bytes bound, (3) bit-identity vs single-device.

Usage::

    python benchmarks/exp_shard_r13.py --capture shard_scale   # full curve
    python benchmarks/exp_shard_r13.py --smoke                 # CI
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from driver_common import ROOT, emit, write_capture          # noqa: E402
from tp_scaling import collectives_of                        # noqa: E402

#: per-shard resident-table slack over the exact N_pad/t·5·4 B — the
#: acceptance bound's ε (padding to a t multiple is the only legitimate
#: source of extra rows)
EPSILON = 0.01


def _force_devices(n: int = 8) -> None:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=%d"
                               % n)


def _run_geometry(N: int, n_t: int, Q: int, reps: int, *, ref_nodes,
                  sorted_np, n_valid, targets):
    """One (N, t) point: build state, compile, read HLO + memory, run
    the wave, return the record row (and the wave's nodes for the
    bit-identity pin)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from opendht_tpu.core.search import ALPHA, SEARCH_NODES
    from opendht_tpu.parallel.partition import shard_table_state
    from opendht_tpu.parallel.sharded import build_tp_lookup, pad_to_multiple

    devs = np.array(jax.devices())
    mesh = Mesh(devs[:n_t].reshape(1, n_t), ("q", "t"))
    padded, _ = pad_to_multiple(sorted_np, n_t)
    state = shard_table_state(mesh, padded, n_valid)
    fn = build_tp_lookup(mesh, state.shard_n, Q, 8, ALPHA, SEARCH_NODES,
                         48, 2)
    a = state.arrays
    t_pl = jax.device_put(targets, NamedSharding(mesh, P("q", None)))
    args = (a["sorted_ids"], a["local_lut"], a["block_lut"], a["n_valid"],
            t_pl, jnp.int32(1))
    compiled = fn.lower(*args).compile()

    # per-shard resident table bytes: read off the placed array itself
    # (ground truth, not a model) and bound-checked against N/t·5·4 B
    shard_bytes = int(a["sorted_ids"].addressable_shards[0].data.nbytes)
    bound = int(padded.shape[0] // n_t * 5 * 4 * (1 + EPSILON))
    assert shard_bytes <= bound, (shard_bytes, bound)
    mem = compiled.memory_analysis()
    att = collectives_of(compiled.as_text())
    per_hop = sum(c["bytes"] for c in att["per_hop"])

    out = jax.block_until_ready(compiled(*args))
    nodes = np.asarray(out["nodes"])
    if ref_nodes is not None:
        np.testing.assert_array_equal(nodes, ref_nodes)   # bit-identical
    best = None
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready(compiled(*args))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    row = {
        "N": N, "n_t": n_t, "Q": Q,
        "shard_rows": state.shard_n,
        "table_bytes_per_shard": shard_bytes,
        "table_bytes_per_shard_bound": bound,
        "block_lut_bytes_replicated": int(
            np.asarray(a["block_lut"]).nbytes),
        "memory_argument_bytes": int(
            getattr(mem, "argument_size_in_bytes", 0) or 0),
        "memory_temp_bytes": int(
            getattr(mem, "temp_size_in_bytes", 0) or 0),
        "collective_sites_in_loop": len(att["per_hop"]),
        "collective_bytes_per_query_per_hop": round(per_hop / Q, 1),
        "p50_hops": int(np.percentile(np.asarray(out["hops"]), 50)),
        "converged": float(np.asarray(out["converged"]).mean()),
        "bit_identical_vs_single_device": ref_nodes is not None,
        "wallclock_s": round(best, 4),
        "lookups_per_s_virtual": round(Q / best, 1),
    }
    return row, nodes


def _committed_tp_row() -> dict:
    with open(os.path.join(ROOT, "TP_SCALING.json")) as f:
        return json.load(f)["rows"][0]


def run_smoke(args) -> int:
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import simulate_lookups

    N, Q = 65_536, 256
    k1, k2 = jax.random.split(jax.random.PRNGKey(13))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = np.asarray(jax.random.bits(k2, (Q, 5), dtype=jnp.uint32))
    sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
    ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets), seed=1)
    row, _nodes = _run_geometry(N, 4, Q, 1, ref_nodes=np.asarray(
        ref["nodes"]), sorted_np=np.asarray(sorted_ids), n_valid=n_valid,
        targets=targets)
    committed = _committed_tp_row()
    # drift gates BOTH directions: an extra in-loop collective fails,
    # and a further fusion that the committed artifact doesn't reflect
    # fails too (regenerate TP_SCALING.json deliberately instead)
    ok_sites = (row["collective_sites_in_loop"]
                == committed["collective_sites_in_loop"])
    ok_bytes = (row["collective_bytes_per_query_per_hop"]
                == committed["bytes_per_local_query_per_hop"])
    emit({"smoke": "shard_r13", **row,
          "committed_sites": committed["collective_sites_in_loop"],
          "committed_bytes_per_query": committed[
              "bytes_per_local_query_per_hop"]})
    if not ok_sites:
        print("FAIL: in-loop collective sites %d != committed "
              "TP_SCALING.json %d — regenerate the artifact if the "
              "change is intentional" % (
                  row["collective_sites_in_loop"],
                  committed["collective_sites_in_loop"]))
        return 1
    if not ok_bytes:
        print("FAIL: %s B/query/hop != committed %s" % (
            row["collective_bytes_per_query_per_hop"],
            committed["bytes_per_local_query_per_hop"]))
        return 1
    print("shard smoke ok: 1 wave @ N=%d t=4, sites=%d, %s B/query/hop, "
          "per-shard table %d B (bound %d)" % (
              N, row["collective_sites_in_loop"],
              row["collective_bytes_per_query_per_hop"],
              row["table_bytes_per_shard"],
              row["table_bytes_per_shard_bound"]))
    return 0


def run_full(args) -> int:
    import jax
    import jax.numpy as jnp
    from opendht_tpu.ops.sorted_table import sort_table
    from opendht_tpu.core.search import simulate_lookups

    Ns = [int(v) for v in args.N.split(",")]
    ts = [int(v) for v in args.t.split(",")]
    Q = args.Q
    rows = []
    for N in Ns:
        k1, k2 = jax.random.split(jax.random.PRNGKey(17 + N % 97))
        table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
        targets = np.asarray(jax.random.bits(k2, (Q, 5), dtype=jnp.uint32))
        sorted_ids, _p, n_valid = jax.block_until_ready(sort_table(table))
        sorted_np = np.asarray(sorted_ids)
        # single-device oracle once per N — the bit-identity pin every
        # t point is compared against (at 10M this is the engine run
        # that needs the WHOLE table on one device; the sharded runs
        # below hold N/t rows per device)
        ref = simulate_lookups(sorted_ids, n_valid, jnp.asarray(targets),
                               seed=1)
        ref_nodes = np.asarray(ref["nodes"])
        del table, sorted_ids, ref
        for n_t in ts:
            row, _ = _run_geometry(N, n_t, Q, args.reps,
                                   ref_nodes=ref_nodes, sorted_np=sorted_np,
                                   n_valid=n_valid, targets=targets)
            rows.append(row)
            emit(row)

    big = [r for r in rows if r["N"] == max(Ns) and r["n_t"] == max(ts)]
    headline = big[0] if big else rows[-1]
    rec = {
        "metric": "t-sharded iterative lookup scaling, virtual CPU mesh "
                  "(q=1 x t), N x t curve; per-shard resident table bytes "
                  "read off the placed shards, collectives off the "
                  "compiled HLO; wall-clock indicates scaling shape only "
                  "(virtual devices share one host, ICI not modeled)",
        "value": headline["lookups_per_s_virtual"],
        "unit": "lookups/s",
        "rows": rows,
        "bound": {
            "table_bytes_per_shard_headline":
                headline["table_bytes_per_shard"],
            "headline_N": headline["N"],
            "headline_t": headline["n_t"],
            "collective_sites_in_loop":
                headline["collective_sites_in_loop"],
            "bytes_per_query_per_hop":
                headline["collective_bytes_per_query_per_hop"],
            "open_bound": "shard_wave_10m (perf_budgets.json): on-chip "
                          "10M-id t-sharded wave latency — settle with "
                          "this driver + baseline_configs -c 3 --tp on "
                          "an accelerator mesh",
        },
    }
    if args.capture:
        write_capture(args.capture, rec)
    else:
        emit({"metric": rec["metric"], "value": rec["value"],
              "unit": rec["unit"]})
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="CI shape: one t=4 wave, HLO-vs-TP_SCALING drift "
                        "gate + per-shard bytes bound + bit-identity")
    p.add_argument("--capture", default="",
                   help="write captures/<name>.json (use: shard_scale)")
    p.add_argument("-N", default="1000000,4000000,10000000",
                   help="comma list of table sizes")
    p.add_argument("-t", default="1,2,4", help="comma list of t widths")
    p.add_argument("-Q", type=int, default=1024)
    p.add_argument("--reps", type=int, default=2)
    args = p.parse_args(argv)

    _force_devices(8)
    import jax
    jax.config.update("jax_platforms", "cpu")
    if args.smoke:
        return run_smoke(args)
    return run_full(args)


if __name__ == "__main__":
    sys.exit(main())
