"""Pipeline-observatory on-cost on the 8192-wave search round (round 22).

The round-22 acceptance gate: with the utilization observatory
tracking every wave — the full lifecycle edge set the serving wave
builder fires (fill_start / take_fill / on_dispatch with idle-gap
bubble classification / on_device_done / on_scatter_done) plus the
history-frame occupancy checkpoint — the 8192-wave iterative-search
round must cost < 1% over the observatory-disabled run.  Every edge is
host-side O(1) ledger arithmetic under one lock (a couple of float
compares, a deque append); the observatory never touches the device —
so the expectation is noise-level.  Measured with the shared
paired-delta estimator (``driver_common.paired_delta``) and committed
as ``captures/pipeutil_overhead.json``.

The driver also pins the wave outputs bit-identical between an
observatory-on trip and an observatory-off trip (the "kernels stay
bit-identical with the observatory on" acceptance line, checked again
in tests/test_pipeline_observatory.py's noop test), asserts the timed
trips left a CLOSED ledger — Σ(busy) + Σ(bubbles) == observed window,
the tentpole's accounting invariant, here against real wall-clock
instead of a scripted fake — and ``--stages`` prints the measured
bubble ledger next to the headline delta.

Usage::

    python benchmarks/exp_pipeutil_r21.py --save      # writes capture
    python benchmarks/exp_pipeutil_r21.py --smoke     # CI band check
    python benchmarks/exp_pipeutil_r21.py --stages    # + bubble ledger
"""

from __future__ import annotations

import argparse
import os
import socket
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import driver_common as dc         # noqa: E402  (puts the repo root on sys.path)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-N", type=int, default=0,
                   help="table rows (default: 1M on accelerator, 128K cpu)")
    p.add_argument("-W", type=int, default=8192, help="wave width")
    dc.add_paired_delta_args(p)
    p.add_argument("--save", action="store_true",
                   help="write captures/pipeutil_overhead.json")
    p.add_argument("--smoke", action="store_true",
                   help="assert observatory overhead < 5%% (generous CI "
                        "band; the committed capture documents the "
                        "tight number against the <1%% acceptance)")
    args = p.parse_args(argv)

    import jax
    from opendht_tpu import telemetry
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (build_prefix_lut, sort_table,
                                              default_lut_bits)
    from opendht_tpu.pipeline_observatory import (PipelineObservatory,
                                                  PipelineObservatoryConfig)

    on_accel = jax.devices()[0].platform != "cpu"
    N = args.N or (1_000_000 if on_accel else 131_072)
    W = args.W

    key = jax.random.PRNGKey(22)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jax.numpy.uint32)
    targets = jax.random.bits(k2, (W, 5), dtype=jax.numpy.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    reg = telemetry.get_registry()
    reg.enabled = True                      # telemetry ON in both modes
    obs = {"on": PipelineObservatory(PipelineObservatoryConfig(enabled=True),
                                     registry=reg),
           "off": PipelineObservatory(PipelineObservatoryConfig(enabled=False),
                                      registry=reg)}

    def trip(mode: str) -> float:
        # the exact per-wave edge sequence the serving builder fires
        # (wave_builder._fire/_launch/_scatter), around the same kernel
        o = obs[mode]
        t0 = time.perf_counter()
        o.note_fill_start()
        t_fill = o.take_fill(time.time())
        seq = o.on_dispatch(t_fill, time.time(), W, socket.AF_INET,
                            8, 0, 0)
        out = simulate_lookups(sorted_ids, n_valid, targets, alpha=3,
                               k=8, lut=lut, state_limbs=2)
        jax.block_until_ready(out)
        o.on_device_done(seq, time.time())
        o.on_scatter_done(seq, time.time())
        o.on_frame()
        return time.perf_counter() - t0

    # bit-identity: an observatory-on trip and an observatory-off trip
    # return the same arrays (the edges only ledger host wall-clock)
    base = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    trip("on")
    profiled = jax.block_until_ready(simulate_lookups(
        sorted_ids, n_valid, targets, alpha=3, k=8, lut=lut,
        state_limbs=2))
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(profiled)):
        assert np.array_equal(np.asarray(a), np.asarray(b)), \
            "wave outputs diverged with the observatory enabled"
    del base, profiled

    pd = dc.paired_delta(trip, args.reps, modes=("off", "on"))

    # observatory sanity: the timed "on" trips were tracked end to end
    # and the ledger CLOSED — busy + attributed bubbles == the observed
    # window (the tentpole invariant, against real wall-clock)
    snap = obs["on"].snapshot()
    acct = obs["on"].account()
    assert snap["waves_total"] >= args.reps, \
        "observatory saw %d waves over %d reps" % (
            snap["waves_total"], args.reps)
    assert snap["open_waves"] == 0, "timed trips leaked open waves"
    closed = abs(acct["attributed_s"] - acct["span_s"]) \
        <= 1e-6 + 1e-9 * acct["span_s"]
    assert closed, "accounting did not close: %r" % (acct,)

    rec_doc = {
        "name": "pipeutil_overhead",
        "value": round(pd["on_pct"], 3),
        "unit": "percent",
        "acceptance_pct": 1.0,
        "wave": W, "N": N, "reps": args.reps,
        "wave_ms_on": round(pd["med_ms"]["on"], 3),
        "wave_ms_off": round(pd["med_ms"]["off"], 3),
        "waves_observed": int(snap["waves_total"]),
        "occupancy": round(acct["busy_s"] / acct["span_s"], 4)
        if acct["span_s"] > 0 else -1,
        "accounting_closed": bool(closed),
        "platform": jax.devices()[0].platform,
        "note": "8192-wave search round, median of per-rep paired "
                "deltas over rotation-interleaved trips "
                "(driver_common.paired_delta): full observatory "
                "lifecycle (fill/dispatch/bubble-classify/device_done/"
                "scatter_done + frame checkpoint) tracking every wave "
                "vs observatory disabled; same executable, telemetry "
                "on in both modes; wave outputs pinned bit-identical; "
                "Σ(busy)+Σ(bubbles)==window asserted on the timed "
                "trips",
    }
    dc.emit(rec_doc)
    if args.stages:
        print("-- bubble ledger (timed 'on' trips)")
        for cause, rec in sorted(snap["bubbles"].items()):
            print("   %-18s %8.3f ms over %d gaps"
                  % (cause, rec["seconds"] * 1e3, rec["count"]))
        print("   busy %.3f ms over %.3f ms window"
              % (acct["busy_s"] * 1e3, acct["span_s"] * 1e3))

    if args.save:
        dc.write_capture("pipeutil_overhead", rec_doc)

    if args.smoke and pd["on_pct"] >= 5.0:
        print("observatory overhead %.2f%% exceeds the 5%% smoke band"
              % pd["on_pct"], file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
