"""Runnable drivers for every BASELINE.json config.

Each config prints one JSON line (same shape as bench.py).  Sizes scale
with the backend: full BASELINE sizes on an accelerator, reduced on CPU
so the suite stays runnable in CI.  Usage::

    python benchmarks/baseline_configs.py            # all configs
    python benchmarks/baseline_configs.py -c 3       # one config

Configs (BASELINE.json):
  1 dhtnode single-process: 1K get() lookups over a 10K-node routing
    table — CPU reference (the native C++ sorted walk) vs the device
    batched lookup.
  2 batched findClosestNodes: 131K queries × 1M ids, top-16 (the
    headline bench — delegates to bench.py's measurement).
  3 iterative Search simulation: α-parallel lookups vs a 10M-node
    simulated network, k=8 convergence, hop counts.
  4 bucket-refresh sweep: full radix partition + per-bucket stats over
    10M ids.
  5 multi-chip sharded table: row-sharded lookup with ICI top-k merge
    (one real chip here; the same code dry-runs on an 8-device virtual
    mesh — __graft_entry__.dryrun_multichip).

Timing: all device numbers use the serialized-chain slope
(bench.chain_slope) — a jitted while_loop (traced trip count) repeats
the workload with index-perturbed inputs and the per-rep time is the slope between two
rep counts.  Wall-clock timing of dispatched work is NOT trusted:
block_until_ready() on a tunneled device can return before execution
completes (see bench.py docstring; it inflated round-1 numbers ~100×).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def config1() -> dict:
    """1K get() lookups over a 10K-node table: native C++ scalar walk
    (the CPU reference) vs the batched device kernel."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.ids import ids_to_bytes
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              expand_table, expanded_topk)
    from opendht_tpu import native

    N, Q, K = 10_000, 1_000, 8
    rng = np.random.default_rng(1)
    table = rng.integers(0, 2**32, size=(N, 5), dtype=np.uint32)
    queries = rng.integers(0, 2**32, size=(Q, 5), dtype=np.uint32)

    sorted_ids, perm, n_valid = jax.block_until_ready(
        sort_table(jnp.asarray(table)))
    lut = build_prefix_lut(sorted_ids, n_valid)
    expanded = expand_table(sorted_ids)

    def body(q, sorted_ids, expanded, n_valid, lut):
        d, idx, c = expanded_topk(sorted_ids, expanded, n_valid, q, k=K,
                                  lut=lut)
        return jnp.sum(c.astype(jnp.float32))

    # per-rep work is ~0.2 ms at this size: use deep rep counts so the
    # slope rises above run-to-run noise (single compile either way —
    # the trip count is traced)
    dt_dev = chain_slope(body, jnp.asarray(queries), sorted_ids, expanded,
                         n_valid, lut, r1=64, r2=512)

    baseline = None
    if native.available():
        t_bytes = ids_to_bytes(np.asarray(sorted_ids)).reshape(N, 20)
        q_bytes = ids_to_bytes(queries).reshape(Q, 20)
        # native path runs on the host CPU: plain wall timing is honest
        from bench import best_of
        baseline = best_of(
            lambda: native.sorted_closest(t_bytes, q_bytes, k=K), tries=7)
    return {"metric": "config1 1K get() over 10K-node table "
                      "(device-serialized chain slope)",
            "value": round(Q / dt_dev, 1), "unit": "lookups/s",
            "vs_baseline": round((Q / dt_dev) / (Q / baseline), 2)
            if baseline else None}


def config3(Q: int = 0, N: int = 0, chunk: int = 0) -> dict:
    """α-parallel iterative lookups to k=8 convergence.

    The north-star shape is ``-Q 1000000`` against the 10M-node table
    (BASELINE.json configs[2]): the query burst is streamed through the
    device in fixed-shape waves (one compiled executable; search state
    for one wave resident at a time) so HBM holds wave state + the
    sorted table, never the full burst.

    Throughput is the chain slope of one wave (device-serialized), and
    burst numbers derive from it: burst time = n_waves × wave time.
    The separately-reported ``p50 burst completion`` is wave-time ×
    (wave index holding the median lookup + 1) — FIFO retire order.
    """
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.core.search import simulate_lookups
    from opendht_tpu.ops.sorted_table import (sort_table, build_prefix_lut,
                                              default_lut_bits)

    on_accel = jax.devices()[0].platform != "cpu"
    N = N or (10_000_000 if on_accel else 100_000)
    Q = Q or (16_384 if on_accel else 1_024)
    # measured optimum wave width on v5e (chunk sweep at -Q 1000000:
    # 16384 → 63.2K/s, 131072 → 56.7K/s — smaller waves keep the
    # while_loop's straggler tail short)
    chunk = min(Q, chunk or (16_384 if on_accel else 1_024))
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    targets = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    sorted_ids, _perm, n_valid = jax.block_until_ready(sort_table(table))
    lut = jax.block_until_ready(build_prefix_lut(
        sorted_ids, n_valid, bits=default_lut_bits(N)))
    del table

    n_waves = (Q + chunk - 1) // chunk
    pad = n_waves * chunk - Q
    if pad:
        targets = jnp.concatenate([targets, targets[:pad]], axis=0)
    waves = [targets[i * chunk:(i + 1) * chunk] for i in range(n_waves)]

    def run_wave(t, sorted_ids=sorted_ids, n_valid=n_valid, lut=lut):
        return simulate_lookups(sorted_ids, n_valid, t, alpha=3, k=8, lut=lut)

    # stats pass over the full burst (hops / convergence are exact)
    hops_all, conv_all = [], []
    for w in waves:
        o = run_wave(w)
        hops_all.append(np.asarray(o["hops"]))
        conv_all.append(np.asarray(o["converged"]))
    hops = np.concatenate(hops_all)[:Q]
    conv = float(np.concatenate(conv_all)[:Q].mean())

    # timed pass: serialized-chain slope of one wave
    def body(t, sorted_ids, n_valid, lut):
        o = run_wave(t, sorted_ids, n_valid, lut)
        return (jnp.sum(o["hops"].astype(jnp.float32))
                + jnp.sum(o["converged"].astype(jnp.float32)))

    wave_dt = chain_slope(body, waves[0], sorted_ids, n_valid, lut,
                          r1=1, r2=4)
    dt = wave_dt * n_waves
    p50_wave = min((Q // 2) // chunk, n_waves - 1)
    return {"metric": "config3 iterative search sim, alpha=3 k=8, "
                      "%d lookups x %d nodes, %d waves of %d; p50 hops %d, "
                      "converged %.3f, p50 burst completion %.3fs "
                      "(wave chain slope %.3fs)"
                      % (Q, N, n_waves, chunk,
                         int(np.percentile(hops, 50)), conv,
                         wave_dt * (p50_wave + 1), wave_dt),
            "value": round(Q / dt, 1), "unit": "lookups/s/chip",
            "vs_baseline": None}


def config4() -> dict:
    """Bucket-refresh sweep: radix partition + per-bucket stats."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops import radix

    on_accel = jax.devices()[0].platform != "cpu"
    N = 10_000_000 if on_accel else 1_000_000
    key = jax.random.PRNGKey(4)
    ids = jax.random.bits(key, (N, 5), dtype=jnp.uint32)
    self_id = jax.random.bits(jax.random.PRNGKey(5), (5,), dtype=jnp.uint32)
    valid = jnp.ones((N,), bool)
    last = jnp.zeros((N,), jnp.float32)

    def body(x, self_id, valid, last):
        b = radix.bucket_of(self_id, x)
        c = radix.bucket_counts(self_id, x, valid)
        s = radix.bucket_last_seen(self_id, x, valid, last)
        return (jnp.sum(b.astype(jnp.float32)) * 1e-9
                + jnp.sum(c.astype(jnp.float32))
                + jnp.sum(s) * 1e-9)

    dt = chain_slope(body, ids, self_id, valid, last, r1=1, r2=4)
    return {"metric": "config4 radix bucket sweep over %d ids "
                      "(device-serialized chain slope)" % N,
            "value": round(N / dt, 1), "unit": "ids/s/chip",
            "vs_baseline": None}


def config5() -> dict:
    """Sharded lookup with top-k merge over the mesh (all local
    devices; multi-chip validated by dryrun_multichip)."""
    import jax
    import jax.numpy as jnp
    from bench import chain_slope
    from opendht_tpu.ops.sorted_table import default_lut_bits
    from opendht_tpu.parallel import (make_mesh, sharded_sort_table,
                                      sharded_expand_table,
                                      sharded_window_lookup)

    n_dev = len(jax.devices())
    on_accel = jax.devices()[0].platform != "cpu"
    N = 8_000_000 if on_accel else 262_144
    Q = 65_536 if on_accel else 4_096
    k1, k2 = jax.random.split(jax.random.PRNGKey(6))
    table = jax.random.bits(k1, (N, 5), dtype=jnp.uint32)
    queries = jax.random.bits(k2, (Q, 5), dtype=jnp.uint32)
    mesh = make_mesh(n_dev)

    sorted_ids, perm, n_valid = jax.block_until_ready(
        sharded_sort_table(mesh, table))
    expanded, lut = jax.block_until_ready(
        sharded_expand_table(mesh, sorted_ids, n_valid,
                             bits=default_lut_bits(N // mesh.shape['t'])))

    def body(q, sorted_ids, perm, n_valid, expanded, lut):
        d, idx = sharded_window_lookup(mesh, q, sorted_ids, perm, n_valid,
                                       k=8, expanded=expanded, lut=lut)
        return jnp.sum((idx >= 0).astype(jnp.float32))

    dt = chain_slope(body, queries, sorted_ids, perm, n_valid, expanded, lut,
                     r1=1, r2=3)
    return {"metric": "config5 sharded lookup, %d devices, "
                      "%d queries x %d ids "
                      "(device-serialized chain slope)" % (n_dev, Q, N),
            "value": round(Q / dt, 1), "unit": "lookups/s",
            "vs_baseline": None}


def config2() -> dict:
    """Delegates to the headline bench (bench.py)."""
    from bench import measure
    out = measure()
    out["metric"] = "config2 " + out["metric"]
    return out


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="BASELINE.json config drivers")
    p.add_argument("-c", "--config", type=int, default=0,
                   help="config number (default: all)")
    p.add_argument("-Q", type=int, default=0,
                   help="config3: concurrent lookup count "
                        "(north star: 1000000)")
    p.add_argument("-N", type=int, default=0,
                   help="config3: network size (default 10M on device)")
    p.add_argument("--chunk", type=int, default=0,
                   help="config3: lookups per device wave")
    args = p.parse_args(argv)
    todo = [args.config] if args.config else sorted(CONFIGS)
    for c in todo:
        kw = ({"Q": args.Q, "N": args.N, "chunk": args.chunk}
              if c == 3 else {})
        print(json.dumps(CONFIGS[c](**kw)))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
